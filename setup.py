"""Build hook: compile the native host runtime with the wheel.

The package also builds the library lazily at first use (native/__init__.py
runs `make` when the .so is missing or stale), so a source checkout works
without installation; this hook just front-loads that for wheels."""

import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        try:
            subprocess.run(
                ["make", "-s"], cwd="hclib_tpu/native", check=True
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            # A toolchain-less install still gets the pure-Python runtime;
            # the native baseline raises NativeBuildError on first use.
            print(f"warning: native runtime not prebuilt ({e})")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
