"""Version-compatibility shims for the jax API surface this package uses.

The package targets jax >= 0.5 but must *degrade structurally* on older
builds (part of the resilience story: a missing API surfaces as a skipped
capability or a clear error naming the requirement, never an
``AttributeError`` deep inside a kernel build):

- ``shard_map``: moved to ``jax.shard_map`` (with ``check_vma``) in 0.5;
  older builds carry it at ``jax.experimental.shard_map`` (``check_rep``).
- ``distributed_is_initialized``: ``jax.distributed.is_initialized`` does
  not exist on 0.4.x; the private global state carries the same fact.
- ``has_mosaic_interpret``: the Mosaic TPU interpret mode
  (``pltpu.InterpretParams`` - simulated remote DMA + semaphores on CPU)
  appeared after 0.4.x. Kernels that simulate an ICI mesh need it; callers
  and tests gate on this instead of crashing mid-trace.
"""

from __future__ import annotations

import jax

__all__ = [
    "shard_map",
    "axis_size",
    "distributed_is_initialized",
    "is_multiprocess_capability_error",
    "has_mosaic_interpret",
]


def axis_size(axis) -> int:
    """``jax.lax.axis_size`` where available; older builds derive it from
    the bound mesh axis env (same value, the public pre-0.5 idiom)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    from jax._src import core as _core

    return _core.get_axis_env().axis_size(axis)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` where available, else the 0.4.x
    ``jax.experimental.shard_map`` spelling (``check_vma`` -> ``check_rep``:
    same replication-check knob, renamed upstream)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` on any supported jax."""
    d = jax.distributed
    if hasattr(d, "is_initialized"):
        return bool(d.is_initialized())
    try:  # 0.4.x: the distributed client exists iff initialize() ran
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def is_multiprocess_capability_error(e: BaseException) -> bool:
    """True for errors a backend raises LOCALLY, at dispatch, because it
    cannot run multiprocess device computations at all (CPU pre-gloo
    jaxlib). Deterministic on every rank - the one failure class a
    committed collective may jointly degrade from. Matched by the two
    SPECIFIC messages of that class (the raw XLA dispatch error and this
    package's structured wrapper), never by a bare status prefix: an
    unrelated rank-local UNIMPLEMENTED must stay fatal, or one rank would
    solo-fallback while its peers sit in the device collective."""
    msg = str(e)
    return (
        "Multiprocess computations aren't implemented" in msg
        or "bulk device collectives are unavailable" in msg
    )


def has_mosaic_interpret() -> bool:
    """True when the Mosaic TPU interpret mode (``pltpu.InterpretParams``)
    exists - required by every kernel that simulates remote DMA +
    semaphores on CPU (device/resident.py and friends)."""
    from jax.experimental.pallas import tpu as pltpu

    return hasattr(pltpu, "InterpretParams")
