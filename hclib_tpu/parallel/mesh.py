"""Device meshes and the mesh-derived locality graph.

The reference describes the machine as a locality-graph JSON (sysmem, cache
slices, GPUs, NIC - locality_graphs/*.json); workers get pop/steal paths over
it. On TPU the machine shape *is* the device mesh, so the locality graph is
synthesized from it: one ``tpu`` locale per device (metadata carries the mesh
coordinates and jax device), an ``hbm`` locale per device, one ``host``
locale, and an ``ici`` locale marked "COMM" standing in for the interconnect
(the reference marks its NIC locale special "COMM",
modules/mpi/src/hclib_mpi.cpp:92). Host workers whose paths include a tpu
locale play the role of the reference's GPU/communication workers.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..runtime.locality import Locale, LocalityGraph

__all__ = [
    "make_mesh", "mesh_locality_graph", "cpu_mesh", "quarantine_locales",
]


def make_mesh(
    axis_shapes: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over the given devices (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(axis_shapes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(axis_shapes))
    return Mesh(arr, tuple(axis_names))


def cpu_mesh(n: int, axis_name: str = "d") -> Mesh:
    """n-device mesh over host-platform CPU devices (virtual devices when
    --xla_force_host_platform_device_count is set). Used for sharding tests
    and multi-chip dry runs without TPU hardware."""
    cpus = jax.devices("cpu")
    if len(cpus) < n:
        raise ValueError(
            f"need {n} cpu devices, have {len(cpus)}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return Mesh(np.array(cpus[:n]), (axis_name,))


def mesh_locality_graph(mesh: Mesh, nworkers: Optional[int] = None) -> LocalityGraph:
    """Locality graph for host workers driving a device mesh.

    Layout: host -- ici(COMM) -- tpu_i -- hbm_i. Worker w's pop path is
    [tpu_(w%ndev), host]; steal paths span every tpu locale then host, so any
    worker can service any device queue.
    """
    devices = list(mesh.devices.flat)
    ndev = len(devices)
    if nworkers is None:
        nworkers = ndev
    locales = []
    host = Locale(0, "host", "host")
    locales.append(host)
    ici = Locale(1, "ici", "ici")
    ici.mark_special("COMM")
    ici.reachable.append(0)
    host.reachable.append(1)
    locales.append(ici)
    tpu_ids = []
    for i, dev in enumerate(devices):
        t = Locale(2 + 2 * i, f"tpu_{i}", "tpu")
        t.metadata["device"] = dev
        t.metadata["ordinal"] = i
        t.metadata["coords"] = tuple(
            int(c) for c in np.argwhere(mesh.devices == dev)[0]
        )
        h = Locale(3 + 2 * i, f"hbm_{i}", "hbm")
        h.metadata["ordinal"] = i
        t.reachable.extend([1, h.id])
        h.reachable.append(t.id)
        ici.reachable.append(t.id)
        locales.extend([t, h])
        tpu_ids.append(t.id)
    # Every worker's paths cover the ici(COMM) locale so comm tasks are
    # always serviced (the reference routes comm through workers whose paths
    # include the NIC locale, modules/mpi/src/hclib_mpi.cpp:92).
    pop_paths = [[tpu_ids[w % ndev], ici.id, 0] for w in range(nworkers)]
    steal_paths = [
        [tpu_ids[(w + k) % ndev] for k in range(1, ndev + 1)] + [ici.id, 0]
        for w in range(nworkers)
    ]
    return LocalityGraph(nworkers, locales, pop_paths, steal_paths)


def quarantine_locales(graph: LocalityGraph, ordinals) -> int:
    """Host-side mirror of the device-mesh quarantine mask: remove the
    named device ordinals' ``tpu``/``hbm`` locales from every worker's
    pop/steal path (in place), so host workers stop routing work at a chip
    the device layer declared dead (heartbeat timeout, ROADMAP device
    chaos). The locales stay in the graph - marked special ``"DEAD"`` -
    for diagnostics; only the scheduling paths forget them. Returns the
    number of path entries removed. Idempotent."""
    ordinals = set(ordinals)  # once: the input may be a one-shot iterable
    dead_ids = set()
    for loc in graph.locales:
        if (
            loc.type in ("tpu", "hbm")
            and loc.metadata.get("ordinal") in ordinals
        ):
            dead_ids.add(loc.id)
            if "DEAD" not in loc.special:
                loc.mark_special("DEAD")
    removed = 0
    for paths in (graph.pop_paths, graph.steal_paths):
        for w, path in enumerate(paths):
            keep = [l for l in path if l not in dead_ids]
            removed += len(path) - len(keep)
            paths[w] = keep
    return removed
