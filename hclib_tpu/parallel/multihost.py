"""Multi-host (DCN) support: jax.distributed lifecycle + global mesh.

The reference reaches other nodes through MPI/OpenSHMEM launchers
(modules/mpi, modules/openshmem: NIC locale + comm worker). The TPU-native
equivalent is JAX's multi-controller runtime: every host runs the same
program, ``jax.distributed.initialize`` wires the controllers over DCN, and
a global ``Mesh`` over ``jax.devices()`` (all hosts' devices) lets the same
``shard_map``/collective code that rides ICI within a slice span hosts -
XLA routes collective edges over ICI inside a slice and DCN between slices.

On a single host everything degrades gracefully: ``init_multihost`` is a
no-op (process 0 of 1), ``global_mesh`` is a mesh over local devices, so
the same program runs unmodified from laptop CPU to multi-host pod - which
is also how this module is tested without a cluster (the reference's
multi-node paths are untestable without one, SURVEY §4).

Typical use (same script on every host, launched by the cluster runtime):

    from hclib_tpu.parallel import multihost as mh
    mh.init_multihost()                  # no-op single-host
    mesh = mh.global_mesh("dp")          # all devices, every host
    ... shard_map / ShardedMegakernel over `mesh` ...
    mh.shutdown()
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from ..jaxcompat import distributed_is_initialized, shard_map
from .mesh import make_mesh

__all__ = [
    "init_multihost",
    "shutdown",
    "process_index",
    "process_count",
    "is_multihost",
    "global_mesh",
    "local_devices",
    "sync_global",
    "bulk_allreduce",
]

_initialized = False
_owns_init = False


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire this controller into the multi-host runtime.

    With explicit arguments, initializes directly. With none, initializes
    (letting JAX's cluster plugins fill in the details) only when a known
    multi-process launcher environment is detected — coordinator-address env
    vars, a multi-task srun/mpirun step, or a multi-worker Cloud TPU pod
    slice. Plain single-process runs skip initialization entirely.
    Idempotent, including when jax.distributed was already initialized by an
    outer launcher or sibling framework (adopted, not re-initialized; such an
    adopted runtime is left for its owner to shut down)."""
    global _initialized, _owns_init
    if _initialized:
        return
    import jax

    if distributed_is_initialized():
        _initialized = True  # wired by someone else: adopt
        return
    explicit = any(
        a is not None for a in (coordinator_address, num_processes, process_id)
    )
    auto_env = _cluster_env_present()
    if explicit or auto_env:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        _owns_init = True


def _cluster_env_present() -> bool:
    import os

    env = os.environ
    if any(
        env.get(k)
        for k in (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
    ):
        return True
    # Multi-task srun/mpirun steps (JAX ships cluster plugins for both).
    # Deliberately NOT SLURM_NTASKS: that leaks into plain `python` runs
    # inside an sbatch allocation, where auto-init would hang waiting for
    # peers; these step-scoped vars are only set by the actual launcher.
    for k in ("SLURM_STEP_NUM_TASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        try:
            if int(env.get(k, "1")) > 1:
                return True
        except ValueError:
            pass
    # Cloud TPU pod slice: worker hostnames list has more than one entry.
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h.strip()]) > 1


def shutdown() -> None:
    """Tear down the distributed runtime if this module started it; an
    adopted external runtime is left for its owner."""
    global _initialized, _owns_init
    if _owns_init:
        import jax

        jax.distributed.shutdown()
        _owns_init = False
    _initialized = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_multihost() -> bool:
    return process_count() > 1


def local_devices():
    import jax

    return jax.local_devices()


def global_mesh(
    *axis_names: str,
    axis_shape: Optional[Sequence[int]] = None,
    devices=None,
):
    """Mesh over ALL hosts' devices (jax.devices() is global under the
    multi-controller runtime). 1 axis name -> 1D mesh over every device;
    more names need an explicit ``axis_shape``. ``devices`` overrides the
    device set (e.g. jax.devices("cpu") for virtual-mesh tests)."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    if axis_shape is None:
        if len(axis_names) != 1:
            raise ValueError("multi-axis mesh needs axis_shape")
        axis_shape = (len(devs),)
    if int(np.prod(axis_shape)) != len(devs):
        raise ValueError(
            f"axis_shape {tuple(axis_shape)} != {len(devs)} devices"
        )
    return make_mesh(tuple(axis_shape), axis_names, devs)


def sync_global(tag: int = 0) -> None:
    """Cross-host barrier (the reference's analogue is MPI_Barrier through
    the NIC locale, modules/mpi/src/hclib_mpi.cpp:220-286).

    Multi-host: delegates to ``multihost_utils.sync_global_devices`` — the
    coordination-service barrier that works with non-addressable devices.
    Single-host: a tiny psum over every local device, exercising the same
    collective path the sharded scheduler uses."""
    if is_multihost():
        from jax.experimental import multihost_utils

        from ..jaxcompat import is_multiprocess_capability_error

        try:
            multihost_utils.sync_global_devices(f"hclib_tpu_sync_{tag}")
        except Exception as e:
            if not is_multiprocess_capability_error(e):
                raise
            # The backend cannot run multiprocess device computations at
            # all (CPU pre-gloo jaxlib): every rank fails this dispatch
            # locally and identically, so all jointly degrade to the
            # coordination-service barrier - the same rendezvous with no
            # device computation in it.
            from jax._src import distributed

            distributed.global_state.client.wait_at_barrier(
                f"hclib_tpu_sync_{tag}", 120_000
            )
        return
    import jax

    devs = tuple(jax.devices())
    out = _local_barrier(devs)(np.full((len(devs),), tag, np.int32))
    np.asarray(out)  # materialize = every participant arrived


def bulk_allreduce(arr: np.ndarray, op: str = "sum") -> np.ndarray:
    """All-process reduction of a per-process host array over the global
    device runtime (the bulk-data path of ProcWorld.allreduce: arrays above
    the control-plane threshold ride XLA's cross-host collectives instead
    of the coordination-service KV store - the reference's AM-packet vs
    bulk-MPI-datatype split, modules/mpi/src/hclib_mpi.cpp:220-286).

    One representative device per process forms a 1-axis mesh; each process
    contributes its array as one shard of a global (nproc, ...) array, and
    a jitted reduce-to-replicated makes XLA emit an actual all-reduce over
    ICI/DCN - O(nbytes) per host, not O(nproc * nbytes) like an allgather
    + host reduce would be."""
    import jax

    arr = np.asarray(arr)
    nproc = jax.process_count()
    if nproc == 1:
        return arr.copy()
    reps = {}
    for d in jax.devices():
        if d.process_index not in reps or d.id < reps[d.process_index].id:
            reps[d.process_index] = d
    if len(reps) != nproc:
        raise RuntimeError(
            f"only {len(reps)}/{nproc} processes contribute devices"
        )
    devs = tuple(reps[p] for p in sorted(reps))
    jitted, sharding = _bulk_reducer(devs, op)
    local = jax.device_put(arr[None], reps[jax.process_index()])
    garr = jax.make_array_from_single_device_arrays(
        (nproc,) + arr.shape, sharding, [local]
    )
    from ..jaxcompat import is_multiprocess_capability_error

    try:
        out = jitted(garr)
    except Exception as e:
        if not is_multiprocess_capability_error(e):
            raise
        # Structured degradation signal: ProcWorld.allreduce recognizes
        # the UNIMPLEMENTED status and jointly falls back to its KV path;
        # direct callers get an error naming the missing capability
        # instead of a dispatch-internal message.
        raise RuntimeError(
            "UNIMPLEMENTED: bulk device collectives are unavailable on "
            f"this backend/jaxlib ({e})"
        ) from e
    return np.asarray(out.addressable_data(0))


@functools.lru_cache(maxsize=32)
def _bulk_reducer(devs, op: str):
    """Jitted reduce-to-replicated, cached per (device set, op) - a fresh
    jit wrapper per call would retrace and recompile every bulk allreduce
    (shape/dtype variations hit jit's own signature cache)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devs), ("p",))
    red = {
        "sum": lambda x: x.sum(0),
        "max": lambda x: x.max(0),
        "min": lambda x: x.min(0),
    }[op]
    jitted = jax.jit(red, out_shardings=NamedSharding(mesh, P()))
    return jitted, NamedSharding(mesh, P("p"))


@functools.lru_cache(maxsize=8)
def _local_barrier(devs):
    """Compiled psum barrier, cached per device set (a fresh jit per call
    would retrace the psum on every barrier)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh("all", devices=devs)

    def f(v):
        return jax.lax.psum(v, "all")

    return jax.jit(
        shard_map(
            f, mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False
        ),
        out_shardings=NamedSharding(mesh, P()),
    )
