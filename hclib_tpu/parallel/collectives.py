"""Collective operations over mesh axes.

The reference's distributed layer is MPI collectives serviced by a NIC-locale
worker (modules/mpi/src/hclib_mpi.cpp:220-286: Allreduce/Bcast/Barrier as
finish{async_nb_at(nic)}). TPU-first these are XLA collectives compiled into
the program and riding ICI/DCN - thin named wrappers so framework code reads
the same on host and device (usable inside jit/shard_map/pallas):

    MPI_Allreduce(SUM)  -> psum(x, axis)
    MPI_Allgather       -> all_gather(x, axis)
    MPI_Reduce_scatter  -> reduce_scatter(x, axis)
    MPI_Alltoall        -> all_to_all(x, axis, ...)
    SHMEM put-to-right  -> ring_permute(x, axis, shift)
"""

from __future__ import annotations

import jax

__all__ = ["psum", "all_gather", "reduce_scatter", "all_to_all", "ring_permute"]


def psum(x, axis: str):
    return jax.lax.psum(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False):
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dimension: int = 0):
    return jax.lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=True
    )


def all_to_all(x, axis: str, *, split_axis: int = 0, concat_axis: int = 0):
    return jax.lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def ring_permute(x, axis: str, shift: int = 1):
    """Rotate shards around the mesh axis (one-sided neighbor exchange)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)
