"""Collective operations over mesh axes.

The reference's distributed layer is MPI collectives serviced by a NIC-locale
worker (modules/mpi/src/hclib_mpi.cpp:220-286: Allreduce/Bcast/Barrier as
finish{async_nb_at(nic)}). TPU-first these are XLA collectives compiled into
the program and riding ICI/DCN. Two tiers live here:

1. Primitive parity aliases (XLA has the op; the name maps the reference's
   vocabulary onto it):

    MPI_Allreduce(SUM)  -> psum(x, axis)
    MPI_Allgather       -> all_gather(x, axis)
    MPI_Reduce_scatter  -> reduce_scatter(x, axis)
    MPI_Alltoall        -> all_to_all(x, axis, ...)
    SHMEM put-to-right  -> ring_permute(x, axis, shift)

2. Composed collectives XLA does NOT expose as single primitives, built
   here from masks and permutes (all usable inside jit/shard_map):

    MPI_Bcast           -> bcast(x, axis, root)       (mask + psum)
    MPI_Reduce          -> reduce(x, axis, root)      (psum + root mask)
    MPI_Exscan          -> exscan(x, axis)            (log-step doubling)
    MPI_Barrier         -> barrier(axis)              (token psum)
    ring_allreduce(x, axis) - the bandwidth-optimal reduce-scatter +
    all-gather ring schedule written out in ppermute steps. XLA's psum
    normally picks this (or better) by itself; this explicit form is for
    pipelining reductions against compute under jax.remat boundaries and
    as the reference schedule the profiler compares psum against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..jaxcompat import axis_size

__all__ = [
    "psum", "all_gather", "reduce_scatter", "all_to_all", "ring_permute",
    "bcast", "reduce", "exscan", "barrier", "ring_allreduce",
]


def psum(x, axis: str):
    return jax.lax.psum(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False):
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dimension: int = 0):
    return jax.lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=True
    )


def all_to_all(x, axis: str, *, split_axis: int = 0, concat_axis: int = 0):
    return jax.lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def ring_permute(x, axis: str, shift: int = 1):
    """Rotate shards around the mesh axis (one-sided neighbor exchange)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def _check_root(root: int, axis: str) -> None:
    n = axis_size(axis)
    if not (0 <= root < n):
        raise ValueError(f"root {root} out of range for {n}-shard axis {axis!r}")


def bcast(x, axis: str, root: int = 0):
    """MPI_Bcast: every shard receives the root shard's value. Composed as
    mask-then-psum (zero everywhere but the root, sum across the axis) -
    one collective, no gather of the full axis."""
    _check_root(root, axis)
    me = jax.lax.axis_index(axis)
    masked = jnp.where(me == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


def reduce(x, axis: str, root: int = 0):
    """MPI_Reduce(SUM): the reduction lands on ``root``; other shards get
    zeros. (XLA computes the allreduce either way on TPU - the rooted
    form exists for API parity and so callers can elide the result's
    later use on non-roots, letting DCE drop it.)"""
    _check_root(root, axis)
    me = jax.lax.axis_index(axis)
    s = jax.lax.psum(x, axis)
    return jnp.where(me == root, s, jnp.zeros_like(s))


def exscan(x, axis: str):
    """MPI_Exscan(SUM): shard i receives sum of shards [0, i) - rank 0
    gets zeros. Hillis-Steele doubling in log2(n) ppermute steps; works
    for any axis size (shifts past the edge contribute zero)."""
    n = axis_size(axis)
    me = jax.lax.axis_index(axis)
    acc = x
    total = jnp.zeros_like(x)
    shift = 1
    while shift < n:
        perm = [(i, i + shift) for i in range(n - shift)]
        moved = jax.lax.ppermute(acc, axis, perm)
        # Ranks < shift received nothing: their incoming slot is zeros
        # (ppermute leaves unnamed destinations zero-filled).
        total = total + jnp.where(me >= shift, moved, jnp.zeros_like(x))
        acc = acc + moved
        shift *= 2
    # ``total`` accumulated every prefix contribution except x itself.
    return total


def barrier(axis: str):
    """MPI_Barrier: a 1-element token allreduce; returns the token so the
    caller can thread a data dependency through it (inside jit, ordering
    IS data dependence - there is no side-effect fence to wait on)."""
    return jax.lax.psum(jnp.ones((), jnp.int32), axis)


def ring_allreduce(x, axis: str):
    """Bandwidth-optimal allreduce written as explicit ring steps:
    reduce-scatter (n-1 ppermutes, each shard ends owning one fully
    reduced chunk) then all-gather (n-1 more). Requires the leading dim
    divisible by the axis size. Matches psum numerically; exists as the
    reference schedule for profiling and for manual compute/comm
    pipelining (interleave chunk FLOPs between steps)."""
    n = axis_size(axis)
    if n == 1:
        return x
    me = jax.lax.axis_index(axis)
    chunks = jnp.stack(jnp.split(x, n, axis=0))  # (n, ...) chunk view
    right = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: at step s, send the partial for chunk (me - s),
    # receive and fold the partial for chunk (me - s - 1).
    send_idx = me
    partial = chunks[send_idx]
    for s in range(n - 1):
        moved = jax.lax.ppermute(partial, axis, right)
        send_idx = (send_idx - 1) % n
        partial = chunks[send_idx] + moved
    # Every shard now owns the fully reduced chunk (me + 1) % n.

    # All-gather: circulate the reduced chunks; scatter each into place.
    own_idx = (me + 1) % n
    out = jnp.zeros_like(chunks)
    cur, cur_idx = partial, own_idx
    out = out.at[cur_idx].set(cur)
    for s in range(n - 1):
        cur = jax.lax.ppermute(cur, axis, right)
        cur_idx = (cur_idx - 1) % n
        out = out.at[cur_idx].set(cur)
    return out.reshape(x.shape)
