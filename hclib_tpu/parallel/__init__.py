"""Multi-device layer: meshes, shardings, collectives, sharded execution.

The reference scales across nodes with MPI/OpenSHMEM modules servicing a NIC
locale (modules/mpi, modules/openshmem). TPU-first, the equivalents are:
- intra-slice: XLA collectives over ICI (psum/all_gather/ppermute/...)
  and Pallas remote DMA between cores,
- inter-host: jax.distributed + the same collectives over DCN,
with the device mesh replacing the locality-graph's machine JSON.
"""

from .collectives import (
    all_gather,
    all_to_all,
    barrier,
    bcast,
    exscan,
    psum,
    reduce,
    reduce_scatter,
    ring_allreduce,
    ring_permute,
)
from .mesh import make_mesh, mesh_locality_graph

__all__ = [
    "make_mesh",
    "mesh_locality_graph",
    "psum",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "ring_permute",
    "bcast",
    "reduce",
    "exscan",
    "barrier",
    "ring_allreduce",
]
