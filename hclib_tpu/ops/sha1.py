"""FIPS-180-1 SHA-1 compression, vectorized over arrays of any shape.

The UTS splittable RNG (one hash per tree node). Generic over the array
module: ``xp=jnp`` hashes device planes inside the vectorized DFS;
``xp=numpy`` hashes whole BFS frontier levels during host seeding
(hclib_tpu/device/uts_vec.py). A scalar single-block variant lives in the
native runtime (hclib_tpu/native/src/sha1.hpp).
"""

from __future__ import annotations

from typing import List

__all__ = ["sha1_block", "sha1_child"]


def _rotl(x, s: int):
    # Plain-int shift amounts keep u32 dtype under both numpy (NEP 50 weak
    # scalars) and jnp weak typing.
    return (x << s) | (x >> (32 - s))


def sha1_block(w16: List, xp):
    """SHA-1 compression of one 16-word block."""
    K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)
    H = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
    w = list(w16)
    a = xp.full_like(w[0], H[0])
    b = xp.full_like(w[0], H[1])
    c = xp.full_like(w[0], H[2])
    d = xp.full_like(w[0], H[3])
    e = xp.full_like(w[0], H[4])
    for i in range(80):
        if i >= 16:
            nw = _rotl(w[(i - 3) % 16] ^ w[(i - 8) % 16] ^ w[(i - 14) % 16]
                       ^ w[i % 16], 1)
            w[i % 16] = nw
        wi = w[i % 16]
        if i < 20:
            f = (b & c) | (~b & d)
            k = K[0]
        elif i < 40:
            f = b ^ c ^ d
            k = K[1]
        elif i < 60:
            f = (b & c) | (b & d) | (c & d)
            k = K[2]
        else:
            f = b ^ c ^ d
            k = K[3]
        tmp = _rotl(a, 5) + f + e + xp.uint32(k) + wi
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return (
        a + xp.uint32(H[0]),
        b + xp.uint32(H[1]),
        c + xp.uint32(H[2]),
        d + xp.uint32(H[3]),
        e + xp.uint32(H[4]),
    )


def sha1_child(state5, child_idx, xp):
    """SHA1(parent_state(20B) || BE32(child)) for UTS's 24-byte messages."""
    zero = xp.zeros_like(state5[0])
    w16 = [
        state5[0], state5[1], state5[2], state5[3], state5[4],
        child_idx.astype(xp.uint32),
        xp.full_like(state5[0], 0x80000000),
        zero, zero, zero, zero, zero, zero, zero, zero,
        xp.full_like(state5[0], 24 * 8),
    ]
    return sha1_block(w16, xp)
