"""Scan-based recurrence solvers for the VPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["decay_cummax"]


def decay_cummax(t, axis: int = -1):
    """Solve c[j] = max(t[j], c[j-1] - 1) in log depth.

    Uses the identity c[j] = max_{j' <= j} (t[j'] - (j - j')) =
    cummax(t + j)[j] - j. This is the in-row horizontal-gap chain of
    Smith-Waterman with unit linear gap (hclib_tpu/device/sw_vec.py).
    """
    j = jnp.arange(t.shape[axis], dtype=t.dtype)
    shape = [1] * t.ndim
    shape[axis] = -1
    j = j.reshape(shape)
    return jax.lax.associative_scan(jnp.maximum, t + j, axis=axis) - j
