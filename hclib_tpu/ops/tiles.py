"""MXU tile kernels shared by device task kernels.

Designed for the TPU compute units rather than translated from LAPACK
(used by hclib_tpu/device/cholesky.py; reference workload
test/cholesky/cholesky.cpp):

- ``factor_tile`` (VPU): lower-Cholesky of a symmetric tile as masked
  rank-1 updates - row j equals column j by symmetry, so both outer-product
  factors come from cheap masked reductions; no transposes, no dynamic lane
  indexing.
- ``tri_inverse`` (MXU): inv(L) via Newton-Schulz X <- X(2I - LX), *exact*
  for triangular matrices after ceil(log2 T) steps - matmuls instead of a
  scalar substitution sweep.
- ``factor_and_inv``: (L, inv(L)) for any tile size - the serial sweep
  runs only on 128x128 diagonal base blocks; larger tiles recurse by 2x2
  blocking with panels/updates/inverse as MXU block algebra.
- ``mm_nt`` (MXU): A @ B^T as a dot_general contraction on the second axis
  of both operands (no materialized transpose), at ~f32 accuracy via a
  3-pass bf16 hi/lo split (2x the throughput of HIGHEST's 6 passes).
- ``dma_copy``: start+wait of a Pallas async copy (HBM<->VMEM staging in
  task kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "factor_tile", "tri_inverse", "factor_and_inv", "mm_nt", "dma_copy",
]


PANEL = 8  # factor-panel width: one sublane group


def factor_tile(t, ts: int):
    """Panel-blocked lower-Cholesky of a symmetric (ts, ts) tile.

    Exploits symmetry: for the 8-column panel J, the rows s[J, :] ARE the
    columns s[:, J] transposed, so the whole panel factorization runs on
    one (8, ts) sublane block with VPU broadcast rank-1 updates (no
    reductions over the full plane, no dynamic indexing - the panel loop
    is fully unrolled, all slices static). The trailing matrix then takes
    ONE rank-8 MXU update per panel (3-pass bf16 split, ~f32 exact),
    replacing 8 full-plane rank-1 sweep iterations - about an order of
    magnitude fewer vector ops than the naive masked rank-1 sweep, which
    dominated the whole Cholesky wall clock at 32 sweeps per n=4096.

    Builds U = L^T row-by-row (static sublane writes) and transposes once.
    """
    assert ts % PANEL == 0, ts
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)
    lanep = jax.lax.broadcasted_iota(jnp.int32, (PANEL, ts), 1)
    prow = jax.lax.broadcasted_iota(jnp.int32, (PANEL, ts), 0)
    s = t
    pans = []
    npanels = ts // PANEL
    for p in range(npanels):
        j0 = p * PANEL
        pan = jax.lax.slice(s, (j0, 0), (j0 + PANEL, ts))

        # All extraction is mask+reduce on the single (PANEL, ts) block,
        # so the 8 micro-iterations share one rolled fori_loop body
        # (unrolling them bloated the kernel ~8x and the register/spill
        # pressure cost far more than the loop saves).
        def micro(q, pan):
            j = j0 + q
            rowq = jnp.sum(
                jnp.where(prow == q, pan, 0.0), axis=0, keepdims=True
            )
            diag = jnp.sum(jnp.where(lanep[:1] == j, rowq, 0.0))
            lrow = jnp.where(lanep[:1] >= j, rowq * jax.lax.rsqrt(diag), 0.0)
            # In-panel rank-1 coefficients = pan's own column j (symmetry),
            # scaled like lrow.
            coeff = jnp.sum(
                jnp.where(lanep == j, pan, 0.0), axis=1, keepdims=True
            ) * jax.lax.rsqrt(diag)
            return jnp.where(
                prow == q, lrow, jnp.where(prow > q, pan - coeff * lrow, pan)
            )

        pan = jax.lax.fori_loop(0, PANEL, micro, pan)
        pans.append(pan)
        if p + 1 < npanels:
            # Rank-8 trailing update in one contraction over the panel:
            # s[m, n] -= sum_q L[m, j0+q] L[n, j0+q] = (pan^T pan)[m, n].
            upd8 = _mm_tn(pan, pan)
            edge = j0 + PANEL - 1
            s = jnp.where((rows > edge) & (cols > edge), s - upd8, s)
    return jnp.transpose(jnp.concatenate(pans, axis=0))


def tri_inverse(l, ts: int):
    """inv(L) for lower-triangular L via Newton-Schulz (exact in log2 ts)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)
    dg = jnp.sum(jnp.where(rows == cols, l, 0.0), axis=1, keepdims=True)
    x = jnp.where(rows == cols, 1.0 / dg, 0.0)
    steps = max(1, int(np.ceil(np.log2(ts))))
    for _ in range(steps):
        lx = mm_nn(l, x)
        x = 2.0 * x - mm_nn(x, lx)
    return x


def factor_and_inv(t, ts: int, base: int = 128):
    """(L, inv(L)) for a symmetric (ts, ts) tile.

    The scalar rank-1 sweep (factor_tile) costs O(ts) serial iterations on
    O(ts^2) planes - ~100us at ts=256 - so tiles larger than ``base`` are
    factored recursively by 2x2 blocking, keeping the sweep on base-sized
    diagonal blocks and doing panels/updates/inverses as MXU block algebra:

        A = [[A00,  . ], [A10, A11]]
        L00, I00 = factor_and_inv(A00);  L10 = A10 I00^T
        L11, I11 = factor_and_inv(A11 - L10 L10^T)
        inv(L)   = [[I00, 0], [-I11 L10 I00, I11]]
    """
    if ts <= base:
        l = factor_tile(t, ts)
        return l, tri_inverse(l, ts)
    h = ts // 2
    a00 = jax.lax.slice(t, (0, 0), (h, h))
    a10 = jax.lax.slice(t, (h, 0), (ts, h))
    a11 = jax.lax.slice(t, (h, h), (ts, ts))
    l00, i00 = factor_and_inv(a00, h, base)
    l10 = mm_nt(a10, i00)
    l11, i11 = factor_and_inv(a11 - mm_nt(l10, l10), h, base)
    off = -mm_nn(mm_nn(i11, l10), i00)
    z = jnp.zeros((h, h), t.dtype)
    l = jnp.concatenate(
        [jnp.concatenate([l00, z], 1), jnp.concatenate([l10, l11], 1)], 0
    )
    inv = jnp.concatenate(
        [jnp.concatenate([i00, z], 1), jnp.concatenate([off, i11], 1)], 0
    )
    return l, inv


def mm_nt(a, b):
    """a @ b^T without materializing the transpose, at ~f32 accuracy via a
    hand-rolled 3-pass bf16 split (hi/lo decomposition of each operand;
    the lo x lo term is below f32 noise). Mosaic lowers only DEFAULT (one
    bf16 pass, ~3 decimal digits worse residuals) and HIGHEST (6 passes,
    2x slower than this with no measurable residual gain on Cholesky:
    7.7e-7 vs 8.8e-7 at n=1024)."""
    dims = (((1,), (1,)), ((), ()))
    return _split3(
        lambda x, y: jax.lax.dot_general(
            x, y, dimension_numbers=dims,
            preferred_element_type=jnp.float32,
        ),
        a, b,
    )


def _split3(d, a, b):
    """The shared 3-pass bf16 hi/lo split: decompose both operands, sum the
    three passes whose products are above f32 noise (lo x lo is not).
    ``d`` supplies the contraction (NT / TN / NN variants below)."""
    ah = a.astype(jnp.bfloat16)
    al = (a - ah.astype(jnp.float32)).astype(jnp.bfloat16)
    bh = b.astype(jnp.bfloat16)
    bl = (b - bh.astype(jnp.float32)).astype(jnp.bfloat16)
    return d(ah, bh) + d(ah, bl) + d(al, bh)


def _mm_tn(a, b):
    """a^T @ b (contraction over axis 0 of both) via the 3-pass bf16
    hi/lo split - the rank-8 panel contraction of factor_tile."""
    dims = (((0,), (0,)), ((), ()))
    return _split3(
        lambda x, y: jax.lax.dot_general(
            x, y, dimension_numbers=dims,
            preferred_element_type=jnp.float32,
        ),
        a, b,
    )


def mm_nn(a, b):
    """a @ b at ~f32 accuracy via the same 3-pass bf16 hi/lo split as
    mm_nt (2x the throughput of Precision.HIGHEST's 6 passes)."""
    return _split3(
        lambda x, y: jnp.dot(x, y, preferred_element_type=jnp.float32),
        a, b,
    )


def dma_copy(src, dst, sem):
    """Start + wait one async copy (task kernels stage HBM<->VMEM)."""
    cp = pltpu.make_async_copy(src, dst, sem)
    cp.start()
    cp.wait()
