"""MXU tile kernels shared by device task kernels.

Designed for the TPU compute units rather than translated from LAPACK
(used by hclib_tpu/device/cholesky.py; reference workload
test/cholesky/cholesky.cpp):

- ``factor_tile`` (VPU): lower-Cholesky of a symmetric tile as masked
  rank-1 updates - row j equals column j by symmetry, so both outer-product
  factors come from cheap masked reductions; no transposes, no dynamic lane
  indexing.
- ``tri_inverse`` (MXU): inv(L) via Newton-Schulz X <- X(2I - LX), *exact*
  for triangular matrices after ceil(log2 T) steps - matmuls instead of a
  scalar substitution sweep.
- ``mm_nt`` (MXU): A @ B^T as a dot_general contraction on the second axis
  of both operands (no materialized transpose). HIGHEST precision keeps f32
  inputs f32 on the MXU.
- ``dma_copy``: start+wait of a Pallas async copy (HBM<->VMEM staging in
  task kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu

__all__ = ["factor_tile", "tri_inverse", "mm_nt", "dma_copy"]


def factor_tile(t, ts: int):
    """Lower-Cholesky a symmetric (ts, ts) tile with masked rank-1 updates."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)

    def body(j, carry):
        s, l = carry
        diag = jnp.sum(jnp.where((rows == j) & (cols == j), s, 0.0))
        inv_sqrt = jax.lax.rsqrt(diag)
        col = jnp.sum(jnp.where(cols == j, s, 0.0), axis=1, keepdims=True)
        row = jnp.sum(jnp.where(rows == j, s, 0.0), axis=0, keepdims=True)
        lcol = jnp.where(rows >= j, col * inv_sqrt, 0.0)
        l = jnp.where(cols == j, lcol, l)
        upd = (col * row) / diag
        s = jnp.where((rows > j) & (cols > j), s - upd, s)
        return s, l

    _, l = jax.lax.fori_loop(0, ts, body, (t, jnp.zeros_like(t)))
    return l


def tri_inverse(l, ts: int):
    """inv(L) for lower-triangular L via Newton-Schulz (exact in log2 ts)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)
    dg = jnp.sum(jnp.where(rows == cols, l, 0.0), axis=1, keepdims=True)
    x = jnp.where(rows == cols, 1.0 / dg, 0.0)
    steps = max(1, int(np.ceil(np.log2(ts))))
    hi = jax.lax.Precision.HIGHEST
    for _ in range(steps):
        lx = jnp.dot(l, x, preferred_element_type=jnp.float32, precision=hi)
        x = 2.0 * x - jnp.dot(
            x, lx, preferred_element_type=jnp.float32, precision=hi
        )
    return x


def mm_nt(a, b):
    """a @ b^T without materializing the transpose. HIGHEST precision keeps
    f32 inputs f32 on the MXU (default rounds through bf16 passes, costing
    ~3 decimal digits on factorization residuals)."""
    return jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def dma_copy(src, dst, sem):
    """Start + wait one async copy (task kernels stage HBM<->VMEM)."""
    cp = pltpu.make_async_copy(src, dst, sem)
    cp.start()
    cp.wait()
