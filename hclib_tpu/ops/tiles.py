"""MXU tile kernels shared by device task kernels.

Designed for the TPU compute units rather than translated from LAPACK
(used by hclib_tpu/device/cholesky.py; reference workload
test/cholesky/cholesky.cpp):

- ``factor_tile`` (VPU): lower-Cholesky of a symmetric tile as masked
  rank-1 updates - row j equals column j by symmetry, so both outer-product
  factors come from cheap masked reductions; no transposes, no dynamic lane
  indexing.
- ``tri_inverse`` (MXU): inv(L) via Newton-Schulz X <- X(2I - LX), *exact*
  for triangular matrices after ceil(log2 T) steps - matmuls instead of a
  scalar substitution sweep.
- ``factor_and_inv``: (L, inv(L)) for any tile size - the serial sweep
  runs only on 128x128 diagonal base blocks; larger tiles recurse by 2x2
  blocking with panels/updates/inverse as MXU block algebra.
- ``mm_nt`` (MXU): A @ B^T as a dot_general contraction on the second axis
  of both operands (no materialized transpose), at ~f32 accuracy via a
  3-pass bf16 hi/lo split (2x the throughput of HIGHEST's 6 passes).
- ``dma_copy``: start+wait of a Pallas async copy (HBM<->VMEM staging in
  task kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "factor_tile", "tri_inverse", "factor_and_inv", "mm_nt", "dma_copy",
    "split_bf16", "mm_nt_split", "mm_nt_rsplit",
]


PANEL = 8  # factor-panel width: one sublane group


def _chol8_and_inv(d8):
    """Serial lower-Cholesky + inverse of an (8, 8) block, fully unrolled
    with static slices (the only truly sequential math in the tile
    factorization; everything around it is MXU block algebra). Returns
    (L8, inv(L8))."""
    rows8 = jax.lax.broadcasted_iota(jnp.int32, (PANEL, PANEL), 0)
    cols8 = jax.lax.broadcasted_iota(jnp.int32, (PANEL, PANEL), 1)
    s8 = d8
    lcols = []
    for q in range(PANEL):
        dq = jax.lax.slice(s8, (q, q), (q + 1, q + 1))
        colq = jax.lax.slice(s8, (0, q), (PANEL, q + 1))
        c = jnp.where(rows8[:, :1] >= q, colq * jax.lax.rsqrt(dq), 0.0)
        lcols.append(c)
        s8 = jnp.where(
            (rows8 > q) & (cols8 > q), s8 - c * jnp.transpose(c), s8
        )
    l8 = jnp.concatenate(lcols, axis=1)
    # Forward substitution, unrolled: row i of inv solves L X = I.
    # (A Newton-Schulz inverse on the (8, 8) block was measured: it
    # shortens the serial chain and buys ~2.6% end-to-end, but costs
    # accuracy the n=8192 residual gate cannot spare - 9.84e-7 vs this
    # form's 9.26e-7 against the 1e-6 bound.)
    xrows = []
    for i in range(PANEL):
        acc = (cols8[:1] == i).astype(d8.dtype)
        for j in range(i):
            lij = jax.lax.slice(l8, (i, j), (i + 1, j + 1))
            acc = acc - lij * xrows[j]
        dii = jax.lax.slice(l8, (i, i), (i + 1, i + 1))
        xrows.append(acc / dii)
    return l8, jnp.concatenate(xrows, axis=0)


def factor_tile(t, ts: int):
    """Panel-blocked lower-Cholesky of a symmetric (ts, ts) tile.

    Exploits symmetry: for the 8-row panel J, the rows s[J, :] ARE the
    columns s[:, J] transposed, so each panel factorization runs on one
    (8, ts) sublane block. The serial math is confined to the panel's
    8x8 diagonal block (_chol8_and_inv, static slices on (8, 8) arrays);
    the rest of the panel's U rows come from ONE (8, 8) @ (8, ts)
    triangular-solve matmul (U_panel = inv(L8) @ S_panel), and the
    trailing matrix takes one rank-8 MXU update per panel (3-pass bf16
    split, ~f32 exact). This replaces the earlier formulation's 8
    full-width masked rank-1 micro-iterations per panel - whose chained
    (8, ts) reductions, not FLOPs, dominated the POTRF tasks' wall clock
    (measured 138 us/task at tile 512, ~31% of the whole n=8192
    factorization).

    Builds U = L^T row-by-row and transposes once at the end.
    """
    assert ts % PANEL == 0, ts
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)
    lanep = jax.lax.broadcasted_iota(jnp.int32, (PANEL, ts), 1)
    s = t
    pans = []
    npanels = ts // PANEL
    for p in range(npanels):
        j0 = p * PANEL
        pan = jax.lax.slice(s, (j0, 0), (j0 + PANEL, ts))
        d8 = jax.lax.slice(pan, (0, j0), (PANEL, j0 + PANEL))
        l8, i8 = _chol8_and_inv(d8)
        # U rows of this panel: inv(L8) @ S[j0:j0+8, :], valid for
        # columns > the diagonal block; the block itself is exactly L8^T
        # (spliced in via static concatenate + mask - Mosaic lowers
        # neither dynamic_update_slice nor pad), columns left of the
        # panel are zeroed.
        u = mm_nn(i8, pan)
        parts = []
        if j0:
            parts.append(jnp.zeros((PANEL, j0), t.dtype))
        parts.append(jnp.transpose(l8))
        if ts - j0 - PANEL:
            parts.append(jnp.zeros((PANEL, ts - j0 - PANEL), t.dtype))
        l8w = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        u = jnp.where((lanep >= j0) & (lanep < j0 + PANEL), l8w, u)
        u = jnp.where(lanep >= j0, u, 0.0)
        pans.append(u)
        if p + 1 < npanels:
            # Rank-8 trailing update in one contraction over the panel:
            # s[m, n] -= sum_q L[m, j0+q] L[n, j0+q] = (u^T u)[m, n].
            upd8 = _mm_tn(u, u)
            edge = j0 + PANEL - 1
            s = jnp.where((rows > edge) & (cols > edge), s - upd8, s)
    return jnp.transpose(jnp.concatenate(pans, axis=0))


def tri_inverse(l, ts: int):
    """inv(L) for lower-triangular L via Newton-Schulz (exact in log2 ts).

    L is constant across the iterations, so its bf16 hi/lo split is
    hoisted out of the loop (each iteration then splits only the two
    fresh operands x and Lx)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ts, ts), 1)
    dg = jnp.sum(jnp.where(rows == cols, l, 0.0), axis=1, keepdims=True)
    x = jnp.where(rows == cols, 1.0 / dg, 0.0)
    steps = max(1, int(np.ceil(np.log2(ts))))
    lh, ll = split_bf16(l)
    for _ in range(steps):
        xh, xl = split_bf16(x)
        lx = _d_nn(lh, xh) + _d_nn(lh, xl) + _d_nn(ll, xh)
        lxh, lxl = split_bf16(lx)
        x = 2.0 * x - (
            _d_nn(xh, lxh) + _d_nn(xh, lxl) + _d_nn(xl, lxh)
        )
    return x


def factor_and_inv(t, ts: int, base: int = 128):
    """(L, inv(L)) for a symmetric (ts, ts) tile.

    The scalar rank-1 sweep (factor_tile) costs O(ts) serial iterations on
    O(ts^2) planes - ~100us at ts=256 - so tiles larger than ``base`` are
    factored recursively by 2x2 blocking, keeping the sweep on base-sized
    diagonal blocks and doing panels/updates/inverses as MXU block algebra:

        A = [[A00,  . ], [A10, A11]]
        L00, I00 = factor_and_inv(A00);  L10 = A10 I00^T
        L11, I11 = factor_and_inv(A11 - L10 L10^T)
        inv(L)   = [[I00, 0], [-I11 L10 I00, I11]]
    """
    if ts <= base:
        l = factor_tile(t, ts)
        return l, tri_inverse(l, ts)
    h = ts // 2
    a00 = jax.lax.slice(t, (0, 0), (h, h))
    a10 = jax.lax.slice(t, (h, 0), (ts, h))
    a11 = jax.lax.slice(t, (h, h), (ts, ts))
    l00, i00 = factor_and_inv(a00, h, base)
    l10 = mm_nt(a10, i00)
    l11, i11 = factor_and_inv(a11 - mm_nt(l10, l10), h, base)
    off = -mm_nn(mm_nn(i11, l10), i00)
    z = jnp.zeros((h, h), t.dtype)
    l = jnp.concatenate(
        [jnp.concatenate([l00, z], 1), jnp.concatenate([l10, l11], 1)], 0
    )
    inv = jnp.concatenate(
        [jnp.concatenate([i00, z], 1), jnp.concatenate([off, i11], 1)], 0
    )
    return l, inv


def split_bf16(x):
    """bf16 hi/lo decomposition of an f32 array: x ~= hi + lo with the
    lo term holding the next ~8 mantissa bits. The shared building block
    of every 3-pass ~f32 matmul here; task kernels also use it to STORE
    operands pre-split (hclib_tpu/device/cholesky.py keeps the L tiles in
    split form so the trailing-update hot loop runs zero VPU splits)."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _d_nt(x, y):
    return jax.lax.dot_general(
        x, y, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _d_nn(x, y):
    return jnp.dot(x, y, preferred_element_type=jnp.float32)


def mm_nt(a, b):
    """a @ b^T without materializing the transpose, at ~f32 accuracy via a
    hand-rolled 3-pass bf16 split (hi/lo decomposition of each operand;
    the lo x lo term is below f32 noise). Mosaic lowers only DEFAULT (one
    bf16 pass, ~3 decimal digits worse residuals) and HIGHEST (6 passes,
    2x slower than this with no measurable residual gain on Cholesky:
    7.7e-7 vs 8.8e-7 at n=1024)."""
    return _split3(_d_nt, a, b)


def mm_nt_split(ah, al, bh, bl):
    """a @ b^T with BOTH operands already bf16 hi/lo split: the three MXU
    passes and nothing else - the hot-loop form for kernels that stream
    pre-split operands (identical rounding to mm_nt on the unsplit
    values)."""
    return _d_nt(ah, bh) + _d_nt(ah, bl) + _d_nt(al, bh)


def mm_nt_rsplit(a, bh, bl):
    """a @ b^T with only the RIGHT operand pre-split (a is split here)."""
    ah, al = split_bf16(a)
    return _d_nt(ah, bh) + _d_nt(ah, bl) + _d_nt(al, bh)


def _split3(d, a, b):
    """The shared 3-pass bf16 hi/lo split: decompose both operands, sum the
    three passes whose products are above f32 noise (lo x lo is not).
    ``d`` supplies the contraction (NT / TN / NN variants below)."""
    ah, al = split_bf16(a)
    bh, bl = split_bf16(b)
    return d(ah, bh) + d(ah, bl) + d(al, bh)


def _mm_tn(a, b):
    """a^T @ b (contraction over axis 0 of both) via the 3-pass bf16
    hi/lo split - the rank-8 panel contraction of factor_tile."""
    dims = (((0,), (0,)), ((), ()))
    return _split3(
        lambda x, y: jax.lax.dot_general(
            x, y, dimension_numbers=dims,
            preferred_element_type=jnp.float32,
        ),
        a, b,
    )


def mm_nn(a, b):
    """a @ b at ~f32 accuracy via the same 3-pass bf16 hi/lo split as
    mm_nt (2x the throughput of Precision.HIGHEST's 6 passes)."""
    return _split3(
        lambda x, y: jnp.dot(x, y, preferred_element_type=jnp.float32),
        a, b,
    )


def dma_copy(src, dst, sem):
    """Start + wait one async copy (task kernels stage HBM<->VMEM)."""
    cp = pltpu.make_async_copy(src, dst, sem)
    cp.start()
    cp.wait()
