"""Reusable device op kernels: the MXU/VPU building blocks task kernels
compose (the layer the package docstring calls ``hclib_tpu.ops``).

- ``tiles``: MXU tile linear algebra (transpose-free A@B^T contraction,
  masked rank-1 Cholesky factorization, Newton-Schulz triangular inverse)
  and the DMA start/wait helper used by megakernel task kernels.
- ``sha1``: the FIPS-180-1 compression function vectorized over arrays of
  any shape, generic over numpy (host seeding) and jnp (device planes) -
  the UTS splittable RNG.
- ``scan``: decay-cummax, the log-depth solution of recurrences
  c[j] = max(t[j], c[j-1] - g) used by the Smith-Waterman row sweep.
"""

from .scan import decay_cummax  # noqa: F401
from .sha1 import sha1_block, sha1_child  # noqa: F401
from .tiles import (  # noqa: F401
    dma_copy,
    factor_and_inv,
    factor_tile,
    mm_nt,
    tri_inverse,
)

__all__ = [
    "decay_cummax",
    "sha1_block",
    "sha1_child",
    "dma_copy",
    "factor_and_inv",
    "factor_tile",
    "mm_nt",
    "tri_inverse",
]
