"""Binary event instrumentation.

Reference design (src/hclib-instrument.c): per-thread double-buffered event
arrays flushed via POSIX AIO to ``$HCLIB_DUMP_DIR/hclib.<ts>.dump/<tid>``;
an event is {timestamp_ns, event_type, START/END transition, id}
(inc/hclib-instrument.h:20-33); event types are registered by name and
written to an ``event_types`` manifest. Notably the reference's recorder is
stubbed out (src/hclib-instrument.c:211-252 returns -1) - scaffolding only.
This implementation is live.

Events are fixed-width records in a per-worker numpy ring (the double buffer:
when a ring fills it is handed to a writer and a fresh one continues
recording), dumped as raw little-endian binary plus a JSON manifest, with a
reader (`load_dump`) so traces are usable in-process.

Enable via ``Runtime(instrument=True)`` or env ``HCLIB_TPU_INSTRUMENT=1``;
dump dir from ``HCLIB_TPU_DUMP_DIR`` (default ``.``), mirroring the
reference's HCLIB_INSTRUMENT / HCLIB_DUMP_DIR envs.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "EventLog",
    "register_event_type",
    "event_type_id",
    "START",
    "END",
    "SINGLE",
    "load_dump",
    "load_manifest",
]

START = 0
END = 1
SINGLE = 2

_EVENT_DTYPE = np.dtype(
    [("ts_ns", "<u8"), ("type", "<u4"), ("transition", "<u4"), ("id", "<u8")]
)

_type_lock = threading.Lock()
_type_names: List[str] = []
_type_ids: Dict[str, int] = {}


def register_event_type(name: str) -> int:
    """Register (or look up) an event type by name; returns its id
    (register_event_type, inc/hclib-instrument.h:53)."""
    with _type_lock:
        if name in _type_ids:
            return _type_ids[name]
        tid = len(_type_names)
        _type_names.append(name)
        _type_ids[name] = tid
        return tid


def event_type_id(name: str) -> Optional[int]:
    with _type_lock:
        return _type_ids.get(name)


class _WorkerBuffer:
    """Double-buffered event ring for one worker."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self.buf = np.zeros(capacity, dtype=_EVENT_DTYPE)
        self.n = 0
        self.full: List[np.ndarray] = []

    def record(self, ts: int, type_: int, transition: int, eid: int) -> None:
        if self.n == self.capacity:
            self.full.append(self.buf)
            self.buf = np.zeros(self.capacity, dtype=_EVENT_DTYPE)
            self.n = 0
        self.buf[self.n] = (ts, type_, transition, eid)
        self.n += 1

    def drain(self) -> np.ndarray:
        parts = self.full + [self.buf[: self.n]]
        self.full = []
        self.n = 0
        return np.concatenate(parts) if len(parts) > 1 else parts[0]


class EventLog:
    """Per-worker event recording + binary dump.

    Worker buffers are lock-free by construction (each is written by
    exactly one worker thread). Records from NON-worker threads - module
    init, procworld progress engines, the watchdog, the main launch
    context before identity binding - used to be silently dropped
    (worker_id outside ``[0, nworkers)``); they now land in a shared
    **external lane** (lane index ``nworkers`` in the dump, guarded by a
    lock since any thread may write it) and are counted in
    ``external_records``. Dumps name the lane in the manifest so readers
    can label it."""

    def __init__(self, nworkers: int, capacity: int = 1 << 16) -> None:
        self.nworkers = nworkers
        # +1: the external overflow lane for non-worker threads.
        self._buffers = [
            _WorkerBuffer(capacity) for _ in range(nworkers + 1)
        ]
        self._id_lock = threading.Lock()
        self._next_id = 0
        self._ext_lock = threading.Lock()
        self.external_records = 0
        # Per-worker id counters (worker w mints w+1 + k*(nworkers+1)):
        # striping keeps ids process-unique WITHOUT the global lock that
        # used to sit on every task execution - a measured hot-path tax
        # guarded by tools/perf_regression.py's instrument-overhead entry.
        self._wid_next = [0] * nworkers

    def new_id(self, worker_id: Optional[int] = None) -> int:
        """Fresh correlation id for a START/END pair. With ``worker_id``
        (the recording worker) the id is minted lock-free from that
        worker's stripe; without, from the locked shared stripe 0."""
        if worker_id is not None and 0 <= worker_id < self.nworkers:
            self._wid_next[worker_id] += 1
            return worker_id + 1 + self._wid_next[worker_id] * (
                self.nworkers + 1
            )
        with self._id_lock:
            self._next_id += 1
            return self._next_id * (self.nworkers + 1)

    def record(self, worker_id: int, type_: int, transition: int = SINGLE,
               eid: int = 0) -> None:
        if 0 <= worker_id < self.nworkers:
            self._buffers[worker_id].record(
                time.monotonic_ns(), type_, transition, eid
            )
        else:
            # Any out-of-range id (None-identity threads pass -1) routes
            # to the shared lane; counted so the dump's completeness is
            # checkable.
            with self._ext_lock:
                self._buffers[self.nworkers].record(
                    time.monotonic_ns(), type_, transition, eid
                )
                self.external_records += 1

    def dump(self, directory: Optional[str] = None) -> str:
        """Write ``hclib.<ts>.dump/<worker>`` binary files + manifest
        (layout parity: src/hclib-instrument.c:50-83). Lane ``nworkers``
        is the external lane (named in the manifest)."""
        from .env import env_raw

        base = directory or env_raw("HCLIB_TPU_DUMP_DIR", ".")
        path = os.path.join(base, f"hclib.{int(time.time() * 1000)}.dump")
        os.makedirs(path, exist_ok=True)
        with _type_lock:
            names = list(_type_names)
        # Drain the external lane atomically with its counter so the
        # manifest count matches exactly what THIS dump's lane file holds
        # (dumps drain; a stale counter would advertise phantom records).
        with self._ext_lock:
            ext_events = self._buffers[self.nworkers].drain()
            ext_count = self.external_records
            self.external_records = 0
        with open(os.path.join(path, "event_types.json"), "w") as f:
            json.dump(
                {
                    "event_types": names,
                    "dtype": _EVENT_DTYPE.descr,
                    "nworkers": self.nworkers,
                    "external_lane": self.nworkers,
                    "external_records": ext_count,
                },
                f,
            )
        for w, b in enumerate(self._buffers[: self.nworkers]):
            b.drain().tofile(os.path.join(path, str(w)))
        ext_events.tofile(os.path.join(path, str(self.nworkers)))
        return path


def load_dump(path: str) -> Tuple[List[str], Dict[int, np.ndarray]]:
    """Read a dump directory back: (event type names, worker -> events).
    Lane ``manifest['external_lane']`` (when present) holds non-worker
    threads' records; ``load_manifest`` exposes the full manifest."""
    with open(os.path.join(path, "event_types.json")) as f:
        manifest = json.load(f)
    out: Dict[int, np.ndarray] = {}
    for entry in os.listdir(path):
        if entry.isdigit():
            out[int(entry)] = np.fromfile(
                os.path.join(path, entry), dtype=_EVENT_DTYPE
            )
    return manifest["event_types"], out


def load_manifest(path: str) -> Dict:
    """The dump's full manifest (event types, dtype, external-lane info;
    old dumps lack the lane keys - callers get {} defaults via .get)."""
    with open(os.path.join(path, "event_types.json")) as f:
        return json.load(f)
