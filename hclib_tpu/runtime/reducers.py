"""Worker-local reducers ("atomics" in the reference).

Per-worker padded slots updated without synchronization (worker-serial), then
gathered at read time (reference: src/hclib_atomic.c, inc/hclib_atomic.h:
82-186 - atomic_t<T>, atomic_sum_t/max_t/or_t). On device, the analogue is a
per-core accumulator in VMEM reduced at kernel exit.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, List

from . import scheduler

__all__ = ["Reducer", "SumReducer", "MaxReducer", "OrReducer"]


class Reducer:
    def __init__(self, init: Any, gather: Callable[[Any, Any], Any]) -> None:
        rt = scheduler.current_runtime()
        self._init = init
        self._gather = gather
        self._vals: List[Any] = [init for _ in range(rt.nworkers)]

    def update(self, fn: Callable[[Any], Any]) -> None:
        w = scheduler.current_worker()
        if w < 0:
            w = 0
        self._vals[w] = fn(self._vals[w])

    def gather(self) -> Any:
        acc = self._init
        for v in self._vals:
            acc = self._gather(acc, v)
        return acc


class SumReducer(Reducer):
    def __init__(self, init: Any = 0) -> None:
        super().__init__(init, operator.add)

    def add(self, v: Any) -> None:
        self.update(lambda x: x + v)


class MaxReducer(Reducer):
    def __init__(self, init: Any = float("-inf")) -> None:
        super().__init__(init, max)

    def put(self, v: Any) -> None:
        self.update(lambda x: x if x >= v else v)


class OrReducer(Reducer):
    def __init__(self, init: int = 0) -> None:
        super().__init__(init, operator.or_)

    def put(self, v: int) -> None:
        self.update(lambda x: x | v)
