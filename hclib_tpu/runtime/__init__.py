"""Host-side runtime: the semantic core of hclib_tpu.

Pins the reference's finish/async/promise/forasync semantics on the host
before the TPU device path re-implements them on-chip (see ../device/).
"""

from .deque import WSDeque
from .finish import Finish
from .forasync import FLAT, RECURSIVE, forasync, forasync_future, register_dist_func
from .locality import (
    Locale,
    LocalityGraph,
    MeshPlacement,
    generate_default_graph,
    load_locality_file,
    resolve_placement,
    steal_hop_order,
)
from .autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    Observation,
    ScaleEvent,
)
from .checkpoint import (
    BundleFault,
    BundleStore,
    CheckpointBundle,
    CheckpointError,
    checkpoint_on_preempt,
    default_store,
    restore_megakernel,
    restore_resident,
    restore_stream,
    snapshot_megakernel,
    snapshot_resident,
    snapshot_stream,
)
from .instrument import EventLog, load_dump, register_event_type
from .mem import allocate_at, async_copy, free_at, memset_at
from .metrics import MetricsRegistry
from .module import Module, register_module, unregister_all_modules
from .promise import Future, Promise, PromiseError
from .reducers import MaxReducer, OrReducer, Reducer, SumReducer
from .resilience import (
    CancelScope,
    CancelledError,
    DeviceFaultPlan,
    FaultPlan,
    InjectedFault,
    RetryPolicy,
    StallError,
)
from .scheduler import (
    Runtime,
    async_,
    async_future,
    current_finish,
    current_runtime,
    current_worker,
    end_finish,
    end_finish_nonblocking,
    finish,
    launch,
    num_workers,
    start_finish,
    run_on_main,
    yield_,
)
from .task import Task
from .timer import IDLE, OVH, SEARCH, WORK, StateTimer
