"""Clock-window telemetry: a fixed MXU probe that labels fast vs throttled
measurement windows, so benchmark numbers are interpretable.

Problem (VERDICT r3, weak items 2-4): the tunnel-attached TPU oscillates
between fast and throttled clock windows with a 2-3x spread over minutes.
A headline number taken in an unlabeled window is ambiguous between a code
regression and weather, and round-over-round records (batch tier 2.30 G ->
1.44 G tasks/s, same code) could not be explained.

Mechanism: a fixed bf16 matmul chain whose achieved TFLOP/s is measured by
the slope between two chain lengths (cancelling the ~70 ms tunnel
launch/transfer overhead, the same harness trick bench.py uses). Sampling
the probe before and after a trial brackets it:

- both samples >= ``fast_frac`` x the best probe seen  -> "fast" window
- either sample below                                   -> "throttled"

``WindowedTrials`` wraps a trial loop: each trial is bracketed, labeled,
and appended to ``perf-logs/clock_<ts>.jsonl`` (one JSON object per line:
probe rates, label, the trial's own metric). The number of record is then
``best_fast`` / ``median_fast`` - statistics over FAST-window trials only -
with the distribution preserved in the log so a future regression is
distinguishable from throttling by reading the probe columns.

The reference has no analogue (its perf-regression logs are raw means,
test/performance-regression/full-apps/); this subsystem exists because
shared/tunneled TPUs are the deployment reality here.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

__all__ = ["ClockProbe", "EpochBracket", "WindowedTrials"]


class EpochBracket:
    """Rounds -> wall-clock conversion for the telemetry plane (ISSUE 19).

    The megakernel has no device wall clock; latency histograms count
    *scheduler rounds*. Each streaming entry brackets its jitted call
    with ``time.monotonic_ns()`` and reports the round-gauge delta the
    kernel echoed; ``accumulate`` folds those (t0, t1, rounds) triples
    into a cumulative epoch so ``ns_per_round`` is the session-wide
    wall-ns / rounds ratio. Entries that advanced zero rounds (pure
    host-side polls) still contribute wall time - the ratio reflects
    what a round *costs end to end* through the tunnel, which is the
    honest conversion for host-facing latency quantiles.

    Monotone by construction: ``total_ns`` and ``total_rounds`` only
    grow, and negative brackets (clock steps, resume re-seeds) are
    clamped to zero rather than rewinding the epoch.
    """

    def __init__(self) -> None:
        self.total_ns = 0
        self.total_rounds = 0
        self.entries = 0

    def accumulate(self, t0_ns: int, t1_ns: int, rounds: int) -> None:
        self.total_ns += max(int(t1_ns) - int(t0_ns), 0)
        self.total_rounds += max(int(rounds), 0)
        self.entries += 1

    def ns_per_round(self) -> Optional[float]:
        """Wall nanoseconds per scheduler round; None before any rounds."""
        if self.total_rounds <= 0:
            return None
        return self.total_ns / self.total_rounds

    def to_ns(self, rounds: float) -> Optional[float]:
        """Convert a round count to nanoseconds (None before any epoch)."""
        npr = self.ns_per_round()
        if npr is None:
            return None
        return float(rounds) * npr


class ClockProbe:
    """Fixed-matmul clock probe. ``sample()`` returns achieved TFLOP/s.

    One timed call of a k-long dependent matmul chain, sized so compute
    (~1.5 s at full clock) dominates the tunnel round-trip (~0.8 s
    observed). The reported rate is therefore biased LOW by a roughly
    constant additive overhead - irrelevant for labeling, where the
    signal being classified is a 2-3x multiplicative clock spread. (A
    slope between two chain lengths would remove the bias but needs 4+
    round-trips per sample; measured RTT jitter here makes that noisier
    than the single-shot form.)"""

    def __init__(
        self,
        device=None,
        n: int = 2048,
        chain: int = 6000,
        fast_frac: float = 0.75,
    ) -> None:
        import jax
        import jax.numpy as jnp

        self.n = int(n)
        self.chain = int(chain)
        self.fast_frac = float(fast_frac)
        self.best = 0.0
        self.samples: List[Dict] = []
        rng = np.random.default_rng(0)
        # Tiny entries so the dependent chain underflows toward zero
        # instead of inf (MXU speed is value-independent; this just keeps
        # the buffers tame).
        a = (rng.standard_normal((n, n)) * 1e-3).astype(jnp.bfloat16)
        b = (rng.standard_normal((n, n)) * 1e-3).astype(jnp.bfloat16)
        if device is not None:
            a, b = jax.device_put(a, device), jax.device_put(b, device)
        k = self.chain

        def chainf(a, b):
            def body(i, c):
                return jax.numpy.dot(
                    c, b, preferred_element_type=jnp.bfloat16
                )

            return jax.lax.fori_loop(0, k, body, a)

        self._fn = jax.jit(chainf)
        self._fn(a, b)  # compile + warm
        self._a, self._b = a, b

    def sample(self, context: str = "") -> float:
        t0 = time.perf_counter()
        out = self._fn(self._a, self._b)
        # D2H of a scalar is the only reliable sync through the tunnel
        # (block_until_ready can return early on remote arrays).
        _ = np.asarray(out[0, 0])
        dt = time.perf_counter() - t0
        tflops = 2.0 * self.n**3 * self.chain / dt / 1e12
        self.best = max(self.best, tflops)
        self.samples.append(
            {"t": time.time(), "probe_tflops": round(tflops, 2),
             "context": context}
        )
        return tflops

    def is_fast(self, tflops: float) -> bool:
        return tflops >= self.fast_frac * self.best


class WindowedTrials:
    """Bracket trials with clock-probe samples; aggregate over fast windows.

    ``run(fn)`` executes one trial (``fn() -> metric value, higher =
    better``), labels its window, logs it. ``stats()`` returns
    best/median over fast-window trials (falling back to all trials if no
    window was fast - then the label says so).
    """

    def __init__(
        self,
        name: str,
        probe: Optional[ClockProbe] = None,
        log_dir: str = "perf-logs",
        device=None,
    ) -> None:
        self.name = name
        self.probe = probe or ClockProbe(device=device)
        self.trials: List[Dict] = []
        self._path = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._path = os.path.join(
                log_dir, f"clock_{int(time.time())}_{name}.jsonl"
            )

    def run(self, fn: Callable[[], float], note: str = "") -> Dict:
        pre = self.probe.sample(f"{self.name}:pre")
        value = fn()
        post = self.probe.sample(f"{self.name}:post")
        rec = {
            "name": self.name,
            "t": time.time(),
            "value": value,
            "probe_pre_tflops": round(pre, 2),
            "probe_post_tflops": round(post, 2),
            "note": note,
        }
        self.trials.append(rec)
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec

    def _labeled(self):
        # Labels are assigned retroactively against the best probe seen
        # across the WHOLE session, so an all-throttled early trial can't
        # self-certify as fast.
        out = []
        for r in self.trials:
            fast = self.probe.is_fast(
                min(r["probe_pre_tflops"], r["probe_post_tflops"])
            )
            out.append((r, "fast" if fast else "throttled"))
        return out

    def _fast_values(self):
        """The fast-window (non-sheared) trial values - the ONE definition
        both stats() and count_fast build on, so bench.py's retry stopping
        rule can't diverge from the n_fast the stats label reports."""
        return [
            r["value"] for r, lb in self._labeled()
            if lb == "fast" and r["value"] > 0
        ]

    def count_fast(self) -> int:
        """Trials currently labeled fast (the statistic bench.py's retry
        loop stops on)."""
        return len(self._fast_values())

    def stats(self) -> Dict:
        labeled = self._labeled()
        # Slope-based trials can yield nonpositive values under extreme
        # clock shear (the two timed legs straddled a window edge);
        # exclude them from statistics rather than poisoning medians.
        # n_trials still counts every trial run (the jsonl records them
        # all), so a dropped trial is visible as n_trials > n_used.
        fast_vals = self._fast_values()
        all_vals = [r["value"] for r, _ in labeled if r["value"] > 0]
        if fast_vals:
            pool, label = fast_vals, "fast"
        elif all_vals:
            pool, label = all_vals, "all-throttled"
        else:
            # Every trial was sheared (nonpositive): report 0.0 rather
            # than None so formatters downstream stay total; the window
            # label says why.
            pool, label = [0.0], "all-sheared"
        s = {
            "name": self.name,
            "window": label,
            "n_trials": len(labeled),
            "n_used": len(all_vals),
            "n_fast": len(fast_vals),
            "best": max(pool),
            "median": float(np.median(pool)),
            "spread": (
                round(max(all_vals) / min(all_vals), 2) if all_vals else None
            ),
            "probe_best_tflops": round(self.probe.best, 2),
        }
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps({"summary": s}) + "\n")
        return s
