"""Locale-aware memory operations.

Each operation spawns an async *at* the target locale and returns a future -
the allocation/copy physically happens on whichever worker services that
locale (reference: src/hclib-mem.c:59-79 alloc, :175-241 copy). Handler
resolution between source and destination modules follows the reference's
MUST_USE/MAY_USE priority rule (src/hclib-mem.c:198-221): a MUST_USE handler
on either side wins; ties go to the destination.

On TPU the interesting instances are host<->device transfers: the ``tpu``
module registers copy handlers that call ``jax.device_put`` / ``device_get``
from a host worker servicing the TPU locale.
"""

from __future__ import annotations

from typing import Any, Optional

from .locality import Locale
from .module import mem_fns_for
from .promise import Future
from .scheduler import async_future

__all__ = ["allocate_at", "free_at", "memset_at", "async_copy"]


def _handler(locale: Locale, op: str):
    ent = mem_fns_for(locale.type).get(op)
    if ent is None:
        raise ValueError(f"no {op!r} handler registered for locale type {locale.type!r}")
    return ent


def allocate_at(nbytes_or_shape: Any, locale: Locale, **kw: Any) -> Future:
    prio, fn = _handler(locale, "alloc")
    return async_future(fn, nbytes_or_shape, locale, at=locale, non_blocking=True, **kw)


def free_at(buf: Any, locale: Locale) -> Future:
    prio, fn = _handler(locale, "free")
    return async_future(fn, buf, locale, at=locale, non_blocking=True)


def memset_at(buf: Any, value: int, locale: Locale) -> Future:
    prio, fn = _handler(locale, "memset")
    return async_future(fn, buf, value, locale, at=locale, non_blocking=True)


def async_copy(
    dst: Any,
    dst_locale: Locale,
    src: Any,
    src_locale: Locale,
    nelems: Optional[int] = None,
) -> Future:
    """Copy src@src_locale -> dst@dst_locale; handler picked by priority
    (reference: src/hclib-mem.c:198-221)."""
    dst_ent = mem_fns_for(dst_locale.type).get("copy")
    src_ent = mem_fns_for(src_locale.type).get("copy")
    if dst_ent is None and src_ent is None:
        raise ValueError(
            f"no copy handler for locale types {dst_locale.type!r}/{src_locale.type!r}"
        )
    if dst_ent is None:
        ent, at = src_ent, src_locale
    elif src_ent is None:
        ent, at = dst_ent, dst_locale
    else:
        # Higher priority wins; tie -> destination side.
        ent, at = (src_ent, src_locale) if src_ent[0] > dst_ent[0] else (dst_ent, dst_locale)
    _, fn = ent
    return async_future(
        fn, dst, dst_locale, src, src_locale, nelems, at=at, non_blocking=True
    )
