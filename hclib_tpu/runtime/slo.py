"""SLO engine over the live telemetry plane (ISSUE 19): streaming
latency quantiles and multi-window burn rates from histogram deltas.

Input model: the on-device latency histograms (device/telemetry.py) are
CUMULATIVE log2-bucketed counters in scheduler rounds, echoed to the
host every streaming entry. ``SloEstimator.observe(counts, t_s)`` folds
one such snapshot in; everything else derives:

- ``quantiles()``: p50/p95/p99 over the whole stream so far, each the
  UPPER edge of the bucket holding that rank (``quantile_from_hist``) -
  a one-bucket-resolution bound, which is exactly the precision the
  acceptance tests hold the host-stamp comparison to.
- ``burn_rates()``: per configured window, the classic SRE burn rate
  ``(bad / total) / (1 - q)`` computed on the DELTA between now and the
  oldest retained sample inside the window - ``bad`` counts requests in
  buckets whose lower edge is >= the objective (whole buckets only, so
  a bucket straddling the objective is charitably counted good; the
  estimator never cries wolf from quantization). A burn of 1.0 means
  violations arrive exactly at the budget rate; 2.0 means the error
  budget for the window halves.
- ``latency_pressure()``: max burn across windows - the one scalar the
  autoscaler's ``Observation`` carries and the ``slo_out`` policy rung
  thresholds against (HCLIB_TPU_SLO_BURN).

No objective configured (``objective_rounds`` None and
HCLIB_TPU_SLO_OBJECTIVE_ROUNDS unset) means burn rates and pressure
read 0.0: the estimator is then a pure quantile tracker and the policy
rung is structurally dead - the same off-path discipline as the rest of
the plane.

Host-side only: no device words, no threads; samples are pruned to the
longest window so a long-lived server holds O(window / poll interval)
snapshots.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..device.telemetry import quantile_from_hist

__all__ = ["SloEstimator", "parse_windows"]


def parse_windows(text: str) -> Tuple[float, ...]:
    """Parse a comma-separated window list ("60,300") into seconds.
    Malformed or non-positive entries raise, naming the knob - an SLO
    misconfiguration must not silently change alerting windows."""
    out = []
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            raise ValueError(
                f"HCLIB_TPU_SLO_WINDOWS_S entry {part!r} is not a number"
            ) from None
        if w <= 0:
            raise ValueError(
                f"HCLIB_TPU_SLO_WINDOWS_S entry {part!r} must be > 0"
            )
        out.append(w)
    if not out:
        raise ValueError("HCLIB_TPU_SLO_WINDOWS_S parsed to no windows")
    return tuple(out)


class SloEstimator:
    """Streaming quantiles + burn rates over cumulative histograms.

    ``objective_rounds``/``quantile``/``windows_s`` default from the
    SLO registry knobs (runtime/env.py; malformed text raises there).
    Feed it with ``observe(counts, t_s)`` where
    ``counts`` is one tenant's (or the summed) cumulative bucket vector
    and ``t_s`` a monotonic clock - tests pass a fake clock.
    """

    def __init__(
        self,
        objective_rounds: Optional[int] = None,
        quantile: Optional[float] = None,
        windows_s: Optional[Sequence[float]] = None,
    ) -> None:
        from .env import env_float, env_int, env_str

        if objective_rounds is None:
            objective_rounds = env_int("HCLIB_TPU_SLO_OBJECTIVE_ROUNDS")
        if quantile is None:
            quantile = env_float("HCLIB_TPU_SLO_QUANTILE", 0.99)
        if windows_s is None:
            windows_s = parse_windows(
                env_str("HCLIB_TPU_SLO_WINDOWS_S", "60,300")
            )
        if not 0 < float(quantile) <= 1:
            raise ValueError(
                f"SLO quantile must be in (0, 1], got {quantile}"
            )
        if objective_rounds is not None and int(objective_rounds) < 0:
            raise ValueError(
                f"objective_rounds must be >= 0, got {objective_rounds}"
            )
        self.objective_rounds = (
            None if objective_rounds is None else int(objective_rounds)
        )
        self.quantile = float(quantile)
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        # (t_s, cumulative counts) samples, oldest first, pruned past
        # the longest window (one extra retained so a window's delta
        # always has a baseline at or before its left edge).
        self._samples: List[Tuple[float, np.ndarray]] = []

    # -- ingestion --

    def observe(self, counts, t_s: float) -> None:
        """Fold one cumulative histogram snapshot taken at ``t_s``."""
        c = np.asarray(counts, dtype=np.int64).reshape(-1)
        if self._samples and c.shape != self._samples[-1][1].shape:
            raise ValueError(
                f"histogram width changed: {self._samples[-1][1].shape}"
                f" -> {c.shape}"
            )
        self._samples.append((float(t_s), c.copy()))
        horizon = float(t_s) - max(self.windows_s)
        # Keep the newest sample at-or-before the horizon as the
        # baseline; drop everything older.
        while (
            len(self._samples) >= 2 and self._samples[1][0] <= horizon
        ):
            self._samples.pop(0)

    # -- derivations --

    @property
    def total(self) -> int:
        if not self._samples:
            return 0
        return int(self._samples[-1][1].sum())

    def quantiles(self, qs: Sequence[float] = (0.5, 0.95, 0.99)):
        """{q: upper-edge rounds} over the whole stream (None-valued
        before any sample lands)."""
        if not self._samples:
            return {float(q): None for q in qs}
        counts = self._samples[-1][1]
        return {
            float(q): quantile_from_hist(counts, float(q)) for q in qs
        }

    def _bad_total(self, delta: np.ndarray) -> Tuple[int, int]:
        """(violations, total) in a delta histogram: whole buckets whose
        lower edge is >= the objective count bad."""
        total = int(delta.sum())
        obj = self.objective_rounds
        if obj is None or total == 0:
            return 0, total
        bad = 0
        for i, c in enumerate(delta.tolist()):
            lo = 0 if i == 0 else 1 << i
            if lo >= obj:
                bad += int(c)
        return bad, total

    def burn_rates(self, now_s: Optional[float] = None):
        """{window_s: burn rate} from histogram deltas. A window with no
        baseline sample yet (stream younger than the window) deltas
        against the oldest sample - early storms still register."""
        out: Dict[float, float] = {}
        if not self._samples or self.objective_rounds is None:
            return {w: 0.0 for w in self.windows_s}
        t_now, cur = self._samples[-1]
        if now_s is not None:
            t_now = float(now_s)
        budget = 1.0 - self.quantile
        for w in self.windows_s:
            base = self._samples[0][1]
            for t, c in self._samples:
                if t <= t_now - w:
                    base = c
                else:
                    break
            bad, total = self._bad_total(cur - base)
            if total <= 0:
                out[w] = 0.0
            elif budget <= 0:
                # q == 1.0: zero error budget - any violation is an
                # infinite burn; report a large finite sentinel so the
                # pressure comparison stays total.
                out[w] = float("inf") if bad else 0.0
            else:
                out[w] = (bad / total) / budget
        return out

    def latency_pressure(self, now_s: Optional[float] = None) -> float:
        """Max burn rate across windows - 0.0 with no objective, no
        samples, or no violations; the Observation's pressure scalar."""
        rates = self.burn_rates(now_s)
        return max(rates.values()) if rates else 0.0

    def stats(self) -> Dict[str, object]:
        """Numeric summary for the metrics registry: total, quantile
        upper edges, per-window burns, pressure."""
        qs = self.quantiles()
        out: Dict[str, object] = {
            "total": self.total,
            "pressure": self.latency_pressure(),
            "objective_rounds": self.objective_rounds or 0,
        }
        for q, v in qs.items():
            if v is not None:
                out[f"p{int(q * 100)}_rounds"] = v
        for w, b in self.burn_rates().items():
            out[f"burn_{int(w)}s"] = b
        return out
