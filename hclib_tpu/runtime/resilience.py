"""Resilience: cancellation, deadlines, task retry, and deterministic chaos.

The reference documents that help-first blocking can deadlock
(test/deadlock/README) but ships no detection or recovery: a stalled
program hangs until the operator kills it, and a failed comm peer wedges
every rank blocked on it. This module gives the runtime a failure model
with *bounded latency*:

- ``CancelScope`` - every ``Finish`` carries one, chained parent-to-child
  exactly like the finish tree. Cancelling a scope makes (a) queued tasks
  of that scope (and descendants) drop instead of run, (b) spawns into it
  raise, and (c) blocked helpers/waiters wake and raise ``CancelledError``.
  Scope checks are epoch-guarded: until the first cancel anywhere in the
  process they cost one module-global int read, so the hot path pays
  nothing for the capability.

- ``StallError`` - the structured form of "this would have hung": raised
  by ``Runtime.run(deadline_s=...)``, ``Future.wait(timeout=...)``,
  ``end_finish(timeout=...)``, the watchdog's escalation ladder, and the
  device layer's stall/deadline detectors. Carries a stats snapshot so
  the failure is diagnosable post-mortem.

- ``RetryPolicy`` - per-spawn (or runtime-default) retry with exponential
  backoff and deterministic jitter. Tasks that exhaust their attempts are
  *quarantined*: recorded in ``Runtime.stats_dict()['resilience']`` with
  the terminal error, and optionally swallowed (``quarantine=True``) so
  one poison task cannot take down a batch run.

- ``FaultPlan`` - seeded, deterministic fault injection: task exceptions,
  delayed steals, worker death, and procworld peer crashes fire at points
  decided by ``hash(seed, site, n)`` where ``n`` is a per-site event
  counter. The *decision table* is a pure function of the seed, so the
  same seed yields the same failure trace (``FaultPlan.trace``) and every
  recovery path above is exercisable in CI on cue.

Wake protocol: cancellation must unpark blocked contexts promptly without
per-park polling (thousands of contexts may be parked). ``CancelScope.
cancel`` bumps the global epoch and invokes a waker the active runtime
registered (``set_cancel_waker``); the runtime sets every parked event,
and each woken context re-checks its own condition - spurious wakes are
safe because every park caller loops.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CancelScope",
    "CancelledError",
    "TenantExpired",
    "StallError",
    "InjectedFault",
    "RetryPolicy",
    "FaultPlan",
    "DeviceFaultPlan",
    "register_abort_hook",
    "unregister_abort_hook",
    "bind_abort_to_scope",
    "register_preempt_hook",
    "unregister_preempt_hook",
    "fire_preempt",
    "preempt_requested",
    "install_preempt_handler",
]

LOG = logging.getLogger("hclib_tpu.resilience")


class CancelledError(RuntimeError):
    """The enclosing scope was cancelled; a control signal, not a fault
    (the runtime does not record it as the run's first error)."""


class TenantExpired(CancelledError):
    """A tenant-lane submission's admission deadline passed - rejected at
    admission, dropped from the host backlog, or lazily discarded by the
    in-kernel tenant poll (device/tenants.py). A control signal like any
    cancellation: counted per tenant (``tenant.<id>.expired``), never
    recorded as the run's first error, and never retried."""


class StallError(RuntimeError):
    """A bounded-latency failure: deadline exceeded, wait timed out, or
    the watchdog escalated a stall. ``stats`` is the runtime (or device)
    counter snapshot at detection time."""

    def __init__(self, message: str, stats: Optional[dict] = None) -> None:
        super().__init__(message)
        self.stats = stats or {}


class InjectedFault(Exception):
    """A fault injected by a FaultPlan (retryable under the default
    RetryPolicy, like any plain Exception)."""


# ---------------------------------------------------------------- epoch/waker

# Fast path: until any scope in the process is ever cancelled, cancelled()
# is a single int comparison. One active runtime at a time (enforced in
# Runtime.run), so a module-level waker suffices.
_cancel_epoch = 0
_waker_lock = threading.Lock()
_waker = None
# Abort hooks: device-side kill switches (StreamingMegakernel.abort and
# friends) registered while a device stream is live, so cancelling ANY
# scope propagates INTO running device kernels (the abort word lands in
# the kernel's round loop) instead of waiting for the stream to drain.
_abort_hooks: List[Any] = []


def set_cancel_waker(fn) -> None:
    """Register the active runtime's unpark-everything hook (None clears)."""
    global _waker
    with _waker_lock:
        _waker = fn


def register_abort_hook(fn) -> None:
    """Register a device-abort hook fired on every scope cancel (e.g. a
    bound ``StreamingMegakernel.abort``); see ``modules.tpu.abort_on_cancel``
    for the scope-filtered wrapper. Hooks must be idempotent and fast."""
    with _waker_lock:
        _abort_hooks.append(fn)


def unregister_abort_hook(fn) -> None:
    with _waker_lock:
        try:
            _abort_hooks.remove(fn)
        except ValueError:
            pass


def bind_abort_to_scope(abort_fn, scope: Optional["CancelScope"] = None):
    """Couple a device kill switch (``abort_fn(reason)``) to host
    cancellation: fires when ``scope`` cancels, or - with ``scope=None`` -
    when ANY scope cancels. The ONE implementation of the
    register-then-replay protocol: the hook is registered first and then
    replayed once, so a cancel() that landed before (or concurrently
    with) registration still aborts - cancel() only notifies hooks it
    saw. Returns an unregister callable. ``abort_fn`` must be idempotent
    (StreamingMegakernel.abort is)."""

    def hook() -> None:
        if scope is None:
            if not any_cancelled():
                return
            reason = "scope cancelled"
        elif scope.cancelled():
            r = scope.cancel_reason()
            reason = (
                "scope cancelled" if r is None
                else f"scope cancelled: {r}"
            )
        else:
            return
        abort_fn(reason)

    register_abort_hook(hook)
    hook()  # replay once: close the check/register race

    def unregister() -> None:
        unregister_abort_hook(hook)

    return unregister


# Preemption hooks (the checkpoint/restore subsystem, runtime/checkpoint
# .py): register_abort_hook's checkpoint-flavored twin. A TPU preemption
# notice (SIGTERM from the maintenance controller, or the
# HCLIB_TPU_PREEMPT env a wrapper script sets) should CHECKPOINT the
# resident megakernel - quiesce at a round boundary, export its state -
# rather than abort-and-lose it. Hooks (typically a bound
# ``StreamingMegakernel.quiesce`` or a host flag a driving loop polls)
# fire on ``fire_preempt``; the sources are the signal handler installed
# by ``install_preempt_handler`` and the watchdog's optional checkpoint
# rung (HCLIB_TPU_WATCHDOG_CHECKPOINT). Hooks must be idempotent/fast.
_preempt_hooks: List[Any] = []
_preempt_fired = False


def register_preempt_hook(fn) -> None:
    """Register a checkpoint trigger fired on preemption; if a preemption
    already fired this process, the hook replays immediately (the same
    register-then-replay protocol as ``bind_abort_to_scope`` - a SIGTERM
    that landed before the stream started must still checkpoint it)."""
    with _waker_lock:
        _preempt_hooks.append(fn)
        fired = _preempt_fired
    if fired or preempt_requested():
        try:
            fn()
        except Exception:
            LOG.exception("preempt hook failed during replay")


def unregister_preempt_hook(fn) -> None:
    with _waker_lock:
        try:
            _preempt_hooks.remove(fn)
        except ValueError:
            pass


def fire_preempt(reason: str = "preempted") -> int:
    """Invoke every registered preemption hook; returns the number
    notified. NOT called directly from signal frames: hooks and this
    function take ordinary locks, so ``install_preempt_handler`` defers
    the call to a daemon thread (same-thread lock re-entry from a signal
    handler would deadlock)."""
    global _preempt_fired
    with _waker_lock:
        _preempt_fired = True
        hooks = list(_preempt_hooks)
    LOG.warning("preemption notice (%s): firing %d checkpoint hook(s)",
                reason, len(hooks))
    for h in hooks:
        try:
            h()
        except Exception:  # a dying stream must not block the others
            LOG.exception("preempt hook failed")
    return len(hooks)


def reset_preempt() -> None:
    """Clear the process-wide preempt flag (tests / fresh launches)."""
    global _preempt_fired
    _preempt_fired = False


def preempt_requested() -> bool:
    """True when this process has been asked to preempt: fire_preempt ran
    (signal/watchdog), or the HCLIB_TPU_PREEMPT env var is set - the
    spelling for wrapper scripts that cannot deliver a signal."""
    if _preempt_fired:
        return True
    from .env import env_bool

    return env_bool("HCLIB_TPU_PREEMPT")


def install_preempt_handler(signals: Optional[Sequence[int]] = None):
    """Install a preemption handler for ``signals`` (default: SIGTERM -
    what TPU maintenance/preemption delivers). The handler itself only
    sets the process-wide flag and hands ``fire_preempt`` to a daemon
    thread: Python signal handlers run between bytecodes ON the main
    thread, so taking ``_waker_lock`` (or a stream's lock, or the
    logging lock) there could deadlock against the very frame the
    signal interrupted - the hooks run lock-safe on their own thread.
    Chains to any previous Python-level handler so an outer framework's
    shutdown logic still runs. Main thread only (CPython restriction);
    returns an uninstall callable."""
    import signal as _signal

    if signals is None:
        signals = (_signal.SIGTERM,)
    prev = {}

    def handler(signum, frame):
        global _preempt_fired
        _preempt_fired = True  # plain store: safe in a signal frame
        threading.Thread(
            target=fire_preempt, args=(f"signal {signum}",), daemon=True,
        ).start()
        p = prev.get(signum)
        if callable(p):
            p(signum, frame)

    for s in signals:
        prev[s] = _signal.signal(s, handler)

    def uninstall() -> None:
        for s, p in prev.items():
            try:
                _signal.signal(s, p)
            except (ValueError, TypeError):
                pass

    return uninstall


def any_cancelled() -> bool:
    """True once any scope has been cancelled since the last epoch reset
    (i.e. within the current launch)."""
    return _cancel_epoch != 0


def reset_cancel_epoch() -> None:
    """Restore the cancelled() fast path for a fresh launch. Scopes from a
    finished runtime are unreachable by live tasks, and without this reset
    one cancel anywhere would tax every later launch in the process with
    parent-chain walks on each spawn/execute/park check."""
    global _cancel_epoch
    _cancel_epoch = 0


class CancelScope:
    """Cancellation flag chained along the finish tree.

    ``cancelled()`` consults self and every ancestor, so cancelling a
    scope implicitly cancels all descendants - no child registry, no
    per-finish bookkeeping that could leak across millions of finishes.

    A scope may also carry a **deadline** (``set_deadline``): an absolute
    ``time.monotonic()`` instant after which admission-time consumers
    (the multi-tenant front door's deadline-aware admission,
    device/tenants.py) treat work bound to the scope as expired.
    Deadlines inherit like cancellation - the nearest deadline on the
    parent chain governs - but they are *advisory*: nothing polls them,
    so a passed deadline does not wake parked waiters by itself; the
    checker that observes it (``deadline_expired()``) decides whether to
    cancel. That keeps the epoch fast path intact: an armed deadline
    costs nothing until someone asks.
    """

    __slots__ = ("parent", "reason", "_cancelled", "deadline_t")

    def __init__(self, parent: Optional["CancelScope"] = None) -> None:
        self.parent = parent
        self.reason: Any = None
        self._cancelled = False
        self.deadline_t: Optional[float] = None

    def cancel(self, reason: Any = None) -> None:
        """Cancel this scope (and, by inheritance, its descendants).
        Idempotent; the first reason wins. Wakes every parked context of
        the active runtime so blocked waiters notice promptly."""
        global _cancel_epoch
        if self._cancelled:
            return
        if reason is not None:
            self.reason = reason
        self._cancelled = True
        _cancel_epoch += 1
        with _waker_lock:
            w = _waker
            hooks = list(_abort_hooks)
        if w is not None:
            try:
                w()
            except Exception:  # a dying runtime must not break cancel()
                pass
        for h in hooks:  # device kill switches (abort words) fire too
            try:
                h()
            except Exception:
                pass

    def cancelled(self) -> bool:
        if _cancel_epoch == 0:
            return False
        s: Optional[CancelScope] = self
        while s is not None:
            if s._cancelled:
                return True
            s = s.parent
        return False

    def describe(self) -> str:
        s: Optional[CancelScope] = self
        while s is not None:
            if s._cancelled:
                r = s.reason
                if r is None:
                    return "scope cancelled"
                return f"scope cancelled: {r}"
            s = s.parent
        return "scope not cancelled"

    def cancel_reason(self) -> Any:
        """The reason of the nearest cancelled scope on the parent chain."""
        s: Optional[CancelScope] = self
        while s is not None:
            if s._cancelled:
                return s.reason
            s = s.parent
        return None

    # -- deadlines (advisory; checked at admission points) --

    def set_deadline(self, seconds: Optional[float] = None,
                     at: Optional[float] = None) -> "CancelScope":
        """Arm a deadline ``seconds`` from now (or at absolute monotonic
        instant ``at``); the earliest armed deadline wins on re-arm.
        Returns self for chaining: ``CancelScope().set_deadline(0.5)``."""
        if (seconds is None) == (at is None):
            raise ValueError("set_deadline wants exactly one of "
                             "seconds= or at=")
        t = time.monotonic() + float(seconds) if at is None else float(at)
        if self.deadline_t is None or t < self.deadline_t:
            self.deadline_t = t
        return self

    def effective_deadline(self) -> Optional[float]:
        """The earliest deadline on self and every ancestor (deadlines
        inherit like cancellation), or None when none is armed."""
        best: Optional[float] = None
        s: Optional[CancelScope] = self
        while s is not None:
            t = s.deadline_t
            if t is not None and (best is None or t < best):
                best = t
            s = s.parent
        return best

    def deadline_expired(self, now: Optional[float] = None) -> bool:
        """True once the effective deadline has passed (``now`` defaults
        to ``time.monotonic()``; injectable for deterministic tests)."""
        t = self.effective_deadline()
        if t is None:
            return False
        return (time.monotonic() if now is None else now) >= t


# ------------------------------------------------------------- deterministic

def _hash01(seed: int, site: str, n: int) -> float:
    """Uniform [0, 1) as a pure function of (seed, site, n) - platform- and
    run-independent, unlike random.Random under thread interleaving."""
    h = hashlib.blake2b(f"{seed}/{site}/{n}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little") / 2.0**64


# ------------------------------------------------------------------- retry

class RetryPolicy:
    """Per-spawn retry: up to ``max_attempts`` total executions, delayed by
    ``backoff_s * multiplier**(attempt-1)`` with deterministic +/-``jitter``
    fraction. ``retry_on`` restricts which exception types retry
    (cancellation and stalls never do). With ``quarantine=True`` a task
    that exhausts its attempts is recorded in the runtime's quarantine and
    *swallowed* (its result promise is poisoned, the run continues);
    otherwise the terminal error propagates to ``launch`` as usual."""

    __slots__ = (
        "max_attempts", "backoff_s", "multiplier", "jitter", "retry_on",
        "quarantine", "seed", "_n", "_lock",
    )

    def __init__(
        self,
        max_attempts: int = 3,
        backoff_s: float = 0.01,
        multiplier: float = 2.0,
        jitter: float = 0.1,
        retry_on: Tuple[type, ...] = (Exception,),
        quarantine: bool = False,
        seed: int = 0,
    ) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self.quarantine = bool(quarantine)
        self.seed = int(seed)
        self._n = 0
        self._lock = threading.Lock()

    def should_retry(self, attempt: int, exc: BaseException) -> bool:
        """True if execution ``attempt`` (0-based) failing with ``exc``
        warrants another attempt."""
        if isinstance(exc, (CancelledError, StallError)):
            return False
        return attempt + 1 < self.max_attempts and isinstance(
            exc, self.retry_on
        )

    def delay_s(self, attempt: int) -> float:
        """Backoff before execution ``attempt`` (1-based retry index)."""
        d = self.backoff_s * (self.multiplier ** max(0, attempt - 1))
        if self.jitter:
            with self._lock:
                self._n += 1
                n = self._n
            u = _hash01(self.seed, "retry-jitter", n)
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return max(0.0, d)


# ------------------------------------------------------------- device chaos

def _env_int(name: str, default: int) -> int:
    from .env import env_int

    return env_int(name, default, malformed=default)


class DeviceFaultPlan:
    """Seeded deterministic fault injection for the interpret-mode ICI mesh
    kernels (``device/resident.py`` and the wrappers that delegate to it).

    Unlike the host ``FaultPlan`` (hooks called from Python), this plan is
    **compiled into the kernel**: every fault decision is a pure function of
    ``(seed, site, round, hop, device)`` evaluated by the SAME scalar-core
    hash on every device of the lockstep SPMD mesh, so injector, victim, and
    every bystander agree on the schedule - injection, detection, and
    recovery stay in lockstep and the fault trace (``fault_stats`` in the
    run's info dict) is byte-reproducible from the seed. A ``None`` plan
    compiles none of this (zero cost when disabled).

    Faults (sites):

    - **dropped steal credit** (``drop_credit_rate`` /
      ``drop_credit_at=[(round, hop, granter_dev), ...]``): the granter
      skips the flow-control credit it owes its hop partner after consuming
      the partner's row transfer. The starved writer stalls the channel for
      ``credit_timeout`` rounds (the pair skips that hop's row exchange -
      the visible cost of detection latency), then *regenerates* the credit
      and resumes; with ``credit_timeout=0`` regeneration is disabled and
      the mesh exits in lockstep with a ``StallError`` naming the starved
      channel instead of hanging.
    - **duplicated steal credit** (``dup_credit_rate`` /
      ``dup_credit_at``): the granter signals twice; the protocol must
      tolerate the surplus (writes stay round-paced, so no overwrite) and
      the exit credit drain must still balance every semaphore to zero.
    - **delayed neighbor xfer** (``delay_xfer_rate``): the sender withholds
      its export quota for that (round, hop) - rows migrate a round late.
    - **dead chip** (``dead_device``, ``dead_round``): from ``dead_round``
      on, device ``dead_device`` stops executing tasks and freezes the
      heartbeat word it folds into the per-round stat exchange (the ICI
      wire and DMA engine stay up - the realistic TPU failure is a wedged
      scalar-core scheduler, not a powered-off chip). Survivors detect the
      frozen heartbeat after ``heartbeat_timeout`` rounds and *quarantine*
      the device id from their steal-eligibility masks; the dead chip's
      recovery path re-homes its queued tasks to its hop partners so the
      surviving mesh drains the workload (totals conserved). Work that
      cannot re-home (non-migratable kernels) surfaces as a ``StallError``
      naming the suspect chip.

    ``credit_timeout`` / ``heartbeat_timeout`` default from the
    ``HCLIB_TPU_CREDIT_TIMEOUT`` / ``HCLIB_TPU_HEARTBEAT_TIMEOUT`` env vars
    (both in ROUNDS of the kernel's exchange schedule, default 2).
    """

    def __init__(
        self,
        seed: int = 0,
        drop_credit_rate: float = 0.0,
        drop_credit_at: Sequence[Tuple[int, int, int]] = (),
        dup_credit_rate: float = 0.0,
        dup_credit_at: Sequence[Tuple[int, int, int]] = (),
        delay_xfer_rate: float = 0.0,
        dead_device: Optional[int] = None,
        dead_round: int = 0,
        credit_timeout: Optional[int] = None,
        heartbeat_timeout: Optional[int] = None,
    ) -> None:
        for name, r in (
            ("drop_credit_rate", drop_credit_rate),
            ("dup_credit_rate", dup_credit_rate),
            ("delay_xfer_rate", delay_xfer_rate),
        ):
            if not (0.0 <= r <= 1.0):
                raise ValueError(f"{name} must be in [0, 1], got {r}")
        self.seed = int(seed)
        # Rates quantized to per-mille for the in-kernel integer compare.
        self.drop_millis = int(round(drop_credit_rate * 1000))
        self.dup_millis = int(round(dup_credit_rate * 1000))
        self.delay_millis = int(round(delay_xfer_rate * 1000))
        self.drop_credit_at = tuple(
            (int(r), int(k), int(g)) for (r, k, g) in drop_credit_at
        )
        self.dup_credit_at = tuple(
            (int(r), int(k), int(g)) for (r, k, g) in dup_credit_at
        )
        self.dead_device = None if dead_device is None else int(dead_device)
        self.dead_round = int(dead_round)
        self.credit_timeout = (
            _env_int("HCLIB_TPU_CREDIT_TIMEOUT", 2)
            if credit_timeout is None
            else int(credit_timeout)
        )
        if self.credit_timeout < 0:
            raise ValueError("credit_timeout must be >= 0 (0 = no regen)")
        self.heartbeat_timeout = max(1, (
            _env_int("HCLIB_TPU_HEARTBEAT_TIMEOUT", 2)
            if heartbeat_timeout is None
            else int(heartbeat_timeout)
        ))

    def drops_credits(self) -> bool:
        return bool(self.drop_millis or self.drop_credit_at)

    def dups_credits(self) -> bool:
        return bool(self.dup_millis or self.dup_credit_at)

    def enabled(self) -> bool:
        return (
            self.drops_credits()
            or self.dups_credits()
            or self.delay_millis > 0
            or self.dead_device is not None
        )


# -------------------------------------------------------------------- chaos

class FaultPlan:
    """Seeded deterministic fault injection across all three layers.

    Sites (each with an independent monotone event counter ``n``):

    - ``task``: before each task body execution, fail with
      ``InjectedFault`` when ``hash01(seed, 'task', n) < task_failure_rate``
      (at most ``max_task_failures`` total when set).
    - ``steal``: after each successful steal, sleep ``steal_delay_s`` when
      ``hash01(seed, 'steal', n) < steal_delay_rate``.
    - worker death: the pool thread bound to identity ``kill_worker`` dies
      after its ``kill_worker_after``-th scheduling poll; the runtime
      re-binds the orphaned identity to a fresh thread (the recovery under
      test) and counts it in ``stats_dict()['resilience']['worker_deaths']``.
    - procworld: rank ``peer_crash_rank``'s progress engine suffers a
      fatal ``InjectedFault`` once it has applied ``peer_crash_after``
      ops, exercising tombstones + reply poisoning on its peers.
    - disk (the durable checkpoint store, runtime/checkpoint.py - one
      hook call per ``BundleStore`` save/restore, so the per-site
      counters ARE the store's save/restore ordinals):

      * ``disk_torn`` - the ``state.npz`` blob is truncated at a
        seeded byte k before it lands (a torn write the sha256 check
        must catch);
      * ``disk_flip`` - one seeded bit of the blob flips (bit rot);
      * ``disk_manifest`` - the manifest goes missing entirely or is
        truncated mid-JSON (seeded coin);
      * ``preempt_save_at=n`` - the n-th store save dies with
        ``InjectedFault`` AFTER staging but BEFORE the atomic publish
        (the preempt-mid-save crash point: the generation must never
        become visible);
      * ``preempt_restore_at=n`` - the n-th ``load_latest`` dies
        before touching any generation (preempt-mid-restore: a retry
        must find the store unchanged).

    Every decision is a pure function of ``(seed, site, n)``, so the
    decision table - and therefore ``trace``, the list of faults that
    fired - is reproducible for a given seed and workload. Thread
    interleaving may reorder ``trace`` between runs; compare
    ``trace_key()`` (sorted) for determinism assertions.
    """

    def __init__(
        self,
        seed: int = 0,
        task_failure_rate: float = 0.0,
        max_task_failures: Optional[int] = None,
        steal_delay_rate: float = 0.0,
        steal_delay_s: float = 0.002,
        kill_worker: Optional[int] = None,
        kill_worker_after: int = 100,
        peer_crash_rank: Optional[int] = None,
        peer_crash_after: int = 0,
        disk_torn_rate: float = 0.0,
        disk_torn_at: Sequence[int] = (),
        disk_flip_rate: float = 0.0,
        disk_flip_at: Sequence[int] = (),
        disk_manifest_rate: float = 0.0,
        disk_manifest_at: Sequence[int] = (),
        preempt_save_at: Optional[int] = None,
        preempt_restore_at: Optional[int] = None,
    ) -> None:
        self.seed = int(seed)
        self.task_failure_rate = float(task_failure_rate)
        self.max_task_failures = max_task_failures
        self.steal_delay_rate = float(steal_delay_rate)
        self.steal_delay_s = float(steal_delay_s)
        self.kill_worker = kill_worker
        self.kill_worker_after = int(kill_worker_after)
        self.peer_crash_rank = peer_crash_rank
        self.peer_crash_after = int(peer_crash_after)
        self.disk_torn_rate = float(disk_torn_rate)
        self.disk_torn_at = tuple(int(n) for n in disk_torn_at)
        self.disk_flip_rate = float(disk_flip_rate)
        self.disk_flip_at = tuple(int(n) for n in disk_flip_at)
        self.disk_manifest_rate = float(disk_manifest_rate)
        self.disk_manifest_at = tuple(int(n) for n in disk_manifest_at)
        self.preempt_save_at = (
            None if preempt_save_at is None else int(preempt_save_at)
        )
        self.preempt_restore_at = (
            None if preempt_restore_at is None
            else int(preempt_restore_at)
        )
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._fired: Set[Tuple[str, int]] = set()
        self._task_faults = 0
        self.trace: List[Tuple[str, int]] = []

    def _next(self, site: str) -> int:
        with self._lock:
            n = self._counters.get(site, 0)
            self._counters[site] = n + 1
            return n

    # -- scheduler hooks --

    def on_task(self, task: Any) -> None:
        """Called before each task body execution; may raise InjectedFault."""
        if self.task_failure_rate <= 0.0:
            return
        n = self._next("task")
        if _hash01(self.seed, "task", n) >= self.task_failure_rate:
            return
        with self._lock:
            if (
                self.max_task_failures is not None
                and self._task_faults >= self.max_task_failures
            ):
                return
            self._task_faults += 1
            self.trace.append(("task", n))
        raise InjectedFault(f"chaos: injected task failure (task #{n})")

    def on_steal(self, wid: int) -> None:
        """Called after each successful steal; may sleep (delayed steal)."""
        if self.steal_delay_rate <= 0.0:
            return
        n = self._next("steal")
        if _hash01(self.seed, "steal", n) < self.steal_delay_rate:
            with self._lock:
                self.trace.append(("steal", n))
            time.sleep(self.steal_delay_s)

    def on_worker_poll(self, wid: int) -> bool:
        """Called per scheduling-loop iteration; True = this thread dies."""
        if self.kill_worker is None or wid != self.kill_worker:
            return False
        key = ("kill_worker", wid)
        with self._lock:
            if key in self._fired:
                return False
        n = self._next(f"worker/{wid}")
        if n + 1 < self.kill_worker_after:
            return False
        with self._lock:
            if key in self._fired:
                return False
            self._fired.add(key)
            self.trace.append(key)
        return True

    # -- procworld hook --

    def on_procworld_poll(self, rank: int, applied: int) -> bool:
        """Called per progress-loop iteration; True = fatal engine crash."""
        if self.peer_crash_rank is None or rank != self.peer_crash_rank:
            return False
        if applied < self.peer_crash_after:
            return False
        key = ("peer_crash", rank)
        with self._lock:
            if key in self._fired:
                return False
            self._fired.add(key)
            self.trace.append(key)
        return True

    # -- durable-store hooks (runtime/checkpoint.py BundleStore) --

    def _fires(self, site: str, rate: float,
               at: Sequence[int], n: int) -> bool:
        return n in at or (
            rate > 0.0 and _hash01(self.seed, site, n) < rate
        )

    def on_bundle_blob(self, blob: bytes) -> bytes:
        """Called with each store save's serialized ``state.npz`` bytes
        before they land on disk; may tear (truncate at a seeded byte)
        or flip one seeded bit. Both corruptions publish - they model
        latent media faults the sha256 validation must quarantine on
        the NEXT load, not crashes (those are ``preempt_save_at``)."""
        n = self._next("disk")
        if self._fires("disk-torn", self.disk_torn_rate,
                       self.disk_torn_at, n):
            k = 1 + int(
                _hash01(self.seed, "disk-torn-k", n) * max(1, len(blob) - 1)
            )
            with self._lock:
                self.trace.append(("disk-torn", n))
            return blob[:k]
        if self._fires("disk-flip", self.disk_flip_rate,
                       self.disk_flip_at, n):
            k = int(_hash01(self.seed, "disk-flip-k", n) * len(blob))
            bit = int(_hash01(self.seed, "disk-flip-b", n) * 8)
            with self._lock:
                self.trace.append(("disk-flip", n))
            return blob[:k] + bytes([blob[k] ^ (1 << bit)]) + blob[k + 1:]
        return blob

    def on_manifest_text(self, text: str) -> Optional[str]:
        """Called with each store save's manifest JSON; may truncate it
        mid-document or drop it entirely (returns None) - the
        missing/unreadable-manifest fault the self-healing restore
        walks past."""
        n = self._next("manifest")
        if self._fires("disk-manifest", self.disk_manifest_rate,
                       self.disk_manifest_at, n):
            with self._lock:
                self.trace.append(("disk-manifest", n))
            if _hash01(self.seed, "disk-manifest-kind", n) < 0.5:
                return None
            return text[: max(1, len(text) // 2)]
        return text

    def on_store_publish(self) -> None:
        """Called once per store save, after staging but before the
        atomic rename; raising here simulates a preemption landing
        mid-save - the staged generation must never become visible."""
        n = self._next("publish")
        if self.preempt_save_at is not None and n == self.preempt_save_at:
            with self._lock:
                self.trace.append(("preempt-save", n))
            raise InjectedFault(
                f"chaos: preempt mid-save (store save #{n}, staged but "
                "unpublished)"
            )

    def on_store_restore(self) -> None:
        """Called once per ``load_latest``, before any generation is
        touched; raising simulates preempt-mid-restore - a retry must
        see the store unchanged (restores never mutate generations)."""
        n = self._next("restore")
        if (
            self.preempt_restore_at is not None
            and n == self.preempt_restore_at
        ):
            with self._lock:
                self.trace.append(("preempt-restore", n))
            raise InjectedFault(
                f"chaos: preempt mid-restore (load_latest call #{n})"
            )

    # -- reproducibility --

    def trace_key(self) -> Tuple[Tuple[str, int], ...]:
        """Order-independent fingerprint of the faults that fired (thread
        interleaving may reorder ``trace`` itself between identical runs)."""
        with self._lock:
            return tuple(sorted(self.trace))
