"""Promises and futures (data-driven futures, DDFs).

Semantics follow the reference runtime's single-assignment promise with a
waiter list (reference: src/hclib-promise.c:132-245, inc/hclib-promise.h:76-90):

- A promise is a single-assignment cell. ``put`` may be called exactly once.
- Tasks waiting on multiple futures register on *at most one* unsatisfied
  future at a time, walking their dependency list in order (reference:
  src/hclib-promise.c:171-195). When that promise is satisfied, the put path
  resumes the walk for each waiter and schedules tasks whose dependencies are
  all satisfied (src/hclib-promise.c:203-245) - this is the only place blocked
  tasks become runnable.
- Blocked *execution contexts* (a thread inside ``Future.wait``) are
  represented as event waiters rather than suspended fibers; the scheduler
  parks the context and keeps the worker count constant
  (see scheduler.py, replacing the reference's LiteCtx fiber swap).

This host-side implementation is intentionally lock-based and simple: it pins
the semantics that the TPU device path (device/) re-implements with on-device
flag words and waiter queues.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

__all__ = ["Promise", "Future", "PromiseError"]

_UNSET = object()


class PromiseError(RuntimeError):
    pass


class Promise:
    """Single-assignment cell with a waiter list."""

    __slots__ = (
        "_lock",
        "_value",
        "_satisfied",
        "_error",
        "_task_waiters",
        "_ctx_waiters",
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value: Any = _UNSET
        self._satisfied = False
        self._error: Optional[BaseException] = None
        # Tasks blocked with this promise as their current registration point.
        self._task_waiters: List[Any] = []
        # Parked execution contexts (threading.Event) waiting on this promise.
        self._ctx_waiters: List[threading.Event] = []

    @property
    def future(self) -> "Future":
        return Future(self)

    def satisfied(self) -> bool:
        return self._satisfied

    def put(self, value: Any = None) -> None:
        """Satisfy the promise and wake every waiter.

        Task waiters resume their dependency-registration walk; contexts are
        simply unparked (they re-check their own wait condition).
        """
        self._satisfy(value, None)

    def poison(self, error: BaseException) -> None:
        """Satisfy the promise with a failure: waiters become runnable, and
        any ``get()`` raises. Producers that die must poison rather than
        leave dependents parked forever (no reference analogue - C tasks
        abort the process; a Python framework must propagate)."""
        self._satisfy(_UNSET, error)

    def poison_if_unset(self, error: BaseException) -> bool:
        """Best-effort poison for cancellation/teardown paths: no-op (False)
        when already satisfied - losing the race to a normal put is fine."""
        try:
            self._satisfy(_UNSET, error)
            return True
        except PromiseError:
            return False

    def _satisfy(self, value: Any, error: Optional[BaseException]) -> None:
        with self._lock:
            if self._satisfied:
                raise PromiseError("promise put() called twice")
            self._value = value
            self._error = error
            self._satisfied = True
            task_waiters, self._task_waiters = self._task_waiters, []
            ctx_waiters, self._ctx_waiters = self._ctx_waiters, []
        # Outside the lock: schedule/resume waiters.
        if task_waiters:
            from . import scheduler

            rt = scheduler.current_runtime()
            for task in task_waiters:
                rt.resume_registration(task)
        for ev in ctx_waiters:
            ev.set()

    def _register_task(self, task: Any) -> bool:
        """Try to add ``task`` as a waiter. Returns False when already
        satisfied (caller should continue its registration walk)."""
        with self._lock:
            if self._satisfied:
                return False
            self._task_waiters.append(task)
            return True

    def _register_ctx(self, event: threading.Event) -> bool:
        with self._lock:
            if self._satisfied:
                return False
            self._ctx_waiters.append(event)
            return True

    def _unregister_ctx(self, event: threading.Event) -> None:
        """Withdraw a parked-context waiter that gave up (wait timeout,
        cancellation): repeated timed waits on a long-unsatisfied promise
        must not accumulate abandoned events."""
        with self._lock:
            try:
                self._ctx_waiters.remove(event)
            except ValueError:
                pass

    def get(self) -> Any:
        if not self._satisfied:
            raise PromiseError("promise value read before put()")
        if self._error is not None:
            raise PromiseError("producer task failed") from self._error
        return self._value


class Future:
    """Read handle on a promise (reference: inc/hclib_future.h)."""

    __slots__ = ("promise",)

    def __init__(self, promise: Promise) -> None:
        self.promise = promise

    def satisfied(self) -> bool:
        return self.promise.satisfied()

    def get(self) -> Any:
        """Non-blocking read; requires the promise to be satisfied."""
        return self.promise.get()

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Block the current execution context until satisfied.

        Equivalent to hclib_future_wait (reference: src/hclib-runtime.c:983):
        help-first runs other tasks inline, then parks the context. With
        ``timeout`` (seconds), raises ``StallError`` instead of blocking
        past it - the promise itself stays unsatisfied and may still be
        waited on again.
        """
        if self.promise.satisfied():
            return self.promise.get()
        from . import scheduler

        scheduler.current_runtime().wait_on(self.promise, timeout=timeout)
        return self.promise.get()


def make_promise_vector(n: int) -> List[Promise]:
    return [Promise() for _ in range(n)]
