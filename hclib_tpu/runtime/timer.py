"""Per-worker WORK/SEARCH/OVH/IDLE state timing.

Reference (src/hclib-timer.c, inc/hclib-timer.h:17-37): a UTS-derived state
machine, off by default (``_TIMER_ON_``); MARK_BUSY/OVH/SEARCH macros wrap
the async path and the steal loop; ``hclib_get_avg_time`` reports per-state
averages. Here states are recorded per worker with monotonic timestamps; the
scheduler marks WORK around task execution, SEARCH around the steal scan,
IDLE while parked/waiting, OVH otherwise.
"""

from __future__ import annotations

import time
from typing import Dict, List

__all__ = ["StateTimer", "WORK", "SEARCH", "OVH", "IDLE", "STATE_NAMES"]

WORK = 0
SEARCH = 1
OVH = 2
IDLE = 3
STATE_NAMES = ("WORK", "SEARCH", "OVH", "IDLE")


class StateTimer:
    """Accumulated nanoseconds per (worker, state)."""

    def __init__(self, nworkers: int) -> None:
        self.nworkers = nworkers
        now = time.monotonic_ns()
        self._state = [OVH] * nworkers
        self._since = [now] * nworkers
        self._accum = [[0] * len(STATE_NAMES) for _ in range(nworkers)]

    def set_state(self, worker_id: int, state: int) -> int:
        """Transition; returns the previous state (hclib_set_state,
        inc/hclib-timer.h:31-37)."""
        if not (0 <= worker_id < self.nworkers):
            return OVH
        now = time.monotonic_ns()
        prev = self._state[worker_id]
        self._accum[worker_id][prev] += now - self._since[worker_id]
        self._state[worker_id] = state
        self._since[worker_id] = now
        return prev

    def finalize(self) -> None:
        for w in range(self.nworkers):
            self.set_state(w, OVH)

    def totals_ns(self) -> List[Dict[str, int]]:
        return [
            {STATE_NAMES[s]: acc[s] for s in range(len(STATE_NAMES))}
            for acc in self._accum
        ]

    def avg_time_ns(self, state: int) -> float:
        """Mean time in ``state`` across workers (hclib_get_avg_time)."""
        tot = sum(acc[state] for acc in self._accum)
        return tot / self.nworkers

    def format(self) -> str:
        lines = ["worker state times (ms):"]
        for w, acc in enumerate(self._accum):
            parts = " ".join(
                f"{STATE_NAMES[s].lower()}={acc[s] / 1e6:.1f}"
                for s in range(len(STATE_NAMES))
            )
            lines.append(f"  worker {w}: {parts}")
        return "\n".join(lines)
