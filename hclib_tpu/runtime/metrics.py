"""Unified telemetry: one registry over every observability source.

The runtime grew five independent sources - ``Runtime.stats_dict()``
(worker counters + resilience retry/quarantine), the megakernel's
``info['tiers']`` dispatch counters, the resident mesh's
``info['fault_stats']``, the device flight recorder
(``info['trace']``, device/tracebuf.py), and ad-hoc run infos - each with
its own shape and no common export. ``MetricsRegistry`` folds them into
ONE snapshot/delta API with JSON and Prometheus-text export, so a
dashboard, the watchdog's stats-dump rung, or a bench artifact all read
the same numbers the same way.

Model:

- ``register(name, source)`` attaches a LIVE source (a zero-arg callable
  returning a mapping, e.g. ``rt.stats_dict``) polled at snapshot time.
- ``record(name, mapping)`` stores a STATIC snapshot (e.g. a device
  run's ``info``); the latest record under a name wins.
- ``add_run_info(name, info)`` is the device-run convenience: it keeps
  the numeric core of an info dict and summarizes ``fault_stats`` and
  the trace ring (per-tag record counts) instead of carrying raw rows.
- ``snapshot()`` flattens everything to ``{dotted.key: number}`` plus a
  timestamp; ``delta(a, b)`` subtracts two snapshots key-wise (counters
  become rates when divided by the timestamp delta).
- ``to_json()`` / ``to_prometheus()`` render a snapshot; the Prometheus
  form sanitizes keys into ``<namespace>_<key>`` gauges.
- ``watch(name, source)`` (ISSUE 19) is the live-refresh face: a
  daemon thread polls the source every interval and records the latest
  mapping, so a scrape endpoint (tools/metrics_serve.py) serves fresh
  numbers without snapshotting on the request path - and the last
  value survives the source going away. ``record_latency(block)``
  stores a scraped ``TelemetryBlock`` whose per-tenant histograms
  export in the native Prometheus histogram form
  (``hclib_latency_bucket{tenant=...,le=...}``, cumulative, ``+Inf``
  capped, plus ``hclib_latency_count``; ``le`` is in scheduler rounds,
  with ``hclib_latency_ns_per_round`` alongside for conversion).

Enable runtime-side via ``Runtime(metrics=True)`` or
``HCLIB_TPU_METRICS=1``: the runtime registers its own ``stats_dict``
and the watchdog's stats-dump rung (strike 2) logs the registry snapshot
alongside ``format_stats()``, so a stalled run's post-mortem carries
device counters too when the program recorded them.
"""

from __future__ import annotations

import json
import numbers
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional

__all__ = ["MetricsRegistry", "CHECKPOINT_EVENTS", "LATENCY_FAMILY"]

# The latency-histogram family name is a dashboard ABI (ISSUE 19's
# acceptance scrapes it literally), pinned independently of the
# registry namespace.
LATENCY_FAMILY = "hclib_latency"

# The canonical durable-store event series (runtime/checkpoint.py's
# BundleStore records one ``record_event`` per store action, so each
# exports as ``<name>.count`` plus ``<name>.last.*``): saves published,
# generations validated+loaded, restores that fell back past a bad
# generation, and generations quarantined. Dashboards alert on
# ``checkpoint.quarantined.count`` rising - a quarantine is never
# silent - and rate() the save/load pair for store traffic.
CHECKPOINT_EVENTS = (
    "checkpoint.save",
    "checkpoint.load",
    "checkpoint.fallback",
    "checkpoint.quarantined",
)


def _is_num(v: Any) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def _flatten(prefix: str, obj: Any, out: Dict[str, float]) -> None:
    """Numeric leaves only: strings/None are dropped (Prometheus carries
    numbers; string context belongs in the JSON info files next to it),
    bools coerce to 0/1, lists index as ``.<i>``."""
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            _flatten(key, v, out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _flatten(f"{prefix}.{i}", v, out)
    elif isinstance(obj, bool):
        out[prefix] = 1.0 if obj else 0.0
    elif _is_num(obj):
        out[prefix] = float(obj)
    # numpy scalars quack like numbers.Real; arrays do not - summarize
    # them before recording (add_run_info does for the known shapes).


class MetricsRegistry:
    """Aggregates live sources and recorded run infos into flat numeric
    snapshots with JSON / Prometheus export. Thread-safe: the watchdog
    thread snapshots while workers record."""

    def __init__(self, namespace: str = "hclib_tpu") -> None:
        self.namespace = namespace
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], Mapping]] = {}
        self._records: Dict[str, Mapping] = {}
        self._watches: Dict[str, threading.Event] = {}
        self._latency = None  # (TelemetryBlock, {index: label})

    # -- wiring --

    def register(self, name: str, source: Callable[[], Mapping]) -> None:
        """Attach a live source polled at every snapshot."""
        if not callable(source):
            raise TypeError(f"source {name!r} must be callable")
        with self._lock:
            self._sources[name] = source

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def record(self, name: str, mapping: Mapping) -> None:
        """Store a static snapshot under ``name`` (latest wins)."""
        with self._lock:
            self._records[name] = dict(mapping)

    def watch(
        self,
        name: str,
        source: Callable[[], Optional[Mapping]],
        interval_s: Optional[float] = None,
        on_update: Optional[Callable[[Mapping], None]] = None,
    ) -> None:
        """Live refresh (ISSUE 19): poll ``source`` on a daemon thread
        every ``interval_s`` (default HCLIB_TPU_TELEMETRY_POLL_S) and
        ``record`` the latest mapping under ``name`` - scrapes then
        read fresh values off the record table without touching the
        source on the request path, and the last value outlives the
        source. ``None`` returns skip (a stream before its first
        entry); a raising source records ``<name>.error = 1`` once and
        keeps polling. ``unwatch(name)`` stops the thread; re-watching
        a name replaces the old watch."""
        if interval_s is None:
            from .env import env_float

            interval_s = env_float("HCLIB_TPU_TELEMETRY_POLL_S", 0.05)
        interval_s = float(interval_s)
        if interval_s <= 0:
            raise ValueError(
                f"watch interval must be > 0 seconds, got {interval_s}"
            )
        stop = threading.Event()
        with self._lock:
            old = self._watches.pop(name, None)
            self._watches[name] = stop
        if old is not None:
            old.set()

        def _loop() -> None:
            while not stop.is_set():
                try:
                    m = source()
                except Exception:
                    m = {"error": 1}
                if m is not None:
                    self.record(name, m)
                    if on_update is not None:
                        on_update(m)
                stop.wait(interval_s)

        threading.Thread(
            target=_loop, name=f"hclib-metrics-watch-{name}", daemon=True
        ).start()

    def unwatch(self, name: str) -> None:
        """Stop a ``watch`` thread; its last recorded value remains."""
        with self._lock:
            stop = self._watches.pop(name, None)
        if stop is not None:
            stop.set()

    def record_latency(self, block, labels: Optional[Mapping] = None):
        """Store a scraped ``TelemetryBlock`` (device/telemetry.py) for
        native histogram exposition. ``labels`` maps tenant INDEX ->
        label text (defaults to the index)."""
        with self._lock:
            self._latency = (
                block,
                None if labels is None else dict(labels),
            )

    def record_event(self, name: str, mapping: Mapping) -> None:
        """Record one occurrence of a recurring event (an autoscaler
        decision, a checkpoint cut): keeps ``<name>.count`` (monotonic)
        plus ``<name>.last.*`` (the latest event's numeric fields) - the
        counter/last-value pair a dashboard rate()s and inspects, without
        the registry ever holding an unbounded event list."""
        with self._lock:
            prev = self._records.get(name)
            count = (
                int(prev.get("count", 0)) + 1
                if isinstance(prev, dict) else 1
            )
            self._records[name] = {"count": count, "last": dict(mapping)}

    def add_run_info(self, name: str, info: Mapping) -> None:
        """Record a device run's ``info`` dict: numeric scalars plus
        ``tiers``/``fault_stats`` pass through; the flight-recorder trace
        is summarized (per-tag counts, written/dropped) rather than
        carried raw; array-valued entries (per_device_counts) reduce to
        per-device executed/rounds. A batch-routed run additionally gets
        the ``lane_occupancy`` gauge - one value per device (mesh runs
        return ``tiers`` as a per-device list; single-device runs read
        as a one-entry list), exported as
        ``<name>.lane_occupancy.<d>`` - the ROADMAP lane-firing-policy
        detector a dashboard watches without digging through tiers. A
        tenant-enabled stream's ``info['tenants']`` additionally mirrors
        under the canonical ``tenant.<id>.*`` prefix."""
        keep: Dict[str, Any] = {}
        for k, v in info.items():
            if k == "trace":
                from ..device.tracebuf import summarize

                keep["trace"] = summarize(v)
            elif k == "per_device_counts":
                import numpy as np

                from ..device.megakernel import C_EXECUTED

                c = np.asarray(v)
                keep["per_device_executed"] = c[:, C_EXECUTED].tolist()
            elif k == "extra_outputs":
                continue
            else:
                keep[k] = v
        tiers = keep.get("tiers")
        if isinstance(tiers, Mapping):
            tiers = [tiers]
        if isinstance(tiers, (list, tuple)) and tiers:
            try:
                keep["lane_occupancy"] = [
                    float(t["batch_occupancy"]) for t in tiers
                ]
            except (KeyError, TypeError):
                pass
            # Partial-batch starvation gauge (the lane-policy watch
            # item): present only on traced batch-routed runs (the
            # detector needs TR_FIRE_BATCH records), exported per device
            # like lane_occupancy so a dashboard alerts on starvation
            # without digging through trace rings.
            ages = [
                t.get("lane_partial_age")
                for t in tiers
                if isinstance(t, Mapping)
            ]
            if any(a is not None for a in ages):
                keep["lane_partial_age"] = [
                    float(a) for a in ages if a is not None
                ]
            # Device-side starved-age gauge (the ISSUE 10 age-triggered
            # firing policy, tstats TS_MAX_AGE): worst consecutive
            # starved-round count any lane reached, per device - the
            # number the lane_max_age knob bounds. Exported beside the
            # trace-derived lane_partial_age so a dashboard alert works
            # on untraced runs too.
            sages = [
                t.get("max_starved_age")
                for t in tiers
                if isinstance(t, Mapping)
            ]
            if any(a is not None for a in sages):
                keep["lane_max_starved_age"] = [
                    float(a) for a in sages if a is not None
                ]
            # Priority-bucket tier gauges (ISSUE 15): bucket-order
            # inversions (age-guard fires that jumped a lower
            # non-empty bucket - a rising rate means the age knob is
            # fighting the priority order) per device, and per-bucket
            # occupancy (traced runs only; <name>.bucket_occupancy.<b>)
            # so a dashboard sees the ordered-retirement structure
            # without digging through trace rings.
            invs = [
                t.get("bucket_inversions")
                for t in tiers
                if isinstance(t, Mapping)
            ]
            if any(i is not None for i in invs):
                keep["bucket_inversions"] = [
                    float(i) for i in invs if i is not None
                ]
            boccs = [
                t.get("bucket_occupancy")
                for t in tiers
                if isinstance(t, Mapping)
            ]
            if any(isinstance(b, Mapping) for b in boccs):
                # One dict per device (mesh runs return tiers as a
                # per-device list), flattened as
                # <name>.bucket_occupancy.<device>.<bucket> - same
                # per-device discipline as lane_occupancy.
                keep["bucket_occupancy"] = [
                    {str(k): float(v) for k, v in b.items()}
                    for b in boccs
                    if isinstance(b, Mapping)
                ]
        # Edge-rate gauge (graph-analytics runs, device/frontier.py):
        # a run info carrying traversed edges and a wall time exports
        # traversed-edges/s directly - the TEPS headline as a metric.
        if "edges" in keep and keep.get("elapsed_s"):
            try:
                keep["teps"] = float(keep["edges"]) / float(
                    keep["elapsed_s"]
                )
            except (TypeError, ZeroDivisionError):
                pass
        tenants = keep.get("tenants")
        if isinstance(tenants, Mapping):
            # Multi-tenant ingress: mirror the per-tenant admission
            # counters under the canonical ``tenant.<id>.*`` prefix
            # (accepted/rejected/expired/completed/backlog ...), the
            # series dashboards and the fairness tests key on -
            # regardless of what ``name`` the run info landed under.
            # (Records flatten after live sources at snapshot time, so
            # this end-of-run mirror wins over a still-registered live
            # ``tenant`` source's stale overlap.)
            self.record(
                "tenant",
                {str(tid): s for tid, s in tenants.items()},
            )
            # One canonical series only: drop the copy that would
            # otherwise also flatten as <name>.tenants.<id>.* and
            # double every tenant counter's scrape cardinality.
            keep.pop("tenants")
        if "program_cache" in keep:
            # Process-wide program-cache gauges (runtime/progcache.py):
            # the run info's per-build hit/miss record stays under
            # <name>.program_cache.*, while the canonical
            # program_cache.{hits,misses,evictions,entries} series
            # reflects the whole process cache - one series regardless
            # of which run name the build landed under.
            from .progcache import cache_stats

            self.record("program_cache", cache_stats())
        self.record(name, keep)

    # -- snapshots --

    def snapshot(self) -> Dict[str, Any]:
        """``{'t': epoch_seconds, 'metrics': {dotted.key: float}}``. A
        live source that raises is reported as ``<name>.error = 1``
        instead of sinking the snapshot (the watchdog must be able to
        snapshot a half-dead runtime)."""
        with self._lock:
            sources = dict(self._sources)
            records = dict(self._records)
        metrics: Dict[str, float] = {}
        for name, fn in sources.items():
            try:
                _flatten(name, fn(), metrics)
            except Exception:
                metrics[f"{name}.error"] = 1.0
        for name, rec in records.items():
            _flatten(name, rec, metrics)
        return {"t": time.time(), "metrics": metrics}

    @staticmethod
    def delta(
        a: Mapping[str, Any], b: Mapping[str, Any]
    ) -> Dict[str, Any]:
        """Key-wise ``b - a`` over two snapshots (missing keys read 0, so
        a source that appeared mid-interval deltas from zero); ``t`` is
        the interval seconds."""
        am: Mapping[str, float] = a.get("metrics", a)
        bm: Mapping[str, float] = b.get("metrics", b)
        keys = set(am) | set(bm)
        return {
            "t": float(b.get("t", 0.0)) - float(a.get("t", 0.0)),
            "metrics": {
                k: float(bm.get(k, 0.0)) - float(am.get(k, 0.0))
                for k in sorted(keys)
            },
        }

    # -- export --

    def to_json(self, snapshot: Optional[Mapping] = None) -> str:
        return json.dumps(snapshot or self.snapshot(), sort_keys=True)

    @staticmethod
    def _sanitize(key: str) -> str:
        out = []
        for ch in key:
            out.append(ch if (ch.isalnum() or ch == "_") else "_")
        name = "".join(out)
        if name and name[0].isdigit():
            name = "_" + name
        return name

    def to_prometheus(self, snapshot: Optional[Mapping] = None) -> str:
        """Prometheus text exposition: one gauge per flattened key,
        ``<namespace>_<sanitized key>``. Values render via repr(float)
        (Prometheus accepts scientific notation)."""
        snap = snapshot or self.snapshot()
        lines = []
        for k in sorted(snap["metrics"]):
            name = f"{self.namespace}_{self._sanitize(k)}"
            v = snap["metrics"][k]
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(v)!r}")
        lines.extend(self._latency_lines())
        lines.append("")
        return "\n".join(lines)

    def _latency_lines(self) -> list:
        """Native Prometheus histogram exposition of the recorded
        TelemetryBlock: per tenant, CUMULATIVE bucket counts with
        ``le`` = the bucket's upper edge in scheduler rounds (the
        overflow bucket folds into ``+Inf``), plus ``_count``; and the
        rounds->ns factor as a gauge when the block carries one."""
        with self._lock:
            rec = self._latency
        if rec is None:
            return []
        from ..device.telemetry import bucket_edges

        block, labels = rec
        fam = LATENCY_FAMILY
        edges = bucket_edges()
        lines = [f"# TYPE {fam} histogram"]
        for t in range(block.tenants):
            label = str(t if labels is None else labels.get(t, t))
            counts = block.hist(t)
            cum = 0
            for (_, hi), c in zip(edges, counts.tolist()):
                cum += int(c)
                if hi is None:
                    continue  # the overflow mass lands in +Inf below
                lines.append(
                    f'{fam}_bucket{{tenant="{label}",le="{hi}"}} {cum}'
                )
            total = int(counts.sum())
            lines.append(
                f'{fam}_bucket{{tenant="{label}",le="+Inf"}} {total}'
            )
            lines.append(f'{fam}_count{{tenant="{label}"}} {total}')
        if block.ns_per_round is not None:
            lines.append(f"# TYPE {fam}_ns_per_round gauge")
            lines.append(
                f"{fam}_ns_per_round {float(block.ns_per_round)!r}"
            )
        return lines
