"""Central registry for ``HCLIB_TPU_*`` environment variables.

Every knob the package reads from the process environment is declared
here ONCE - name, type, default, and one-line doc - and read through the
typed accessors below. The registry is what makes the env surface
auditable: ``tools/lint.py`` forbids raw ``os.environ`` access to
``HCLIB_TPU_*`` names outside this module and cross-checks that every
name mentioned anywhere in the tree has a registry row, and the README
environment table renders from ``registry_table()``.

Parsing conventions (the PR 8 rule: a typo must not silently change
behavior):

- ``env_int`` / ``env_float`` raise ``ValueError`` naming the variable
  on malformed text unless the call site passes ``malformed=`` (a few
  legacy knobs deliberately degrade - e.g. ``HCLIB_TPU_TRACE=junk``
  enables default-capacity tracing rather than aborting a run the env
  owner never wrote).
- ``env_bool``: unset, empty, and ``"0"`` are False; anything else is
  True (the HCLIB_TPU_METRICS convention).
- ``env_flag``: any nonempty string is True - the legacy
  ``bool(os.environ.get(...))`` truthiness some older knobs keep for
  compatibility (``HCLIB_TPU_STATS=0`` enables stats; documented wart).

Accessors refuse unregistered names so a new knob cannot be added
without a doc row.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

__all__ = [
    "EnvVar",
    "REGISTRY",
    "env_raw",
    "env_set",
    "env_flag",
    "env_bool",
    "env_int",
    "env_float",
    "env_str",
    "registry_table",
]


@dataclass(frozen=True)
class EnvVar:
    name: str
    kind: str       # int | float | bool | flag | str | list
    default: str    # human-readable default, for the doc table
    doc: str
    legacy: Tuple[str, ...] = field(default_factory=tuple)


def _v(name, kind, default, doc, legacy=()):
    return EnvVar(name, kind, default, doc, tuple(legacy))


# One row per knob. Keys are the canonical names; legacy aliases are
# consulted (in order) when the canonical name is unset.
REGISTRY = {
    e.name: e
    for e in [
        # -- host runtime (runtime/scheduler.py) --
        _v("HCLIB_TPU_WORKERS", "int", "cpu_count",
           "host worker threads", legacy=("HCLIB_WORKERS",)),
        _v("HCLIB_TPU_LOCALITY_FILE", "str", "generated",
           "locality-graph JSON path", legacy=("HCLIB_LOCALITY_FILE",)),
        _v("HCLIB_TPU_STATS", "flag", "off",
           "per-worker scheduler stats"),
        _v("HCLIB_TPU_INSTRUMENT", "flag", "off",
           "host event log (runtime/instrument.py)",
           legacy=("HCLIB_INSTRUMENT",)),
        _v("HCLIB_TPU_TIMER", "flag", "off",
           "per-worker state timer"),
        _v("HCLIB_TPU_WATCHDOG_S", "float", "0 (off)",
           "stall watchdog period, seconds",
           legacy=("HCLIB_TPU_WATCHDOG",)),
        _v("HCLIB_TPU_WATCHDOG_ESCALATE", "bool", "on",
           "watchdog report->dump->cancel ladder (0 = report only)"),
        _v("HCLIB_TPU_WATCHDOG_CHECKPOINT", "bool", "off",
           "watchdog strike-2 rung fires preempt hooks (checkpoint)"),
        _v("HCLIB_TPU_METRICS", "bool", "off",
           "MetricsRegistry on the Runtime"),
        _v("HCLIB_TPU_DUMP_DIR", "str", ".",
           "EventLog dump directory"),
        # -- resilience / preemption (runtime/resilience.py) --
        _v("HCLIB_TPU_PREEMPT", "bool", "off",
           "wrapper-script preemption request (no-signal spelling)"),
        _v("HCLIB_TPU_CREDIT_TIMEOUT", "int", "2",
           "steal-credit starvation window, exchange rounds"),
        _v("HCLIB_TPU_HEARTBEAT_TIMEOUT", "int", "2",
           "dead-chip heartbeat window, exchange rounds"),
        # -- autoscaler (runtime/autoscaler.py) --
        _v("HCLIB_TPU_AUTOSCALE_OUT", "float", "32",
           "scale-out backlog threshold, tasks/device"),
        _v("HCLIB_TPU_AUTOSCALE_IN", "float", "2",
           "scale-in backlog threshold, tasks/device"),
        _v("HCLIB_TPU_AUTOSCALE_OUT_DELTA", "float", "8",
           "scale-out backlog RISE threshold, tasks/device/slice "
           "(the live-delta signal; malformed text raises)"),
        _v("HCLIB_TPU_AUTOSCALE_TENANT_PRESSURE", "float", "0.25",
           "deadline-budget drain fraction per slice that triggers an "
           "immediate deadline_out scale-out (malformed text raises)"),
        # -- durable checkpoint store (runtime/checkpoint.py) --
        _v("HCLIB_TPU_CKPT_DIR", "str", "unset",
           "BundleStore root directory: default_store() and the "
           "autoscaler's preempt hook write generations under it"),
        _v("HCLIB_TPU_CKPT_KEEP", "int", "3",
           "BundleStore retention: generations kept after each "
           "publish (>= 1; malformed text raises)"),
        _v("HCLIB_TPU_CKPT_FSYNC", "bool", "on",
           "fsync bundle members and directories at publish "
           "(0 = fast mode for tests; crash-safety not guaranteed)"),
        # -- device megakernel (device/megakernel.py) --
        _v("HCLIB_TPU_TRACE", "int", "0 (off)",
           "flight-recorder ring capacity (1 = default capacity)"),
        _v("HCLIB_TPU_CHECKPOINT", "bool", "off",
           "compile the quiesce protocol into schedulers"),
        _v("HCLIB_TPU_QUIESCE_STRIDE", "int", "1",
           "poll the quiesce word every Nth round"),
        _v("HCLIB_TPU_LANE_MAX_AGE", "int", "0 (off)",
           "age-triggered lane firing policy threshold, rounds"),
        _v("HCLIB_TPU_PRIORITY_BUCKETS", "int", "0 (off)",
           "priority-bucket dispatch tier: bucket rings per batch "
           "lane, popped lowest-nonempty-first (2..8; malformed or "
           "out-of-range text raises)"),
        _v("HCLIB_TPU_VERIFY", "bool", "off; on under pytest",
           "build-time static verifier (hclib_tpu.analysis; 0 forces "
           "off, nonzero forces on)"),
        # -- program cache (runtime/progcache.py) --
        _v("HCLIB_TPU_PROGRAM_CACHE", "bool", "on",
           "process-wide content-keyed program cache: jitted "
           "executables shared across content-identical builds "
           "(byte-identical programs; 0 forces off)"),
        _v("HCLIB_TPU_PROGRAM_CACHE_CAP", "int", "256",
           "program-cache LRU entry bound (>= 1; malformed or "
           "non-positive text raises)"),
        # -- model checker (hclib_tpu/analysis: explore.py / model.py) --
        _v("HCLIB_TPU_MODEL_DEPTH", "int", "64",
           "bounded-interleaving explorer depth bound, actions per "
           "path (malformed text raises)"),
        _v("HCLIB_TPU_MODEL_BUDGET_S", "float", "20",
           "bounded-interleaving explorer wall budget, seconds; an "
           "exhausted budget flags the result incomplete (malformed "
           "text raises)"),
        _v("HCLIB_TPU_MODEL_PERMS", "int", "3",
           "schedule-independence certification: permuted pop orders "
           "checked per claim (malformed text raises)"),
        # -- dispatch tiers --
        _v("HCLIB_TPU_FORASYNC_WIDTH", "int", "8",
           "default forasync device-tier batch width"),
        # -- multi-tenant ingress (device/tenants.py) --
        _v("HCLIB_TPU_TENANTS", "int", "0 (off)",
           "enable N equal tenant lanes on streaming runs"),
        _v("HCLIB_TPU_MESH_TENANTS", "int", "0 (off)",
           "enable N equal tenant lanes on resident inject meshes "
           "(shares the per-lane WEIGHTS/RATE/BURST/INFLIGHT/"
           "DEADLINE_S knobs above; malformed text raises)"),
        _v("HCLIB_TPU_TENANT_WEIGHTS", "list", "unset",
           "per-lane WRR weights, e.g. 4,2,1 (implies lane count)"),
        _v("HCLIB_TPU_TENANT_RATE", "float", "unset",
           "per-lane token-bucket refill rate, submits/s"),
        _v("HCLIB_TPU_TENANT_BURST", "float", "rate",
           "per-lane token-bucket capacity"),
        _v("HCLIB_TPU_TENANT_INFLIGHT", "float", "unset",
           "per-lane in-flight admission budget (whole number)"),
        _v("HCLIB_TPU_TENANT_DEADLINE_S", "float", "unset",
           "per-lane default admission deadline, seconds"),
        # -- completion-mailbox egress (device/egress.py) --
        _v("HCLIB_TPU_EGRESS_DEPTH", "int", "0 (off)",
           "completion-mailbox ring depth, rows; enables submit "
           "futures on tenant runs (malformed text raises)"),
        _v("HCLIB_TPU_EGRESS_BACKOFF_S", "float", "0.05",
           "Future.result() bounded-backoff poll cap, seconds "
           "(malformed text raises)"),
        # -- live telemetry plane (device/telemetry.py, runtime/slo.py) --
        _v("HCLIB_TPU_TELEMETRY", "bool", "off",
           "compile the live telemetry plane into egress-enabled "
           "streams: per-request lifecycle stamps + on-device latency "
           "histograms, scrapeable mid-run (0 forces off)"),
        _v("HCLIB_TPU_TELEMETRY_POLL_S", "float", "0.05",
           "TelemetryPoller snapshot interval, seconds (malformed "
           "text raises)"),
        _v("HCLIB_TPU_SLO_QUANTILE", "float", "0.99",
           "SLO objective quantile for the burn-rate engine, in "
           "(0, 1] (malformed text raises)"),
        _v("HCLIB_TPU_SLO_OBJECTIVE_ROUNDS", "int", "unset",
           "SLO latency objective, scheduler rounds: requests over "
           "this are burn-budget violations (malformed text raises)"),
        _v("HCLIB_TPU_SLO_BURN", "float", "2.0",
           "burn-rate threshold that fires the slo_out scale-out "
           "(max over windows; malformed text raises)"),
        _v("HCLIB_TPU_SLO_WINDOWS_S", "str", "60,300",
           "comma-separated burn-rate window lengths, seconds "
           "(malformed text raises)"),
        # -- dynamic graph service (device/dyngraph.py) --
        _v("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", "int", "2",
           "spare edge blocks pre-allocated per vertex for in-kernel "
           "edge splices (>= 1; malformed or non-positive text "
           "raises)"),
        _v("HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY", "int", "0",
           "bucket ring the UPDATE kind routes into on priority-"
           "bucketed dyngraph builds (0 = highest, fires before "
           "queries; clipped into [0, priority_buckets); malformed "
           "text raises)"),
        # -- native C++ runtime (read by getenv in native/, not here) --
        _v("HCLIB_TPU_AFFINITY", "str", "none",
           "native worker CPU pinning: strided | chunked | none",
           legacy=("HCLIB_AFFINITY",)),
        # -- harnesses --
        _v("HCLIB_TPU_BENCH_BUDGET_S", "float", "780",
           "bench.py wall budget for budget-gated sections, seconds"),
        _v("HCLIB_TPU_BIG_TESTS", "flag", "off",
           "opt into hardware-scale test variants (any nonempty value)"),
    ]
}


def _lookup(name: str) -> Optional[str]:
    """Raw environment text for a registered name: canonical first,
    then legacy aliases. An EMPTY canonical value falls through to the
    aliases (the pre-registry ``get(new) or get(old)`` idiom, where
    ``HCLIB_TPU_WORKERS= cmd`` wrapper lines must not mask a set
    legacy name); if every spelling is empty-or-unset, the first empty
    is returned (set-but-empty stays observable to ``env_raw``
    callers), else None."""
    try:
        var = REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not in the hclib_tpu env registry "
            "(runtime/env.py): add a row with its type and doc line"
        ) from None
    first_empty: Optional[str] = None
    for spelling in (var.name,) + var.legacy:
        v = os.environ.get(spelling)
        if v:
            return v
        if v is not None and first_empty is None:
            first_empty = v
    return first_empty


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    v = _lookup(name)
    return default if v is None else v


def env_set(name: str) -> bool:
    """Variable present AND nonempty (any text, including '0')."""
    return bool(_lookup(name))


def env_flag(name: str) -> bool:
    """Legacy truthiness: any nonempty string is True ('0' included)."""
    return bool(_lookup(name))


def env_bool(name: str, default: bool = False) -> bool:
    """Unset -> default; ''/'0' -> False; anything else -> True."""
    v = _lookup(name)
    if v is None:
        return default
    return v not in ("", "0")


def _parse(name: str, conv, malformed):
    v = _lookup(name)
    if not v:
        return None
    try:
        return conv(v)
    except (TypeError, ValueError):
        if malformed == "raise":
            raise ValueError(
                f"{name}={v!r} must be {'an int' if conv is int else 'a number'}"
            ) from None
        return malformed


def env_int(name: str, default: Optional[int] = None, *,
            malformed="raise") -> Optional[int]:
    """Int value; unset/empty -> ``default``. Malformed text raises
    (naming the variable) unless ``malformed=`` supplies a fallback."""
    v = _parse(name, int, malformed)
    return default if v is None else v


def env_float(name: str, default: Optional[float] = None, *,
              malformed="raise") -> Optional[float]:
    v = _parse(name, float, malformed)
    return default if v is None else v


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = _lookup(name)
    return default if not v else v


def registry_table():
    """(name, kind, default, doc) rows for README / tooling, sorted."""
    return [
        (v.name, v.kind, v.default, v.doc)
        for _, v in sorted(REGISTRY.items())
    ]
