"""Checkpoint/restore: preemption-tolerant snapshot and elastic resume of
the persistent megakernel.

A resident kernel that runs for minutes is exactly what TPU preemption
kills: a SIGTERM or maintenance event used to lose the whole task graph,
and the only mitigations were abort-and-rerun (the abort words, README
"Device faults") and post-mortem traces (the flight recorder). This module
is the missing robustness layer:

- **Quiesce** (device side, compiled in only with
  ``Megakernel(checkpoint=True)`` - the DeviceFaultPlan discipline): a
  host-writable quiesce word rides next to the abort word and is polled
  inside every round loop (megakernel sched, streaming-inject ctl[5],
  resident-mesh ctl word [1] folded into the termination collective). On
  observing it, workers stop popping at the next round boundary - per-kind
  batch lanes spill to the ready ring, in-flight prefetches drain, the
  resident mesh keeps its exchange rounds until the wire is empty (sent ==
  recv, outboxes drained) - and the kernel returns with its LIVE scheduler
  state through the aliased outputs: task table, ready ring, counters,
  value heap, tier counters, fault/trace cursors.

- **Bundle** (this module): ``CheckpointBundle`` serializes that exported
  state plus the host-held descriptor metadata into a versioned on-disk
  artifact - a directory holding ``state.npz`` (the arrays) and
  ``manifest.json`` (magic, version, kind, kernel-table names, capacities,
  mesh dims, sha256 of the npz) - integrity-checked on load.

- **Restore**: ``restore_megakernel`` / ``restore_stream`` /
  ``restore_resident`` validate the manifest against a freshly-built
  (same-code) runner and relaunch MID-GRAPH: the re-entry stages all value
  slots and rebuilds row free stacks from completion tombstones (the
  sharded steal loop's re-entrant discipline), so for a deterministic
  workload *checkpoint at round k + restore + run to completion* is
  bit-identical to the uninterrupted run (asserted in
  tests/test_checkpoint.py under interpret mode).

- **Elastic resume** (``CheckpointBundle.reshard``): a resident-mesh
  bundle taken on N chips restores onto M != N chips by re-homing the
  per-chip queues host-side - the same task-conservation semantics as the
  PR 2 dead-chip re-homing path (link-free migratable rows move whole;
  totals conserved), applied at rest instead of over ICI. Rows that cannot
  re-home (successor links, homed-migration proxies, dynamic out slots)
  are refused with a diagnostic naming the offending row.

- **Preemption wiring** (``checkpoint_on_preempt``): SIGTERM (via
  ``resilience.install_preempt_handler``), the ``HCLIB_TPU_PREEMPT`` env,
  or the watchdog's optional checkpoint rung
  (``HCLIB_TPU_WATCHDOG_CHECKPOINT``) fire registered preemption hooks;
  binding a stream quiesces it so the driving ``run_stream`` returns a
  restorable snapshot instead of losing the graph - checkpoint, then stop.

- **Durable store** (``BundleStore``): a generational on-disk store of
  bundles with crash-safe publish (stage to a temp dir, fsync, atomic
  rename, generation pointer written LAST - a torn save is never
  visible), bounded retention (``keep=K`` generations), and
  self-healing restore: ``load_latest()`` walks generations
  newest-first, quarantines torn/corrupt/version-mismatched ones aside
  with a typed ``BundleFault`` report (metrics-counted, TR_CKPT-traced
  via the CK_* subcodes), and resumes from the newest generation that
  validates. An unrecoverable store raises so the caller can poison
  outstanding futures through the serving degradation ladder instead of
  hanging. The autoscaler's preempt hook writes through it.

Caveats (stated, not hidden): host-side tasks and help-first host
execution are NOT captured - the bundle holds device scheduler state only,
so checkpoint the device layer and re-enter the host program idempotently
(the same caveat class as ``help_finish``'s documented timeout limit).
Resharding a bundle whose live rows carry successor links or per-device
data buffers is refused; exported wait tables RE-HOME across mesh sizes
(the parked rows deal with their waits as one unit), with the refusal
narrowed to waits whose satisfier sits in unexported host residue
(``meta['host_residue']`` - the puts target the OLD device coordinates,
so they must be re-issued against the resumed mesh before a resize).
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import shutil
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import resilience

__all__ = [
    "BUNDLE_VERSION",
    "BundleFault",
    "BundleStore",
    "CheckpointBundle",
    "CheckpointError",
    "default_store",
    "snapshot_megakernel",
    "snapshot_stream",
    "snapshot_resident",
    "restore_megakernel",
    "restore_stream",
    "restore_resident",
    "checkpoint_on_preempt",
]

MAGIC = "hclib-tpu-checkpoint"
BUNDLE_VERSION = 1

# state dict keys serialized for every kind (data buffers ride as
# ``data/<name>`` entries; the stream kind adds ``ring_rows`` - plus the
# per-tenant ``tctl``/``tstats`` counter blocks when the front door runs
# tenant lanes, and the per-row submit-token table ``etok`` when the
# completion-mailbox egress runs (device/egress.py; tokens of
# installed-but-unretired rows survive the cut so their futures resolve
# after resume) - the resident kind adds its exported wait table and -
# when injecting - the per-device ring residue + cursor words. A
# telemetry-enabled stream (device/telemetry.py) adds the echoed
# histogram/gauge block ``tele`` and per-row stamp table ``tlat`` so
# the round timebase and per-tenant latency totals stay cumulative
# across the cut).
_STATE_KEYS = ("tasks", "succ", "ready", "counts", "ivalues")
_OPT_KEYS = (
    "ring_rows", "waits", "ictl", "tctl", "tstats", "etok",
    "tele", "tlat",
)

# Descriptor-word indices, bound once (descriptor ABI, device/descriptor).
from ..device.descriptor import (  # noqa: E402
    DESC_WORDS,
    F_CSR_N,
    F_DEP,
    F_FN,
    F_HOME,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
)


class CheckpointError(RuntimeError):
    """A bundle failed validation: corrupt artifact, version mismatch, or
    a restore target whose configuration contradicts the manifest."""


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives power loss
    (the rename itself is atomic; its durability needs the parent
    flushed). Best-effort: some filesystems refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _kernel_meta(mk) -> Dict[str, Any]:
    m = {
        "kernel_names": list(mk.kernel_names),
        "capacity": int(mk.capacity),
        "num_values": int(mk.num_values),
        "succ_capacity": int(mk.succ_capacity),
        "data_specs": {
            k: {"shape": list(s.shape), "dtype": str(np.dtype(s.dtype))}
            for k, s in mk.data_specs.items()
        },
    }
    # Dynamic-graph builds stamp their layout (plain ints + the bound
    # update stream) into the manifest: reshard's canonical-rebuild path
    # keys off ``meta['dyngraph']`` (device/dyngraph.reshard_dyngraph).
    dg = getattr(mk, "_dyngraph", None)
    if dg is not None:
        m["dyngraph"] = {
            k: (list(map(list, v)) if k == "updates" else v)
            for k, v in dg.items()
        }
    return m


def _kind_classes(mk) -> Dict[str, str]:
    """Build-time migratability classification for the bundle manifest
    (hclib_tpu.analysis; memoized on the megakernel) - ``reshard``
    reads it back for upfront whole-program diagnostics. Best-effort:
    a kernel table the shim cannot interpret classes 'unknown'."""
    try:
        from ..analysis import classify_megakernel

        return dict(classify_megakernel(mk))
    except Exception:  # noqa: BLE001 - manifest enrichment only
        return {}


def _kernel_table_diff(mine: List[str], theirs: List[str]) -> str:
    """Positional diff of two kernel tables (the ``diff()``-style
    context a table mismatch error carries): F_FN words index by
    POSITION, so 'same names, different order' is the silent-wrong-
    kernel hazard and the per-position story is what fixes it."""
    lines = []
    for i in range(max(len(mine), len(theirs))):
        a = mine[i] if i < len(mine) else "<absent>"
        b = theirs[i] if i < len(theirs) else "<absent>"
        if a != b:
            lines.append(f"[{i}] {a!r} here != {b!r} in the bundle")
    return "; ".join(lines)


def _where(bundle_or_meta) -> str:
    """Location context for a diagnostic: the bundle's source path and
    store generation when it came off disk, empty for in-memory ones."""
    src = getattr(bundle_or_meta, "source_path", None)
    gen = getattr(bundle_or_meta, "generation", None)
    if src is None and gen is None:
        return ""
    parts = []
    if src is not None:
        parts.append(str(src))
    if gen is not None:
        parts.append(f"generation {gen}")
    return f" ({', '.join(parts)})"


def _check_kernel_meta(mk, meta: Dict[str, Any], where: str = "") -> None:
    """The restore target must be the SAME program shape the bundle was
    taken from: descriptor F_FN words index the kernel table by position,
    so a renamed/reordered table would silently run the wrong kernels.
    ``where`` carries the bundle's path/generation into every error."""
    mine = _kernel_meta(mk)
    if mine["kernel_names"] != meta.get("kernel_names"):
        detail = _kernel_table_diff(
            list(mine["kernel_names"]),
            list(meta.get("kernel_names") or []),
        )
        raise CheckpointError(
            f"restore target mismatch{where}: the kernel_names table "
            f"differs positionally - {detail} - rebuild the megakernel "
            "exactly as checkpointed (names, order, capacities)"
        )
    for key in ("capacity", "num_values", "succ_capacity"):
        if mine[key] != meta.get(key):
            raise CheckpointError(
                f"restore target mismatch{where}: {key} is {mine[key]!r} "
                f"here but {meta.get(key)!r} in the bundle - rebuild the "
                "megakernel exactly as checkpointed (names, order, "
                "capacities)"
            )
    if set(mine["data_specs"]) != set(meta.get("data_specs", {})):
        raise CheckpointError(
            f"restore target mismatch{where}: data buffers "
            f"{sorted(mine['data_specs'])} != bundle "
            f"{sorted(meta.get('data_specs', {}))}"
        )


class CheckpointBundle:
    """One checkpoint: ``kind`` ("megakernel" | "stream" | "resident"),
    ``meta`` (the JSON manifest body) and ``arrays`` (flat name ->
    np.ndarray; data buffers under ``data/<name>``)."""

    def __init__(self, kind: str, meta: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]) -> None:
        self.kind = kind
        self.meta = meta
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        # Provenance, set by load()/BundleStore: every later diagnostic
        # (version/program mismatch, reshard refusal) names WHERE the
        # offending artifact lives instead of just what is wrong.
        self.source_path: Optional[str] = None
        self.generation: Optional[int] = None

    # ---- state <-> arrays ----

    @staticmethod
    def _flatten_state(state: Dict[str, Any],
                       meta: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Flatten a runner state dict into named arrays. Extension
        dtypes numpy cannot round-trip through npz (bfloat16 data
        buffers save as raw ``|V2`` void and reload unusable) are stored
        as same-width unsigned views with the true dtype recorded in
        ``meta['dtypes']`` - ``state()`` views them back bit-exactly."""
        arrays = {k: np.asarray(state[k]) for k in _STATE_KEYS}
        for k in _OPT_KEYS:
            if k in state:
                arrays[k] = np.asarray(state[k])
        for name, buf in (state.get("data") or {}).items():
            arrays[f"data/{name}"] = np.asarray(buf)
        dtypes: Dict[str, str] = {}
        for k, v in arrays.items():
            if v.dtype.kind not in "biufc":
                dtypes[k] = str(v.dtype)
                arrays[k] = v.view(f"u{v.dtype.itemsize}")
        if dtypes:
            meta["dtypes"] = dtypes
        return arrays

    def _restore_dtype(self, key: str, arr: np.ndarray) -> np.ndarray:
        name = (self.meta.get("dtypes") or {}).get(key)
        if name is None:
            return arr.copy()
        import ml_dtypes  # noqa: F401  (registers bfloat16 & friends)

        return arr.view(np.dtype(name)).copy()

    def state(self) -> Dict[str, Any]:
        """The resumable state dict (what ``Megakernel.resume`` /
        ``run_stream(resume_state=)`` / ``run(resume_state=)`` take)."""
        st: Dict[str, Any] = {
            k: self._restore_dtype(k, self.arrays[k]) for k in _STATE_KEYS
        }
        for k in _OPT_KEYS:
            if k in self.arrays:
                st[k] = self.arrays[k].copy()
        st["data"] = {
            k.split("/", 1)[1]: self._restore_dtype(k, v)
            for k, v in self.arrays.items()
            if k.startswith("data/")
        }
        return st

    # ---- persistence ----

    def save(self, path: str, metrics=None, fsync: bool = False,
             fault_plan=None) -> Dict[str, Any]:
        """Write the bundle as a directory: ``state.npz`` +
        ``manifest.json`` (magic, version, kind, meta, npz sha256).
        Returns {bundle_bytes, save_s, sha256}; with ``metrics`` (a
        MetricsRegistry) the stats are recorded under "checkpoint".
        ``fsync=True`` flushes both members and the directory (the
        BundleStore publish discipline); ``fault_plan`` routes the
        bytes through the chaos disk sites (torn write, bit flip,
        missing/truncated manifest) for the durability soak."""
        t0 = time.monotonic()
        os.makedirs(path, exist_ok=True)
        buf = io.BytesIO()
        np.savez(buf, **self.arrays)
        blob = buf.getvalue()
        sha = hashlib.sha256(blob).hexdigest()
        if fault_plan is not None:
            blob = fault_plan.on_bundle_blob(blob)
        npz_path = os.path.join(path, "state.npz")
        with open(npz_path, "wb") as f:
            f.write(blob)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        manifest = {
            "magic": MAGIC,
            "version": BUNDLE_VERSION,
            "kind": self.kind,
            "created_unix": time.time(),
            "sha256": sha,
            "meta": self.meta,
        }
        text = json.dumps(manifest, indent=1, sort_keys=True)
        if fault_plan is not None:
            text = fault_plan.on_manifest_text(text)
        if text is not None:  # a chaos-dropped manifest never lands
            with open(os.path.join(path, "manifest.json"), "w") as f:
                f.write(text)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
        if fsync:
            _fsync_dir(path)
        stats = {
            "bundle_bytes": len(blob),
            "save_s": round(time.monotonic() - t0, 6),
            "sha256": sha,
        }
        if metrics is not None:
            rec = {"bundle_bytes": stats["bundle_bytes"],
                   "save_s": stats["save_s"]}
            for k in ("quiesce_latency_s", "quiesce_round", "executed_at"):
                if k in self.meta and self.meta[k] is not None:
                    rec[k] = self.meta[k]
            metrics.record("checkpoint", rec)
        return stats

    @classmethod
    def load(cls, path: str,
             generation: Optional[int] = None) -> "CheckpointBundle":
        """Load + integrity-check a saved bundle. Raises CheckpointError
        on a missing/foreign manifest, a version from the future, or an
        npz whose sha256 disagrees with the manifest (bit rot, truncated
        copy, tampering). Every error names the offending file path -
        and the store generation, when ``generation`` is passed (as
        ``BundleStore`` does) - so a multi-generation post-mortem
        points at ONE artifact, not "some bundle somewhere"."""
        gen = "" if generation is None else f" (generation {generation})"
        man_path = os.path.join(path, "manifest.json")
        npz_path = os.path.join(path, "state.npz")
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"unreadable checkpoint manifest {man_path}{gen}: {e}"
            )
        if manifest.get("magic") != MAGIC:
            raise CheckpointError(
                f"{man_path}{gen} is not a {MAGIC} bundle "
                f"(magic={manifest.get('magic')!r})"
            )
        try:
            version = int(manifest.get("version", -1))
        except (TypeError, ValueError):
            version = -1  # a mangled field is a corrupt manifest
        if version != BUNDLE_VERSION:
            raise CheckpointError(
                f"bundle version {manifest.get('version')!r} in "
                f"{man_path}{gen} != supported {BUNDLE_VERSION}: "
                "re-checkpoint with this build or restore with the "
                "build that wrote it"
            )
        try:
            with open(npz_path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(
                f"unreadable checkpoint state {npz_path}{gen}: {e}"
            )
        sha = hashlib.sha256(blob).hexdigest()
        if sha != manifest.get("sha256"):
            raise CheckpointError(
                f"checkpoint state corrupt: sha256 {sha[:12]}... != "
                f"manifest {str(manifest.get('sha256'))[:12]}... "
                f"({npz_path}{gen})"
            )
        try:
            with np.load(io.BytesIO(blob)) as z:
                arrays = {k: z[k] for k in z.files}
        except (OSError, ValueError, KeyError) as e:
            # A blob that hashes right but will not parse (a manifest
            # re-stamped over a torn npz) is corrupt, not a crash.
            raise CheckpointError(
                f"unparseable checkpoint state {npz_path}{gen}: {e}"
            )
        b = cls(manifest["kind"], manifest.get("meta", {}), arrays)
        b.source_path = path
        b.generation = generation
        return b

    # ---- elastic resume (resident mesh only) ----

    def reshard(self, ndev_new: int) -> "CheckpointBundle":
        """Re-home a resident-mesh bundle's per-chip queues onto
        ``ndev_new`` devices (N -> M re-sharding) - checkpoint-time
        elasticity with the PR 2 dead-chip re-homing semantics: only
        link-free migratable rows move (whole, conserving the pending
        total), dealt round-robin; per-device accumulator value slots
        fold by SUM into the new devices' symmetric host regions (the
        ``ShardedMegakernel.migratable_fns`` contract: migratable kernels
        write accumulate-style slots the host combines) and executed
        counters fold the same way, so executed + pending totals are
        conserved exactly. Refused with a diagnostic when any live row
        carries successor links / a home-link / a dynamic out slot, or
        when the kernel has per-device data buffers (no generic fold
        exists for those).

        Exported wait tables RE-HOME: a wait-parked row (its dep
        counter holds exactly one bump per wait parked on it) moves as
        ONE UNIT with all its waits - parked rows group per channel and
        deal round-robin onto the new roster, allocated but NOT in the
        ready ring, with the wait entries rewritten to the new (device,
        row) coordinates; wait counts and per-channel need sums are
        conserved exactly. Needs stay in their export rebasing (arrival
        counters restart at zero on every resume), and host puts issued
        AFTER the resume target the resumed roster, so re-homed waits
        fire exactly as on the original mesh. The one refusal left:
        waits whose satisfier sits in unexported host residue
        (``meta['host_residue']``, declared at snapshot time) - those
        puts were aimed at the OLD coordinates, so the whole-program
        diagnostic names every stranded channel and the fix (re-issue
        the residue on the original size, or drain it first)."""
        from ..device.megakernel import (
            C_ALLOC, C_EXECUTED, C_PENDING, C_VALLOC,
        )

        if self.kind != "resident":
            raise CheckpointError(
                f"reshard applies to resident-mesh bundles, not {self.kind}"
            )
        try:
            ndev_new = int(ndev_new)
        except (TypeError, ValueError):
            raise CheckpointError(
                f"reshard wants an integer device count, got {ndev_new!r}"
            )
        tasks = self.arrays["tasks"]
        counts = self.arrays["counts"]
        ivalues = self.arrays["ivalues"]
        ndev, cap, _ = tasks.shape
        if ndev_new < 1 or (ndev_new & (ndev_new - 1)):
            raise CheckpointError(
                f"reshard wants a power-of-two device count >= 1, got "
                f"{ndev_new} (the resident mesh's hypercube hop schedule "
                "is pof2-only; an evacuation drops to the next pof2 "
                "below the survivor count)"
            )
        if self.meta.get("dyngraph"):
            # Mutable-adjacency bundles DO carry per-device data buffers
            # (the spliced block rows) - but their layout stamp gives
            # reshard what the generic path lacks: a canonical rebuild
            # (static rows + union-applied updates in uid order) every
            # new device can share. Delegate wholesale; the dyngraph
            # merge owns its own eligibility/conservation story.
            from ..device.dyngraph import reshard_dyngraph

            return reshard_dyngraph(self, ndev_new)
        if any(k.startswith("data/") for k in self.arrays):
            raise CheckpointError(
                "reshard cannot re-home per-device data buffers: restore "
                "onto the original mesh size, or drain and re-partition "
                "at the application level"
            )
        waits = self.arrays.get("waits")
        # Parse the exported wait table into parked[(d, row)] ->
        # [(chan, need), ...]. Needs are already rebased (need minus the
        # old device's arrival count at export), and resume restarts
        # every arrival counter at zero, so a re-homed entry means the
        # same thing on ANY roster: "this row fires after `need` more
        # puts on `chan` reach its device". The only waits that cannot
        # re-home are those whose remaining puts sit in unexported host
        # residue - the caller aimed them at the OLD (device, row)
        # coordinates (declared via ``meta['host_residue']``:
        # {channel name: outstanding put count}).
        parked: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
        if waits is not None:
            warr = np.asarray(waits)
            chan_names = list(self.meta.get("channels") or [])

            def _chan(ch: int) -> str:
                return (
                    repr(chan_names[ch])
                    if 0 <= ch < len(chan_names) else f"id {ch}"
                )

            residue = {
                str(k): int(v)
                for k, v in dict(self.meta.get("host_residue") or {}).items()
                if int(v) > 0
            }
            stranded: Dict[int, List[Tuple[int, int, int]]] = {}
            for d in range(warr.shape[0]):
                for i in range(int(warr[d, 0, 0])):
                    ch, need, row = (int(x) for x in warr[d, 1 + i])
                    name = (
                        chan_names[ch]
                        if 0 <= ch < len(chan_names) else None
                    )
                    if need > 0 and name is not None and name in residue:
                        stranded.setdefault(ch, []).append((d, row, need))
                    parked.setdefault((d, row), []).append((ch, need))
            if stranded:
                # Whole-program refusal (the ISSUE 12 discipline): one
                # error names EVERY stranded channel, its wait and
                # residue counts, and the fix - not the first wait hit.
                per_chan = [
                    f"channel {_chan(ch)}: {len(ws)} wait(s) needing "
                    f"{sum(n for _d, _r, n in ws)} more arrival(s) vs "
                    f"{residue[chan_names[ch]]} unexported host put(s)"
                    for ch, ws in sorted(stranded.items())
                ]
                d0, r0, n0 = stranded[min(stranded)][0]
                raise CheckpointError(
                    f"reshard: "
                    f"{sum(len(ws) for ws in stranded.values())} pending "
                    f"wait(s) on {len(stranded)} channel(s) have their "
                    f"satisfier in unexported host residue "
                    f"({'; '.join(per_chan)}); e.g. device {d0} row {r0} "
                    f"still needs {n0} arrival(s) - the outstanding puts "
                    "target the original (device, row) coordinates, so "
                    "resume on the original mesh size and re-issue (or "
                    "drain) the residue before resizing"
                )
        V = ivalues.shape[1]
        va = int(counts[:, C_VALLOC].max())
        # Whole-program eligibility scan (ISSUE 12): instead of refusing
        # at the FIRST offending row, collect every violation, fold it
        # per kernel kind, and - when the bundle carries the build-time
        # ``kind_classes`` classification (Megakernel.describe() /
        # hclib_tpu.analysis) - lead the diagnostic with the per-kind
        # story, so one error names everything that must drain before a
        # resize instead of a row-by-row whack-a-mole.
        kind_names = list(self.meta.get("kernel_names") or [])
        kind_classes = dict(self.meta.get("kind_classes") or {})
        violations: List[Tuple[int, int, int, str]] = []
        live_rows: List[np.ndarray] = []
        parked_rows: List[Tuple[int, int, np.ndarray]] = []
        for d in range(ndev):
            alloc = int(counts[d][C_ALLOC])
            for i in range(alloc):
                row = tasks[d, i]
                if int(row[F_DEP]) == -1:
                    continue  # tombstone (completed/exported)
                bad = None
                nwaits = len(parked.get((d, i), ()))
                dep = int(row[F_DEP])
                if dep != nwaits:
                    # A wait-parked row carries exactly one dep bump per
                    # wait parked on it (the export contract); anything
                    # else is a real dependency the deal cannot re-home.
                    bad = (
                        f"a dependency counter {dep} != its "
                        f"{nwaits} parked wait(s)"
                        if nwaits else "a nonzero dependency counter"
                    )
                elif (
                    int(row[F_SUCC0]) != NO_TASK
                    or int(row[F_SUCC1]) != NO_TASK
                    or int(row[F_CSR_N]) != 0
                ):
                    bad = "successor links"
                elif int(row[F_HOME]) >= 0:
                    bad = "a migration home-link"
                elif int(row[F_OUT]) >= va:
                    bad = f"a dynamic out slot ({int(row[F_OUT])} >= {va})"
                if bad is not None:
                    violations.append((d, i, int(row[F_FN]), bad))
                    continue
                if nwaits:
                    parked_rows.append((d, i, row.copy()))
                else:
                    live_rows.append(row.copy())
        if violations:
            by_kind: Dict[int, int] = {}
            for _d, _i, fn, _bad in violations:
                by_kind[fn] = by_kind.get(fn, 0) + 1
            kinds = []
            for fn, n in sorted(by_kind.items()):
                name = (
                    kind_names[fn]
                    if 0 <= fn < len(kind_names) else f"id {fn}"
                )
                cls = kind_classes.get(str(name))
                kinds.append(
                    f"{name!r}"
                    + (f" [{cls}]" if cls else "")
                    + f": {n} row(s)"
                )
            d0, i0, _fn0, bad0 = violations[0]
            raise CheckpointError(
                f"reshard: {len(violations)} live row(s) across "
                f"{ndev} device(s) are not link-free "
                f"({'; '.join(kinds)}); e.g. device {d0} row {i0} "
                f"carries {bad0}; only ready link-free rows re-home "
                "across mesh sizes (quiesce drains dependent subgraphs "
                "first, or restore onto the original mesh size)"
            )
        pend_total = int(counts[:, C_PENDING].sum())
        if pend_total != len(live_rows) + len(parked_rows):
            raise CheckpointError(
                f"reshard conservation check failed: {pend_total} pending "
                f"!= {len(live_rows)} live + {len(parked_rows)} "
                "wait-parked rows - the bundle is not a clean quiesce "
                "snapshot"
            )
        parts: List[List[np.ndarray]] = [[] for _ in range(ndev_new)]
        for i, row in enumerate(live_rows):
            parts[i % ndev_new].append(row)
        # Wait-parked rows re-home as UNITS - each row moves with every
        # wait parked on it. Deterministic order (first-wait channel,
        # then original coordinates) grouped per channel, then dealt
        # round-robin, so a channel's waiters spread across the new
        # roster the same way on every run.
        park_parts: List[List[Tuple[int, int, np.ndarray]]] = [
            [] for _ in range(ndev_new)
        ]
        park_order = sorted(
            parked_rows,
            key=lambda e: (min(ch for ch, _n in parked[(e[0], e[1])]),
                           e[0], e[1]),
        )
        for k, entry in enumerate(park_order):
            park_parts[k % ndev_new].append(entry)
        for j, p in enumerate(parts):
            if len(p) + len(park_parts[j]) > cap:
                # The M=1 (and any aggressive scale-in) failure mode:
                # the folded backlog must still fit each survivor's
                # task table. Diagnose with the numbers that fix it.
                total = len(live_rows) + len(parked_rows)
                raise CheckpointError(
                    f"reshard {ndev} -> {ndev_new}: device {j} would "
                    f"hold {len(p) + len(park_parts[j])} rows > capacity "
                    f"{cap} ({total} live+parked rows total); scale in "
                    f"less aggressively (>= {-(-total // cap)} "
                    "devices) or rebuild with a larger capacity"
                )
        tasks_new = np.zeros((ndev_new, cap, DESC_WORDS), np.int32)
        ready_new = np.full((ndev_new, cap), NO_TASK, np.int32)
        counts_new = np.zeros((ndev_new, 8), np.int32)
        ivalues_new = np.zeros((ndev_new, V), np.int32)
        waits_new = None
        if waits is not None:
            warr = np.asarray(waits)
            max_w = warr.shape[1] - 1
            waits_new = np.zeros(
                (ndev_new,) + warr.shape[1:], np.int32
            )
        for j, p in enumerate(parts):
            for i, row in enumerate(p):
                tasks_new[j, i] = row
                ready_new[j, i] = i
            n = len(p)
            # Parked rows land AFTER the ready rows: allocated (and
            # counted pending) but NOT in the ready ring - resume's
            # no-bump restage leaves their dep counters holding the
            # wait bumps, exactly the exported shape.
            for k, (od, orow, row) in enumerate(park_parts[j]):
                slot = n + k
                tasks_new[j, slot] = row
                if waits_new is not None:
                    for ch, need in parked[(od, orow)]:
                        w = int(waits_new[j, 0, 0])
                        if w >= max_w:
                            raise CheckpointError(
                                f"reshard {ndev} -> {ndev_new}: device "
                                f"{j} would park > {max_w} wait(s); "
                                "scale in less aggressively or raise "
                                "max_waits"
                            )
                        waits_new[j, 1 + w] = (ch, need, slot)
                        waits_new[j, 0, 0] = w + 1
            total_j = n + len(park_parts[j])
            counts_new[j][0] = 0  # head
            counts_new[j][1] = n  # tail (ready ring: live rows only)
            counts_new[j][C_ALLOC] = total_j
            counts_new[j][C_PENDING] = total_j
            counts_new[j][C_VALLOC] = va
        # Fold the old devices' accumulator host regions and executed
        # counters mod M: column-wise sums (what the host combines at the
        # end) are conserved exactly.
        for d in range(ndev):
            j = d % ndev_new
            ivalues_new[j][:va] += ivalues[d][:va]
            counts_new[j][C_EXECUTED] += int(counts[d][C_EXECUTED])
        scap = self.arrays["succ"].shape[1]
        succ_new = np.full((ndev_new, scap), NO_TASK, np.int32)
        arrays = {
            "tasks": tasks_new, "succ": succ_new, "ready": ready_new,
            "counts": counts_new, "ivalues": ivalues_new,
        }
        if waits_new is not None:
            warr = np.asarray(waits)
            # Post-deal conservation: total wait count and per-channel
            # need sums must survive the re-home exactly.
            if int(waits_new[:, 0, 0].sum()) != int(warr[:, 0, 0].sum()):
                raise CheckpointError(
                    "reshard wait conservation check failed: "
                    f"{int(waits_new[:, 0, 0].sum())} re-homed wait(s) "
                    f"!= {int(warr[:, 0, 0].sum())} exported"
                )
            need_old: Dict[int, int] = {}
            need_new: Dict[int, int] = {}
            for arr, acc in ((warr, need_old), (waits_new, need_new)):
                for d in range(arr.shape[0]):
                    for i in range(int(arr[d, 0, 0])):
                        ch, need, _row = (int(x) for x in arr[d, 1 + i])
                        acc[ch] = acc.get(ch, 0) + need
            if need_old != need_new:
                raise CheckpointError(
                    "reshard wait conservation check failed: per-channel "
                    f"need sums diverged ({need_old} -> {need_new})"
                )
            arrays["waits"] = waits_new
        if "ring_rows" in self.arrays:
            # Inject-ring residue re-homes like the task rows: injected
            # descriptors are link-free by construction (inject refuses
            # dep_count != 0), so the rows are location-free and deal
            # round-robin; the consumed cursor was already folded into
            # the packed-from-zero representation at export.
            rr = np.asarray(self.arrays["ring_rows"])
            ic = np.asarray(self.arrays["ictl"])
            R = rr.shape[1]
            residue = [
                rr[d, i]
                for d in range(rr.shape[0])
                for i in range(int(ic[d, 0]))
            ]
            rr_new = np.zeros((ndev_new, R) + rr.shape[2:], np.int32)
            ic_new = np.zeros((ndev_new, 8), np.int32)
            ic_new[:, 1] = ic[:, 1].max() if ic.size else 0  # close flag
            for i, row in enumerate(residue):
                j = i % ndev_new
                slot = ic_new[j, 0]
                if slot >= R:
                    raise CheckpointError(
                        f"reshard {ndev} -> {ndev_new}: device {j} would "
                        f"hold > {R} inject-ring residue rows "
                        f"({len(residue)} total); scale in less "
                        "aggressively or raise ring_capacity"
                    )
                rr_new[j, slot] = row
                ic_new[j, 0] = slot + 1
            if int(ic_new[:, 0].sum()) != len(residue):
                raise CheckpointError(
                    "reshard ring conservation check failed"
                )
            arrays["ring_rows"] = rr_new
            arrays["ictl"] = ic_new
        # Mesh-tenancy counter blocks (aggregate (T, 8) tctl/tstats,
        # MeshTenantTable.export_state): device-count-free by
        # construction, so a reshard passes them through untouched -
        # per-tenant accepted/completed/expired totals are conserved
        # across N -> M exactly like the tagged residue rows above.
        for k in ("tctl", "tstats"):
            if k in self.arrays:
                arrays[k] = np.asarray(self.arrays[k]).copy()
        meta = dict(self.meta)
        meta["ndev"] = ndev_new
        meta["resharded_from"] = int(ndev)
        return CheckpointBundle("resident", meta, arrays)

    def diff(self, other: "CheckpointBundle") -> Dict[str, Any]:
        """Structural comparison of two bundles, for the bit-identity
        assertions the storm tests make: returns ``{'equal': bool,
        'kind': ..., 'only_self': [...], 'only_other': [...],
        'mismatched': {key: {n, max_abs}}}``. Arrays compare bit-exactly
        (shape + values); dtype views are compared raw (two bundles of
        the same build store identically)."""
        only_self = sorted(set(self.arrays) - set(other.arrays))
        only_other = sorted(set(other.arrays) - set(self.arrays))
        mismatched: Dict[str, Any] = {}
        for k in sorted(set(self.arrays) & set(other.arrays)):
            a, b = self.arrays[k], other.arrays[k]
            if a.shape != b.shape or a.dtype != b.dtype:
                mismatched[k] = {
                    "shape": [list(a.shape), list(b.shape)],
                    "dtype": [str(a.dtype), str(b.dtype)],
                }
                continue
            if not np.array_equal(a, b):
                av = a.astype(np.int64) if a.dtype.kind in "biu" else a
                bv = b.astype(np.int64) if b.dtype.kind in "biu" else b
                d = np.abs(av - bv)
                mismatched[k] = {
                    "n": int((av != bv).sum()),
                    "max_abs": float(d.max()),
                }
        return {
            "equal": not (only_self or only_other or mismatched)
            and self.kind == other.kind,
            "kind": [self.kind, other.kind],
            "only_self": only_self,
            "only_other": only_other,
            "mismatched": mismatched,
        }


# ---------------------------------------------------------- durable store

@dataclass
class BundleFault:
    """One generation ``BundleStore.load_latest`` could not use: typed
    so chaos harnesses (and operators) can assert on WHAT failed, not
    parse message text. ``reason`` is one of ``torn`` (manifest missing
    or unparseable - the mid-save crash signature), ``corrupt`` (sha256
    or npz-payload mismatch - bit rot), ``version`` (format from a
    different build), ``foreign`` (not a bundle at all)."""

    generation: int
    path: str
    reason: str
    error: str


def _classify_fault(msg: str) -> str:
    low = msg.lower()
    if "magic" in low:
        return "foreign"
    if "sha256" in low or "unparseable" in low:
        return "corrupt"  # payload damage (flip/truncation past the sha)
    if "version" in low:
        return "version"
    if "manifest" in low or "missing" in low:
        return "torn"  # the mid-save crash signature: no valid manifest
    return "corrupt"


class BundleStore:
    """Generational on-disk store of ``CheckpointBundle``s with
    crash-safe publish and self-healing restore.

    Layout under ``root``::

        gen-000001/          one published generation (a bundle dir)
        gen-000002/
        CURRENT              newest generation number (a hint, not an
                             authority - load_latest() walks the dirs)
        quarantine/          generations load_latest() refused, moved
                             aside with their fault recorded

    Publish discipline (the crash-safety invariant): ``save`` stages
    the bundle into ``.tmp-gen-N`` (members written and - with
    ``fsync`` on - flushed to disk), fsyncs the staging dir, then
    atomically renames it to ``gen-N`` and fsyncs ``root``; the
    ``CURRENT`` pointer is rewritten LAST (tmp + rename). A crash at
    ANY byte of that sequence leaves either the previous store state or
    the new generation - never a visible torn bundle
    (``analysis/explore.py``'s ``BundleStoreModel`` certifies the
    ordering over every crash x concurrent-load interleaving).

    Restore discipline (self-healing): ``load_latest`` walks
    generations NEWEST-FIRST; one that fails validation is moved to
    ``quarantine/`` with a typed ``BundleFault`` appended to
    ``self.faults`` (metrics ``checkpoint.quarantined``, trace
    CK_QUARANTINE), and the walk continues - the newest generation that
    validates wins (``checkpoint.fallback`` when it was not the newest
    on disk). An EMPTY walk raises ``CheckpointError`` listing every
    fault so the caller can poison outstanding futures through the
    serving degradation ladder instead of hanging on a resume that will
    never come.

    Knobs: ``keep`` (default ``HCLIB_TPU_CKPT_KEEP``, 3) bounds
    retention - older generations are pruned after each publish;
    ``fsync`` (default ``HCLIB_TPU_CKPT_FSYNC``, on) trades crash
    durability for speed in tests; ``fault_plan`` routes the PR 13 disk
    chaos sites (torn blob, bit flip, manifest loss, preempt mid-save /
    mid-restore) through the store for ``chaos_soak --durability``.
    """

    def __init__(self, root: str, keep: Optional[int] = None,
                 fsync: Optional[bool] = None, metrics=None,
                 fault_plan=None) -> None:
        from . import env as _env

        self.root = str(root)
        if keep is None:
            keep = _env.env_int("HCLIB_TPU_CKPT_KEEP", 3)
        self.keep = int(keep)
        if self.keep < 1:
            raise CheckpointError(
                f"BundleStore keep={self.keep} must be >= 1 (retention "
                "of zero generations would unpublish every save)"
            )
        if fsync is None:
            fsync = _env.env_bool("HCLIB_TPU_CKPT_FSYNC", True)
        self.fsync = bool(fsync)
        self.metrics = metrics
        self.fault_plan = fault_plan
        self.faults: List[BundleFault] = []
        # Host-emitted TR_CKPT records ([tag, ordinal, -(1+CK_*), gen]);
        # trace_info() brackets them for tools/timeline.py.
        self.events: List[List[int]] = []
        self._t0_ns = time.monotonic_ns()
        os.makedirs(self.root, exist_ok=True)

    # -- internals ----------------------------------------------------

    def _trace(self, code: int, generation: int) -> None:
        from ..device import tracebuf as tb

        self.events.append(
            [tb.TR_CKPT, len(self.events), -(1 + code), int(generation)]
        )

    def _count(self, name: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.record_event(name, fields)

    def path_of(self, generation: int) -> str:
        return os.path.join(self.root, f"gen-{int(generation):06d}")

    def generations(self) -> List[int]:
        """Published generation numbers, ascending (staging and
        quarantine dirs excluded)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for n in names:
            if n.startswith("gen-"):
                try:
                    out.append(int(n[4:]))
                except ValueError:
                    continue
        return sorted(out)

    # -- publish ------------------------------------------------------

    def save(self, bundle: CheckpointBundle) -> int:
        """Publish ``bundle`` as the next generation; returns its
        number. Crash-safe per the class docstring: an interruption
        anywhere in here leaves the staging dir invisible to
        ``load_latest`` and the store at its previous state."""
        from ..device import tracebuf as tb

        gens = self.generations()
        gen = (gens[-1] + 1) if gens else 1
        tmp = os.path.join(self.root, f".tmp-gen-{gen}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        bundle.save(tmp, fsync=self.fsync, fault_plan=self.fault_plan)
        if self.fault_plan is not None:
            # The preempt-mid-save chaos site: fires BEFORE the rename,
            # so an injected kill proves a staged-but-unpublished save
            # is invisible.
            self.fault_plan.on_store_publish()
        os.rename(tmp, self.path_of(gen))
        if self.fsync:
            _fsync_dir(self.root)
        # Pointer LAST, and only ever to a published generation: a
        # torn pointer is harmless because load_latest treats it as a
        # hint, never an authority.
        cur_tmp = os.path.join(self.root, ".tmp-CURRENT")
        with open(cur_tmp, "w") as f:
            f.write(f"{gen}\n")
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        os.rename(cur_tmp, os.path.join(self.root, "CURRENT"))
        if self.fsync:
            _fsync_dir(self.root)
        for old in self.generations()[:-self.keep]:
            shutil.rmtree(self.path_of(old), ignore_errors=True)
        self._trace(tb.CK_SAVE, gen)
        self._count("checkpoint.save", generation=gen,
                    kept=len(self.generations()))
        return gen

    # -- restore ------------------------------------------------------

    def _quarantine(self, gen: int, err: CheckpointError) -> BundleFault:
        from ..device import tracebuf as tb

        qdir = os.path.join(self.root, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        src = self.path_of(gen)
        dst = os.path.join(qdir, f"gen-{gen:06d}")
        if os.path.exists(dst):
            shutil.rmtree(dst, ignore_errors=True)
        try:
            shutil.move(src, dst)
        except OSError:
            dst = src  # refuse-to-move is not refuse-to-heal
        fault = BundleFault(
            generation=gen, path=dst,
            reason=_classify_fault(str(err)), error=str(err),
        )
        self.faults.append(fault)
        self._trace(tb.CK_QUARANTINE, gen)
        self._count("checkpoint.quarantined", generation=gen)
        return fault

    def load_latest(self) -> CheckpointBundle:
        """Newest generation that VALIDATES (sha256, magic, version,
        parseable members) - quarantining the ones that don't. Raises
        ``CheckpointError`` naming every fault when no generation
        survives; the caller owns poisoning outstanding futures through
        the degradation ladder (``FutureTable.poison``) at that point."""
        from ..device import tracebuf as tb

        if self.fault_plan is not None:
            # Preempt-mid-restore chaos site: a retried load_latest
            # must be idempotent (quarantine moves are re-entrant).
            self.fault_plan.on_store_restore()
        gens = self.generations()
        walked: List[BundleFault] = []
        newest = gens[-1] if gens else 0
        for gen in reversed(gens):
            try:
                b = CheckpointBundle.load(self.path_of(gen),
                                          generation=gen)
            except CheckpointError as e:
                walked.append(self._quarantine(gen, e))
                continue
            if gen != newest:
                self._trace(tb.CK_FALLBACK, gen)
                self._count("checkpoint.fallback", generation=gen,
                            newest=newest, quarantined=len(walked))
            self._trace(tb.CK_LOAD, gen)
            self._count("checkpoint.load", generation=gen)
            return b
        self._trace(tb.CK_POISON, newest)
        self._count("checkpoint.poison", generations=len(gens))
        detail = "; ".join(
            f"gen {f.generation}: {f.reason} ({f.error})" for f in walked
        ) or "the store holds no generations"
        raise CheckpointError(
            f"BundleStore at {self.root!r} is unrecoverable - no "
            f"generation validates ({detail}); poison outstanding "
            "futures through the degradation ladder and cold-start"
        )

    def trace_info(self) -> Dict[str, Any]:
        """trace_info-shaped dict of the store's host-emitted TR_CKPT
        records, mergeable by ``tools/timeline.py`` (the autoscaler's
        ``host_trace_info`` contract)."""
        from ..device.tracebuf import host_trace_info

        return host_trace_info(
            self.events or np.zeros((0, 4), np.int64),
            self._t0_ns, time.monotonic_ns(),
        )


def default_store(**kw) -> Optional[BundleStore]:
    """The env-configured store (``HCLIB_TPU_CKPT_DIR``), or None when
    the knob is unset - so callers can write ``store = default_store()``
    and gate their preempt hooks on it."""
    from . import env as _env

    root = _env.env_str("HCLIB_TPU_CKPT_DIR")
    if not root:
        return None
    return BundleStore(root, **kw)


# --------------------------------------------------------------- snapshot

def _require_quiesced(info: Dict[str, Any], what: str) -> Dict[str, Any]:
    if not info.get("quiesced") or "state" not in info:
        raise CheckpointError(
            f"{what}: the run info carries no quiesced state - pass "
            "quiesce= (or call .quiesce()) so the kernel exports its "
            "scheduler state at a round boundary"
        )
    return info["state"]


def snapshot_megakernel(mk, info: Dict[str, Any],
                        meta: Optional[Dict[str, Any]] = None
                        ) -> CheckpointBundle:
    """Bundle a quiesced ``Megakernel.run/resume`` info dict."""
    state = _require_quiesced(info, "snapshot_megakernel")
    m = _kernel_meta(mk)
    m.update(info.get("quiesce") or {})
    m.update(meta or {})
    return CheckpointBundle(
        "megakernel", m, CheckpointBundle._flatten_state(state, m)
    )


def snapshot_stream(sm, info: Dict[str, Any],
                    meta: Optional[Dict[str, Any]] = None
                    ) -> CheckpointBundle:
    """Bundle a quiesced ``StreamingMegakernel.run_stream`` return."""
    state = _require_quiesced(info, "snapshot_stream")
    m = _kernel_meta(sm.mk)
    m["ring_capacity"] = int(sm.ring_capacity)
    m["quiesce_latency_s"] = info.get("quiesce_latency_s")
    m["quiesce_round"] = info.get("quiesce_observed_round")
    m.update(meta or {})
    # After the user meta: the roster is what restore_stream's
    # mismatch guard validates - a descriptive meta={'tenants': ...}
    # must not clobber (or counterfeit) it.
    if getattr(sm, "tenants", None) is not None:
        m["tenants"] = list(sm.tenants.ids)
    else:
        m.pop("tenants", None)
    return CheckpointBundle(
        "stream", m, CheckpointBundle._flatten_state(state, m)
    )


def snapshot_resident(rk, info: Dict[str, Any],
                      meta: Optional[Dict[str, Any]] = None
                      ) -> CheckpointBundle:
    """Bundle a quiesced ``ResidentKernel.run`` info dict."""
    state = _require_quiesced(info, "snapshot_resident")
    m = _kernel_meta(rk.mk)
    m["kind_classes"] = _kind_classes(rk.mk)
    m["ndev"] = int(rk.ndev)
    m["dims"] = [int(d) for d in rk.dims]
    m["quiesce_round"] = max(
        f["quiesce_round"] for f in info["fault_stats"]
    )
    m.update(meta or {})
    # After the user meta (as snapshot_stream): the roster is what
    # restore_resident's mismatch guard validates; the channel-name
    # table is what reshard's wait re-homing diagnostics (and the
    # meta['host_residue'] refusal) key on, so a descriptive meta=
    # must not counterfeit it either.
    if getattr(rk, "chan_id", None):
        m["channels"] = [
            name for name, _cid in
            sorted(rk.chan_id.items(), key=lambda kv: kv[1])
        ]
    if getattr(rk, "tenant_specs", None):
        m["tenants"] = [s.id for s in rk.tenant_specs]
    else:
        m.pop("tenants", None)
    return CheckpointBundle(
        "resident", m, CheckpointBundle._flatten_state(state, m)
    )


# ---------------------------------------------------------------- restore

def _as_bundle(bundle_or_path) -> CheckpointBundle:
    if isinstance(bundle_or_path, CheckpointBundle):
        return bundle_or_path
    return CheckpointBundle.load(bundle_or_path)


def restore_megakernel(bundle_or_path, mk, fuel: int = 1 << 22,
                       quiesce=None):
    """Validate + relaunch a megakernel bundle mid-graph on ``mk`` (built
    exactly as checkpointed, ``checkpoint=True`` not required unless you
    pass ``quiesce=`` to re-checkpoint). Returns (ivalues, data, info) of
    the continued run."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "megakernel":
        raise CheckpointError(
            f"restore_megakernel got a {b.kind!r} bundle"
        )
    _check_kernel_meta(mk, b.meta, where=_where(b))
    return mk.resume(b.state(), fuel=fuel, quiesce=quiesce)


def restore_stream(bundle_or_path, sm, **run_stream_kw):
    """Validate + resume a stream bundle on ``sm`` (a StreamingMegakernel
    whose Megakernel matches the manifest). The residue rows re-publish
    on the fresh ring; the stream starts OPEN - inject()/close() as
    usual, or close() first for drain-and-exit semantics."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "stream":
        raise CheckpointError(f"restore_stream got a {b.kind!r} bundle")
    _check_kernel_meta(sm.mk, b.meta, where=_where(b))
    # Tenant roster must match EXACTLY (ids AND order): residue rows and
    # the tctl/tstats counter blocks are keyed by lane index, so a
    # same-count reordered roster would silently credit one tenant's
    # work and quotas to another.
    want = b.meta.get("tenants")
    have = None if getattr(sm, "tenants", None) is None else (
        sm.tenants.ids
    )
    if (want or None) != (have or None):
        raise CheckpointError(
            f"tenant roster mismatch: bundle carries {want!r}, the "
            f"target stream has {have!r} (ids and order must match - "
            "lane state is keyed by index)"
        )
    return sm.run_stream(resume_state=b.state(), **run_stream_kw)


def restore_resident(bundle_or_path, rk, quantum: int = 64,
                     max_rounds: int = 1 << 14, quiesce=None,
                     tenant_table=None):
    """Validate + relaunch a resident-mesh bundle on ``rk``. A mesh-size
    mismatch re-homes the queues automatically (``reshard`` - totals
    conserved; see its docstring for the eligibility rules). A
    tenant-enabled bundle needs a fresh ``tenant_table`` matching the
    roster - residue re-deals into its lanes. Returns (ivalues, data,
    info) of the continued run."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "resident":
        raise CheckpointError(f"restore_resident got a {b.kind!r} bundle")
    _check_kernel_meta(rk.mk, b.meta, where=_where(b))
    # Tenant roster must match EXACTLY (ids AND order) - lane state is
    # keyed by index, as on the stream restore path.
    want = b.meta.get("tenants")
    have = (
        [s.id for s in rk.tenant_specs]
        if getattr(rk, "tenant_specs", None) else None
    )
    if (want or None) != (have or None):
        raise CheckpointError(
            f"tenant roster mismatch: bundle carries {want!r}, the "
            f"target mesh has {have!r} (ids and order must match - "
            "lane state is keyed by index)"
        )
    if int(b.meta.get("ndev", rk.ndev)) != rk.ndev:
        b = b.reshard(rk.ndev)
    kw = {} if tenant_table is None else {"tenant_table": tenant_table}
    return rk.run(
        resume_state=b.state(), quantum=quantum, max_rounds=max_rounds,
        quiesce=quiesce, **kw,
    )


# ------------------------------------------------------------- preemption

@contextlib.contextmanager
def checkpoint_on_preempt(stream, after_executed: int = 0):
    """Bind a running stream's checkpoint trigger to process preemption:
    SIGTERM (after ``resilience.install_preempt_handler()``), the
    ``HCLIB_TPU_PREEMPT`` env, or the watchdog's checkpoint rung
    (``HCLIB_TPU_WATCHDOG_CHECKPOINT=1``) quiesce the stream - the
    driving run_stream returns with ``info['quiesced']=True`` and the
    caller saves the bundle (checkpoint, then stop). Register-then-replay:
    a preemption that fired BEFORE this binding still checkpoints.

    ::

        with checkpoint_on_preempt(sm):
            iv, info = sm.run_stream(b, ...)
        if info.get("quiesced"):
            snapshot_stream(sm, info).save(path)
    """

    def hook() -> None:
        stream.quiesce(after_executed)

    resilience.register_preempt_hook(hook)
    try:
        yield
    finally:
        resilience.unregister_preempt_hook(hook)
