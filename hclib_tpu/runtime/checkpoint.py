"""Checkpoint/restore: preemption-tolerant snapshot and elastic resume of
the persistent megakernel.

A resident kernel that runs for minutes is exactly what TPU preemption
kills: a SIGTERM or maintenance event used to lose the whole task graph,
and the only mitigations were abort-and-rerun (the abort words, README
"Device faults") and post-mortem traces (the flight recorder). This module
is the missing robustness layer:

- **Quiesce** (device side, compiled in only with
  ``Megakernel(checkpoint=True)`` - the DeviceFaultPlan discipline): a
  host-writable quiesce word rides next to the abort word and is polled
  inside every round loop (megakernel sched, streaming-inject ctl[5],
  resident-mesh ctl word [1] folded into the termination collective). On
  observing it, workers stop popping at the next round boundary - per-kind
  batch lanes spill to the ready ring, in-flight prefetches drain, the
  resident mesh keeps its exchange rounds until the wire is empty (sent ==
  recv, outboxes drained) - and the kernel returns with its LIVE scheduler
  state through the aliased outputs: task table, ready ring, counters,
  value heap, tier counters, fault/trace cursors.

- **Bundle** (this module): ``CheckpointBundle`` serializes that exported
  state plus the host-held descriptor metadata into a versioned on-disk
  artifact - a directory holding ``state.npz`` (the arrays) and
  ``manifest.json`` (magic, version, kind, kernel-table names, capacities,
  mesh dims, sha256 of the npz) - integrity-checked on load.

- **Restore**: ``restore_megakernel`` / ``restore_stream`` /
  ``restore_resident`` validate the manifest against a freshly-built
  (same-code) runner and relaunch MID-GRAPH: the re-entry stages all value
  slots and rebuilds row free stacks from completion tombstones (the
  sharded steal loop's re-entrant discipline), so for a deterministic
  workload *checkpoint at round k + restore + run to completion* is
  bit-identical to the uninterrupted run (asserted in
  tests/test_checkpoint.py under interpret mode).

- **Elastic resume** (``CheckpointBundle.reshard``): a resident-mesh
  bundle taken on N chips restores onto M != N chips by re-homing the
  per-chip queues host-side - the same task-conservation semantics as the
  PR 2 dead-chip re-homing path (link-free migratable rows move whole;
  totals conserved), applied at rest instead of over ICI. Rows that cannot
  re-home (successor links, homed-migration proxies, dynamic out slots)
  are refused with a diagnostic naming the offending row.

- **Preemption wiring** (``checkpoint_on_preempt``): SIGTERM (via
  ``resilience.install_preempt_handler``), the ``HCLIB_TPU_PREEMPT`` env,
  or the watchdog's optional checkpoint rung
  (``HCLIB_TPU_WATCHDOG_CHECKPOINT``) fire registered preemption hooks;
  binding a stream quiesces it so the driving ``run_stream`` returns a
  restorable snapshot instead of losing the graph - checkpoint, then stop.

Caveats (stated, not hidden): host-side tasks and help-first host
execution are NOT captured - the bundle holds device scheduler state only,
so checkpoint the device layer and re-enter the host program idempotently
(the same caveat class as ``help_finish``'s documented timeout limit).
Resident quiesce with pending host-declared waits is refused (the wait
table is kernel scratch), as is resharding a bundle whose live rows carry
successor links or per-device data buffers.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import resilience

__all__ = [
    "BUNDLE_VERSION",
    "CheckpointBundle",
    "CheckpointError",
    "snapshot_megakernel",
    "snapshot_stream",
    "snapshot_resident",
    "restore_megakernel",
    "restore_stream",
    "restore_resident",
    "checkpoint_on_preempt",
]

MAGIC = "hclib-tpu-checkpoint"
BUNDLE_VERSION = 1

# state dict keys serialized for every kind (data buffers ride as
# ``data/<name>`` entries; the stream kind adds ``ring_rows`` - plus the
# per-tenant ``tctl``/``tstats`` counter blocks when the front door runs
# tenant lanes, and the per-row submit-token table ``etok`` when the
# completion-mailbox egress runs (device/egress.py; tokens of
# installed-but-unretired rows survive the cut so their futures resolve
# after resume) - the resident kind adds its exported wait table and -
# when injecting - the per-device ring residue + cursor words).
_STATE_KEYS = ("tasks", "succ", "ready", "counts", "ivalues")
_OPT_KEYS = ("ring_rows", "waits", "ictl", "tctl", "tstats", "etok")

# Descriptor-word indices, bound once (descriptor ABI, device/descriptor).
from ..device.descriptor import (  # noqa: E402
    DESC_WORDS,
    F_CSR_N,
    F_DEP,
    F_FN,
    F_HOME,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
)


class CheckpointError(RuntimeError):
    """A bundle failed validation: corrupt artifact, version mismatch, or
    a restore target whose configuration contradicts the manifest."""


def _kernel_meta(mk) -> Dict[str, Any]:
    return {
        "kernel_names": list(mk.kernel_names),
        "capacity": int(mk.capacity),
        "num_values": int(mk.num_values),
        "succ_capacity": int(mk.succ_capacity),
        "data_specs": {
            k: {"shape": list(s.shape), "dtype": str(np.dtype(s.dtype))}
            for k, s in mk.data_specs.items()
        },
    }


def _kind_classes(mk) -> Dict[str, str]:
    """Build-time migratability classification for the bundle manifest
    (hclib_tpu.analysis; memoized on the megakernel) - ``reshard``
    reads it back for upfront whole-program diagnostics. Best-effort:
    a kernel table the shim cannot interpret classes 'unknown'."""
    try:
        from ..analysis import classify_megakernel

        return dict(classify_megakernel(mk))
    except Exception:  # noqa: BLE001 - manifest enrichment only
        return {}


def _check_kernel_meta(mk, meta: Dict[str, Any]) -> None:
    """The restore target must be the SAME program shape the bundle was
    taken from: descriptor F_FN words index the kernel table by position,
    so a renamed/reordered table would silently run the wrong kernels."""
    mine = _kernel_meta(mk)
    for key in ("kernel_names", "capacity", "num_values", "succ_capacity"):
        if mine[key] != meta.get(key):
            raise CheckpointError(
                f"restore target mismatch: {key} is {mine[key]!r} here but "
                f"{meta.get(key)!r} in the bundle - rebuild the megakernel "
                "exactly as checkpointed (names, order, capacities)"
            )
    if set(mine["data_specs"]) != set(meta.get("data_specs", {})):
        raise CheckpointError(
            f"restore target mismatch: data buffers "
            f"{sorted(mine['data_specs'])} != bundle "
            f"{sorted(meta.get('data_specs', {}))}"
        )


class CheckpointBundle:
    """One checkpoint: ``kind`` ("megakernel" | "stream" | "resident"),
    ``meta`` (the JSON manifest body) and ``arrays`` (flat name ->
    np.ndarray; data buffers under ``data/<name>``)."""

    def __init__(self, kind: str, meta: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]) -> None:
        self.kind = kind
        self.meta = meta
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}

    # ---- state <-> arrays ----

    @staticmethod
    def _flatten_state(state: Dict[str, Any],
                       meta: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Flatten a runner state dict into named arrays. Extension
        dtypes numpy cannot round-trip through npz (bfloat16 data
        buffers save as raw ``|V2`` void and reload unusable) are stored
        as same-width unsigned views with the true dtype recorded in
        ``meta['dtypes']`` - ``state()`` views them back bit-exactly."""
        arrays = {k: np.asarray(state[k]) for k in _STATE_KEYS}
        for k in _OPT_KEYS:
            if k in state:
                arrays[k] = np.asarray(state[k])
        for name, buf in (state.get("data") or {}).items():
            arrays[f"data/{name}"] = np.asarray(buf)
        dtypes: Dict[str, str] = {}
        for k, v in arrays.items():
            if v.dtype.kind not in "biufc":
                dtypes[k] = str(v.dtype)
                arrays[k] = v.view(f"u{v.dtype.itemsize}")
        if dtypes:
            meta["dtypes"] = dtypes
        return arrays

    def _restore_dtype(self, key: str, arr: np.ndarray) -> np.ndarray:
        name = (self.meta.get("dtypes") or {}).get(key)
        if name is None:
            return arr.copy()
        import ml_dtypes  # noqa: F401  (registers bfloat16 & friends)

        return arr.view(np.dtype(name)).copy()

    def state(self) -> Dict[str, Any]:
        """The resumable state dict (what ``Megakernel.resume`` /
        ``run_stream(resume_state=)`` / ``run(resume_state=)`` take)."""
        st: Dict[str, Any] = {
            k: self._restore_dtype(k, self.arrays[k]) for k in _STATE_KEYS
        }
        for k in _OPT_KEYS:
            if k in self.arrays:
                st[k] = self.arrays[k].copy()
        st["data"] = {
            k.split("/", 1)[1]: self._restore_dtype(k, v)
            for k, v in self.arrays.items()
            if k.startswith("data/")
        }
        return st

    # ---- persistence ----

    def save(self, path: str, metrics=None) -> Dict[str, Any]:
        """Write the bundle as a directory: ``state.npz`` +
        ``manifest.json`` (magic, version, kind, meta, npz sha256).
        Returns {bundle_bytes, save_s, sha256}; with ``metrics`` (a
        MetricsRegistry) the stats are recorded under "checkpoint"."""
        t0 = time.monotonic()
        os.makedirs(path, exist_ok=True)
        buf = io.BytesIO()
        np.savez(buf, **self.arrays)
        blob = buf.getvalue()
        sha = hashlib.sha256(blob).hexdigest()
        npz_path = os.path.join(path, "state.npz")
        with open(npz_path, "wb") as f:
            f.write(blob)
        manifest = {
            "magic": MAGIC,
            "version": BUNDLE_VERSION,
            "kind": self.kind,
            "created_unix": time.time(),
            "sha256": sha,
            "meta": self.meta,
        }
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        stats = {
            "bundle_bytes": len(blob),
            "save_s": round(time.monotonic() - t0, 6),
            "sha256": sha,
        }
        if metrics is not None:
            rec = {"bundle_bytes": stats["bundle_bytes"],
                   "save_s": stats["save_s"]}
            for k in ("quiesce_latency_s", "quiesce_round", "executed_at"):
                if k in self.meta and self.meta[k] is not None:
                    rec[k] = self.meta[k]
            metrics.record("checkpoint", rec)
        return stats

    @classmethod
    def load(cls, path: str) -> "CheckpointBundle":
        """Load + integrity-check a saved bundle. Raises CheckpointError
        on a missing/foreign manifest, a version from the future, or an
        npz whose sha256 disagrees with the manifest (bit rot, truncated
        copy, tampering)."""
        man_path = os.path.join(path, "manifest.json")
        npz_path = os.path.join(path, "state.npz")
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"unreadable checkpoint manifest {man_path}: {e}"
            )
        if manifest.get("magic") != MAGIC:
            raise CheckpointError(
                f"{man_path} is not a {MAGIC} bundle "
                f"(magic={manifest.get('magic')!r})"
            )
        try:
            version = int(manifest.get("version", -1))
        except (TypeError, ValueError):
            version = -1  # a mangled field is a corrupt manifest
        if version != BUNDLE_VERSION:
            raise CheckpointError(
                f"bundle version {manifest.get('version')!r} != supported "
                f"{BUNDLE_VERSION}: re-checkpoint with this build or "
                "restore with the build that wrote it"
            )
        try:
            with open(npz_path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"unreadable checkpoint state: {e}")
        sha = hashlib.sha256(blob).hexdigest()
        if sha != manifest.get("sha256"):
            raise CheckpointError(
                f"checkpoint state corrupt: sha256 {sha[:12]}... != "
                f"manifest {str(manifest.get('sha256'))[:12]}... "
                f"({npz_path})"
            )
        with np.load(io.BytesIO(blob)) as z:
            arrays = {k: z[k] for k in z.files}
        return cls(manifest["kind"], manifest.get("meta", {}), arrays)

    # ---- elastic resume (resident mesh only) ----

    def reshard(self, ndev_new: int) -> "CheckpointBundle":
        """Re-home a resident-mesh bundle's per-chip queues onto
        ``ndev_new`` devices (N -> M re-sharding) - checkpoint-time
        elasticity with the PR 2 dead-chip re-homing semantics: only
        link-free migratable rows move (whole, conserving the pending
        total), dealt round-robin; per-device accumulator value slots
        fold by SUM into the new devices' symmetric host regions (the
        ``ShardedMegakernel.migratable_fns`` contract: migratable kernels
        write accumulate-style slots the host combines) and executed
        counters fold the same way, so executed + pending totals are
        conserved exactly. Refused with a diagnostic when any live row
        carries successor links / a home-link / a dynamic out slot, or
        when the kernel has per-device data buffers (no generic fold
        exists for those)."""
        from ..device.megakernel import (
            C_ALLOC, C_EXECUTED, C_PENDING, C_VALLOC,
        )

        if self.kind != "resident":
            raise CheckpointError(
                f"reshard applies to resident-mesh bundles, not {self.kind}"
            )
        try:
            ndev_new = int(ndev_new)
        except (TypeError, ValueError):
            raise CheckpointError(
                f"reshard wants an integer device count, got {ndev_new!r}"
            )
        tasks = self.arrays["tasks"]
        counts = self.arrays["counts"]
        ivalues = self.arrays["ivalues"]
        ndev, cap, _ = tasks.shape
        if ndev_new < 1 or (ndev_new & (ndev_new - 1)):
            raise CheckpointError(
                f"reshard wants a power-of-two device count >= 1, got "
                f"{ndev_new} (the resident mesh's hypercube hop schedule "
                "is pof2-only; an evacuation drops to the next pof2 "
                "below the survivor count)"
            )
        if any(k.startswith("data/") for k in self.arrays):
            raise CheckpointError(
                "reshard cannot re-home per-device data buffers: restore "
                "onto the original mesh size, or drain and re-partition "
                "at the application level"
            )
        waits = self.arrays.get("waits")
        if waits is not None and int(np.asarray(waits)[:, 0, 0].sum()) > 0:
            # A pending wait pins its parked row to the device whose
            # channel counters it watches (needs are rebased per-device
            # arrival counts); its row also carries a dep bump, so the
            # row scan below would refuse it anyway - but name the real
            # reason first.
            raise CheckpointError(
                "reshard: the bundle carries pending host-declared waits "
                "(per-device channel arrival counts do not re-home); "
                "resume on the original mesh size and let the waits fire "
                "before resizing"
            )
        V = ivalues.shape[1]
        va = int(counts[:, C_VALLOC].max())
        # Whole-program eligibility scan (ISSUE 12): instead of refusing
        # at the FIRST offending row, collect every violation, fold it
        # per kernel kind, and - when the bundle carries the build-time
        # ``kind_classes`` classification (Megakernel.describe() /
        # hclib_tpu.analysis) - lead the diagnostic with the per-kind
        # story, so one error names everything that must drain before a
        # resize instead of a row-by-row whack-a-mole.
        kind_names = list(self.meta.get("kernel_names") or [])
        kind_classes = dict(self.meta.get("kind_classes") or {})
        violations: List[Tuple[int, int, int, str]] = []
        live_rows: List[np.ndarray] = []
        for d in range(ndev):
            alloc = int(counts[d][C_ALLOC])
            for i in range(alloc):
                row = tasks[d, i]
                if int(row[F_DEP]) == -1:
                    continue  # tombstone (completed/exported)
                bad = None
                if int(row[F_DEP]) != 0:
                    bad = "a nonzero dependency counter"
                elif (
                    int(row[F_SUCC0]) != NO_TASK
                    or int(row[F_SUCC1]) != NO_TASK
                    or int(row[F_CSR_N]) != 0
                ):
                    bad = "successor links"
                elif int(row[F_HOME]) >= 0:
                    bad = "a migration home-link"
                elif int(row[F_OUT]) >= va:
                    bad = f"a dynamic out slot ({int(row[F_OUT])} >= {va})"
                if bad is not None:
                    violations.append((d, i, int(row[F_FN]), bad))
                    continue
                live_rows.append(row.copy())
        if violations:
            by_kind: Dict[int, int] = {}
            for _d, _i, fn, _bad in violations:
                by_kind[fn] = by_kind.get(fn, 0) + 1
            kinds = []
            for fn, n in sorted(by_kind.items()):
                name = (
                    kind_names[fn]
                    if 0 <= fn < len(kind_names) else f"id {fn}"
                )
                cls = kind_classes.get(str(name))
                kinds.append(
                    f"{name!r}"
                    + (f" [{cls}]" if cls else "")
                    + f": {n} row(s)"
                )
            d0, i0, _fn0, bad0 = violations[0]
            raise CheckpointError(
                f"reshard: {len(violations)} live row(s) across "
                f"{ndev} device(s) are not link-free "
                f"({'; '.join(kinds)}); e.g. device {d0} row {i0} "
                f"carries {bad0}; only ready link-free rows re-home "
                "across mesh sizes (quiesce drains dependent subgraphs "
                "first, or restore onto the original mesh size)"
            )
        pend_total = int(counts[:, C_PENDING].sum())
        if pend_total != len(live_rows):
            raise CheckpointError(
                f"reshard conservation check failed: {pend_total} pending "
                f"!= {len(live_rows)} live rows - the bundle is not a "
                "clean quiesce snapshot"
            )
        parts: List[List[np.ndarray]] = [[] for _ in range(ndev_new)]
        for i, row in enumerate(live_rows):
            parts[i % ndev_new].append(row)
        for j, p in enumerate(parts):
            if len(p) > cap:
                # The M=1 (and any aggressive scale-in) failure mode:
                # the folded backlog must still fit each survivor's
                # task table. Diagnose with the numbers that fix it.
                raise CheckpointError(
                    f"reshard {ndev} -> {ndev_new}: device {j} would "
                    f"hold {len(p)} rows > capacity {cap} "
                    f"({len(live_rows)} live rows total); scale in less "
                    f"aggressively (>= {-(-len(live_rows) // cap)} "
                    "devices) or rebuild with a larger capacity"
                )
        tasks_new = np.zeros((ndev_new, cap, DESC_WORDS), np.int32)
        ready_new = np.full((ndev_new, cap), NO_TASK, np.int32)
        counts_new = np.zeros((ndev_new, 8), np.int32)
        ivalues_new = np.zeros((ndev_new, V), np.int32)
        for j, p in enumerate(parts):
            for i, row in enumerate(p):
                tasks_new[j, i] = row
                ready_new[j, i] = i
            n = len(p)
            counts_new[j][0] = 0  # head
            counts_new[j][1] = n  # tail
            counts_new[j][C_ALLOC] = n
            counts_new[j][C_PENDING] = n
            counts_new[j][C_VALLOC] = va
        # Fold the old devices' accumulator host regions and executed
        # counters mod M: column-wise sums (what the host combines at the
        # end) are conserved exactly.
        for d in range(ndev):
            j = d % ndev_new
            ivalues_new[j][:va] += ivalues[d][:va]
            counts_new[j][C_EXECUTED] += int(counts[d][C_EXECUTED])
        scap = self.arrays["succ"].shape[1]
        succ_new = np.full((ndev_new, scap), NO_TASK, np.int32)
        arrays = {
            "tasks": tasks_new, "succ": succ_new, "ready": ready_new,
            "counts": counts_new, "ivalues": ivalues_new,
        }
        if waits is not None:
            # Verified empty above: a fresh all-zero table for M devices.
            arrays["waits"] = np.zeros(
                (ndev_new,) + np.asarray(waits).shape[1:], np.int32
            )
        if "ring_rows" in self.arrays:
            # Inject-ring residue re-homes like the task rows: injected
            # descriptors are link-free by construction (inject refuses
            # dep_count != 0), so the rows are location-free and deal
            # round-robin; the consumed cursor was already folded into
            # the packed-from-zero representation at export.
            rr = np.asarray(self.arrays["ring_rows"])
            ic = np.asarray(self.arrays["ictl"])
            R = rr.shape[1]
            residue = [
                rr[d, i]
                for d in range(rr.shape[0])
                for i in range(int(ic[d, 0]))
            ]
            rr_new = np.zeros((ndev_new, R) + rr.shape[2:], np.int32)
            ic_new = np.zeros((ndev_new, 8), np.int32)
            ic_new[:, 1] = ic[:, 1].max() if ic.size else 0  # close flag
            for i, row in enumerate(residue):
                j = i % ndev_new
                slot = ic_new[j, 0]
                if slot >= R:
                    raise CheckpointError(
                        f"reshard {ndev} -> {ndev_new}: device {j} would "
                        f"hold > {R} inject-ring residue rows "
                        f"({len(residue)} total); scale in less "
                        "aggressively or raise ring_capacity"
                    )
                rr_new[j, slot] = row
                ic_new[j, 0] = slot + 1
            if int(ic_new[:, 0].sum()) != len(residue):
                raise CheckpointError(
                    "reshard ring conservation check failed"
                )
            arrays["ring_rows"] = rr_new
            arrays["ictl"] = ic_new
        # Mesh-tenancy counter blocks (aggregate (T, 8) tctl/tstats,
        # MeshTenantTable.export_state): device-count-free by
        # construction, so a reshard passes them through untouched -
        # per-tenant accepted/completed/expired totals are conserved
        # across N -> M exactly like the tagged residue rows above.
        for k in ("tctl", "tstats"):
            if k in self.arrays:
                arrays[k] = np.asarray(self.arrays[k]).copy()
        meta = dict(self.meta)
        meta["ndev"] = ndev_new
        meta["resharded_from"] = int(ndev)
        return CheckpointBundle("resident", meta, arrays)

    def diff(self, other: "CheckpointBundle") -> Dict[str, Any]:
        """Structural comparison of two bundles, for the bit-identity
        assertions the storm tests make: returns ``{'equal': bool,
        'kind': ..., 'only_self': [...], 'only_other': [...],
        'mismatched': {key: {n, max_abs}}}``. Arrays compare bit-exactly
        (shape + values); dtype views are compared raw (two bundles of
        the same build store identically)."""
        only_self = sorted(set(self.arrays) - set(other.arrays))
        only_other = sorted(set(other.arrays) - set(self.arrays))
        mismatched: Dict[str, Any] = {}
        for k in sorted(set(self.arrays) & set(other.arrays)):
            a, b = self.arrays[k], other.arrays[k]
            if a.shape != b.shape or a.dtype != b.dtype:
                mismatched[k] = {
                    "shape": [list(a.shape), list(b.shape)],
                    "dtype": [str(a.dtype), str(b.dtype)],
                }
                continue
            if not np.array_equal(a, b):
                av = a.astype(np.int64) if a.dtype.kind in "biu" else a
                bv = b.astype(np.int64) if b.dtype.kind in "biu" else b
                d = np.abs(av - bv)
                mismatched[k] = {
                    "n": int((av != bv).sum()),
                    "max_abs": float(d.max()),
                }
        return {
            "equal": not (only_self or only_other or mismatched)
            and self.kind == other.kind,
            "kind": [self.kind, other.kind],
            "only_self": only_self,
            "only_other": only_other,
            "mismatched": mismatched,
        }


# --------------------------------------------------------------- snapshot

def _require_quiesced(info: Dict[str, Any], what: str) -> Dict[str, Any]:
    if not info.get("quiesced") or "state" not in info:
        raise CheckpointError(
            f"{what}: the run info carries no quiesced state - pass "
            "quiesce= (or call .quiesce()) so the kernel exports its "
            "scheduler state at a round boundary"
        )
    return info["state"]


def snapshot_megakernel(mk, info: Dict[str, Any],
                        meta: Optional[Dict[str, Any]] = None
                        ) -> CheckpointBundle:
    """Bundle a quiesced ``Megakernel.run/resume`` info dict."""
    state = _require_quiesced(info, "snapshot_megakernel")
    m = _kernel_meta(mk)
    m.update(info.get("quiesce") or {})
    m.update(meta or {})
    return CheckpointBundle(
        "megakernel", m, CheckpointBundle._flatten_state(state, m)
    )


def snapshot_stream(sm, info: Dict[str, Any],
                    meta: Optional[Dict[str, Any]] = None
                    ) -> CheckpointBundle:
    """Bundle a quiesced ``StreamingMegakernel.run_stream`` return."""
    state = _require_quiesced(info, "snapshot_stream")
    m = _kernel_meta(sm.mk)
    m["ring_capacity"] = int(sm.ring_capacity)
    m["quiesce_latency_s"] = info.get("quiesce_latency_s")
    m["quiesce_round"] = info.get("quiesce_observed_round")
    m.update(meta or {})
    # After the user meta: the roster is what restore_stream's
    # mismatch guard validates - a descriptive meta={'tenants': ...}
    # must not clobber (or counterfeit) it.
    if getattr(sm, "tenants", None) is not None:
        m["tenants"] = list(sm.tenants.ids)
    else:
        m.pop("tenants", None)
    return CheckpointBundle(
        "stream", m, CheckpointBundle._flatten_state(state, m)
    )


def snapshot_resident(rk, info: Dict[str, Any],
                      meta: Optional[Dict[str, Any]] = None
                      ) -> CheckpointBundle:
    """Bundle a quiesced ``ResidentKernel.run`` info dict."""
    state = _require_quiesced(info, "snapshot_resident")
    m = _kernel_meta(rk.mk)
    m["kind_classes"] = _kind_classes(rk.mk)
    m["ndev"] = int(rk.ndev)
    m["dims"] = [int(d) for d in rk.dims]
    m["quiesce_round"] = max(
        f["quiesce_round"] for f in info["fault_stats"]
    )
    m.update(meta or {})
    # After the user meta (as snapshot_stream): the roster is what
    # restore_resident's mismatch guard validates.
    if getattr(rk, "tenant_specs", None):
        m["tenants"] = [s.id for s in rk.tenant_specs]
    else:
        m.pop("tenants", None)
    return CheckpointBundle(
        "resident", m, CheckpointBundle._flatten_state(state, m)
    )


# ---------------------------------------------------------------- restore

def _as_bundle(bundle_or_path) -> CheckpointBundle:
    if isinstance(bundle_or_path, CheckpointBundle):
        return bundle_or_path
    return CheckpointBundle.load(bundle_or_path)


def restore_megakernel(bundle_or_path, mk, fuel: int = 1 << 22,
                       quiesce=None):
    """Validate + relaunch a megakernel bundle mid-graph on ``mk`` (built
    exactly as checkpointed, ``checkpoint=True`` not required unless you
    pass ``quiesce=`` to re-checkpoint). Returns (ivalues, data, info) of
    the continued run."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "megakernel":
        raise CheckpointError(
            f"restore_megakernel got a {b.kind!r} bundle"
        )
    _check_kernel_meta(mk, b.meta)
    return mk.resume(b.state(), fuel=fuel, quiesce=quiesce)


def restore_stream(bundle_or_path, sm, **run_stream_kw):
    """Validate + resume a stream bundle on ``sm`` (a StreamingMegakernel
    whose Megakernel matches the manifest). The residue rows re-publish
    on the fresh ring; the stream starts OPEN - inject()/close() as
    usual, or close() first for drain-and-exit semantics."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "stream":
        raise CheckpointError(f"restore_stream got a {b.kind!r} bundle")
    _check_kernel_meta(sm.mk, b.meta)
    # Tenant roster must match EXACTLY (ids AND order): residue rows and
    # the tctl/tstats counter blocks are keyed by lane index, so a
    # same-count reordered roster would silently credit one tenant's
    # work and quotas to another.
    want = b.meta.get("tenants")
    have = None if getattr(sm, "tenants", None) is None else (
        sm.tenants.ids
    )
    if (want or None) != (have or None):
        raise CheckpointError(
            f"tenant roster mismatch: bundle carries {want!r}, the "
            f"target stream has {have!r} (ids and order must match - "
            "lane state is keyed by index)"
        )
    return sm.run_stream(resume_state=b.state(), **run_stream_kw)


def restore_resident(bundle_or_path, rk, quantum: int = 64,
                     max_rounds: int = 1 << 14, quiesce=None,
                     tenant_table=None):
    """Validate + relaunch a resident-mesh bundle on ``rk``. A mesh-size
    mismatch re-homes the queues automatically (``reshard`` - totals
    conserved; see its docstring for the eligibility rules). A
    tenant-enabled bundle needs a fresh ``tenant_table`` matching the
    roster - residue re-deals into its lanes. Returns (ivalues, data,
    info) of the continued run."""
    b = _as_bundle(bundle_or_path)
    if b.kind != "resident":
        raise CheckpointError(f"restore_resident got a {b.kind!r} bundle")
    _check_kernel_meta(rk.mk, b.meta)
    # Tenant roster must match EXACTLY (ids AND order) - lane state is
    # keyed by index, as on the stream restore path.
    want = b.meta.get("tenants")
    have = (
        [s.id for s in rk.tenant_specs]
        if getattr(rk, "tenant_specs", None) else None
    )
    if (want or None) != (have or None):
        raise CheckpointError(
            f"tenant roster mismatch: bundle carries {want!r}, the "
            f"target mesh has {have!r} (ids and order must match - "
            "lane state is keyed by index)"
        )
    if int(b.meta.get("ndev", rk.ndev)) != rk.ndev:
        b = b.reshard(rk.ndev)
    kw = {} if tenant_table is None else {"tenant_table": tenant_table}
    return rk.run(
        resume_state=b.state(), quantum=quantum, max_rounds=max_rounds,
        quiesce=quiesce, **kw,
    )


# ------------------------------------------------------------- preemption

@contextlib.contextmanager
def checkpoint_on_preempt(stream, after_executed: int = 0):
    """Bind a running stream's checkpoint trigger to process preemption:
    SIGTERM (after ``resilience.install_preempt_handler()``), the
    ``HCLIB_TPU_PREEMPT`` env, or the watchdog's checkpoint rung
    (``HCLIB_TPU_WATCHDOG_CHECKPOINT=1``) quiesce the stream - the
    driving run_stream returns with ``info['quiesced']=True`` and the
    caller saves the bundle (checkpoint, then stop). Register-then-replay:
    a preemption that fired BEFORE this binding still checkpoints.

    ::

        with checkpoint_on_preempt(sm):
            iv, info = sm.run_stream(b, ...)
        if info.get("quiesced"):
            snapshot_stream(sm, info).save(path)
    """

    def hook() -> None:
        stream.quiesce(after_executed)

    resilience.register_preempt_hook(hook)
    try:
        yield
    finally:
        resilience.unregister_preempt_hook(hook)
