"""Module/plugin registry.

The reference's modules are dlopen'd shared libraries that register lifecycle
hooks and locale-type handlers via static initializers
(HCLIB_REGISTER_MODULE, inc/hclib-module.h:64; src/hclib_module.c:49-152).
Here a module is a Python object (or entry-point) registered before launch:

- ``pre_init(runtime)`` runs before workers start - register locale types.
- ``post_init(runtime)`` runs after workers start - open device/comm state
  (the reference initializes MPI / CUDA streams here).
- ``finalize(runtime)`` runs at shutdown.
- Locale-type memory handlers (alloc/free/memset/copy) are registered per
  locale *type* with a MAY_USE/MUST_USE priority, resolved by mem.py
  (reference: src/hclib-mem.c:16-50, 198-221).
- Per-worker module state: ``add_per_worker_state`` returns a slot id; the
  runtime materializes one value per worker (reference:
  src/hclib_module.c:129-152) - used e.g. for per-worker comm contexts
  (modules/sos pattern).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Module",
    "register_module",
    "unregister_all_modules",
    "MAY_USE",
    "MUST_USE",
    "register_mem_fns",
    "mem_fns_for",
]

MAY_USE = 0
MUST_USE = 1


class Module:
    """Base class; subclasses override any subset of the hooks."""

    name = "module"

    def pre_init(self, runtime) -> None:  # pragma: no cover - interface
        pass

    def post_init(self, runtime) -> None:  # pragma: no cover - interface
        pass

    def finalize(self, runtime) -> None:  # pragma: no cover - interface
        pass


_modules: List[Module] = []
# locale type -> op name -> (priority, fn)
_mem_fns: Dict[str, Dict[str, Tuple[int, Callable]]] = {}
_per_worker_factories: List[Callable[[int], Any]] = []


def register_module(mod: Module) -> Module:
    if all(m is not mod for m in _modules):
        _modules.append(mod)
    return mod


def unregister_all_modules() -> None:
    _modules.clear()
    _mem_fns.clear()
    _per_worker_factories.clear()


def registered_modules() -> List[Module]:
    return list(_modules)


def call_pre_init(runtime) -> None:
    for m in _modules:
        m.pre_init(runtime)


def call_post_init(runtime) -> None:
    runtime.per_worker_state = [
        [f(w) for f in _per_worker_factories] for w in range(runtime.nworkers)
    ]
    for m in _modules:
        m.post_init(runtime)


def call_finalize(runtime) -> None:
    for m in _modules:
        m.finalize(runtime)


def add_per_worker_state(factory: Callable[[int], Any]) -> int:
    """Returns a slot id usable with ``get_per_worker_state``."""
    _per_worker_factories.append(factory)
    return len(_per_worker_factories) - 1


def get_per_worker_state(runtime, worker_id: int, slot: int) -> Any:
    return runtime.per_worker_state[worker_id][slot]


def register_mem_fns(
    locale_type: str,
    *,
    alloc: Optional[Callable] = None,
    free: Optional[Callable] = None,
    memset: Optional[Callable] = None,
    copy: Optional[Callable] = None,
    priority: int = MAY_USE,
) -> None:
    ops = _mem_fns.setdefault(locale_type, {})
    for name, fn in (("alloc", alloc), ("free", free), ("memset", memset), ("copy", copy)):
        if fn is not None:
            ops[name] = (priority, fn)


def mem_fns_for(locale_type: str) -> Dict[str, Tuple[int, Callable]]:
    return _mem_fns.get(locale_type, {})
