"""Locality graph: locales, reachability, and per-worker pop/steal paths.

Core idea (reference: inc/hclib-locality-graph.h:9-50): every *locale* (a
hardware component - cache slice, sysmem, TPU core, host, NIC) owns one deque
per worker. A worker has a *pop path* (locales it drains its own deques from,
in order) and a *steal path* (locales where it scans all workers' deques).
"Comm worker" and "device worker" are not special mechanisms - they are
workers whose paths include the NIC/TPU locale.

The machine description is a JSON document compatible with the reference
schema (locality_graphs/*.json; parser src/hclib-locality-graph.c:372-566):
``nworkers``, ``declarations`` (locale names; the prefix before the first
``_`` or digit is the locale *type*), ``reachability`` edges, and
``pop_paths``/``steal_paths`` keyed per-worker-index or ``default``, with
``$(id / k)`` / ``$(id % k)`` arithmetic interpolation.

When no file is given, a default star graph is generated - sysmem plus one L1
per worker (reference: src/hclib-locality-graph.c:581-643). For TPU meshes,
parallel/mesh.py synthesizes a graph with one ``tpu`` locale per device plus
``hbm`` and ``host`` locales.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = ["Locale", "LocalityGraph", "generate_default_graph", "load_locality_file"]


@dataclass
class Locale:
    id: int
    name: str
    type: str
    reachable: List[int] = field(default_factory=list)
    # Mark-special labels, e.g. "COMM" for the NIC locale
    # (hclib_locale_mark_special, src/hclib-locality-graph.c:829-837).
    special: Dict[str, bool] = field(default_factory=dict)
    # Backend payload (e.g. device ordinal for tpu locales).
    metadata: Dict[str, object] = field(default_factory=dict)

    def mark_special(self, label: str) -> None:
        self.special[label] = True

    def is_special(self, label: str) -> bool:
        return self.special.get(label, False)


def _locale_type(name: str) -> str:
    """Type is the segment before the first underscore (L2_0_3 -> L2,
    L1_0 -> L1); names without one drop a trailing ordinal (GPU0 -> GPU).
    Mirrors the reference's prefix-matching of declared labels against
    registered type names (src/hclib-locality-graph.c:322-331)."""
    head = name.split("_", 1)[0]
    if "_" in name:
        return head
    stripped = head.rstrip("0123456789")
    return stripped or head


class LocalityGraph:
    def __init__(self, nworkers: int, locales: Sequence[Locale],
                 pop_paths: Sequence[Sequence[int]],
                 steal_paths: Sequence[Sequence[int]]) -> None:
        if len(pop_paths) != nworkers or len(steal_paths) != nworkers:
            raise ValueError("need one pop/steal path per worker")
        self.nworkers = nworkers
        self.locales: List[Locale] = list(locales)
        self.by_name: Dict[str, Locale] = {l.name: l for l in self.locales}
        self.pop_paths: List[List[int]] = [list(p) for p in pop_paths]
        self.steal_paths: List[List[int]] = [list(p) for p in steal_paths]

    # -- queries (reference: inc/hclib-locality-graph.h:111-121) --

    def locale(self, locale_id: int) -> Locale:
        return self.locales[locale_id]

    def locales_of_type(self, type_: str) -> List[Locale]:
        return [l for l in self.locales if l.type == type_]

    def central_locale(self) -> Locale:
        """The locale reachable on every worker's pop path (sysmem in the
        default graph); falls back to the most common path member
        (cf. thread-private/central place computation,
        src/hclib-locality-graph.c:917-1093)."""
        common = set(self.pop_paths[0])
        for p in self.pop_paths[1:]:
            common &= set(p)
        if common:
            # Deepest common = last on worker 0's path that is common.
            for lid in reversed(self.pop_paths[0]):
                if lid in common:
                    return self.locales[lid]
        return self.locales[0]

    def closest_locale(self, worker_id: int) -> Locale:
        """First locale on the worker's pop path."""
        return self.locales[self.pop_paths[worker_id][0]]

    def closest_of_type(self, worker_id: int, type_: str) -> Optional[Locale]:
        """BFS from the worker's closest locale over reachability edges
        (reference: src/hclib-locality-graph.c:1136-1164)."""
        start = self.closest_locale(worker_id)
        seen = {start.id}
        frontier = [start]
        while frontier:
            nxt: List[Locale] = []
            for loc in frontier:
                if loc.type == type_:
                    return loc
                for nid in loc.reachable:
                    if nid not in seen:
                        seen.add(nid)
                        nxt.append(self.locales[nid])
            frontier = nxt
        return None


def generate_default_graph(nworkers: int) -> LocalityGraph:
    """Star graph: one sysmem root plus one L1 per worker
    (reference fallback: src/hclib-locality-graph.c:581-643)."""
    sysmem = Locale(0, "sysmem", "sysmem")
    locales = [sysmem]
    for w in range(nworkers):
        l1 = Locale(1 + w, f"L1_{w}", "L1")
        l1.reachable.append(0)
        sysmem.reachable.append(l1.id)
        locales.append(l1)
    pop_paths = [[1 + w, 0] for w in range(nworkers)]
    # Steal path covers every worker's L1 so all work is globally stealable
    # (tasks default to the spawner's closest locale, i.e. its L1).
    steal_paths = [
        [0] + [1 + v for v in range(nworkers) if v != w] for w in range(nworkers)
    ]
    return LocalityGraph(nworkers, locales, pop_paths, steal_paths)


_INTERP = re.compile(r"\$\(\s*id\s*([/%+*-])\s*(\d+)\s*\)")


def _interpolate(name: str, worker_id: int) -> str:
    """Evaluate ``$(id OP k)`` arithmetic in path entries
    (reference: src/hclib-locality-graph.c:196-237)."""

    def repl(m: re.Match) -> str:
        op, k = m.group(1), int(m.group(2))
        if op == "/":
            return str(worker_id // k)
        if op == "%":
            return str(worker_id % k)
        if op == "+":
            return str(worker_id + k)
        if op == "-":
            return str(worker_id - k)
        return str(worker_id * k)

    return _INTERP.sub(repl, name)


def graph_from_dict(doc: dict, nworkers: Optional[int] = None) -> LocalityGraph:
    n = int(nworkers if nworkers is not None else doc.get("nworkers", 1))
    names = list(doc["declarations"])
    locales = [Locale(i, name, _locale_type(name)) for i, name in enumerate(names)]
    by_name = {l.name: l for l in locales}
    for a, b in doc.get("reachability", []):
        la, lb = by_name[a], by_name[b]
        la.reachable.append(lb.id)
        lb.reachable.append(la.id)

    def paths_for(key: str) -> List[List[int]]:
        spec = doc.get(key, {})
        out: List[List[int]] = []
        for w in range(n):
            entries = spec.get(str(w), spec.get("default", []))
            path = []
            for e in entries:
                nm = _interpolate(e, w)
                if nm not in by_name:
                    raise ValueError(f"unknown locale {nm!r} in {key}[{w}]")
                path.append(by_name[nm].id)
            if not path:
                raise ValueError(f"empty {key} for worker {w}")
            out.append(path)
        return out

    return LocalityGraph(n, locales, paths_for("pop_paths"), paths_for("steal_paths"))


def load_locality_file(path: str, nworkers: Optional[int] = None) -> LocalityGraph:
    with open(path) as f:
        return graph_from_dict(json.load(f), nworkers)
