"""Locality graph: locales, reachability, and per-worker pop/steal paths.

Core idea (reference: inc/hclib-locality-graph.h:9-50): every *locale* (a
hardware component - cache slice, sysmem, TPU core, host, NIC) owns one deque
per worker. A worker has a *pop path* (locales it drains its own deques from,
in order) and a *steal path* (locales where it scans all workers' deques).
"Comm worker" and "device worker" are not special mechanisms - they are
workers whose paths include the NIC/TPU locale.

The machine description is a JSON document compatible with the reference
schema (locality_graphs/*.json; parser src/hclib-locality-graph.c:372-566):
``nworkers``, ``declarations`` (locale names; the prefix before the first
``_`` or digit is the locale *type*), ``reachability`` edges, and
``pop_paths``/``steal_paths`` keyed per-worker-index or ``default``, with
``$(id / k)`` / ``$(id % k)`` arithmetic interpolation.

When no file is given, a default star graph is generated - sysmem plus one L1
per worker (reference: src/hclib-locality-graph.c:581-643). For TPU meshes,
parallel/mesh.py synthesizes a graph with one ``tpu`` locale per device plus
``hbm`` and ``host`` locales.
"""

from __future__ import annotations

import bisect
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

__all__ = [
    "Locale",
    "LocalityGraph",
    "generate_default_graph",
    "load_locality_file",
    "MeshPlacement",
    "resolve_placement",
    "steal_hop_order",
    "xor_hop_order",
    "device_distance_matrix",
]


@dataclass
class Locale:
    id: int
    name: str
    type: str
    reachable: List[int] = field(default_factory=list)
    # Mark-special labels, e.g. "COMM" for the NIC locale
    # (hclib_locale_mark_special, src/hclib-locality-graph.c:829-837).
    special: Dict[str, bool] = field(default_factory=dict)
    # Backend payload (e.g. device ordinal for tpu locales).
    metadata: Dict[str, object] = field(default_factory=dict)

    def mark_special(self, label: str) -> None:
        self.special[label] = True

    def is_special(self, label: str) -> bool:
        return self.special.get(label, False)


def _locale_type(name: str) -> str:
    """Type is the segment before the first underscore (L2_0_3 -> L2,
    L1_0 -> L1); names without one drop a trailing ordinal (GPU0 -> GPU).
    Mirrors the reference's prefix-matching of declared labels against
    registered type names (src/hclib-locality-graph.c:322-331)."""
    head = name.split("_", 1)[0]
    if "_" in name:
        return head
    stripped = head.rstrip("0123456789")
    return stripped or head


class LocalityGraph:
    def __init__(self, nworkers: int, locales: Sequence[Locale],
                 pop_paths: Sequence[Sequence[int]],
                 steal_paths: Sequence[Sequence[int]]) -> None:
        if len(pop_paths) != nworkers or len(steal_paths) != nworkers:
            raise ValueError("need one pop/steal path per worker")
        self.nworkers = nworkers
        self.locales: List[Locale] = list(locales)
        self.by_name: Dict[str, Locale] = {l.name: l for l in self.locales}
        self.pop_paths: List[List[int]] = [list(p) for p in pop_paths]
        self.steal_paths: List[List[int]] = [list(p) for p in steal_paths]

    # -- queries (reference: inc/hclib-locality-graph.h:111-121) --

    def locale(self, locale_id: int) -> Locale:
        return self.locales[locale_id]

    def locales_of_type(self, type_: str) -> List[Locale]:
        return [l for l in self.locales if l.type == type_]

    def central_locale(self) -> Locale:
        """The locale reachable on every worker's pop path (sysmem in the
        default graph); falls back to the most common path member
        (cf. thread-private/central place computation,
        src/hclib-locality-graph.c:917-1093)."""
        common = set(self.pop_paths[0])
        for p in self.pop_paths[1:]:
            common &= set(p)
        if common:
            # Deepest common = last on worker 0's path that is common.
            for lid in reversed(self.pop_paths[0]):
                if lid in common:
                    return self.locales[lid]
        return self.locales[0]

    def closest_locale(self, worker_id: int) -> Locale:
        """First locale on the worker's pop path."""
        return self.locales[self.pop_paths[worker_id][0]]

    def closest_of_type(self, worker_id: int, type_: str) -> Optional[Locale]:
        """BFS from the worker's closest locale over reachability edges
        (reference: src/hclib-locality-graph.c:1136-1164)."""
        start = self.closest_locale(worker_id)
        seen = {start.id}
        frontier = [start]
        while frontier:
            nxt: List[Locale] = []
            for loc in frontier:
                if loc.type == type_:
                    return loc
                for nid in loc.reachable:
                    if nid not in seen:
                        seen.add(nid)
                        nxt.append(self.locales[nid])
            frontier = nxt
        return None


def generate_default_graph(nworkers: int) -> LocalityGraph:
    """Star graph: one sysmem root plus one L1 per worker
    (reference fallback: src/hclib-locality-graph.c:581-643)."""
    sysmem = Locale(0, "sysmem", "sysmem")
    locales = [sysmem]
    for w in range(nworkers):
        l1 = Locale(1 + w, f"L1_{w}", "L1")
        l1.reachable.append(0)
        sysmem.reachable.append(l1.id)
        locales.append(l1)
    pop_paths = [[1 + w, 0] for w in range(nworkers)]
    # Steal path covers every worker's L1 so all work is globally stealable
    # (tasks default to the spawner's closest locale, i.e. its L1).
    steal_paths = [
        [0] + [1 + v for v in range(nworkers) if v != w] for w in range(nworkers)
    ]
    return LocalityGraph(nworkers, locales, pop_paths, steal_paths)


_INTERP = re.compile(r"\$\(\s*id\s*([/%+*-])\s*(\d+)\s*\)")


def _interpolate(name: str, worker_id: int) -> str:
    """Evaluate ``$(id OP k)`` arithmetic in path entries
    (reference: src/hclib-locality-graph.c:196-237)."""

    def repl(m: re.Match) -> str:
        op, k = m.group(1), int(m.group(2))
        if op == "/":
            return str(worker_id // k)
        if op == "%":
            return str(worker_id % k)
        if op == "+":
            return str(worker_id + k)
        if op == "-":
            return str(worker_id - k)
        return str(worker_id * k)

    return _INTERP.sub(repl, name)


def graph_from_dict(doc: dict, nworkers: Optional[int] = None) -> LocalityGraph:
    n = int(nworkers if nworkers is not None else doc.get("nworkers", 1))
    names = list(doc["declarations"])
    locales = [Locale(i, name, _locale_type(name)) for i, name in enumerate(names)]
    by_name = {l.name: l for l in locales}
    for a, b in doc.get("reachability", []):
        la, lb = by_name[a], by_name[b]
        la.reachable.append(lb.id)
        lb.reachable.append(la.id)

    def paths_for(key: str) -> List[List[int]]:
        spec = doc.get(key, {})
        out: List[List[int]] = []
        for w in range(n):
            entries = spec.get(str(w), spec.get("default", []))
            path = []
            for e in entries:
                nm = _interpolate(e, w)
                if nm not in by_name:
                    raise ValueError(f"unknown locale {nm!r} in {key}[{w}]")
                path.append(by_name[nm].id)
            if not path:
                raise ValueError(f"empty {key} for worker {w}")
            out.append(path)
        return out

    return LocalityGraph(n, locales, paths_for("pop_paths"), paths_for("steal_paths"))


def load_locality_file(path: str, nworkers: Optional[int] = None) -> LocalityGraph:
    with open(path) as f:
        return graph_from_dict(json.load(f), nworkers)


# ------------------------------------------------- device-tier placement
#
# The forasync device tier (device/forasync_tier.py) treats placement as
# DATA: a flat tile index maps to a device ordinal through either a
# classic dist-func callable or a JSON mesh-placement descriptor resolved
# against a machine graph in locality_graphs/ - the same files the host
# runtime loads, now consumed by the device path too. The graph's ``tpu``
# locales define the device roster AND the steal-scan ordering: a device
# prefers stealing from graph-near neighbors first (ICI hops), so a
# misplaced tile is recovered from next door before the far side of the
# mesh is scanned.


def _tpu_ordinal(loc: Locale) -> int:
    """Device ordinal of a tpu locale: explicit metadata wins, else the
    trailing integer of the name (tpu_3 -> 3, tpu3 -> 3)."""
    if "device" in loc.metadata:
        return int(loc.metadata["device"])  # type: ignore[arg-type]
    m = re.search(r"(\d+)$", loc.name)
    if not m:
        raise ValueError(f"tpu locale {loc.name!r} has no ordinal")
    return int(m.group(1))


def device_distance_matrix(graph: LocalityGraph) -> List[List[int]]:
    """All-pairs BFS hop distances over the graph's ``tpu`` locales,
    walking ONLY tpu-to-tpu reachability edges (the ICI topology; going
    through hbm/sysmem would make every device 2 hops from every other
    and erase the mesh shape). Row/column order is device ordinal.
    Unreachable pairs read as ndev (an effective +inf that still sorts)."""
    tpus = graph.locales_of_type("tpu")
    if not tpus:
        raise ValueError("graph has no tpu locales")
    by_ord = {_tpu_ordinal(l): l for l in tpus}
    if sorted(by_ord) != list(range(len(tpus))):
        raise ValueError(
            f"tpu ordinals {sorted(by_ord)} are not dense from 0"
        )
    ndev = len(tpus)
    tpu_ids = {l.id for l in tpus}
    dist = [[ndev] * ndev for _ in range(ndev)]
    for d in range(ndev):
        start = by_ord[d]
        dist[d][d] = 0
        frontier = [start]
        hops = 0
        seen = {start.id}
        while frontier:
            hops += 1
            nxt: List[Locale] = []
            for loc in frontier:
                for nid in loc.reachable:
                    if nid in tpu_ids and nid not in seen:
                        seen.add(nid)
                        nb = graph.locales[nid]
                        dist[d][_tpu_ordinal(nb)] = hops
                        nxt.append(nb)
            frontier = nxt
    return dist


def steal_hop_order(
    graph: Union[LocalityGraph, str], ndev: Optional[int] = None
) -> List[int]:
    """Hypercube hop distances for the bulk-synchronous steal exchange
    (device/sharded.py), ordered NEAR-NEIGHBORS-FIRST by the machine
    graph: for each candidate hop d the mean ICI distance between every
    device i and its partner (i + d) % ndev is computed over the tpu
    reachability edges, and hops sort ascending by that mean (ties break
    toward the smaller hop). The default scan order [1, 2, 4, ...] is
    flat-ring thinking; on a 2x2 ICI ring (v5e_4.json) every hop-2
    partner is a direct neighbor while half the hop-1 partners sit two
    hops out, so the graph reorders the scan to [2, 1] - and swapping
    the JSON swaps the scan with zero code changes."""
    if isinstance(graph, str):
        graph = load_locality_file(graph)
    dist = device_distance_matrix(graph)
    n = len(dist)
    if ndev is None:
        ndev = n
    if ndev != n:
        raise ValueError(
            f"graph describes {n} tpu devices, mesh has {ndev}"
        )
    hops = [d for d in (1 << k for k in range(16)) if d < ndev]
    mean = {
        d: sum(dist[i][(i + d) % ndev] for i in range(ndev)) / ndev
        for d in hops
    }
    return sorted(hops, key=lambda d: (mean[d], d))


def xor_hop_order(
    graph: Union[LocalityGraph, str], ndev: Optional[int] = None
) -> List[int]:
    """XOR-partner deltas for the resident mesh's paired hypercube
    exchange (device/resident.py ``fold_and_steal``), ordered
    NEAR-NEIGHBORS-FIRST by the machine graph: hop delta ``d`` pairs
    device ``i`` with ``i ^ d``, so for each power-of-two delta the mean
    ICI distance between every device and its XOR partner is computed
    over the tpu reachability edges, and deltas sort ascending by that
    mean (ties toward the smaller delta). Unlike ``steal_hop_order``
    (the additive-ring scan, where any nonempty subset terminates), the
    resident fold NEEDS every hypercube dimension each round - the
    recursive-doubling sums and the XOR all-to-all are products of
    commuting per-dimension exchanges - so the result is always a FULL
    permutation of the deltas; only the order (which partner's steal
    exchange runs while backlogs are freshest) changes."""
    if isinstance(graph, str):
        graph = load_locality_file(graph)
    dist = device_distance_matrix(graph)
    n = len(dist)
    if ndev is None:
        ndev = n
    if ndev != n:
        raise ValueError(
            f"graph describes {n} tpu devices, mesh has {ndev}"
        )
    if ndev & (ndev - 1):
        raise ValueError(
            f"xor_hop_order wants a power-of-two roster (the resident "
            f"mesh constraint), got {ndev} devices"
        )
    deltas = [1 << k for k in range(ndev.bit_length() - 1)]
    mean = {
        d: sum(dist[i][i ^ d] for i in range(ndev)) / ndev for d in deltas
    }
    return sorted(deltas, key=lambda d: (mean[d], d))


class MeshPlacement:
    """Data-driven flat-tile -> device mapping for the forasync device
    tier: the device-side rendering of the reference's loop dist-funcs
    (hclib_register_dist_func, inc/hclib-forasync.h:349-380), where the
    policy is a JSON document instead of compiled code.

    Descriptor schema (see locality_graphs/README.md)::

        {
          "graph":   "v5e_4.json",        # machine graph (optional; gives
                                          #  ndev + the steal-scan order)
          "devices": 4,                   # explicit ndev (optional when
                                          #  "graph" provides it)
          "policy":  "block",             # block | cyclic | weights | single
          "weights": [4, 2, 1, 1],        # policy=weights: proportional
                                          #  block sizes per device
          "device":  0                    # policy=single: the one target
        }

    ``device_of(flat, total)`` is a pure function of the descriptor, so a
    placement is reproducible from the file alone; ``counts(total)``
    returns the per-device initial tile counts the seeded ready rings
    will hold (the quantity the placement acceptance tests pin down).
    """

    POLICIES = ("block", "cyclic", "weights", "single")

    def __init__(
        self,
        ndev: int,
        policy: str = "block",
        weights: Optional[Sequence[float]] = None,
        device: int = 0,
        graph: Optional[LocalityGraph] = None,
    ) -> None:
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown placement policy {policy!r} "
                f"(one of {self.POLICIES})"
            )
        if ndev < 1:
            raise ValueError(f"need >= 1 device, got {ndev}")
        self.ndev = int(ndev)
        self.policy = policy
        self.graph = graph
        if policy == "weights":
            if weights is None or len(weights) != ndev:
                raise ValueError(
                    f"policy=weights wants {ndev} weights, got "
                    f"{None if weights is None else len(weights)}"
                )
            if any(w < 0 for w in weights) or sum(weights) <= 0:
                raise ValueError(f"weights must be >= 0, sum > 0: {weights}")
            self.weights = [float(w) for w in weights]
        else:
            self.weights = None
        if policy == "single" and not 0 <= device < ndev:
            raise ValueError(f"device {device} out of range [0, {ndev})")
        self.device = int(device)
        # Block boundaries depend only on ``total``: memoized so the
        # per-tile device_of scan over a large loop does not rebuild the
        # cumulative list per call (place_tiles is O(total)).
        self._bounds_cache: Dict[int, List[int]] = {}

    @classmethod
    def from_dict(
        cls, doc: Dict, base_dir: Optional[str] = None
    ) -> "MeshPlacement":
        # Unknown keys raise (the PR 8 malformed-env convention): a
        # typoed "polcy" must not silently fall back to block placement.
        unknown = set(doc) - {"graph", "devices", "policy", "weights",
                              "device"}
        if unknown:
            raise ValueError(
                f"unknown placement-descriptor keys {sorted(unknown)} "
                "(schema: graph, devices, policy, weights, device)"
            )
        graph = None
        ndev = doc.get("devices")
        gname = doc.get("graph")
        if gname:
            gpath = (
                gname
                if os.path.isabs(gname) or base_dir is None
                else os.path.join(base_dir, gname)
            )
            graph = load_locality_file(gpath)
            gdev = len(graph.locales_of_type("tpu"))
            if ndev is None:
                ndev = gdev
            elif int(ndev) != gdev:
                raise ValueError(
                    f"descriptor says devices={ndev} but graph "
                    f"{gname!r} has {gdev} tpu locales"
                )
        if ndev is None:
            raise ValueError(
                "placement descriptor needs 'devices' or a 'graph' "
                "whose tpu locales define the roster"
            )
        return cls(
            int(ndev),
            policy=doc.get("policy", "block"),
            weights=doc.get("weights"),
            device=int(doc.get("device", 0)),
            graph=graph,
        )

    @classmethod
    def from_file(cls, path: str) -> "MeshPlacement":
        """Load a JSON placement descriptor; a relative ``graph`` entry
        resolves against the descriptor's own directory, so the files in
        locality_graphs/ reference each other by name."""
        with open(path) as f:
            doc = json.load(f)
        return cls.from_dict(doc, base_dir=os.path.dirname(os.path.abspath(path)))

    # -- the mapping --

    def _bounds(self, total: int) -> List[int]:
        """Cumulative block boundaries for block/weights policies
        (memoized per ``total``)."""
        b = self._bounds_cache.get(total)
        if b is None:
            if self.policy == "weights":
                w = self.weights
            else:
                w = [1.0] * self.ndev
            acc, b, s = 0.0, [0], sum(w)
            for wi in w:
                acc += wi
                b.append(int(round(total * acc / s)))
            self._bounds_cache[total] = b
        return b

    def device_of(self, flat: int, total: int) -> int:
        """Device ordinal for flat tile ``flat`` of ``total``."""
        if not 0 <= flat < total:
            raise ValueError(f"flat {flat} out of range [0, {total})")
        if self.policy == "single":
            return self.device
        if self.policy == "cyclic":
            return flat % self.ndev
        b = self._bounds(total)
        return min(bisect.bisect_right(b, flat) - 1, self.ndev - 1)

    def counts(self, total: int) -> List[int]:
        """Initial tiles per device - what the seeded ready rings hold."""
        if self.policy == "single":
            out = [0] * self.ndev
            out[self.device] = total
            return out
        if self.policy == "cyclic":
            return [
                total // self.ndev + (1 if d < total % self.ndev else 0)
                for d in range(self.ndev)
            ]
        b = self._bounds(total)
        return [b[d + 1] - b[d] for d in range(self.ndev)]

    def dist_func(self) -> Callable[[int, int, int], int]:
        """Classic ``(ndim, flat, total) -> locale`` dist-func spelling,
        usable wherever runtime/forasync.py accepts one."""
        return lambda ndim, flat, total: self.device_of(flat, total)

    def hop_order(self) -> Optional[List[int]]:
        """Graph-derived steal-scan order; None without a graph AND on a
        1-device roster (no hops exist - callers must fall back to the
        runner's default rather than pass an empty override)."""
        if self.graph is None:
            return None
        return steal_hop_order(self.graph, self.ndev) or None

    def xor_hop_order(self) -> Optional[List[int]]:
        """Graph-derived XOR-exchange order for the resident runner
        (``ResidentKernel.run(hop_order=)``); None without a graph and
        on a 1-device roster, like ``hop_order``."""
        if self.graph is None:
            return None
        return xor_hop_order(self.graph, self.ndev) or None


def resolve_placement(
    placement: Union["MeshPlacement", Dict, str, Callable],
    ndev: Optional[int] = None,
) -> "MeshPlacement | Callable":
    """Normalize a placement argument: a MeshPlacement passes through, a
    dict is a descriptor, a str is a descriptor file path, and a callable
    is a dist-func ``(ndim, flat, total) -> device`` used as-is."""
    if isinstance(placement, MeshPlacement):
        mp = placement
    elif isinstance(placement, dict):
        mp = MeshPlacement.from_dict(placement)
    elif isinstance(placement, str):
        mp = MeshPlacement.from_file(placement)
    elif callable(placement):
        return placement
    else:
        raise TypeError(
            f"placement must be MeshPlacement | dict | path | dist-func, "
            f"got {type(placement).__name__}"
        )
    if ndev is not None and mp.ndev != ndev:
        raise ValueError(
            f"placement describes {mp.ndev} devices, mesh has {ndev}"
        )
    return mp
