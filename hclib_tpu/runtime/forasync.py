"""Parallel loops: forasync 1D/2D/3D in flat and recursive modes.

Mirrors the reference semantics (src/hclib.c:158-473, inc/hclib-forasync.h):

- FLAT mode tiles the iteration space and spawns one task per tile; each tile
  task runs the body over its indices (src/hclib.c:316-416).
- RECURSIVE mode binary-splits the largest dimension until every piece is at
  most one tile, spawning a task per split (src/hclib.c:158-314).
- Auto-tile picks ``ceil(N / nworkers)`` per dimension (src/hclib.c:452-464).
- ``forasync_future`` wraps the loop in a non-blocking finish and returns its
  completion future (src/hclib.c:466-473).
- A registered *distribution function* maps each flat tile to a locale
  (hclib_register_dist_func / loop_dist_func, src/hclib.c:19-30,
  inc/hclib-forasync.h:349-380); the default places tiles at the central
  locale. RECURSIVE mode sees the SAME flat-tile -> locale mapping: a leaf
  piece is keyed by the flat index of the tile holding its low corner, so a
  flat-index dist func places both modes identically whenever the recursion
  lands on the flat tile grid (power-of-two tile counts) and consistently
  otherwise.

``place="device"`` lowers the loop onto the TPU megakernel's batched
same-kind dispatch lanes instead of spawning host tasks
(device/forasync_tier.py): the body is then a ``TileKernel`` slab pipeline,
``dist_func`` doubles as the mesh placement (dist-func callable or JSON
placement descriptor resolved against ``locality_graphs/``), and the call
returns ``(data_out, info)``. The device tier is FLAT-mode only and
requires tiles that divide the bounds exactly (slab shapes are static).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Tuple

from .promise import Future
from .scheduler import (
    async_,
    current_runtime,
    end_finish_nonblocking,
    finish,
    start_finish,
)

__all__ = ["forasync", "forasync_future", "FLAT", "RECURSIVE", "register_dist_func"]

FLAT = "flat"
RECURSIVE = "recursive"

_dist_funcs: dict = {}


def register_dist_func(name: str, fn: Callable[..., Any]) -> None:
    """Register a tile->locale distribution function by name."""
    _dist_funcs[name] = fn


def lookup_dist_func(name: str) -> Callable[..., Any]:
    return _dist_funcs[name]


def _normalize(bounds: Sequence, tile: Optional[Sequence], nworkers: int):
    dims = []
    for b in bounds:
        if isinstance(b, int):
            dims.append((0, b))
        else:
            lo, hi = b
            dims.append((int(lo), int(hi)))
    if tile is None:
        tile_dims = [max(1, math.ceil((hi - lo) / nworkers)) for lo, hi in dims]
    elif isinstance(tile, int):
        tile_dims = [tile] * len(dims)
    else:
        tile_dims = [int(t) for t in tile]
    if len(tile_dims) != len(dims):
        raise ValueError("tile rank must match loop rank")
    return dims, tile_dims


def _run_tile(fn: Callable, ranges: Tuple[Tuple[int, int], ...]) -> None:
    ndim = len(ranges)
    if ndim == 1:
        (lo0, hi0), = ranges
        for i in range(lo0, hi0):
            fn(i)
    elif ndim == 2:
        (lo0, hi0), (lo1, hi1) = ranges
        for i in range(lo0, hi0):
            for j in range(lo1, hi1):
                fn(i, j)
    else:
        (lo0, hi0), (lo1, hi1), (lo2, hi2) = ranges
        for i in range(lo0, hi0):
            for j in range(lo1, hi1):
                for k in range(lo2, hi2):
                    fn(i, j, k)


def _tile_counts(dims, tile_dims):
    return [math.ceil((hi - lo) / t) for (lo, hi), t in zip(dims, tile_dims)]


def _flat_of_ranges(ranges, dims, tile_dims, tile_counts) -> int:
    """Flat tile index of the piece whose low corner is ``ranges``'s -
    the key RECURSIVE leaves use so a flat-index dist func sees the same
    tile -> locale mapping as FLAT mode. When the recursion lands exactly
    on the flat tile grid (power-of-two tile counts) the piece IS that
    flat tile; otherwise the low corner picks the covering tile."""
    flat = 0
    for (plo, _), (lo, _), t, c in zip(ranges, dims, tile_dims, tile_counts):
        idx = min((plo - lo) // t, c - 1)
        flat = flat * c + idx
    return flat


def _spawn_flat(fn, dims, tile_dims, dist_func) -> None:
    ndim = len(dims)
    if isinstance(dist_func, str):
        dist_func = lookup_dist_func(dist_func)
    if dist_func is None:
        # Reference default: flat tiles are routed to the central place
        # (hclib's default loop_dist_func, src/hclib-runtime.c:231-239).
        central = current_runtime().graph.central_locale()
        dist_func = lambda ndim_, tile_, total_: central  # noqa: E731
    tile_counts = _tile_counts(dims, tile_dims)
    total = math.prod(tile_counts)
    for flat in range(total):
        idx = []
        rem = flat
        for c in reversed(tile_counts):
            idx.append(rem % c)
            rem //= c
        idx.reverse()
        ranges = tuple(
            (lo + i * t, min(hi, lo + (i + 1) * t))
            for (lo, hi), t, i in zip(dims, tile_dims, idx)
        )
        async_(_run_tile, fn, ranges, at=dist_func(ndim, flat, total))


def _spawn_recursive(fn, ranges, tile_dims, dims=None, dist_func=None) -> None:
    # Split the largest over-tile dimension in half; recurse via new tasks
    # (reference: src/hclib.c:158-314). ``dims``/``dist_func`` thread the
    # flat-tile placement context down to the leaves: a leaf piece spawns
    # at ``dist_func(ndim, flat-of-low-corner, total)``, the SAME mapping
    # FLAT mode applies, so placement policy is mode-independent. With no
    # dist func, leaves run inline/at the spawner's locale as before.
    widest, wdim = -1, -1
    for d, ((lo, hi), t) in enumerate(zip(ranges, tile_dims)):
        if hi - lo > t and hi - lo > widest:
            widest, wdim = hi - lo, d
    if wdim < 0:
        if dist_func is not None:
            tile_counts = _tile_counts(dims, tile_dims)
            flat = _flat_of_ranges(ranges, dims, tile_dims, tile_counts)
            total = math.prod(tile_counts)
            async_(
                _run_tile, fn, tuple(ranges),
                at=dist_func(len(dims), flat, total),
            )
        else:
            _run_tile(fn, tuple(ranges))
        return
    lo, hi = ranges[wdim]
    mid = (lo + hi) // 2
    left = list(ranges)
    right = list(ranges)
    left[wdim] = (lo, mid)
    right[wdim] = (mid, hi)
    async_(_spawn_recursive, fn, left, tile_dims, dims, dist_func)
    _spawn_recursive(fn, right, tile_dims, dims, dist_func)


def _spawn_all(fn, dims, tile_dims, mode, dist_func) -> None:
    if mode == FLAT:
        _spawn_flat(fn, dims, tile_dims, dist_func)
    else:
        if isinstance(dist_func, str):
            dist_func = lookup_dist_func(dist_func)
        _spawn_recursive(fn, dims, tile_dims, dims, dist_func)


def forasync(
    fn: Callable[..., Any],
    bounds: Sequence,
    tile: Optional[Sequence] = None,
    mode: str = FLAT,
    dist_func: Optional[Callable[[int, int, int], Any]] = None,
    blocking: bool = True,
    place: Optional[str] = None,
    **device_kw,
):
    """Parallel loop over a 1-3D iteration space.

    ``bounds`` is a sequence of ``int`` (upper bound, from 0) or ``(lo, hi)``
    pairs, one per dimension. ``fn`` receives one index per dimension.

    ``place="device"`` runs the loop on the TPU megakernel's batch-lane
    tier instead (see module docstring): ``fn`` must be a
    ``device.forasync_tier.TileKernel``, ``tile`` is required,
    ``dist_func`` doubles as the mesh placement, and extra keywords
    (``data=``, ``width=``, ``mesh=``, ...) forward to
    ``run_forasync_device``, whose ``(data_out, info)`` is returned.
    """
    if mode not in (FLAT, RECURSIVE):
        raise ValueError(f"unknown forasync mode {mode!r}")
    if place not in (None, "host", "device"):
        raise ValueError(f"unknown forasync place {place!r}")
    if place == "device":
        if mode != FLAT:
            raise ValueError(
                "place='device' supports mode=FLAT only: recursive "
                "splitting produces unaligned piece shapes, and device "
                "slab DMAs are static-shaped"
            )
        if tile is None:
            raise ValueError(
                "place='device' needs an explicit tile= (auto-tile is a "
                "host-worker-count policy; device tiles size the slabs)"
            )
        if not blocking:
            raise ValueError(
                "place='device' is synchronous (the megakernel runs the "
                "loop to completion and returns its results): "
                "blocking=False has no device spelling"
            )
        from ..device.forasync_tier import run_forasync_device

        return run_forasync_device(
            fn, bounds, tile, placement=dist_func, **device_kw
        )
    if device_kw:
        raise TypeError(
            f"unexpected arguments {sorted(device_kw)} (device-tier "
            "options need place='device')"
        )
    if not 1 <= len(bounds) <= 3:
        raise ValueError("forasync supports 1-3 dimensions")
    rt = current_runtime()
    dims, tile_dims = _normalize(bounds, tile, rt.nworkers)

    if blocking:
        with finish():
            _spawn_all(fn, dims, tile_dims, mode, dist_func)
    else:
        _spawn_all(fn, dims, tile_dims, mode, dist_func)


def forasync_future(
    fn: Callable[..., Any],
    bounds: Sequence,
    tile: Optional[Sequence] = None,
    mode: str = FLAT,
    dist_func: Optional[Callable[[int, int, int], Any]] = None,
) -> Future:
    """Non-blocking forasync; returns a future satisfied when every tile has
    completed (hclib_forasync_future: src/hclib.c:466-473)."""
    if mode not in (FLAT, RECURSIVE):
        raise ValueError(f"unknown forasync mode {mode!r}")
    rt = current_runtime()
    dims, tile_dims = _normalize(bounds, tile, rt.nworkers)
    fin = start_finish()
    _spawn_all(fn, dims, tile_dims, mode, dist_func)
    return end_finish_nonblocking(fin)
