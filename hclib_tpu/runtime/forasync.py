"""Parallel loops: forasync 1D/2D/3D in flat and recursive modes.

Mirrors the reference semantics (src/hclib.c:158-473, inc/hclib-forasync.h):

- FLAT mode tiles the iteration space and spawns one task per tile; each tile
  task runs the body over its indices (src/hclib.c:316-416).
- RECURSIVE mode binary-splits the largest dimension until every piece is at
  most one tile, spawning a task per split (src/hclib.c:158-314).
- Auto-tile picks ``ceil(N / nworkers)`` per dimension (src/hclib.c:452-464).
- ``forasync_future`` wraps the loop in a non-blocking finish and returns its
  completion future (src/hclib.c:466-473).
- A registered *distribution function* maps each flat tile to a locale
  (hclib_register_dist_func / loop_dist_func, src/hclib.c:19-30,
  inc/hclib-forasync.h:349-380); the default places tiles at the central
  locale.

On the device path, flat forasync tiles become task descriptors executed by
the Pallas megakernel grid; see device/.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Tuple

from .promise import Future
from .scheduler import (
    async_,
    current_runtime,
    end_finish_nonblocking,
    finish,
    start_finish,
)

__all__ = ["forasync", "forasync_future", "FLAT", "RECURSIVE", "register_dist_func"]

FLAT = "flat"
RECURSIVE = "recursive"

_dist_funcs: dict = {}


def register_dist_func(name: str, fn: Callable[..., Any]) -> None:
    """Register a tile->locale distribution function by name."""
    _dist_funcs[name] = fn


def lookup_dist_func(name: str) -> Callable[..., Any]:
    return _dist_funcs[name]


def _normalize(bounds: Sequence, tile: Optional[Sequence], nworkers: int):
    dims = []
    for b in bounds:
        if isinstance(b, int):
            dims.append((0, b))
        else:
            lo, hi = b
            dims.append((int(lo), int(hi)))
    if tile is None:
        tile_dims = [max(1, math.ceil((hi - lo) / nworkers)) for lo, hi in dims]
    elif isinstance(tile, int):
        tile_dims = [tile] * len(dims)
    else:
        tile_dims = [int(t) for t in tile]
    if len(tile_dims) != len(dims):
        raise ValueError("tile rank must match loop rank")
    return dims, tile_dims


def _run_tile(fn: Callable, ranges: Tuple[Tuple[int, int], ...]) -> None:
    ndim = len(ranges)
    if ndim == 1:
        (lo0, hi0), = ranges
        for i in range(lo0, hi0):
            fn(i)
    elif ndim == 2:
        (lo0, hi0), (lo1, hi1) = ranges
        for i in range(lo0, hi0):
            for j in range(lo1, hi1):
                fn(i, j)
    else:
        (lo0, hi0), (lo1, hi1), (lo2, hi2) = ranges
        for i in range(lo0, hi0):
            for j in range(lo1, hi1):
                for k in range(lo2, hi2):
                    fn(i, j, k)


def _spawn_flat(fn, dims, tile_dims, dist_func) -> None:
    ndim = len(dims)
    if isinstance(dist_func, str):
        dist_func = lookup_dist_func(dist_func)
    if dist_func is None:
        # Reference default: flat tiles are routed to the central place
        # (hclib's default loop_dist_func, src/hclib-runtime.c:231-239).
        central = current_runtime().graph.central_locale()
        dist_func = lambda ndim_, tile_, total_: central  # noqa: E731
    tile_counts = [math.ceil((hi - lo) / t) for (lo, hi), t in zip(dims, tile_dims)]
    total = math.prod(tile_counts)
    for flat in range(total):
        idx = []
        rem = flat
        for c in reversed(tile_counts):
            idx.append(rem % c)
            rem //= c
        idx.reverse()
        ranges = tuple(
            (lo + i * t, min(hi, lo + (i + 1) * t))
            for (lo, hi), t, i in zip(dims, tile_dims, idx)
        )
        async_(_run_tile, fn, ranges, at=dist_func(ndim, flat, total))


def _spawn_recursive(fn, ranges, tile_dims) -> None:
    # Split the largest over-tile dimension in half; recurse via new tasks
    # (reference: src/hclib.c:158-314).
    widest, wdim = -1, -1
    for d, ((lo, hi), t) in enumerate(zip(ranges, tile_dims)):
        if hi - lo > t and hi - lo > widest:
            widest, wdim = hi - lo, d
    if wdim < 0:
        _run_tile(fn, tuple(ranges))
        return
    lo, hi = ranges[wdim]
    mid = (lo + hi) // 2
    left = list(ranges)
    right = list(ranges)
    left[wdim] = (lo, mid)
    right[wdim] = (mid, hi)
    async_(_spawn_recursive, fn, left, tile_dims)
    _spawn_recursive(fn, right, tile_dims)


def forasync(
    fn: Callable[..., Any],
    bounds: Sequence,
    tile: Optional[Sequence] = None,
    mode: str = FLAT,
    dist_func: Optional[Callable[[int, int, int], Any]] = None,
    blocking: bool = True,
) -> None:
    """Parallel loop over a 1-3D iteration space.

    ``bounds`` is a sequence of ``int`` (upper bound, from 0) or ``(lo, hi)``
    pairs, one per dimension. ``fn`` receives one index per dimension.
    """
    if not 1 <= len(bounds) <= 3:
        raise ValueError("forasync supports 1-3 dimensions")
    if mode not in (FLAT, RECURSIVE):
        raise ValueError(f"unknown forasync mode {mode!r}")
    rt = current_runtime()
    dims, tile_dims = _normalize(bounds, tile, rt.nworkers)

    def spawn_all() -> None:
        if mode == FLAT:
            _spawn_flat(fn, dims, tile_dims, dist_func)
        else:
            _spawn_recursive(fn, dims, tile_dims)

    if blocking:
        with finish():
            spawn_all()
    else:
        spawn_all()


def forasync_future(
    fn: Callable[..., Any],
    bounds: Sequence,
    tile: Optional[Sequence] = None,
    mode: str = FLAT,
    dist_func: Optional[Callable[[int, int, int], Any]] = None,
) -> Future:
    """Non-blocking forasync; returns a future satisfied when every tile has
    completed (hclib_forasync_future: src/hclib.c:466-473)."""
    if mode not in (FLAT, RECURSIVE):
        raise ValueError(f"unknown forasync mode {mode!r}")
    rt = current_runtime()
    dims, tile_dims = _normalize(bounds, tile, rt.nworkers)
    fin = start_finish()
    if mode == FLAT:
        _spawn_flat(fn, dims, tile_dims, dist_func)
    else:
        _spawn_recursive(fn, dims, tile_dims)
    return end_finish_nonblocking(fin)
