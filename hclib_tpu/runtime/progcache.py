"""Process-wide content-keyed program cache (ISSUE 18).

Every ``Megakernel`` (and every runner embedding one - sharded steal,
resident mesh, ICI/PGAS fallbacks, the streaming front door) pays the
full JAX trace -> lower -> compile pipeline on its first run, even when
a byte-identical program was built moments ago by another instance: the
dominant cost on warm machines, the tier-1 wall-clock tax, and the whole
price of a serving cold start or an autoscaler resize. The persistent
``JAX_COMPILATION_CACHE_DIR`` does not help: it dedupes identical XLA
*compilations*, after the trace/lower work that dominates warm builds
has already been paid.

This module is the layer above: a process-wide registry of JITTED
EXECUTABLES keyed on a content fingerprint of everything that shapes the
compiled program:

- the kernel table, positionally (the comparison ``CheckpointBundle``
  already uses), plus each kernel's CODE fingerprint (bytecode, consts,
  closure cell values - arrays hash by content) so same-named but
  different-bodied kernels can never collide;
- routed ``BatchSpec``s (width/prefetch/body/drain/priority fns);
- device-word knobs: checkpoint, quiesce_stride, lane_max_age,
  priority_buckets, trace capacity, tenants/egress shape;
- capacities and buffer specs (capacity, num_values, succ_capacity,
  data_specs, scratch_specs, vmem_limit_bytes, uses_row_values,
  tracks_home, interpret);
- the runner's own static config (mesh shape + device ids + hop order,
  steal windows, injection ring shape) via the ``variant`` argument;
- the hclint layout-table fingerprint (``analysis/layout.py``), so any
  device-word layout drift invalidates every key.

A hit returns the very jitted callable a cache-off build would have
produced for the same content - ``jax.jit`` tracing is lazy and cached
per-callable, so the second instance's first call rides JAX's own
fast path with zero trace/lower work. Lowered text is byte-identical
by construction (asserted in ``tests/test_progcache.py`` and the
``program-cache`` perf guard).

Fail-open discipline: a value the fingerprinter cannot reduce to
content (an exotic closure cell, a cycle deeper than the bound) makes
that build UNCACHEABLE - it builds privately, never poisons the table.
Address-bearing ``repr``s are safe by uniqueness: they can only ever
miss, never falsely hit.

Knobs (``runtime/env.py`` registry): ``HCLIB_TPU_PROGRAM_CACHE``
(default on; ``0`` forces off - byte-identity makes on safe under
pytest and in serving alike) and ``HCLIB_TPU_PROGRAM_CACHE_CAP``
(bounded entry count; malformed or non-positive text raises).
Eviction is cost-weighted LRU: on overflow the victim is the entry
with the smallest measured ``build_s`` among the quarter of entries
least recently used, so expensive mesh builds outlive bursts of cheap
scalar ones without letting any entry pin the cache forever.
"""

from __future__ import annotations

import hashlib
import threading
import time
import types
from collections import OrderedDict
from itertools import islice
from typing import Any, Callable, Dict, Optional, Tuple

from .env import env_int, env_raw

__all__ = [
    "enabled",
    "cache_cap",
    "fingerprint",
    "layout_fingerprint",
    "megakernel_fingerprint",
    "mesh_key",
    "shared_build",
    "probe",
    "cache_stats",
    "reset",
]

_DEFAULT_CAP = 256
_MAX_DEPTH = 32


class Uncacheable(Exception):
    """A build input the fingerprinter refuses to reduce to content
    (cycle past the depth bound, an object that raises under
    inspection). The build proceeds uncached - never a wrong hit."""


class _FP:
    """Streaming content hash. Every ``add`` reduces one object to
    bytes fed into blake2b; containers and closures recurse with a
    depth bound and an id-keyed cycle guard."""

    def __init__(self) -> None:
        self._h = hashlib.blake2b(digest_size=16)
        self._seen: Dict[int, int] = {}
        self._pins: list = []  # keep ids alive while memoized

    def _feed(self, *parts) -> None:
        for p in parts:
            b = p if isinstance(p, bytes) else str(p).encode()
            self._h.update(b)
            self._h.update(b"\x1f")

    def digest(self) -> str:
        return self._h.hexdigest()

    # -- recursive reduction --

    def add(self, obj: Any, depth: int = 0) -> None:
        if depth > _MAX_DEPTH:
            raise Uncacheable("fingerprint depth bound exceeded")
        if obj is None or isinstance(obj, (bool, int, float, complex)):
            self._feed("s", type(obj).__name__, repr(obj))
            return
        if isinstance(obj, (str, bytes)):
            self._feed("t", type(obj).__name__, obj)
            return
        oid = id(obj)
        if oid in self._seen:
            self._feed("cycle", self._seen[oid])
            return
        self._seen[oid] = len(self._seen)
        self._pins.append(obj)
        import numpy as np

        if isinstance(obj, np.dtype):
            self._feed("dtype", obj.str)
            return
        if isinstance(obj, np.generic):
            self._feed("npscalar", obj.dtype.str, repr(obj.item()))
            return
        if isinstance(obj, np.ndarray) or type(obj).__name__ == "ArrayImpl":
            a = np.asarray(obj)
            self._feed("nd", a.shape, a.dtype.str)
            self._h.update(np.ascontiguousarray(a).tobytes())
            return
        if isinstance(obj, (tuple, list)):
            self._feed("seq", type(obj).__name__, len(obj))
            for x in obj:
                self.add(x, depth + 1)
            return
        if isinstance(obj, dict):
            self._feed("map", len(obj))
            for k in sorted(obj, key=repr):
                self.add(k, depth + 1)
                self.add(obj[k], depth + 1)
            return
        if isinstance(obj, (set, frozenset)):
            self._feed("set", len(obj))
            for x in sorted(obj, key=repr):
                self.add(x, depth + 1)
            return
        import functools

        if isinstance(obj, functools.partial):
            self._feed("partial")
            self.add(obj.func, depth + 1)
            self.add(obj.args, depth + 1)
            self.add(obj.keywords, depth + 1)
            return
        if isinstance(obj, types.MethodType):
            self._feed("method")
            self.add(obj.__func__, depth + 1)
            self.add(obj.__self__, depth + 1)
            return
        if isinstance(obj, types.FunctionType):
            self._add_fn(obj, depth)
            return
        if isinstance(obj, types.BuiltinFunctionType):
            self._feed("builtin", getattr(obj, "__module__", ""),
                       getattr(obj, "__qualname__", obj.__name__))
            return
        if isinstance(obj, types.CodeType):
            self._add_code(obj, depth)
            return
        if isinstance(obj, type):
            self._feed("class", obj.__module__, obj.__qualname__)
            return
        # ShapeDtypeStruct and kin: shape + dtype IS the content.
        shape = getattr(obj, "shape", None)
        dtype = getattr(obj, "dtype", None)
        if shape is not None and dtype is not None:
            self._feed("sds", type(obj).__name__, tuple(shape), str(dtype))
            return
        # Generic object: type identity + attribute dict. Objects
        # without inspectable state fall through to repr below -
        # address-bearing reprs are SAFE BY UNIQUENESS (permanent
        # miss, never a false hit).
        t = type(obj)
        state = getattr(obj, "__dict__", None)
        if state is None and hasattr(t, "__slots__"):
            state = {
                s: getattr(obj, s)
                for s in t.__slots__ if hasattr(obj, s)
            }
        if isinstance(state, dict):
            self._feed("obj", t.__module__, t.__qualname__)
            self.add(state, depth + 1)
            return
        self._feed("repr", t.__module__, t.__qualname__, repr(obj))

    def _add_fn(self, fn, depth: int) -> None:
        self._feed("fn", getattr(fn, "__module__", ""),
                   getattr(fn, "__qualname__", ""))
        self._add_code(fn.__code__, depth)
        self.add(fn.__defaults__, depth + 1)
        kwd = fn.__kwdefaults__
        if kwd:
            self.add(dict(kwd), depth + 1)
        if fn.__closure__:
            self._feed("closure", len(fn.__closure__))
            for cell in fn.__closure__:
                try:
                    v = cell.cell_contents
                except ValueError:
                    self._feed("emptycell")
                    continue
                self.add(v, depth + 1)

    def _add_code(self, code, depth: int) -> None:
        self._feed("code", code.co_name, code.co_argcount,
                   code.co_flags & 0x0F)
        self._h.update(code.co_code)
        self._feed(*code.co_names)
        for c in code.co_consts:
            if isinstance(c, types.CodeType):
                self._add_code(c, depth + 1)
            else:
                self.add(c, depth + 1)


def fingerprint(*objs: Any) -> str:
    """Content digest of arbitrary host objects (the test/verification
    entry point; raises :class:`Uncacheable` on irreducible input)."""
    fp = _FP()
    for o in objs:
        fp.add(o)
    return fp.digest()


def layout_fingerprint() -> str:
    """Digest of the hclint device-word layout table
    (``analysis/layout.py``: LAYOUT + the checkpoint state-key rosters).
    Part of every program key, so ANY layout drift - a new word, a
    moved offset, a renamed checkpoint member - invalidates the whole
    cache rather than risking a stale program against a new ABI.
    Recomputed per call (the table is small) so tests can prove the
    sensitivity by patching the table."""
    from ..analysis import layout as L

    fp = _FP()
    fp._feed("layout", len(L.LAYOUT))
    for name in sorted(L.LAYOUT):
        fp._feed(name)
        fp.add(L.LAYOUT[name])
    fp._feed(*L._CKPT_STATE_KEYS)
    fp._feed(*L._CKPT_OPT_KEYS)
    return fp.digest()


def mesh_key(mesh) -> Tuple:
    """The mesh facts a compiled program is pinned to: axis names,
    per-axis extents, and the flat device-id order (a reshuffled mesh
    must not reuse another's executable)."""
    return (
        tuple(mesh.axis_names),
        tuple(int(d) for d in mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def megakernel_fingerprint(mk) -> str:
    """Content digest of one ``Megakernel``'s program-shaping state:
    the kernel table (positional names + body fingerprints), routed
    BatchSpecs, buffer specs and capacities, and every device-word
    knob, prefixed with :func:`layout_fingerprint`. Raises
    :class:`Uncacheable` when some component resists content
    reduction (the caller then builds uncached)."""
    fp = _FP()
    fp._feed("hclib-progcache-v1", layout_fingerprint())
    fp._feed("kernels", len(mk.kernel_names))
    for name, fn in zip(mk.kernel_names, mk.kernel_fns):
        fp._feed(name)
        fp.add(fn)
    fp._feed("batch", len(mk.batch_specs))
    for fid, spec in mk.batch_specs:
        fp._feed(fid)
        fp.add(spec)
    fp.add(mk.data_specs)
    fp.add(mk.scratch_specs)
    fp.add((
        mk.capacity, mk.num_values, mk.succ_capacity,
        bool(mk.interpret), bool(mk.uses_row_values),
        mk.vmem_limit_bytes, bool(mk.tracks_home),
        bool(mk.checkpoint), getattr(mk, "quiesce_stride", 1),
        mk.lane_max_age, mk.priority_buckets,
    ))
    tr = mk.trace
    fp.add(None if tr is None
           else (getattr(tr, "capacity", None), getattr(tr, "words", None)))
    return fp.digest()


# ------------------------------------------------------------ registry

def enabled() -> bool:
    """``HCLIB_TPU_PROGRAM_CACHE``: unset -> on (byte-identity makes
    the cache safe by default, under pytest and in serving alike);
    ``''``/``'0'`` -> off; anything else -> on."""
    v = env_raw("HCLIB_TPU_PROGRAM_CACHE")
    if v is None:
        return True
    return v not in ("", "0")


def cache_cap() -> int:
    """``HCLIB_TPU_PROGRAM_CACHE_CAP``: LRU entry bound (default
    256). Malformed text raises via the env registry; non-positive
    values raise here - a cap of 0 would silently disable caching
    under an innocent-looking spelling."""
    cap = env_int("HCLIB_TPU_PROGRAM_CACHE_CAP", _DEFAULT_CAP)
    if cap < 1:
        raise ValueError(
            f"HCLIB_TPU_PROGRAM_CACHE_CAP={cap} must be >= 1 (set "
            "HCLIB_TPU_PROGRAM_CACHE=0 to turn the cache off)"
        )
    return cap


class ProgramCache:
    """Bounded-LRU registry of jitted executables, with COST-WEIGHTED
    eviction: each entry remembers its measured ``build_s``, and on
    overflow the victim is the CHEAPEST-to-rebuild entry among the
    ``len // 4`` least-recently-used (ties: least recently used, so
    uniform costs - and any cache small enough that the window is one
    entry - degrade to exact LRU). A 40 s resident-mesh build thus
    survives a burst of 50 ms scalar builds that would have rolled it
    off the tail, while a hot expensive entry still cannot pin the
    cache forever (it ages into the window like everything else).
    Thread-safe; builds run outside the lock (a racing identical build
    is wasted work, not a correctness problem - first insert wins so
    every holder shares one callable)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> (fn, build_s); OrderedDict order IS the recency order.
        self._entries: "OrderedDict[Tuple[str, str], Tuple[Any, float]]" \
            = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key):
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return ent[0]
            return None

    def put(self, key, fn, cap: int, build_s: float = 0.0):
        with self._lock:
            self.misses += 1
            kept = self._entries.setdefault(key, (fn, float(build_s)))
            self._entries.move_to_end(key)
            while len(self._entries) > cap:
                k = max(1, len(self._entries) // 4)
                window = list(islice(self._entries.items(), k))
                # min() is stable: equal costs evict the oldest.
                victim = min(window, key=lambda kv: kv[1][1])[0]
                del self._entries[victim]
                self.evictions += 1
            return kept[0]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "entries": len(self._entries),
            }

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0


_CACHE = ProgramCache()


def cache_stats() -> Dict[str, int]:
    """Process-wide counters: ``hits`` / ``misses`` / ``evictions`` /
    ``entries`` (the ``program_cache.*`` gauges MetricsRegistry
    exports)."""
    return _CACHE.stats()


def reset() -> None:
    """Drop every entry and zero the counters (test isolation)."""
    _CACHE.reset()


def _key(mk, variant) -> Optional[Tuple[str, str]]:
    try:
        return (megakernel_fingerprint(mk), fingerprint(variant))
    except Uncacheable:
        return None
    except Exception:
        # Fingerprinting must NEVER sink a build: an inspection that
        # raises (an exotic closure, a half-built object) means
        # uncacheable, not broken.
        return None


def probe(mk, variant) -> bool:
    """True when the program for (mk content, runner variant) is warm
    in the registry - the zero-rebuild read the autoscaler's
    ``ScaleEvent.cache_hit`` records. Does not touch LRU order or the
    hit counters."""
    if not enabled():
        return False
    key = _key(mk, variant)
    return key is not None and _CACHE.contains(key)


def shared_build(mk, variant, build: Callable[[], Any]):
    """The one integration point every runner threads its jit through:

    ``fn, stats = shared_build(mk, variant, lambda: jax.jit(...))``

    ``variant`` is any content-reducible object naming the runner's own
    static build parameters (fuel/quantum/windows/mesh/hop order...);
    the megakernel fingerprint plus the variant digest is the cache
    key. Returns the shared callable and a stats dict: ``hit``,
    ``cache_lookup_s`` (fingerprint + registry probe), ``build_s``
    (0.0 on a hit). Cache off / uncacheable input degrade to a plain
    timed build with ``hit=False``."""
    t0 = time.perf_counter()
    key = None
    if enabled():
        key = _key(mk, variant)
        if key is not None:
            fn = _CACHE.get(key)
            if fn is not None:
                return fn, {
                    "hit": True,
                    "cache_lookup_s": time.perf_counter() - t0,
                    "build_s": 0.0,
                }
    lookup_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    fn = build()
    build_s = time.perf_counter() - t1
    if key is not None:
        fn = _CACHE.put(key, fn, cache_cap(), build_s=build_s)
    return fn, {
        "hit": False,
        "cache_lookup_s": lookup_s,
        "build_s": build_s,
    }
