"""Elastic autoscaling: a metrics-driven quiesce -> reshard -> resume
control loop over the resident mesh.

PRs 2, 4, and 5 built the three ingredients - device fault detection +
quarantine (``DeviceFaultPlan``, heartbeat quarantine masks), the
``MetricsRegistry``, and ``CheckpointBundle`` + ``reshard(M)`` - and this
module is their production composition: a host controller that keeps a
resident mesh serving through preemption, chip death, and load swings
without losing the task graph. SURVEY.md notes the HClib reference has
*no elastic recovery, no checkpointing*; this is where the rebuild
overtakes the paper rather than reproducing it.

Control model (one **slice** per loop iteration):

1. Run the mesh for a bounded slice: ``rk.run(..., quiesce=slice_rounds)``
   makes every device stop popping at round ``slice_rounds`` and exit in
   lockstep with its live scheduler state (the PR 5 clean-cut quiesce) -
   or exit normally if the workload drained first.
2. Observe: per-device ready backlog, pending, executed delta,
   inject-ring backlog, and the quarantine masks from ``fault_stats``
   fold into an :class:`Observation`.
3. Decide: :class:`AutoscalerPolicy` is a PURE decision function with
   hysteresis (a resize needs ``hysteresis`` consecutive over/under-
   threshold observations) and a post-resize ``cooldown`` (slices during
   which no further resize fires) - so the controller never flaps, and
   the policy is unit-testable with synthetic observations, no mesh
   required.
4. Act: a resize snapshots the quiesced state
   (``snapshot_resident``), re-homes it with ``CheckpointBundle.
   reshard(M)`` (totals conserved; the PR 2 dead-chip semantics), builds
   the M-device kernel, and resumes mid-graph. **Evacuation** is the
   fault-driven special case: any chip named in a survivor's quarantine
   mask is resharded around immediately (no hysteresis, no cooldown
   gate) - the controller beats the watchdog's escalation to it.
5. Record: every decision is a typed :class:`ScaleEvent` - appended to
   ``Autoscaler.events``, recorded in the :class:`MetricsRegistry`
   (``autoscale.*``), and emitted as a ``TR_SCALE`` record that
   ``Autoscaler.trace_info()`` exposes in the flight-recorder ABI, so
   ``tools/timeline.py --perfetto`` renders scale events beside device
   rounds on one timeline.

Preemption composes: when ``resilience.preempt_requested()`` turns true
between slices (SIGTERM via ``install_preempt_handler``, the
``HCLIB_TPU_PREEMPT`` env, or ``fire_preempt``), the controller saves
the current quiesced state as an on-disk bundle (``checkpoint_dir``) and
returns with ``info['preempted'] = True`` - checkpoint, then stop; a
later ``Autoscaler.run(resume_bundle=...)`` (any mesh size the policy
picks) continues the graph.

Off-path cost: none. The autoscaler is a host-side composition - it
spawns no threads, compiles nothing into kernels, and a mesh run outside
it is byte-identical to PR 5 behavior (asserted in
tests/test_autoscaler.py).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from . import resilience
from .checkpoint import (
    BundleStore,
    CheckpointBundle,
    CheckpointError,
    snapshot_resident,
)

__all__ = [
    "Observation",
    "ScaleEvent",
    "AutoscalerPolicy",
    "Autoscaler",
]

# ScaleEvent.kind -> TR_SCALE b-word code, derived from the one SC_*
# table (device/tracebuf.py SC_NAMES; timeline.py labels from the same
# table, so codes, kinds, and rendered names cannot drift apart).
# tracebuf imports only numpy at module scope, so this is host-safe.
from ..device.tracebuf import SC_NAMES as _SC_NAMES  # noqa: E402

_KIND_CODES = {
    name.replace(" ", "_"): code for code, name in _SC_NAMES.items()
}


def _pof2_floor(n: int) -> int:
    """Largest power of two <= n (0 for n < 1)."""
    n = int(n)
    if n < 1:
        return 0
    return 1 << (n.bit_length() - 1)


class Observation:
    """One control slice's view of the mesh - everything the policy may
    read. Built from a quiesced run's ``info`` by the controller, or
    constructed directly in policy unit tests.

    ``tenants`` (mesh-tenancy runs): the per-tenant pressure feed -
    ``{tid: {backlog, in_flight, ring_residue, expired, budget, ...}}``
    (``MeshTenantTable.pressure()`` is the canonical producer). The
    policy reads deadline-budget DRAIN (expired deltas between
    consecutive observations) and the strand set (tenants with
    in-flight / ring-resident rows a scale-in would disturb) off it."""

    __slots__ = (
        "ndev", "backlog", "pending", "executed_delta", "inject_backlog",
        "quarantined", "slice_s", "tenants", "latency_pressure",
    )

    def __init__(
        self,
        ndev: int,
        backlog: Sequence[int],
        pending: int = 0,
        executed_delta: int = 0,
        inject_backlog: int = 0,
        quarantined: Sequence[int] = (),
        slice_s: float = 0.0,
        tenants: Optional[Dict[str, Dict[str, float]]] = None,
        latency_pressure: float = 0.0,
    ) -> None:
        self.ndev = int(ndev)
        self.backlog = [int(b) for b in backlog]
        self.pending = int(pending)
        self.executed_delta = int(executed_delta)
        self.inject_backlog = int(inject_backlog)
        self.quarantined = tuple(sorted(set(int(q) for q in quarantined)))
        self.slice_s = float(slice_s)
        self.tenants = tenants
        # Max burn rate across the SLO engine's windows (runtime/slo.py
        # SloEstimator.latency_pressure(); 0.0 when no SLO is
        # configured or the feed is absent - the rung is then dead).
        self.latency_pressure = float(latency_pressure)

    @property
    def stranded_tenants(self) -> List[str]:
        """Tenants a scale-in would disturb mid-flight: nonzero
        in-flight quota or ring residue (host backlog re-homes freely;
        published-but-unconsumed rows are the strand risk)."""
        if not self.tenants:
            return []
        return sorted(
            tid for tid, s in self.tenants.items()
            if float(s.get("in_flight", 0)) > 0
            or float(s.get("ring_residue", 0)) > 0
        )

    @property
    def backlog_per_device(self) -> float:
        """Mean READY backlog per device (+ any unconsumed inject rows):
        the actionable-work pressure the thresholds compare against.
        ``pending`` also counts dependency-blocked rows, which no amount
        of extra devices could run - deliberately not the signal."""
        if self.ndev <= 0:
            return 0.0
        return (sum(self.backlog) + self.inject_backlog) / self.ndev

    @classmethod
    def from_info(
        cls, ndev: int, info: Dict[str, Any], executed_before: int,
        slice_s: float,
        tenants: Optional[Dict[str, Dict[str, float]]] = None,
        latency_pressure: float = 0.0,
    ) -> "Observation":
        from ..device.megakernel import C_HEAD, C_TAIL

        counts = np.asarray(info["per_device_counts"])
        backlog = (counts[:, C_TAIL] - counts[:, C_HEAD]).tolist()
        quarantined = sorted({
            q for f in info.get("fault_stats", []) for q in f["quarantined"]
        })
        inj = 0
        ic = info.get("inject_ctl")
        if ic is not None:
            ic = np.asarray(ic)
            inj = int((ic[:, 0] - ic[:, 2]).sum())
        return cls(
            ndev=ndev, backlog=backlog, pending=int(info["pending"]),
            executed_delta=int(info["executed"]) - int(executed_before),
            inject_backlog=inj, quarantined=quarantined, slice_s=slice_s,
            tenants=tenants, latency_pressure=latency_pressure,
        )


class ScaleEvent:
    """One typed controller decision (every slice produces exactly one).

    ``kind``: ``scale_out`` | ``scale_in`` | ``evacuate`` | ``hold`` |
    ``checkpoint`` (preemption cut) | ``finish`` (workload drained).
    ``resize_latency_s`` is the full quiesced-state -> resumable-state
    cost of a resize (snapshot + reshard + state rebuild), the number
    ``bench.py --autoscale`` reports. ``cache_hit`` (resizes only):
    whether the target shape's program was already warm in the
    process-wide program cache (runtime/progcache.py), i.e. the resume
    pays zero trace/lower/compile work.
    """

    __slots__ = (
        "kind", "slice_idx", "t_ns", "from_ndev", "to_ndev", "reason",
        "backlog", "pending", "executed", "resize_latency_s",
        "cache_hit",
    )

    def __init__(
        self, kind: str, slice_idx: int, from_ndev: int, to_ndev: int,
        reason: str, backlog: int = 0, pending: int = 0, executed: int = 0,
        resize_latency_s: Optional[float] = None,
        cache_hit: Optional[bool] = None,
    ) -> None:
        if kind not in _KIND_CODES:
            raise ValueError(f"unknown ScaleEvent kind {kind!r}")
        self.kind = kind
        self.slice_idx = int(slice_idx)
        self.t_ns = time.monotonic_ns()
        self.from_ndev = int(from_ndev)
        self.to_ndev = int(to_ndev)
        self.reason = str(reason)
        self.backlog = int(backlog)
        self.pending = int(pending)
        self.executed = int(executed)
        self.resize_latency_s = resize_latency_s
        self.cache_hit = cache_hit

    @property
    def resized(self) -> bool:
        return self.from_ndev != self.to_ndev

    def as_dict(self) -> Dict[str, Any]:
        d = {k: getattr(self, k) for k in self.__slots__}
        return d

    def record(self, t: Optional[int] = None) -> List[int]:
        """The TR_SCALE flight-recorder row ([tag, t, a, b]): ``t``
        defaults to the control-slice index (callers spanning several
        run()s pass an ordinal instead - ring timebases must be
        monotonic), a packs (from << 8) | to, b the kind."""
        from ..device.tracebuf import TR_SCALE

        return [
            TR_SCALE, self.slice_idx if t is None else int(t),
            (self.from_ndev << 8) | self.to_ndev,
            _KIND_CODES[self.kind],
        ]

    def __repr__(self) -> str:
        arrow = (
            f" {self.from_ndev}->{self.to_ndev}" if self.resized else ""
        )
        return (
            f"<ScaleEvent {self.kind}{arrow} slice={self.slice_idx} "
            f"({self.reason})>"
        )


class AutoscalerPolicy:
    """The pure decision function: observation in, (target, kind, reason)
    out. Hysteresis and cooldown are the no-flap machinery:

    - scale OUT when mean ready backlog per device stays >=
      ``scale_out_backlog`` for ``hysteresis`` consecutive slices - OR
      (the LIVE-DELTA signal, ISSUE 13) when it RISES by >=
      ``scale_out_delta`` per slice while the executed rate is not
      rising, for the same streak: a storm is caught while it builds,
      not after it crosses the level threshold;
    - scale IN when it stays <= ``scale_in_backlog`` (and nothing is
      queued on the inject rings) for ``hysteresis`` slices - but NEVER
      while it would strand a tenant's in-flight quota or ring residue
      (``obs.tenants``): the refusal is a typed ``strand_hold`` event,
      and the streak stays armed so a drained mesh shrinks at the very
      next slice;
    - DEADLINE PRESSURE bypasses hysteresis AND cooldown: a tenant
      whose deadline budget drains by >= ``tenant_pressure`` (fraction
      of its budget) within one slice triggers an immediate
      ``deadline_out`` scale-out - the controller must beat the
      watchdog's strike ladder (budget exhaustion cancels the lane) to
      the punch, so this path has no flap guard, only the post-resize
      cooldown it sets;
    - SLO BURN (ISSUE 19) rides the same no-guard lane: an observation
      whose ``latency_pressure`` (max multi-window burn rate from
      ``runtime/slo.py``) reaches ``slo_burn`` triggers an immediate
      ``slo_out`` scale-out - the latency ladder's earliest rung,
      firing before tail latency converts into deadline-budget drain;
    - EVACUATION bypasses both too: a quarantined chip is resharded
      around at the first observation that names it - fault recovery
      must not wait out a flap guard. The target drops to the largest
      power of two that fits the survivors (the hypercube hop schedule
      is pof2-only).

    Thresholds default from ``HCLIB_TPU_AUTOSCALE_OUT`` /
    ``HCLIB_TPU_AUTOSCALE_IN`` (tasks per device),
    ``HCLIB_TPU_AUTOSCALE_OUT_DELTA`` (tasks per device per slice) and
    ``HCLIB_TPU_AUTOSCALE_TENANT_PRESSURE`` (budget fraction per
    slice; the new knobs raise on malformed text). The instance is
    stateful (streak/cooldown counters + the previous slice's levels
    the deltas difference against): use one per controlled mesh.
    """

    def __init__(
        self,
        min_devices: int = 1,
        max_devices: int = 8,
        scale_out_backlog: Optional[float] = None,
        scale_in_backlog: Optional[float] = None,
        hysteresis: int = 2,
        cooldown: int = 2,
        scale_out_delta: Optional[float] = None,
        tenant_pressure: Optional[float] = None,
        slo_burn: Optional[float] = None,
    ) -> None:
        if min_devices < 1 or _pof2_floor(min_devices) != min_devices:
            raise ValueError(
                f"min_devices must be a power of two >= 1, got {min_devices}"
            )
        if _pof2_floor(max_devices) != max_devices:
            raise ValueError(
                f"max_devices must be a power of two, got {max_devices}"
            )
        if max_devices < min_devices:
            raise ValueError("max_devices < min_devices")
        if hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        if cooldown < 0:
            raise ValueError("cooldown must be >= 0")
        self.min_devices = int(min_devices)
        self.max_devices = int(max_devices)

        def _envf(name: str, default: float) -> float:
            from .env import env_float

            return env_float(name, default, malformed=default)

        self.scale_out_backlog = (
            _envf("HCLIB_TPU_AUTOSCALE_OUT", 32.0)
            if scale_out_backlog is None else float(scale_out_backlog)
        )
        self.scale_in_backlog = (
            _envf("HCLIB_TPU_AUTOSCALE_IN", 2.0)
            if scale_in_backlog is None else float(scale_in_backlog)
        )
        if self.scale_in_backlog >= self.scale_out_backlog:
            raise ValueError(
                f"scale_in_backlog ({self.scale_in_backlog}) must be < "
                f"scale_out_backlog ({self.scale_out_backlog}): an "
                "overlapping band would oscillate by construction"
            )
        # The live-delta knobs (new in ISSUE 13) parse with RAISE
        # semantics: a typo'd threshold must not silently change the
        # elasticity policy.
        from .env import env_float

        self.scale_out_delta = (
            env_float("HCLIB_TPU_AUTOSCALE_OUT_DELTA", 8.0)
            if scale_out_delta is None else float(scale_out_delta)
        )
        if self.scale_out_delta <= 0:
            raise ValueError(
                f"scale_out_delta must be > 0, got {self.scale_out_delta}"
            )
        self.tenant_pressure = (
            env_float("HCLIB_TPU_AUTOSCALE_TENANT_PRESSURE", 0.25)
            if tenant_pressure is None else float(tenant_pressure)
        )
        if not 0 < self.tenant_pressure <= 1:
            raise ValueError(
                f"tenant_pressure must be in (0, 1], got "
                f"{self.tenant_pressure} (it is a fraction of the "
                "tenant's deadline budget drained per slice)"
            )
        # The SLO burn rung (ISSUE 19): raise semantics for the same
        # reason as the live-delta knobs.
        self.slo_burn = (
            env_float("HCLIB_TPU_SLO_BURN", 2.0)
            if slo_burn is None else float(slo_burn)
        )
        if self.slo_burn <= 0:
            raise ValueError(
                f"slo_burn must be > 0, got {self.slo_burn}"
            )
        self.hysteresis = int(hysteresis)
        self.cooldown = int(cooldown)
        self._out_streak = 0
        self._in_streak = 0
        self._cooling = 0
        # Previous-slice levels the delta signals difference against
        # (None until the first observation lands).
        self._prev_per_dev: Optional[float] = None
        self._prev_rate: Optional[float] = None
        self._prev_expired: Optional[Dict[str, float]] = None

    def reset(self) -> None:
        self._out_streak = self._in_streak = self._cooling = 0
        self._prev_per_dev = self._prev_rate = None
        self._prev_expired = None

    def _resized(self) -> None:
        self._out_streak = self._in_streak = 0
        self._cooling = self.cooldown

    def _roll_deltas(self, obs: Observation):
        """Advance the previous-slice levels and return this slice's
        delta signals: (backlog_delta, rate_delta, worst_drain,
        worst_tenant). Every decide() path must pass through here
        exactly once, or the deltas would stretch across skipped
        slices."""
        per_dev = obs.backlog_per_device
        rate = (
            obs.executed_delta / obs.slice_s if obs.slice_s > 0 else None
        )
        backlog_delta = (
            None if self._prev_per_dev is None
            else per_dev - self._prev_per_dev
        )
        rate_delta = (
            None if rate is None or self._prev_rate is None
            else rate - self._prev_rate
        )
        drain, worst = 0.0, None
        if obs.tenants:
            prev = self._prev_expired
            for tid, s in obs.tenants.items():
                budget = float(s.get("budget") or 0)
                if budget <= 0:
                    continue
                if prev is None:
                    # First observation: no baseline, no drain - a
                    # resumed deployment's cumulative expiry count must
                    # not read as a fresh storm.
                    continue
                d = (
                    float(s.get("expired", 0)) - prev.get(tid, 0.0)
                ) / budget
                if d > drain:
                    drain, worst = d, tid
        self._prev_per_dev = per_dev
        if rate is not None:
            self._prev_rate = rate
        if obs.tenants is not None:
            self._prev_expired = {
                tid: float(s.get("expired", 0))
                for tid, s in obs.tenants.items()
            }
        return backlog_delta, rate_delta, drain, worst

    def decide(self, obs: Observation):
        """-> (target_ndev, kind, reason). ``target == obs.ndev`` means
        hold (kind names why)."""
        backlog_delta, rate_delta, drain, worst = self._roll_deltas(obs)
        # Fault first: reshard around quarantined chips immediately.
        if obs.quarantined:
            survivors = obs.ndev - len(obs.quarantined)
            target = max(self.min_devices, _pof2_floor(survivors))
            if target < obs.ndev:
                self._resized()
                return (
                    target, "evacuate",
                    f"quarantined chip(s) {list(obs.quarantined)}: "
                    f"{survivors} survivors -> {target} devices",
                )
            return (
                obs.ndev, "hold",
                f"quarantined {list(obs.quarantined)} but already at "
                f"min_devices={self.min_devices} (watchdog owns this)",
            )
        # Deadline pressure next, BEFORE the cooldown gate: a tenant
        # burning its budget must scale out before the watchdog's
        # strike ladder (budget exhaustion -> lane cancel) fires, and a
        # flap guard is exactly the latency that would lose that race.
        if (
            drain >= self.tenant_pressure
            and obs.ndev < self.max_devices
        ):
            target = min(obs.ndev * 2, self.max_devices)
            self._resized()
            return (
                target, "deadline_out",
                f"tenant {worst!r} deadline budget draining "
                f"({drain:.0%}/slice >= {self.tenant_pressure:.0%}): "
                "scale out before the watchdog strikes",
            )
        # SLO burn (ISSUE 19) shares the no-flap-guard contract: a
        # breaching burn rate means the latency error budget drains
        # NOW, and the scale-out must land before the tail breaches
        # hard enough to trip the deadline-budget rung above (or the
        # watchdog behind it). Only the post-resize cooldown it sets
        # gates repeats.
        if (
            obs.latency_pressure >= self.slo_burn
            and obs.ndev < self.max_devices
        ):
            target = min(obs.ndev * 2, self.max_devices)
            self._resized()
            return (
                target, "slo_out",
                f"latency burn {obs.latency_pressure:.2f} >= "
                f"{self.slo_burn:g}: SLO error budget draining",
            )
        if self._cooling > 0:
            self._cooling -= 1
            return obs.ndev, "hold", f"cooldown ({self._cooling + 1} left)"
        per_dev = obs.backlog_per_device
        hot_level = per_dev >= self.scale_out_backlog
        # The delta arm: backlog RISING while the executed rate is not -
        # extra devices will absorb the rise; a rising rate means the
        # mesh is still ramping and levels should decide.
        hot_delta = (
            backlog_delta is not None
            and backlog_delta >= self.scale_out_delta
            and (rate_delta is None or rate_delta <= 0)
        )
        if (hot_level or hot_delta) and obs.ndev < self.max_devices:
            self._out_streak += 1
            self._in_streak = 0
            if self._out_streak >= self.hysteresis:
                target = min(obs.ndev * 2, self.max_devices)
                self._resized()
                why = (
                    f"backlog {per_dev:.1f}/dev >= "
                    f"{self.scale_out_backlog:g}"
                    if hot_level else
                    f"backlog rising {backlog_delta:+.1f}/dev/slice >= "
                    f"{self.scale_out_delta:g} with rate flat"
                )
                return (
                    target, "scale_out",
                    f"{why} for {self.hysteresis} slices",
                )
            return (
                obs.ndev, "hold",
                f"backlog high ({per_dev:.1f}/dev"
                + (f", {backlog_delta:+.1f}/slice" if hot_delta else "")
                + f"), streak {self._out_streak}/{self.hysteresis}",
            )
        if (
            per_dev <= self.scale_in_backlog
            and obs.inject_backlog == 0
            and obs.ndev > self.min_devices
        ):
            self._in_streak += 1
            self._out_streak = 0
            if self._in_streak >= self.hysteresis:
                stranded = obs.stranded_tenants
                if stranded:
                    # Typed refusal, streak left armed: the mesh shrinks
                    # at the first slice whose residue has drained.
                    self._in_streak = self.hysteresis
                    return (
                        obs.ndev, "strand_hold",
                        f"scale-in refused: would strand in-flight "
                        f"rows of tenant(s) {stranded}",
                    )
                target = max(obs.ndev // 2, self.min_devices)
                self._resized()
                return (
                    target, "scale_in",
                    f"backlog {per_dev:.1f}/dev <= "
                    f"{self.scale_in_backlog:g} for "
                    f"{self.hysteresis} slices",
                )
            return (
                obs.ndev, "hold",
                f"backlog low ({per_dev:.1f}/dev), streak "
                f"{self._in_streak}/{self.hysteresis}",
            )
        self._out_streak = self._in_streak = 0
        return obs.ndev, "hold", f"backlog {per_dev:.1f}/dev in band"


class Autoscaler:
    """The control loop. ``make_kernel(ndev)`` builds the ResidentKernel
    for a mesh size (its Megakernel MUST be built ``checkpoint=True`` -
    the quiesce word is the slicing mechanism); the same kernel-table
    shape must come back for every size (restore validates it). A
    factory that places meshes on REAL devices should also accept a
    ``quarantined=`` keyword (a frozenset of evacuated flat device ids,
    cumulative across the deployment) and build the mesh around those
    chips - the controller passes it whenever the factory's signature
    admits it, so a later scale-out cannot resurrect a chip it already
    evacuated. (The interpret-mode tests, whose devices are virtual,
    ignore it.)

    ``slice_rounds`` is the control interval in exchange rounds: each
    slice runs at most that many rounds, then quiesces for an
    observation. ``metrics`` (a MetricsRegistry) receives every decision
    under ``autoscale`` plus a live gauge source ``autoscale.state``
    (call ``close()`` to unregister it when retiring a controller whose
    registry outlives it); ``checkpoint_dir`` arms the preemption path
    (the quiesced state is saved there when a preemption notice arrives
    between slices).

    A resize the bundle refuses (per-device data buffers, waits whose
    satisfier sits in unexported host residue, an overfull target)
    downgrades to a hold - the mesh keeps serving on its current size
    and resize attempts back off for ``policy.cooldown`` slices -
    instead of killing the loop.

    No controller thread: the loop runs on the calling thread, slicing
    the mesh via quiesce - the off-path (not using this class) is
    exactly PR 5 behavior.
    """

    def __init__(
        self,
        make_kernel: Callable[..., Any],
        policy: Optional[AutoscalerPolicy] = None,
        *,
        slice_rounds: int = 64,
        max_slices: int = 1 << 10,
        metrics=None,
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        if slice_rounds < 1:
            raise ValueError("slice_rounds must be >= 1")
        self.make_kernel = make_kernel
        self.policy = policy or AutoscalerPolicy()
        self.slice_rounds = int(slice_rounds)
        self.max_slices = int(max_slices)
        self.metrics = metrics
        if checkpoint_dir is None:
            # The env-configured store root arms the preemption path
            # without a code change (HCLIB_TPU_CKPT_DIR).
            from .env import env_str

            checkpoint_dir = env_str("HCLIB_TPU_CKPT_DIR")
        self.checkpoint_dir = checkpoint_dir
        self._store: Optional[BundleStore] = None
        self.events: List[ScaleEvent] = []
        self.ndev: Optional[int] = None
        self.quarantined: frozenset = frozenset()
        self._kernels: Dict[Any, Any] = {}
        self._refusal_backoff = 0
        self._t0_ns = self._t1_ns = time.monotonic_ns()
        if metrics is not None:
            metrics.register("autoscale.state", self._gauges)

    def close(self) -> None:
        """Retire the controller: unregister the live gauge source so a
        long-lived registry does not keep this instance (and its cached
        compiled kernels) alive."""
        if self.metrics is not None:
            self.metrics.unregister("autoscale.state")

    # -- wiring --

    def _gauges(self) -> Dict[str, Any]:
        by_kind: Dict[str, int] = {}
        for e in self.events:
            by_kind[e.kind] = by_kind.get(e.kind, 0) + 1
        return {
            "ndev": self.ndev or 0,
            "events": len(self.events),
            "resizes": sum(1 for e in self.events if e.resized),
            **{f"kind.{k}": v for k, v in by_kind.items()},
        }

    def _kernel_for(self, ndev: int):
        key = (ndev, self.quarantined)
        rk = self._kernels.get(key)
        if rk is None:
            # Factories that accept quarantined= get the cumulative
            # evacuation history, so a scale-out after an evacuation
            # builds around the dead chips instead of resurrecting them.
            import inspect

            try:
                params = inspect.signature(self.make_kernel).parameters
                takes_q = "quarantined" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()
                )
            except (TypeError, ValueError):
                takes_q = False
            rk = (
                self.make_kernel(ndev, quarantined=self.quarantined)
                if takes_q else self.make_kernel(ndev)
            )
            if not getattr(rk.mk, "checkpoint", False):
                raise ValueError(
                    "Autoscaler needs make_kernel(ndev) to build its "
                    "Megakernel with checkpoint=True: quiesce is the "
                    "control-slice mechanism"
                )
            if rk.ndev != ndev:
                raise ValueError(
                    f"make_kernel({ndev}) returned a {rk.ndev}-device "
                    "kernel"
                )
            self._kernels[key] = rk
        return rk

    def _bundle_store(self) -> Optional[BundleStore]:
        """The durable store rooted at ``checkpoint_dir`` (lazily built
        so an unused dir knob costs nothing): the preempt hook WRITES
        THROUGH it - generational publish, crash-safe, retention-pruned
        - instead of scattering loose timestamped bundle dirs."""
        if self._store is None and self.checkpoint_dir:
            self._store = BundleStore(
                self.checkpoint_dir, metrics=self.metrics
            )
        return self._store

    def _event(self, ev: ScaleEvent) -> ScaleEvent:
        self.events.append(ev)
        self._t1_ns = time.monotonic_ns()
        if self.metrics is not None:
            rec = {
                k: v for k, v in ev.as_dict().items()
                if isinstance(v, (int, float)) and v is not None
            }
            self.metrics.record_event(f"autoscale.{ev.kind}", rec)
        return ev

    def trace_info(self) -> Dict[str, Any]:
        """The controller's decisions in the flight-recorder ABI (one
        host ring of TR_SCALE records; the timebase is the event
        ordinal, monotonic even across several run()s on one
        controller) - feed it to ``tools/timeline.py --perfetto`` (or
        ``export_perfetto(traces=[...])``) next to device traces."""
        from ..device.tracebuf import host_trace_info

        return host_trace_info(
            [e.record(t=i) for i, e in enumerate(self.events)],
            self._t0_ns, max(self._t1_ns, self._t0_ns + 1),
        )

    # -- the loop --

    def run(
        self,
        builders: Optional[Sequence[Any]] = None,
        *,
        resume_bundle=None,
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        waits: Optional[Sequence[Sequence]] = None,
        inject_rows: Optional[Sequence[Sequence]] = None,
        quantum: int = 8,
        max_rounds: int = 1 << 14,
        tenant_table=None,
    ):
        """Serve ``builders`` (one per starting device) - or continue a
        saved ``resume_bundle`` (a resident CheckpointBundle, a bundle
        dir, a ``BundleStore`` - or a store ROOT dir, walked with the
        self-healing ``load_latest``) - to completion under the policy.
        Returns ``(ivalues, data, info)`` of the final slice, with
        ``info['scale_events']`` (every typed
        decision) and ``info['ndev_final']`` attached; a preemption
        notice instead returns early with ``info['preempted'] = True``
        and (with ``checkpoint_dir``) ``info['bundle_path']``.

        Result contract across resizes: per-device accumulator slots and
        executed counters fold by sum at every reshard (the
        ``migratable_fns`` contract), so summed ivalues and executed
        totals are invariant - the storm soak asserts them bit-equal to
        an uninterrupted run's.

        ``tenant_table`` (mesh-tenancy runs, device/tenants.py): the
        ``MeshTenantTable`` fronting the mesh. It is passed through to
        every slice's ``rk.run`` (the table pumps/absorbs the per-device
        rings + tctl blocks), its ``pressure()`` feed rides every
        Observation (so the policy sees per-tenant backlog and
        deadline-budget drain), and a resize swaps in a fresh
        ``resized(M)`` table - lane state rides the resharded bundle,
        never the table object, so per-tenant counts conserve across
        every cut by the same mechanism the single-device stream uses."""
        if (builders is None) == (resume_bundle is None):
            raise ValueError(
                "run() wants exactly one of builders= or resume_bundle="
            )
        run_base = len(self.events)  # this run's slice of the event log
        if run_base == 0:
            self._t0_ns = time.monotonic_ns()
        if resume_bundle is not None:
            if isinstance(resume_bundle, CheckpointBundle):
                b = resume_bundle
            elif isinstance(resume_bundle, BundleStore):
                # Self-healing restore: the newest generation that
                # validates (corrupt ones quarantined); unrecoverable
                # stores raise so the caller poisons futures instead
                # of hanging.
                b = resume_bundle.load_latest()
            elif isinstance(resume_bundle, str) and not os.path.exists(
                os.path.join(resume_bundle, "manifest.json")
            ):
                # A directory that is not itself a bundle is a STORE
                # root (what checkpoint_dir now writes): walk its
                # generations. Covers empty/missing dirs too - the
                # store raises its every-fault diagnostic.
                b = BundleStore(
                    resume_bundle, metrics=self.metrics
                ).load_latest()
            else:
                b = CheckpointBundle.load(resume_bundle)
            if b.kind != "resident":
                raise CheckpointError(
                    f"Autoscaler.run got a {b.kind!r} bundle"
                )
            ndev = int(b.meta.get("ndev", b.arrays["tasks"].shape[0]))
            target = min(
                max(ndev, self.policy.min_devices), self.policy.max_devices
            )
            if target != ndev:
                try:
                    b = b.reshard(target)
                    ndev = target
                except CheckpointError:
                    # The bundle cannot legally re-home into the policy
                    # band (data buffers, host-residue waits, overfull
                    # target): resume at its original size and let the
                    # policy resize later, instead of dying at restart.
                    pass
            state: Optional[Dict[str, Any]] = b.state()
        else:
            ndev = len(builders)
            state = None
        self.ndev = ndev
        rk = self._kernel_for(ndev)
        executed_before = 0
        iv = data_o = info = None
        tkw = {} if tenant_table is None else {
            "tenant_table": tenant_table
        }
        for slice_idx in range(self.max_slices):
            t0 = time.monotonic()
            if state is None:
                iv, data_o, info = rk.run(
                    builders, data=data, ivalues=ivalues, waits=waits,
                    inject_rows=inject_rows, quantum=quantum,
                    max_rounds=max_rounds, quiesce=self.slice_rounds,
                    **tkw,
                )
            else:
                iv, data_o, info = rk.run(
                    resume_state=state, quantum=quantum,
                    max_rounds=max_rounds, quiesce=self.slice_rounds,
                    **tkw,
                )
            slice_s = time.monotonic() - t0
            if not info.get("quiesced"):
                # Drained (or aborted): the loop's terminal state.
                self._event(ScaleEvent(
                    "finish", slice_idx, rk.ndev, rk.ndev,
                    "aborted" if info.get("aborted") else
                    "workload drained",
                    pending=int(info["pending"]),
                    executed=int(info["executed"]),
                ))
                break
            obs = Observation.from_info(
                rk.ndev, info, executed_before, slice_s,
                tenants=(
                    None if tenant_table is None
                    else tenant_table.pressure()
                ),
            )
            executed_before = int(info["executed"])
            if self.metrics is not None:
                # The slice's run info lands in the registry (minus the
                # state arrays), so dashboards read the same backlog /
                # fault / tier signals the policy just decided on.
                self.metrics.add_run_info(
                    "autoscale.slice",
                    {k: v for k, v in info.items() if k != "state"},
                )
            if resilience.preempt_requested():
                # Checkpoint, then stop - the PR 5 preemption semantics,
                # now holding the WHOLE autoscaled deployment.
                bundle = snapshot_resident(rk, info)
                path = None
                store = self._bundle_store()
                if store is not None:
                    gen = store.save(bundle)
                    path = store.path_of(gen)
                    info["bundle_generation"] = gen
                self._event(ScaleEvent(
                    "checkpoint", slice_idx, rk.ndev, rk.ndev,
                    "preemption notice: checkpointed and stopped",
                    backlog=sum(obs.backlog), pending=obs.pending,
                    executed=executed_before,
                ))
                info["preempted"] = True
                info["bundle"] = bundle
                if path:
                    info["bundle_path"] = path
                break
            target, kind, reason = self.policy.decide(obs)
            if (
                self._refusal_backoff > 0
                and target != rk.ndev
                and kind != "evacuate"
            ):
                # A recent resize was refused by the bundle; keep
                # serving on the current size until the backoff drains
                # (retrying every slice would pay a futile snapshot +
                # reshard each time). EVACUATION is exempt - the
                # no-gates contract: a dead chip reshard-around is
                # attempted at every observation that names it.
                self._refusal_backoff -= 1
                target, kind = rk.ndev, "hold"
                reason = f"resize backoff after refusal ({reason})"
            if target != rk.ndev:
                t0r = time.monotonic()
                try:
                    bundle = snapshot_resident(rk, info).reshard(target)
                except CheckpointError as e:
                    # The quiesced state cannot legally re-home (data
                    # buffers, pending waits, overfull target): serving
                    # beats dying - downgrade to a hold that names the
                    # refusal and back off further attempts.
                    self._refusal_backoff = max(1, self.policy.cooldown)
                    state = info["state"]
                    self._event(ScaleEvent(
                        "hold", slice_idx, obs.ndev, obs.ndev,
                        f"{kind} {obs.ndev}->{target} refused: {e}",
                        backlog=sum(obs.backlog), pending=obs.pending,
                        executed=executed_before,
                    ))
                else:
                    self._refusal_backoff = 0  # a legal resize resets it
                    if kind == "evacuate":
                        self.quarantined = self.quarantined | frozenset(
                            obs.quarantined
                        )
                    rk = self._kernel_for(target)
                    # Before the next slice triggers the (re)build:
                    # warm means the target shape's program is already
                    # in this kernel's jit table or the process-wide
                    # program cache, so the resume traces nothing.
                    cache_hit = rk.program_cached(
                        quantum=quantum, max_rounds=max_rounds,
                    )
                    state = bundle.state()
                    self.ndev = target
                    if tenant_table is not None:
                        # Fresh table, same roster: residue + counters
                        # ride the resharded bundle state, which the
                        # next slice's run feeds to resume_from.
                        tenant_table = tenant_table.resized(target)
                        tkw = {"tenant_table": tenant_table}
                    self._event(ScaleEvent(
                        kind, slice_idx, obs.ndev, target, reason,
                        backlog=sum(obs.backlog), pending=obs.pending,
                        executed=executed_before,
                        resize_latency_s=round(
                            time.monotonic() - t0r, 6
                        ),
                        cache_hit=cache_hit,
                    ))
            else:
                state = info["state"]
                self._event(ScaleEvent(
                    kind, slice_idx, obs.ndev, obs.ndev, reason,
                    backlog=sum(obs.backlog), pending=obs.pending,
                    executed=executed_before,
                ))
        else:
            from .resilience import StallError

            raise StallError(
                f"autoscaler exceeded max_slices={self.max_slices} with "
                f"{info['pending'] if info else '?'} pending",
                stats={"events": [e.as_dict() for e in self.events]},
            )
        self._t1_ns = time.monotonic_ns()
        # THIS run's decisions only: a controller reused across runs
        # (checkpoint -> resume_bundle) keeps the full log in
        # self.events / trace_info(), but per-run consumers (bench,
        # the storm assertions) must not see a previous run's events.
        info["scale_events"] = [
            e.as_dict() for e in self.events[run_base:]
        ]
        info["ndev_final"] = rk.ndev
        if self.metrics is not None:
            self.metrics.record("autoscale", self._gauges())
        return iv, data_o, info
