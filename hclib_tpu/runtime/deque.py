"""Work-stealing deque.

The reference uses a fixed-capacity Chase-Lev-style circular deque with CAS
steals (src/hclib-deque.c:75-139, src/inc/hclib-deque.h). Under CPython the
GIL serializes bytecode anyway, so this host-side deque keeps the same *API
shape* (owner pushes/pops at the tail, thieves take from the head) over a
lock-protected ring; the lock-free protocol lives where it matters - in the
device queues (device/queue.py) and the C++ native runtime (native/).

Unlike the reference, which statically allocates 2^20 slots and asserts on
overflow (src/hclib-runtime.c:520-524), this deque grows on demand.
"""

from __future__ import annotations

import threading
from collections import deque as _pydeque
from typing import Any, Optional

__all__ = ["WSDeque"]


class WSDeque:
    __slots__ = ("_lock", "_items")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: _pydeque = _pydeque()

    def push(self, item: Any) -> bool:
        """Owner-side push at the tail."""
        with self._lock:
            self._items.append(item)
        return True

    def pop(self) -> Optional[Any]:
        """Owner-side pop at the tail (LIFO: depth-first own work)."""
        with self._lock:
            if self._items:
                return self._items.pop()
        return None

    def steal(self) -> Optional[Any]:
        """Thief-side take from the head (FIFO: steal the oldest/biggest)."""
        with self._lock:
            if self._items:
                return self._items.popleft()
        return None

    def __len__(self) -> int:
        return len(self._items)
