"""Finish scopes.

The reference tracks a finish as {parent, counter, finish_dep} where the
counter counts outstanding child tasks plus one for the spawning task
(src/inc/hclib-finish.h:6-10, src/hclib-runtime.c:1219-1247). Here the lock
makes the +1 trick unnecessary: ``counter`` counts outstanding children only,
and reaching zero fires the completion promise / parked-context event
(reference equivalent: promise-put on finish_dep at src/hclib-runtime.c:437-446).
"""

from __future__ import annotations

import threading
from typing import Optional

from .promise import Promise
from .resilience import CancelScope

__all__ = ["Finish"]


class Finish:
    __slots__ = ("parent", "_lock", "counter", "on_zero", "_zero_event",
                 "scope")

    def __init__(self, parent: Optional["Finish"] = None) -> None:
        self.parent = parent
        self._lock = threading.Lock()
        self.counter = 0
        # Promise satisfied when the scope drains (nonblocking finish /
        # escaping continuation), cf. finish_dep.
        self.on_zero: Optional[Promise] = None
        self._zero_event: Optional[threading.Event] = None
        # Cancellation chains along the finish tree (resilience.py):
        # cancelling a scope cancels every descendant by inheritance.
        self.scope = CancelScope(
            parent=None if parent is None else parent.scope
        )

    def check_in(self) -> None:
        """A child task is spawned under this scope (check_in_finish)."""
        with self._lock:
            self.counter += 1

    def check_out(self) -> None:
        """A child task completed (check_out_finish)."""
        with self._lock:
            self.counter -= 1
            if self.counter != 0:
                return
            on_zero, event = self.on_zero, self._zero_event
            self.on_zero, self._zero_event = None, None
        if on_zero is not None:
            on_zero.put(None)
        if event is not None:
            event.set()

    def quiesced(self) -> bool:
        return self.counter == 0

    def arm_event(self) -> Optional[threading.Event]:
        """Arm a parked-context event; returns None if already quiescent.

        A cached event that is already set (a cancel-wake sets parked
        events spuriously; waiters re-check and re-park) is replaced with
        a fresh one, so a spurious set can never turn later parks into a
        busy spin."""
        with self._lock:
            if self.counter == 0:
                return None
            if self._zero_event is None or self._zero_event.is_set():
                self._zero_event = threading.Event()
            return self._zero_event

    def arm_promise(self) -> Optional[Promise]:
        """Attach a completion promise; returns None if already quiescent
        (caller should treat the scope as complete immediately)."""
        with self._lock:
            if self.counter == 0:
                return None
            if self.on_zero is None:
                self.on_zero = Promise()
            return self.on_zero
