"""Finish scopes.

The reference tracks a finish as {parent, counter, finish_dep} where the
counter counts outstanding child tasks plus one for the spawning task
(src/inc/hclib-finish.h:6-10, src/hclib-runtime.c:1219-1247). Here the lock
makes the +1 trick unnecessary: ``counter`` counts outstanding children only,
and reaching zero fires the completion promise / parked-context event
(reference equivalent: promise-put on finish_dep at src/hclib-runtime.c:437-446).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from .promise import Promise
from .resilience import CancelScope

__all__ = ["Finish"]


class Finish:
    __slots__ = ("parent", "_lock", "counter", "on_zero", "_zero_events",
                 "scope")

    def __init__(self, parent: Optional["Finish"] = None) -> None:
        self.parent = parent
        self._lock = threading.Lock()
        self.counter = 0
        # Promise satisfied when the scope drains (nonblocking finish /
        # escaping continuation), cf. finish_dep.
        self.on_zero: Optional[Promise] = None
        # Parked-context waiters, one CALLER-OWNED event each (the
        # Promise._ctx_waiters shape). A shared cached event was a trap:
        # run_on_main wakes a parked main thread by setting its park
        # event, and setting a SHARED finish event both woke every other
        # waiter on that scope and (before arm_event grew its is-set
        # check) left the cached event permanently set while counter > 0,
        # degrading every later park on the scope into a busy spin
        # (ADVICE r5 medium). Per-caller events make a targeted set()
        # reach exactly one park, with nothing cached to poison.
        self._zero_events: List[threading.Event] = []
        # Cancellation chains along the finish tree (resilience.py):
        # cancelling a scope cancels every descendant by inheritance.
        self.scope = CancelScope(
            parent=None if parent is None else parent.scope
        )

    def check_in(self) -> None:
        """A child task is spawned under this scope (check_in_finish)."""
        with self._lock:
            self.counter += 1

    def check_out(self) -> None:
        """A child task completed (check_out_finish)."""
        with self._lock:
            self.counter -= 1
            if self.counter != 0:
                return
            on_zero, events = self.on_zero, self._zero_events
            self.on_zero, self._zero_events = None, []
        if on_zero is not None:
            on_zero.put(None)
        for event in events:
            event.set()

    def quiesced(self) -> bool:
        return self.counter == 0

    def register_event(self, event: threading.Event) -> bool:
        """Register a caller-owned parked-context event, set once at
        quiescence. Returns False when already quiescent (caller should
        not park). Callers that abandon the park (timeout, cancellation,
        spurious wake) must ``unregister_event`` so repeated parks on a
        long-lived scope don't accumulate dead waiters."""
        with self._lock:
            if self.counter == 0:
                return False
            self._zero_events.append(event)
            return True

    def unregister_event(self, event: threading.Event) -> None:
        """Withdraw a parked-context waiter that gave up."""
        with self._lock:
            try:
                self._zero_events.remove(event)
            except ValueError:
                pass

    def arm_promise(self) -> Optional[Promise]:
        """Attach a completion promise; returns None if already quiescent
        (caller should treat the scope as complete immediately)."""
        with self._lock:
            if self.counter == 0:
                return None
            if self.on_zero is None:
                self.on_zero = Promise()
            return self.on_zero
