"""Task representation.

Mirrors the reference task struct (inc/hclib-task.h:32-44): a function, its
arguments, the owning finish scope, an ordered dependency list with a
registration cursor, a target locale, and a ``non_blocking`` promise that the
task never suspends (letting it run inline on any context -
src/hclib-runtime.c:673-693).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

__all__ = ["Task"]


class Task:
    __slots__ = (
        "fn",
        "args",
        "kwargs",
        "finish",
        "waiting_on",
        "wait_index",
        "locale",
        "non_blocking",
        "result_promise",
        "retry",
        "attempt",
    )

    def __init__(
        self,
        fn: Callable[..., Any],
        args: Sequence[Any] = (),
        kwargs: Optional[dict] = None,
        finish: Any = None,
        waiting_on: Sequence[Any] = (),
        locale: Any = None,
        non_blocking: bool = False,
        result_promise: Any = None,
        retry: Any = None,
    ) -> None:
        self.fn = fn
        self.args = tuple(args)
        self.kwargs = kwargs or {}
        self.finish = finish
        # Futures this task depends on; registration walks them in order,
        # one unsatisfied promise at a time (src/hclib-promise.c:171-195).
        self.waiting_on = list(waiting_on)
        self.wait_index = 0
        self.locale = locale
        self.non_blocking = non_blocking
        # When set, the task's return value is put() here on completion
        # (hclib_async_future trampoline, src/hclib.c:59-81).
        self.result_promise = result_promise
        # Resilience (runtime/resilience.py): optional RetryPolicy and the
        # 0-based execution attempt. Execution itself lives in the
        # scheduler (Runtime._run_task_body), the ONE place that handles
        # result-promise put/poison, cancellation skip, retry, and
        # quarantine.
        self.retry = retry
        self.attempt = 0

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        name = getattr(self.fn, "__name__", repr(self.fn))
        return f"<Task {name} deps={len(self.waiting_on)} locale={self.locale}>"
