"""Host-side work-stealing scheduler.

Architecture (re-designed from the reference, not translated):

The reference binds one pthread per worker and uses stackful fibers (LiteCtx)
so a *context* that blocks in end-finish/future-wait can be swapped out while
the worker keeps executing tasks (src/hclib-runtime.c:912-945, 1067-1119).
Python has no cheap fibers, so this runtime inverts the binding: there are
``nworkers`` fixed worker *identities* (each owning its deques, paths, and
stats), and a dynamic pool of OS threads that bind to identities. When an
execution context blocks, it releases its identity - a spare thread picks the
identity up and keeps draining deques, so the effective worker count stays
constant. When the context is resumed it re-acquires an identity, *possibly a
different one* - mirroring the reference, where a resumed continuation may run
on a different worker (src/hclib-runtime.c:1272-1275).

Blocking follows the reference's help-first policy (src/hclib-runtime.c:
646-694): before parking, a blocked context runs tasks inline when safe - a
task is inline-safe if it is non-blocking or belongs to the finish scope being
awaited. A popped task that is not inline-safe is pushed back and the context
parks (the reference instead swaps to a fresh fiber seeded with that task -
same effect: the task runs on another context, the blocked stack sleeps).

This host runtime is the semantic model for the TPU device scheduler
(device/megakernel.py), where worker identities become TPU cores, deques
become HBM descriptor rings, and parked contexts become re-enqueued
continuation descriptors.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import env, resilience
from .deque import WSDeque
from .finish import Finish
from .locality import Locale, LocalityGraph, generate_default_graph, load_locality_file
from .promise import Future, Promise
from .resilience import (
    CancelledError,
    FaultPlan,
    RetryPolicy,
    StallError,
)
from .task import Task

__all__ = [
    "Runtime",
    "current_runtime",
    "launch",
    "async_",
    "async_future",
    "finish",
    "start_finish",
    "end_finish",
    "end_finish_nonblocking",
    "yield_",
    "current_worker",
    "num_workers",
]

_THREAD_STACK = 1 << 21  # 2 MB: room for deep inline help recursion
_MAX_THREADS = 4096
# Quarantine keeps at most this many terminal-failure records (plus a
# total count) so a hot poison task can't grow stats without bound.
_QUARANTINE_KEEP = 32


class _Context(threading.local):
    """Per-thread execution context (what the reference keeps in the worker
    struct + current fiber: inc/hclib-rt.h:80-111)."""

    identity: Optional[int] = None
    current_finish: Optional[Finish] = None
    current_task: Optional[Task] = None
    runtime: Optional["Runtime"] = None


_tls = _Context()
_global_runtime: Optional["Runtime"] = None


def current_runtime() -> "Runtime":
    rt = _tls.runtime or _global_runtime
    if rt is None:
        raise RuntimeError("no active hclib_tpu runtime; call inside launch()")
    return rt


class _WorkerStats:
    __slots__ = ("executed", "spawned", "steals", "parks", "yields", "stolen_from")

    def __init__(self, nworkers: int) -> None:
        self.executed = 0
        self.spawned = 0
        self.steals = 0
        self.parks = 0
        self.yields = 0
        # steal matrix row (reference HCLIB_STATS: src/hclib-runtime.c:83-104)
        self.stolen_from = [0] * nworkers


class _IdentityManager:
    """Hands worker identities to threads. Resumed contexts (priority) beat
    generic pool threads so program state is never starved of a worker."""

    def __init__(self, nworkers: int, on_priority_wait=None) -> None:
        self._cv = threading.Condition()
        self._free: List[int] = list(range(nworkers))
        self._priority_waiters = 0
        self._normal_waiters = 0
        self._shutdown = False
        self.has_priority_waiter = False  # racy read is fine; checked under lock on release
        # Pokes the runtime's work condvar so idle workers wake, see the
        # priority waiter, and hand their identity over - event-driven
        # instead of the workers' idle poll discovering it.
        self._on_priority_wait = on_priority_wait

    def acquire(self, priority: bool) -> Optional[int]:
        if priority:
            # Flag FIRST, then wake: a worker woken by the notify must see
            # the waiter (the flag's racy read is already the protocol);
            # flag-after-notify would let it re-park for the full timeout.
            self.has_priority_waiter = True
            if self._on_priority_wait is not None:
                # Called before taking our lock (no lock-order coupling
                # with the runtime's condvar); harmless when an identity
                # is free.
                self._on_priority_wait()
        with self._cv:
            if priority:
                self._priority_waiters += 1
                self.has_priority_waiter = True
            else:
                self._normal_waiters += 1
            try:
                while True:
                    if self._shutdown and not priority:
                        return None
                    if self._free and (priority or self._priority_waiters == 0):
                        return self._free.pop()
                    # Every wake path notifies (release, shutdown, last
                    # priority waiter leaving); the timeout is a safety
                    # net, not the latency floor.
                    self._cv.wait(1.0)
            finally:
                if priority:
                    self._priority_waiters -= 1
                    self.has_priority_waiter = self._priority_waiters > 0
                    if self._priority_waiters == 0:
                        # Normal waiters blocked behind priority ones must
                        # learn the road is clear.
                        self._cv.notify_all()
                else:
                    self._normal_waiters -= 1

    def release(self, wid: int) -> bool:
        """Returns True if a spare thread should be spawned to keep the
        worker count constant. A waiter can absorb exactly ONE identity:
        comparing free identities against waiter count (not testing
        waiters == 0) closes the leak where two near-simultaneous
        releases both saw the same single waiter, neither spawned a
        spare, and the second identity sat unclaimed forever while every
        live thread was a parked blocked context (chaos-surfaced wedge)."""
        with self._cv:
            self._free.append(wid)
            self._cv.notify_all()
            return (
                len(self._free)
                > self._priority_waiters + self._normal_waiters
                and not self._shutdown
            )

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()


class Runtime:
    def __init__(
        self,
        nworkers: Optional[int] = None,
        locality_graph: Optional[LocalityGraph] = None,
        stats: Optional[bool] = None,
        instrument: Optional[bool] = None,
        timer: Optional[bool] = None,
        watchdog_s: Optional[float] = None,
        watchdog_escalate: Optional[bool] = None,
        fault_plan: Optional[FaultPlan] = None,
        default_retry: Optional[RetryPolicy] = None,
        metrics: Optional[bool] = None,
    ) -> None:
        if nworkers is None:
            nworkers = env.env_int(
                "HCLIB_TPU_WORKERS", os.cpu_count() or 1
            )
        if locality_graph is None:
            path = env.env_str("HCLIB_TPU_LOCALITY_FILE")
            locality_graph = (
                load_locality_file(path, nworkers) if path else generate_default_graph(nworkers)
            )
        if locality_graph.nworkers != nworkers:
            nworkers = locality_graph.nworkers
        self.nworkers = nworkers
        self.graph = locality_graph
        self.stats_enabled = (
            stats if stats is not None else env.env_flag("HCLIB_TPU_STATS")
        )
        # One deque per (locale, worker) - the core locality-graph invariant
        # (inc/hclib-locality-graph.h:9-50).
        self.deques: Dict[Tuple[int, int], WSDeque] = {
            (loc.id, w): WSDeque()
            for loc in self.graph.locales
            for w in range(nworkers)
        }
        self.worker_stats = [_WorkerStats(nworkers) for _ in range(nworkers)]
        self._last_steal = [0] * nworkers
        self._idmgr = _IdentityManager(
            nworkers, on_priority_wait=self._wake_workers
        )
        self._work_cv = threading.Condition()
        self._pending = 0  # tasks in deques (approximate wakeup hint)
        self._shutdown = False
        self._threads: List[threading.Thread] = []
        self._nthreads_lock = threading.Lock()
        self._nthreads = 0
        self.root_finish: Optional[Finish] = None
        # First exception raised by any task; re-raised at launch exit.
        self._first_error: Optional[BaseException] = None
        self._first_error_lock = threading.Lock()
        # Idle callbacks per locale (locale_register_idle_task,
        # src/hclib-locality-graph.c:807-827) - used by comm backends to poll.
        self._idle_fns: List[Callable[[int], bool]] = []
        # Observability (SURVEY §5): event log, state timer, stall watchdog.
        if instrument is None:
            instrument = env.env_flag("HCLIB_TPU_INSTRUMENT")
        if timer is None:
            timer = env.env_flag("HCLIB_TPU_TIMER")
        if watchdog_s is None:
            watchdog_s = env.env_float("HCLIB_TPU_WATCHDOG_S", 0.0)
        if watchdog_escalate is None:
            e = env.env_raw("HCLIB_TPU_WATCHDOG_ESCALATE")
            watchdog_escalate = e != "0" if e is not None else True
        self.event_log = None
        self._ev_task = None
        if instrument:
            from .instrument import EventLog, register_event_type

            self.event_log = EventLog(nworkers)
            self._ev_task = register_event_type("task")
        self.state_timer = None
        if timer:
            from .timer import StateTimer

            self.state_timer = StateTimer(nworkers)
        # Unified telemetry (runtime/metrics.py): a MetricsRegistry with
        # this runtime's stats_dict pre-registered; device runs record
        # their infos into it (rt.metrics.add_run_info) and the watchdog's
        # stats-dump rung logs its snapshot.
        if metrics is None:
            # Same convention as HCLIB_TPU_TRACE: "0" (and empty) is OFF.
            metrics = env.env_bool("HCLIB_TPU_METRICS")
        self.metrics = None
        if metrics:
            from .metrics import MetricsRegistry

            self.metrics = MetricsRegistry()
            self.metrics.register("runtime", self.stats_dict)
        self._watchdog_s = watchdog_s
        self._watchdog_escalate = watchdog_escalate
        self._watchdog_thread: Optional[threading.Thread] = None
        self.stall_reports = 0
        # Resilience (runtime/resilience.py): chaos plan, default retry
        # policy, deadline, parked-context wake registry, and counters.
        self._fault_plan = fault_plan
        self._default_retry = default_retry
        self._deadline_timer: Optional[threading.Timer] = None
        # Event twin of the _shutdown flag so sleepers (watchdog) notice
        # shutdown promptly instead of finishing a full sleep interval.
        self._shutdown_evt = threading.Event()
        # Armed park events of every blocked context; a cancel sets them
        # all (spurious wakes are safe - park callers loop and re-check).
        # Refcounted: contexts blocked on the same finish share one event,
        # and registration/removal must be O(1) - thousands of contexts
        # can park and wake in one cancellation wave.
        self._parked_lock = threading.Lock()
        self._parked_events: Dict[threading.Event, int] = {}
        self._res_lock = threading.Lock()
        self.cancelled_tasks = 0
        self.task_retries = 0
        # Deferred-retry timers pending fire; nonzero means an active
        # backoff cycle, which the watchdog must not read as a stall.
        self._deferred_pending = 0
        self.worker_deaths = 0
        self.quarantined = 0
        self._quarantine: List[dict] = []
        # Main-thread-affine execution (hclib_run_on_main_ctx,
        # src/hclib-runtime.c:1340-1358): workers queue requests; the
        # launch thread services them in its help loops and while joining
        # workers at finalize (the reference's :1420-1423 loop).
        self._main_ident: Optional[int] = None
        self._main_ctx_q: List[tuple] = []
        self._main_ctx_lock = threading.Lock()
        self._main_park_evt: Optional[threading.Event] = None

    # ------------------------------------------------------------------ spawn

    def spawn(
        self,
        fn: Callable[..., Any],
        args: Sequence[Any] = (),
        kwargs: Optional[dict] = None,
        locale: Optional[Locale] = None,
        waiting_on: Sequence[Future] = (),
        non_blocking: bool = False,
        escaping: bool = False,
        result_promise: Optional[Promise] = None,
        retry: Optional[RetryPolicy] = None,
    ) -> Task:
        fin = None if escaping else _tls.current_finish
        if fin is not None and fin.scope.cancelled():
            # Spawning into a cancelled scope raises so runaway spawn
            # trees (recursive fib/UTS bodies) unwind promptly instead of
            # flooding the deques with tasks that would only be dropped.
            raise CancelledError(fin.scope.describe())
        task = Task(
            fn,
            args,
            kwargs,
            finish=fin,
            waiting_on=waiting_on,
            locale=locale,
            non_blocking=non_blocking,
            result_promise=result_promise,
            retry=retry if retry is not None else self._default_retry,
        )
        if fin is not None:
            fin.check_in()
        wid = _tls.identity
        if wid is not None:
            self.worker_stats[wid].spawned += 1
        self._try_schedule(task)
        return task

    def _try_schedule(self, task: Task) -> None:
        """Register on the first unsatisfied dependency, else enqueue
        (try_schedule_async: src/hclib-runtime.c:558-570)."""
        while task.wait_index < len(task.waiting_on):
            fut = task.waiting_on[task.wait_index]
            if fut.promise._register_task(task):
                return  # parked on this promise; put() resumes the walk
            task.wait_index += 1
        self._enqueue(task)

    def resume_registration(self, task: Task) -> None:
        task.wait_index += 1
        self._try_schedule(task)

    def _enqueue(self, task: Task) -> None:
        wid = _tls.identity
        if wid is None:
            wid = 0
        locale = task.locale
        if locale is None:
            locale = self.graph.closest_locale(wid)
            task.locale = locale
        self.deques[(locale.id, wid)].push(task)
        with self._work_cv:
            self._pending += 1
            self._work_cv.notify_all()

    # ------------------------------------------------------------------ find

    def _find_task(self, wid: int) -> Optional[Task]:
        # Pop path: drain own deques, closest locale first
        # (locale_pop_task: src/hclib-locality-graph.c:774-805).
        for lid in self.graph.pop_paths[wid]:
            t = self.deques[(lid, wid)].pop()
            if t is not None:
                with self._work_cv:
                    self._pending -= 1
                return t
        # Steal path: scan every worker's deque at each locale, rotating the
        # starting victim (locale_steal_task: src/hclib-locality-graph.c:843-888).
        st = self.state_timer
        if st is not None:
            from .timer import SEARCH

            st.set_state(wid, SEARCH)
        start = self._last_steal[wid]
        for lid in self.graph.steal_paths[wid]:
            for i in range(self.nworkers):
                v = (start + i) % self.nworkers
                if v == wid and lid in self.graph.pop_paths[wid]:
                    continue
                t = self.deques[(lid, v)].steal()
                if t is not None:
                    self._last_steal[wid] = v
                    st = self.worker_stats[wid]
                    st.steals += 1
                    st.stolen_from[v] += 1
                    with self._work_cv:
                        self._pending -= 1
                    if self._fault_plan is not None:
                        self._fault_plan.on_steal(wid)
                    return t
        return None

    # --------------------------------------------------------------- execute

    def _execute(self, task: Task) -> None:
        prev_finish, prev_task = _tls.current_finish, _tls.current_task
        _tls.current_finish = task.finish
        _tls.current_task = task
        wid = _tls.identity
        ev, st = self.event_log, self.state_timer
        eid = 0
        if ev is not None and wid is not None:
            from .instrument import START

            eid = ev.new_id(wid)
            ev.record(wid, self._ev_task, START, eid)
        if st is not None and wid is not None:
            from .timer import WORK

            st.set_state(wid, WORK)
        # Completion is tracked in a LOCAL, not on the task: a deferred
        # task's backoff timer can fire and the re-execution complete on
        # another worker before this frame's finally runs - reading the
        # (shared, by-then-reset) task state here would double check_out
        # and corrupt the finish counter.
        completed = True
        try:
            completed = self._run_task_body(task)
        finally:
            _tls.current_finish, _tls.current_task = prev_finish, prev_task
            if task.finish is not None and completed:
                # A deferred (backoff-retried) task has NOT completed: its
                # finish stays checked in until the re-enqueued attempt
                # finishes for real.
                task.finish.check_out()
            wid = _tls.identity
            if wid is not None:
                if completed:
                    # A deferred-retry frame did NOT complete the task;
                    # counting it would inflate executed (and every
                    # tasks/sec figure derived from it) per attempt.
                    self.worker_stats[wid].executed += 1
                if ev is not None:
                    from .instrument import END

                    ev.record(wid, self._ev_task, END, eid)
                if st is not None:
                    from .timer import OVH

                    st.set_state(wid, OVH)

    # ----------------------------------------------------------- resilience

    def _run_task_body(self, task: Task) -> bool:
        """Execute the task body under the resilience policies: skip (and
        poison) when the scope is cancelled, inject planned faults, retry
        per the task's RetryPolicy (inline when backoff is zero, deferred
        re-enqueue otherwise), and quarantine terminal failures.

        Returns False when the task was DEFERRED for a delayed retry (the
        caller must then skip check_out: the task has not completed, and
        once the timer is armed another worker may already be re-running
        it); True on every completed path."""
        scope = task.finish.scope if task.finish is not None else None
        fp = self._fault_plan
        while True:
            if scope is not None and scope.cancelled():
                with self._res_lock:
                    self.cancelled_tasks += 1
                if task.result_promise is not None:
                    task.result_promise.poison_if_unset(
                        CancelledError(scope.describe())
                    )
                return True
            try:
                if fp is not None:
                    fp.on_task(task)
                result = task.fn(*task.args, **task.kwargs)
            except BaseException as e:
                pol = task.retry
                if (
                    pol is not None
                    and (scope is None or not scope.cancelled())
                    and pol.should_retry(task.attempt, e)
                ):
                    task.attempt += 1
                    with self._res_lock:
                        self.task_retries += 1
                    delay = pol.delay_s(task.attempt)
                    if delay <= 0.0:
                        continue
                    self._defer(task, delay)
                    return False
                if (
                    pol is not None and pol.quarantine
                    and not isinstance(e, CancelledError)
                ):
                    # Poison-task containment: dependents fail via the
                    # poisoned promise, the run itself continues; the
                    # failure survives in stats_dict()['resilience'].
                    # (quarantine=False exhaustion is NOT recorded here -
                    # that error propagates and fails the run, and stats
                    # claiming containment would misreport it.)
                    self._quarantine_task(task, e)
                    if task.result_promise is not None:
                        task.result_promise.poison_if_unset(e)
                    return True
                if task.result_promise is not None:
                    # Wake dependents with a failure instead of stranding
                    # them on a never-satisfied promise.
                    task.result_promise.poison_if_unset(e)
                raise
            else:
                if task.result_promise is not None:
                    task.result_promise.put(result)
                return True

    def _defer(self, task: Task, delay: float) -> None:
        """Re-enqueue ``task`` after ``delay`` seconds (retry backoff)."""
        with self._res_lock:
            self._deferred_pending += 1
        t = threading.Timer(delay, self._fire_deferred, args=(task,))
        t.daemon = True
        t.start()

    def _fire_deferred(self, task: Task) -> None:
        with self._res_lock:
            self._deferred_pending -= 1
        self._enqueue(task)

    def _quarantine_task(self, task: Task, err: BaseException) -> None:
        name = getattr(task.fn, "__name__", repr(task.fn))
        with self._res_lock:
            self.quarantined += 1
            if len(self._quarantine) < _QUARANTINE_KEEP:
                self._quarantine.append({
                    "fn": name,
                    "attempts": task.attempt + 1,
                    "error": repr(err),
                })
        from .resilience import LOG

        LOG.warning(
            "task %s quarantined after %d attempts: %r",
            name, task.attempt + 1, err,
        )

    def _raise_if_cancelled(self, scope) -> None:
        if scope is not None and scope.cancelled():
            raise CancelledError(scope.describe())

    def _wake_parked(self) -> None:
        """Unpark every blocked context (cancel waker): spurious wakes are
        safe - park callers loop and re-check their own condition."""
        with self._parked_lock:
            evs = list(self._parked_events)
        for ev in evs:
            ev.set()
        with self._work_cv:
            self._work_cv.notify_all()

    def _on_deadline(self, deadline_s: float) -> None:
        """Runtime deadline fired: cancel the root scope with a structured
        StallError; everything blocked wakes and the error surfaces at
        launch exit in bounded time."""
        if self._shutdown:
            return
        if self.root_finish is None:
            # The launch is still initializing (module post_init can block
            # for seconds, e.g. a comm world connecting): re-arm until the
            # root scope exists so the bound still lands instead of the
            # one-shot timer silently expiring into an unbounded run.
            t = threading.Timer(0.05, self._on_deadline, args=(deadline_s,))
            t.daemon = True
            t.start()
            self._deadline_timer = t
            return
        if self.root_finish.quiesced():
            # The program finished right at the boundary (help_finish
            # returned, shutdown not yet flagged): a completed run must
            # not be retroactively failed.
            return
        err = StallError(
            f"runtime deadline of {deadline_s}s exceeded with work "
            f"outstanding (backlog={self.backlog()})",
            stats=self.stats_dict(),
        )
        self._record_error(err)
        resilience.LOG.error("deadline exceeded: cancelling root scope")
        self.root_finish.scope.cancel(err)

    # ------------------------------------------------------------- work loop

    def _core_work_loop(self, wid: int) -> Tuple[bool, int]:
        """Drain/steal/execute until shutdown or a resumed context needs this
        identity (core_work_loop: src/hclib-runtime.c:705-724). Returns
        (died, wid): ``died`` when a FaultPlan killed this thread (the
        caller re-binds the orphaned identity to a fresh thread), and the
        identity this thread holds NOW - an executed task that blocked
        released the entry identity and re-acquired, possibly a different
        one, so the caller must release the current binding, not its
        stale argument (releasing the stale one double-frees an identity
        another thread owns and leaks this thread's real one: a
        chaos-surfaced wedge)."""
        _tls.identity = wid
        fp = self._fault_plan
        while not self._shutdown:
            if fp is not None and fp.on_worker_poll(wid):
                _tls.identity = None
                return True, wid  # chaos: this worker thread dies here
            if self._idmgr.has_priority_waiter:
                break  # hand the identity to a resumed context
            task = self._find_task(wid)
            if task is not None:
                try:
                    self._execute(task)
                except BaseException as e:
                    # A task failing on a pool thread must not kill the
                    # worker or vanish: record it for launch() to re-raise.
                    self._record_error(e)
                # The task may have blocked and re-bound this thread to a
                # different identity: refresh before scanning again.
                wid = _tls.identity
                continue
            if self._run_idle_fns(wid):
                continue
            with self._work_cv:
                if (
                    self._pending == 0
                    and not self._shutdown
                    # Re-checked under the condvar lock: a priority
                    # waiter's flag-then-notify cannot be lost against
                    # this predicate (the notify blocks on this lock
                    # until wait() releases it).
                    and not self._idmgr.has_priority_waiter
                ):
                    # Event-driven park: spawns, shutdown, and priority
                    # waiters all notify. Registered idle fns (comm
                    # pollers) still need a polling cadence; the 0.2s cap
                    # bounds one theoretical flag race (a departing
                    # priority waiter clearing over an arriving one's
                    # pre-lock set) instead of being the latency floor.
                    self._work_cv.wait(0.01 if self._idle_fns else 0.2)
        _tls.identity = None
        return False, wid

    def _wake_workers(self) -> None:
        """Wake idle workers (a resumed context needs an identity: the
        next loop iteration sees has_priority_waiter and yields one)."""
        with self._work_cv:
            self._work_cv.notify_all()

    def _record_error(self, e: BaseException) -> None:
        if isinstance(e, CancelledError) and resilience.any_cancelled():
            # Fallout of a real cancellation is a control signal: the
            # cause (deadline StallError, user cancel) is recorded by
            # whoever cancelled, and the per-task CancelledError must not
            # mask it. A CancelledError raised by user code while NOTHING
            # was cancelled this launch is an ordinary failure - record it.
            return
        with self._first_error_lock:
            if self._first_error is None:
                self._first_error = e

    def _run_idle_fns(self, wid: int) -> bool:
        did = False
        for fn in self._idle_fns:
            try:
                did = bool(fn(wid)) or did
            except Exception:  # idle pollers must not kill workers
                pass
        return did

    def register_idle_fn(self, fn: Callable[[int], bool]) -> None:
        self._idle_fns.append(fn)

    def _thread_main(self) -> None:
        _tls.runtime = self
        while True:
            wid = self._idmgr.acquire(priority=False)
            if wid is None:
                return
            died, wid = self._core_work_loop(wid)
            if died:
                # Chaos worker death: the thread is gone, but the worker
                # identity (deques, stats) survives - release it and spawn
                # a replacement thread so the worker count heals, the
                # recovery path FaultPlan.kill_worker exists to exercise.
                with self._res_lock:
                    self.worker_deaths += 1
                if self._idmgr.release(wid):
                    self._spawn_thread()
                return
            if self._shutdown:
                self._idmgr.release(wid)
                return
            self._idmgr.release(wid)

    def _spawn_thread(self) -> None:
        with self._nthreads_lock:
            if self._nthreads >= _MAX_THREADS:
                # A parked context released its identity expecting a spare to
                # pick it up; failing silently here would deadlock the
                # program, so fail loudly instead.
                raise RuntimeError(
                    f"worker thread cap ({_MAX_THREADS}) reached: too many "
                    "simultaneously blocked contexts; restructure with "
                    "data-driven tasks (async_future/await_) or raise the cap"
                )
            self._nthreads += 1
        # Bounded stacks keep thousands of blocked contexts affordable
        # (cf. the reference's 256 KB fiber stacks, src/inc/litectx.h:25).
        try:
            prev = threading.stack_size(_THREAD_STACK)
        except (ValueError, RuntimeError):
            prev = None
        try:
            t = threading.Thread(
                target=self._thread_main, daemon=True, name="hclib-worker"
            )
            t.start()
        finally:
            if prev is not None:
                threading.stack_size(prev)
        self._threads.append(t)

    # ------------------------------------------------------------- blocking

    def _inline_safe(self, task: Task, fin: Optional[Finish]) -> bool:
        """Reference rule (src/hclib-runtime.c:673-689): run inline iff the
        task can't block this stack indefinitely - it is declared non-blocking
        or belongs to the very finish scope we are draining. A task whose
        scope is already cancelled is trivially inline-safe: its body is
        skipped, so any context may drain it (lets yield_/help loops clear
        a cancelled backlog without parking)."""
        if task.non_blocking or (fin is not None and task.finish is fin):
            return True
        return task.finish is not None and task.finish.scope.cancelled()

    def _park(
        self,
        register: Callable[[threading.Event], Optional[threading.Event]],
        check: Optional[Callable[[], bool]] = None,
        deadline: Optional[float] = None,
        unregister: Optional[Callable[[threading.Event], None]] = None,
    ) -> None:
        """Release identity, sleep until the event fires, re-bind an identity.

        ``check`` returning True abandons the park (the caller's loop
        re-checks its condition and typically raises - used for scope
        cancellation); ``deadline`` (monotonic) bounds the sleep for timed
        waits. Cancellation wakes are event-driven (the registered event is
        in ``_parked_events`` and ``_wake_parked`` sets it), so unbounded
        parks never poll. ``unregister`` runs on every exit so the waiter
        the ``register`` callback added (e.g. to a promise's ctx list) is
        withdrawn when the park is abandoned - without it, repeated timed
        waits on one promise would leak an Event per attempt."""
        ev = threading.Event()
        armed = register(ev)
        if armed is None:
            return  # condition already satisfied
        wid = _tls.identity
        st = self.state_timer
        if wid is not None:
            self.worker_stats[wid].parks += 1
            if st is not None:
                from .timer import IDLE

                st.set_state(wid, IDLE)
            _tls.identity = None
            if self._idmgr.release(wid):
                self._spawn_thread()
        is_main = threading.get_ident() == self._main_ident
        if is_main:
            # Publish the park event so run_on_main can wake this thread;
            # under the SAME lock, self-wake if requests raced in before
            # the publication (no missed wakeup, no deadlock). Spurious
            # wakes are safe: every park caller loops on its condition.
            with self._main_ctx_lock:
                self._main_park_evt = armed
                if self._main_ctx_q:
                    armed.set()
        with self._parked_lock:
            self._parked_events[armed] = self._parked_events.get(armed, 0) + 1
        try:
            # Re-check AFTER registration: a cancel between the caller's
            # loop-head check and this park would otherwise have fired
            # _wake_parked before our event was registered (missed wakeup).
            # One wait suffices: every asynchronous wake source (quiesce,
            # promise put, cancel) sets the registered event, and a timed
            # wait returns at its deadline on its own - the caller's loop
            # re-checks its condition either way. No polling.
            if not (check is not None and check()):
                if deadline is None:
                    armed.wait()
                else:
                    armed.wait(max(0.0, deadline - time.monotonic()))
        finally:
            with self._parked_lock:
                n = self._parked_events.get(armed, 0) - 1
                if n <= 0:
                    self._parked_events.pop(armed, None)
                else:
                    self._parked_events[armed] = n
            if unregister is not None:
                unregister(armed)
        if is_main:
            with self._main_ctx_lock:
                self._main_park_evt = None
            self._service_main_ctx()
        _tls.identity = self._idmgr.acquire(priority=True)
        if st is not None and _tls.identity is not None:
            from .timer import OVH

            st.set_state(_tls.identity, OVH)

    def _execute_recording(self, task: Task) -> None:
        """Execute a task, converting its exception into a recorded error
        (re-raised at launch exit) - the same policy pool workers follow, so
        task failures behave identically whether run inline or stolen."""
        try:
            self._execute(task)
        except BaseException as e:
            self._record_error(e)

    def _service_main_ctx(self) -> None:
        """Run queued main-thread-affine requests (no-op off-main)."""
        if threading.get_ident() != self._main_ident:
            return
        while True:
            with self._main_ctx_lock:
                if not self._main_ctx_q:
                    return
                fn, args, box, prom = self._main_ctx_q.pop(0)
            try:
                box["value"] = fn(*args)
            except BaseException as e:  # caller re-raises
                box["error"] = e
            prom.put(None)

    def run_on_main(self, fn: Callable[..., Any], *args: Any) -> Any:
        """Execute ``fn`` on the launch (main) thread and return its result
        (hclib_run_on_main_ctx, src/hclib-runtime.c:1340-1358) - for
        main-thread-affine operations (GUI toolkits, signal setup, some
        foreign runtimes). From the main thread it runs inline; from a
        worker it blocks (helping with other tasks meanwhile) until the
        main thread services the request - in its help loops while the
        program runs, or in the finalize join loop (the reference
        services requests there too, :1420-1423). ``fn``'s exception
        re-raises in the caller."""
        if threading.get_ident() == self._main_ident:
            return fn(*args)
        box: dict = {}
        prom = Promise()
        with self._main_ctx_lock:
            # _main_ident is cleared under this lock at finalize (after
            # failing queued requests), so checking it HERE means a late
            # caller raises instead of enqueueing into a dead launch.
            if self._main_ident is None:
                raise RuntimeError("run_on_main requires an active launch")
            self._main_ctx_q.append((fn, args, box, prom))
            evt = self._main_park_evt
        if evt is not None:
            evt.set()  # wake a parked main thread (loops re-check)
        self.wait_on(prom)
        if "error" in box:
            raise box["error"]
        return box["value"]

    def help_finish(self, fin: Finish, timeout: Optional[float] = None) -> None:
        """Help-first drain of a finish scope (help_finish:
        src/hclib-runtime.c:1067-1119). Raises ``CancelledError`` when the
        scope (or an ancestor) is cancelled; with ``timeout``, cancels the
        scope and raises ``StallError`` if it fails to quiesce in time.

        Help-first caveat: the timeout bounds THIS context's join wait. A
        child of this scope inlined onto this stack that then blocks on an
        unrelated, untimed condition parks beyond the timeout's reach -
        the runtime-level ``deadline_s``/watchdog still bounds those."""
        deadline = None if timeout is None else time.monotonic() + timeout
        wid = _tls.identity
        scope = fin.scope
        while not fin.quiesced():
            self._raise_if_cancelled(scope)
            if deadline is not None and time.monotonic() >= deadline:
                err = StallError(
                    f"finish scope failed to quiesce within {timeout}s "
                    f"({fin.counter} tasks outstanding)",
                    stats=self.stats_dict(),
                )
                scope.cancel(err)
                raise err
            self._service_main_ctx()
            task = self._find_task(wid) if wid is not None else None
            if task is None:
                # The park event is CALLER-OWNED (registered on the finish
                # like Promise._register_ctx): a run_on_main wake targets
                # exactly this park instead of poisoning a shared scope
                # event, and the unregister hook withdraws the waiter on
                # spurious/timed exits so long scopes don't accumulate
                # dead events.
                self._park(
                    lambda ev, f=fin: ev if f.register_event(ev) else None,
                    check=scope.cancelled,
                    deadline=deadline,
                    unregister=fin.unregister_event,
                )
                wid = _tls.identity
                continue
            if self._inline_safe(task, fin):
                self._execute_recording(task)
                # An inline same-finish task can itself block (nested
                # finish), re-binding this thread to another identity.
                wid = _tls.identity
            else:
                # The reference swaps to a fresh fiber seeded with this task;
                # we re-enqueue it and park - another thread runs it.
                self._requeue_and_park(
                    task,
                    lambda ev, f=fin: ev if f.register_event(ev) else None,
                    check=scope.cancelled, deadline=deadline,
                    unregister=fin.unregister_event,
                )
                wid = _tls.identity

    def wait_on(self, promise: Promise, timeout: Optional[float] = None) -> None:
        """Future-wait (hclib_future_wait: src/hclib-runtime.c:983-1025):
        help with non-blocking tasks, else park on the promise. Raises
        ``CancelledError`` when the waiting context's scope is cancelled;
        with ``timeout``, raises ``StallError`` past it (the promise stays
        unsatisfied and may be waited on again)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        wid = _tls.identity
        fin = _tls.current_finish
        scope = fin.scope if fin is not None else None
        check = scope.cancelled if scope is not None else None
        while not promise.satisfied():
            self._raise_if_cancelled(scope)
            if deadline is not None and time.monotonic() >= deadline:
                raise StallError(
                    f"Promise.wait timed out after {timeout}s",
                    stats=self.stats_dict(),
                )
            self._service_main_ctx()
            task = self._find_task(wid) if wid is not None else None
            if task is None:
                self._park(
                    lambda ev, p=promise: ev if p._register_ctx(ev) else None,
                    check=check, deadline=deadline,
                    unregister=promise._unregister_ctx,
                )
                wid = _tls.identity
                continue
            if self._inline_safe(task, None):
                self._execute_recording(task)
                wid = _tls.identity  # non-blocking, but stay consistent
            else:
                self._requeue_and_park(
                    task,
                    lambda ev, p=promise: ev if p._register_ctx(ev) else None,
                    check=check, deadline=deadline,
                    unregister=promise._unregister_ctx,
                )
                wid = _tls.identity

    def _requeue_and_park(
        self, task: Task, register, check=None, deadline=None,
        unregister=None,
    ) -> None:
        self._enqueue(task)
        self._park(register, check=check, deadline=deadline,
                   unregister=unregister)

    def _find_task_at(self, wid: int, locale: Locale) -> Optional[Task]:
        """Pop/steal only at one locale (yield_at semantics: a comm worker
        polling the NIC locale must not pick up arbitrary compute tasks)."""
        t = self.deques[(locale.id, wid)].pop()
        if t is None:
            for v in range(self.nworkers):
                if v == wid:
                    continue
                t = self.deques[(locale.id, v)].steal()
                if t is not None:
                    break
        if t is not None:
            with self._work_cv:
                self._pending -= 1
        return t

    def yield_(self, locale: Optional[Locale] = None) -> bool:
        """Run at most one other task inline (hclib_yield:
        src/hclib-runtime.c:1142-1217). Returns True if a task ran."""
        wid = _tls.identity
        if wid is None:
            return False
        self.worker_stats[wid].yields += 1
        task = self._find_task_at(wid, locale) if locale is not None else self._find_task(wid)
        if task is None:
            return False
        if self._inline_safe(task, _tls.current_finish):
            self._execute_recording(task)
            return True
        self._enqueue(task)  # put it back; a blocking task can't run on this stack
        return False

    # ------------------------------------------------------------- watchdog

    def _watchdog_main(self) -> None:
        """Stall detector (SURVEY §5: the reference documents that help-first
        blocking can deadlock, test/deadlock/README, but detects nothing).

        Escalation ladder, one rung per consecutive stalled interval (no
        task executed while work is outstanding):

        1. report   - logging.warning + 'stall' instrument event
        2. dump     - logging.error with the full format_stats() snapshot
        3. escalate - cancel the root scope with a structured StallError
                      (``watchdog_escalate=False`` stops at rung 2)

        Progress at any point resets the ladder. The Event-based sleep
        notices runtime shutdown promptly instead of finishing a full
        ``watchdog_s`` interval (``_shutdown_evt`` is set in run())."""
        log = resilience.LOG
        ev_stall = None
        if self.event_log is not None:
            from .instrument import register_event_type

            ev_stall = register_event_type("stall")
        last_progress = -1
        strikes = 0
        while not self._shutdown_evt.wait(self._watchdog_s):
            executed = sum(st.executed for st in self.worker_stats)
            # Retries count as progress: an active backoff cycle (deferred
            # re-enqueues pending on timers) is not a stall.
            progress = executed + self.cancelled_tasks + self.task_retries
            outstanding = (
                self.root_finish is not None
                and not self.root_finish.quiesced()
            )
            if self._deferred_pending > 0:
                # A retry backoff timer is armed: the run is waiting on
                # purpose, not stalled - even when the backoff spans
                # several watchdog intervals.
                last_progress = progress
                strikes = 0
                continue
            if progress == last_progress and outstanding:
                strikes += 1
                self.stall_reports += 1
                if self.event_log is not None:
                    from .instrument import SINGLE

                    # -1 routes to the external lane: the watchdog thread
                    # must not write worker 0's lock-free buffer (a real
                    # cross-thread race before the lane existed).
                    self.event_log.record(-1, ev_stall, SINGLE, strikes)
                head = (
                    f"hclib_tpu watchdog: no task executed in "
                    f"{self._watchdog_s:.1f}s with work outstanding "
                    f"(executed={executed} backlog={self.backlog()} "
                    f"pending={self._pending} strike={strikes})"
                )
                if strikes == 1:
                    log.warning("%s", head)
                elif strikes == 2:
                    dump = self.format_stats()
                    if self.metrics is not None:
                        # The stats-dump rung carries the unified snapshot
                        # too: device counters a program recorded into the
                        # registry survive in the stall post-mortem.
                        dump += "\nmetrics: " + self.metrics.to_json()
                    log.error("%s\n%s", head, dump)
                    if env.env_bool("HCLIB_TPU_WATCHDOG_CHECKPOINT"):
                        # Optional checkpoint rung: before escalation can
                        # cancel (and abort device streams, losing their
                        # task graphs), fire the preemption hooks so any
                        # registered resident stream quiesces and exports
                        # its state - the stall post-mortem then carries
                        # a restorable snapshot, not just counters.
                        resilience.fire_preempt("watchdog stall strike 2")
                if strikes >= 3 and self._watchdog_escalate:
                    err = StallError(
                        f"watchdog: stalled for "
                        f"{strikes * self._watchdog_s:.1f}s with work "
                        f"outstanding; cancelling root scope",
                        stats=self.stats_dict(),
                    )
                    self._record_error(err)
                    log.error("%s - escalating: cancelling root scope", head)
                    if self.root_finish is not None:
                        self.root_finish.scope.cancel(err)
                    return
            else:
                strikes = 0
            last_progress = progress

    # ------------------------------------------------------------ lifecycle

    def run(
        self,
        fn: Callable[..., Any],
        *args: Any,
        deadline_s: Optional[float] = None,
    ) -> Any:
        """Launch: bind the caller as a worker, run ``fn`` under the root
        finish, drain, shut down (hclib_launch: src/hclib-runtime.c:1460-1478).

        With ``deadline_s``, the whole launch is bounded: past the deadline
        the root scope is cancelled and a structured ``StallError`` (with a
        stats snapshot) raises here instead of the program hanging."""
        global _global_runtime
        if _global_runtime is not None:
            raise RuntimeError("an hclib_tpu runtime is already active")
        _global_runtime = self
        _tls.runtime = self
        from .module import call_pre_init, call_post_init, call_finalize

        call_pre_init(self)
        # A cancel in some EARLIER launch must not slow this one down:
        # restore the epoch-guarded fast path (scopes of dead runtimes
        # are unreachable by live tasks).
        resilience.reset_cancel_epoch()
        resilience.set_cancel_waker(self._wake_parked)
        if self._watchdog_s > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_main, daemon=True, name="hclib-watchdog"
            )
            self._watchdog_thread.start()
        if deadline_s is not None:
            self._deadline_timer = threading.Timer(
                deadline_s, self._on_deadline, args=(deadline_s,)
            )
            self._deadline_timer.daemon = True
            self._deadline_timer.start()
        for _ in range(self.nworkers):
            self._spawn_thread()
        _tls.identity = self._idmgr.acquire(priority=True)
        self._main_ident = threading.get_ident()
        call_post_init(self)
        self.root_finish = Finish()
        prev_finish = _tls.current_finish
        _tls.current_finish = self.root_finish
        result: List[Any] = [None]
        err: List[Optional[BaseException]] = [None]

        def root() -> None:
            try:
                result[0] = fn(*args)
            except BaseException as e:  # propagate to launcher
                err[0] = e

        try:
            try:
                # spawn is inside the handler too: a deadline firing this
                # early cancels the root scope and makes spawn itself
                # raise CancelledError - the recorded StallError must
                # still win.
                self.spawn(root)
                self.help_finish(self.root_finish)
            except CancelledError as ce:
                # Root cancellation: surface the CAUSE (deadline/watchdog
                # StallError, a task's recorded failure) when one exists;
                # a bare user cancel propagates as CancelledError itself.
                with self._first_error_lock:
                    fe = self._first_error
                if fe is None:
                    raise
                raise fe from ce
        finally:
            _tls.current_finish = prev_finish
            self._shutdown = True
            self._shutdown_evt.set()
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()
            resilience.set_cancel_waker(None)
            self._idmgr.shutdown()
            with self._work_cv:
                self._work_cv.notify_all()
            for t in self._threads:
                # Service main-ctx requests while joining: an escaping
                # task may still be blocked in run_on_main (the reference
                # services these in its finalize loop,
                # src/hclib-runtime.c:1420-1423).
                deadline = time.monotonic() + 5.0
                while t.is_alive() and time.monotonic() < deadline:
                    self._service_main_ctx()
                    t.join(timeout=0.05)
            with self._main_ctx_lock:
                # Close the launch under the queue's lock, failing any
                # request that raced past the join loop - late callers
                # get an error instead of hanging on a promise nobody
                # will ever service (and no stale fn can leak into a
                # later launch's queue).
                self._main_ident = None
                stranded, self._main_ctx_q = self._main_ctx_q, []
            for _, _, box, prom in stranded:
                box["error"] = RuntimeError(
                    "run_on_main request outlived the launch"
                )
                prom.put(None)
            call_finalize(self)
            if _tls.identity is not None:
                _tls.identity = None
            _global_runtime = None
            _tls.runtime = None
            if self.stats_enabled:
                self.print_stats()
            if self.state_timer is not None:
                self.state_timer.finalize()
            if self.event_log is not None and env.env_flag(
                "HCLIB_TPU_INSTRUMENT"
            ):
                # Env-driven runs flush at finalize like the reference
                # (src/hclib-runtime.c:1465); programmatic users call
                # event_log.dump() with their own directory.
                self.last_dump_path = self.event_log.dump()
        if err[0] is not None:
            if isinstance(err[0], CancelledError) and self._first_error is not None:
                # The root body tripped over the cancellation (e.g. a spawn
                # into the cancelled root scope); the recorded cause wins.
                raise self._first_error from err[0]
            raise err[0]
        if self._first_error is not None:
            raise self._first_error
        return result[0]

    # ----------------------------------------------------------------- misc

    def backlog(self) -> int:
        """Tasks currently enqueued (hclib_current_worker_backlog,
        src/hclib-runtime.c:1365-1368)."""
        return sum(len(d) for d in self.deques.values())

    def print_stats(self) -> None:
        print(self.format_stats())

    def stats_dict(self) -> dict:
        """Worker counters as a JSON-ready dict (steal matrix included) -
        the machine-readable form of format_stats, consumed by
        tools/timeline.py's report renderer."""
        with self._res_lock:
            quarantine = [dict(q) for q in self._quarantine]
            res = {
                "cancelled_tasks": self.cancelled_tasks,
                "retries": self.task_retries,
                "worker_deaths": self.worker_deaths,
                "quarantined": self.quarantined,
                "quarantine": quarantine,
                "stall_reports": self.stall_reports,
            }
        return {
            "nworkers": self.nworkers,
            "workers": [
                {
                    "executed": st.executed,
                    "spawned": st.spawned,
                    "steals": st.steals,
                    "parks": st.parks,
                    "yields": st.yields,
                    "stolen_from": list(st.stolen_from),
                }
                for st in self.worker_stats
            ],
            "resilience": res,
        }

    def format_stats(self) -> str:
        lines = ["hclib_tpu runtime stats:"]
        for w, st in enumerate(self.worker_stats):
            lines.append(
                f"  worker {w}: executed={st.executed} spawned={st.spawned} "
                f"steals={st.steals} parks={st.parks} yields={st.yields}"
            )
        if (
            self.cancelled_tasks or self.task_retries or self.worker_deaths
            or self.quarantined or self.stall_reports
        ):
            lines.append(
                f"  resilience: cancelled={self.cancelled_tasks} "
                f"retries={self.task_retries} deaths={self.worker_deaths} "
                f"quarantined={self.quarantined} stalls={self.stall_reports}"
            )
        return "\n".join(lines)


# ---------------------------------------------------------------- public API


def launch(
    fn: Callable[..., Any],
    *args: Any,
    nworkers: Optional[int] = None,
    locality_graph: Optional[LocalityGraph] = None,
    stats: Optional[bool] = None,
    instrument: Optional[bool] = None,
    timer: Optional[bool] = None,
    watchdog_s: Optional[float] = None,
    watchdog_escalate: Optional[bool] = None,
    deadline_s: Optional[float] = None,
    fault_plan: Optional[FaultPlan] = None,
    default_retry: Optional[RetryPolicy] = None,
    metrics: Optional[bool] = None,
) -> Any:
    """Run ``fn`` inside a fresh runtime; returns its result."""
    return Runtime(
        nworkers=nworkers,
        locality_graph=locality_graph,
        stats=stats,
        instrument=instrument,
        timer=timer,
        watchdog_s=watchdog_s,
        watchdog_escalate=watchdog_escalate,
        fault_plan=fault_plan,
        default_retry=default_retry,
        metrics=metrics,
    ).run(fn, *args, deadline_s=deadline_s)


def async_(
    fn: Callable[..., Any],
    *args: Any,
    at: Optional[Locale] = None,
    await_: Sequence[Future] = (),
    non_blocking: bool = False,
    escaping: bool = False,
    retry: Optional[RetryPolicy] = None,
    **kwargs: Any,
) -> None:
    """Spawn a task under the current finish scope (hclib::async family,
    inc/hclib-async.h:162-547)."""
    current_runtime().spawn(
        fn,
        args,
        kwargs,
        locale=at,
        waiting_on=await_,
        non_blocking=non_blocking,
        escaping=escaping,
        retry=retry,
    )


def async_future(
    fn: Callable[..., Any],
    *args: Any,
    at: Optional[Locale] = None,
    await_: Sequence[Future] = (),
    non_blocking: bool = False,
    retry: Optional[RetryPolicy] = None,
    **kwargs: Any,
) -> Future:
    """Spawn and return a future satisfied with the task's return value
    (hclib_async_future: src/hclib.c:59-81)."""
    p = Promise()
    current_runtime().spawn(
        fn,
        args,
        kwargs,
        locale=at,
        waiting_on=await_,
        non_blocking=non_blocking,
        result_promise=p,
        retry=retry,
    )
    return p.future


def start_finish() -> Finish:
    fin = Finish(parent=_tls.current_finish)
    _tls.current_finish = fin
    return fin


def end_finish(
    fin: Optional[Finish] = None, timeout: Optional[float] = None
) -> None:
    cur = _tls.current_finish
    if fin is None:
        fin = cur
    if fin is None:
        raise RuntimeError("end_finish with no open finish scope")
    try:
        current_runtime().help_finish(fin, timeout=timeout)
    finally:
        # Pop the scope even if draining failed, so later spawns don't check
        # into a dead finish.
        _tls.current_finish = fin.parent


def end_finish_nonblocking(fin: Optional[Finish] = None) -> Future:
    """Close the scope without blocking; the returned future is satisfied
    when the scope drains (hclib_end_finish_nonblocking)."""
    cur = _tls.current_finish
    if fin is None:
        fin = cur
    if fin is None:
        raise RuntimeError("end_finish_nonblocking with no open finish scope")
    _tls.current_finish = fin.parent
    p = fin.arm_promise()
    if p is None:
        p = Promise()
        p.put(None)
    return p.future


class finish:
    """``with hclib_tpu.finish():`` context manager (hclib::finish,
    inc/hclib-async.h:550-563). ``timeout`` (seconds) bounds the join:
    past it the scope is cancelled and ``StallError`` raises."""

    def __init__(self, timeout: Optional[float] = None) -> None:
        self._fin: Optional[Finish] = None
        self._timeout = timeout

    def __enter__(self) -> Finish:
        self._fin = start_finish()
        return self._fin

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Drain children even when the body raised, so the scope's tasks are
        # not left running; task failures during the drain are recorded by
        # the runtime and re-raised at launch exit, never swallowed.
        try:
            end_finish(self._fin, timeout=self._timeout)
        except (CancelledError, StallError):
            if exc is None:
                raise
            # The body already failed with its own (more informative)
            # exception; the cancellation / timeout still took effect
            # (the scope is cancelled either way) and must not mask it.
        return False


def yield_(at: Optional[Locale] = None) -> bool:
    return current_runtime().yield_(at)


def run_on_main(fn: Callable[..., Any], *args: Any) -> Any:
    """Execute ``fn`` on the launch thread (hclib_run_on_main_ctx)."""
    return current_runtime().run_on_main(fn, *args)


def current_worker() -> int:
    wid = _tls.identity
    return -1 if wid is None else wid


def num_workers() -> int:
    return current_runtime().nworkers


def current_finish() -> Optional[Finish]:
    return _tls.current_finish
