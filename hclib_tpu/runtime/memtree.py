"""Pinned host-buffer registry: interval map over host address ranges.

The reference keeps an AVL tree of pinned host allocations so the CUDA
module can answer "is this pointer inside a pinned buffer?" before choosing
a fast DMA path (src/hclib-tree.c:8-11, hooked into the runtime context
under HC_CUDA, src/inc/hclib-internal.h:101-104).

The TPU analogue tracks host buffers registered for device transfer: a
buffer registered here is promised stable (not resized/moved/freed) for the
duration of its registration, so the tpu module's host->device copy handler
may hand it to ``jax.device_put`` zero-copy instead of taking a defensive
staging copy first.

Python needs no AVL rebalancing story - a sorted start-address list with
bisect gives O(log n) queries and O(n) inserts, and registrations are rare
and coarse (whole arrays, not sub-ranges).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass
from typing import Any, List, Optional

__all__ = ["MemoryTree", "PinnedEntry", "pin", "unpin", "lookup", "global_tree"]


@dataclass
class PinnedEntry:
    start: int
    length: int
    meta: Any = None

    @property
    def end(self) -> int:
        return self.start + self.length

    def contains(self, address: int) -> bool:
        return self.start <= address < self.end


class MemoryTree:
    """Interval map keyed by start address (reference API:
    hclib_memory_tree_insert/remove/contains)."""

    def __init__(self) -> None:
        self._starts: List[int] = []
        self._entries: List[PinnedEntry] = []
        self._lock = threading.Lock()

    def insert(self, start: int, length: int, meta: Any = None) -> PinnedEntry:
        if length <= 0:
            raise ValueError("length must be positive")
        e = PinnedEntry(start, length, meta)
        with self._lock:
            i = bisect.bisect_left(self._starts, start)
            # Overlap with the previous or next interval is a registration
            # bug (double pin / overlapping buffers) - reject loudly.
            if i > 0 and self._entries[i - 1].end > start:
                raise ValueError(f"overlaps existing range at {self._entries[i-1]}")
            if i < len(self._starts) and e.end > self._starts[i]:
                raise ValueError(f"overlaps existing range at {self._entries[i]}")
            self._starts.insert(i, start)
            self._entries.insert(i, e)
        return e

    def remove(self, start: int) -> PinnedEntry:
        """Remove the interval containing ``start`` (the reference removes
        by any interior address, src/hclib-tree.c remove)."""
        with self._lock:
            i = self._locate(start)
            if i is None:
                raise KeyError(f"no pinned range contains {start:#x}")
            self._starts.pop(i)
            return self._entries.pop(i)

    def contains(self, address: int) -> bool:
        return self.lookup(address) is not None

    def lookup(self, address: int) -> Optional[PinnedEntry]:
        with self._lock:
            i = self._locate(address)
            return self._entries[i] if i is not None else None

    def _locate(self, address: int) -> Optional[int]:
        i = bisect.bisect_right(self._starts, address) - 1
        if i >= 0 and self._entries[i].contains(address):
            return i
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_GLOBAL = MemoryTree()


def global_tree() -> MemoryTree:
    return _GLOBAL


def _addr_len(buf: Any) -> tuple:
    """(address, nbytes) of a numpy array's backing store."""
    import numpy as np

    a = np.asarray(buf)
    if not a.flags["C_CONTIGUOUS"]:
        raise ValueError("only contiguous buffers can be pinned")
    return a.ctypes.data, a.nbytes


def pin(buf: Any, meta: Any = None) -> PinnedEntry:
    """Register a host buffer as transfer-stable (zero-copy eligible)."""
    addr, n = _addr_len(buf)
    return _GLOBAL.insert(addr, n, meta if meta is not None else buf)


def unpin(buf: Any) -> PinnedEntry:
    addr, _ = _addr_len(buf)
    return _GLOBAL.remove(addr)


def lookup(buf: Any) -> Optional[PinnedEntry]:
    addr, _ = _addr_len(buf)
    return _GLOBAL.lookup(addr)
