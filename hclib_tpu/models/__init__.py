"""Benchmark workloads (the reference's acceptance suite, SURVEY.md section 4/6):

- fib: finish/async recursion + DDF variant (reference: test/fib/fib.c)
- uts: unbalanced tree search, canonical trees (reference: test/uts)
- cholesky: tiled Cholesky with promise/future tile deps (reference: test/cholesky)
- smithwaterman: 2D wavefront DP over per-tile promises (reference:
  test/smithwaterman/smith_waterman.cpp:77-180)
- arrayadd: flat forasync loops (reference: test/forasync/arrayadd)

Each model runs on the host runtime (CPU baseline) and, where implemented, on
the device megakernel (hclib_tpu.device).
"""
