"""Recursive Fibonacci - the canonical finish/async microbenchmark.

Two variants, as in the reference (test/fib/fib.c and test/misc fib-ddt):
- ``fib_finish``: nested finish + async pairs (blocking joins).
- ``fib_ddf``: data-driven futures, no blocking anywhere.

The metric is tasks/sec: fib(n) spawns ~2*F(n+1)-1 tasks
(fib prints "Throughput (op/s)", reference test/fib/fib.c:29-33).
"""

from __future__ import annotations

import time

import hclib_tpu as hc

__all__ = ["fib_finish", "fib_ddf", "run", "fib_seq", "task_count"]


def fib_seq(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def task_count(n: int) -> int:
    """Number of recursive calls in naive fib(n): 2*F(n+1) - 1."""
    return 2 * fib_seq(n + 1) - 1


def fib_finish(n: int, cutoff: int = 2) -> int:
    """fib via nested finish/async. ``cutoff`` switches to sequential
    recursion below the threshold (the reference's PR1 config uses none)."""
    if n < cutoff:
        return fib_seq(n)
    out = [0, 0]

    def child(m: int, slot: int) -> None:
        out[slot] = fib_finish(m, cutoff)

    with hc.finish():
        hc.async_(child, n - 1, 0)
        hc.async_(child, n - 2, 1)
    return out[0] + out[1]


def fib_ddf(n: int, cutoff: int = 2) -> hc.Future:
    """fib via futures: each node is a non-blocking task awaiting its two
    children's futures."""
    if n < cutoff:
        return hc.async_future(fib_seq, n, non_blocking=True)
    a = fib_ddf(n - 1, cutoff)
    b = fib_ddf(n - 2, cutoff)
    return hc.async_future(
        lambda: a.get() + b.get(), await_=[a, b], non_blocking=True
    )


def run(n: int = 25, variant: str = "finish", nworkers=None, cutoff: int = 2,
        **launch_kwargs) -> dict:
    """Launch, compute fib(n), return {value, tasks, seconds, tasks_per_sec}.
    Extra keywords (deadline_s, fault_plan, default_retry, ...) pass through
    to ``hclib_tpu.launch`` - the chaos harness injects faults this way."""
    t0 = time.perf_counter()
    if variant == "finish":
        value = hc.launch(fib_finish, n, cutoff, nworkers=nworkers,
                          **launch_kwargs)
    elif variant == "ddf":
        value = hc.launch(lambda: fib_ddf(n, cutoff).wait(),
                          nworkers=nworkers, **launch_kwargs)
    else:
        raise ValueError(f"unknown fib variant {variant!r}")
    dt = time.perf_counter() - t0
    expected = fib_seq(n)
    if value != expected:
        raise AssertionError(f"fib({n}) = {value}, expected {expected}")
    # Task count for the throughput metric: nodes(m) = 1 + nodes(m-1) +
    # nodes(m-2) with nodes(m<cutoff) = 1, computed iteratively.
    lo = max(cutoff, 2)
    counts = [1] * lo
    for m in range(lo, n + 1):
        counts.append(1 + counts[m - 1] + counts[m - 2])
    tasks = counts[n]
    return {
        "value": value,
        "tasks": tasks,
        "seconds": dt,
        "tasks_per_sec": tasks / dt if dt > 0 else float("inf"),
    }


if __name__ == "__main__":  # pragma: no cover
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    variant = sys.argv[2] if len(sys.argv) > 2 else "finish"
    print(run(n, variant))
