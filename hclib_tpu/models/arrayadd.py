"""Flat forasync loops: elementwise array add (reference: test/forasync/arrayadd).

1D and 2D variants over numpy buffers; the device analogue is a grid of tile
task descriptors executed by the megakernel (or, when the loop is regular,
a straight Pallas grid - which is what a TPU-first design prefers).
"""

from __future__ import annotations

import time

import numpy as np

import hclib_tpu as hc

__all__ = ["arrayadd_1d", "arrayadd_2d", "run"]


def arrayadd_1d(n: int, tile: int = 4096, mode: str = hc.FLAT) -> np.ndarray:
    a = np.arange(n, dtype=np.float64)
    b = 2.0 * np.arange(n, dtype=np.float64)
    c = np.zeros(n, dtype=np.float64)

    def main() -> None:
        def body(i: int) -> None:
            c[i] = a[i] + b[i]

        hc.forasync(body, [n], tile=tile, mode=mode)

    hc.launch(main)
    assert np.array_equal(c, 3.0 * np.arange(n)), "arrayadd_1d mismatch"
    return c


def arrayadd_2d(n: int, m: int, tile=(64, 64), mode: str = hc.FLAT) -> np.ndarray:
    a = np.fromfunction(lambda i, j: i + j, (n, m))
    b = np.fromfunction(lambda i, j: i * j, (n, m))
    c = np.zeros((n, m))

    def main() -> None:
        def body(i: int, j: int) -> None:
            c[i, j] = a[i, j] + b[i, j]

        hc.forasync(body, [n, m], tile=list(tile), mode=mode)

    hc.launch(main)
    assert np.array_equal(c, a + b), "arrayadd_2d mismatch"
    return c


def run(n: int = 1 << 20, tile: int = 1 << 14) -> dict:
    t0 = time.perf_counter()
    arrayadd_1d(n, tile)
    dt = time.perf_counter() - t0
    ntasks = (n + tile - 1) // tile
    return {"n": n, "tile": tile, "seconds": dt, "tasks": ntasks}


if __name__ == "__main__":  # pragma: no cover
    print(run())
