"""Tiled Cholesky factorization as a promise/future dataflow DAG.

The reference version (test/cholesky/cholesky.cpp) expresses the classic
right-looking tiled algorithm as data-driven tasks. This rebuild uses the
same dependency structure with tiles updated in place:

Let U[i,j,k] be the completion future of tile (i,j) after applying the rank-k
update (U[i,j,-1] = initial tile ready):

- potrf(k):   awaits U[k,k,k-1]                -> L[k,k]   (future P[k])
- trsm(i,k):  awaits U[i,k,k-1], P[k]          -> L[i,k]   (future S[i,k])
- syrk(i,k):  awaits U[i,i,k-1], S[i,k]        -> U[i,i,k]
- gemm(i,j,k):awaits U[i,j,k-1], S[i,k], S[j,k]-> U[i,j,k]   (i > j > k)

Every tile's update chain is serialized through U, so in-place numpy tile
mutation is race-free. The device variant (device/workloads.py) runs the same
DAG inside the Pallas megakernel with MXU tile kernels.

Self-check: reconstructed L L^T must match the input (the reference diffs
against a golden file, test/cholesky/run.sh:1-8).
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import numpy as np

import hclib_tpu as hc

__all__ = ["cholesky_tiled", "run", "make_spd"]


def make_spd(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    return a @ a.T + n * np.eye(n)


def cholesky_tiled(a: np.ndarray, tile: int, nworkers=None) -> np.ndarray:
    """Factor SPD ``a`` (n x n, n % tile == 0) into lower-triangular L using
    the DDF task graph; returns L."""
    n = a.shape[0]
    if n % tile != 0:
        raise ValueError("matrix size must be a multiple of the tile size")
    nt = n // tile
    # Tile views; tasks mutate tiles of `w` in place.
    w = a.copy()

    def T(i: int, j: int) -> np.ndarray:
        return w[i * tile : (i + 1) * tile, j * tile : (j + 1) * tile]

    def main() -> None:
        # U[(i, j)] = future of the most recent update of tile (i, j);
        # rebound as the DAG is built (build order follows k).
        U: Dict[Tuple[int, int], hc.Future] = {}
        P: Dict[int, hc.Future] = {}
        S: Dict[Tuple[int, int], hc.Future] = {}

        def deps(*futs) -> list:
            return [f for f in futs if f is not None]

        def potrf(k: int) -> None:
            t = T(k, k)
            np.copyto(t, np.linalg.cholesky(t))

        def trsm(i: int, k: int) -> None:
            # Solve X L[k,k]^T = A[i,k]  ->  X = A[i,k] L[k,k]^-T
            lkk = T(k, k)
            t = T(i, k)
            np.copyto(t, np.linalg.solve(lkk, t.T).T)

        def syrk(i: int, k: int) -> None:
            lik = T(i, k)
            t = T(i, i)
            t -= lik @ lik.T

        def gemm(i: int, j: int, k: int) -> None:
            t = T(i, j)
            t -= T(i, k) @ T(j, k).T

        with hc.finish():
            for k in range(nt):
                P[k] = hc.async_future(
                    potrf, k, await_=deps(U.get((k, k))), non_blocking=True
                )
                for i in range(k + 1, nt):
                    S[(i, k)] = hc.async_future(
                        trsm, i, k,
                        await_=deps(U.get((i, k)), P[k]),
                        non_blocking=True,
                    )
                for i in range(k + 1, nt):
                    U[(i, i)] = hc.async_future(
                        syrk, i, k,
                        await_=deps(U.get((i, i)), S[(i, k)]),
                        non_blocking=True,
                    )
                    for j in range(k + 1, i):
                        U[(i, j)] = hc.async_future(
                            gemm, i, j, k,
                            await_=deps(U.get((i, j)), S[(i, k)], S[(j, k)]),
                            non_blocking=True,
                        )

    hc.launch(main, nworkers=nworkers)
    return np.tril(w)


def run(n: int = 512, tile: int = 64, nworkers=None) -> dict:
    a = make_spd(n)
    t0 = time.perf_counter()
    L = cholesky_tiled(a, tile, nworkers=nworkers)
    dt = time.perf_counter() - t0
    err = float(np.max(np.abs(L @ L.T - a)))
    nt = n // tile
    # nt potrf + nt(nt-1)/2 trsm + nt(nt-1)(nt+1)/6 syrk/gemm
    ntasks = nt + nt * (nt - 1) // 2 + nt * (nt - 1) * (nt + 1) // 6
    gflops = (n**3 / 3.0) / dt / 1e9
    return {
        "n": n,
        "tile": tile,
        "max_error": err,
        "seconds": dt,
        "gflops": gflops,
        "tasks": ntasks,
        "ok": err < 1e-6 * n,
    }


if __name__ == "__main__":  # pragma: no cover
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    tile = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    print(run(n, tile))
