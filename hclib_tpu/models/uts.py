"""UTS - Unbalanced Tree Search.

Re-implementation of the UTS benchmark tree specification (reference:
test/uts/uts.c, test/uts/rng/brg_sha1.c) from its published algorithm:

- Node state: 20-byte SHA-1 digest. Root: SHA1(16 zero bytes || BE32(seed))
  (rng_init, test/uts/rng/brg_sha1.c:49-65). Child i of a node:
  SHA1(parent_state || BE32(i)) (rng_spawn, :67-81).
- rng_rand: last 4 state bytes, big-endian, masked positive
  (:83-93); toProb = r / 2^31 (test/uts/uts.c:143-148).
- GEO child count (test/uts/uts.c:171-221): target branching b_i from the
  shape function - LINEAR: b0*(1 - d/gen_mx); EXPDEC: b0*d^(-ln b0/ln gen_mx);
  CYCLIC; FIXED: b0 while d < gen_mx else 0 - then p = 1/(1+b_i) and
  numChildren = floor(log(1-u)/log(1-p)), capped at 100 (uts.h:31).

Canonical trees (test/uts/sample_trees.sh): T1 = GEO/FIXED d=10 b=4 r=19
(4,130,071 nodes); T1L = GEO/FIXED d=13 b=4 r=29 (102,181,082 nodes).

The parallel traversal spawns one task per node (work-stealing stress). The
device path (device/) runs the same tree with an on-chip SHA-1 in the
megakernel.
"""

from __future__ import annotations

import hashlib
import math
import struct
import time
from dataclasses import dataclass
from typing import List, Tuple

import hclib_tpu as hc

__all__ = [
    "UTSParams", "T1", "T1L", "T1XL", "T1XXL", "T2", "T3", "T5",
    "count_seq", "count_parallel", "run",
]

MAX_CHILDREN = 100  # MAXNUMCHILDREN (reference: test/uts/uts.h:31)

LINEAR, EXPDEC, CYCLIC, FIXED = 0, 1, 2, 3  # geoshape enum (uts.h:65)


@dataclass(frozen=True)
class UTSParams:
    shape: int = FIXED  # -a
    gen_mx: int = 10  # -d (tree depth)
    b0: float = 4.0  # -b (branching factor)
    root_seed: int = 19  # -r


# Canonical trees (reference: test/uts/sample_trees.sh:18,37)
T1 = UTSParams(shape=FIXED, gen_mx=10, b0=4.0, root_seed=19)  # 4,130,071 nodes
T1L = UTSParams(shape=FIXED, gen_mx=13, b0=4.0, root_seed=29)  # 102,181,082 nodes
# Canonical depth-varying trees (test/uts/sample_trees.sh:20-24):
T5 = UTSParams(shape=LINEAR, gen_mx=20, b0=4.0, root_seed=34)  # 4,147,582
T2 = UTSParams(shape=CYCLIC, gen_mx=16, b0=6.0, root_seed=502)  # 4,117,769
# test/uts/sample_trees.sh XL/XXL geometric trees. Per-lane counters stay
# well under int32 for both; T1XXL's 4.23B TOTAL exceeds int32, which is
# why engine totals are summed in int64 on the host.
T1XL = UTSParams(shape=FIXED, gen_mx=15, b0=4.0, root_seed=29)  # 1,635,119,272
T1XXL = UTSParams(shape=FIXED, gen_mx=15, b0=4.0, root_seed=19)  # 4,230,646,601
T3 = UTSParams(shape=FIXED, gen_mx=5, b0=4.0, root_seed=42)  # small, for tests


def root_state(seed: int) -> bytes:
    return hashlib.sha1(b"\x00" * 16 + struct.pack(">i", seed)).digest()


def spawn_state(parent: bytes, i: int) -> bytes:
    return hashlib.sha1(parent + struct.pack(">i", i)).digest()


def rng_rand(state: bytes) -> int:
    return struct.unpack(">I", state[16:20])[0] & 0x7FFFFFFF


def _branching(params: UTSParams, depth: int) -> float:
    if depth <= 0:
        return params.b0
    if params.shape == LINEAR:
        return params.b0 * (1.0 - depth / params.gen_mx)
    if params.shape == EXPDEC:
        return params.b0 * depth ** (-math.log(params.b0) / math.log(params.gen_mx))
    if params.shape == CYCLIC:
        if depth > 5 * params.gen_mx:
            return 0.0
        return params.b0 ** math.sin(2.0 * math.pi * depth / params.gen_mx)
    if params.shape == FIXED:
        return params.b0 if depth < params.gen_mx else 0.0
    raise ValueError(f"unknown shape {params.shape}")


def num_children(params: UTSParams, state: bytes, depth: int) -> int:
    b_i = _branching(params, depth)
    if b_i <= 0.0:
        return 0
    p = 1.0 / (1.0 + b_i)
    u = rng_rand(state) / 2147483648.0
    n = int(math.floor(math.log(1.0 - u) / math.log(1.0 - p)))
    return min(n, MAX_CHILDREN)


def count_seq(params: UTSParams) -> Tuple[int, int, int]:
    """Sequential traversal; returns (nodes, leaves, max_depth)."""
    nodes = leaves = max_depth = 0
    stack = [(root_state(params.root_seed), 0)]
    while stack:
        state, depth = stack.pop()
        nodes += 1
        max_depth = max(max_depth, depth)
        nc = num_children(params, state, depth)
        if nc == 0:
            leaves += 1
        for i in range(nc):
            stack.append((spawn_state(state, i), depth + 1))
    return nodes, leaves, max_depth


def count_parallel(params: UTSParams, nworkers=None, grain: int = 1,
                   **launch_kwargs) -> Tuple[int, int, int]:
    """Task-parallel traversal. grain=1 spawns one async per node (the
    reference's per-node tasking); grain>1 makes each task expand up to
    ``grain`` nodes depth-first locally before spawning the rest of its
    frontier as new tasks (amortizes task overhead, keeps stealable slack).
    Extra keywords (deadline_s, fault_plan, default_retry, ...) pass through
    to ``hclib_tpu.launch`` - the chaos harness injects faults this way."""

    def main():
        nodes = hc.SumReducer()
        leaves = hc.SumReducer()
        depth_r = hc.MaxReducer(0)

        def visit(state: bytes, depth: int) -> None:
            stack: List[Tuple[bytes, int]] = [(state, depth)]
            processed = 0
            while stack:
                if processed >= grain:
                    # Hand the remaining frontier to new tasks.
                    for s, d in stack:
                        hc.async_(visit, s, d)
                    return
                s, d = stack.pop()
                processed += 1
                nodes.add(1)
                depth_r.put(d)
                nc = num_children(params, s, d)
                if nc == 0:
                    leaves.add(1)
                    continue
                for i in range(nc):
                    stack.append((spawn_state(s, i), d + 1))

        with hc.finish():
            hc.async_(visit, root_state(params.root_seed), 0)
        return nodes.gather(), leaves.gather(), depth_r.gather()

    return hc.launch(main, nworkers=nworkers, **launch_kwargs)


def run(params: UTSParams = T3, nworkers=None, **launch_kwargs) -> dict:
    t0 = time.perf_counter()
    nodes, leaves, max_depth = count_parallel(params, nworkers=nworkers,
                                              **launch_kwargs)
    dt = time.perf_counter() - t0
    return {
        "nodes": nodes,
        "leaves": leaves,
        "max_depth": max_depth,
        "seconds": dt,
        "tasks_per_sec": nodes / dt if dt > 0 else float("inf"),
    }


if __name__ == "__main__":  # pragma: no cover
    import sys

    name = sys.argv[1] if len(sys.argv) > 1 else "T3"
    params = {"T1": T1, "T1L": T1L, "T2": T2, "T3": T3, "T5": T5}[name]
    print(run(params))
