"""Parallel sorting: qsort and cilksort.

Two of the reference's performance-regression apps (test/performance-
regression/full-apps; BASELINE.md rows qsort/cilksort, BOTS-derived).

- ``qsort_par``: quicksort - partition, spawn the two halves, sequential
  (numpy introsort) below a threshold.
- ``cilksort``: the classic cilksort - 4-way split mergesort whose merges
  are themselves recursively parallel (binary-search split of the larger
  run), so both the sort and the merge phases scale.

Arrays are numpy; leaf sorts vectorize (np.sort is the "registered kernel"
the tasks dispatch - the device analogue is a bitonic tile sort on the VPU).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import hclib_tpu as hc

__all__ = ["qsort_par", "cilksort", "run"]


# ---------------------------------------------------------------------- qsort


def _qsort_range(a: np.ndarray, lo: int, hi: int, threshold: int) -> None:
    while hi - lo > threshold:
        # median-of-three pivot, Hoare partition
        mid = (lo + hi) // 2
        p = sorted((a[lo], a[mid], a[hi - 1]))[1]
        i, j = lo, hi - 1
        while i <= j:
            while a[i] < p:
                i += 1
            while a[j] > p:
                j -= 1
            if i <= j:
                a[i], a[j] = a[j], a[i]
                i += 1
                j -= 1
        # Spawn the smaller side, iterate on the larger (bounded task depth).
        if j + 1 - lo < hi - i:
            hc.async_(_qsort_range, a, lo, j + 1, threshold)
            lo = i
        else:
            hc.async_(_qsort_range, a, i, hi, threshold)
            hi = j + 1
    a[lo:hi] = np.sort(a[lo:hi], kind="quicksort")


def qsort_par(a: np.ndarray, threshold: int = 4096) -> np.ndarray:
    """In-place parallel quicksort under one finish scope."""
    with hc.finish():
        hc.async_(_qsort_range, a, 0, len(a), threshold)
    return a


# ------------------------------------------------------------------- cilksort


def _merge_seq(src: np.ndarray, lo1: int, hi1: int, lo2: int, hi2: int,
               dst: np.ndarray, out: int) -> None:
    n1, n2 = hi1 - lo1, hi2 - lo2
    merged = np.empty(n1 + n2, dtype=src.dtype)
    a, b = src[lo1:hi1], src[lo2:hi2]
    # vectorized two-run merge via searchsorted
    pos_a = np.searchsorted(b, a, side="right") + np.arange(n1)
    merged[pos_a] = a
    mask = np.ones(n1 + n2, dtype=bool)
    mask[pos_a] = False
    merged[mask] = b
    dst[out:out + n1 + n2] = merged


def _merge_par(src: np.ndarray, lo1: int, hi1: int, lo2: int, hi2: int,
               dst: np.ndarray, out: int, threshold: int) -> None:
    """Parallel merge: split the larger run at its midpoint, binary-search
    the split value in the other run, merge halves in parallel (cilksort's
    cilkmerge shape)."""
    if (hi1 - lo1) + (hi2 - lo2) <= threshold:
        _merge_seq(src, lo1, hi1, lo2, hi2, dst, out)
        return
    if hi1 - lo1 < hi2 - lo2:
        lo1, hi1, lo2, hi2 = lo2, hi2, lo1, hi1
    mid1 = (lo1 + hi1) // 2
    split = int(np.searchsorted(src[lo2:hi2], src[mid1])) + lo2
    left_out = out
    right_out = out + (mid1 - lo1) + (split - lo2)
    hc.async_(_merge_par, src, lo1, mid1, lo2, split, dst, left_out, threshold)
    hc.async_(_merge_par, src, mid1, hi1, split, hi2, dst, right_out, threshold)


def _cilksort_range(a: np.ndarray, tmp: np.ndarray, lo: int, hi: int,
                    threshold: int) -> None:
    n = hi - lo
    if n <= threshold:
        a[lo:hi] = np.sort(a[lo:hi])
        return
    q = n // 4
    cuts = [lo, lo + q, lo + 2 * q, lo + 3 * q, hi]
    with hc.finish():
        for i in range(4):
            hc.async_(_cilksort_range, a, tmp, cuts[i], cuts[i + 1], threshold)
    with hc.finish():
        hc.async_(_merge_par, a, cuts[0], cuts[1], cuts[1], cuts[2], tmp, cuts[0],
                  threshold)
        hc.async_(_merge_par, a, cuts[2], cuts[3], cuts[3], cuts[4], tmp, cuts[2],
                  threshold)
    with hc.finish():
        hc.async_(_merge_par, tmp, cuts[0], cuts[2], cuts[2], cuts[4], a, cuts[0],
                  threshold)


def cilksort(a: np.ndarray, threshold: int = 4096) -> np.ndarray:
    tmp = np.empty_like(a)
    with hc.finish():
        hc.async_(_cilksort_range, a, tmp, 0, len(a), threshold)
    return a


# ----------------------------------------------------------------------- run


def run(n: int = 1 << 20, variant: str = "cilksort", threshold: int = 4096,
        nworkers: Optional[int] = None, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 1 << 30, size=n).astype(np.int64)
    expect = np.sort(a.copy())
    t0 = time.perf_counter()
    if variant == "qsort":
        hc.launch(qsort_par, a, threshold, nworkers=nworkers)
    elif variant == "cilksort":
        hc.launch(cilksort, a, threshold, nworkers=nworkers)
    else:
        raise ValueError(f"unknown sort variant {variant!r}")
    dt = time.perf_counter() - t0
    if not np.array_equal(a, expect):
        raise AssertionError(f"{variant} produced an unsorted array")
    return {"n": n, "seconds": dt, "keys_per_sec": n / dt if dt > 0 else float("inf")}


if __name__ == "__main__":  # pragma: no cover
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    variant = sys.argv[2] if len(sys.argv) > 2 else "cilksort"
    print(run(n, variant))
