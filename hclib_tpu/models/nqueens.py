"""N-Queens solution counting - irregular task recursion.

One of the reference's performance-regression apps (test/performance-
regression/full-apps, BOTS nqueens; baseline row in BASELINE.md). Each
placement level spawns one task per safe column; counts accumulate through
worker-local reducers (hclib_tpu.runtime.reducers - the reference's
atomic_sum_t, inc/hclib_atomic.h:82-186) instead of a shared atomic.
"""

from __future__ import annotations

import time
from typing import List

import hclib_tpu as hc

__all__ = ["nqueens_count", "run", "KNOWN_COUNTS"]

# Known solution counts for self-checking.
KNOWN_COUNTS = {1: 1, 2: 0, 3: 0, 4: 2, 5: 10, 6: 4, 7: 40, 8: 92, 9: 352,
                10: 724, 11: 2680, 12: 14200, 13: 73712}


def _safe(cols: List[int], row: int, col: int) -> bool:
    for r, c in enumerate(cols[:row]):
        if c == col or abs(c - col) == row - r:
            return False
    return True


def _count_seq(cols: List[int], row: int, n: int) -> int:
    if row == n:
        return 1
    total = 0
    for col in range(n):
        if _safe(cols, row, col):
            cols[row] = col
            total += _count_seq(cols, row + 1, n)
    return total


def nqueens_count(n: int, cutoff: int = 3) -> int:
    """Parallel count: spawn per safe column until ``cutoff`` levels deep,
    then sequential search; sum via a worker-local reducer."""
    total = hc.SumReducer(0)

    def explore(cols: List[int], row: int) -> None:
        if row >= cutoff:
            total.add(_count_seq(list(cols), row, n))
            return
        for col in range(n):
            if _safe(cols, row, col):
                hc.async_(explore, cols[:row] + [col] + [0] * (n - row - 1), row + 1)

    with hc.finish():
        hc.async_(explore, [0] * n, 0)
    return total.gather()


def run(n: int = 8, cutoff: int = 3, nworkers=None) -> dict:
    t0 = time.perf_counter()
    value = hc.launch(nqueens_count, n, cutoff, nworkers=nworkers)
    dt = time.perf_counter() - t0
    if n in KNOWN_COUNTS and value != KNOWN_COUNTS[n]:
        raise AssertionError(f"nqueens({n}) = {value}, expected {KNOWN_COUNTS[n]}")
    return {"value": value, "seconds": dt, "n": n}


if __name__ == "__main__":  # pragma: no cover
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    print(run(n))
