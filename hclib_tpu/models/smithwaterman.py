"""Smith-Waterman local alignment as a 2D wavefront of tile tasks.

The reference (test/smithwaterman/smith_waterman.cpp:77-180) tiles the DP
matrix and gives every tile a promise; tile (i,j) awaits its left, upper, and
diagonal neighbors' promises, fills its block of the score matrix, then puts
its own promise - a 2D data-driven wavefront. Same structure here; the score
recurrence is the classic affine-free SW:

    H[i,j] = max(0, H[i-1,j-1] + sub(a_i, b_j), H[i-1,j] - gap, H[i,j-1] - gap)

Self-check: the task-parallel tiled result must equal the sequential DP.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import numpy as np

import hclib_tpu as hc

__all__ = ["sw_seq", "sw_tiled", "run", "random_seq"]

MATCH = 2
MISMATCH = -1
GAP = 1


def random_seq(n: int, seed: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(0, 4, size=n, dtype=np.int32)


def sw_seq(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sequential reference DP; returns the full (len(a)+1, len(b)+1) H."""
    h = np.zeros((len(a) + 1, len(b) + 1), dtype=np.int32)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            sub = MATCH if a[i - 1] == b[j - 1] else MISMATCH
            h[i, j] = max(
                0, h[i - 1, j - 1] + sub, h[i - 1, j] - GAP, h[i, j - 1] - GAP
            )
    return h


def _fill_tile(h: np.ndarray, a: np.ndarray, b: np.ndarray,
               i0: int, i1: int, j0: int, j1: int) -> None:
    for i in range(i0, i1):
        ai = a[i - 1]
        for j in range(j0, j1):
            sub = MATCH if ai == b[j - 1] else MISMATCH
            v = h[i - 1, j - 1] + sub
            u = h[i - 1, j] - GAP
            l = h[i, j - 1] - GAP
            m = v if v > u else u
            if l > m:
                m = l
            h[i, j] = m if m > 0 else 0


def sw_tiled(a: np.ndarray, b: np.ndarray, tile: int) -> np.ndarray:
    """Task-parallel tiled SW over the wavefront DAG; returns H."""
    n, m = len(a), len(b)
    h = np.zeros((n + 1, m + 1), dtype=np.int32)
    nt_i = (n + tile - 1) // tile
    nt_j = (m + tile - 1) // tile

    def main() -> None:
        done: Dict[Tuple[int, int], hc.Future] = {}
        with hc.finish():
            for ti in range(nt_i):
                for tj in range(nt_j):
                    deps = [
                        done[key]
                        for key in ((ti - 1, tj), (ti, tj - 1), (ti - 1, tj - 1))
                        if key in done
                    ]
                    i0, i1 = ti * tile + 1, min((ti + 1) * tile, n) + 1
                    j0, j1 = tj * tile + 1, min((tj + 1) * tile, m) + 1
                    done[(ti, tj)] = hc.async_future(
                        _fill_tile, h, a, b, i0, i1, j0, j1,
                        await_=deps, non_blocking=True,
                    )

    hc.launch(main)
    return h


def run(n: int = 512, m: int = 512, tile: int = 64) -> dict:
    a, b = random_seq(n, 1), random_seq(m, 2)
    t0 = time.perf_counter()
    h = sw_tiled(a, b, tile)
    dt = time.perf_counter() - t0
    nt = ((n + tile - 1) // tile) * ((m + tile - 1) // tile)
    return {
        "n": n,
        "m": m,
        "tile": tile,
        "score": int(h.max()),
        "seconds": dt,
        "tiles": nt,
        "tasks_per_sec": nt / dt if dt > 0 else float("inf"),
    }


if __name__ == "__main__":  # pragma: no cover
    print(run())
