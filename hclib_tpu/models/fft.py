"""Task-parallel FFT (Cooley-Tukey).

One of the reference's performance-regression apps (test/performance-
regression/full-apps FFT; BASELINE.md row). Radix-2 decimation-in-time:
each level spawns the even/odd half-transforms as tasks, switching to the
vectorized leaf transform (np.fft) below a threshold; the butterfly combine
is a vectorized twiddle multiply. Self-checks against np.fft.fft.

The device-path analogue dispatches leaf transforms as tiles through
``modules.tpu.async_device`` (XLA lowers jnp.fft.fft to the TPU's FFT
fusion); ``run(device=True)`` exercises it.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import hclib_tpu as hc

__all__ = ["fft_par", "run"]


def _fft_task(x: np.ndarray, out: np.ndarray, threshold: int) -> None:
    n = len(x)
    if n <= threshold:
        out[:] = np.fft.fft(x)
        return
    half = n // 2
    even_out = np.empty(half, dtype=np.complex128)
    odd_out = np.empty(half, dtype=np.complex128)
    with hc.finish():
        hc.async_(_fft_task, x[0::2], even_out, threshold)
        hc.async_(_fft_task, x[1::2], odd_out, threshold)
    tw = np.exp(-2j * np.pi * np.arange(half) / n) * odd_out
    out[:half] = even_out + tw
    out[half:] = even_out - tw


def fft_par(x: np.ndarray, threshold: int = 1 << 12) -> np.ndarray:
    n = len(x)
    if n & (n - 1):
        raise ValueError("fft_par requires power-of-two length")
    out = np.empty(n, dtype=np.complex128)
    with hc.finish():
        hc.async_(_fft_task, np.asarray(x, dtype=np.complex128), out, threshold)
    return out


def _fft_device(x: np.ndarray) -> np.ndarray:
    """One fused device dispatch (jnp.fft.fft) via the tpu module."""
    import jax.numpy as jnp

    from ..modules.tpu import async_device

    return np.asarray(async_device(jnp.fft.fft, x.astype(np.complex64)).wait())


def run(n: int = 1 << 16, threshold: int = 1 << 12,
        nworkers: Optional[int] = None, seed: int = 0,
        device: bool = False) -> dict:
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    expect = np.fft.fft(x)
    t0 = time.perf_counter()
    if device:
        from ..modules.tpu import TpuModule
        from ..parallel.mesh import cpu_mesh, mesh_locality_graph
        import jax

        hc.register_module(TpuModule())
        graph = mesh_locality_graph(cpu_mesh(len(jax.devices("cpu"))))
        out = hc.launch(_fft_device, x, locality_graph=graph)
        tol = 1e-2  # complex64 on device
    else:
        out = hc.launch(fft_par, x, threshold, nworkers=nworkers)
        tol = 1e-8
    dt = time.perf_counter() - t0
    err = float(np.max(np.abs(out - expect)) / np.max(np.abs(expect)))
    if err > tol:
        raise AssertionError(f"fft mismatch: rel err {err}")
    return {"n": n, "seconds": dt, "rel_err": err,
            "points_per_sec": n / dt if dt > 0 else float("inf")}


if __name__ == "__main__":  # pragma: no cover
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 16
    print(run(n))
