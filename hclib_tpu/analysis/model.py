"""Schedule-independence certification (the ``schedule-independence``
rule) - the third model-checker analysis.

The frontier traversals (BFS/SSSP as monotone label correction,
PageRank as conserved integer mass) and the forasync tile loops claim
their results are independent of execution order - that claim is what
lets "bit-identical across scalar dispatch, batched tier, and the
mesh" hold with no ordering machinery, and what makes their rows
migratable/reshardable without replay. This module CHECKS the claim
instead of trusting the docstring: run the kernel's abstract body
(the same relax/compute trace the device executes, host-side over
concrete numpy state) to the fixpoint under K permuted pop orders and
prove the final state identical. Identical -> a certificate surfaced in
``Megakernel.describe()`` beside the reshard classification; divergent
-> certification is REFUSED with both schedules in the diagnostic (an
``AnalysisError`` whose witness carries the two pop orders and the
first differing word).

Like every hclint analysis this is host-only composition - no Pallas
build, no Mosaic - and lazy: builders stamp ``mk.si_claim`` at
construction for free, and the certification runs on demand
(describe(), tools/hclint.py, the CI step), memoized per claim.

A certificate is evidence over K orders of a seeded configuration, not
a proof over all schedules - which is exactly the exactness contract
the runtime leans on (the acceptance suites then pin bit-identity on
the real schedules). K rides ``HCLIB_TPU_MODEL_PERMS``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.env import env_int
from .findings import ERROR, AnalysisReport
from .shim import BodyTrace, FakeRef, _norm_box, _patched

__all__ = [
    "certify_claim",
    "certify_bnb_schedule",
    "certify_frontier_schedule",
    "certify_tile_schedule",
]

RULE = "schedule-independence"

# Tile spaces above this are not concretely simulated K times at
# describe() time (the certificate would cost more than the build);
# hclint's curated spaces sit far below it.
TILE_SPACE_CAP = 4096
# Fixpoint step cap: a (buggy) diverging claim terminates the
# certification instead of the process.
STEP_CAP = 200_000

_frontier_cache: Dict[Tuple, Dict[str, Any]] = {}

import weakref  # noqa: E402

_tile_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _perms() -> int:
    return max(2, env_int("HCLIB_TPU_MODEL_PERMS", 3))


def _np_index(box) -> Tuple:
    return tuple(slice(lo, hi) for lo, hi in box)


def _fill(shape, dtype, salt: int) -> np.ndarray:
    """Deterministic synthetic buffer contents (iota + salt, wrapped
    small so int dtypes never overflow under arithmetic bodies)."""
    n = int(np.prod(shape)) if shape else 1
    base = (np.arange(n, dtype=np.int64) * 7 + salt * 13) % 97
    return base.reshape(shape).astype(dtype)


def _finding_jsonable(f) -> List[Dict[str, Any]]:
    return [f.to_jsonable()]


def _schedule_witness(order: Sequence, cap: int = 16) -> List:
    out = [list(map(int, np.atleast_1d(o))) if not np.isscalar(o)
           else int(o) for o in list(order)[:cap]]
    if len(order) > cap:
        out.append(f"... {len(order) - cap} more")
    return out


# ------------------------------------------------------------ tiles


def certify_tile_schedule(tk, bounds, tile, *,
                          perms: Optional[int] = None, seed: int = 0,
                          report: Optional[AnalysisReport] = None,
                          raise_on_error: bool = True) -> Dict[str, Any]:
    """Certify one forasync tile loop: execute every tile's
    load->compute->store pipeline concretely over synthetic buffers in
    K permuted orders; identical final buffers = certified. A tile
    whose LOADS overlap another tile's STORES is order-dependent (the
    in-place-stencil bug class) and diverges concretely - refused with
    the two schedules."""
    from ..device.forasync_tier import tile_args, tile_grid

    perms = _perms() if perms is None else int(perms)
    key = (repr(tuple(bounds)),
           repr(tuple(tile) if not isinstance(tile, int) else (tile,)),
           perms, seed)
    cached = _tile_cache.get(tk)
    if cached is not None and key in cached:
        return cached[key]
    dims, tile_dims, counts, total = tile_grid(bounds, tile)
    cert: Dict[str, Any] = {
        "claim": "forasync-tiles", "kernel": tk.name, "tiles": total,
        "orders": perms,
    }
    if total > TILE_SPACE_CAP:
        cert["status"] = f"unverified (tile space {total} > cap)"
        return cert

    def run_order(order) -> Dict[str, np.ndarray]:
        bufs = {
            name: _fill(tuple(spec.shape), np.dtype(spec.dtype), si)
            for si, (name, spec) in enumerate(sorted(
                tk.data_specs.items()
            ))
        }
        for flat in order:
            args = tuple(tile_args(dims, tile_dims, counts, int(flat)))
            ins = {}
            for s in tk.loads:
                box = _norm_box(bufs[s.data].shape, s.index(args))
                ins[s.name] = bufs[s.data][_np_index(box)].copy()
            outs = tk.compute(ins)
            for s in tk.stores:
                box = _norm_box(bufs[s.data].shape, s.index(args))
                bufs[s.data][_np_index(box)] = np.asarray(outs[s.name])
        return bufs

    rng = np.random.default_rng(seed)
    orders = [list(range(total))]
    for _ in range(perms - 1):
        orders.append(list(rng.permutation(total)))
    ref = run_order(orders[0])
    for k in range(1, perms):
        got = run_order(orders[k])
        for name in sorted(ref):
            if not np.array_equal(ref[name], got[name]):
                diff = np.argwhere(
                    np.asarray(ref[name]) != np.asarray(got[name])
                )[0]
                report = report or AnalysisReport()
                f = report.add(
                    RULE, ERROR, tk.name,
                    f"tile loop {tk.name!r} is order-DEPENDENT: buffer "
                    f"{name!r} diverges at {tuple(int(i) for i in diff)} "
                    "between two pop orders (a tile reads a window "
                    "another tile stores); certification refused",
                    buffer=name, index=tuple(int(i) for i in diff),
                    schedule_a=_schedule_witness(orders[0]),
                    schedule_b=_schedule_witness(orders[k]),
                    value_a=ref[name][tuple(diff)],
                    value_b=got[name][tuple(diff)],
                )
                cert["status"] = "refused (order-dependent)"
                # Only THIS refusal rides the certificate (the caller's
                # report may hold unrelated program findings).
                cert["findings"] = _finding_jsonable(f)
                if raise_on_error:
                    report.raise_errors()
                return cert
    cert["status"] = "certified"
    if cached is None:
        try:
            _tile_cache[tk] = {key: cert}
        except TypeError:
            pass
    else:
        cached[key] = cert
    return cert


# --------------------------------------------------------- frontier


class _AbsFrontierCtx:
    """The concrete-interpretation context one frontier task body runs
    against: real numpy ivalues behind a FakeRef (so ``pl.when`` /
    ``fori_loop`` patched by the shim evaluate concretely) and a spawn
    sink feeding the worklist."""

    def __init__(self, iv: np.ndarray, sink: List[Tuple[int, ...]]):
        self.ivalues = FakeRef("abs:ivalues", "smem", backing=iv)
        self._sink = sink

    def spawn(self, fn, args=(), nargs=None, **kw) -> int:
        self._sink.append(
            tuple(int(np.asarray(a)) for a in args)
        )
        return 0


def _small_graph(seed: int):
    from ..device.frontier import Graph
    from ..device.workloads import rmat_edges

    n, src, dst, w = rmat_edges(4, efactor=4, seed=seed + 11)
    return Graph(n, src, dst, w)


def certify_frontier_schedule(kind: str, *, reps: int = 64,
                              perms: Optional[int] = None, seed: int = 0,
                              buckets: int = 0, delta: int = 1,
                              report: Optional[AnalysisReport] = None,
                              raise_on_error: bool = True,
                              fk=None, graph=None) -> Dict[str, Any]:
    """Certify one frontier traversal kind: run its relax body (the
    SAME ``_relax_block`` loop both dispatch spellings trace) to the
    fixpoint over a small seeded R-MAT graph under K permuted worklist
    pop orders, and prove the per-vertex state identical. With
    ``buckets`` (a priority-bucketed build's claim, ISSUE 15) one extra
    order is the BUCKETED pop - always take a lowest-bucket entry, via
    the host spelling of the device priority function
    (frontier.priority_bucket) - so the priority tier's pop order is
    certified against the same fixpoint as the random permutations.
    ``fk``/``graph`` override the defaults (the order-dependent-refusal
    tests pass a planted kernel)."""
    from ..device.frontier import _KINDS, priority_bucket, seed_frontier

    perms = _perms() if perms is None else int(perms)
    custom = fk is not None or graph is not None
    key = ("frontier", kind, reps, perms, seed, buckets, delta)
    if not custom and key in _frontier_cache:
        return _frontier_cache[key]
    g = graph if graph is not None else _small_graph(seed)
    if fk is None:
        if kind not in _KINDS:
            raise ValueError(f"unknown frontier kind {kind!r}")
        fk = _KINDS[kind](reps=reps) if kind == "pagerank" else (
            _KINDS[kind]()
        )
    fk.st_base = g.st_base
    m0 = 1 << 12
    seeds = seed_frontier(None, g, kind, src=0, m0=m0, reps=reps)
    cert: Dict[str, Any] = {
        "claim": "frontier", "kind": kind,
        "orders": perms + (1 if buckets else 0),
        "vertices": g.n, "seeds": len(seeds),
        **({"buckets": int(buckets), "delta": int(delta)}
           if buckets else {}),
    }

    def run_order(perm_seed: int):
        from ..device.frontier import _pr_seed_rank

        iv = g.preset_values(g.num_value_slots, fk.state0).astype(
            np.int64
        )
        if kind in ("bfs", "sssp"):
            iv[g.st_base] = 0
        elif kind == "pagerank":
            iv[g.st_base : g.st_base + g.n] = _pr_seed_rank(g, m0, reps)
        wl: List[Tuple[int, ...]] = list(seeds)
        rng = np.random.default_rng(seed * 1000 + max(perm_seed, 0))
        schedule: List[Tuple[int, ...]] = []
        steps = 0
        trace = BodyTrace()
        with _patched(trace):
            while wl:
                steps += 1
                if steps > STEP_CAP:
                    return None, schedule, steps
                if perm_seed == 0:
                    i = 0
                elif perm_seed == -1:
                    # The bucketed pop order: lowest clipped bucket
                    # first (FIFO within a bucket) - exactly what the
                    # device's bucket-major drain retires.
                    i = int(np.argmin([
                        min(priority_bucket(kind, c, delta=delta,
                                            reps=reps), buckets - 1)
                        for _v, _b, c, _c in wl
                    ]))
                else:
                    i = int(rng.integers(len(wl)))
                v, blk, carry, cnt = wl.pop(i)
                schedule.append((v, blk, carry, cnt))
                ctx = _AbsFrontierCtx(iv, wl)
                fk._relax_block(
                    ctx,
                    lambda e, blk=blk: int(g.indices[blk][int(e)]),
                    (lambda e, blk=blk: int(g.weights[blk][int(e)]))
                    if fk.weighted else None,
                    carry,
                    cnt,
                )
        return iv[g.st_base : g.st_base + g.n].copy(), schedule, steps

    ref, sched0, steps0 = run_order(0)
    if ref is None:
        cert["status"] = f"unverified (fixpoint > {STEP_CAP} steps)"
        return cert
    cert["tasks"] = steps0
    order_ids = list(range(1, perms)) + ([-1] if buckets else [])
    for k in order_ids:
        got, schedk, _ = run_order(k)
        if got is None:
            cert["status"] = f"unverified (fixpoint > {STEP_CAP} steps)"
            return cert
        if not np.array_equal(ref, got):
            v = int(np.argwhere(ref != got)[0][0])
            report = report or AnalysisReport()
            f = report.add(
                RULE, ERROR, fk.name,
                f"frontier kind {fk.name!r} is order-DEPENDENT: vertex "
                f"{v} fixpoint diverges ({int(ref[v])} vs {int(got[v])})"
                " between two pop orders; certification refused - the "
                "two divergent schedules ride the witness",
                vertex=v, value_a=int(ref[v]), value_b=int(got[v]),
                schedule_a=_schedule_witness(sched0),
                schedule_b=_schedule_witness(schedk),
            )
            cert["status"] = "refused (order-dependent)"
            cert["findings"] = _finding_jsonable(f)
            if raise_on_error:
                report.raise_errors()
            return cert
    cert["status"] = "certified"
    if not custom:
        _frontier_cache[key] = cert
    return cert


# ---------------------------------------------------------- dyngraph

_dyngraph_cache: Dict[Tuple, Dict[str, Any]] = {}


def certify_dyngraph_schedule(kind: str, *, reps: int = 64,
                              buckets: int = 0,
                              updates: Sequence[Tuple[int, int, int]] = (),
                              perms: Optional[int] = None, seed: int = 0,
                              report: Optional[AnalysisReport] = None,
                              raise_on_error: bool = True,
                              graph=None) -> Dict[str, Any]:
    """Certify a dynamic-graph claim (device/dyngraph.py): the mutated
    fixpoint is independent of how splices interleave with frontier
    expansion. Runs the host incremental twin (same splice rule - spare
    bounds, drop mirror - same relax) over a small seeded R-MAT
    ``DynGraph`` carrying the claim's update stream, under K permuted
    op-pool orders PLUS the two adversarial extremes (every update
    before any expansion, and after all initial expansion), and proves
    every fixpoint equal to the FROM-SCRATCH reference on the mutated
    graph (bfs/sssp, bit-identity) or total mass conserved exactly
    (pagerank - the result is schedule-dependent by design; the
    certificate claims conservation, which is what the serving tier
    promises). Update endpoints fold into the model graph's vertex
    range - the certificate is about the SPLICE PROTOCOL, not the
    caller's instance (the frontier discipline)."""
    from ..device.dyngraph import (
        DynGraph, host_dyngraph, host_incremental,
        host_incremental_pagerank,
    )

    perms = _perms() if perms is None else int(perms)
    ups = tuple(
        (int(u), int(v), max(int(w), 0)) for u, v, w in updates
    )
    custom = graph is not None
    key = ("dyngraph", kind, reps, perms, seed, buckets, ups)
    if not custom and key in _dyngraph_cache:
        return _dyngraph_cache[key]
    if graph is None:
        from ..device.workloads import rmat_edges

        n, src, dst, w = rmat_edges(4, efactor=4, seed=seed + 11)
        graph = DynGraph(n, src, dst, w, spare_blocks=2,
                         upd_cap=max(len(ups), 1) + 1)
    for u, v, w in ups:
        graph.add_update(u % graph.n, v % graph.n, w)
    cert: Dict[str, Any] = {
        "claim": "dyngraph", "kind": kind,
        "updates": len(graph.updates), "vertices": graph.n,
        **({"buckets": int(buckets)} if buckets else {}),
    }
    rng = np.random.default_rng(seed * 1000 + 7)
    m0 = 1 << 12

    if kind == "pagerank":
        rank0, _ = host_incremental_pagerank(graph, m0=m0, reps=reps)
        total = int(rank0.sum())
        cert["mass"] = total
    elif kind in ("bfs", "sssp"):
        ref = host_dyngraph(kind, graph, src=0)
    else:
        raise ValueError(
            f"unknown dyngraph kind {kind!r} (bfs|sssp|pagerank)"
        )

    def order_list(tag):
        if kind == "pagerank":
            rank, _ = host_incremental_pagerank(
                graph, m0=m0, reps=reps, order=tag
            )
            return rank
        return host_incremental(kind, graph, src=0, order=tag)

    # Pool size as the twins build it.
    if kind == "pagerank":
        npool = sum(
            1
            for v in range(graph.n)
            for _u in graph.adj[v]
            if _pr_survives(graph, v, m0, reps)
        ) + len(graph.updates)
    else:
        npool = 1 + len(graph.updates)
    idx = np.arange(npool)
    upd_lo = npool - len(graph.updates)
    extremes = [
        np.concatenate([idx[upd_lo:], idx[:upd_lo]]),  # updates first
        idx.copy(),                                    # updates last
    ]
    tags = [None] + [rng.permutation(npool) for _ in range(perms)]
    tags += [e for e in extremes]
    cert["orders"] = len(tags)
    for t in tags:
        got = order_list(None if t is None else list(int(i) for i in t))
        if kind == "pagerank":
            if int(got.sum()) != total:
                report = report or AnalysisReport()
                f = report.add(
                    RULE, ERROR, "dg_update",
                    "dyngraph pagerank mass is NOT conserved across "
                    f"splice interleavings: {int(got.sum())} vs {total};"
                    " certification refused",
                    value_a=total, value_b=int(got.sum()),
                )
                cert["status"] = "refused (mass not conserved)"
                cert["findings"] = _finding_jsonable(f)
                if raise_on_error:
                    report.raise_errors()
                return cert
        elif not np.array_equal(ref, got):
            v = int(np.argwhere(ref != got)[0][0])
            report = report or AnalysisReport()
            f = report.add(
                RULE, ERROR, "dg_update",
                f"dyngraph kind {kind!r} incremental fixpoint is "
                f"order-DEPENDENT: vertex {v} diverges "
                f"({int(ref[v])} vs {int(got[v])}) from the "
                "from-scratch reference on the mutated graph; "
                "certification refused",
                vertex=v, value_a=int(ref[v]), value_b=int(got[v]),
            )
            cert["status"] = "refused (order-dependent)"
            cert["findings"] = _finding_jsonable(f)
            if raise_on_error:
                report.raise_errors()
            return cert
    cert["status"] = "certified"
    if not custom:
        _dyngraph_cache[key] = cert
    return cert


def _pr_survives(graph, v: int, m0: int, reps: int) -> bool:
    from ..device.frontier import _pr_split

    deg = int(graph.deg[v])
    qc = _pr_split(m0, deg)
    return m0 >= reps and qc > 0 and deg > 0


# -------------------------------------------------------------- bnb

_bnb_cache: Dict[Tuple, Dict[str, Any]] = {}


def certify_bnb_schedule(values, weights, cap: int, *,
                         buckets: int = 0,
                         perms: Optional[int] = None, seed: int = 0,
                         report: Optional[AnalysisReport] = None,
                         raise_on_error: bool = True) -> Dict[str, Any]:
    """Certify a branch-and-bound claim (device/bnb.py): the OPTIMUM a
    run proves is independent of the pop order. Runs the host worklist
    model (same bound test, same branch rule as the device body) under
    K permuted orders plus - when the claim is bucketed - the
    best-first order itself, and proves the final incumbent identical.
    Pruned/executed counts legitimately differ per schedule (that IS
    the priority speedup) and are deliberately not compared."""
    from ..device.bnb import Knapsack, bnb_bucket

    perms = _perms() if perms is None else int(perms)
    key = ("bnb", tuple(values), tuple(weights), int(cap), int(buckets),
           perms, seed)
    if key in _bnb_cache:
        return _bnb_cache[key]
    kp = Knapsack(values, weights, cap)
    cert: Dict[str, Any] = {
        "claim": "bnb", "kind": "bnb", "items": kp.n, "cap": kp.cap,
        "orders": perms + (1 if buckets else 0),
        **({"buckets": int(buckets)} if buckets else {}),
    }

    def run_order(perm_seed: int):
        rng = np.random.default_rng(seed * 1000 + max(perm_seed, 0))
        best, steps = 0, 0
        wl: List[Tuple[int, int, int, int]] = [(0, 0, 0, kp.total)]
        schedule: List[Tuple[int, ...]] = []
        while wl:
            steps += 1
            if steps > STEP_CAP:
                return None, schedule, steps
            if perm_seed == 0:
                i = 0
            elif perm_seed == -1:
                # The bucketed (best-first) pop: lowest bucket id =
                # highest bound, via the host spelling of the device
                # priority function.
                i = int(np.argmin([
                    min(bnb_bucket(kp, b, buckets), buckets - 1)
                    for _l, _v, _w, b in wl
                ]))
            else:
                i = int(rng.integers(len(wl)))
            level, value, weight, bound = wl.pop(i)
            schedule.append((level, value, weight, bound))
            if bound <= best:
                continue
            if level == kp.n:
                best = max(best, value)
                continue
            sfx = int(kp.suffix[level + 1])
            wl.append((level + 1, value, weight, value + sfx))
            v_i, w_i = int(kp.values[level]), int(kp.weights[level])
            if weight + w_i <= kp.cap:
                wl.append(
                    (level + 1, value + v_i, weight + w_i,
                     value + v_i + sfx)
                )
        return best, schedule, steps

    ref, sched0, steps0 = run_order(0)
    if ref is None:
        cert["status"] = f"unverified (search > {STEP_CAP} steps)"
        return cert
    cert["tasks"] = steps0
    cert["optimum"] = int(ref)
    for k in list(range(1, perms)) + ([-1] if buckets else []):
        got, schedk, _ = run_order(k)
        if got is None:
            cert["status"] = f"unverified (search > {STEP_CAP} steps)"
            return cert
        if got != ref:
            report = report or AnalysisReport()
            f = report.add(
                RULE, ERROR, "bnb_node",
                f"branch-and-bound incumbent is order-DEPENDENT: "
                f"optimum {ref} vs {got} between two pop orders; "
                "certification refused - the two divergent schedules "
                "ride the witness",
                value_a=int(ref), value_b=int(got),
                schedule_a=_schedule_witness(sched0),
                schedule_b=_schedule_witness(schedk),
            )
            cert["status"] = "refused (order-dependent)"
            cert["findings"] = _finding_jsonable(f)
            if raise_on_error:
                report.raise_errors()
            return cert
    cert["status"] = "certified"
    _bnb_cache[key] = cert
    return cert


# ------------------------------------------------------------ claims


def certify_claim(mk, *, raise_on_error: bool = True,
                  report: Optional[AnalysisReport] = None
                  ) -> Optional[Dict[str, Any]]:
    """Resolve and certify ``mk.si_claim`` (stamped by
    make_frontier_megakernel / run_forasync_device). Returns the
    certificate dict, or None when the builder made no claim. With
    ``raise_on_error`` a refused certification raises ``AnalysisError``
    carrying both divergent schedules."""
    claim = getattr(mk, "si_claim", None)
    if claim is None:
        return None
    if claim[0] == "frontier":
        # 3-tuple: (tag, kind, reps) - the unbucketed spelling. The
        # priority-bucketed builders (ISSUE 15) stamp the 5-tuple
        # (tag, kind, reps, buckets, delta) so the bucketed pop order
        # itself is one of the certified schedules.
        _tag, kind, reps = claim[:3]
        buckets = int(claim[3]) if len(claim) > 3 and claim[3] else 0
        delta = int(claim[4]) if len(claim) > 4 and claim[4] else 1
        return certify_frontier_schedule(
            kind, reps=int(reps or 64), buckets=buckets, delta=delta,
            report=report, raise_on_error=raise_on_error,
        )
    if claim[0] == "dyngraph":
        # (tag, kind, reps, buckets, updates) - the dynamic-graph
        # service claim (ISSUE 20). ``updates`` is None at build time
        # (the tile-claim discipline: certifying an unbound claim would
        # prove a stream the build never ran); run_dyngraph stamps the
        # registered stream before the run.
        _tag, kind, reps, buckets, updates = claim
        if updates is None:
            return {
                "claim": "dyngraph", "kind": kind,
                "status": "unbound (no update stream run yet: "
                          "run_dyngraph stamps it)",
            }
        return certify_dyngraph_schedule(
            kind, reps=int(reps or 64), buckets=int(buckets or 0),
            updates=updates, report=report,
            raise_on_error=raise_on_error,
        )
    if claim[0] == "bnb":
        _tag, values, weights, cap, buckets = claim
        return certify_bnb_schedule(
            values, weights, int(cap), buckets=int(buckets or 0),
            report=report, raise_on_error=raise_on_error,
        )
    if claim[0] == "tile":
        _tag, tk, bounds, tile = claim
        if bounds is None:
            return {
                "claim": "forasync-tiles", "kernel": tk.name,
                "status": "unbound (no tile space run yet: "
                          "run_forasync_device stamps it)",
            }
        return certify_tile_schedule(
            tk, bounds, tile, report=report,
            raise_on_error=raise_on_error,
        )
    raise ValueError(f"unknown schedule-independence claim {claim[0]!r}")
