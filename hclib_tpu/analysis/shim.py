"""Recording abstract interpreter for device kernel bodies.

Kernel bodies are plain Python that *emits* device code through a small
surface: ``jnp`` math, ``pl.when`` predication, ``pltpu.make_async_copy``
DMA, loop combinators, and the ``KernelContext``/``BatchContext``
facilities. That surface is narrow enough to run a body ONCE, host-only,
over **concrete synthetic descriptors and fake buffers**, recording the
effects the static analyses need:

- every DMA start/wait as a (src, dst, sem) triple of buffer *windows*
  (concrete index boxes - synthetic descriptor args are plain ints, so
  the windows a body computes from them evaluate to numbers),
- every value-slot write, tagged with the batch slot that made it
  (``slot_ctx``/``set_out`` attribution),
- every dynamic spawn / continuation transfer, with its (static) link
  words - the migratability classification input.

No Pallas trace happens and no Mosaic is imported: ``pl.when`` /
``make_async_copy`` / the loop combinators are patched to host
equivalents for the duration of one body evaluation, math runs eagerly
on concrete values, and loops are truncated at ``LOOP_CAP`` iterations
(structure discovery, not value computation). A body using machinery
outside this surface raises ``ShimUnsupported`` - the caller reports a
``shim-unsupported`` info finding and verifies nothing (soundness over
false alarms).

Synthetic descriptor args are ``(slot+1) * ARG_STRIDE + word*7``: large
and slot-distinct, so store windows computed from a slot's own args land
far apart and windows that *coincide* across slots mean the body ignored
its descriptor - the classic copy-paste batch race.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..device.descriptor import (
    DESC_WORDS, F_A0, F_DEP, F_FN, F_HOME, F_OUT, F_SUCC0, F_SUCC1,
    NO_TASK,
)

__all__ = [
    "ShimUnsupported",
    "BodyTrace",
    "run_batch_body",
    "run_drain",
    "run_scalar_kernel",
    "ARG_STRIDE",
    "OUT_BASE",
    "OUT_STRIDE",
]

# Synthetic-descriptor layout (see module docstring).
ARG_STRIDE = 1 << 16
OUT_BASE = 1000
OUT_STRIDE = 17
LOOP_CAP = 128          # fori/while truncation (structure, not values)
SHIM_BUDGET_S = 5.0     # per-body wall ceiling (tier-1 safety valve)
_CAPACITY = 512         # synthetic task-table rows

_lock = threading.Lock()  # the patches touch module globals
# Thread-transparency for the module-global patches: only the thread
# that entered _patched() sees the host-loop/recording behavior; any
# OTHER thread (a streaming megakernel's device threads, a concurrent
# trace) that calls jax.lax.fori_loop / pl.when / make_async_copy while
# a shim run is active is routed to the saved originals.
_tls = threading.local()


class ShimUnsupported(RuntimeError):
    """The body used machinery outside the shim's surface; nothing was
    verified (the caller downgrades to an info finding)."""


# ------------------------------------------------------------- fake refs


def _as_int(x) -> int:
    return int(np.asarray(x))


def _norm_box(shape, idx) -> Tuple[Tuple[int, int], ...]:
    """Normalize an indexer (ints / slices / pl.ds / Ellipsis) into a
    per-axis (start, stop) box over ``shape`` (None dims = unbounded)."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    dims = list(shape) if shape is not None else [1 << 30] * len(idx)
    # Expand Ellipsis.
    if any(x is Ellipsis for x in idx):
        k = idx.index(Ellipsis)
        pad = len(dims) - (len(idx) - 1)
        idx = idx[:k] + (slice(None),) * pad + idx[k + 1:]
    box = []
    for ax, x in enumerate(idx):
        d = dims[ax] if ax < len(dims) else 1 << 30
        if isinstance(x, slice):
            lo = 0 if x.start is None else _as_int(x.start)
            hi = d if x.stop is None else _as_int(x.stop)
            box.append((lo, hi))
        elif hasattr(x, "start") and hasattr(x, "size"):  # pl.ds
            lo = _as_int(x.start)
            box.append((lo, lo + _as_int(x.size)))
        else:
            i = _as_int(x)
            box.append((i, i + 1))
    for d in dims[len(idx):]:
        box.append((0, d))
    return tuple(box)


class Window:
    """A window of a fake ref: the DMA-endpoint representation."""

    def __init__(self, ref: "FakeRef", box) -> None:
        self.ref = ref
        self.box = box

    @property
    def key(self):
        return self.ref.name


class _AtHelper:
    def __init__(self, ref: "FakeRef") -> None:
        self._ref = ref

    def __getitem__(self, idx) -> Window:
        return Window(self._ref, _norm_box(self._ref.shape, idx))


class FakeRef:
    """Concrete stand-in for a device memory ref: numpy backing for
    reads, recorded writes, ``.at[...]`` windows for DMA endpoints."""

    def __init__(self, name: str, kind: str, shape=None, dtype=np.int32,
                 backing: Optional[np.ndarray] = None) -> None:
        self.name = name
        self.kind = kind  # data | scratch | smem | sem
        self.shape = tuple(shape) if shape is not None else None
        self.writes: List[Tuple[Tuple[Tuple[int, int], ...], Any]] = []
        if backing is not None:
            self.backing = backing
            self.shape = backing.shape
        elif self.shape is not None and kind != "sem":
            self.backing = np.zeros(self.shape, dtype)
        else:
            self.backing = None

    @property
    def at(self) -> _AtHelper:
        return _AtHelper(self)

    def _np_idx(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        out = []
        for x in idx:
            if isinstance(x, slice) or x is Ellipsis:
                out.append(x)
            elif hasattr(x, "start") and hasattr(x, "size"):  # pl.ds
                lo = _as_int(x.start)
                out.append(slice(lo, lo + _as_int(x.size)))
            else:
                out.append(_as_int(x))
        return tuple(out)

    def __getitem__(self, idx):
        if self.backing is None:
            raise ShimUnsupported(f"read of value-less ref {self.name}")
        try:
            return self.backing[self._np_idx(idx)]
        except (IndexError, TypeError) as e:
            raise ShimUnsupported(f"unmodelled read {self.name}[{idx}]: {e}")

    def __setitem__(self, idx, val) -> None:
        self.writes.append((_norm_box(self.shape, idx), val))
        if self.backing is None:
            return
        try:
            self.backing[self._np_idx(idx)] = np.asarray(val)
        except (IndexError, TypeError, ValueError):
            pass  # out-of-range synthetic index: structure recorded above


# ------------------------------------------------------------ the trace


@dataclass
class DMAEvent:
    op: str  # start | wait
    src: Tuple[str, Any]
    dst: Tuple[str, Any]
    dst_kind: str
    sem: Tuple[str, Any]
    seq: int

    def triple(self):
        return (self.src, self.dst, self.sem)


@dataclass
class BodyTrace:
    dma: List[DMAEvent] = field(default_factory=list)
    # (slot-or-None, value-slot index, seq)
    value_writes: List[Tuple[Optional[int], int, int]] = field(
        default_factory=list
    )
    value_reads: List[Tuple[Optional[int], int, int]] = field(
        default_factory=list
    )
    # (slot-or-None, {dep_count, succ0, succ1, out, fn})
    spawns: List[Tuple[Optional[int], Dict[str, int]]] = field(
        default_factory=list
    )
    # On-device promise ops (the direction-1 serving surface): every
    # ``ctx.wait_value(slot)`` / ``ctx.satisfy(slot)`` a body performs,
    # as (slot-or-None, value-slot index, seq). The wait-graph analysis
    # (analysis/waits.py) matches waiters to satisfiers across kinds.
    waits: List[Tuple[Optional[int], int, int]] = field(
        default_factory=list
    )
    satisfies: List[Tuple[Optional[int], int, int]] = field(
        default_factory=list
    )
    continuations: int = 0
    next_reads: List[Tuple[int, int]] = field(default_factory=list)
    # Loops whose bounds were truncated at LOOP_CAP or derived from the
    # synthetic descriptor args (>= ARG_STRIDE): the trace is then an
    # UNDER-approximation. ``approx_marks`` holds the seq position of
    # each truncation - the point where the skipped iterations WOULD
    # have emitted their events - so protocol findings demote only when
    # their witness's missing half could sit inside a skipped window
    # (an unmatched wait before every mark, or an unmatched start after
    # every mark, is an EXACT-window finding and stays an error).
    approx_loops: int = 0
    approx_marks: List[int] = field(default_factory=list)
    seq: int = 0

    def tick(self) -> int:
        self.seq += 1
        return self.seq

    def starts(self) -> List[DMAEvent]:
        return [e for e in self.dma if e.op == "start"]

    def unmatched_starts(self) -> List[DMAEvent]:
        """Starts with no later wait on the same (src, dst, sem) triple
        (each wait retires the earliest open start of its triple)."""
        open_: List[DMAEvent] = []
        for e in self.dma:
            if e.op == "start":
                open_.append(e)
            else:
                for s in open_:
                    if s.triple() == e.triple():
                        open_.remove(s)
                        break
        return open_

    def unmatched_waits(self) -> List[DMAEvent]:
        open_: List[DMAEvent] = []
        bad: List[DMAEvent] = []
        for e in self.dma:
            if e.op == "start":
                open_.append(e)
            else:
                for s in open_:
                    if s.triple() == e.triple():
                        open_.remove(s)
                        break
                else:
                    bad.append(e)
        return bad


class _RecCopy:
    def __init__(self, trace: BodyTrace, src, dst, sem) -> None:
        self._trace = trace
        self._src = self._end(src)
        self._dst = self._end(dst)
        self._dst_kind = self._kind(dst)
        self._sem = self._end(sem)

    @staticmethod
    def _end(x):
        if isinstance(x, Window):
            return (x.ref.name, x.box)
        if isinstance(x, FakeRef):
            full = (
                tuple((0, d) for d in x.shape)
                if x.shape is not None else ()
            )
            return (x.name, full)
        raise ShimUnsupported(f"DMA endpoint {type(x).__name__} unmodelled")

    @staticmethod
    def _kind(x):
        return x.ref.kind if isinstance(x, Window) else getattr(
            x, "kind", "?"
        )

    def _emit(self, op: str) -> None:
        self._trace.dma.append(DMAEvent(
            op, self._src, self._dst, self._dst_kind, self._sem,
            self._trace.tick(),
        ))

    def start(self) -> None:
        self._emit("start")

    def wait(self) -> None:
        self._emit("wait")


# ------------------------------------------------------------- patching


@contextlib.contextmanager
def _patched(trace: BodyTrace):
    """Swap pl.when / pltpu.make_async_copy / pltpu.roll / lax loop
    combinators for host equivalents while one body runs (module-global
    patch, guarded by a lock; construction-time only)."""
    import time

    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    deadline = time.monotonic() + SHIM_BUDGET_S

    def _tick():
        if time.monotonic() > deadline:
            raise ShimUnsupported(
                f"body evaluation exceeded the {SHIM_BUDGET_S:.0f}s "
                "shim budget"
            )

    def _mine() -> bool:
        return getattr(_tls, "active", False)

    def _when(cond):
        if not _mine():
            return saved_when(cond)
        live = bool(np.asarray(cond))

        def deco(fn):
            if live:
                fn()
            return fn

        return deco

    def _fori(lo, hi, body, init, **kw):
        if not _mine():
            return saved_fori(lo, hi, body, init, **kw)
        val = init
        lo, hi = _as_int(lo), _as_int(hi)
        # A well-formed static loop is a small forward range; anything
        # else (reversed/empty-by-arithmetic bounds, ranges past the
        # cap) is taken as arg-dependent and marks the trace
        # approximate - the synthetic descriptor args make such bounds
        # meaningless (cholesky's nj = i - k goes negative).
        approx = not (0 <= lo <= hi <= lo + LOOP_CAP)
        if approx:
            trace.approx_loops += 1
        for i in range(lo, min(hi, lo + LOOP_CAP)):
            _tick()
            val = body(i, val)
        if approx:
            # Skipped iterations run (conceptually) HERE, after the
            # executed prefix - the mark the demotion window keys on.
            trace.approx_marks.append(trace.tick())
        return val

    def _while(cond, body, init):
        if not _mine():
            return saved_while(cond, body, init)
        val = init
        for i in range(LOOP_CAP + 1):
            if not bool(np.asarray(cond(val))):
                break
            if i == LOOP_CAP:
                trace.approx_loops += 1
                trace.approx_marks.append(trace.tick())
                break
            _tick()
            val = body(val)
        return val

    def _roll(x, shift, axis=None, **kw):
        if not _mine():
            return saved_roll(x, shift, axis=axis, **kw)
        import jax.numpy as jnp

        return jnp.roll(x, _as_int(shift), axis=axis)

    def _copy(src, dst, sem, **kw):
        if not _mine():
            return saved_copy(src, dst, sem, **kw)
        return _RecCopy(trace, src, dst, sem)

    saved_when = pl.when
    saved_copy = pltpu.make_async_copy
    saved_fori = jax.lax.fori_loop
    saved_while = jax.lax.while_loop
    saved_roll = getattr(pltpu, "roll", None)
    saved = [
        (pl, "when", saved_when),
        (pltpu, "make_async_copy", saved_copy),
        (jax.lax, "fori_loop", saved_fori),
        (jax.lax, "while_loop", saved_while),
    ]
    if saved_roll is not None:
        saved.append((pltpu, "roll", saved_roll))
    with _lock:
        try:
            _tls.active = True
            pl.when = _when
            pltpu.make_async_copy = _copy
            jax.lax.fori_loop = _fori
            jax.lax.while_loop = _while
            if saved_roll is not None:
                pltpu.roll = _roll
            yield
        finally:
            _tls.active = False
            for mod, attr, fn in saved:
                setattr(mod, attr, fn)


# ------------------------------------------------- recording contexts


_ctx_classes = None


def _make_recording_contexts():
    """Subclass the real contexts lazily (import cycle: megakernel
    imports nothing from analysis; analysis subclasses megakernel) and
    once (class creation is measurable at per-construction frequency)."""
    global _ctx_classes
    if _ctx_classes is not None:
        return _ctx_classes
    from ..device.megakernel import BatchContext, KernelContext

    class RecordingKernelContext(KernelContext):
        _shim_trace: BodyTrace = None  # set per instance
        _shim_slot: Optional[int] = None

        def value(self, slot):
            self._shim_trace.value_reads.append(
                (self._shim_slot, _as_int(slot), self._shim_trace.tick())
            )
            return super().value(slot)

        def set_value(self, slot, v) -> None:
            self._shim_trace.value_writes.append(
                (self._shim_slot, _as_int(slot), self._shim_trace.tick())
            )
            super().set_value(slot, v)

        def set_out(self, v) -> None:
            self._shim_trace.value_writes.append(
                (self._shim_slot, _as_int(self.out_slot),
                 self._shim_trace.tick())
            )
            super().set_out(v)

        def wait_value(self, slot, spin_cap=None):
            # Record the promise-wait; never spin (the synthetic flag is
            # unset, and the wait-graph analysis - not execution order -
            # decides whether a satisfier exists). Return the flag word
            # like the real op so bodies that COMPUTE with the waited
            # value keep interpreting past the wait.
            self._shim_trace.waits.append(
                (self._shim_slot, _as_int(slot), self._shim_trace.tick())
            )
            return self.ivalues[slot]

        def satisfy(self, slot, v=1) -> None:
            self._shim_trace.satisfies.append(
                (self._shim_slot, _as_int(slot), self._shim_trace.tick())
            )
            super().satisfy(slot, v)

        def spawn(self, fn, args=(), dep_count=0, succ0=NO_TASK,
                  succ1=NO_TASK, out=0, nargs=None):
            row = super().spawn(
                fn, args, dep_count=dep_count, succ0=succ0, succ1=succ1,
                out=out, nargs=nargs,
            )
            self._shim_trace.spawns.append((self._shim_slot, {
                "fn": _as_int(fn), "dep_count": _as_int(dep_count),
                "succ0": _as_int(succ0), "succ1": _as_int(succ1),
                "out": _as_int(out),
            }))
            return row

        def take_continuation(self, new_idx) -> None:
            self._shim_trace.continuations += 1
            super().take_continuation(new_idx)

    class RecordingBatchContext(BatchContext):
        _shim_trace: BodyTrace = None

        def value(self, slot):
            self._shim_trace.value_reads.append(
                (None, _as_int(slot), self._shim_trace.tick())
            )
            return super().value(slot)

        def set_value(self, slot, v) -> None:
            self._shim_trace.value_writes.append(
                (None, _as_int(slot), self._shim_trace.tick())
            )
            super().set_value(slot, v)

        def set_out(self, s, v) -> None:
            self._shim_trace.value_writes.append(
                (int(s), _as_int(self.out_slot(s)), self._shim_trace.tick())
            )
            super().set_out(s, v)

        def wait_value(self, slot, spin_cap=None):
            self._shim_trace.waits.append(
                (None, _as_int(slot), self._shim_trace.tick())
            )
            return self.k.ivalues[slot]

        def satisfy(self, slot, v=1) -> None:
            self._shim_trace.satisfies.append(
                (None, _as_int(slot), self._shim_trace.tick())
            )
            super().satisfy(slot, v)

        def next_idx(self, s):
            self._shim_trace.next_reads.append(
                (int(s), _as_int(self.prefetch_count))
            )
            return super().next_idx(s)

        def slot_ctx(self, s):
            ctx = super().slot_ctx(s)
            rec = RecordingKernelContext(
                ctx.idx, ctx._tasks, ctx._succ, ctx._ready, ctx._counts,
                ctx.ivalues, ctx.data, ctx.scratch, ctx._capacity,
                ctx._free, ctx._num_values, ctx._vfree,
                ctx._uses_row_values, ctx._tracks_home,
            )
            rec._shim_trace = self._shim_trace
            rec._shim_slot = int(s)
            return rec

    _ctx_classes = (RecordingKernelContext, RecordingBatchContext)
    return _ctx_classes


# --------------------------------------------------------- environments


def _spec_shape_dtype(spec):
    shape = getattr(spec, "shape", None)
    dtype = getattr(spec, "dtype", None)
    try:
        dtype = np.dtype(dtype) if dtype is not None else np.int32
    except TypeError:
        dtype = np.int32
    return shape, dtype


def _fake_env(data_specs: Dict[str, Any], scratch_specs: Dict[str, Any]):
    data = {}
    for name, s in (data_specs or {}).items():
        shape, dtype = _spec_shape_dtype(s)
        data[name] = FakeRef(f"data:{name}", "data", shape, dtype)
    scratch = {}
    for name, s in (scratch_specs or {}).items():
        shape, dtype = _spec_shape_dtype(s)
        kind = "sem" if "Semaphore" in type(s).__name__ else "scratch"
        if kind == "sem":
            scratch[name] = FakeRef(f"scratch:{name}", "sem", shape)
        else:
            scratch[name] = FakeRef(f"scratch:{name}", "scratch", shape,
                                    dtype)
    return data, scratch


def synth_arg(slot: int, word: int) -> int:
    """The synthetic descriptor arg of batch slot ``slot``, word ``word``
    (slot-distinct, far apart - see module docstring)."""
    return (slot + 1) * ARG_STRIDE + word * 7


def _synth_tasks(fid: int, width: int, nxt: int) -> np.ndarray:
    tasks = np.zeros((_CAPACITY, DESC_WORDS), np.int64)
    for r in range(width + nxt):
        tasks[r, F_FN] = fid
        tasks[r, F_DEP] = 0
        tasks[r, F_SUCC0] = NO_TASK
        tasks[r, F_SUCC1] = NO_TASK
        tasks[r, F_HOME] = NO_TASK
        for i in range(6):
            tasks[r, F_A0 + i] = synth_arg(r, i)
        tasks[r, F_OUT] = OUT_BASE + r * OUT_STRIDE
    return tasks


def _core_refs(tasks: np.ndarray):
    from ..device.megakernel import C_ALLOC, C_PENDING, C_VALLOC, C_VBASE

    t = FakeRef("smem:tasks", "smem", backing=tasks)
    succ = FakeRef("smem:succ", "smem", (64,))
    ready = FakeRef("smem:ready", "smem", (_CAPACITY,))
    counts = FakeRef("smem:counts", "smem", (8,))
    n = _CAPACITY // 2
    counts.backing[C_ALLOC] = n
    counts.backing[C_PENDING] = n
    counts.backing[C_VALLOC] = OUT_BASE + _CAPACITY * OUT_STRIDE
    counts.backing[C_VBASE] = 1 << 20  # row-owned blocks far above outs
    ivalues = FakeRef("smem:ivalues", "smem", (64,))
    free = FakeRef("smem:free", "smem", (_CAPACITY + 1,))
    vfree = FakeRef("smem:vfree", "smem", (_CAPACITY + 1,))
    return t, succ, ready, counts, ivalues, free, vfree


class _BigValues:
    """ivalues stand-in: reads return 0 for ANY slot (synthetic out
    slots range far), writes recorded by the recording contexts."""

    def __init__(self) -> None:
        self.name = "smem:ivalues"
        self.kind = "smem"
        self.shape = None

    def __getitem__(self, idx):
        return np.int32(0)

    def __setitem__(self, idx, val) -> None:
        pass


def _run(fn, trace: BodyTrace):
    try:
        with _patched(trace):
            fn()
    except ShimUnsupported as e:
        # The partial trace rides the exception: events recorded BEFORE
        # the unmodelled construct (a promise wait, say) are real, and
        # the wait-graph gate must still see them - otherwise any
        # unmodelled tail would silently evade the deadlock analysis.
        e.trace = trace
        raise
    except Exception as e:  # noqa: BLE001 - any body failure = unmodelled
        exc = ShimUnsupported(f"{type(e).__name__}: {e}")
        exc.trace = trace
        raise exc from e
    return trace


def run_batch_body(spec, fid: int, data_specs, scratch_specs, *,
                   prefetch_count: int = 0, ctx_hook=None) -> BodyTrace:
    """Evaluate ``spec.body`` once over a full-width synthetic batch
    (``prefetch_count`` next-batch descriptors announced, none
    pre-loaded); returns the recorded trace."""
    RecordingKernelContext, RecordingBatchContext = (
        _make_recording_contexts()
    )
    trace = BodyTrace()
    tasks, succ, ready, counts, ivalues, free, vfree = (
        _core_refs(_synth_tasks(fid, spec.width, prefetch_count))
    )
    data, scratch = _fake_env(data_specs, scratch_specs)
    lanes = FakeRef(
        "smem:lanes", "smem",
        backing=np.tile(np.arange(_CAPACITY, dtype=np.int64), (1, 1)),
    )
    kctx = RecordingKernelContext(
        0, tasks, succ, ready, counts, _BigValues(), data, scratch,
        _CAPACITY, free, 1 << 22, vfree, False, False,
    )
    kctx._shim_trace = trace
    bctx = RecordingBatchContext(
        kctx, lanes, 0, 0, np.int32(spec.width), spec.width,
        np.int32(0), np.int32(0), np.int32(prefetch_count), _CAPACITY,
        ctx_hook=ctx_hook,
    )
    bctx._shim_trace = trace
    return _run(lambda: spec.body(bctx), trace)


def run_drain(spec, fid: int, data_specs, scratch_specs, *,
              prefetched: int, buf: int) -> BodyTrace:
    """Evaluate ``spec.drain`` as the scheduler's exit path would: the
    in-flight prefetch covers ``prefetched`` descriptors (the rows the
    body's prefetch pass targeted) in operand half ``buf``."""
    RecordingKernelContext, RecordingBatchContext = (
        _make_recording_contexts()
    )
    trace = BodyTrace()
    tasks, succ, ready, counts, ivalues, free, vfree = (
        _core_refs(_synth_tasks(fid, spec.width, prefetched))
    )
    data, scratch = _fake_env(data_specs, scratch_specs)
    lanes = FakeRef(
        "smem:lanes", "smem",
        backing=np.tile(np.arange(_CAPACITY, dtype=np.int64), (1, 1)),
    )
    kctx = RecordingKernelContext(
        spec.width, tasks, succ, ready, counts, _BigValues(), data,
        scratch, _CAPACITY, free, 1 << 22, vfree, False, False,
    )
    kctx._shim_trace = trace
    # head = width: the drained prefetch targets the rows BEHIND the
    # batch the body just ran - exactly what its next_arg reads saw.
    bctx = RecordingBatchContext(
        kctx, lanes, 0, spec.width, np.int32(prefetched), spec.width,
        np.int32(prefetched), np.int32(buf), np.int32(0), _CAPACITY,
    )
    bctx._shim_trace = trace
    return _run(lambda: spec.drain(bctx), trace)


def run_scalar_kernel(fn, data_specs, scratch_specs,
                      args=None) -> BodyTrace:
    """Evaluate a scalar kernel-table entry once over one synthetic
    descriptor (row 0, the same ``synth_arg`` scheme batch bodies get:
    arg-derived values land ``>= ARG_STRIDE``, which is how the
    wait-graph analysis tells an arg-carried promise slot from a static
    one; arg-bounded loops truncate and mark the trace approximate);
    the trace's spawns/continuations drive classification."""
    RecordingKernelContext, _ = _make_recording_contexts()
    trace = BodyTrace()
    tasks, succ, ready, counts, ivalues, free, vfree = (
        _core_refs(_synth_tasks(0, 1, 0))
    )
    for i in range(6):
        tasks.backing[0, F_A0 + i] = (
            args[i] if args is not None and i < len(args)
            else synth_arg(0, i)
        )
    data, scratch = _fake_env(data_specs, scratch_specs)
    ctx = RecordingKernelContext(
        0, tasks, succ, ready, counts, _BigValues(), data, scratch,
        _CAPACITY, free, 1 << 22, vfree, False, False,
    )
    ctx._shim_trace = trace
    ctx._shim_slot = 0
    return _run(lambda: fn(ctx), trace)
