"""Wait-graph deadlock detection over the per-kind spawn/wait/satisfy
graph (the ``wait-cycle`` rule).

Dependency edges cannot deadlock this runtime: a task with outstanding
deps simply isn't ready, so the scheduler never blocks on one. The
construct that CAN deadlock it is the on-device promise wait
(``KernelContext.wait_value`` - the direction-1 serving-loop surface):
an in-body spin occupies the core, so a kind that waits a flag only
satisfied by a kind that (transitively) waits on the first can wedge
under EVERY schedule. That property is decidable at build time from the
recording shim: one pass per kind records every ``wait_value`` /
``satisfy`` slot (concrete ints under the synthetic descriptors) and
every spawn, and the analysis proves the waits-on graph cycle-free - or
emits the concrete cycle (the kind chain) as the witness.

Edges and verdicts:

- kind A *waits-on* kind B when A waits a slot B satisfies (and A did
  not satisfy it itself EARLIER in its own body - the local-handshake
  pattern). Waiting kinds with NO satisfier anywhere are a guaranteed
  stall (``wait-cycle`` error, witness = the orphan slot); a cycle in
  the waits-on graph (including a self-loop: a kind that waits a slot
  only later instances of itself satisfy) is the deadlock witness.
- The analysis is deliberately conservative: a cycle is refused even
  when an alternate acyclic satisfier exists for some edge (annotate a
  deliberate topology with ``verify_suppress=("wait-cycle",)``).

Cost discipline: ``check_wait_graph`` first scans each kernel's code
objects (nested closures included) for the wait-op names - a tree with
no on-device waits (today's entire tree) pays a few dict lookups and
ZERO shim passes. When waits exist, the shim pass is the same memoized
``kind_summaries`` pass the reshard classification shares. Known gap:
a body that waits only through an out-of-module helper whose NAME
never appears in the body's code objects evades the pre-filter - keep
the promise ops spelled ``ctx.wait_value`` / ``ctx.satisfy`` at the
call site (the repo convention everywhere else, e.g. ``pl.when``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .classify import kind_summaries
from .findings import ERROR, INFO, AnalysisReport
from .shim import ARG_STRIDE

__all__ = ["check_wait_graph", "wait_graph"]

_WAIT_OPS = ("wait_value", "satisfy")


def _code_mentions(obj, names, depth: int = 0) -> bool:
    """True when a function's code object (or any nested one) names one
    of ``names`` - the cheap static pre-filter that keeps wait-free
    trees at zero shim passes."""
    code = getattr(obj, "__code__", None)
    if code is None or depth > 4:
        return False

    def scan(c, d):
        if any(n in c.co_names for n in names):
            return True
        if d > 4:
            return False
        for const in c.co_consts:
            if type(const).__name__ == "code" and scan(const, d + 1):
                return True
        return False

    return scan(code, depth)


def _any_wait_mentions(mk) -> bool:
    from ..device.megakernel import _is_batch_spec

    for fn in mk.kernel_fns:
        if _code_mentions(fn, _WAIT_OPS):
            return True
    for _name, spec in mk.route.items():
        if _is_batch_spec(spec) and _code_mentions(spec.body, _WAIT_OPS):
            return True
    return False


def wait_graph(mk) -> Dict[str, Dict[str, object]]:
    """The static spawn/wait/satisfy graph per kind: {kind: {waits:
    {slot: first_seq}, satisfies: {slot: first_seq}, spawns: [kind]}}.
    Built from the shared (memoized) shim summaries."""
    summaries = kind_summaries(mk)
    graph: Dict[str, Dict[str, object]] = {}
    for name, s in summaries.items():
        waits: Dict[int, int] = {}
        for slot, seq in s.waits:
            waits.setdefault(slot, seq)
        sats: Dict[int, int] = {}
        for slot, seq in s.satisfies:
            sats.setdefault(slot, seq)
        graph[name] = {
            "waits": waits,
            "satisfies": sats,
            "spawns": [
                mk.kernel_names[f]
                for f in s.spawn_fns
                if 0 <= f < len(mk.kernel_names)
            ],
        }
    return graph


def _find_cycle(edges: Dict[str, Set[str]]) -> Optional[List[str]]:
    """First cycle in the waits-on graph as the kind chain
    ``[a, b, ..., a]`` (DFS with an explicit path stack)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {k: WHITE for k in edges}
    path: List[str] = []

    def dfs(k: str) -> Optional[List[str]]:
        color[k] = GRAY
        path.append(k)
        for nxt in sorted(edges.get(k, ())):
            if color.get(nxt, WHITE) == GRAY:
                i = path.index(nxt)
                return path[i:] + [nxt]
            if color.get(nxt, WHITE) == WHITE:
                c = dfs(nxt)
                if c is not None:
                    return c
        path.pop()
        color[k] = BLACK
        return None

    for k in sorted(edges):
        if color[k] == WHITE:
            c = dfs(k)
            if c is not None:
                return c
    return None

def check_wait_graph(mk, report: Optional[AnalysisReport] = None,
                     suppress: Sequence[str] = ()) -> AnalysisReport:
    """Prove ``mk``'s wait graph deadlock-free; findings otherwise (rule
    ``wait-cycle``, error severity - the construction gate for any
    on-device-wait kind). Near-zero cost when no kind waits.

    Slots computed from DESCRIPTOR ARGS (the dynamic per-request
    plumbing a serving loop uses) evaluate under the shim's synthetic
    args to values ``>= ARG_STRIDE``: those are statically unmatchable -
    the graph neither claims an orphan nor builds edges for them, and
    an info note records that the runtime spin budget (``OVF_PROMISE``)
    is the backstop for that kind. Only STATIC slots (literals /
    host-preset layout constants, the repo convention) participate in
    the orphan and cycle verdicts."""
    report = report or AnalysisReport(suppress)
    if not _any_wait_mentions(mk):
        return report
    g = wait_graph(mk)
    # Satisfier index over STATIC slots: slot -> kinds (with first seq).
    satisfiers: Dict[int, List[Tuple[str, int]]] = {}
    any_dynamic_sat = False
    for name, node in g.items():
        for slot, seq in node["satisfies"].items():
            if slot >= ARG_STRIDE:
                any_dynamic_sat = True
                continue
            satisfiers.setdefault(slot, []).append((name, seq))
    edges: Dict[str, Set[str]] = {name: set() for name in g}
    for name, node in g.items():
        dynamic_waits = sorted(
            s for s in node["waits"] if s >= ARG_STRIDE
        )
        if dynamic_waits:
            report.add(
                "wait-cycle", INFO, name,
                f"kind {name!r} waits {len(dynamic_waits)} arg-carried "
                "slot(s) (computed from descriptor args): the static "
                "wait graph cannot match them - the bounded spin "
                "budget (OVF_PROMISE) is the runtime backstop",
                kind=name, slots=tuple(dynamic_waits),
            )
        for slot, wseq in node["waits"].items():
            if slot >= ARG_STRIDE:
                continue
            own = node["satisfies"].get(slot)
            if own is not None and own < wseq:
                continue  # locally satisfied before the wait: no edge
            sats = [s for s, _seq in satisfiers.get(slot, ())
                    if s != name or node["satisfies"].get(slot, 1 << 60)
                    > wseq]
            if not sats:
                if any_dynamic_sat:
                    # An arg-carried satisfy exists somewhere: it COULD
                    # target this slot at runtime, so an orphan claim
                    # would be unsound - note it instead of refusing.
                    report.add(
                        "wait-cycle", INFO, name,
                        f"kind {name!r} waits value slot {slot} with no "
                        "static satisfier; an arg-carried satisfy "
                        "elsewhere may cover it at runtime (not "
                        "statically provable)",
                        slot=slot, kind=name,
                    )
                else:
                    report.add(
                        "wait-cycle", ERROR, name,
                        f"kind {name!r} waits value slot {slot} that no "
                        "kind ever satisfies (guaranteed stall: the "
                        "wait spins out its budget under every "
                        "schedule)",
                        slot=slot, kind=name,
                    )
                continue
            edges[name].update(sats)
    cycle = _find_cycle(edges)
    if cycle is not None:
        chain = " -> ".join(cycle)
        report.add(
            "wait-cycle", ERROR, cycle[0],
            f"wait cycle: {chain} (each kind's promise wait can only "
            "be satisfied by the next, so no schedule can order the "
            "satisfactions; break the cycle or satisfy before waiting)",
            cycle=tuple(cycle),
        )
    return report
