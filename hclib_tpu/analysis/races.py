"""Batch-slot race detection + prefetch-protocol conformance.

Two spellings, matching how the kernels declare themselves:

- **Slab-declared kernels** (``TileKernel``): the store windows are pure
  Python ``index(args)`` callables, so ``check_tile_windows`` evaluates
  them CONCRETELY over the whole tile space and proves pairwise
  disjointness - the witness of a violation is the two colliding tile
  coordinates and their windows. This is the strong, whole-loop result
  (any two ready tiles can share a batch round).

- **Raw batch bodies** (any ``BatchSpec``): ``check_batch_spec``
  abstract-interprets the body once with the recording shim over a
  slot-distinct synthetic batch and checks (a) per-slot DMA store
  windows into data buffers are pairwise disjoint, (b) per-slot value
  writes hit disjoint slots, (c) every DMA wait matches a start, (d)
  with a prefetch announced, the residual (unwaited) starts are EXACTLY
  what ``drain`` retires. A body the shim cannot run yields one
  ``shim-unsupported`` info finding instead of false alarms.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .findings import ERROR, INFO, WARN, AnalysisReport
from .shim import (
    BodyTrace, ShimUnsupported, run_batch_body, run_drain,
)

__all__ = [
    "boxes_overlap",
    "check_batch_spec",
    "check_splice",
    "check_tile_windows",
]


def boxes_overlap(a, b) -> bool:
    """Axis-aligned boxes ((start, stop) per axis) intersect; shorter
    box = full range on the missing trailing axes."""
    n = max(len(a), len(b))
    for i in range(n):
        lo_a, hi_a = a[i] if i < len(a) else (0, 1 << 62)
        lo_b, hi_b = b[i] if i < len(b) else (0, 1 << 62)
        if hi_a <= lo_b or hi_b <= lo_a:
            return False
    return True


# ------------------------------------------------------- tile windows


import weakref

# Clean verdicts memoized per (TileKernel instance, bounds, tile):
# run_forasync_device re-proves on every call otherwise (repeated bench
# / mesh runs over one kernel), and the proof is O(tiles x stores)
# Python. Only CLEAN results cache - a violation raises at the caller
# and re-deriving its witness is the cheap path.
_tile_clean: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def check_tile_windows(tk, bounds, tile,
                       report: Optional[AnalysisReport] = None,
                       suppress: Sequence[str] = ()) -> AnalysisReport:
    """Prove every pair of tiles of one forasync loop stores disjoint
    windows (per store slab/buffer) by concrete evaluation over the
    whole tile space. Witness: the two colliding tile coordinates."""
    from ..device.forasync_tier import tile_args, tile_grid

    report = report or AnalysisReport(suppress)
    key = (repr(tuple(bounds)), repr(tuple(tile) if not isinstance(
        tile, int) else (tile,)))
    try:
        if key in _tile_clean.get(tk, ()):
            return report
    except TypeError:
        pass
    dims, tile_dims, counts, total = tile_grid(bounds, tile)
    # buffer -> list of (box, flat, los)
    per_buffer: Dict[str, List[Tuple[Any, int, Tuple[int, ...]]]] = {}
    from .shim import _norm_box

    for flat in range(total):
        args = tile_args(dims, tile_dims, counts, flat)
        for s in tk.stores:
            try:
                idx = s.index(tuple(args))
            except Exception as e:  # noqa: BLE001
                report.add(
                    "shim-unsupported", INFO, tk.name,
                    f"store slab {s.name!r} index not concretely "
                    f"evaluable: {e}",
                )
                return report
            shape = tuple(tk.data_specs[s.data].shape)
            box = _norm_box(shape, idx)
            per_buffer.setdefault(s.data, []).append(
                (box, flat, tuple(args[1:1 + len(dims)]))
            )
    for buf, wins in per_buffer.items():
        # Sweep in first-axis order so disjoint layouts exit near-linearly.
        wins.sort(key=lambda w: w[0][0] if w[0] else (0, 0))
        active: List[Tuple[Any, int, Tuple[int, ...]]] = []
        for box, flat, los in wins:
            lo0 = box[0][0] if box else 0
            active = [w for w in active if (w[0][0][1] if w[0] else 1 << 62)
                      > lo0]
            for obox, oflat, olos in active:
                if boxes_overlap(box, obox):
                    report.add(
                        "tile-race", ERROR, tk.name,
                        f"tiles {olos} and {los} store overlapping "
                        f"windows of buffer {buf!r}",
                        buffer=buf, tile_a=olos, tile_b=los,
                        window_a=obox, window_b=box,
                        flat_a=oflat, flat_b=flat,
                    )
                    return report  # one witness is enough
            active.append((box, flat, los))
    try:
        _tile_clean.setdefault(tk, set()).add(key)
    except TypeError:
        pass
    return report


# -------------------------------------------------------- batch bodies


def _slot_of_box(box, width: int) -> Optional[int]:
    """Best-effort slot attribution of a window: which synthetic slot's
    arg stride the first nonzero start coordinate falls under."""
    from .shim import ARG_STRIDE

    for lo, _hi in box:
        if lo >= ARG_STRIDE:
            s = lo // ARG_STRIDE - 1
            return s if 0 <= s < width else None
    return None


def check_batch_spec(name: str, fid: int, spec, data_specs, scratch_specs,
                     report: Optional[AnalysisReport] = None,
                     suppress: Sequence[str] = (),
                     ctx_hook=None) -> AnalysisReport:
    """Run the four shim-based checks over one routed BatchSpec (see
    module docstring). ``suppress`` composes with the spec's own
    ``verify_suppress`` annotation (a per-rule opt-out the spec owner
    writes next to the deliberate violation)."""
    sup = tuple(suppress) + tuple(getattr(spec, "verify_suppress", ()))
    if report is not None:
        sup = sup + tuple(report._suppress)
        sub = AnalysisReport(sup)
    else:
        report = sub = AnalysisReport(sup)
    try:
        t = run_batch_body(
            spec, fid, data_specs, scratch_specs,
            prefetch_count=0, ctx_hook=ctx_hook,
        )
    except ShimUnsupported as e:
        sub.add(
            "shim-unsupported", INFO, name,
            f"batch body not abstractly interpretable ({e}); "
            "slot-race and prefetch-protocol checks skipped",
        )
    else:
        _check_round_trace(name, spec, t, sub)
        if spec.prefetch:
            _check_prefetch(name, fid, spec, data_specs, scratch_specs,
                            sub)
    if sub is not report:
        report.extend(sub)
    return report


def _check_round_trace(name: str, spec, t: BodyTrace,
                       report: AnalysisReport) -> None:
    # (c) wait/start matching within a round with nothing announced.
    # A trace with truncated / arg-bounded loops is an UNDER-
    # approximation, but only around the truncation points (the seq
    # marks where skipped iterations would have emitted): an unmatched
    # START demotes only when a truncated window sits AFTER it (the
    # missing wait could be in the skipped iterations - the cholesky
    # pipelined row stream), an unmatched WAIT only when one sits
    # BEFORE it (the missing start could). Findings whose whole
    # matching window was observed exactly stay errors - a blanket
    # demotion would let an exact-window protocol bug ride along with
    # one unrelated arg-dependent loop.
    uw, us = t.unmatched_waits(), t.unmatched_starts()
    marks = t.approx_marks
    dem_w = [w for w in uw if any(m < w.seq for m in marks)]
    dem_s = [s for s in us if any(m > s.seq for m in marks)]
    if dem_w or dem_s:
        report.add(
            "shim-unsupported", INFO, name,
            f"{t.approx_loops} loop(s) ran truncated (arg-dependent "
            f"bounds); {len(dem_s)} start(s)/{len(dem_w)} wait(s) "
            "left unmatched inside the truncated windows - DMA "
            "protocol not verifiable for those events (exact-window "
            "events still check)",
        )
        uw = [w for w in uw if w not in dem_w]
        us = [s for s in us if s not in dem_s]
    for w in uw:
        report.add(
            "prefetch-protocol", ERROR, name,
            f"DMA wait with no matching start: {w.src[0]} -> "
            f"{w.dst[0]}{list(w.dst[1])}",
            dst=w.dst, sem=w.sem,
        )
    for s in us:
        report.add(
            "prefetch-protocol", ERROR, name,
            "DMA start never waited in a round with no prefetch "
            f"announced (it would outlive the batch's completions): "
            f"{s.src[0]} -> {s.dst[0]}{list(s.dst[1])}",
            dst=s.dst, sem=s.sem,
        )
    # (a) per-slot store windows into data buffers pairwise disjoint.
    stores = [e for e in t.starts() if e.dst_kind == "data"]
    for a, b in itertools.combinations(stores, 2):
        if a.dst[0] != b.dst[0]:
            continue
        if boxes_overlap(a.dst[1], b.dst[1]):
            sa = _slot_of_box(a.dst[1], spec.width)
            sb = _slot_of_box(b.dst[1], spec.width)
            if sa is not None and sa == sb:
                continue  # one slot touching its own window twice
            report.add(
                "batch-race", ERROR, name,
                f"two batch slots store overlapping windows of "
                f"{a.dst[0]!r} "
                f"(slots {sa} and {sb}: the slab index ignores the "
                "slot's descriptor)",
                buffer=a.dst[0], window_a=a.dst[1], window_b=b.dst[1],
                slot_a=sa, slot_b=sb,
            )
            return
    # (b) per-slot value-slot writes disjoint. A BLIND overwrite of a
    # slot another batch slot already wrote is the copy-paste bug (the
    # second writer's result is independent of the first, so one slot's
    # output is silently lost); a read-modify-write chain (the slot
    # READ the value after the earlier write, before its own) is the
    # legitimate sequential-accumulator pattern - batch bodies run
    # their slots in order, so in-SMEM accumulation is well-defined.
    last_write: Dict[int, Tuple[int, int]] = {}  # vs -> (slot, seq)
    for slot, vs, seq in sorted(t.value_writes, key=lambda w: w[2]):
        if slot is None:
            last_write[vs] = (-1, seq)
            continue
        prev = last_write.get(vs)
        if prev is not None and prev[0] not in (slot, -1):
            read_between = any(
                rvs == vs and rslot in (slot, None)
                and prev[1] < rseq < seq
                for rslot, rvs, rseq in t.value_reads
            )
            if not read_between:
                report.add(
                    "batch-race", ERROR, name,
                    f"batch slots {prev[0]} and {slot} both write value "
                    f"slot {vs}, and slot {slot} never read it first "
                    "(blind overwrite: one slot's output is lost)",
                    value_slot=vs, slot_a=prev[0], slot_b=slot,
                )
                return
        last_write[vs] = (slot, seq)
    # Overreach: next-batch reads beyond the announced count (announced
    # 0 here, so ANY next read is unguarded).
    for s, pfc in t.next_reads:
        report.add(
            "prefetch-protocol", WARN, name,
            f"reads prospective next-batch slot {s} with only {pfc} "
            "announced (guard next_arg/next_idx with "
            "pl.when(s < ctx.prefetch_count))",
            slot=s, announced=pfc,
        )
        break


# ----------------------------------------------------- splice protocol


def check_splice(mk, report: Optional[AnalysisReport] = None,
                 suppress: Sequence[str] = ()) -> AnalysisReport:
    """The dynamic-graph splice protocol (device/dyngraph.py; builds
    stamped ``mk._dyngraph``). Three rules:

    1. NO lane of a dyngraph build runs the cross-round prefetch: a
       prefetched edge slab could race the write-back of the same block
       row by an UPDATE in the current round (rule ``splice-protocol``).
    2. The spare-region wiring is exact: ``spare_base + n * spare`` rows
       of spares behind the static rows must equal the stamped block
       total AND the ``indices`` buffer's leading dim - a mismatch means
       splices write past the buffer or EXPANDs read phantom blocks.
    3. Abstract-interpret the UPDATE batch body (recording shim) and
       require every DMA store into a data buffer to be either a
       READ-MODIFY-WRITE (the same window was DMA-read earlier in the
       trace - the tail-append spelling) or target a row at/above
       ``spare_base`` - the BLIND-OVERWRITE EXEMPTION: the append
       cursor owns fresh spare rows uniquely, so building the row whole
       in VMEM and storing it without a prior read is legal THERE and
       only there. A blind store into a static row is the data-loss
       spelling (it would clobber live edges) and is refused.
    """
    dg = getattr(mk, "_dyngraph", None)
    report = report or AnalysisReport(suppress)
    if dg is None:
        return report
    # (1) prefetch off on every routed lane.
    for fid, spec in mk.batch_specs:
        if spec.prefetch:
            report.add(
                "splice-protocol", ERROR, mk.kernel_names[fid],
                "dyngraph build routes a lane WITH cross-round "
                "prefetch: a prefetched edge slab can race an UPDATE's "
                "block write-back in the same round - build dyngraph "
                "megakernels with prefetch off on every kind",
                fid=fid,
            )
    # (2) spare-region bounds wiring.
    total = dg["spare_base"] + dg["n"] * dg["spare"]
    rows = tuple(mk.data_specs["indices"].shape)[0]
    if total != dg["total_blocks"] or rows != dg["total_blocks"]:
        report.add(
            "splice-protocol", ERROR, "dg_update",
            f"spare-region bounds disagree: spare_base {dg['spare_base']}"
            f" + n {dg['n']} * spare {dg['spare']} = {total}, stamped "
            f"total_blocks {dg['total_blocks']}, indices rows {rows} - "
            "splices would write past the adjacency (or EXPANDs read "
            "phantom rows)",
            computed=total, stamped=dg["total_blocks"], rows=rows,
        )
    # (3) blind-overwrite exemption scoped to the spare region.
    upd_fid = None
    for fid, spec in mk.batch_specs:
        if mk.kernel_names[fid] == "dg_update":
            upd_fid = fid
            upd_spec = spec
    if upd_fid is None:
        return report  # scalar build: no routed body to interpret
    try:
        t = run_batch_body(
            upd_spec, upd_fid, mk.data_specs, mk.scratch_specs,
            prefetch_count=0,
        )
    except ShimUnsupported as e:
        report.add(
            "shim-unsupported", INFO, "dg_update",
            f"splice body not abstractly interpretable ({e}); "
            "blind-overwrite scoping not verifiable",
        )
        return report
    spare_base = int(dg["spare_base"])
    for ev in t.dma:
        if ev.op != "start" or ev.dst_kind != "data":
            continue
        row_lo = ev.dst[1][0][0] if ev.dst[1] else 0
        if row_lo >= spare_base:
            continue  # the exemption: fresh spare rows are owned
        rmw = any(
            o.op == "start" and o.seq < ev.seq and o.src[0] == ev.dst[0]
            and boxes_overlap(o.src[1], ev.dst[1])
            for o in t.dma
        )
        if not rmw:
            report.add(
                "splice-protocol", ERROR, "dg_update",
                f"blind DMA store into STATIC block row {row_lo} of "
                f"{ev.dst[0]!r} (< spare_base {spare_base}) with no "
                "prior read of that window: static rows hold live "
                "edges - append via read-modify-write, or target the "
                "spare region the append cursor owns",
                buffer=ev.dst[0], window=ev.dst[1],
                spare_base=spare_base,
            )
    return report


def _check_prefetch(name: str, fid: int, spec, data_specs, scratch_specs,
                    report: AnalysisReport) -> None:
    """(d): announce a prefetch of k, collect the body's residual
    starts, and require drain() to retire exactly those."""
    k = min(2, spec.width)
    try:
        tb = run_batch_body(
            spec, fid, data_specs, scratch_specs, prefetch_count=k,
        )
    except ShimUnsupported as e:
        report.add(
            "shim-unsupported", INFO, name,
            f"prefetch pass not interpretable ({e})",
        )
        return
    residual = tb.unmatched_starts()
    if not residual:
        if not tb.dma:
            # A compute-only body that opted into prefetch pops (FIFO
            # lane order) without any operand DMA: the protocol is
            # vacuously satisfied - nothing to issue, nothing to drain.
            pass
        elif tb.approx_loops:
            report.add(
                "shim-unsupported", INFO, name,
                "prefetch pass ran with truncated arg-dependent loops "
                "and left no residual starts; start-count conformance "
                "not verifiable",
            )
        else:
            report.add(
                "prefetch-protocol", ERROR, name,
                f"the tier announced a prefetch of {k} next-batch "
                "descriptors but the body issued no residual DMA starts "
                "(a prefetch body MUST issue exactly the starts the tier "
                "announces)",
                announced=k,
            )
        return
    # Which operand half did the prefetch target? The scheduler records
    # LS_PF_BUF = 1 - buf; the shim ran the body with buf=0.
    try:
        td = run_drain(
            spec, fid, data_specs, scratch_specs, prefetched=k, buf=1,
        )
    except ShimUnsupported as e:
        report.add(
            "shim-unsupported", INFO, name,
            f"drain not interpretable ({e})",
        )
        return
    approx = bool(tb.approx_loops or td.approx_loops)
    open_ = [s.triple() for s in residual]
    for w in td.dma:
        if w.op != "wait":
            continue
        if w.triple() in open_:
            open_.remove(w.triple())
        elif approx:
            report.add(
                "shim-unsupported", INFO, name,
                "drain/body DMA sets disagree under truncated "
                "arg-dependent loops; conformance not verifiable",
            )
            return
        else:
            report.add(
                "prefetch-protocol", ERROR, name,
                "drain waits a copy the body never started "
                f"(start-count mismatch): {w.src[0]} -> "
                f"{w.dst[0]}{list(w.dst[1])}",
                dst=w.dst, sem=w.sem, announced=k,
            )
            return
    for s in open_:
        if approx:
            report.add(
                "shim-unsupported", INFO, name,
                "residual prefetch start not drained under truncated "
                "arg-dependent loops; conformance not verifiable",
            )
            return
        report.add(
            "prefetch-protocol", ERROR, name,
            "prefetch DMA start never drained (the scheduler's exit "
            f"path would leave it in flight): {s[0][0]} -> "
            f"{s[1][0]}{list(s[1][1])}",
            src=s[0], dst=s[1], sem=s[2], announced=k,
        )
        return
