"""Reshard/migratability classification of kernel-table kinds.

``checkpoint.reshard`` moves only *link-free* rows (no successor links,
no home-link, no dynamic out slot), and ``ShardedMegakernel``'s
``migratable_fns`` contract requires migratable kernels to only read
their args and write accumulate-style slots. Whether a KIND can satisfy
those contracts is decidable at build time: run the kernel body once
through the recording shim and look at what it *does* -

- ``link-free``: no dynamic spawns with links, no continuation
  transfer; rows of this kind stay link-free unless the host built
  links into them.
- ``home-linked``: the body spawns successor-linked children or
  transfers its continuation (the fib/UTS family) - live rows of this
  kind carry links, so they migrate only through the resident
  home-link protocol and are never reshard-eligible.
- ``vector``: a subtree-tier routed kind (completes in place).
- ``unknown``: the shim could not interpret the body; no claim.

``classify_megakernel`` returns {kernel name: class} and is surfaced
through ``Megakernel.describe()``; ``check_migratable`` is the
``reshard-class`` rule - a kind claimed migratable by a runner whose
classification says ``home-linked`` is a mislabel caught before any row
ever migrates wrong.

Priority-bucketed kinds (ISSUE 15) keep their reshard class by
construction: ``BatchSpec.priority`` is pop-time ROUTING state - a
pure function of descriptor arg words evaluated by the scheduler, not
body code - so the recording-shim pass (which runs only the body)
classifies a bucketed kind exactly as its unbucketed twin, and
reshard/steal row filters need no bucket awareness (the bucket id
re-derives from the row's own words wherever it lands). Asserted in
tests/test_priority.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..device.descriptor import NO_TASK
from .findings import ERROR, WARN, AnalysisReport
from .shim import ShimUnsupported, run_batch_body, run_scalar_kernel

__all__ = [
    "KindSummary",
    "classify_megakernel",
    "check_migratable",
    "kind_summaries",
    "trace_class",
]

LINK_FREE = "link-free"
HOME_LINKED = "home-linked"
VECTOR = "vector"
UNKNOWN = "unknown"

# Scalar kernel fns are usually module-level functions shared across
# every construction in a process (the suite builds the same families
# hundreds of times) - the summary depends only on what the body DOES,
# so memoize per function object. Weak keys: a dynamically created
# closure's entry dies with it.
import weakref  # noqa: E402

_scalar_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


@dataclass
class KindSummary:
    """Everything one recording-shim pass teaches about a kernel kind:
    the reshard classification plus the promise-op events the wait-graph
    analysis consumes - so classification and deadlock detection share
    ONE pass per function object."""

    cls: str
    waits: List[Tuple[int, int]] = field(default_factory=list)
    satisfies: List[Tuple[int, int]] = field(default_factory=list)
    spawn_fns: List[int] = field(default_factory=list)


def trace_class(trace) -> str:
    """Classification verdict of one recorded body trace."""
    if trace.continuations:
        return HOME_LINKED
    for _slot, sp in trace.spawns:
        if (
            sp["succ0"] != NO_TASK or sp["succ1"] != NO_TASK
            or sp["dep_count"] != 0
        ):
            return HOME_LINKED
    return LINK_FREE


def _summarize(trace) -> KindSummary:
    return KindSummary(
        cls=trace_class(trace),
        waits=[(vs, seq) for _s, vs, seq in trace.waits],
        satisfies=[(vs, seq) for _s, vs, seq in trace.satisfies],
        spawn_fns=sorted({sp["fn"] for _s, sp in trace.spawns}),
    )


def kind_summaries(mk) -> Dict[str, KindSummary]:
    """{kernel name: KindSummary} for every kernel-table entry of ``mk``
    (memoized on the instance AND per scalar function object, so
    construction-time wait-graph checks, describe(), and reshard
    diagnostics all share one shim pass per body)."""
    cached = getattr(mk, "_kind_summaries", None)
    if cached is not None:
        return cached
    from ..device.megakernel import _is_batch_spec, _is_vector_spec

    out: Dict[str, KindSummary] = {}
    batch_bodies = {name: spec for name, spec in mk.route.items()
                    if _is_batch_spec(spec)}
    for i, name in enumerate(mk.kernel_names):
        if (name in mk.route and _is_vector_spec(mk.route[name])) or (
            getattr(mk.kernel_fns[i], "_hclib_vector_wrapped", False)
        ):
            # Never abstract-interpret a subtree engine (it embeds
            # whole-engine sweeps); vector kinds complete in place and
            # expose no promise ops.
            out[name] = KindSummary(cls=VECTOR)
            continue
        try:
            if name in batch_bodies:
                t = run_batch_body(
                    batch_bodies[name], i, mk.data_specs,
                    mk.scratch_specs, prefetch_count=0,
                )
                out[name] = _summarize(t)
            else:
                fn = mk.kernel_fns[i]
                try:
                    hit = _scalar_cache.get(fn)
                except TypeError:
                    hit = None
                if hit is not None:
                    out[name] = hit
                else:
                    t = run_scalar_kernel(
                        fn, mk.data_specs, mk.scratch_specs,
                    )
                    out[name] = _summarize(t)
                    try:
                        _scalar_cache[fn] = out[name]
                    except TypeError:
                        pass
        except ShimUnsupported as e:
            # Keep the promise-op events recorded BEFORE the unmodelled
            # construct (the partial trace rides the exception): a body
            # whose tail the shim cannot run must still feed its waits
            # to the deadlock gate - UNKNOWN classification, known
            # waits.
            partial = getattr(e, "trace", None)
            out[name] = (
                _summarize_partial(partial) if partial is not None
                else KindSummary(cls=UNKNOWN)
            )
    mk._kind_summaries = out
    return out


def _summarize_partial(trace) -> KindSummary:
    s = _summarize(trace)
    s.cls = UNKNOWN
    return s


def classify_megakernel(mk) -> Dict[str, str]:
    """{kernel name: class} for every kernel-table entry of ``mk``
    (memoized on the instance - construction and every later
    describe()/snapshot call share one shim pass)."""
    cached = getattr(mk, "_kind_classes", None)
    if cached is not None:
        return cached
    out = {name: s.cls for name, s in kind_summaries(mk).items()}
    mk._kind_classes = out
    return out


def check_migratable(mk, migratable_fns, runner: str,
                     report: Optional[AnalysisReport] = None,
                     suppress: Sequence[str] = (),
                     homed: bool = False) -> AnalysisReport:
    """The ``reshard-class`` audit: report every kernel id a runner
    claims migratable whose body classifies home-linked. NOT a runtime
    refusal - the exchanges carry row-level link filters, so such a
    claim legally moves just the kind's link-free rows - but it IS the
    signal that ``checkpoint.reshard`` will refuse bundles holding this
    kind's linked residue, which is what the warn spells out. hclint
    runs this over every in-repo mesh program; ``homed=True`` runners
    carry linked rows through the proxy protocol and are exempt."""
    report = report or AnalysisReport(suppress)
    if homed:
        return report
    classes = classify_megakernel(mk)
    for f in sorted(int(f) for f in migratable_fns):
        if not 0 <= f < len(mk.kernel_names):
            report.add(
                "reshard-class", ERROR, None,
                f"{runner} lists migratable kernel id {f} but the "
                f"kernel table has {len(mk.kernel_names)} entries",
                fn_id=f,
            )
            continue
        name = mk.kernel_names[f]
        if classes.get(name) == HOME_LINKED:
            report.add(
                "reshard-class", WARN, name,
                f"{runner} lists {name!r} (id {f}) as migratable, but "
                "its body spawns successor-linked children "
                "(home-linked): only its link-free rows will move "
                "under the exchange's row filter, and reshard will "
                "refuse checkpoints holding its linked residue",
                fn_id=f, classification=HOME_LINKED,
            )
    return report
