"""hclint: the build-time program verifier (host-only static analysis).

The batch/prefetch/migration contracts this runtime leans on -
"mutually independent by construction" batch slots, "output buffers
disjointly written across tiles", "a prefetch body MUST issue exactly
the starts the tier announces", "reshard moves link-free rows only" -
live in docstrings and are otherwise discovered at runtime, or never
(interpret mode can land the right bytes through a real slab race).
This package checks them when a program is BUILT:

- ``verify_megakernel(mk)`` - the construction-time entry
  ``Megakernel(verify=True)`` / ``HCLIB_TPU_VERIFY`` (default-on under
  pytest) runs: word-layout consistency, per-kind migratability
  classification, and for every routed ``BatchSpec`` the slot-race and
  prefetch-protocol conformance checks (recording-shim abstract
  interpretation; see shim.py).
- ``check_tile_windows(tk, bounds, tile)`` - whole-loop store-window
  disjointness over a concrete tile space (``run_forasync_device``
  calls it when verification is on).
- ``check_migratable(mk, fns, runner)`` - the reshard-class rule the
  multi-device runners apply to their ``migratable_fns`` claims.

Everything is pure host composition over the already-built Python
objects: no Mosaic, no Pallas trace, zero new device words - a build
with ``verify=False`` (or unset, outside pytest) is byte-identical to a
build that predates this package, and even with ``verify=True`` the
compiled program is untouched (the verifier can only *raise*).

Findings carry concrete witnesses (colliding tile coordinates, the
unmatched DMA start, the disagreeing layout word, the mislabeled kernel
id); error findings raise ``AnalysisError`` at construction unless
suppressed (``verify_suppress=("rule",)`` or ``("rule:kernel",)``).
``tools/hclint.py`` drives the same checks over the repo's program
builders from the command line / CI.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .classify import (
    KindSummary, check_migratable, classify_megakernel, kind_summaries,
    trace_class,
)
from .explore import (
    CreditExchangeModel, ExploreResult, InjectQuiesceModel,
    check_protocols, explore,
)
from .findings import (
    AnalysisError, AnalysisFinding, AnalysisReport, verify_default,
)
from .layout import check_layout
from .model import (
    certify_bnb_schedule, certify_claim, certify_frontier_schedule,
    certify_tile_schedule,
)
from .races import (
    boxes_overlap, check_batch_spec, check_splice, check_tile_windows,
)
from .shim import ShimUnsupported
from .waits import check_wait_graph, wait_graph

__all__ = [
    "AnalysisError",
    "AnalysisFinding",
    "AnalysisReport",
    "CreditExchangeModel",
    "ExploreResult",
    "InjectQuiesceModel",
    "KindSummary",
    "ShimUnsupported",
    "boxes_overlap",
    "certify_bnb_schedule",
    "certify_claim",
    "certify_frontier_schedule",
    "certify_tile_schedule",
    "check_batch_spec",
    "check_layout",
    "check_splice",
    "check_migratable",
    "check_protocols",
    "check_tile_windows",
    "check_wait_graph",
    "classify_megakernel",
    "explore",
    "kind_summaries",
    "trace_class",
    "verify_default",
    "verify_megakernel",
    "wait_graph",
]


def verify_megakernel(mk, suppress: Sequence[str] = (),
                      raise_on_error: bool = True,
                      report: Optional[AnalysisReport] = None
                      ) -> AnalysisReport:
    """Run every construction-time analysis over a built ``Megakernel``;
    returns the report (and raises ``AnalysisError`` on unsuppressed
    error findings unless ``raise_on_error=False``)."""
    report = report or AnalysisReport(suppress)
    report.extend(check_layout())
    for fid, spec in mk.batch_specs:
        name = mk.kernel_names[fid]
        check_batch_spec(
            name, fid, spec, mk.data_specs, mk.scratch_specs,
            report=report,
        )
    # Dynamic-graph builds (mk._dyngraph, device/dyngraph.py) carry the
    # splice protocol on top: prefetch off everywhere, spare-region
    # bounds exact, blind block-row stores scoped to the spare region
    # the append cursor owns (races.check_splice).
    if getattr(mk, "_dyngraph", None) is not None:
        check_splice(mk, report=report)
    # Wait-graph deadlock detection (waits.py): the construction gate
    # for any kind performing an on-device promise wait. A tree with no
    # wait ops pays a cheap code-object scan and zero shim passes; a
    # waiting tree shares the memoized kind_summaries pass with the
    # reshard classification.
    check_wait_graph(mk, report=report)
    # Kind classification is LAZY (classify_megakernel memoizes on the
    # instance): its consumers are describe(), snapshot meta, and
    # reshard's upfront diagnostics, none of which every construction
    # pays for - the tier-1 budget is the binding constraint. The
    # bounded-interleaving explorer and schedule-independence
    # certification (explore.py / model.py) are likewise lazy/budgeted:
    # they run from tools/hclint.py, describe(), and the CI step, never
    # per construction.
    if raise_on_error:
        report.raise_errors()
    return report
