"""Typed findings + report plumbing for the build-time verifier.

A finding is a single rule violation with a concrete *witness* - the two
colliding store windows, the unmatched DMA start, the disagreeing layout
word - so a report reads like a failing assertion, not a style nag.

Rule ids (stable; the suppression syntax and README table key on them):

    batch-race        two batch slots write overlapping data (store
                      windows or value slots) in one round
    tile-race         two tiles of one forasync loop store overlapping
                      windows of an output buffer
    prefetch-protocol a prefetch body/drain pair violates the tier's
                      DMA handshake (unmatched start or wait, overreach)
    layout            a shared word-layout constant disagrees between
                      modules
    reshard-class     a kernel kind's migratability claim contradicts
                      its classified behavior (home-linked mislabeled
                      migratable)
    wait-cycle        the per-kind spawn/wait/satisfy graph holds a
                      cycle (or an unsatisfiable wait): an on-device
                      promise wait that can deadlock under every
                      schedule (analysis/waits.py)
    interleaving      the bounded-interleaving explorer found a
                      protocol violation - deadlock/wedge, conservation
                      break, or quiesce-freeze divergence - with the
                      action-prefix interleaving as the witness
                      (analysis/explore.py)
    schedule-independence  a kernel claiming schedule-independence
                      diverged across permuted pop orders; the witness
                      is the two divergent schedules (analysis/model.py)
    shim-unsupported  a body could not be abstractly interpreted
                      (info only: nothing verified, nothing refuted)

Severities: ``error`` findings make construction raise
``AnalysisError`` (unless suppressed); ``warn`` and ``info`` ride the
report only. Suppression: ``"<rule>"`` silences a rule everywhere in
that kernel's verification, ``"<rule>:<kernel-name>"`` only for the
named kernel-table entry; suppressed findings stay in the report with
``suppressed=True`` so hclint can still show them.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..runtime.env import env_raw

__all__ = [
    "AnalysisError",
    "AnalysisFinding",
    "AnalysisReport",
    "verify_default",
]

ERROR = "error"
WARN = "warn"
INFO = "info"


@dataclass
class AnalysisFinding:
    rule: str
    severity: str
    kernel: Optional[str]      # kernel-table entry name, when attributable
    message: str
    witness: Dict[str, Any] = field(default_factory=dict)
    suppressed: bool = False

    def __str__(self) -> str:
        k = f" [{self.kernel}]" if self.kernel else ""
        w = f" witness={self.witness}" if self.witness else ""
        s = " (suppressed)" if self.suppressed else ""
        return f"{self.severity}: {self.rule}{k}: {self.message}{w}{s}"

    def to_jsonable(self) -> Dict[str, Any]:
        """The one serialization of a finding (the --json-out artifact
        schema): reports and certificate-embedded findings must agree
        field-for-field so the CI diff never splits."""
        return {
            "rule": self.rule, "severity": self.severity,
            "kernel": self.kernel, "message": self.message,
            "witness": {k: repr(v) for k, v in self.witness.items()},
            "suppressed": self.suppressed,
        }


class AnalysisError(ValueError):
    """Raised at construction when unsuppressed error-severity findings
    exist; carries the full report."""

    def __init__(self, report: "AnalysisReport") -> None:
        self.report = report
        errs = report.errors()
        lines = "\n  ".join(str(f) for f in errs)
        super().__init__(
            f"hclint: {len(errs)} build-time verification failure(s):\n"
            f"  {lines}\n(suppress a deliberate violation with "
            "verify_suppress=('<rule>' or '<rule>:<kernel>',); "
            "disable verification with verify=False / HCLIB_TPU_VERIFY=0)"
        )


class AnalysisReport:
    """Findings accumulator with suppression applied at add() time."""

    def __init__(self, suppress: Sequence[str] = ()) -> None:
        self.findings: List[AnalysisFinding] = []
        self._suppress = tuple(suppress or ())
        # Kind classification (classify.py fills this): name -> class.
        self.kind_classes: Dict[str, str] = {}

    def suppressed(self, rule: str, kernel: Optional[str]) -> bool:
        for s in self._suppress:
            if s == rule:
                return True
            if kernel is not None and s == f"{rule}:{kernel}":
                return True
        return False

    def add(self, rule: str, severity: str, kernel: Optional[str],
            message: str, **witness) -> AnalysisFinding:
        f = AnalysisFinding(
            rule, severity, kernel, message, witness,
            suppressed=self.suppressed(rule, kernel),
        )
        self.findings.append(f)
        return f

    def extend(self, other: "AnalysisReport") -> None:
        self.findings.extend(other.findings)
        self.kind_classes.update(other.kind_classes)

    def errors(self) -> List[AnalysisFinding]:
        return [
            f for f in self.findings
            if f.severity == ERROR and not f.suppressed
        ]

    def actionable(self) -> List[AnalysisFinding]:
        """What hclint's exit code counts: anything above info that was
        not deliberately suppressed."""
        return [
            f for f in self.findings
            if f.severity in (ERROR, WARN) and not f.suppressed
        ]

    def raise_errors(self) -> None:
        if self.errors():
            raise AnalysisError(self)

    def to_jsonable(self) -> List[Dict[str, Any]]:
        return [f.to_jsonable() for f in self.findings]


def verify_default() -> bool:
    """The ``verify=None`` resolution: HCLIB_TPU_VERIFY wins when set
    ('0' forces off, anything else on); otherwise default ON under
    pytest (the suite is where the contracts are exercised; production
    builds opt in) and off everywhere else."""
    v = env_raw("HCLIB_TPU_VERIFY")
    if v is not None and v != "":
        return v != "0"
    import os

    return "PYTEST_CURRENT_TEST" in os.environ or "pytest" in sys.modules
