"""Bounded-interleaving protocol exploration (the ``interleaving`` rule).

The repo's concurrency protocols - the WRR tenant inject poll, the
steal-credit exchange, the quiesce-settle condition - already have
host-side executable specs (``tenants.wrr_poll_reference``, the credit
discipline documented in device/resident.py, the freeze contract the
checkpoint export promises). Runtime tests exercise ONE schedule per
seed; this module explores EVERY schedule of a small seeded
configuration, depth-bounded, and checks the properties the specs
promise:

- **termination / no wedge**: every maximal interleaving reaches a
  terminal state with no work pending (a terminal state with pending
  work is a deadlock - the credit-wedge shape ``credit_timeout=0``
  produces at runtime, found here as a concrete action prefix);
- **conservation**: installed == executed + dropped + residue at every
  terminal state (nothing lost, nothing double-counted);
- **quiesce freeze**: once quiesce is observed, the words the
  checkpoint would export are exactly the words still live at exit - a
  poll that keeps consuming after the freeze diverges and is refused.

The explorer is a stateful DFS with full state deduplication: states
are small tuples, so the REACHABLE SPACE - not the path space - bounds
the work, which is the reduction that matters at these sizes. A
footprint-based persistent-set reduction was tried and REJECTED as
unsound here: disjointness against the currently-enabled set is not
enough, because an action can disable a FUTURE dependency (exec
consuming the victim's surplus disables the steal request whose
interleaving holds the wedge) - pruning on it silently dropped the
credit-wedge witness. ``Model.footprint`` remains part of the model
interface (it documents each action's resource set and feeds the
independence diagnostics in witnesses), but no schedule is ever
skipped. Depth and wall budget are knobs (``HCLIB_TPU_MODEL_DEPTH`` /
``HCLIB_TPU_MODEL_BUDGET_S``, runtime/env.py); an exhausted budget
flags the result incomplete instead of silently passing.

Everything is host-only numpy/python - no Pallas, no Mosaic - and the
poll model calls ``wrr_poll_reference`` itself, so the explored
semantics can never drift from the executable spec the fairness tests
and chaos scenarios run.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.env import env_float, env_int
from .findings import ERROR, AnalysisReport

__all__ = [
    "Action",
    "BundleStoreModel",
    "CreditExchangeModel",
    "EgressMailboxModel",
    "ExploreResult",
    "InjectQuiesceModel",
    "check_protocols",
    "default_depth",
    "default_budget_s",
    "explore",
]

Action = Tuple  # ("name", arg, ...) - hashable, printable


def default_depth() -> int:
    return env_int("HCLIB_TPU_MODEL_DEPTH", 64)


def default_budget_s() -> float:
    return env_float("HCLIB_TPU_MODEL_BUDGET_S", 20.0)


@dataclass
class Violation:
    message: str
    witness: Tuple[Action, ...]
    state: Tuple


@dataclass
class ExploreResult:
    """What one bounded exploration established."""

    states: int = 0
    terminals: int = 0
    transitions: int = 0
    complete: bool = True    # False: depth/budget bound cut the search
    violations: List[Violation] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.violations


def explore(model, depth: Optional[int] = None,
            budget_s: Optional[float] = None,
            max_states: int = 200_000) -> ExploreResult:
    """Explore every interleaving of ``model`` from its initial state
    (up to dedup + reduction), checking terminal states. Stops early -
    flagged incomplete - on the depth bound, the wall budget, or the
    state cap."""
    depth = default_depth() if depth is None else int(depth)
    budget = default_budget_s() if budget_s is None else float(budget_s)
    t_end = time.monotonic() + budget
    res = ExploreResult()
    seen: Dict[Tuple, int] = {}
    # DFS stack of (state, prefix tuple).
    stack: List[Tuple[Tuple, Tuple[Action, ...]]] = [(model.initial(), ())]
    while stack:
        if time.monotonic() > t_end or len(seen) > max_states:
            res.complete = False
            break
        state, prefix = stack.pop()
        if state in seen:
            continue
        seen[state] = len(prefix)
        res.states += 1
        enabled = model.enabled(state)
        if not enabled:
            res.terminals += 1
            for msg in model.check_final(state):
                res.violations.append(Violation(msg, prefix, state))
            continue
        if len(prefix) >= depth:
            res.complete = False
            continue
        # EVERY enabled action branches - no schedule is skipped (see
        # the module docstring for why footprint-based pruning against
        # the enabled set alone is unsound: it can hide an interleaving
        # whose key action only becomes enabled later). The state dedup
        # above is the whole reduction.
        for a in enabled:
            res.transitions += 1
            stack.append((model.apply(state, a), prefix + (a,)))
    return res


# ----------------------------------------------- inject poll + quiesce


class InjectQuiesceModel:
    """The streaming-inject front door as a model: per-tenant ring
    regions consumed by the WRR poll (``wrr_poll_reference`` - the
    executable spec itself, called per transition), an install queue the
    scheduler drains, and the quiesce freeze.

    Config: ``lanes`` is a sequence of (rows, weight) or (rows, weight,
    expired_mask, paused); ``capacity`` bounds the scheduler headroom
    (install queue depth); ``quiesce=True`` adds the quiesce action;
    ``freeze_poll=False`` plants the protocol bug where the poll keeps
    consuming after the freeze - the seeded quiesce-divergence fixture.

    State: (consumed per lane, dropped per lane, expired per lane,
    queue, executed, polls, quiescing, exported-residue-or-None).
    """

    def __init__(self, lanes: Sequence[Tuple], capacity: int = 4,
                 quiesce: bool = False, freeze_poll: bool = True,
                 region_rows: int = 8) -> None:
        norm = []
        for lane in lanes:
            rows, weight = lane[0], lane[1]
            expired = tuple(lane[2]) if len(lane) > 2 else ()
            paused = bool(lane[3]) if len(lane) > 3 else False
            if rows > region_rows:
                raise ValueError(
                    f"lane rows {rows} exceed region_rows {region_rows}"
                )
            norm.append((int(rows), int(weight), expired, paused))
        self.lanes = norm
        self.capacity = int(capacity)
        self.quiesce = bool(quiesce)
        self.freeze_poll = bool(freeze_poll)
        self.region_rows = int(region_rows)
        self.total_rows = sum(r for r, _w, _e, _p in norm)

    def initial(self) -> Tuple:
        T = len(self.lanes)
        return ((0,) * T, (0,) * T, (0,) * T, 0, 0, 0, 0, None)

    def _residue(self, state) -> Tuple[int, ...]:
        cons = state[0]
        return tuple(
            rows - c for (rows, _w, _e, _p), c in zip(self.lanes, cons)
        )

    def enabled(self, state) -> List[Action]:
        cons, _drop, _exp, queue, _ex, _polls, quiescing, _snap = state
        out: List[Action] = []
        poll_frozen = quiescing and self.freeze_poll
        if not poll_frozen and queue < self.capacity:
            if any(
                rows - c > 0 and w > 0 and not p
                for (rows, w, _e, p), c in zip(self.lanes, cons)
            ) or any(
                rows - c > 0 and p
                for (rows, _w, _e, p), c in zip(self.lanes, cons)
            ):
                out.append(("poll",))
        if queue > 0:
            out.append(("exec",))
        if self.quiesce and not quiescing:
            out.append(("quiesce",))
        return out

    def apply(self, state, action) -> Tuple:
        cons, drop, exp, queue, executed, polls, quiescing, snap = state
        if action[0] == "exec":
            return (cons, drop, exp, queue - 1, executed + 1, polls,
                    quiescing, snap)
        if action[0] == "quiesce":
            return (cons, drop, exp, queue, executed, polls, 1,
                    self._residue(state))
        # poll: rebuild the numpy tctl/ring and run the executable spec.
        from ..device.descriptor import RING_ROW, TEN_EXPIRED
        from ..device.tenants import (
            TC_CONSUMED, TC_DROPPED, TC_EXPIRED, TC_PAUSE, TC_TAIL,
            TC_WEIGHT, wrr_poll_reference,
        )

        T = len(self.lanes)
        tctl = np.zeros((T, 8), np.int64)
        ring = np.zeros((T * self.region_rows, RING_ROW), np.int32)
        for li, (rows, w, expired, paused) in enumerate(self.lanes):
            tctl[li, TC_TAIL] = rows
            tctl[li, TC_CONSUMED] = cons[li]
            tctl[li, TC_WEIGHT] = w
            tctl[li, TC_PAUSE] = 1 if paused else 0
            for r in expired:
                ring[li * self.region_rows + int(r), TEN_EXPIRED] = 1
        installed = wrr_poll_reference(
            ring, tctl, self.region_rows, polls, self.capacity - queue
        )
        return (
            tuple(int(tctl[li, TC_CONSUMED]) for li in range(T)),
            tuple(
                drop[li] + int(tctl[li, TC_DROPPED]) for li in range(T)
            ),
            tuple(
                exp[li] + int(tctl[li, TC_EXPIRED]) for li in range(T)
            ),
            queue + len(installed),
            executed,
            # Only the WRR start-lane rotation reads the round index, so
            # the state keeps it mod T - the state space stays finite.
            (polls + 1) % T,
            quiescing,
            snap,
        )

    def footprint(self, action) -> FrozenSet[str]:
        return {
            "poll": frozenset({"ring", "queue"}),
            "exec": frozenset({"queue"}),
            "quiesce": frozenset({"ring", "quiesce"}),
        }[action[0]]

    def check_final(self, state) -> List[str]:
        cons, drop, exp, queue, executed, _polls, quiescing, snap = state
        out: List[str] = []
        residue = self._residue(state)
        consumed = sum(cons)
        if consumed != executed + sum(drop) + sum(exp) + queue:
            out.append(
                "conservation: consumed "
                f"{consumed} != executed {executed} + dropped "
                f"{sum(drop)} + expired {sum(exp)} + queued {queue}"
            )
        # Cursor sanity per lane (residue = rows - consumed is an
        # identity, so "seeded == consumed + residue" would be a
        # tautology; the checkable property is the cursor staying
        # inside its region - a poll that walked past tail or backward
        # would double-count or resurrect rows).
        for li, ((rows, _w, _e, _p), c) in enumerate(
            zip(self.lanes, cons)
        ):
            if not 0 <= c <= rows:
                out.append(
                    f"conservation: lane {li} consumed cursor {c} "
                    f"outside its region [0, {rows}]"
                )
        if quiescing and snap is not None and tuple(snap) != residue:
            out.append(
                "quiesce-freeze: the residue exported at observation "
                f"{tuple(snap)} != the residue at exit {residue} (the "
                "poll consumed rows the checkpoint already exported)"
            )
        return out


# --------------------------------------------------- credit exchange


class CreditExchangeModel:
    """The steal-credit exchange as a model (the device/resident.py
    discipline): a thief requests, the victim grants a row over the
    wire WITH a credit, the thief's owed wait consumes the credit and
    installs the row. A dropped credit (``drop_credit=k`` drops the
    k-th grant's credit - the seeded DeviceFaultPlan fault) leaves the
    row in flight and the thief's wait never enabled: without
    regeneration (``regen=False``, the ``credit_timeout=0`` lockstep
    wedge) the exploration finds the terminal-with-work-pending
    interleaving and returns it as the witness; ``regen=True`` (the
    shipped recovery: a starved waiter skips the owed wait and recovers
    the row) restores termination + conservation on every schedule.

    State: (tasks per dev, executed, request-or-None, wire row count,
    credits per dev, grants, dropped credits).
    """

    def __init__(self, tasks: Sequence[int],
                 drop_credit: Optional[int] = None,
                 regen: bool = False, max_steals: int = 4) -> None:
        self.tasks0 = tuple(int(t) for t in tasks)
        self.ndev = len(self.tasks0)
        self.drop_credit = drop_credit
        self.regen = bool(regen)
        self.max_steals = int(max_steals)
        self.total = sum(self.tasks0)

    def initial(self) -> Tuple:
        return (self.tasks0, 0, None, 0, (0,) * self.ndev, 0, 0)

    def enabled(self, state) -> List[Action]:
        tasks, _ex, req, wire, credits, grants, _drops = state
        out: List[Action] = []
        for d in range(self.ndev):
            if tasks[d] > 0:
                out.append(("exec", d))
        if req is None and grants < self.max_steals:
            for t in range(self.ndev):
                if tasks[t] == 0 and credits[t] == 0:
                    for v in range(self.ndev):
                        if v != t and tasks[v] > 1:
                            out.append(("request", t, v))
        if req is not None:
            # A victim drained between request and response answers
            # EMPTY (deny) - it cannot grant a row it no longer holds.
            if tasks[req[1]] > 0:
                out.append(("grant", req[0], req[1]))
            else:
                out.append(("deny", req[0], req[1]))
        orphaned = wire - sum(credits)
        for t in range(self.ndev):
            if credits[t] > 0 and wire > 0:
                out.append(("recv", t))
            elif self.regen and orphaned > 0 and credits[t] == 0:
                # Starved-channel credit regeneration (the shipped
                # recovery): a waiter whose owed credit never arrived -
                # an ORPHANED in-flight row exists - skips the owed
                # wait and recovers the row.
                out.append(("regen", t))
        return out

    def apply(self, state, action) -> Tuple:
        tasks, ex, req, wire, credits, grants, drops = state
        tasks = list(tasks)
        credits = list(credits)
        kind = action[0]
        if kind == "exec":
            tasks[action[1]] -= 1
            ex += 1
        elif kind == "request":
            req = (action[1], action[2])
        elif kind == "deny":
            req = None
        elif kind == "grant":
            t, v = action[1], action[2]
            tasks[v] -= 1
            wire += 1
            if self.drop_credit is not None and grants == self.drop_credit:
                drops += 1  # the credit is lost in flight
            else:
                credits[t] += 1
            grants += 1
            req = None
        elif kind == "recv":
            t = action[1]
            credits[t] -= 1
            wire -= 1
            tasks[t] += 1
        elif kind == "regen":
            t = action[1]
            wire -= 1
            tasks[t] += 1
        return (tuple(tasks), ex, req, wire, tuple(credits), grants,
                drops)

    def footprint(self, action) -> FrozenSet:
        kind = action[0]
        if kind == "exec":
            return frozenset({("dev", action[1])})
        if kind == "recv" or kind == "regen":
            return frozenset({("dev", action[1]), "wire"})
        # request/grant touch both endpoints and the wire.
        return frozenset(
            {("dev", action[1]), ("dev", action[2]), "wire"}
        )

    def check_final(self, state) -> List[str]:
        tasks, ex, _req, wire, _credits, _grants, drops = state
        out: List[str] = []
        if ex + sum(tasks) + wire != self.total:
            out.append(
                f"conservation: executed {ex} + queued {sum(tasks)} + "
                f"in-flight {wire} != seeded {self.total}"
            )
        if ex < self.total:
            why = (
                f"credit wedge: {wire} stolen row(s) in flight with "
                f"{drops} dropped credit(s) and no regeneration - the "
                "thief's owed wait never fires, so the mesh exits with "
                f"{self.total - ex} task(s) unrun"
                if drops
                else f"deadlock: {self.total - ex} task(s) unrun with "
                "no enabled action"
            )
            out.append(why)
        return out


# --------------------------------------------------- egress mailbox


class EgressMailboxModel:
    """The completion-mailbox egress protocol (device/egress.py +
    inject.py, ISSUE 16) as a model: tokened rows install under the
    credit gate (parked + in-flight < park capacity), retire into the
    mailbox - or PARK when it is full (explicit backpressure, never a
    drop) - parked rows flush as mailbox room opens, the host consumes
    published rows, and a quiesce cut exports by draining BOTH regions
    (the run_stream driver is the drainer at the entry boundary, so the
    export does not depend on the client poller being alive).

    The property the curated configs prove: a FULL mailbox - even with
    a dead poller (``poller=False``: no consume action ever fires) -
    cannot wedge the quiesce export or the drained exit. Every maximal
    interleaving terminates with both regions empty and every seeded
    row accounted for: resolved + preempted + still-pending == seeded,
    exactly (the conservation identity the chaos soak checks at
    runtime).

    ``drain_parked=False`` plants the protocol bug where the export
    forgets the park ring - the seeded egress-wedge fixture: rows
    parked at the cut leak, and the exploration returns the concrete
    action prefix that loses them.

    State: (pending, inflight, mailbox, parked, resolved, preempted,
    quiescing, done).
    """

    def __init__(self, rows: int = 4, depth: int = 1,
                 park_cap: Optional[int] = None, poller: bool = True,
                 quiesce: bool = False, drain_parked: bool = True) -> None:
        self.rows = int(rows)
        self.depth = int(depth)
        # The shipped layout ties the park ring to the mailbox depth
        # (park_cap = depth in inject.py); override only to model
        # hypothetical geometries.
        self.park_cap = self.depth if park_cap is None else int(park_cap)
        self.poller = bool(poller)
        self.quiesce = bool(quiesce)
        self.drain_parked = bool(drain_parked)

    def initial(self) -> Tuple:
        return (self.rows, 0, 0, 0, 0, 0, 0, 0)

    def enabled(self, state) -> List[Action]:
        pend, infl, mail, park, _res, _pre, quiescing, done = state
        if done:
            return []
        out: List[Action] = []
        # Credit gate (the tpoll clamp): a retiring row ALWAYS has a
        # mailbox slot or a park slot, by construction - remove this
        # bound and the park append overflows.
        if pend > 0 and not quiescing and infl + park < self.park_cap:
            out.append(("install",))
        if infl > 0:
            out.append(("retire",))
        if park > 0 and mail < self.depth:
            out.append(("flush",))
        if self.poller and mail > 0:
            out.append(("consume",))
        if self.quiesce and not quiescing:
            out.append(("quiesce",))
        if quiescing:
            out.append(("export",))
        return out

    def apply(self, state, action) -> Tuple:
        pend, infl, mail, park, res, pre, quiescing, done = state
        kind = action[0]
        if kind == "install":
            return (pend - 1, infl + 1, mail, park, res, pre,
                    quiescing, done)
        if kind == "retire":
            # Full mailbox -> park, never drop, never abort.
            if mail < self.depth:
                return (pend, infl - 1, mail + 1, park, res, pre,
                        quiescing, done)
            return (pend, infl - 1, mail, park + 1, res, pre,
                    quiescing, done)
        if kind == "flush":
            return (pend, infl, mail + 1, park - 1, res, pre,
                    quiescing, done)
        if kind == "consume":
            return (pend, infl, mail - 1, park, res + 1, pre,
                    quiescing, done)
        if kind == "quiesce":
            return (pend, infl, mail, park, res, pre, 1, done)
        # export: the driver drains the mailbox (and the park ring)
        # directly - no client poller involved - then preempts the
        # installed-but-unretired tokens (they ride the etok export and
        # reattach after resume).
        drained = mail + (park if self.drain_parked else 0)
        return (pend, 0, 0, 0 if self.drain_parked else park,
                res + drained, pre + infl, quiescing, 1)

    def footprint(self, action) -> FrozenSet[str]:
        return {
            "install": frozenset({"ring", "etok"}),
            "retire": frozenset({"etok", "mailbox", "park"}),
            "flush": frozenset({"mailbox", "park"}),
            "consume": frozenset({"mailbox"}),
            "quiesce": frozenset({"quiesce"}),
            "export": frozenset({"mailbox", "park", "quiesce"}),
        }[action[0]]

    def check_final(self, state) -> List[str]:
        pend, infl, mail, park, res, pre, quiescing, done = state
        out: List[str] = []
        if pend + infl + mail + park + res + pre != self.rows:
            out.append(
                f"conservation: pending {pend} + in-flight {infl} + "
                f"mailbox {mail} + parked {park} + resolved {res} + "
                f"preempted {pre} != seeded {self.rows}"
            )
        if park > self.park_cap or mail > self.depth:
            out.append(
                f"egress-overflow: mailbox {mail}/{self.depth} or park "
                f"{park}/{self.park_cap} over capacity - the credit "
                "gate failed"
            )
        if done and (mail or park):
            out.append(
                f"egress-wedge: quiesce export exited with {mail} "
                f"mailbox row(s) and {park} parked row(s) undrained - "
                "their futures hang instead of resolving or preempting"
            )
        if quiescing and not done:
            out.append(
                "egress-wedge: quiesce observed but the export never "
                "completed (a full mailbox wedged the cut)"
            )
        if not quiescing and self.poller and (pend or infl or mail or park):
            out.append(
                f"egress-wedge: live poller but terminal with pending "
                f"{pend} / in-flight {infl} / mailbox {mail} / parked "
                f"{park} - the drained exit would hang"
            )
        return out


class BundleStoreModel:
    """The durable checkpoint store's publish protocol
    (``runtime/checkpoint.BundleStore.save``, ISSUE 17) as a model:
    a saver stages a generation member-by-member (npz blob, then
    manifest, then the atomic rename that publishes), a crash can land
    between ANY two steps, and concurrent ``load_latest`` readers walk
    the published generations at any point. The property the curated
    config proves: under the shipped ordering - rename LAST, after
    every member is staged - no schedule exposes a partial generation
    to a reader; a crash leaves only whole generations (or none), so
    the self-healing walk always has a valid newest to land on.

    ``publish_before_manifest=True`` plants the torn-publish bug (the
    rename lands before the manifest is written): a reader interleaved
    into that window observes a manifest-less generation, and the
    exploration returns the concrete save/crash/read prefix that
    exposes it - the seeded fixture for the durability soak's
    crash-point matrix.

    State: (saves_done, stage, gens_complete, gens_partial, crashed,
    reads_done, exposed). ``stage`` walks one save: 0 idle, 1 npz
    staged, 2 manifest staged (shipped ordering) or published-torn
    (bug ordering).
    """

    def __init__(self, saves: int = 2, crash: bool = True,
                 max_reads: int = 2,
                 publish_before_manifest: bool = False) -> None:
        self.saves = int(saves)
        self.crash = bool(crash)
        self.max_reads = int(max_reads)
        self.publish_before_manifest = bool(publish_before_manifest)

    def initial(self) -> Tuple:
        return (0, 0, 0, 0, 0, 0, 0)

    def enabled(self, state) -> List[Action]:
        saves, stage, _gc, _gp, crashed, reads, _exp = state
        out: List[Action] = []
        saving = not crashed and saves < self.saves
        if saving:
            out.append(("step",))
            if self.crash:
                out.append(("crash",))
        if reads < self.max_reads:
            out.append(("read",))
        return out

    def apply(self, state, action) -> Tuple:
        saves, stage, gc, gp, crashed, reads, exp = state
        kind = action[0]
        if kind == "crash":
            # Whatever was staged (stage 1/2) dies invisible - EXCEPT a
            # bug-ordering torn publish (counted in gens_partial), which
            # a crash leaves ON DISK for every later reader to trip on.
            return (saves, 0, gc, gp, 1, reads, exp)
        if kind == "read":
            # load_latest walks the published dirs: a partial
            # generation on disk right now is an exposure.
            return (saves, stage, gc, gp, crashed, reads + 1,
                    exp or (1 if gp else 0))
        # step: advance the in-flight save one member.
        if stage == 0:
            return (saves, 1, gc, gp, crashed, reads, exp)  # npz staged
        if stage == 1:
            if self.publish_before_manifest:
                # BUG ordering: rename lands now, manifest still unwritten.
                return (saves, 2, gc, gp + 1, crashed, reads, exp)
            return (saves, 2, gc, gp, crashed, reads, exp)  # manifest
        # stage == 2: the final member. Shipped ordering: fsync +
        # atomic rename publishes a WHOLE generation; bug ordering: the
        # late manifest completes the prematurely-published one.
        if self.publish_before_manifest:
            return (saves + 1, 0, gc + 1, gp - 1, crashed, reads, exp)
        return (saves + 1, 0, gc + 1, gp, crashed, reads, exp)

    def footprint(self, action) -> FrozenSet[str]:
        return {
            "step": frozenset({"store"}),
            "crash": frozenset({"saver"}),
            "read": frozenset({"store"}),
        }[action[0]]

    def check_final(self, state) -> List[str]:
        saves, stage, gc, gp, crashed, _reads, exp = state
        out: List[str] = []
        if exp:
            out.append(
                "durable-store: a schedule exposed a partial generation "
                "to load_latest (published before its manifest landed) - "
                "the rename-LAST publish ordering is violated"
            )
        if crashed and gp:
            out.append(
                f"durable-store: crash left {gp} torn generation(s) "
                "visible on disk - every restart pays a quarantine for "
                "a save that never completed"
            )
        if not crashed and (saves < self.saves or stage):
            out.append(
                f"durable-store: saver wedged at {saves}/{self.saves} "
                f"publish(es), stage {stage}"
            )
        return out


# ------------------------------------------------------------ curated


def check_protocols(report: Optional[AnalysisReport] = None,
                    depth: Optional[int] = None,
                    budget_s: Optional[float] = None,
                    configs: Optional[Sequence[Tuple[str, Any]]] = None
                    ) -> AnalysisReport:
    """Run the explorer over the curated protocol configurations (the
    hclint/CI audit): the WRR poll with skewed weights + expired rows +
    backpressure, the poll under a mid-stream quiesce, and the credit
    exchange with the shipped regeneration recovery. All must explore
    clean; violations land as ``interleaving`` error findings with the
    action-prefix witness."""
    report = report or AnalysisReport()
    if configs is None:
        configs = [
            (
                "inject-wrr(2:1, expired, backpressure)",
                InjectQuiesceModel(
                    [(3, 2, (1,)), (2, 1), (2, 1, (), True)],
                    capacity=2,
                ),
            ),
            (
                "inject-quiesce(freeze)",
                InjectQuiesceModel(
                    [(2, 1), (2, 2)], capacity=2, quiesce=True,
                ),
            ),
            (
                "steal-credit(regen)",
                CreditExchangeModel(
                    (3, 0), drop_credit=0, regen=True, max_steals=2,
                ),
            ),
            (
                "steal-credit(clean)",
                CreditExchangeModel((2, 1), max_steals=2),
            ),
            (
                # A 1-deep mailbox, a DEAD poller, a mid-flight quiesce:
                # the cut must still export clean - full mailboxes are
                # backpressure, never a wedge.
                "egress-mailbox(full, dead poller, quiesce)",
                EgressMailboxModel(
                    rows=4, depth=1, poller=False, quiesce=True,
                ),
            ),
            (
                # Live (arbitrarily slow) poller, no cut: every
                # interleaving drains to resolved == seeded.
                "egress-mailbox(slow poller, drain)",
                EgressMailboxModel(rows=3, depth=1, poller=True),
            ),
            (
                # Two staged publishes, a crash between any two member
                # writes, concurrent load_latest readers: no schedule
                # may expose a partial generation (rename is LAST).
                "bundle-store(crash x concurrent load)",
                BundleStoreModel(saves=2, crash=True, max_reads=2),
            ),
        ]
    for label, model in configs:
        res = explore(model, depth=depth, budget_s=budget_s)
        for v in res.violations:
            report.add(
                "interleaving", ERROR, None,
                f"protocol model {label}: {v.message}",
                interleaving=v.witness, config=label, state=v.state,
            )
        if not res.complete:
            report.add(
                "shim-unsupported", "info", None,
                f"protocol model {label}: exploration hit its "
                f"depth/budget bound after {res.states} states - "
                "verdicts cover the explored prefix only",
            )
    return report
