"""Word-layout consistency: ONE table, cross-checked against every
module that hard-codes part of the shared device ABI.

The descriptor ABI (descriptor.py), the ring-row transport words
(tenants.py / inject.py / resident.py), the batch-tier counter rows
(megakernel.py), and the checkpoint export key set (checkpoint.py) all
agree on word positions only by convention; this table is the
convention, and ``check_layout`` is the build-time assertion that no
module drifted. The witness of a violation is the word's name plus the
two disagreeing values - the exact edit to make.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .findings import ERROR, AnalysisReport

__all__ = ["LAYOUT", "check_layout"]

# word name -> (expected value, module paths that must agree). A module
# listed here must expose the attribute with exactly this value.
LAYOUT = {
    # descriptor ABI (device/descriptor.py)
    "DESC_WORDS": (16, ("hclib_tpu.device.descriptor",
                        "hclib_tpu.runtime.checkpoint")),
    "NO_TASK": (-1, ("hclib_tpu.device.descriptor",)),
    "F_FN": (0, ("hclib_tpu.device.descriptor",)),
    "F_DEP": (1, ("hclib_tpu.device.descriptor",)),
    "F_SUCC0": (2, ("hclib_tpu.device.descriptor",)),
    "F_SUCC1": (3, ("hclib_tpu.device.descriptor",)),
    "F_CSR_OFF": (4, ("hclib_tpu.device.descriptor",)),
    "F_CSR_N": (5, ("hclib_tpu.device.descriptor",)),
    "F_A0": (6, ("hclib_tpu.device.descriptor",)),
    "F_OUT": (12, ("hclib_tpu.device.descriptor",)),
    "F_HOME": (13, ("hclib_tpu.device.descriptor",)),
    "F_HROW": (14, ("hclib_tpu.device.descriptor",)),
    "F_VMASK": (15, ("hclib_tpu.device.descriptor",)),
    # injection-ring transport words: every module that stamps or reads
    # them must share the descriptor-side canonical home.
    "RING_ROW": (256, ("hclib_tpu.device.descriptor",
                       "hclib_tpu.device.inject",
                       "hclib_tpu.device.resident")),
    "TEN_ID": (16, ("hclib_tpu.device.descriptor",)),
    "TEN_EXPIRED": (17, ("hclib_tpu.device.descriptor",)),
    "TEN_DEADLINE_MS": (18, ("hclib_tpu.device.descriptor",)),
    "TEN_TOKEN": (19, ("hclib_tpu.device.descriptor",)),
    "TEN_ADMIT_ROUND": (20, ("hclib_tpu.device.descriptor",)),
    # completion-mailbox EGR row ABI (device/egress.py, ISSUE 16): the
    # host drain, the numpy executable spec, and the in-kernel publish
    # path (device/inject.py) all index these words; the ectl cursor
    # block (EC_*) rides beside them like the inject ctl row.
    "EGR_STATUS": (0, ("hclib_tpu.device.egress",)),
    "EGR_TOKEN": (1, ("hclib_tpu.device.egress",)),
    "EGR_TEN": (2, ("hclib_tpu.device.egress",)),
    "EGR_FN": (3, ("hclib_tpu.device.egress",)),
    "EGR_SLOT": (4, ("hclib_tpu.device.egress",)),
    "EGR_VALUE": (5, ("hclib_tpu.device.egress",)),
    "EGR_T_ADMIT": (6, ("hclib_tpu.device.egress",)),
    "EGR_T_SPANS": (7, ("hclib_tpu.device.egress",)),
    "EGR_WORDS": (8, ("hclib_tpu.device.egress",)),
    "EC_WRITE": (0, ("hclib_tpu.device.egress",)),
    "EC_CONSUMED": (1, ("hclib_tpu.device.egress",)),
    "EC_PARKED": (2, ("hclib_tpu.device.egress",)),
    "EC_PARK_COUNT": (3, ("hclib_tpu.device.egress",)),
    "EC_PARK_HEAD": (4, ("hclib_tpu.device.egress",)),
    "EC_INFLIGHT": (5, ("hclib_tpu.device.egress",)),
    # tctl ABI (one 8-word control row per tenant lane, device/tenants):
    # the host pump, the single-device stream poll, the resident-mesh
    # WRR poll, and the numpy reference model all index these words -
    # one drifted cursor slot would silently corrupt every lane.
    "TC_TAIL": (0, ("hclib_tpu.device.tenants",
                    "hclib_tpu.device.inject",
                    "hclib_tpu.device.resident")),
    "TC_CONSUMED": (1, ("hclib_tpu.device.tenants",
                        "hclib_tpu.device.inject",
                        "hclib_tpu.device.resident")),
    "TC_WEIGHT": (2, ("hclib_tpu.device.tenants",
                      "hclib_tpu.device.inject",
                      "hclib_tpu.device.resident")),
    "TC_PAUSE": (3, ("hclib_tpu.device.tenants",
                     "hclib_tpu.device.inject",
                     "hclib_tpu.device.resident")),
    "TC_EXPIRED": (4, ("hclib_tpu.device.tenants",
                       "hclib_tpu.device.inject",
                       "hclib_tpu.device.resident")),
    "TC_INSTALLED": (5, ("hclib_tpu.device.tenants",
                         "hclib_tpu.device.inject",
                         "hclib_tpu.device.resident")),
    "TC_DROPPED": (6, ("hclib_tpu.device.tenants",
                       "hclib_tpu.device.inject",
                       "hclib_tpu.device.resident")),
    # tstats ABI (host-side cumulative counters serialized per tenant
    # into checkpoint bundles).
    "TS_ACCEPTED": (0, ("hclib_tpu.device.tenants",)),
    "TS_REJECTED": (1, ("hclib_tpu.device.tenants",)),
    "TS_EXPIRED_HOST": (2, ("hclib_tpu.device.tenants",)),
    "TS_POISONED": (3, ("hclib_tpu.device.tenants",)),
    "TS_DROPPED": (4, ("hclib_tpu.device.tenants",)),
    "TS_THROTTLED": (5, ("hclib_tpu.device.tenants",)),
    "TS_QUARANTINED": (6, ("hclib_tpu.device.tenants",)),
    # batch-tier counter/state rows (device/megakernel.py)
    "TS_WORDS": (12, ("hclib_tpu.device.megakernel",)),
    "LS_WORDS": (8, ("hclib_tpu.device.megakernel",)),
    "LS_AGE": (5, ("hclib_tpu.device.megakernel",)),
    # priority-bucket tier words (ISSUE 15): the static bucket-ring
    # cap and the two tstats counters the bucketed scheduler writes.
    # The bucket id itself rides the DESCRIPTOR's own arg words
    # (BatchSpec.priority is a pure function of them - see the routing
    # site in megakernel.py), so there is no bucket transport word to
    # pin: residue re-buckets on resume/reshard by construction.
    "BK_MAX": (8, ("hclib_tpu.device.megakernel",)),
    "TS_BUCKET_FIRES": (10, ("hclib_tpu.device.megakernel",)),
    "TS_INVERSIONS": (11, ("hclib_tpu.device.megakernel",)),
    "QC_FLAG": (0, ("hclib_tpu.device.megakernel",)),
    "QC_AFTER": (1, ("hclib_tpu.device.megakernel",)),
    "C_EXECUTED": (5, ("hclib_tpu.device.megakernel",)),
    "C_ROUNDS": (7, ("hclib_tpu.device.megakernel",)),
    # live-telemetry word ABI (device/telemetry.py, ISSUE 19): the
    # per-row stamp table (tlat), the gauge row (TG_*), and the
    # histogram width the kernel fold, the host wrapper, and the
    # reconciliation tests all index.
    "LAT_ADMIT": (0, ("hclib_tpu.device.telemetry",)),
    "LAT_INSTALL": (1, ("hclib_tpu.device.telemetry",)),
    "LAT_FIRE": (2, ("hclib_tpu.device.telemetry",)),
    "LAT_WORDS": (4, ("hclib_tpu.device.telemetry",)),
    "LAT_BUCKETS": (16, ("hclib_tpu.device.telemetry",)),
    "TG_ROUNDS": (0, ("hclib_tpu.device.telemetry",)),
    "TG_INSTALLS": (1, ("hclib_tpu.device.telemetry",)),
    "TG_RETIRES": (2, ("hclib_tpu.device.telemetry",)),
    "TG_PARKED": (3, ("hclib_tpu.device.telemetry",)),
    "TG_BACKLOG": (4, ("hclib_tpu.device.telemetry",)),
    "TG_ENTRIES": (5, ("hclib_tpu.device.telemetry",)),
    "TG_WORDS": (8, ("hclib_tpu.device.telemetry",)),
    # dynamic-graph service ABI (device/dyngraph.py, ISSUE 20): the
    # UPDATE/QUERY kernel-table positions (EXPAND keeps frontier.py's
    # FR_EXPAND=0) and the counter value slots the splice ledger bumps -
    # the reshard merge, the serving pump, and the conservation asserts
    # all index these words. The per-vertex spare-region layout itself
    # is a pure function stamped per build (mk._dyngraph) and checked
    # structurally by races.check_splice, not a process constant.
    "DG_UPDATE": (1, ("hclib_tpu.device.dyngraph",)),
    "DG_QUERY": (2, ("hclib_tpu.device.dyngraph",)),
    "V_UPDATES": (2, ("hclib_tpu.device.dyngraph",)),
    "V_FREE": (3, ("hclib_tpu.device.dyngraph",)),
    "V_DROPPED": (4, ("hclib_tpu.device.dyngraph",)),
    "V_QUERIES": (5, ("hclib_tpu.device.dyngraph",)),
    "TR_SPLICE": (21, ("hclib_tpu.device.tracebuf",)),
}

# checkpoint.py's export key sets: resharding and restore key on these
# literal names riding the bundle npz.
_CKPT_STATE_KEYS = ("tasks", "succ", "ready", "counts", "ivalues")
_CKPT_OPT_KEYS = (
    "ring_rows", "waits", "ictl", "tctl", "tstats", "etok",
    "tele", "tlat",
)

_cache: Optional[AnalysisReport] = None


def check_layout(report: Optional[AnalysisReport] = None,
                 suppress: Sequence[str] = (),
                 force: bool = False) -> AnalysisReport:
    """Cross-check LAYOUT against the live modules (memoized: the
    constants cannot change within a process, so every megakernel
    construction after the first reuses the verdict)."""
    global _cache
    if _cache is not None and not force and report is None and not suppress:
        return _cache
    import importlib

    report = report or AnalysisReport(suppress)
    rows: List[Tuple[str, str, int, int]] = []
    for word, (expected, modules) in LAYOUT.items():
        for modname in modules:
            mod = importlib.import_module(modname)
            actual = getattr(mod, word, None)
            if actual != expected:
                rows.append((word, modname, expected, actual))
    for word, modname, expected, actual in rows:
        report.add(
            "layout", ERROR, None,
            f"layout word {word} disagrees: table says {expected}, "
            f"{modname} has {actual}",
            word=word, module=modname, expected=expected, actual=actual,
        )
    # Structural invariants that no single constant captures.
    from ..device import descriptor as d
    from ..device import megakernel as m

    if not (d.DESC_WORDS <= d.TEN_ID < d.TEN_EXPIRED
            < d.TEN_DEADLINE_MS < d.TEN_TOKEN
            < d.TEN_ADMIT_ROUND < d.RING_ROW):
        report.add(
            "layout", ERROR, None,
            "ring-row transport words must sit beyond the descriptor "
            f"ABI and inside the padded row: DESC_WORDS={d.DESC_WORDS} "
            f"<= TEN_ID={d.TEN_ID} < TEN_EXPIRED={d.TEN_EXPIRED} < "
            f"TEN_DEADLINE_MS={d.TEN_DEADLINE_MS} < "
            f"TEN_TOKEN={d.TEN_TOKEN} < "
            f"TEN_ADMIT_ROUND={d.TEN_ADMIT_ROUND} < "
            f"RING_ROW={d.RING_ROW} violated",
            word="TEN_ID",
        )
    from ..device import egress as e

    if not (e.EGR_STATUS < e.EGR_TOKEN < e.EGR_TEN < e.EGR_FN
            < e.EGR_SLOT < e.EGR_VALUE < e.EGR_T_ADMIT
            < e.EGR_T_SPANS < e.EGR_WORDS
            and 0 <= e.EC_WRITE < e.EC_CONSUMED < e.EC_PARKED
            < e.EC_PARK_COUNT < e.EC_PARK_HEAD < e.EC_INFLIGHT < 8):
        report.add(
            "layout", ERROR, None,
            "completion-mailbox words violate the transport-word "
            f"ordering invariant: EGR {e.EGR_STATUS},{e.EGR_TOKEN},"
            f"{e.EGR_TEN},{e.EGR_FN},{e.EGR_SLOT},{e.EGR_VALUE},"
            f"{e.EGR_T_ADMIT},{e.EGR_T_SPANS} must "
            f"ascend below EGR_WORDS={e.EGR_WORDS} and the EC cursor "
            "words must ascend inside the 8-word ectl row",
            word="EGR_STATUS",
        )
    from ..device import telemetry as t

    if not (0 <= t.LAT_ADMIT < t.LAT_INSTALL < t.LAT_FIRE < t.LAT_WORDS
            and t.TG_ROUNDS < t.TG_INSTALLS < t.TG_RETIRES
            < t.TG_PARKED < t.TG_BACKLOG < t.TG_ENTRIES
            < t.TG_WORDS <= t.LAT_BUCKETS):
        report.add(
            "layout", ERROR, None,
            "telemetry words violate the ordering invariant: the LAT "
            f"stamps ({t.LAT_ADMIT},{t.LAT_INSTALL},{t.LAT_FIRE}) must "
            f"ascend below LAT_WORDS={t.LAT_WORDS}, and the TG gauge "
            f"words must ascend below TG_WORDS={t.TG_WORDS} which must "
            f"fit the LAT_BUCKETS={t.LAT_BUCKETS}-wide gauge row",
            word="LAT_ADMIT",
        )
    if not (m.LS_AGE < m.LS_WORDS
            and m.TS_MAX_AGE < m.TS_BUCKET_FIRES
            < m.TS_INVERSIONS < m.TS_WORDS):
        report.add(
            "layout", ERROR, None,
            "lane/tier state words exceed their declared row widths "
            "(or the bucket-tier counters overlap the age words)",
            word="LS_WORDS",
        )
    from ..device import dyngraph as dg
    from ..device import frontier as fr

    if not (fr.V_EDGES < fr.V_RELAX < dg.V_UPDATES < dg.V_FREE
            < dg.V_DROPPED < dg.V_QUERIES < fr.VT_BASE
            and fr.FR_EXPAND < dg.DG_UPDATE < dg.DG_QUERY):
        report.add(
            "layout", ERROR, None,
            "dynamic-graph counter slots must ascend between the "
            f"frontier counters and the vertex table (V_EDGES="
            f"{fr.V_EDGES} < V_RELAX={fr.V_RELAX} < V_UPDATES="
            f"{dg.V_UPDATES} < V_FREE={dg.V_FREE} < V_DROPPED="
            f"{dg.V_DROPPED} < V_QUERIES={dg.V_QUERIES} < VT_BASE="
            f"{fr.VT_BASE}), and the service kinds must follow EXPAND "
            f"in the kernel table (FR_EXPAND={fr.FR_EXPAND} < "
            f"DG_UPDATE={dg.DG_UPDATE} < DG_QUERY={dg.DG_QUERY})",
            word="V_UPDATES",
        )
    from ..runtime import checkpoint as c

    if tuple(c._STATE_KEYS) != _CKPT_STATE_KEYS:
        report.add(
            "layout", ERROR, None,
            f"checkpoint state keys drifted: {c._STATE_KEYS} != "
            f"{_CKPT_STATE_KEYS}",
            word="_STATE_KEYS", actual=tuple(c._STATE_KEYS),
        )
    if tuple(c._OPT_KEYS) != _CKPT_OPT_KEYS:
        report.add(
            "layout", ERROR, None,
            f"checkpoint optional keys drifted: {c._OPT_KEYS} != "
            f"{_CKPT_OPT_KEYS}",
            word="_OPT_KEYS", actual=tuple(c._OPT_KEYS),
        )
    if report.findings == [] and not suppress:
        _cache = report
    return report
