"""Vectorized Smith-Waterman: batched row-sweep alignment on the VPU.

The megakernel SW (device/smithwaterman.py) demonstrates wavefront *DDF
scheduling* - tiles as tasks gated on neighbor promises, the reference
workload's structure (test/smithwaterman/smith_waterman.cpp:77-180). A
single scheduler core executes tiles one at a time, so it is latency-bound.
This module is the *throughput* engine, designed for how the hardware wants
to compute SW:

- One alignment sweeps the DP matrix row by row (`lax.scan`); the in-row
  horizontal-gap dependency h[j] = max(t[j], h[j-1]-1) is solved in log
  depth with the decay-cummax identity

      h[j] = max_{j' <= j} (t[j'] - (j - j')) = cummax(t + j)[j] - j

  (an `associative_scan` of `maximum` - exact for the linear gap penalty
  GAP=1 used by the reference workload's scoring).
- Throughput comes from **batching**: `vmap` over B independent pairs makes
  every row step a (B, m) plane op, which is the standard bioinformatics
  shape (score one query against a database) and the shape the VPU wants.

Exact versus the sequential reference DP (models/smithwaterman.py sw_seq)
for the MATCH=2 / MISMATCH=-1 / GAP=1 scheme.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..models.smithwaterman import GAP, MATCH, MISMATCH
from ..ops.scan import decay_cummax

__all__ = ["sw_scores", "sw_score_one"]

assert GAP == 1, "decay-cummax form assumes unit linear gap"


def _sw_one(a, b):
    """Best local-alignment score for one pair; rows of `a` scanned, `b` is
    the in-register row dimension."""
    m = b.shape[0]
    jidx = jnp.arange(m, dtype=jnp.int32)

    def row(prev, ai):
        s = jnp.where(b == ai, MATCH, MISMATCH).astype(jnp.int32)
        diag = jnp.concatenate([jnp.zeros(1, jnp.int32), prev[:-1]])
        t = jnp.maximum(jnp.maximum(diag + s, prev - GAP), 0)
        c = decay_cummax(t)
        return c, jnp.max(c)

    prev0 = jnp.zeros(m, jnp.int32)
    _, row_best = jax.lax.scan(row, prev0, a)
    return jnp.max(row_best)


@jax.jit
def sw_scores(a_batch, b_batch):
    """Scores for B pairs: a_batch (B, n) vs b_batch (B, m) -> (B,) i32."""
    return jax.vmap(_sw_one)(
        jnp.asarray(a_batch, jnp.int32), jnp.asarray(b_batch, jnp.int32)
    )


def sw_score_one(a: np.ndarray, b: np.ndarray) -> int:
    return int(sw_scores(np.asarray(a)[None], np.asarray(b)[None])[0])
