"""Branch-and-bound search on the priority-bucket dispatch tier.

ISSUE 15's third workload - the one where priority IS the speedup. The
frontier traversals use buckets to do the *same* fixpoint with less
re-relaxation; branch-and-bound is different: the set of nodes a run
must EXPAND depends on how early a good incumbent is found, so
best-first retirement (highest optimistic bound first) prunes
subtrees an unordered run would fully explore. The proven optimum is
schedule-independent either way - any completed leaf only ever RAISES
the incumbent (a monotone max in one SMEM value slot), and a node is
pruned only when its bound cannot beat the incumbent, which can never
cut off the optimal leaf's prefix - so "bit-identical optimum, fewer
executed nodes" is the whole acceptance story (certified by
analysis/model.py over permuted pop orders including the best-first
one; the pruning COUNTS legitimately differ per schedule and are
reported, not certified).

The concrete problem is 0/1 knapsack over a seeded item set
(``make_knapsack``): small enough that the exact optimum has an
independent host witness (the classic DP, ``host_knapsack_opt``), rich
enough that bound-ordered exploration prunes hard. One descriptor kind:

    ``NODE(level, value, weight, bound)``

``level`` items are decided; ``value``/``weight`` are the committed
totals; ``bound = value + suffix_value[level]`` is the optimistic
completion (take everything remaining) computed AT SPAWN - so the
priority rides the descriptor's own arg word 3 (the ISSUE 15 bucket
discipline: residue re-buckets on resume/reshard because the bucket is
a pure function of descriptor words). A popped node re-checks its bound
against the CURRENT incumbent (it was spawned against an older one),
prunes or branches take/skip, and leaves fold into the incumbent.

Best-first priority: bucket 0 = highest bound, so
``bucket = ((total - bound) * B) // (total + 1)`` - a pure arg-word
function, the same shape as the frontier's ``dist // delta``.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
from jax.experimental import pallas as pl

from .descriptor import TaskGraphBuilder
from .megakernel import BatchSpec, Megakernel, _batch_stub

__all__ = [
    "BB_NODE",
    "V_BEST",
    "V_PRUNED",
    "V_LEAVES",
    "make_knapsack",
    "host_knapsack_opt",
    "host_bnb",
    "bnb_bucket",
    "make_bnb_megakernel",
    "run_bnb",
]

# The one kernel-table entry (single-kind family, like the frontier).
BB_NODE = 0

# Value-slot layout: three counters, then the host-preset tables.
V_BEST = 0    # incumbent (monotone max; the PROVEN optimum at drain)
V_PRUNED = 1  # nodes cut by the bound test (schedule-dependent count)
V_LEAVES = 2  # complete assignments folded into the incumbent
BB_TAB = 8    # suffix-value sums [n+1], then values [n], then weights [n]


class Knapsack:
    """One seeded 0/1-knapsack instance: int item values/weights, a
    weight capacity, and the suffix-value table the bound uses."""

    def __init__(self, values, weights, cap: int) -> None:
        self.values = np.asarray(values, np.int64)
        self.weights = np.asarray(weights, np.int64)
        if self.values.shape != self.weights.shape or self.values.ndim != 1:
            raise ValueError("values/weights must be equal-length 1D")
        if len(self.values) and (
            self.values.min() < 0 or self.weights.min() <= 0
        ):
            raise ValueError("values must be >= 0 and weights > 0")
        self.n = int(len(self.values))
        self.cap = int(cap)
        # suffix[k] = total value of items k.. (suffix[n] = 0): the
        # optimistic take-everything completion bound.
        self.suffix = np.zeros(self.n + 1, np.int64)
        self.suffix[:-1] = np.cumsum(self.values[::-1])[::-1]
        self.total = int(self.suffix[0])

    @property
    def num_value_slots(self) -> int:
        return BB_TAB + (self.n + 1) + 2 * self.n

    def preset_values(self, num_values: int) -> np.ndarray:
        if num_values < self.num_value_slots:
            raise ValueError(
                f"knapsack wants num_values >= {self.num_value_slots}, "
                f"got {num_values}"
            )
        iv = np.zeros(num_values, np.int32)
        iv[BB_TAB : BB_TAB + self.n + 1] = self.suffix
        v0 = BB_TAB + self.n + 1
        iv[v0 : v0 + self.n] = self.values
        iv[v0 + self.n : v0 + 2 * self.n] = self.weights
        return iv


def make_knapsack(n: int, seed: int = 0,
                  cap_frac: float = 0.5) -> Knapsack:
    """Seeded instance: values 1..100, weights 1..50 (independent, so
    value density varies and greedy order is wrong often enough that
    the bound test has real work), capacity = cap_frac of the total
    weight. Pure function of the arguments - every arm rebuilds the
    identical instance."""
    if n < 1:
        raise ValueError(f"knapsack n must be >= 1, got {n}")
    rng = np.random.default_rng(seed)
    values = rng.integers(1, 101, n)
    weights = rng.integers(1, 51, n)
    return Knapsack(values, weights, int(cap_frac * weights.sum()))


def host_knapsack_opt(kp: Knapsack) -> int:
    """The exact optimum by the classic weight-indexed DP - an
    INDEPENDENT witness (no bounds, no search order) the device
    incumbent must equal bit-for-bit."""
    dp = np.zeros(kp.cap + 1, np.int64)
    for v, w in zip(kp.values, kp.weights):
        w = int(w)
        if w <= kp.cap:
            dp[w:] = np.maximum(dp[w:], dp[:-w or None] + v)
    return int(dp.max())


def bnb_bucket(kp: Knapsack, bound: int, buckets: int) -> int:
    """HOST spelling of the best-first priority (the device twin lives
    in make_bnb_megakernel - keep in lockstep; analysis/model.py
    certifies the best-first pop order through this one): bucket 0 =
    highest optimistic bound, spread linearly over [0, total]."""
    return ((kp.total - int(bound)) * int(buckets)) // (kp.total + 1)


def host_bnb(kp: Knapsack, best_first: bool = False) -> Dict[str, int]:
    """Host worklist model of the device search (same bound, same
    branch rule): returns {best, executed, pruned, leaves}. The
    ``best_first`` arm pops max-bound-first - the model of the bucketed
    device run; FIFO otherwise. Both return the identical ``best``
    (the schedule-independence claim); executed/pruned differ."""
    import heapq
    from collections import deque

    best, executed, pruned, leaves = 0, 0, 0, 0
    if best_first:
        wl = [(-kp.total, 0, 0, 0, kp.total)]
    else:
        wl = deque([(0, 0, 0, 0, kp.total)])
    while wl:
        if best_first:
            _, level, value, weight, bound = heapq.heappop(wl)
        else:
            _, level, value, weight, bound = wl.popleft()
        executed += 1
        if bound <= best:
            pruned += 1
            continue
        if level == kp.n:
            leaves += 1
            best = max(best, value)
            continue
        sfx = int(kp.suffix[level + 1])
        v_i, w_i = int(kp.values[level]), int(kp.weights[level])
        children = [(level + 1, value, weight, value + sfx)]
        if weight + w_i <= kp.cap:
            children.append(
                (level + 1, value + v_i, weight + w_i,
                 value + v_i + sfx)
            )
        for c in children:
            if best_first:
                heapq.heappush(wl, (-c[3],) + c)
            else:
                wl.append((0,) + c)
    return {
        "best": best, "executed": executed, "pruned": pruned,
        "leaves": leaves,
    }


# ----------------------------------------------------------- device tier


def _node_kernel(kp: Knapsack):
    """The per-node scalar body (both dispatch spellings run it: scalar
    via the switch table, batched per-slot via slot_ctx)."""
    import jax.numpy as jnp

    n = kp.n
    cap = kp.cap
    v0 = BB_TAB + n + 1

    def body(ctx) -> None:
        level = ctx.arg(0)
        value = ctx.arg(1)
        weight = ctx.arg(2)
        bound = ctx.arg(3)
        best = ctx.value(V_BEST)
        live = bound > best

        @pl.when(jnp.logical_not(live))
        def _():
            ctx.set_value(V_PRUNED, ctx.value(V_PRUNED) + 1)

        @pl.when(live & (level == jnp.int32(n)))
        def _():
            # Complete assignment: fold into the incumbent (monotone
            # max - the write every schedule agrees on at the fixpoint).
            ctx.set_value(V_BEST, jnp.maximum(best, value))
            ctx.set_value(V_LEAVES, ctx.value(V_LEAVES) + 1)

        @pl.when(live & (level < jnp.int32(n)))
        def _():
            sfx = ctx.value(BB_TAB + 1 + level)  # suffix[level + 1]
            v_i = ctx.value(v0 + level)
            w_i = ctx.value(v0 + n + level)
            # Skip child: always feasible; bound tightens by v_i.
            ctx.spawn(
                BB_NODE, [level + 1, value, weight, value + sfx],
                nargs=4,
            )

            @pl.when(weight + w_i <= jnp.int32(cap))
            def _():
                ctx.spawn(
                    BB_NODE,
                    [level + 1, value + v_i, weight + w_i,
                     value + v_i + sfx],
                    nargs=4,
                )

    return body


def make_bnb_megakernel(
    kp: Knapsack,
    *,
    width: int = 4,
    priority_buckets: Optional[int] = None,
    capacity: int = 1024,
    num_values: Optional[int] = None,
    interpret: Optional[bool] = None,
    trace=None,
    lane_max_age: Optional[int] = None,
) -> Megakernel:
    """Build the search megakernel. ``width=0`` is scalar dispatch;
    ``width>0`` batches NODE expansion per-slot (the spawn-heavy
    slot_ctx spelling, like fib); ``priority_buckets=B`` additionally
    arms best-first retirement - bucket 0 = highest bound, and the
    age-fire guard (default 4*width, the frontier discipline) keeps
    low-bound buckets from starving outright."""
    import jax.numpy as jnp

    if num_values is None:
        num_values = kp.num_value_slots + 8
    if priority_buckets is None:
        # Process-wide spelling (the builder needs the resolved value
        # for the priority fn and the age-default scale).
        from ..runtime.env import env_int

        priority_buckets = env_int("HCLIB_TPU_PRIORITY_BUCKETS", None)
    priority_buckets = int(priority_buckets or 0)
    body = _node_kernel(kp)
    if width:
        def batch_body(ctx) -> None:
            for s in range(ctx.width):
                @pl.when(ctx.live(s))
                def _(s=s):
                    body(ctx.slot_ctx(s))

        total = kp.total
        nbk = int(priority_buckets or 0)
        spec = BatchSpec(
            batch_body,
            width=width,
            # Device twin of bnb_bucket (kept in lockstep): highest
            # bound -> bucket 0; a pure function of arg word 3, so
            # residue re-buckets wherever it lands.
            priority=(
                (lambda arg: ((jnp.int32(total) - arg(3))
                              * jnp.int32(nbk)) // jnp.int32(total + 1))
                if nbk else None
            ),
        )
        kernels = [("bnb_node", _batch_stub)]
        route = {"bnb_node": spec}
        if lane_max_age is None:
            from ..runtime.env import env_set

            if env_set("HCLIB_TPU_LANE_MAX_AGE"):
                # The process-wide spelling wins (pass None through so
                # Megakernel resolves + validates it) - the frontier
                # builder's discipline.
                lane_max_age = None
            else:
                # Bucketed: drain-period-scale backstop (the frontier
                # discipline - see make_frontier_megakernel);
                # unbucketed: PR 10's latency tune.
                lane_max_age = 2 * capacity if nbk else 4 * width
    else:
        if priority_buckets:
            raise ValueError(
                "priority_buckets needs the batched arm (width > 0)"
            )
        kernels = [("bnb_node", body)]
        route = None
        lane_max_age = 0 if lane_max_age is None else lane_max_age
    mk = Megakernel(
        kernels=kernels,
        route=route,
        capacity=capacity,
        num_values=num_values,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        lane_max_age=lane_max_age,
        priority_buckets=priority_buckets,
    )
    mk._bnb_instance = kp
    # Schedule-independence claim: the OPTIMUM is order-free (the
    # incumbent is a monotone max and the bound test never cuts the
    # optimal prefix); certify_claim proves it over permuted pop orders
    # including the best-first one (analysis/model.py).
    mk.si_claim = (
        "bnb", tuple(map(int, kp.values)), tuple(map(int, kp.weights)),
        kp.cap, int(priority_buckets or 0),
    )
    return mk


def run_bnb(
    kp: Knapsack,
    *,
    width: int = 4,
    priority_buckets: Optional[int] = None,
    capacity: int = 1024,
    interpret: Optional[bool] = None,
    trace=None,
    fuel: Optional[int] = None,
    mk: Optional[Megakernel] = None,
) -> Tuple[int, Dict]:
    """Run the search to the proven optimum; returns ``(best, info)``
    with ``info['pruned']``/``info['leaves']`` beside the scheduler
    counters (``executed`` is the expanded-node count the priority arm
    shrinks)."""
    if mk is None:
        mk = make_bnb_megakernel(
            kp, width=width, priority_buckets=priority_buckets,
            capacity=capacity, interpret=interpret, trace=trace,
        )
    elif getattr(mk, "_bnb_instance", None) is not kp:
        raise ValueError(
            "prebuilt bnb megakernel is bound to a different knapsack "
            "instance (the tables/bounds are baked into the trace): "
            "build one per instance via make_bnb_megakernel"
        )
    b = TaskGraphBuilder()
    b.reserve_values(kp.num_value_slots)
    b.add(BB_NODE, args=[0, 0, 0, kp.total])
    iv = kp.preset_values(mk.num_values)
    iv_o, _, info = mk.run(
        b, ivalues=iv, fuel=1 << 22 if fuel is None else fuel
    )
    info["pruned"] = int(iv_o[V_PRUNED])
    info["leaves"] = int(iv_o[V_LEAVES])
    return int(iv_o[V_BEST]), info
