"""Sharded megakernel: one resident scheduler per mesh device.

SPMD re-design of the reference's multi-worker runtime: instead of pthreads
stealing from each other's deques, every mesh device runs the single-core
megakernel over its own queue partition under ``shard_map``, and global
results/termination combine with XLA collectives (psum). This is the
"locality graph over the mesh": locale i's deque is device i's task table.

The host partitions the task graph round-robin across devices (each
partition must be internally closed under dependencies, like the reference's
per-locale task placement); optional **bulk-synchronous work stealing**
rebalances load at runtime: each round, every device runs its resident
scheduler for a bounded quantum, then surplus *migratable* ready tasks
(successor-free descriptors whose kernel is whitelisted) exchange over the
ICI ring at hop distances 1, 2, 4, ... (hypercube diffusion: a fully-skewed
load reaches every device in one round), and a ``psum`` over the pending
counters decides termination. This is the reference's work-stealing loop
(src/hclib-deque.c steals, src/hclib-runtime.c:403-421 done-flag join)
re-designed for XLA's SPMD model: instead of thieves CASing a victim's deque
top, surplus diffuses over the ICI ring in bulk steps, and the pthread-join
termination becomes a collective.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jaxcompat import shard_map
from .descriptor import (
    DESC_WORDS,
    F_CSR_N,
    F_DEP,
    F_FN,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
    TaskGraphBuilder,
)
from .megakernel import (
    C_ALLOC,
    C_EXECUTED,
    C_HEAD,
    C_OVERFLOW,
    C_PENDING,
    C_ROUNDS,
    C_TAIL,
    C_VALLOC,
    Megakernel,
    TS_WORDS,
)

__all__ = [
    "ShardedMegakernel",
    "round_robin_partition",
    "partition_builders",
    "abort_words",
]


def abort_words(abort, ndev: int) -> np.ndarray:
    """Normalize a runner's ``abort=`` argument (None / truthy scalar /
    per-device sequence of flags) into the (ndev, 8) int32 abort-word
    array the round-loop kernels re-read from HBM. One definition so the
    length validation applies to every runner."""
    arr = np.zeros((ndev, 8), np.int32)
    if abort is None:
        return arr
    if isinstance(abort, np.ndarray) and abort.ndim == 0:
        abort = bool(abort)  # 0-d array: a scalar flag, not a sequence
    flags = (
        list(abort)
        if isinstance(abort, (list, tuple, np.ndarray))
        else [abort] * ndev
    )
    if len(flags) != ndev:
        raise ValueError(
            f"abort wants {ndev} per-device flags, got {len(flags)}"
        )
    for d, f in enumerate(flags):
        arr[d, 0] = 1 if f else 0
    return arr


def partition_builders(
    mk: Megakernel, ndev: int, builders: Sequence[TaskGraphBuilder]
):
    """Finalize one builder per device into stacked (tasks, succ, ring,
    counts) arrays - shared by every multi-device runner."""
    if len(builders) != ndev:
        raise ValueError(f"need {ndev} partitions, got {len(builders)}")
    cap, scap = mk.capacity, mk.succ_capacity
    parts = [b.finalize(capacity=cap, succ_capacity=scap) for b in builders]
    return (
        np.stack([p[0] for p in parts]),
        np.stack([p[1] for p in parts]),
        np.stack([p[2] for p in parts]),
        np.stack([p[3] for p in parts]),
    )


def execute_partitions(
    mk: Megakernel,
    mesh: Mesh,
    ndev: int,
    jitted,
    builders: Sequence[TaskGraphBuilder],
    data: Optional[Dict[str, np.ndarray]],
    ivalues: Optional[np.ndarray],
    with_rounds: bool,
    mutate=None,
    extra_inputs: Sequence[np.ndarray] = (),
    state=None,
    keep_inputs: bool = False,
):
    """Shared host-side driver for the multi-device runners: partition the
    builders, widen per-device value allocs over presets, validate data
    keys, device_put everything sharded on the mesh axis, invoke, and
    unpack (ivalues, data, info). Raising on overflow/stall is left to the
    caller (the runners word their diagnostics differently).

    ``mutate(tasks, succ, ring, counts)`` lets a runner adjust the
    partitioned arrays in place before upload (e.g. the PGAS runner's
    wait-dependency bumps); ``extra_inputs`` are device_put after the data
    buffers (same leading device axis). ``state`` (a checkpoint snapshot:
    stacked per-device tasks/succ/ready/counts/ivalues) bypasses the
    builder partitioning and preset widening entirely - the arrays are a
    quiesced run's exported state, already consistent. ``keep_inputs``
    surfaces the uploaded input arrays as ``info['inputs']`` (the
    checkpoint path needs the succ CSR, which is input-only)."""
    if state is not None:
        tasks = np.asarray(state["tasks"]).copy()
        succ = np.asarray(state["succ"]).copy()
        ring = np.asarray(state["ready"]).copy()
        counts = np.asarray(state["counts"]).copy()
        ivalues = np.asarray(state["ivalues"]).copy()
    else:
        tasks, succ, ring, counts = partition_builders(mk, ndev, builders)
        if ivalues is None:
            ivalues = np.zeros((ndev, mk.num_values), np.int32)
        else:
            ivalues = np.asarray(ivalues)
            for d in range(ndev):
                mk.widen_value_alloc(counts[d], ivalues[d])
    # Mutate AFTER preset widening: runners that symmetrize or validate
    # the per-device value_alloc (ResidentKernel's symmetric-heap layout
    # and migration result-slot check) must see the final values.
    if mutate is not None:
        mutate(tasks, succ, ring, counts)
    for c in counts:
        mk.check_row_values(int(c[C_VALLOC]))
    data = dict(data or {})
    if set(data.keys()) != set(mk.data_specs.keys()):
        raise ValueError(
            f"data buffers {sorted(data)} != declared {sorted(mk.data_specs)}"
        )
    sh = NamedSharding(mesh, P(tuple(mesh.axis_names)))
    put = lambda x: jax.device_put(np.ascontiguousarray(x), sh)  # noqa: E731
    outs = jitted(
        put(tasks), put(succ), put(ring), put(counts), put(ivalues),
        *[put(data[k]) for k in mk.data_specs.keys()],
        *[put(x) for x in extra_inputs],
    )
    counts_o, iv_o, gcounts = outs[0], outs[1], outs[2]
    nd = len(mk.data_specs)
    data_o = dict(zip(mk.data_specs.keys(), outs[3 : 3 + nd]))
    g = np.asarray(gcounts)[0]  # identical on every row
    info = {
        "executed": int(g[C_EXECUTED]),
        "pending": int(g[C_PENDING]),
        "overflow": bool(g[C_OVERFLOW]),
        "per_device_counts": np.asarray(counts_o),
    }
    # Runner-specific trailing outputs (e.g. the resident kernel's
    # per-device fault/abort stats) ride after the data buffers.
    info["extra_outputs"] = [np.asarray(x) for x in outs[3 + nd :]]
    if keep_inputs:
        info["inputs"] = {"succ": succ}
    if with_rounds:
        info["steal_rounds"] = int(np.asarray(counts_o)[0][C_ROUNDS])
    return np.asarray(iv_o), data_o, info


class ShardedMegakernel:
    """Runs one ``Megakernel`` instance per device of a 1D mesh.

    ``data_specs`` shapes are per-device; the sharded run takes per-device
    data stacked on a leading mesh axis.
    """

    def __init__(
        self,
        mk: Megakernel,
        mesh: Mesh,
        migratable_fns: Iterable[int] = (),
    ) -> None:
        if len(mesh.axis_names) != 1:
            raise ValueError("ShardedMegakernel wants a 1D mesh (queue axis)")
        # Batch-routed kernels ride this runner via the SPILL DISCIPLINE:
        # _build_raw allocates the per-kind lanes, and sched() spills every
        # unrun lane entry back to the ready ring at each kernel exit, so
        # the bulk-synchronous steal/export pass between entries only ever
        # scans ring rows - a lane-resident descriptor can never be
        # invisible to a thief because lanes are empty whenever the
        # exchange runs. The appended tstats output is threaded through
        # both step functions below (accumulated across steal rounds) and
        # decoded into per-device info['tiers'].
        # The trace ring cannot ride this runner: same appended-output
        # problem as tstats (positional out_specs), and the bulk-
        # synchronous steal loop re-enters the kernel per round (each
        # entry resets the ring). The fully-resident runners trace.
        self._suppress_trace = False
        if mk.trace is not None:
            if getattr(mk, "trace_from_env", False):
                # HCLIB_TPU_TRACE is a process-wide opt-in; building this
                # runner untraced beats failing a run the env owner never
                # wrote trace= into. Suppression is LOCAL to this runner's
                # builds - the shared Megakernel keeps its ring for
                # mk.run() / the resident runners.
                import logging

                logging.getLogger("hclib_tpu.device").warning(
                    "ShardedMegakernel cannot trace; ignoring "
                    "HCLIB_TPU_TRACE for this runner's builds"
                )
                self._suppress_trace = True
            else:
                raise ValueError(
                    "ShardedMegakernel does not support the trace ring; "
                    "use ResidentKernel/ICIStealMegakernel tracing or "
                    "build the Megakernel with trace=None"
                )
        # Checkpoint quiesce cannot ride this runner either: the appended
        # qstat output breaks the positional out_specs, and the bulk-
        # synchronous steal loop re-enters the kernel per round with its
        # OWN state threading (quiesce mid-round would race the exchange).
        # Use ResidentKernel(checkpoint) for mesh checkpoints.
        self._suppress_ckpt = False
        if mk.checkpoint:
            if getattr(mk, "checkpoint_from_env", False):
                import logging

                logging.getLogger("hclib_tpu.device").warning(
                    "ShardedMegakernel cannot checkpoint; ignoring "
                    "HCLIB_TPU_CHECKPOINT for this runner's builds"
                )
                self._suppress_ckpt = True
            else:
                raise ValueError(
                    "ShardedMegakernel does not support checkpoint "
                    "quiesce; use ResidentKernel for mesh checkpoint/"
                    "restore or build the Megakernel with checkpoint=False"
                )
        self.mk = mk
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.ndev = int(np.prod(mesh.devices.shape))
        # Kernel-table ids whose tasks may migrate between devices. A
        # migratable kernel must be location-independent: it may only read
        # its args and write accumulate-style value slots (the host combines
        # per-device ivalues), like forasync tiles or UTS node counters.
        self.migratable_fns = frozenset(int(f) for f in migratable_fns)
        # The claim itself must index the kernel table (the exchange
        # whitelist is a per-kind mask): an out-of-range id would
        # silently never migrate, so refuse unconditionally - the check
        # is a cheap scan, no reason to gate it on the verifier flag.
        # Kind-LEVEL classification is deliberately NOT enforced here:
        # the exchange carries its own row-level link filter, so
        # claiming a home-linked kind (fib forests) legally moves just
        # its link-free rows; the classification rides
        # Megakernel.describe() and the checkpoint bundles for
        # reshard's upfront diagnostics.
        bad = [f for f in self.migratable_fns
               if not 0 <= f < len(mk.kernel_names)]
        if bad:
            raise ValueError(
                f"migratable_fns {sorted(bad)} outside the kernel "
                f"table (0..{len(mk.kernel_names) - 1})"
            )
        self._jitted: Dict[Any, Any] = {}
        self._pc_stats: Optional[Dict[str, Any]] = None

    @contextlib.contextmanager
    def _maybe_untraced(self):
        """Build-time trace/checkpoint suppression for env-derived
        enablement: restores mk state afterwards so other runners sharing
        the kernel keep the capability."""
        if not (self._suppress_trace or self._suppress_ckpt):
            yield
            return
        saved_trace = self.mk.trace
        saved_ckpt = self.mk.checkpoint
        if self._suppress_trace:
            self.mk.trace = None
        if self._suppress_ckpt:
            self.mk.checkpoint = False
        try:
            yield
        finally:
            self.mk.trace = saved_trace
            self.mk.checkpoint = saved_ckpt

    def _build(self, fuel: int):
        # Single kernel entry per launch: lean value staging suffices (run()
        # widens value_alloc over presets before the call).
        with self._maybe_untraced():
            inner = self.mk._build_raw(fuel)
        ndata = len(self.mk.data_specs)
        nbatch = 1 if self.mk.batch_specs else 0
        axis = self.axis

        def step(tasks, succ, ring, counts, iv, *data):
            outs = inner(
                tasks[0], succ[0], ring[0], counts[0], iv[0], *[d[0] for d in data]
            )
            tasks_o, ready_o, counts_o, iv_o = outs[:4]
            data_o = outs[4 : 4 + ndata]
            # Batched-tier counters ride last (appended by _build_raw when
            # any kind is batch-routed): surfaced per device.
            tstats_o = outs[4 + ndata :]
            # Global termination/health: executed/pending/overflow summed
            # across the mesh (the reference's done-flag join becomes a
            # collective - src/hclib-runtime.c:403-421).
            gcounts = jax.lax.psum(counts_o, axis)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
                *[t[None] for t in tstats_o],
            )

        nin = 5 + ndata
        f = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axis),) * nin,
            out_specs=(P(self.axis),) * (3 + ndata + nbatch),
            check_vma=False,
        )
        return jax.jit(f)

    def _build_steal(
        self, quantum: int, window: int, max_rounds: int,
        hop_order: Optional[Sequence[int]] = None,
    ):
        """Steal-round executor: run-for-quantum, migrate surplus over the
        device ring, repeat until psum(pending) == 0.

        ``hop_order`` overrides the default hypercube hop sequence
        [1, 2, 4, ...] with a caller-supplied scan order - the locality
        hook: ``runtime.locality.steal_hop_order`` derives it from a
        machine graph so each exchange reaches graph-NEAR peers first
        (on a 2x2 ICI ring, hop 2 is the adjacent chip and hop 1 the
        diagonal, so the graph flips the scan to [2, 1]). Any nonempty
        set of distances in [1, ndev) terminates - backlog still
        diffuses every round and the psum decides completion - but
        covering the hypercube set keeps one-round full diffusion."""
        # Full value staging: the round loop re-enters the kernel, and value
        # slots above value_alloc (row-owned blocks, bump allocations) carry
        # live results between entries. Descriptor rows freed in earlier
        # rounds ARE reusable (stage() rebuilds the row free stack from
        # completion tombstones), so capacity tracks the live set; only
        # bump-side alloc_values blocks ratchet across rounds.
        with self._maybe_untraced():
            inner = self.mk._build_raw(quantum, stage_all_values=True)
        ndata = len(self.mk.data_specs)
        nbatch = 1 if self.mk.batch_specs else 0
        axis = self.axis
        ndev = self.ndev
        cap = self.mk.capacity
        K = window
        wl_host = np.zeros(max(1, len(self.mk.kernel_fns)), bool)
        for f in self.migratable_fns:
            wl_host[f] = True
        # Hypercube diffusion: each round exchanges at hop distances 1, 2,
        # 4, ... so a fully-skewed load reaches every device in ONE round
        # (log2(ndev) ppermutes) instead of diffusing one neighbor per
        # round - the SPMD rendering of the reference thief scanning ALL
        # victims along its steal path (src/hclib-locality-graph.c:843-888),
        # rather than only the adjacent one.
        if hop_order is None:
            hop_dists = [d for d in (1 << k for k in range(16)) if d < ndev]
        else:
            hop_dists = [int(d) for d in hop_order]
            if not hop_dists or any(
                not 1 <= d < ndev for d in hop_dists
            ):
                raise ValueError(
                    f"hop_order must be nonempty distances in "
                    f"[1, {ndev}), got {hop_dists}"
                )

        def step(tasks, succ, ring, counts, iv, *data):
            succ0 = succ[0]
            wl = jnp.asarray(wl_host)
            j = jnp.arange(K)

            def exchange(tasks, ring_, counts, perm):
                # ---- export: eligible tasks from the head-side window,
                # oldest first (the Chase-Lev thief steals from the top;
                # here the "thief" is the ring neighbor). Eligible
                # candidates are COMPACTED across the whole scanned window
                # - a non-migratable task at the head does not block the
                # ones behind it; the survivors are compacted back toward
                # the head so the ring stays dense.
                head, tail = counts[C_HEAD], counts[C_TAIL]
                backlog = tail - head
                gavg = jax.lax.psum(backlog, axis) // ndev
                quota = jnp.clip(backlog - gavg, 0, K)
                scanned = j < jnp.minimum(backlog, K)
                ring_idx = (head + j) % cap
                cand = ring_[ring_idx]
                desc = tasks[jnp.clip(cand, 0, cap - 1)]
                elig = (
                    scanned
                    & (cand >= 0)
                    & wl[jnp.clip(desc[:, F_FN], 0, wl.shape[0] - 1)]
                    & (desc[:, F_SUCC0] == NO_TASK)
                    & (desc[:, F_SUCC1] == NO_TASK)
                    & (desc[:, F_CSR_N] == 0)
                )
                rank_e = jnp.cumsum(elig.astype(jnp.int32)) - 1
                send = elig & (rank_e < quota)
                nsend = jnp.sum(send.astype(jnp.int32))
                # Gather exported descriptors densely into sendbuf[0:nsend]
                # (OOB scatter lanes drop the non-send rows).
                sendbuf = (
                    jnp.zeros((K, DESC_WORDS), jnp.int32)
                    .at[jnp.where(send, rank_e, K)]
                    .set(desc)
                )
                # Compact the scanned-but-kept entries to the new head so
                # no live slot is skipped when head advances.
                keep = scanned & jnp.logical_not(send)
                rank_k = jnp.cumsum(keep.astype(jnp.int32)) - 1
                ring_ = ring_.at[
                    jnp.where(keep, (head + nsend + rank_k) % cap, cap)
                ].set(cand, mode="drop")
                # Tombstone the exported rows (F_DEP=-1): the task now lives
                # on the neighbor, so the victim's row is dead and stage()
                # can hand it to future spawns/imports. Unmasked lanes point
                # out of bounds - scatter drops OOB updates, so there are
                # no duplicate-index write races.
                tasks = tasks.at[jnp.where(send, cand, cap), F_DEP].set(-1)
                counts = counts.at[C_HEAD].add(nsend).at[C_PENDING].add(-nsend)
                # ---- exchange over the ICI ring at this hop distance.
                recvbuf = jax.lax.ppermute(sendbuf, axis, perm)
                nrecv = jax.lax.ppermute(
                    nsend.reshape(1), axis, perm
                )[0]
                # ---- import: reuse tombstoned (freed/exported) rows first,
                # then fresh rows from the bump cursor - so steal-heavy runs
                # only need capacity for the LIVE set, not cumulative
                # imports.
                alloc, tail = counts[C_ALLOC], counts[C_TAIL]
                tomb = (tasks[:, F_DEP] == -1) & (
                    jnp.arange(cap) < alloc
                )
                # First (at most) K tombstoned row indices, ascending; the
                # cap fill value is only reachable on lanes j >= nre, which
                # take the fresh-row branch below.
                (reuse,) = jnp.nonzero(tomb, size=K, fill_value=cap)
                ntomb = jnp.sum(tomb.astype(jnp.int32))
                can = jnp.minimum(nrecv, ntomb + (cap - alloc))
                nre = jnp.minimum(can, ntomb)
                take = j < can
                rows = jnp.where(j < nre, reuse[j], alloc + j - nre)
                # OOB indices on untaken lanes: scatter drops them, avoiding
                # duplicate-index races with the taken lanes' writes.
                tasks = tasks.at[jnp.where(take, rows, cap)].set(recvbuf)
                slot = jnp.where(take, (tail + j) % cap, cap)
                ring_ = ring_.at[slot].set(rows)
                counts = (
                    counts.at[C_ALLOC].add(can - nre)
                    .at[C_TAIL].add(can)
                    .at[C_PENDING].add(can)
                    .at[C_OVERFLOW].max(
                        jnp.where(nrecv > can, 1, 0).astype(jnp.int32)
                    )
                )
                return tasks, ring_, counts

            def cond(carry):
                tasks, ring_, counts, iv, data, tacc, rounds = carry
                return (jax.lax.psum(counts[C_PENDING], axis) > 0) & (
                    rounds < max_rounds
                )

            def body(carry):
                tasks, ring_, counts, iv, data, tacc, rounds = carry
                outs = inner(tasks, succ0, ring_, counts, iv, *data)
                tasks, ring_, counts, iv = outs[:4]
                data = tuple(outs[4 : 4 + ndata])
                if nbatch:
                    # tstats resets at every kernel entry (per-entry
                    # scratch semantics), so the steal loop accumulates
                    # the rounds' counters into a cumulative per-device
                    # row - occupancy over the whole run, not the last
                    # quantum.
                    tacc = tacc + outs[4 + ndata]
                for d in hop_dists:
                    perm = [(i, (i + d) % ndev) for i in range(ndev)]
                    tasks, ring_, counts = exchange(tasks, ring_, counts, perm)
                return (tasks, ring_, counts, iv, data, tacc, rounds + 1)

            init = (
                tasks[0], ring[0], counts[0], iv[0], tuple(d[0] for d in data),
                jnp.zeros((TS_WORDS,), jnp.int32),
                jnp.int32(0),
            )
            tasks_o, ring_o, counts_o, iv_o, data_o, tacc_o, rounds = (
                jax.lax.while_loop(cond, body, init)
            )
            counts_o = counts_o.at[C_ROUNDS].set(rounds)
            gcounts = jax.lax.psum(counts_o, axis)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
                *([tacc_o[None]] if nbatch else []),
            )

        nin = 5 + ndata
        f = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axis),) * nin,
            out_specs=(P(self.axis),) * (3 + ndata + nbatch),
            check_vma=False,
        )
        return jax.jit(f)

    def partition(self, builders: Sequence[TaskGraphBuilder]):
        """Finalize one builder per device into stacked arrays."""
        return partition_builders(self.mk, self.ndev, builders)

    def run(
        self,
        builders: Sequence[TaskGraphBuilder],
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        fuel: int = 1 << 22,
        steal: bool = False,
        quantum: int = 256,
        window: int = 32,
        max_rounds: int = 1 << 16,
        hop_order: Optional[Sequence[int]] = None,
    ):
        """Execute all partitions; returns (ivalues[ndev, V], data, info).

        ``steal=True`` enables bulk-synchronous work stealing: devices run
        ``quantum`` tasks per round, then up to ``window`` surplus migratable
        ready tasks hop one device along the ring between rounds.
        ``hop_order`` reorders the exchange's hop-distance scan (see
        ``_build_steal``; ``runtime.locality.steal_hop_order`` derives a
        near-neighbors-first order from a machine graph)."""
        # fuel is unused on the steal path (each round runs `quantum`), so
        # keep it out of that cache key - varying fuel must not recompile.
        hops = tuple(hop_order) if hop_order is not None else None
        key = (
            (True, quantum, window, max_rounds, hops)
            if steal else (False, fuel)
        )
        first_build = key not in self._jitted
        if first_build:
            # Content-keyed program cache (runtime/progcache.py): the
            # variant names every static fact this runner compiles in
            # beyond the Megakernel's own content - mesh shape/devices,
            # migration whitelist, the env-suppression flags (a
            # suppressed-trace build is a DIFFERENT program than the
            # same mk built by a resident runner), and the steal
            # parameters.
            from ..runtime.progcache import mesh_key, shared_build

            self._jitted[key], self._pc_stats = shared_build(
                self.mk,
                ("sharded", mesh_key(self.mesh),
                 tuple(sorted(self.migratable_fns)),
                 self._suppress_trace, self._suppress_ckpt) + key,
                lambda: (
                    self._build_steal(quantum, window, max_rounds, hops)
                    if steal
                    else self._build(fuel)
                ),
            )
        t0_ns = time.monotonic_ns()
        iv_o, data_o, info = execute_partitions(
            self.mk, self.mesh, self.ndev, self._jitted[key], builders,
            data, ivalues, with_rounds=steal,
        )
        t1_ns = time.monotonic_ns()
        if (
            first_build and self._pc_stats is not None
            and not self._pc_stats["hit"]
        ):
            # jax.jit is lazy: a cache MISS pays trace/lower/compile
            # inside this first entry (the Megakernel._execute
            # discipline), so fold the first wall into build_s before
            # it is reported.
            self._pc_stats["build_s"] += (t1_ns - t0_ns) / 1e9
        if self._pc_stats is not None:
            info["program_cache"] = dict(self._pc_stats)
        tail = info.pop("extra_outputs", None)
        if self.mk.batch_specs and tail:
            # Per-device batched-tier counters (cumulative over the steal
            # rounds on the steal path): info['tiers'][d] mirrors the
            # single-device decode, so mesh occupancy reads the same way.
            trows = tail[-1]
            info["tiers"] = [
                self.mk.decode_tier_stats(trows[d])
                for d in range(self.ndev)
            ]
        if info["overflow"]:
            raise RuntimeError("sharded megakernel task-table overflow")
        if info["pending"] != 0:
            raise RuntimeError(
                f"sharded megakernel stalled with {info['pending']} pending "
                f"tasks after {info['executed']} executed (dependency cycle "
                f"or fuel {fuel} exhausted)"
            )
        return iv_o, data_o, info


def round_robin_partition(
    items: Sequence[Any], ndev: int
) -> List[List[Any]]:
    """Deal independent work items across devices."""
    parts: List[List[Any]] = [[] for _ in range(ndev)]
    for i, it in enumerate(items):
        parts[i % ndev].append(it)
    return parts
