"""Sharded megakernel: one resident scheduler per mesh device.

SPMD re-design of the reference's multi-worker runtime: instead of pthreads
stealing from each other's deques, every mesh device runs the single-core
megakernel over its own queue partition under ``shard_map``, and global
results/termination combine with XLA collectives (psum). This is the
"locality graph over the mesh": locale i's deque is device i's task table.

Work distribution is static in v1 - the host partitions the task graph
round-robin across devices (each partition must be internally closed under
dependencies, like the reference's per-locale task placement). Cross-device
task stealing via Pallas remote DMA and cross-device dependency edges are the
round-2 follow-ons; the partitioned form already covers data-parallel
forasync grids and independent task trees.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .descriptor import DESC_WORDS, TaskGraphBuilder
from .megakernel import C_EXECUTED, C_OVERFLOW, C_PENDING, Megakernel

__all__ = ["ShardedMegakernel"]


class ShardedMegakernel:
    """Runs one ``Megakernel`` instance per device of a 1D mesh.

    ``data_specs`` shapes are per-device; the sharded run takes per-device
    data stacked on a leading mesh axis.
    """

    def __init__(self, mk: Megakernel, mesh: Mesh) -> None:
        if len(mesh.axis_names) != 1:
            raise ValueError("ShardedMegakernel wants a 1D mesh (queue axis)")
        self.mk = mk
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.ndev = int(np.prod(mesh.devices.shape))
        self._jitted: Dict[int, Any] = {}

    def _build(self, fuel: int):
        inner = self.mk._build_raw(fuel)
        ndata = len(self.mk.data_specs)
        axis = self.axis

        def step(tasks, succ, ring, counts, iv, *data):
            outs = inner(
                tasks[0], succ[0], ring[0], counts[0], iv[0], *[d[0] for d in data]
            )
            tasks_o, ready_o, counts_o, iv_o = outs[:4]
            data_o = outs[4:]
            # Global termination/health: executed/pending/overflow summed
            # across the mesh (the reference's done-flag join becomes a
            # collective - src/hclib-runtime.c:403-421).
            gcounts = jax.lax.psum(counts_o, axis)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
            )

        nin = 5 + ndata
        f = jax.shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axis),) * nin,
            out_specs=(P(self.axis),) * (3 + ndata),
            check_vma=False,
        )
        return jax.jit(f)

    def partition(self, builders: Sequence[TaskGraphBuilder]):
        """Finalize one builder per device into stacked arrays."""
        if len(builders) != self.ndev:
            raise ValueError(f"need {self.ndev} partitions, got {len(builders)}")
        cap, scap = self.mk.capacity, self.mk.succ_capacity
        parts = [b.finalize(capacity=cap, succ_capacity=scap) for b in builders]
        tasks = np.stack([p[0] for p in parts])
        succ = np.stack([p[1] for p in parts])
        ring = np.stack([p[2] for p in parts])
        counts = np.stack([p[3] for p in parts])
        return tasks, succ, ring, counts

    def run(
        self,
        builders: Sequence[TaskGraphBuilder],
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        fuel: int = 1 << 22,
    ):
        """Execute all partitions; returns (ivalues[ndev, V], data, info)."""
        tasks, succ, ring, counts = self.partition(builders)
        if ivalues is None:
            ivalues = np.zeros((self.ndev, self.mk.num_values), np.int32)
        data = dict(data or {})
        if set(data.keys()) != set(self.mk.data_specs.keys()):
            raise ValueError(
                f"data buffers {sorted(data)} != declared {sorted(self.mk.data_specs)}"
            )
        if fuel not in self._jitted:
            self._jitted[fuel] = self._build(fuel)
        sh = NamedSharding(self.mesh, P(self.axis))
        put = lambda x: jax.device_put(np.ascontiguousarray(x), sh)  # noqa: E731
        outs = self._jitted[fuel](
            put(tasks),
            put(succ),
            put(ring),
            put(counts),
            put(ivalues),
            *[put(data[k]) for k in self.mk.data_specs.keys()],
        )
        counts_o, iv_o, gcounts = outs[0], outs[1], outs[2]
        data_o = dict(zip(self.mk.data_specs.keys(), outs[3:]))
        g = np.asarray(gcounts)[0]  # identical on every row
        info = {
            "executed": int(g[C_EXECUTED]),
            "pending": int(g[C_PENDING]),
            "overflow": bool(g[C_OVERFLOW]),
            "per_device_counts": np.asarray(counts_o),
        }
        if info["overflow"]:
            raise RuntimeError("sharded megakernel task-table overflow")
        if info["pending"] != 0:
            raise RuntimeError(
                f"sharded megakernel stalled with {info['pending']} pending "
                f"tasks after {info['executed']} executed (dependency cycle "
                f"or fuel {fuel} exhausted)"
            )
        return np.asarray(iv_o), data_o, info


def round_robin_partition(
    items: Sequence[Any], ndev: int
) -> List[List[Any]]:
    """Deal independent work items across devices."""
    parts: List[List[Any]] = [[] for _ in range(ndev)]
    for i, it in enumerate(items):
        parts[i % ndev].append(it)
    return parts
