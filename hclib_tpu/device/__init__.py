"""Device execution: task descriptors + the persistent Pallas megakernel.

The reference's work-stealing loop (pthread workers polling Chase-Lev deques,
src/hclib-runtime.c:705-724) is re-imagined TPU-first: a single long-running
Pallas kernel per core whose scalar unit runs a resident scheduler loop over
an SMEM task table and ready ring, dispatching to a static kernel table
(``lax.switch`` - TPU has no function pointers) whose entries do scalar work
in SMEM or drive the MXU/VPU on HBM/VMEM tiles. Promise satisfaction is a
dep-counter decrement + ready-ring push instead of a waiter-list walk.
"""

from .descriptor import (
    DESC_WORDS,
    F_A0,
    F_CSR_N,
    F_CSR_OFF,
    F_DEP,
    F_FN,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
    TaskGraphBuilder,
)
from .forasync_tier import (
    Slab,
    TileKernel,
    make_forasync_megakernel,
    place_tiles,
    run_forasync_device,
    seed_tiles,
)
from .frontier import (
    Graph,
    host_bfs,
    host_pagerank,
    host_sssp,
    make_frontier_megakernel,
    run_frontier,
)
from .megakernel import BatchContext, BatchSpec, KernelContext, Megakernel
from .resident import ResidentKernel
from .tenants import Admission, TenantSpec, TenantTable
from .tracebuf import TraceRing, decode_ring, trace_to_jsonable

__all__ = [
    "Admission",
    "Graph",
    "host_bfs",
    "host_pagerank",
    "host_sssp",
    "make_frontier_megakernel",
    "run_frontier",
    "Slab",
    "TileKernel",
    "make_forasync_megakernel",
    "place_tiles",
    "run_forasync_device",
    "seed_tiles",
    "TenantSpec",
    "TenantTable",
    "ResidentKernel",
    "TraceRing",
    "decode_ring",
    "trace_to_jsonable",
    "BatchContext",
    "BatchSpec",
    "DESC_WORDS",
    "NO_TASK",
    "TaskGraphBuilder",
    "KernelContext",
    "Megakernel",
    "F_FN",
    "F_DEP",
    "F_SUCC0",
    "F_SUCC1",
    "F_CSR_OFF",
    "F_CSR_N",
    "F_A0",
    "F_OUT",
]
