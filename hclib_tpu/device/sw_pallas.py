"""Fused-Pallas Smith-Waterman: the batched row sweep resident on-core.

sw_vec.py expresses the sweep as a ``lax.scan`` whose (B, m) carry and
~20 plane ops per row round-trip HBM between XLA ops - the same unfused
overhead the UTS engine shed in uts_pallas.py. Here one kernel runs the
whole n-row sweep with the DP row, the running best, and both sequence
blocks VMEM-resident; a grid over batch blocks lets Pallas double-buffer
the next block's sequence data while the current block computes.

Layout is the transpose of sw_vec's: **batch on the lane axis, sequence on
sublanes** ((m, B) planes, sequences passed pre-transposed). That makes
the per-row query symbol an 8-aligned sublane slice + select (Mosaic can
neither vector-load a 1-wide lane slice nor prove unaligned sublane
offsets), the diagonal shift a static sublane concat, and the horizontal
chain a sublane-shifted max cascade - no transposes, no gathers, no MXU.

Same recurrences as sw_vec (shared constants; exact vs the sequential
reference DP models/smithwaterman.py):
- vertical/diagonal: t = max(diag + subst, prev - GAP, 0)
- in-row horizontal chain via the decay-cummax identity
  c[j] = cummax(t + j)[j] - j, computed as log2(m) shifted maxima
  (associative_scan does not lower in Mosaic).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ..models.smithwaterman import GAP, MATCH, MISMATCH

__all__ = ["sw_scores_pallas"]

assert GAP == 1, "decay-cummax form assumes unit linear gap"

_NEG = -(1 << 30)  # plain int: a jnp scalar here would be captured as a
# traced constant, which pallas kernels reject


def _shifted_cummax0(c):
    """cummax along axis 0 (sublanes) as log2(m) static shifted maxima."""
    m = c.shape[0]
    sh = 1
    while sh < m:
        pad = jnp.full((sh, c.shape[1]), _NEG, c.dtype)
        c = jnp.maximum(c, jnp.concatenate([pad, c[:-sh, :]], axis=0))
        sh *= 2
    return c


def _kernel(n: int, a_ref, b_ref, out_ref):
    bs = b_ref[...]  # (m, Bb)
    m, Bb = bs.shape
    iidx = jax.lax.broadcasted_iota(jnp.int32, (m, Bb), 0)
    sel_iota = jax.lax.broadcasted_iota(jnp.int32, (8, Bb), 0)

    def row(i, carry):
        prev, best = carry
        # Query symbol i for every batch lane: 8-aligned sublane slice of
        # the (n, Bb) query block, then an in-register row select.
        base = (i // 8) * 8
        blk = a_ref[pl.ds(base, 8), :]  # (8, Bb)
        ai = jnp.sum(
            jnp.where(sel_iota == (i - base), blk, 0), axis=0, keepdims=True
        )  # (1, Bb)
        s = jnp.where(bs == ai, MATCH, MISMATCH).astype(jnp.int32)
        diag = jnp.concatenate(
            [jnp.zeros((1, Bb), jnp.int32), prev[:-1, :]], axis=0
        )
        t = jnp.maximum(jnp.maximum(diag + s, prev - GAP), 0)
        c = _shifted_cummax0(t + iidx) - iidx
        return c, jnp.maximum(best, c)

    zeros = jnp.zeros((m, Bb), jnp.int32)
    _, best = jax.lax.fori_loop(0, n, row, (zeros, zeros))
    out_ref[...] = jnp.max(best, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def _sw_pallas(a_t, b_t, block_b: int = 512, interpret: bool = False):
    """a_t (n, B) and b_t (m, B) pre-transposed; returns (1, B) scores.
    B must be a whole number of batch blocks (sw_scores_pallas pads)."""
    n, B = a_t.shape
    m = b_t.shape[0]
    if B % block_b:
        raise ValueError(f"B={B} not a multiple of block_b={block_b}")
    grid = (B // block_b,)
    return pl.pallas_call(
        functools.partial(_kernel, n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, block_b), lambda g: (0, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, block_b), lambda g: (0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_b), lambda g: (0, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.int32),
        interpret=interpret,  # bool: the fast XLA-backed interpreter
        # (InterpretParams would select the slow Mosaic one - only
        # remote-DMA/semaphore kernels need that; see megakernel.py)
    )(a_t, b_t)


def sw_scores_pallas(a_batch, b_batch, block_b: int = 512,
                     interpret=None) -> np.ndarray:
    """Scores for B pairs: a_batch (B, n) vs b_batch (B, m) -> (B,) i32.
    B is padded to a whole number of batch blocks and n to a multiple of 8
    (pad symbol -1 matches nothing, so scores are unchanged)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    a = np.asarray(a_batch, np.int32)
    b = np.asarray(b_batch, np.int32)
    B = a.shape[0]
    # Lane-axis blocks must be 128-multiples; tiny batches pad up to one
    # minimal block.
    block_b = max(128, (min(block_b, B) // 128) * 128)
    padb = (-B) % block_b
    if padb:
        a = np.concatenate([a, np.zeros((padb, a.shape[1]), np.int32)])
        b = np.concatenate([b, np.full((padb, b.shape[1]), -1, np.int32)])
    padn = (-a.shape[1]) % 8
    if padn:
        a = np.concatenate(
            [a, np.full((a.shape[0], padn), -1, np.int32)], axis=1
        )
    out = _sw_pallas(
        jnp.asarray(a.T), jnp.asarray(b.T), block_b=block_b,
        interpret=interpret,  # bool: the fast XLA-backed interpreter
        # (InterpretParams would select the slow Mosaic one - only
        # remote-DMA/semaphore kernels need that; see megakernel.py)
    )
    return np.asarray(out)[0, :B]
