"""In-kernel ICI work stealing: the whole multi-device run is ONE resident
kernel per device - scheduling, migration, and termination never exit to XLA.

This is the fully-resident evolution of device/sharded.py's bulk-synchronous
steal loop (which re-enters the kernel every round and exchanges surplus with
host-jitted ``ppermute``): here each device's kernel runs rounds internally,

  1. drain the local ready ring for a bounded quantum
     (megakernel._make_core's scheduler - the same pop/dispatch/complete),
  2. for every XOR dimension k < log2(ndev): a paired stats exchange with
     the partner at distance 2^k folds (pending, backlog) partial sums -
     recursive-doubling termination in log2(ndev) hops - then a paired
     row exchange pairwise-equalizes backlog (send (mine - theirs)/2,
     window-capped) by remote-DMAing descriptor rows straight between SMEM
     task tables, importing before the next hop so received work diffuses
     further the same round,
  3. exit when the folded global pending hits zero.

(Non-power-of-two 1D meshes keep the older schedule: a ring allreduce for
termination plus one cycling partner per round.)

The reference analogue is the thief CASing a victim's deque slot from
another core (src/hclib-locality-graph.c:843-888, src/hclib-deque.c:75-106);
on TPU the "CAS" becomes paired remote DMAs with semaphore flow control:

- every (hop, sub-channel) inbox is 1-deep with a fixed writer: the
  receiver signals that writer's REGULAR *credit* semaphore after
  consuming, and the writer waits a credit before its next-round write -
  so an inbox is never overwritten before it is consumed, without any
  global barrier;
- recv DMA semaphores are per-hop: a device two hops ahead may deliver
  early, and a shared recv semaphore would hand its signal to a wait for a
  different hop's message (desynchronizing the pairing);
- all devices execute the identical hop schedule, so every semaphore wait
  has a matching signal by construction (lockstep SPMD, no dynamic
  handshakes to deadlock on).

Tested end-to-end on 8-device 1D and 4x2 2D simulated meshes via Mosaic's
TPU interpret mode (``pltpu.InterpretParams`` - simulates remote DMA +
semaphores on CPU) and compiled/run on real TPU hardware on a 1-device mesh
(self-loop exchange).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .descriptor import (
    DESC_WORDS,
    F_CSR_N,
    F_DEP,
    F_FN,
    F_SUCC0,
    F_SUCC1,
    TaskGraphBuilder,
)
from .megakernel import (
    interpret_mode,
    C_HEAD,
    C_PENDING,
    C_ROUNDS,
    C_TAIL,
    Megakernel,
)
from .tracebuf import (
    HDR as _TR_HDR,
    NullTracer,
    TR_ABORT,
    TR_XFER,
    Tracer,
    trace_info,
)

__all__ = ["ICIStealMegakernel"]


class ICIStealMegakernel:
    """Runs one resident scheduler+steal kernel per device of a 1D/2D/3D
    mesh.

    ``mk`` supplies the kernel table/capacities (as for ShardedMegakernel);
    ``migratable_fns`` whitelists kernel ids whose successor-free tasks may
    migrate; ``window`` bounds rows per exchange; ``scan`` bounds how far
    past the ring head the exporter looks for eligible rows.

    Power-of-two device counts (the practical case: TPU slices come in
    pof2 per-axis shapes) use the **paired hypercube dimension-exchange**:
    every round runs ALL log2(ndev) XOR-partner hops, each hop pairwise-
    equalizing backlog (send (mine - theirs)/2, capped at ``window``) and
    folding (pending, backlog) partial sums into the same hop schedule -
    recursive-doubling termination in log2(ndev) hops with no separate
    ring collective, and a maximal skew spreads across the whole mesh in
    one or two rounds instead of one window per round. On a 2D mesh the
    XOR dimensions decompose into per-axis exchanges (low bits = minor
    axis), so every hop is a torus-neighbor-distance transfer. Non-pof2
    1D meshes keep the cycling single-partner schedule with the ring
    termination collective.
    """

    def __init__(
        self,
        mk: Megakernel,
        mesh: Mesh,
        migratable_fns: Iterable[int] = (),
        window: int = 8,
        scan: Optional[int] = None,
        fault_plan=None,
    ) -> None:
        if len(mesh.axis_names) not in (1, 2, 3):
            raise ValueError("ICIStealMegakernel wants a 1D/2D/3D mesh")
        self.mk = mk
        self.mesh = mesh
        self.axes = tuple(mesh.axis_names)
        self.axis = self.axes[0]  # psum axis for gcounts (legacy name)
        self.dims = tuple(int(d) for d in mesh.devices.shape)
        self.ndev = int(np.prod(self.dims))
        self._pof2 = self.ndev & (self.ndev - 1) == 0
        if len(self.axes) > 1 and not self._pof2:
            raise ValueError("2D/3D meshes need power-of-two device counts")
        self.migratable_fns = frozenset(int(f) for f in migratable_fns)
        self.window = int(window)
        self.scan = int(scan) if scan is not None else 2 * self.window
        self._jitted: Dict[Any, Any] = {}
        self._pc_stats: Optional[Dict[str, Any]] = None
        # Power-of-two meshes delegate to the unified resident kernel
        # (device/resident.py) in its steal-only, whole-row-migration
        # configuration - this class remains the non-pof2 fallback (and
        # the named legacy API). Seeded device fault injection
        # (DeviceFaultPlan) lives in the resident kernel's exchange
        # protocol; the non-pof2 ring supports the abort word only.
        self._resident = None
        if self._pof2:
            from .resident import ResidentKernel

            self._resident = ResidentKernel(
                mk, mesh, steal=True, migratable_fns=self.migratable_fns,
                homed=False, window=self.window, scan=self.scan,
                fault_plan=fault_plan,
            )
        elif fault_plan is not None and fault_plan.enabled():
            raise ValueError(
                "DeviceFaultPlan injection needs a power-of-two mesh (the "
                "resident kernel's credited hypercube exchange); the "
                "non-pof2 ring supports only the abort word"
            )

    # -- shared kernel helpers --

    def _flat_me(self):
        """Flattened device index. This class's own kernel bodies only ever
        run on non-pof2 1D meshes - every pof2 mesh (the only legal
        multi-axis shape) delegates run() to ResidentKernel, whose
        addressing handles 1D/2D/3D."""
        assert len(self.axes) == 1, "multi-axis meshes delegate to resident"
        return jax.lax.axis_index(self.axes[0])

    def _did(self, flat):
        """Remote-op device_id for a flattened index (1D: the logical id;
        see _flat_me for why multi-axis never reaches this)."""
        assert len(self.axes) == 1
        return flat

    @property
    def _did_type(self):
        # 1D-only like _flat_me/_did: multi-axis meshes never reach this
        # class's kernel bodies (pof2 delegates to ResidentKernel).
        assert len(self.axes) == 1
        return pltpu.DeviceIdType.LOGICAL

    def _make_xfer(self, core, tasks, ready, counts, free, candbuf, sendbuf):
        """Shared transfer closures for both kernel bodies: paired remote
        copy (device-id type per mesh rank), the export scan/compact pass,
        and descriptor-row import via the core's adoption path."""
        cap = self.mk.capacity
        W = self.window
        SCAN = self.scan
        wl = sorted(self.migratable_fns)
        did_type = self._did_type

        def remote_copy(src, dst, dev, s_send, s_recv):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=s_send, recv_sem=s_recv,
                device_id=dev, device_id_type=did_type,
            )
            rdma.start()
            rdma.wait()

        def export(quota):
            """Scan up to SCAN entries behind the ring head (the cold,
            steal-side end of the Chase-Lev split), move up to ``quota``
            eligible rows into sendbuf, compact the kept candidates back
            against the new head. Returns nsend."""
            head = counts[C_HEAD]
            backlog = counts[C_TAIL] - head
            S = jnp.minimum(backlog, SCAN)

            def copy_cand(j, _):
                candbuf[j] = ready[(head + j) % cap]
                return 0

            jax.lax.fori_loop(0, S, copy_cand, 0)

            def elig_of(cand):
                d_fn = tasks[cand, F_FN]
                ok = jnp.bool_(False)
                for f in wl:
                    ok = ok | (d_fn == f)
                return (
                    ok
                    & (tasks[cand, F_SUCC0] == -1)
                    & (tasks[cand, F_SUCC1] == -1)
                    & (tasks[cand, F_CSR_N] == 0)
                )

            def count_elig(j, n):
                return n + elig_of(candbuf[j]).astype(jnp.int32)

            nelig = jax.lax.fori_loop(0, S, count_elig, jnp.int32(0))
            nsend = jnp.minimum(quota, nelig)

            def classify(j, carry):
                se, kp = carry
                cand = candbuf[j]
                take = elig_of(cand) & (se < nsend)

                @pl.when(take)
                def _():
                    for w in range(DESC_WORDS):
                        sendbuf[se, w] = tasks[cand, w]
                    # The task now lives on the target: tombstone + free
                    # the row (spawn/import reuse it).
                    tasks[cand, F_DEP] = -1
                    nf = free[0] + 1
                    free[0] = nf
                    free[nf] = cand

                @pl.when(jnp.logical_not(take))
                def _():
                    ready[(head + nsend + kp) % cap] = cand

                return (
                    se + take.astype(jnp.int32),
                    kp + (1 - take.astype(jnp.int32)),
                )

            jax.lax.fori_loop(0, S, classify, (jnp.int32(0), jnp.int32(0)))
            counts[C_HEAD] = head + nsend
            counts[C_PENDING] = counts[C_PENDING] - nsend
            return nsend

        def import_rows(box):
            """Install received descriptors through the shared adoption
            path (core.install_descriptor: freed rows first, then the bump
            cursor; stolen rows came off a ready ring so their dep counter
            is 0 and they go straight back to ready)."""
            n = box[W, 0]

            def one(i, _):
                core.install_descriptor(lambda w: box[i, w])
                return 0

            jax.lax.fori_loop(0, n, one, 0)

        return remote_copy, export, import_rows

    # -- the kernel --

    def _kernel(self, quantum: int, max_rounds: int, trace, *refs) -> None:
        # ``trace`` captured at _build time (pallas traces lazily; see
        # Megakernel._kernel).
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = 1 if mk.batch_specs else 0
        ntrace = 1 if trace is not None else 0
        n_in = 6 + ndata  # + abort word (last input)
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + 4 + ndata + nbatch + ntrace]
        rest = refs[n_in + 4 + ndata + nbatch + ntrace :]
        nscratch = len(mk.scratch_specs)
        scratch_refs = rest[:nscratch]
        stail = list(rest[nscratch:])
        (
            free, vfree, candbuf, sendbuf, inbox, statsnd, statrcv,
            abuf, dsems, csems, asem,
        ) = stail[:11]
        # Batched dispatch tier (ISSUE 7): lane scratch rides last; the
        # spill discipline empties it at every sched() exit, so the steal
        # export scan between rounds only ever sees ring rows. The length
        # check keeps the positional bind loud: an edit to _build's
        # scratch list that forgets these indices must fail at trace
        # time, not scribble batch descriptors into a neighboring ref.
        assert len(stail) == 11 + 2 * nbatch, len(stail)
        lanes, lstate = (stail[11], stail[12]) if nbatch else (None, None)
        abort_in = in_refs[n_in - 1]
        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        tasks, ready, counts, ivalues = out_refs[:4]
        data = dict(zip(mk.data_specs.keys(), out_refs[4 : 4 + ndata]))
        tstats = out_refs[4 + ndata] if nbatch else None
        tr = (
            Tracer(out_refs[4 + ndata + nbatch], trace.capacity)
            if ntrace
            else NullTracer()
        )
        scratch = dict(zip(mk.scratch_specs.keys(), scratch_refs))
        # stage_all_values=True: imported tasks may read/accumulate value
        # slots the local partition never declared (an empty partition has
        # value_alloc 0 but still hosts migrated counter tasks).
        core = mk._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, True,
            lanes=lanes, lstate=lstate, tstats=tstats,
            tracer=tr if tr.enabled else None,
        )

        ndev = self.ndev
        W = self.window
        axis = self.axis
        # Hop schedule: powers of two below ndev (hypercube diffusion); a
        # 1-device ring degenerates to hop 0 = self-exchange, which still
        # exercises the full remote-DMA path (quota is 0 vs oneself).
        nh = max(1, (ndev - 1).bit_length())

        me = jax.lax.axis_index(axis)
        right = (me + 1) % ndev
        left = (me + ndev - 1) % ndev
        remote_copy, export, import_rows = self._make_xfer(
            core, tasks, ready, counts, free, candbuf, sendbuf
        )

        def allreduce(r, local_abort):
            """Ring-allreduce of (pending, backlog, abort): every device
            learns the global totals in ndev-1 hops (the done-flag join,
            src/hclib-runtime.c:403-421, as an in-kernel collective). The
            abort word rides the same fold so a host abort exits the
            WHOLE ring in lockstep one round later - a divergent exit
            would strand neighbors in the paired exchanges."""
            cur_p = counts[C_PENDING]
            cur_b = counts[C_TAIL] - counts[C_HEAD]
            cur_a = local_abort.astype(jnp.int32)
            tot_p, tot_b, tot_a = cur_p, cur_b, cur_a
            for k in range(ndev - 1):
                statsnd[0] = cur_p
                statsnd[1] = cur_b
                statsnd[2] = cur_a
                if k > 0:
                    pltpu.semaphore_wait(csems.at[0], 1)
                else:

                    @pl.when(r > 0)
                    def _():
                        pltpu.semaphore_wait(csems.at[0], 1)

                remote_copy(
                    statsnd, statrcv, right, dsems.at[0], dsems.at[1]
                )
                cur_p = statrcv[0]
                cur_b = statrcv[1]
                cur_a = statrcv[2]
                # Consumed: free the writer (our left neighbor) to send its
                # next step into our statrcv.
                pltpu.semaphore_signal(
                    csems.at[0], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                tot_p = tot_p + cur_p
                tot_b = tot_b + cur_b
                tot_a = tot_a + cur_a
            return tot_p, tot_b, tot_a

        def exchange(r, tot_b):
            """One steal hop: send surplus rows to the device at distance
            d = 2^(r mod nh), receive from the mirror device."""
            d = (jnp.int32(1) << (r % nh)) % ndev
            target = (me + d) % ndev
            gavg = tot_b // ndev
            backlog = counts[C_TAIL] - counts[C_HEAD]
            quota = jnp.clip(backlog - gavg, 0, W)
            nsend = export(quota)
            sendbuf[W, 0] = nsend

            @pl.when(nsend > 0)
            def _():
                tr.emit(TR_XFER, tr.now(), target, nsend)
            # Credit: our *target's* inbox is free once it signalled us at
            # the end of its previous round (it signals its next-round
            # source, which is exactly us because the hop schedule is
            # global). Round 0 inboxes start free.
            @pl.when(r > 0)
            def _():
                pltpu.semaphore_wait(csems.at[1], 1)

            remote_copy(sendbuf, inbox, target, dsems.at[2], dsems.at[3])
            import_rows(inbox)
            # Our inbox is consumed: credit the device that targets it
            # next round (distance 2^((r+1) mod nh)).
            dn = (jnp.int32(1) << ((r + 1) % nh)) % ndev
            src_next = (me + ndev - dn) % ndev
            pltpu.semaphore_signal(
                csems.at[1], inc=1, device_id=src_next,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        core.stage()

        def cond(carry):
            r, done = carry
            return jnp.logical_not(done) & (r < max_rounds)

        def body(carry):
            r, done = carry
            core.sched(quantum)
            # Host abort word: re-read from HBM inside the round loop, so
            # an abort stops a running quantum stream within one round.
            cpa = pltpu.make_async_copy(abort_in, abuf, asem.at[0])
            cpa.start()
            cpa.wait()
            tot_p, tot_b, tot_a = allreduce(r, abuf[0] != 0)
            done = (tot_p == 0) | (tot_a > 0)

            @pl.when(tot_a > 0)
            def _():
                tr.emit(TR_ABORT, tr.now(), r)

            @pl.when(jnp.logical_not(done))
            def _():
                exchange(r, tot_b)

            return r + 1, done

        r, done = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.bool_(False))
        )
        counts[C_ROUNDS] = r
        # Drain outstanding flow-control credits so semaphores are zero at
        # kernel exit: the first send of each channel never waited (round-0
        # priming), so each channel holds exactly one unconsumed credit
        # once it was used at all.
        e = jnp.where(done, r - 1, r)  # rounds that ran an exchange

        @pl.when(e >= 1)
        def _():
            pltpu.semaphore_wait(csems.at[1], 1)

        if ndev > 1:

            @pl.when(r >= 1)
            def _():
                pltpu.semaphore_wait(csems.at[0], 1)

    def _kernel_hc(self, quantum: int, max_rounds: int, trace,
                   *refs) -> None:
        """Paired hypercube dimension-exchange body (pof2 device counts).

        Each round: drain the local ring for a quantum, then for every XOR
        dimension k: (1) paired stats exchange folding (pending, backlog)
        partial sums - recursive-doubling termination - and carrying the
        partner's current backlog, (2) paired row exchange sending
        clip((mine - theirs)/2, 0, W) eligible rows, importing the mirror
        flow immediately so later hops diffuse just-received work further.
        Every (hop, sub-channel) has its own inbox buffer and credit
        semaphore: the writer for a given hop never changes, so a 1-deep
        credited channel per hop is race-free without any global barrier.
        """
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = 1 if mk.batch_specs else 0
        ntrace = 1 if trace is not None else 0
        # + abort word (last input; this body polls nothing but must
        # count it - _build passes 6 + ndata inputs to whichever body it
        # binds, and miscounting here shears every ref slice after the
        # inputs). NOTE: run() delegates every pof2 mesh to
        # ResidentKernel, so this body is unreachable today; it is kept
        # aligned with _build so a direct build fails loudly (the
        # scratch-tail length assert below) rather than silently.
        n_in = 6 + ndata
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + 4 + ndata + nbatch + ntrace]
        rest = refs[n_in + 4 + ndata + nbatch + ntrace :]
        nscratch = len(mk.scratch_specs)
        scratch_refs = rest[:nscratch]
        nh = self._nh
        tail = rest[nscratch:]
        free, vfree, candbuf, sendbuf, statsnd = tail[:5]
        statrcv = tail[5 : 5 + nh]
        inboxes = tail[5 + nh : 5 + 2 * nh]
        ssems, rsems, csems = tail[5 + 2 * nh : 5 + 2 * nh + 3]
        assert len(tail) == 5 + 2 * nh + 3 + 2 * nbatch, len(tail)
        lanes, lstate = (
            (tail[5 + 2 * nh + 3], tail[5 + 2 * nh + 4])
            if nbatch else (None, None)
        )
        tasks_in, succ, ready_in, counts_in, ivalues_in = in_refs[:5]
        tasks, ready, counts, ivalues = out_refs[:4]
        data = dict(zip(mk.data_specs.keys(), out_refs[4 : 4 + ndata]))
        tstats = out_refs[4 + ndata] if nbatch else None
        if ntrace:
            # This body is only reachable on pof2 meshes, which run()
            # routes to ResidentKernel (the traced path) - but keep the
            # appended output deterministic if built directly.
            for w in range(_TR_HDR):
                out_refs[4 + ndata + nbatch][w] = 0
        scratch = dict(zip(mk.scratch_specs.keys(), scratch_refs))
        core = mk._make_core(
            succ, tasks, ready, counts, ivalues, data, scratch, free, vfree,
            tasks_in, ready_in, counts_in, ivalues_in, True,
            lanes=lanes, lstate=lstate, tstats=tstats,
        )

        ndev = self.ndev
        cap = mk.capacity
        W = self.window
        SCAN = self.scan
        wl = sorted(self.migratable_fns)
        me = self._flat_me()
        did_type = self._did_type

        def remote_copy(src, dst, dev, s_send, s_recv):
            rdma = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=s_send, recv_sem=s_recv,
                device_id=dev, device_id_type=did_type,
            )
            rdma.start()
            rdma.wait()

        def export(quota):
            head = counts[C_HEAD]
            backlog = counts[C_TAIL] - head
            S = jnp.minimum(backlog, SCAN)

            def copy_cand(j, _):
                candbuf[j] = ready[(head + j) % cap]
                return 0

            jax.lax.fori_loop(0, S, copy_cand, 0)

            def elig_of(cand):
                d_fn = tasks[cand, F_FN]
                ok = jnp.bool_(False)
                for f in wl:
                    ok = ok | (d_fn == f)
                return (
                    ok
                    & (tasks[cand, F_SUCC0] == -1)
                    & (tasks[cand, F_SUCC1] == -1)
                    & (tasks[cand, F_CSR_N] == 0)
                )

            def count_elig(j, n):
                return n + elig_of(candbuf[j]).astype(jnp.int32)

            nelig = jax.lax.fori_loop(0, S, count_elig, jnp.int32(0))
            nsend = jnp.minimum(quota, nelig)

            def classify(j, carry):
                se, kp = carry
                cand = candbuf[j]
                take = elig_of(cand) & (se < nsend)

                @pl.when(take)
                def _():
                    for w in range(DESC_WORDS):
                        sendbuf[se, w] = tasks[cand, w]
                    tasks[cand, F_DEP] = -1
                    nf = free[0] + 1
                    free[0] = nf
                    free[nf] = cand

                @pl.when(jnp.logical_not(take))
                def _():
                    ready[(head + nsend + kp) % cap] = cand

                return (
                    se + take.astype(jnp.int32),
                    kp + (1 - take.astype(jnp.int32)),
                )

            jax.lax.fori_loop(0, S, classify, (jnp.int32(0), jnp.int32(0)))
            counts[C_HEAD] = head + nsend
            counts[C_PENDING] = counts[C_PENDING] - nsend
            return nsend

        def import_rows(box):
            n = box[W, 0]

            def one(i, _):
                core.install_descriptor(lambda w: box[i, w])
                return 0

            jax.lax.fori_loop(0, n, one, 0)

        core.stage()

        def cond(carry):
            r, done = carry
            return jnp.logical_not(done) & (r < max_rounds)

        def body(carry):
            r, done = carry
            core.sched(quantum)
            # Round-start snapshot: every task is either in some device's
            # pending count or was already executed - nothing is in flight
            # between rounds, so the folded sums are exact.
            tot_p = counts[C_PENDING]
            for k in range(nh):
                partner = (me ^ (1 << k)) % ndev  # ndev==1: self-loop
                pdev = self._did(partner)
                statsnd[0] = tot_p
                statsnd[1] = counts[C_TAIL] - counts[C_HEAD]

                @pl.when(r > 0)
                def _(k=k):
                    pltpu.semaphore_wait(csems.at[2 * k], 1)

                # Per-hop recv semaphores: a faster device two hops ahead
                # may deliver its hop-k' message while we still wait at
                # hop k - a shared recv sem would hand us its signal and
                # desynchronize the pairing (observed as a deadlock).
                remote_copy(
                    statsnd, statrcv[k], pdev, ssems.at[0], rsems.at[2 * k]
                )
                tot_p = tot_p + statrcv[k][0]
                peer_b = statrcv[k][1]
                pltpu.semaphore_signal(
                    csems.at[2 * k], inc=1, device_id=pdev,
                    device_id_type=did_type,
                )
                myb = counts[C_TAIL] - counts[C_HEAD]
                quota = jnp.clip((myb - peer_b + 1) // 2, 0, W)
                # Zero quota (balanced or deficit side - the steady state)
                # skips the whole export scan/compact pass.
                sendbuf[W, 0] = 0

                @pl.when(quota > 0)
                def _():
                    sendbuf[W, 0] = export(quota)

                @pl.when(r > 0)
                def _(k=k):
                    pltpu.semaphore_wait(csems.at[2 * k + 1], 1)

                remote_copy(
                    sendbuf, inboxes[k], pdev, ssems.at[1],
                    rsems.at[2 * k + 1],
                )
                import_rows(inboxes[k])
                pltpu.semaphore_signal(
                    csems.at[2 * k + 1], inc=1, device_id=pdev,
                    device_id_type=did_type,
                )
            return r + 1, tot_p == 0

        r, done = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.bool_(False))
        )
        counts[C_ROUNDS] = r
        # Every executed round ran every hop and its first send never
        # waited, so each of the 2*nh credit channels holds exactly one
        # unconsumed credit once any round ran.
        for k in range(2 * self._nh):

            @pl.when(r >= 1)
            def _(k=k):
                pltpu.semaphore_wait(csems.at[k], 1)

    @property
    def _nh(self) -> int:
        return max(1, (self.ndev - 1).bit_length())

    # -- host entry --

    def _build(self, quantum: int, max_rounds: int):
        mk = self.mk
        ndata = len(mk.data_specs)
        nbatch = 1 if mk.batch_specs else 0
        smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
        anyspace = functools.partial(pl.BlockSpec, memory_space=pl.ANY)
        ntrace = 1 if mk.trace is not None else 0
        # Trailing abort-word input (HBM: the kernel re-reads it per round).
        in_specs = [smem()] * 5 + [anyspace()] * ndata + [anyspace()]
        out_specs = tuple(
            [smem()] * 4 + [anyspace()] * ndata
            + [smem()] * nbatch  # tstats (batch-routed builds)
            + [smem()] * ntrace
        )
        data_shapes = [
            jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in mk.data_specs.values()
        ]
        from .megakernel import TS_WORDS

        out_shape = tuple(
            [
                jax.ShapeDtypeStruct((mk.capacity, DESC_WORDS), jnp.int32),
                jax.ShapeDtypeStruct((mk.capacity,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((mk.num_values,), jnp.int32),
            ]
            + data_shapes
            + (
                [jax.ShapeDtypeStruct((TS_WORDS,), jnp.int32)]
                if nbatch else []
            )
            + ([mk.trace.out_shape()] if ntrace else [])
        )
        aliases = {0: 0, 2: 1, 3: 2, 4: 3}
        for i in range(ndata):
            aliases[5 + i] = 4 + i
        from .megakernel import VBLOCK

        W = self.window
        base_scratch = list(mk.scratch_specs.values()) + [
            pltpu.SMEM((mk.capacity + 1,), jnp.int32),  # free
            pltpu.SMEM((mk.num_values // VBLOCK + 1,), jnp.int32),
            pltpu.SMEM((self.scan,), jnp.int32),  # candbuf
            pltpu.SMEM((W + 1, DESC_WORDS), jnp.int32),  # sendbuf
        ]
        if self._pof2:
            nh = self._nh
            body = self._kernel_hc
            scratch = base_scratch + (
                [pltpu.SMEM((4,), jnp.int32)]  # statsnd
                + [pltpu.SMEM((4,), jnp.int32) for _ in range(nh)]
                + [
                    pltpu.SMEM((W + 1, DESC_WORDS), jnp.int32)
                    for _ in range(nh)
                ]  # per-hop inboxes (fixed writer each -> own channel)
                + [
                    pltpu.SemaphoreType.DMA((2,)),  # send sems (stat, rows)
                    pltpu.SemaphoreType.DMA((2 * nh,)),  # per-hop recv sems
                    pltpu.SemaphoreType.REGULAR((2 * nh,)),
                ]
            )
        else:
            body = self._kernel
            scratch = base_scratch + [
                pltpu.SMEM((W + 1, DESC_WORDS), jnp.int32),  # inbox
                pltpu.SMEM((4,), jnp.int32),  # statsnd (+ abort word)
                pltpu.SMEM((4,), jnp.int32),  # statrcv
                pltpu.SMEM((8,), jnp.int32),  # abuf (abort staging)
                pltpu.SemaphoreType.DMA((4,)),
                pltpu.SemaphoreType.REGULAR((2,)),
                pltpu.SemaphoreType.DMA((1,)),  # asem
            ]
        if mk.batch_specs:
            # Batched dispatch tier lane scratch (both bodies unpack it
            # last): re-entrant across sched() entries via the spill
            # discipline, so the steal exchange never sees a lane entry.
            nb = mk.lane_scratch_rows  # kinds x priority buckets
            from .megakernel import LS_WORDS

            scratch += [
                pltpu.SMEM((nb, mk.capacity), jnp.int32),  # lanes
                pltpu.SMEM((nb, LS_WORDS), jnp.int32),  # lstate
            ]
        kern = pl.pallas_call(
            functools.partial(body, quantum, max_rounds, mk.trace),
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
            input_output_aliases=aliases,
            interpret=interpret_mode() if mk.interpret else False,
        )

        def step(tasks, succ, ring, counts, iv, *rest):
            data = rest[:ndata]
            abort = rest[ndata]
            outs = kern(
                tasks[0], succ[0], ring[0], counts[0], iv[0],
                *[d[0] for d in data], abort[0]
            )
            tasks_o, ready_o, counts_o, iv_o = outs[:4]
            data_o = outs[4 : 4 + ndata]
            extra_o = outs[4 + ndata :]  # [tstats?, trace?]
            gcounts = jax.lax.psum(counts_o, self.axes)
            return (
                counts_o[None],
                iv_o[None],
                gcounts[None],
                *[d[None] for d in data_o],
                *[t[None] for t in extra_o],
            )

        nin = 6 + ndata
        f = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(self.axes),) * nin,
            out_specs=(P(self.axes),) * (3 + ndata + nbatch + ntrace),
            check_vma=False,
        )
        return jax.jit(f)

    def run(
        self,
        builders: Sequence[TaskGraphBuilder],
        data: Optional[Dict[str, np.ndarray]] = None,
        ivalues: Optional[np.ndarray] = None,
        quantum: int = 64,
        max_rounds: int = 1 << 14,
        abort=None,
    ):
        """Execute all partitions fully on-device; returns
        (ivalues[ndev, V], data, info). ``abort``: host abort word (truthy
        or per-device flags) - the round loops observe it within one round
        and the mesh exits in lockstep with ``info['aborted']`` instead of
        running the workload out."""
        from .sharded import execute_partitions

        if self._resident is not None:
            iv_o, data_o, info = self._resident.run(
                builders, data=data, ivalues=ivalues, quantum=quantum,
                max_rounds=max_rounds, abort=abort,
            )
            info["steal_rounds"] = info.pop("rounds")
            return iv_o, data_o, info
        key = (quantum, max_rounds)
        first_build = key not in self._jitted
        if first_build:
            from ..runtime.progcache import mesh_key, shared_build

            variant = (
                "ici", mesh_key(self.mesh),
                tuple(sorted(self.migratable_fns)), self.window,
                self.scan,
            ) + key
            self._jitted[key], self._pc_stats = shared_build(
                self.mk, variant,
                lambda: self._build(quantum, max_rounds),
            )
        from .sharded import abort_words

        abort_arr = abort_words(abort, self.ndev)
        t0_ns = time.monotonic_ns()
        iv_o, data_o, info = execute_partitions(
            self.mk, self.mesh, self.ndev, self._jitted[key], builders,
            data, ivalues, with_rounds=True, extra_inputs=[abort_arr],
        )
        t1_ns = time.monotonic_ns()
        if (
            first_build and self._pc_stats is not None
            and not self._pc_stats["hit"]
        ):
            # jax.jit is lazy: a cache MISS pays trace/lower/compile
            # inside this first entry (the Megakernel._execute
            # discipline), so fold the first wall into build_s before
            # it is reported.
            self._pc_stats["build_s"] += (t1_ns - t0_ns) / 1e9
        if self._pc_stats is not None:
            info["program_cache"] = dict(self._pc_stats)
        tail = info.pop("extra_outputs", None)
        if self.mk.trace is not None and tail:
            info["trace"] = trace_info(
                [tail[-1][d] for d in range(self.ndev)], t0_ns, t1_ns,
                self.mk.trace.capacity,
            )
        if self.mk.batch_specs and tail:
            # Per-device batched-tier counters (tstats rides before the
            # trace ring in the appended outputs).
            trows = tail[0]
            info["tiers"] = [
                self.mk.decode_tier_stats(trows[d])
                for d in range(self.ndev)
            ]
        info["aborted"] = bool(abort_arr[:, 0].any()) and info["pending"] != 0
        if info["overflow"]:
            raise RuntimeError("ici steal: task-table overflow")
        if info["pending"] != 0 and not info["aborted"]:
            from ..runtime.resilience import StallError

            raise StallError(
                f"ici steal stalled: {info['pending']} pending after "
                f"{info['executed']} executed ({info['steal_rounds']} rounds)",
                stats=info,
            )
        return iv_o, data_o, info
