"""Device task kernels for the benchmark workloads (scalar + tile kernels).

These run inside the megakernel's ``lax.switch`` table. fib demonstrates
dynamic on-device spawning with continuation passing; arrayadd demonstrates
tile tasks that DMA HBM data through VMEM and use the VPU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .descriptor import TaskGraphBuilder
from .megakernel import (
    VBLOCK,
    BatchContext,
    BatchSpec,
    KernelContext,
    Megakernel,
    fault_mix,
)

__all__ = [
    "device_fib",
    "device_arrayadd",
    "make_fib_megakernel",
    "make_vfib_megakernel",
    "device_vfib",
    "make_uts_megakernel",
    "device_uts_mk",
    "UTS_NODE",
    "batch_of",
]


# ------------------------------------------------------------------- fib

FIB = 0
SUM = 1


def _fib_kernel(ctx: KernelContext) -> None:
    n = ctx.arg(0)

    @pl.when(n < 2)
    def _():
        ctx.set_out(n)

    @pl.when(n >= 2)
    def _():
        # The SUM task is this task's continuation: it inherits our
        # successors and produces our output slot. The children write into
        # the value block OWNED BY SUM'S ROW - no allocator call, and the
        # block recycles with the row when SUM completes (by which point
        # its result is already in the parent's block).
        # nargs declares each spawn's true arity: the scalar tier's cost IS
        # its SMEM op count, so dead arg-zeroing writes are skipped (SUM's
        # two args are set right below via set_arg).
        sum_idx = ctx.spawn(SUM, dep_count=2, out=ctx.out_slot, nargs=0)
        ctx.take_continuation(sum_idx)
        base = ctx.row_values(sum_idx)
        ctx.set_arg(sum_idx, 0, base)
        ctx.set_arg(sum_idx, 1, base + 1)
        ctx.spawn(FIB, [n - 1], succ0=sum_idx, out=base, nargs=1)
        ctx.spawn(FIB, [n - 2], succ0=sum_idx, out=base + 1, nargs=1)


def _sum_kernel(ctx: KernelContext) -> None:
    ctx.set_out(ctx.value(ctx.arg(0)) + ctx.value(ctx.arg(1)))


def batch_of(scalar_kernel, width: int = 8) -> BatchSpec:
    """Batched same-kind spelling of a scalar task kernel: one batch round
    pops up to ``width`` same-kind descriptors and runs ``scalar_kernel``
    once per live slot through ``BatchContext.slot_ctx`` - bit-identical to
    scalar dispatch (the per-slot context shares every ref), but the per-
    descriptor ring pop + lax.switch overhead is paid once per ROUND
    instead of once per task. This is how spawn-heavy scalar families
    (fib/UTS nodes) ride the batch tier; tile families with a genuinely
    fused body (SW waves, Cholesky updrow) write their own BatchSpec."""

    def body(ctx: BatchContext) -> None:
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                scalar_kernel(ctx.slot_ctx(s))

    return BatchSpec(body, width=width)


def make_fib_megakernel(
    capacity: int = 768,  # SMEM windows pad scalars ~32B/word: ~800-row max
    interpret: Optional[bool] = None,
    num_values: Optional[int] = None,
    trace=None,
    batch_width: Optional[int] = None,
    checkpoint: Optional[bool] = None,
) -> Megakernel:
    # Descriptor rows recycle, and value blocks are row-owned (SUM reads
    # its children's results out of its own row's block), so both live
    # sets are ~ the spawn-tree depth and a small table runs arbitrarily
    # deep fibs. The value buffer must cover every row's block plus the
    # host slots.
    need = VBLOCK * capacity + 16  # 16 host slots for presets/outputs
    if num_values is None:
        num_values = need
    elif num_values < need:
        raise ValueError(
            f"fib uses row-owned value blocks: num_values must be >= "
            f"VBLOCK*capacity+16 = {need}, got {num_values}"
        )
    # batch_width routes the FIB kind through the batched same-kind tier
    # (one batch round runs up to batch_width fib bodies per-slot through
    # slot_ctx - bit-identical to scalar dispatch); SUM stays scalar: join
    # tasks become ready one at a time as their children complete, so a
    # SUM lane would fire near-empty batches for pure routing overhead.
    route = (
        {"fib": batch_of(_fib_kernel, width=batch_width)}
        if batch_width else None
    )
    return Megakernel(
        kernels=[("fib", _fib_kernel), ("sum", _sum_kernel)],
        capacity=capacity,
        num_values=num_values,
        succ_capacity=64,
        interpret=interpret,
        uses_row_values=True,
        trace=trace,
        route=route,
        checkpoint=checkpoint,
    )


def device_fib(
    n: int,
    capacity: int = 768,
    interpret: Optional[bool] = None,
    num_values: Optional[int] = None,
) -> Tuple[int, dict]:
    """Compute fib(n) entirely on-device via dynamic task spawning."""
    mk = make_fib_megakernel(capacity, interpret, num_values=num_values)
    b = TaskGraphBuilder()
    b.add(FIB, args=[n], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# ------------------------------------------------------- fib, vector tier

VFIB = 0


def make_vfib_megakernel(
    max_n: int = 32,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
    capacity: int = 64,
) -> Megakernel:
    """fib on the megakernel's batch-dispatch tier: one seed descriptor in
    the scalar table; the subtree runs wide over VPU lanes
    (device/vector_engine.py). Far larger fibs fit than on the scalar tier
    (the tree lives in per-lane VMEM stacks, not SMEM descriptor rows)."""
    from .vector_engine import fib_spec

    return Megakernel(
        kernels=[("vfib", fib_spec(max_n=max_n, lanes=lanes))],
        capacity=capacity,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
    )


def device_vfib(
    n: int,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
) -> Tuple[int, dict]:
    """Compute fib(n) via batched vector dispatch; info['executed'] counts
    the full recursion tree (2*fib(n+1) - 1 tasks)."""
    mk = make_vfib_megakernel(max_n=n + 2, lanes=lanes, interpret=interpret)
    b = TaskGraphBuilder()
    b.add(VFIB, args=[n], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# ------------------------------------------------------ UTS, scalar tier

UTS_NODE = 0


def make_uts_megakernel(
    seed: int = 19,
    q_millis: int = 440,
    m_children: int = 4,
    max_depth: int = 12,
    capacity: int = 1024,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    quiesce_stride: Optional[int] = None,
    batch_width: Optional[int] = None,
) -> Megakernel:
    """Seeded unbalanced-tree search on the scalar megakernel tier: the
    dynamic-spawn UTS-style workload (the reference's north-star tree,
    models/uts.py, reduced to the descriptor ABI) used by the checkpoint
    tests/bench to quiesce a traversal mid-tree.

    Every node task counts itself into value slot 0 and spawns child c
    (c < ``m_children``) iff ``fault_mix(seed, c, node_id, 0, depth) <
    q_millis`` - the same in-kernel integer mixer the DeviceFaultPlan
    decision tables use, so the whole tree is a pure function of the
    seed (deterministic, reproducible, unbalanced by construction). The
    root (depth 0) spawns all ``m_children`` (the b0 root factor of UTS);
    ``max_depth`` bounds the traversal. Spawned rows are link-free
    (count-accumulate only), so they are migratable on every multi-device
    runner AND re-homeable across mesh sizes by
    ``CheckpointBundle.reshard``."""

    def node(ctx: KernelContext) -> None:
        ctx.set_value(0, ctx.value(0) + 1)
        node_id = ctx.arg(0)
        depth = ctx.arg(1)

        @pl.when(depth < max_depth)
        def _():
            for c in range(m_children):
                h = fault_mix(seed, c, node_id, 0, depth)
                exists = (depth == 0) | (h < q_millis)

                @pl.when(exists)
                def _(c=c):
                    ctx.spawn(
                        UTS_NODE,
                        [node_id * 31 + jnp.int32(7 * c + 1) + depth,
                         depth + 1],
                        nargs=2,
                    )

    # batch_width: run node expansion through the batched same-kind tier
    # (the whole tree is one kind, so every round past the root fires a
    # near-full batch); rows stay link-free, so batched UTS remains
    # migratable AND reshardable - the lanes-active checkpoint workload.
    route = (
        {"uts_node": batch_of(node, width=batch_width)}
        if batch_width else None
    )
    return Megakernel(
        kernels=[("uts_node", node)],
        capacity=capacity,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        checkpoint=checkpoint,
        quiesce_stride=quiesce_stride,
        route=route,
    )


def device_uts_mk(
    seed: int = 19,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    **mk_kw,
) -> Tuple[int, dict]:
    """Run the seeded UTS tree to completion; returns (nodes, info)."""
    if mk is None:
        mk = make_uts_megakernel(seed=seed, interpret=interpret, **mk_kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# --------------------------------------------------- n-queens, vector tier

VNQUEENS = 0


def device_nqueens(
    n: int,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
) -> Tuple[int, dict]:
    """Count n-queens solutions via batched vector dispatch;
    info['executed'] counts safe partial placements (the search tree)."""
    from .vector_engine import nqueens_spec

    mk = Megakernel(
        kernels=[("vnqueens", nqueens_spec(n, lanes=lanes))],
        capacity=64,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
    )
    b = TaskGraphBuilder()
    b.add(VNQUEENS, args=[0], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# --------------------------------------------------------------- arrayadd

ADD_TILE = 0
_TILE = (8, 128)  # f32 min tile


def _addtile_kernel(ctx: KernelContext) -> None:
    t = ctx.arg(0)
    a, b_, c = ctx.data["a"], ctx.data["b"], ctx.data["c"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sems = ctx.scratch["sems"]
    in_a = pltpu.make_async_copy(a.at[t], va, sems.at[0])
    in_b = pltpu.make_async_copy(b_.at[t], vb, sems.at[1])
    in_a.start()
    in_b.start()
    in_a.wait()
    in_b.wait()
    va[:] = va[:] + vb[:]
    out = pltpu.make_async_copy(va, c.at[t], sems.at[2])
    out.start()
    out.wait()


def device_arrayadd(ntiles: int = 16, interpret: Optional[bool] = None):
    """c = a + b over (ntiles, 8, 128) f32 blocks, one tile task per block."""
    shape = (ntiles,) + _TILE
    spec = jax.ShapeDtypeStruct(shape, jnp.float32)
    mk = Megakernel(
        kernels=[("add_tile", _addtile_kernel)],
        data_specs={"a": spec, "b": spec, "c": spec},
        scratch_specs={
            "va": pltpu.VMEM(_TILE, jnp.float32),
            "vb": pltpu.VMEM(_TILE, jnp.float32),
            "sems": pltpu.SemaphoreType.DMA((3,)),
        },
        capacity=max(64, ntiles),
        num_values=8,
        succ_capacity=8,
        interpret=interpret,
    )
    b = TaskGraphBuilder()
    for t in range(ntiles):
        b.add(ADD_TILE, args=[t])
    rng = np.random.default_rng(0)
    a = rng.standard_normal(shape).astype(np.float32)
    bb = rng.standard_normal(shape).astype(np.float32)
    c = np.zeros(shape, dtype=np.float32)
    _, data, info = mk.run(b, data={"a": a, "b": bb, "c": c})
    return a, bb, np.asarray(data["c"]), info
