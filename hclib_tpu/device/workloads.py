"""Device task kernels for the benchmark workloads (scalar + tile kernels).

These run inside the megakernel's ``lax.switch`` table. fib demonstrates
dynamic on-device spawning with continuation passing; arrayadd demonstrates
tile tasks that DMA HBM data through VMEM and use the VPU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .descriptor import TaskGraphBuilder
from .megakernel import (
    VBLOCK,
    BatchContext,
    BatchSpec,
    KernelContext,
    Megakernel,
    fault_mix,
)

__all__ = [
    "device_fib",
    "device_arrayadd",
    "make_fib_megakernel",
    "make_vfib_megakernel",
    "device_vfib",
    "make_uts_megakernel",
    "device_uts_mk",
    "UTS_NODE",
    "batch_of",
    "rmat_edges",
    "stencil_loop",
    "stencil_body",
    "stencil_reference",
    "stencil_data",
    "map_loop",
    "map_body",
    "map_reference",
    "map_data",
]


# ------------------------------------------------------------------- fib

FIB = 0
SUM = 1


def _fib_kernel(ctx: KernelContext) -> None:
    n = ctx.arg(0)

    @pl.when(n < 2)
    def _():
        ctx.set_out(n)

    @pl.when(n >= 2)
    def _():
        # The SUM task is this task's continuation: it inherits our
        # successors and produces our output slot. The children write into
        # the value block OWNED BY SUM'S ROW - no allocator call, and the
        # block recycles with the row when SUM completes (by which point
        # its result is already in the parent's block).
        # nargs declares each spawn's true arity: the scalar tier's cost IS
        # its SMEM op count, so dead arg-zeroing writes are skipped (SUM's
        # two args are set right below via set_arg).
        sum_idx = ctx.spawn(SUM, dep_count=2, out=ctx.out_slot, nargs=0)
        ctx.take_continuation(sum_idx)
        base = ctx.row_values(sum_idx)
        ctx.set_arg(sum_idx, 0, base)
        ctx.set_arg(sum_idx, 1, base + 1)
        ctx.spawn(FIB, [n - 1], succ0=sum_idx, out=base, nargs=1)
        ctx.spawn(FIB, [n - 2], succ0=sum_idx, out=base + 1, nargs=1)


def _sum_kernel(ctx: KernelContext) -> None:
    ctx.set_out(ctx.value(ctx.arg(0)) + ctx.value(ctx.arg(1)))


def batch_of(scalar_kernel, width: int = 8) -> BatchSpec:
    """Batched same-kind spelling of a scalar task kernel: one batch round
    pops up to ``width`` same-kind descriptors and runs ``scalar_kernel``
    once per live slot through ``BatchContext.slot_ctx`` - bit-identical to
    scalar dispatch (the per-slot context shares every ref), but the per-
    descriptor ring pop + lax.switch overhead is paid once per ROUND
    instead of once per task. This is how spawn-heavy scalar families
    (fib/UTS nodes) ride the batch tier; tile families with a genuinely
    fused body (SW waves, Cholesky updrow) write their own BatchSpec."""

    def body(ctx: BatchContext) -> None:
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                scalar_kernel(ctx.slot_ctx(s))

    return BatchSpec(body, width=width)


def make_fib_megakernel(
    capacity: int = 768,  # SMEM windows pad scalars ~32B/word: ~800-row max
    interpret: Optional[bool] = None,
    num_values: Optional[int] = None,
    trace=None,
    batch_width: Optional[int] = None,
    checkpoint: Optional[bool] = None,
) -> Megakernel:
    # Descriptor rows recycle, and value blocks are row-owned (SUM reads
    # its children's results out of its own row's block), so both live
    # sets are ~ the spawn-tree depth and a small table runs arbitrarily
    # deep fibs. The value buffer must cover every row's block plus the
    # host slots.
    need = VBLOCK * capacity + 16  # 16 host slots for presets/outputs
    if num_values is None:
        num_values = need
    elif num_values < need:
        raise ValueError(
            f"fib uses row-owned value blocks: num_values must be >= "
            f"VBLOCK*capacity+16 = {need}, got {num_values}"
        )
    # batch_width routes the FIB kind through the batched same-kind tier
    # (one batch round runs up to batch_width fib bodies per-slot through
    # slot_ctx - bit-identical to scalar dispatch); SUM stays scalar: join
    # tasks become ready one at a time as their children complete, so a
    # SUM lane would fire near-empty batches for pure routing overhead.
    route = (
        {"fib": batch_of(_fib_kernel, width=batch_width)}
        if batch_width else None
    )
    return Megakernel(
        kernels=[("fib", _fib_kernel), ("sum", _sum_kernel)],
        capacity=capacity,
        num_values=num_values,
        succ_capacity=64,
        interpret=interpret,
        uses_row_values=True,
        trace=trace,
        route=route,
        checkpoint=checkpoint,
        # hclint reshard-class: fib is DELIBERATELY claimed migratable
        # on the mesh runners (forest seeds are link-free rows; the
        # exchanges' row filter keeps the spawned continuation chains
        # home) - annotate the intent so the audit shows the finding as
        # suppressed instead of flagging every forest run.
        verify_suppress=("reshard-class:fib",),
    )


def device_fib(
    n: int,
    capacity: int = 768,
    interpret: Optional[bool] = None,
    num_values: Optional[int] = None,
) -> Tuple[int, dict]:
    """Compute fib(n) entirely on-device via dynamic task spawning."""
    mk = make_fib_megakernel(capacity, interpret, num_values=num_values)
    b = TaskGraphBuilder()
    b.add(FIB, args=[n], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# ------------------------------------------------------- fib, vector tier

VFIB = 0


def make_vfib_megakernel(
    max_n: int = 32,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
    capacity: int = 64,
) -> Megakernel:
    """fib on the megakernel's batch-dispatch tier: one seed descriptor in
    the scalar table; the subtree runs wide over VPU lanes
    (device/vector_engine.py). Far larger fibs fit than on the scalar tier
    (the tree lives in per-lane VMEM stacks, not SMEM descriptor rows)."""
    from .vector_engine import fib_spec

    return Megakernel(
        kernels=[("vfib", fib_spec(max_n=max_n, lanes=lanes))],
        capacity=capacity,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
    )


def device_vfib(
    n: int,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
) -> Tuple[int, dict]:
    """Compute fib(n) via batched vector dispatch; info['executed'] counts
    the full recursion tree (2*fib(n+1) - 1 tasks)."""
    mk = make_vfib_megakernel(max_n=n + 2, lanes=lanes, interpret=interpret)
    b = TaskGraphBuilder()
    b.add(VFIB, args=[n], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# ------------------------------------------------------ UTS, scalar tier

UTS_NODE = 0


def make_uts_megakernel(
    seed: int = 19,
    q_millis: int = 440,
    m_children: int = 4,
    max_depth: int = 12,
    capacity: int = 1024,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    quiesce_stride: Optional[int] = None,
    batch_width: Optional[int] = None,
) -> Megakernel:
    """Seeded unbalanced-tree search on the scalar megakernel tier: the
    dynamic-spawn UTS-style workload (the reference's north-star tree,
    models/uts.py, reduced to the descriptor ABI) used by the checkpoint
    tests/bench to quiesce a traversal mid-tree.

    Every node task counts itself into value slot 0 and spawns child c
    (c < ``m_children``) iff ``fault_mix(seed, c, node_id, 0, depth) <
    q_millis`` - the same in-kernel integer mixer the DeviceFaultPlan
    decision tables use, so the whole tree is a pure function of the
    seed (deterministic, reproducible, unbalanced by construction). The
    root (depth 0) spawns all ``m_children`` (the b0 root factor of UTS);
    ``max_depth`` bounds the traversal. Spawned rows are link-free
    (count-accumulate only), so they are migratable on every multi-device
    runner AND re-homeable across mesh sizes by
    ``CheckpointBundle.reshard``."""

    def node(ctx: KernelContext) -> None:
        ctx.set_value(0, ctx.value(0) + 1)
        node_id = ctx.arg(0)
        depth = ctx.arg(1)

        @pl.when(depth < max_depth)
        def _():
            for c in range(m_children):
                h = fault_mix(seed, c, node_id, 0, depth)
                exists = (depth == 0) | (h < q_millis)

                @pl.when(exists)
                def _(c=c):
                    ctx.spawn(
                        UTS_NODE,
                        [node_id * 31 + jnp.int32(7 * c + 1) + depth,
                         depth + 1],
                        nargs=2,
                    )

    # batch_width: run node expansion through the batched same-kind tier
    # (the whole tree is one kind, so every round past the root fires a
    # near-full batch); rows stay link-free, so batched UTS remains
    # migratable AND reshardable - the lanes-active checkpoint workload.
    route = (
        {"uts_node": batch_of(node, width=batch_width)}
        if batch_width else None
    )
    return Megakernel(
        kernels=[("uts_node", node)],
        capacity=capacity,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        checkpoint=checkpoint,
        quiesce_stride=quiesce_stride,
        route=route,
    )


def device_uts_mk(
    seed: int = 19,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    **mk_kw,
) -> Tuple[int, dict]:
    """Run the seeded UTS tree to completion; returns (nodes, info)."""
    if mk is None:
        mk = make_uts_megakernel(seed=seed, interpret=interpret, **mk_kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# --------------------------------------------------- n-queens, vector tier

VNQUEENS = 0


def device_nqueens(
    n: int,
    lanes: Tuple[int, int] = (8, 128),
    interpret: Optional[bool] = None,
) -> Tuple[int, dict]:
    """Count n-queens solutions via batched vector dispatch;
    info['executed'] counts safe partial placements (the search tree)."""
    from .vector_engine import nqueens_spec

    mk = Megakernel(
        kernels=[("vnqueens", nqueens_spec(n, lanes=lanes))],
        capacity=64,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
    )
    b = TaskGraphBuilder()
    b.add(VNQUEENS, args=[0], out=0)
    ivalues, _, info = mk.run(b)
    return int(ivalues[0]), info


# --------------------------------------- forasync tile loops (device tier)
#
# The two acceptance workloads of the forasync device tier
# (device/forasync_tier.py): a 2D Jacobi-style 5-point stencil and a
# map-style batched-apply loop. Both are int32 so "bit-identical across
# host forasync, scalar device dispatch, and the tile tier" is airtight
# (no float summation-order caveats); inputs are bounded so no arithmetic
# wraps. Each workload ships four spellings of the SAME computation:
# the TileKernel (device, both dispatch tiers derive from it), the
# per-index host-forasync body, a vectorized numpy reference, and a data
# factory - tests/bench/CI compare the spellings instead of trusting any
# one of them.

MAP_MUL = 3
MAP_ADD = 7


def stencil_loop(H: int, W: int, th: int = 8, tw: int = 128):
    """2D Jacobi-style stencil over an (H, W) interior held in (H+2, W+2)
    halo-padded int32 grids ``gin`` -> ``gout``:

        gout[i, j] = gin[i, j] + gin[i-1, j] + gin[i+1, j]
                   + gin[i, j-1] + gin[i, j+1]      (padded coordinates)

    Returns ``(tile_kernel, bounds, tile)`` for the forasync entry
    points. Each (th, tw) tile's operand slab is the (th+2, tw+2) window
    around it - exactly the slab shape the tier's double-buffered
    prefetch pipeline moves one round early."""
    from .forasync_tier import Slab, TileKernel

    pad = jax.ShapeDtypeStruct((H + 2, W + 2), jnp.int32)

    def compute(ins):
        v = ins["vin"]
        c = v[1:-1, 1:-1]
        return {
            "vout": (
                c + v[:-2, 1:-1] + v[2:, 1:-1] + v[1:-1, :-2] + v[1:-1, 2:]
            )
        }

    tk = TileKernel(
        loads=[Slab(
            "vin", "gin",
            # Interior row i lives at padded row i+1: the slab around
            # interior rows [lo0, lo0+th) is padded rows [lo0, lo0+th+2).
            lambda a: (pl.ds(a[1], th + 2), pl.ds(a[2], tw + 2)),
            (th + 2, tw + 2),
        )],
        stores=[Slab(
            "vout", "gout",
            lambda a: (pl.ds(a[1] + 1, th), pl.ds(a[2] + 1, tw)),
            (th, tw),
        )],
        compute=compute,
        data_specs={"gin": pad, "gout": pad},
        name="fa_stencil",
    )
    return tk, [H, W], [th, tw]


def stencil_body(gin: np.ndarray, gout: np.ndarray):
    """Per-index host-forasync body over the padded numpy grids (the
    host arm of the three-way bit-identity acceptance)."""

    def body(i, j):
        gout[i + 1, j + 1] = (
            gin[i + 1, j + 1] + gin[i, j + 1] + gin[i + 2, j + 1]
            + gin[i + 1, j] + gin[i + 1, j + 2]
        )

    return body


def stencil_reference(gin: np.ndarray) -> np.ndarray:
    """Vectorized numpy oracle (padded in -> padded out, halo zero)."""
    out = np.zeros_like(gin)
    out[1:-1, 1:-1] = (
        gin[1:-1, 1:-1] + gin[:-2, 1:-1] + gin[2:, 1:-1]
        + gin[1:-1, :-2] + gin[1:-1, 2:]
    )
    return out


def stencil_data(H: int, W: int, seed: int = 0):
    """Padded (gin, gout) int32 grids; values bounded so the 5-point sum
    never wraps."""
    rng = np.random.default_rng(seed)
    gin = np.zeros((H + 2, W + 2), np.int32)
    gin[1:-1, 1:-1] = rng.integers(0, 1 << 20, size=(H, W), dtype=np.int32)
    return gin, np.zeros_like(gin)


def map_loop(T: int, th: int = 8, tw: int = 128):
    """Map-style batched-apply loop (the batched-inference shape): block
    t of the (T, th, tw) int32 input maps elementwise through
    ``x * MAP_MUL + MAP_ADD`` into the output block. The 1D loop runs
    over all T*th*tw elements with one (th*tw)-element tile per block,
    so the flat tile index IS the block index."""
    from .forasync_tier import Slab, TileKernel

    spec = jax.ShapeDtypeStruct((T, th, tw), jnp.int32)

    def compute(ins):
        return {"vout": ins["vin"] * MAP_MUL + MAP_ADD}

    tk = TileKernel(
        loads=[Slab("vin", "vin", lambda a: (a[0],), (th, tw))],
        stores=[Slab("vout", "vout", lambda a: (a[0],), (th, tw))],
        compute=compute,
        data_specs={"vin": spec, "vout": spec},
        name="fa_map",
    )
    return tk, [T * th * tw], [th * tw]


def map_body(vin: np.ndarray, vout: np.ndarray):
    """Per-index host-forasync body over flat views of the block arrays."""
    fin = vin.reshape(-1)
    fout = vout.reshape(-1)

    def body(i):
        fout[i] = fin[i] * MAP_MUL + MAP_ADD

    return body


def map_reference(vin: np.ndarray) -> np.ndarray:
    return (vin * MAP_MUL + MAP_ADD).astype(np.int32)


def map_data(T: int, th: int = 8, tw: int = 128, seed: int = 0):
    rng = np.random.default_rng(seed)
    vin = rng.integers(0, 1 << 20, size=(T, th, tw), dtype=np.int32)
    return vin, np.zeros_like(vin)


# ------------------------------------------------- R-MAT graph generator
#
# Seeded edge factory for the graph-analytics frontier tier
# (device/frontier.py): the skewed, power-law-ish degree distribution of
# the Graph500 R-MAT recursion is exactly the load shape ROADMAP
# direction 5 wants - hub vertices whose expansion floods the ready ring
# with same-kind EXPAND descriptors while the long tail trickles.


def rmat_edges(
    scale: int,
    efactor: int = 8,
    seed: int = 0,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    max_weight: int = 16,
):
    """Seeded R-MAT-style edge list over ``N = 2**scale`` vertices with
    ``efactor * N`` samples (self-loops dropped, duplicates merged, so
    the returned edge count is a bit lower). Returns ``(n, src, dst,
    weights)`` int32 arrays - weights uniform in [1, max_weight], for
    the SSSP arm. Pure function of the arguments (one seeded
    Generator), so every bench/test arm rebuilds the identical graph."""
    if scale < 1:
        raise ValueError(f"rmat scale must be >= 1, got {scale}")
    n = 1 << scale
    ne = int(efactor) * n
    rng = np.random.default_rng(seed)
    src = np.zeros(ne, np.int64)
    dst = np.zeros(ne, np.int64)
    d = 1.0 - a - b - c
    if d <= 0:
        raise ValueError(f"rmat quadrants must leave d > 0, got {d}")
    for _ in range(scale):
        sb = rng.random(ne) >= (a + b)  # src bit: lower half vs upper
        pd = np.where(sb, d / (c + d), b / (a + b))
        db = rng.random(ne) < pd
        src = (src << 1) | sb
        dst = (dst << 1) | db
    keep = src != dst
    key = np.unique(src[keep] * n + dst[keep])
    src = (key // n).astype(np.int32)
    dst = (key % n).astype(np.int32)
    w = rng.integers(1, max_weight + 1, size=len(src)).astype(np.int32)
    return n, src, dst, w


# --------------------------------------------------------------- arrayadd

ADD_TILE = 0
_TILE = (8, 128)  # f32 min tile


def _addtile_kernel(ctx: KernelContext) -> None:
    t = ctx.arg(0)
    a, b_, c = ctx.data["a"], ctx.data["b"], ctx.data["c"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    sems = ctx.scratch["sems"]
    in_a = pltpu.make_async_copy(a.at[t], va, sems.at[0])
    in_b = pltpu.make_async_copy(b_.at[t], vb, sems.at[1])
    in_a.start()
    in_b.start()
    in_a.wait()
    in_b.wait()
    va[:] = va[:] + vb[:]
    out = pltpu.make_async_copy(va, c.at[t], sems.at[2])
    out.start()
    out.wait()


def device_arrayadd(ntiles: int = 16, interpret: Optional[bool] = None):
    """c = a + b over (ntiles, 8, 128) f32 blocks, one tile task per block."""
    shape = (ntiles,) + _TILE
    spec = jax.ShapeDtypeStruct(shape, jnp.float32)
    mk = Megakernel(
        kernels=[("add_tile", _addtile_kernel)],
        data_specs={"a": spec, "b": spec, "c": spec},
        scratch_specs={
            "va": pltpu.VMEM(_TILE, jnp.float32),
            "vb": pltpu.VMEM(_TILE, jnp.float32),
            "sems": pltpu.SemaphoreType.DMA((3,)),
        },
        capacity=max(64, ntiles),
        num_values=8,
        succ_capacity=8,
        interpret=interpret,
    )
    b = TaskGraphBuilder()
    for t in range(ntiles):
        b.add(ADD_TILE, args=[t])
    rng = np.random.default_rng(0)
    a = rng.standard_normal(shape).astype(np.float32)
    bb = rng.standard_normal(shape).astype(np.float32)
    c = np.zeros(shape, dtype=np.float32)
    _, data, info = mk.run(b, data={"a": a, "b": bb, "c": c})
    return a, bb, np.asarray(data["c"]), info
