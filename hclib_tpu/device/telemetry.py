"""Live telemetry plane: on-device latency histograms + lifecycle stamps.

ISSUE 19. The serving loop (device/egress.py) made submit->result latency
the headline number, but it was measured HOST-side only (Future wall
stamps) and every device counter surfaced only after a run exited. This
module defines the device-word ABI and the host math for the live plane:

- **Timebase** - the stream's cumulative scheduler-round counter
  (``TG_ROUNDS``), incremented once per inner sched round by the
  ``round_hook`` seam of ``megakernel._make_core`` and carried across
  entries/checkpoint cuts in the echoed telemetry block. All stamps and
  histogram buckets are in these units; the host converts rounds->ns
  with the ``clockprobe.EpochBracket`` wall bracket around each entry
  (the PR 4 no-device-clock trick).

- **Per-row stamp table** ``tlat[capacity, LAT_WORDS]`` - admit round
  (copied from the ring row's TEN_ADMIT_ROUND transport word at
  install), install round, and fire round per task-table row. Dispatch
  and completion are atomic within one inner round in this core, so
  retire round == fire round; the egress publish carries the span back
  to the host via EGR_T_ADMIT / EGR_T_SPANS.

- **Histogram + gauge block** ``tele[1 + T, LAT_BUCKETS]`` - row 0 is
  the live-gauge row (``TG_*`` words: rounds, installs, retires,
  parked, backlog, entries), rows 1..T are per-tenant log2-bucketed
  latency histograms. The egress fold bumps
  ``tele[1 + tenant, bucket(retire - admit)]`` at every tracked
  retirement. Both blocks ride the ctl-echo discipline (host-seeded
  SMEM in, copied to the echo out at kernel entry, mutated in-kernel),
  so every entry boundary re-exports them and a host
  :class:`TelemetryPoller` thread can snapshot them MID-STREAM.

- **Off-path rule** - telemetry unset compiles ZERO new device words:
  no extra operands, no hooks, lowered text byte-identical
  (tests/test_telemetry.py asserts it).

The numpy functions here (:func:`bucket_of`, :func:`hist_fold_reference`)
are the EXECUTABLE SPEC of the in-kernel fold, the same role
``egress_reference`` plays for the mailbox: chaos scenarios and the
reconciliation tests drive them directly, and the in-kernel fold in
device/inject.py is written to match them word for word.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "LAT_BUCKETS",
    "LAT_ADMIT",
    "LAT_INSTALL",
    "LAT_FIRE",
    "LAT_WORDS",
    "TG_ROUNDS",
    "TG_INSTALLS",
    "TG_RETIRES",
    "TG_PARKED",
    "TG_BACKLOG",
    "TG_ENTRIES",
    "TG_WORDS",
    "bucket_of",
    "bucket_edges",
    "unpack_spans",
    "hist_fold_reference",
    "quantile_from_hist",
    "TelemetryBlock",
    "TelemetryPoller",
]

# ------------------------------------------------------------- word ABI
#
# Histogram shape: LAT_BUCKETS log2 buckets of (retire - admit) in
# scheduler rounds. Bucket 0 is [0, 2); bucket i is [2^i, 2^(i+1)) for
# 1 <= i <= LAT_BUCKETS - 2; the LAST bucket is the overflow bucket
# [2^(LAT_BUCKETS - 1), inf) - overflow is COUNTED, never dropped (the
# tracebuf overflow-counted idiom). The in-kernel fold computes the
# bucket branch-free as b = sum_k [d >= 2^k] for k in 1..LAT_BUCKETS-1,
# which lands exactly on these edges (bucket_of is the host spec).
LAT_BUCKETS = 16

# Per-row stamp words (the tlat table, one row per task-table slot).
# All in cumulative scheduler rounds; 0 = unstamped. Word 3 reserved.
LAT_ADMIT = 0    # TEN_ADMIT_ROUND of the installed ring row (host pump
                 # stamp; ring-wait time is INSIDE the measured span)
LAT_INSTALL = 1  # round the tenant poll installed the row
LAT_FIRE = 2     # round the scheduler dispatched it (== retire round)
LAT_WORDS = 4

# Live-gauge words (row 0 of the tele block). Cumulative counters are
# monotonic across entries AND checkpoint cuts (the host re-seeds the
# echoed block on resume); point-in-time gauges are refreshed every
# round by the round_hook.
TG_ROUNDS = 0    # cumulative inner scheduler rounds (the timebase)
TG_INSTALLS = 1  # cumulative ring-row installs (tracked + untracked)
TG_RETIRES = 2   # cumulative tracked retirements (== histogram mass)
TG_PARKED = 3    # point-in-time: rows in the egress park buffer
TG_BACKLOG = 4   # point-in-time: ready-ring occupancy (tail - head)
TG_ENTRIES = 5   # cumulative kernel entries (host-bumped per call)
TG_WORDS = 8     # words 6..7 reserved; row padded to LAT_BUCKETS


def bucket_of(d: int) -> int:
    """Host spec of the in-kernel bucket formula: the log2 bucket of a
    latency delta ``d`` (rounds). Negative deltas (clock-free streams
    never produce them; the kernel clamps anyway) land in bucket 0."""
    d = int(d)
    b = 0
    for k in range(1, LAT_BUCKETS):
        if d >= (1 << k):
            b += 1
    return b


def bucket_edges() -> List[Tuple[int, Optional[int]]]:
    """``[(lo, hi), ...]`` per bucket - hi exclusive, ``None`` for the
    unbounded overflow bucket."""
    edges: List[Tuple[int, Optional[int]]] = [(0, 2)]
    for k in range(1, LAT_BUCKETS - 1):
        edges.append((1 << k, 1 << (k + 1)))
    edges.append((1 << (LAT_BUCKETS - 1), None))
    return edges


def unpack_spans(admit: int, spans: int) -> Tuple[int, int, int, int]:
    """Decode EGR_T_ADMIT / EGR_T_SPANS into absolute rounds
    ``(admit, install, fire, retire)``. retire == fire by construction
    (see egress.py EGR_T_SPANS)."""
    admit = int(admit)
    spans = int(spans) & 0xFFFFFFFF
    install = admit + (spans & 0xFFFF)
    fire = install + ((spans >> 16) & 0xFFFF)
    return admit, install, fire, fire


def hist_fold_reference(
    tele: np.ndarray, retirements: Sequence[Tuple[int, int]]
) -> np.ndarray:
    """EXECUTABLE SPEC of the in-kernel egress fold: fold a sequence of
    ``(tenant, delta_rounds)`` retirements into a copy of a tele block.
    Each retirement bumps one per-tenant bucket and TG_RETIRES; deltas
    clamp at 0 exactly as the kernel does."""
    out = np.array(tele, dtype=np.int64, copy=True)
    if out.ndim != 2 or out.shape[1] != LAT_BUCKETS:
        raise ValueError(f"tele block must be (1+T, {LAT_BUCKETS}), got {out.shape}")
    for ten, d in retirements:
        ten = int(ten)
        if not (0 <= ten < out.shape[0] - 1):
            raise ValueError(f"tenant {ten} out of range for {out.shape[0] - 1} lanes")
        out[1 + ten, bucket_of(max(int(d), 0))] += 1
        out[0, TG_RETIRES] += 1
    return out


def quantile_from_hist(counts: Sequence[int], q: float) -> Optional[float]:
    """The q-quantile latency (rounds) from one histogram row: the
    UPPER edge of the bucket holding the ceil(q * total)-th sample -
    conservative, at most one log2 bucket above the exact order
    statistic. The overflow bucket has no upper edge, so it reports its
    LOWER edge (a floor: "at least this"). None on an empty histogram."""
    c = np.asarray(counts, dtype=np.int64)
    total = int(c.sum())
    if total == 0:
        return None
    q = float(q)
    if not (0.0 <= q <= 1.0):
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    rank = max(1, int(np.ceil(q * total)))
    cum = np.cumsum(c)
    b = int(np.searchsorted(cum, rank))
    lo, hi = bucket_edges()[b]
    return float(hi if hi is not None else lo)


class TelemetryBlock:
    """Host wrapper over one scraped ``tele`` block: gauge access,
    per-tenant histograms, quantiles, merge/delta arithmetic. Rows->ns
    conversion rides an optional ``ns_per_round`` (from the entry
    epoch brackets, clockprobe.EpochBracket)."""

    def __init__(self, tele: np.ndarray, ns_per_round: Optional[float] = None):
        self.tele = np.array(tele, dtype=np.int64, copy=True)
        if self.tele.ndim != 2 or self.tele.shape[1] != LAT_BUCKETS:
            raise ValueError(
                f"tele block must be (1+T, {LAT_BUCKETS}), got {self.tele.shape}"
            )
        self.ns_per_round = None if ns_per_round is None else float(ns_per_round)

    @property
    def tenants(self) -> int:
        return self.tele.shape[0] - 1

    def gauges(self) -> Dict[str, int]:
        g = self.tele[0]
        return {
            "rounds": int(g[TG_ROUNDS]),
            "installs": int(g[TG_INSTALLS]),
            "retires": int(g[TG_RETIRES]),
            "parked": int(g[TG_PARKED]),
            "backlog": int(g[TG_BACKLOG]),
            "entries": int(g[TG_ENTRIES]),
        }

    def hist(self, tenant: Optional[int] = None) -> np.ndarray:
        """One tenant's bucket counts, or the all-tenant sum."""
        if tenant is None:
            return self.tele[1:].sum(axis=0)
        return np.array(self.tele[1 + int(tenant)])

    def total(self, tenant: Optional[int] = None) -> int:
        return int(self.hist(tenant).sum())

    def quantile(self, q: float, tenant: Optional[int] = None) -> Optional[float]:
        """q-quantile in ROUNDS (see quantile_from_hist)."""
        return quantile_from_hist(self.hist(tenant), q)

    def quantile_s(self, q: float, tenant: Optional[int] = None) -> Optional[float]:
        """q-quantile in SECONDS via ns_per_round; None without a
        conversion factor or on an empty histogram."""
        if self.ns_per_round is None:
            return None
        r = self.quantile(q, tenant)
        return None if r is None else r * self.ns_per_round / 1e9

    def merge(self, other: "TelemetryBlock") -> "TelemetryBlock":
        """Element-wise sum (mesh: fold per-device blocks into one).
        Point-in-time gauges sum too - a mesh's backlog is the sum of
        its devices' backlogs."""
        if other.tele.shape != self.tele.shape:
            raise ValueError("cannot merge tele blocks of different shapes")
        return TelemetryBlock(self.tele + other.tele, self.ns_per_round)

    def delta(self, prev: "TelemetryBlock") -> "TelemetryBlock":
        """Histogram/counter advance since ``prev`` (same-stream earlier
        snapshot): the SLO estimator's windowed input."""
        if prev.tele.shape != self.tele.shape:
            raise ValueError("cannot diff tele blocks of different shapes")
        return TelemetryBlock(self.tele - prev.tele, self.ns_per_round)


class TelemetryPoller:
    """Host thread that snapshots a live stream's telemetry MID-RUN.

    ``source`` is a zero-arg callable returning a snapshot dict (the
    ``StreamingMegakernel.telemetry_snapshot`` face: ``seq``, ``tele``,
    ``rounds``, ``ns_per_round``, ...) or None before the first entry
    completes. The poller keeps every DISTINCT snapshot (seq-deduped)
    in ``snapshots`` and invokes ``on_snapshot(snap)`` for each - the
    seam the MetricsRegistry live source and the SLO estimator hang off.
    """

    def __init__(
        self,
        source: Callable[[], Optional[Dict[str, Any]]],
        interval_s: float = 0.05,
        on_snapshot: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> None:
        interval_s = float(interval_s)
        if interval_s <= 0:
            raise ValueError(f"poll interval must be > 0 seconds, got {interval_s}")
        self._source = source
        self._interval_s = interval_s
        self._on_snapshot = on_snapshot
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.snapshots: List[Dict[str, Any]] = []

    def _poll_once(self) -> bool:
        snap = self._source()
        if snap is None:
            return False
        with self._lock:
            if self.snapshots and self.snapshots[-1].get("seq") == snap.get("seq"):
                return False
            self.snapshots.append(snap)
        if self._on_snapshot is not None:
            self._on_snapshot(snap)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            self._poll_once()
            self._stop.wait(self._interval_s)

    def start(self) -> "TelemetryPoller":
        if self._thread is not None:
            raise RuntimeError("poller already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hclib-telemetry-poller", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_poll: bool = True) -> None:
        """Stop the thread; by default take one last synchronous poll so
        the stream's final state is never missed by sampling."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if final_poll:
            self._poll_once()

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.snapshots[-1] if self.snapshots else None

    def latest_block(self) -> Optional[TelemetryBlock]:
        snap = self.latest()
        if snap is None:
            return None
        return TelemetryBlock(snap["tele"], snap.get("ns_per_round"))

    def wait_for(self, n: int, timeout_s: float = 30.0) -> bool:
        """Block until ``n`` distinct snapshots exist (tests/CI smoke)."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if len(self.snapshots) >= n:
                    return True
            time.sleep(min(self._interval_s, 0.01))
        with self._lock:
            return len(self.snapshots) >= n
