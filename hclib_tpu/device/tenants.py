"""Multi-tenant streaming front door: prioritized tenant lanes with
quotas, deadline admission, and explicit backpressure.

The streaming-inject path (device/inject.py) was a single anonymous
firehose: one ring, one tail, no admission control - a greedy or
misbehaving producer could starve every other workload, and the only
host-visible failure mode was a wedge. Serving millions of users means
many concurrent injection streams, so this module splits the ingress
into **N prioritized tenant lanes**, the generalization of HClib's
signal-driven wait-sets and active-message injection (openshmem
``poll_on_waits``'s self-re-spawning poll task, openshmem-am
``async_remote``'s descriptor injection into a remote core's queue) into
a traffic-shaped, fault-isolated front door:

- **Ring regions + WRR poll** (device side, device/inject.py): the
  injection ring is partitioned into per-tenant contiguous regions, each
  with its own tail/consumed cursor in a per-tenant ``tctl`` control row.
  The in-kernel poll visits lanes weighted-round-robin INSIDE the device
  round loop - up to ``weight`` rows per lane per poll, rotating the
  start lane every round - and consumes at most the scheduler's live
  ``headroom()`` so a full task table becomes *backpressure on the ring*
  (host-visible through the consumed-cursor echo) instead of an overflow
  abort.

- **Admission** (host side, this module): every submission gets a typed
  ``Admission`` verdict - ``ACCEPTED`` (within the tenant's in-flight
  budget; publishes at the next entry), ``QUEUED`` (over budget but the
  host backlog has room), or ``REJECTED(reason)`` (rate / backlog / ring
  budget / expired / quarantined / cancelled / closed). Quotas are a
  per-tenant in-flight task budget plus an enqueue-rate ``TokenBucket``
  (injectable clock, so rate decisions are deterministic under test).
  ``submit(wait=True)`` converts rate/backlog rejections into a blocking
  wait with bounded exponential backoff.

- **Deadline admission** (resilience.CancelScope deadlines): a
  submission carries a deadline from ``deadline_s=``, the nearest
  deadline on its ``CancelScope`` chain, or the tenant's default.
  Expired at admission -> rejected on the spot; expired while queued on
  the host -> dropped at the next pump; expired while published on the
  ring -> the host marks the row's ``TEN_EXPIRED`` word and the device
  poll lazily drops it with a counted ``TenantExpired`` record
  (TR_TENANT trace tag). A tenant whose expirations exceed its
  ``deadline_budget`` gets its per-tenant CancelScope cancelled -
  siblings keep flowing.

- **Poison isolation**: a tenant whose rows keep failing their
  ``validator`` (retried per the lane's RetryPolicy) - or whose executed
  tasks the embedding runtime reports via ``report_failure`` after its
  RetryPolicy quarantined them - climbs a ladder: *throttled* (WRR
  weight clamps to 1) then *quarantined* (lane paused on device, backlog
  dropped, submissions rejected). Other tenants are untouched.

- **Survivability**: tenant identity rides the ring row itself
  (``TEN_ID``, descriptor.py), so quiesce exports per-tenant residue +
  cumulative counters (``tctl``/``tstats`` arrays in the checkpoint
  bundle), resume re-publishes them per lane, and a resident-mesh
  ``reshard(M)`` re-deals tenant-tagged residue with per-tenant counts
  conserved by construction. Deadlines survive cuts too: export stamps
  each residue row's REMAINING budget (``TEN_DEADLINE_MS``, the row's
  own transport word - never a wall-clock instant) and resume re-arms
  it against the resuming clock, so a deadline storm that straddles a
  checkpoint reconciles exactly on the other side.

- **Mesh-wide tenancy** (:class:`MeshTenantTable`): the same tenant
  roster spanning every device of a resident mesh - each device's
  injection ring is partitioned into the same per-tenant regions, one
  tctl/tstats echo block per device, and ``submit()`` ROUTES each
  admission to a device by placement/backlog while the typed Admission
  ladder stays the single-device ladder verbatim (each per-device
  replica's ``admit`` is unchanged). Rate quotas are mesh-wide (one
  aggregate token bucket per tenant, charged once before routing);
  in-flight / backlog / ring budgets are per device-lane region. The
  poison ladder and the deadline budget are enforced on AGGREGATE
  counts, so a misbehaving tenant cannot evade isolation by spreading
  its failures across devices.

Observability: per-tenant MetricsRegistry series
``tenant.<id>.accepted/rejected/expired/completed/backlog`` via
``TenantTable.metrics`` (register it as a live source), and the
TR_TENANT trace record makes per-lane install/expire traffic visible in
the Perfetto timeline (tools/timeline.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Tuple, Union,
)

import numpy as np

from ..runtime.resilience import (
    CancelScope,
    CancelledError,
    RetryPolicy,
    StallError,
    TenantExpired,
)
from .descriptor import (
    F_A0,
    F_DEP,
    F_FN,
    F_HOME,
    F_OUT,
    F_SUCC0,
    F_SUCC1,
    NO_TASK,
    NUM_ARGS,
    RING_ROW,
    TEN_ADMIT_ROUND,
    TEN_DEADLINE_MS,
    TEN_EXPIRED,
    TEN_ID,
    TEN_TOKEN,
)
from .egress import FutureTable, normalize_egress

__all__ = [
    "ADMIT_ACCEPTED",
    "ADMIT_QUEUED",
    "ADMIT_REJECTED",
    "Admission",
    "TenantExpired",  # re-export: the deadline-drop control signal
    "TokenBucket",
    "TenantSpec",
    "TenantTable",
    "MeshTenantTable",
    "build_row",
    "normalize_tenants",
    "tenants_from_env",
    "mesh_tenants_from_env",
    "normalize_mesh_tenants",
    "per_tenant_ring_counts",
    "wrr_poll_reference",
    "TC_TAIL",
    "TC_CONSUMED",
    "TC_WEIGHT",
    "TC_PAUSE",
    "TC_EXPIRED",
    "TC_INSTALLED",
    "TC_DROPPED",
]

# ---- tctl ABI: one 8-word int32 control row per tenant lane, published
# by the host at every entry and echoed back (cumulative counters are
# host-seeded so they survive entries, resumes, and reshards).
TC_TAIL = 0       # rows published into this lane's ring region
TC_CONSUMED = 1   # device consume cursor (region-relative; echo)
TC_WEIGHT = 2     # WRR credit: rows this lane may install per poll
TC_PAUSE = 3      # nonzero = poll skips the lane (throttle/quarantine)
TC_EXPIRED = 4    # cumulative rows dropped expired at the poll (echo)
TC_INSTALLED = 5  # cumulative rows installed into the scheduler (echo)
TC_DROPPED = 6    # cumulative rows swept (consumed uninstalled) while the
                  # lane was paused - quarantine/cancel/abort drains (echo)

# ---- tstats: host-side cumulative counters serialized per tenant into
# checkpoint bundles (int32 words).
TS_ACCEPTED = 0
TS_REJECTED = 1
TS_EXPIRED_HOST = 2  # expired while queued on the host (pre-publish)
TS_POISONED = 3
TS_DROPPED = 4       # backlog dropped by quarantine / cancellation
TS_THROTTLED = 5
TS_QUARANTINED = 6

ADMIT_ACCEPTED = "ACCEPTED"
ADMIT_QUEUED = "QUEUED"
ADMIT_REJECTED = "REJECTED"


class Admission:
    """The typed verdict of one ``submit``: status, tenant, and - for
    rejections - a machine-readable reason (``rate`` | ``backlog`` |
    ``ring`` | ``expired`` | ``quarantined`` | ``cancelled`` |
    ``closed``). Truthy iff the row was admitted (accepted OR queued).
    Mesh-routed admissions (:class:`MeshTenantTable`) additionally carry
    ``device`` - the flat device id the row was routed to - and
    egress-enabled tables attach ``future`` (device/egress.py), the
    typed handle whose ``result(timeout=)`` rides the completion
    mailbox; rejections carry ``future=None``."""

    __slots__ = ("status", "tenant", "reason", "index", "device",
                 "future")

    def __init__(self, status: str, tenant: str,
                 reason: Optional[str] = None,
                 index: Optional[int] = None,
                 device: Optional[int] = None,
                 future=None) -> None:
        self.status = status
        self.tenant = tenant
        self.reason = reason
        self.index = index  # per-tenant admission sequence number
        self.device = device  # mesh routing target (MeshTenantTable)
        self.future = future  # egress-enabled tables only

    def __bool__(self) -> bool:
        return self.status != ADMIT_REJECTED

    @property
    def accepted(self) -> bool:
        return self.status == ADMIT_ACCEPTED

    @property
    def queued(self) -> bool:
        return self.status == ADMIT_QUEUED

    @property
    def rejected(self) -> bool:
        return self.status == ADMIT_REJECTED

    def __repr__(self) -> str:
        r = f", reason={self.reason!r}" if self.reason else ""
        return f"Admission({self.status}, tenant={self.tenant!r}{r})"


class TokenBucket:
    """Enqueue-rate quota: ``rate`` tokens/second up to ``burst``. The
    clock is injectable (``clock=`` any monotonic float callable), so a
    fake clock makes refill - and therefore every admission decision -
    a pure function of the submission sequence (asserted in
    tests/test_tenants.py). Not thread-safe by itself; the owning
    TenantTable serializes access under its lock."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if rate < 0 or burst <= 0:
            raise ValueError(f"bad token bucket rate={rate} burst={burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()

    def _refill(self) -> None:
        now = self._clock()
        if now > self._t:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
        self._t = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def wait_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0 = now)."""
        self._refill()
        if self._tokens >= n:
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (n - self._tokens) / self.rate


class TenantSpec:
    """One tenant lane's contract.

    - ``weight``: WRR priority - rows the device poll may install per
      visit (relative throughput under contention is weight-proportional).
    - ``rate``/``burst``: host enqueue-rate token bucket (None = no rate
      quota; burst defaults to ``max(8, weight * 8)``).
    - ``max_in_flight``: cap on published-but-unconsumed rows (None = the
      lane's whole ring region).
    - ``queue_capacity``: host backlog bound - past it submissions are
      REJECTED("backlog"), the explicit form of backpressure.
    - ``deadline_s``: default admission deadline per submission (None =
      no deadline unless the submit or its CancelScope carries one).
    - ``deadline_budget``: total expirations (host + device) after which
      the lane's CancelScope cancels - the tenant is misconfigured or
      drowning, stop accepting instead of burning ring slots.
    - ``poison_throttle``/``poison_quarantine``: ladder thresholds on
      terminal task failures (validator exhaustion or
      ``report_failure``): throttled (weight -> 1), then quarantined.
    - ``retry``: RetryPolicy for validator attempts (attempts are
      immediate - the pump must not stall sibling lanes on backoff
      sleeps); None = one attempt.
    - ``validator``: optional host-side admission-time check run at
      publish (the hook chaos uses to model a poison tenant).
    """

    def __init__(
        self,
        id: str,
        weight: int = 1,
        rate: Optional[float] = None,
        burst: Optional[float] = None,
        max_in_flight: Optional[int] = None,
        queue_capacity: int = 1024,
        deadline_s: Optional[float] = None,
        deadline_budget: Optional[int] = None,
        poison_throttle: int = 2,
        poison_quarantine: int = 4,
        retry: Optional[RetryPolicy] = None,
        validator: Optional[Callable[[np.ndarray], None]] = None,
    ) -> None:
        self.id = str(id)
        self.weight = int(weight)
        if self.weight < 1:
            raise ValueError(f"tenant {id!r}: weight must be >= 1")
        self.rate = None if rate is None else float(rate)
        if burst is None:
            burst = max(8.0, self.weight * 8.0)
        self.burst = float(burst)
        self.max_in_flight = (
            None if max_in_flight is None else int(max_in_flight)
        )
        if self.max_in_flight is not None and self.max_in_flight < 1:
            raise ValueError(f"tenant {id!r}: max_in_flight must be >= 1")
        self.queue_capacity = int(queue_capacity)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.deadline_budget = (
            None if deadline_budget is None else int(deadline_budget)
        )
        self.poison_throttle = int(poison_throttle)
        self.poison_quarantine = int(poison_quarantine)
        if not (1 <= self.poison_throttle <= self.poison_quarantine):
            raise ValueError(
                f"tenant {id!r}: need 1 <= poison_throttle <= "
                "poison_quarantine"
            )
        self.retry = retry
        self.validator = validator


def build_row(fn: int, args: Sequence[int] = (), out: int = 0,
              succ0: int = NO_TASK, succ1: int = NO_TASK) -> np.ndarray:
    """One injection-ring row (RING_ROW int32 words) in the descriptor
    ABI; tenant metadata words are stamped by the admitting lane.
    Injected rows are dependency-free by construction (the inject()
    contract: nothing could ever decrement a dependent ring row)."""
    if len(args) > NUM_ARGS:
        raise ValueError(f"at most {NUM_ARGS} args per descriptor")
    row = np.zeros(RING_ROW, np.int32)
    row[F_FN] = int(fn)
    row[F_DEP] = 0
    row[F_SUCC0] = int(succ0)
    row[F_SUCC1] = int(succ1)
    for i, a in enumerate(args):
        row[F_A0 + i] = int(a)
    row[F_OUT] = int(out)
    row[F_HOME] = NO_TASK
    return row


class _Pending:
    """One admitted row in flight on the host side."""

    __slots__ = ("row", "deadline_at", "t_submit", "index", "marked",
                 "token")

    def __init__(self, row: np.ndarray, deadline_at: Optional[float],
                 t_submit: float) -> None:
        self.row = row
        self.deadline_at = deadline_at
        self.t_submit = t_submit
        self.index = -1     # region-relative publish index (once published)
        self.marked = False  # host marked TEN_EXPIRED on the ring
        # Submit token of a tracked request (rides the row's TEN_TOKEN
        # word; 0 = untracked). Zeroed once its future reached a
        # terminal state host-side, so each token resolves exactly once.
        self.token = int(row[TEN_TOKEN])


def _remaining_ms(deadline_at: Optional[float], now: float) -> int:
    """A live deadline's remaining budget as a TEN_DEADLINE_MS word:
    whole milliseconds, floored at 1 (a nonzero deadline must never
    round down to "no deadline"), clamped to int32."""
    if deadline_at is None:
        return 0
    return max(1, min(2**31 - 1, int((deadline_at - now) * 1000.0)))


def _readmit_pending(row: np.ndarray, now: float) -> "_Pending":
    """Rebuild a residue row's host-side pending record at resume: the
    stamped TEN_DEADLINE_MS remaining budget re-arms against the
    resuming clock, and the transport word is cleared so the republished
    ring row is identical to a freshly admitted one."""
    r = np.array(row, np.int32)
    ms = int(r[TEN_DEADLINE_MS])
    r[TEN_DEADLINE_MS] = 0
    deadline_at = (now + ms / 1000.0) if ms > 0 else None
    return _Pending(r, deadline_at, now)


class _Lane:
    __slots__ = (
        "spec", "idx", "scope", "bucket", "queue", "pub_meta",
        "published", "consumed", "dev_expired", "dev_dropped", "installed",
        "accepted", "rejected", "expired_host", "poisoned", "dropped",
        "throttled", "quarantined", "latencies",
    )

    def __init__(self, spec: TenantSpec, idx: int, parent_scope,
                 clock) -> None:
        self.spec = spec
        self.idx = idx
        self.scope = CancelScope(parent=parent_scope)
        self.bucket = (
            None if spec.rate is None
            else TokenBucket(spec.rate, spec.burst, clock)
        )
        self.queue: deque = deque()
        self.pub_meta: deque = deque()
        self.published = 0
        self.consumed = 0
        self.dev_expired = 0
        self.dev_dropped = 0
        self.installed = 0
        self.accepted = 0
        self.rejected = 0
        self.expired_host = 0
        self.poisoned = 0
        self.dropped = 0
        self.throttled = False
        self.quarantined: Optional[str] = None
        self.latencies: deque = deque(maxlen=2048)

    @property
    def in_flight(self) -> int:
        return self.published - self.consumed

    @property
    def backlog(self) -> int:
        return len(self.queue) + self.in_flight

    @property
    def expired(self) -> int:
        return self.expired_host + self.dev_expired

    def paused(self) -> bool:
        return self.quarantined is not None or self.scope.cancelled()


class TenantTable:
    """The host half of the front door: N lanes over one injection ring
    partitioned into ``region_rows``-row regions (lane i owns ring rows
    ``[i * region_rows, (i + 1) * region_rows)``). Thread-safe: any
    thread admits while the stream driver pumps/absorbs."""

    def __init__(self, specs: Sequence[TenantSpec], region_rows: int,
                 clock: Callable[[], float] = time.monotonic,
                 egress=None,
                 futures: Optional[FutureTable] = None) -> None:
        specs = list(specs)
        if not specs:
            raise ValueError("at least one tenant lane")
        ids = [s.id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate tenant ids: {ids}")
        if region_rows < 8 or region_rows % 8:
            raise ValueError(
                f"region_rows must be a positive multiple of 8 (the poll "
                f"fetches 8-row DMA chunks), got {region_rows}"
            )
        self.region_rows = int(region_rows)
        self.clock = clock
        self.scope = CancelScope()
        self._lock = threading.Lock()
        # Set under the lock by export_state (quiesce cut) and
        # close_if_drained (normal drain exit): a submit racing either
        # stream exit lands before it (its row rides along in the
        # residue / next pump) or sees this flag and gets a clean
        # "closed" verdict - never an ACCEPTED row that silently never
        # runs. resume_from reopens.
        self._closed = False
        # Telemetry admit-round stamp (ISSUE 19, device/telemetry.py):
        # the stream driver feeds back the last echoed cumulative round
        # gauge and the next pump stamps it onto newly published rows'
        # TEN_ADMIT_ROUND word. 0 = telemetry off / first entry.
        self._admit_round = 0
        self._lanes: List[_Lane] = [
            _Lane(s, i, self.scope, clock) for i, s in enumerate(specs)
        ]
        self._by_id: Dict[str, _Lane] = {
            lane.spec.id: lane for lane in self._lanes
        }
        # Completion-mailbox egress (device/egress.py): ``futures=``
        # shares an existing FutureTable (mesh replica tables all feed
        # the MeshTenantTable's one ledger); otherwise an egress spec -
        # explicit or HCLIB_TPU_EGRESS_DEPTH - makes this table OWN one.
        # ``self.futures is None`` on non-serving tables: admit then
        # stamps TEN_TOKEN = 0 and attaches no future, so every
        # pre-egress call site behaves bit-identically.
        self.egress = normalize_egress(egress)
        if futures is not None:
            self.futures: Optional[FutureTable] = futures
            self._owns_futures = False
        elif self.egress is not None:
            self.futures = FutureTable(
                backoff_s=self.egress.backoff_s, clock=clock
            )
            self._owns_futures = True
        else:
            self.futures = None
            self._owns_futures = False

    # ---- lookups ----

    def __len__(self) -> int:
        return len(self._lanes)

    @property
    def ids(self) -> List[str]:
        return [lane.spec.id for lane in self._lanes]

    @property
    def specs(self) -> List[TenantSpec]:
        return [lane.spec for lane in self._lanes]

    def protocol_model(self, rows_per_lane: int = 2,
                       capacity: int = 3, quiesce: bool = True):
        """Seed the bounded-interleaving explorer with THIS table's
        lane roster (analysis/explore.py): ``rows_per_lane`` published
        rows per lane at the table's real WRR weights, scheduler
        headroom ``capacity``, and (by default) a mid-stream quiesce
        action - so hclint explores every schedule of the poll this
        roster will actually run, against the same executable spec
        (``wrr_poll_reference``) the fairness tests pin."""
        from ..analysis.explore import InjectQuiesceModel

        return InjectQuiesceModel(
            [(int(rows_per_lane), lane.spec.weight)
             for lane in self._lanes],
            capacity=int(capacity),
            quiesce=bool(quiesce),
            region_rows=max(int(rows_per_lane), 8),
        )

    def _lane(self, tenant: Union[str, int]) -> _Lane:
        if isinstance(tenant, int):
            if not 0 <= tenant < len(self._lanes):
                # No negative wrap-around: an off-by-one producer must
                # not silently charge the LAST tenant's quota.
                raise KeyError(f"no tenant lane {tenant}")
            return self._lanes[tenant]
        lane = self._by_id.get(str(tenant))
        if lane is None:
            raise KeyError(
                f"unknown tenant {tenant!r} (have {self.ids})"
            )
        return lane

    # ---- admission (any thread) ----

    def resolve_deadline(self, tenant: Union[str, int],
                         deadline_s: Optional[float],
                         cancel_scope: Optional[CancelScope]) -> (
                             Optional[float]):
        """The absolute admission deadline for one submit: explicit
        ``deadline_s`` wins, else the nearest CancelScope deadline, else
        the tenant's default ``deadline_s``."""
        lane = self._lane(tenant)
        now = self.clock()
        if deadline_s is not None:
            return now + float(deadline_s)
        if cancel_scope is not None:
            t = cancel_scope.effective_deadline()
            if t is not None:
                # Scope deadlines are absolute instants in the TABLE'S
                # clock domain: with the default clock that is what
                # set_deadline(seconds=) produces; with an injected
                # clock, arm scopes via set_deadline(at=table.clock()+s)
                # (a raw monotonic instant would never compare sanely
                # against a fake clock - the deterministic tests use the
                # at= spelling for exactly this reason).
                return t
        if lane.spec.deadline_s is not None:
            return now + lane.spec.deadline_s
        return None

    def admit(self, tenant: Union[str, int], row: np.ndarray,
              deadline_at: Optional[float] = None,
              cancel_scope: Optional[CancelScope] = None,
              record_reject: bool = True) -> Admission:
        """Non-blocking admission of one prepared ring row. Checks run
        cheapest-first and quota checks only consume a rate token when
        every cheaper gate already passed."""
        lane = self._lane(tenant)
        tid = lane.spec.id
        now = self.clock()

        def reject(reason: str) -> Admission:
            if record_reject:
                with self._lock:
                    lane.rejected += 1
            return Admission(ADMIT_REJECTED, tid, reason)

        if lane.quarantined is not None:
            return reject("quarantined")
        if lane.scope.cancelled() or (
            cancel_scope is not None and cancel_scope.cancelled()
        ):
            return reject("cancelled")
        if deadline_at is not None and now >= deadline_at:
            return reject("expired")
        with self._lock:
            if self._closed:
                lane.rejected += record_reject
                return Admission(ADMIT_REJECTED, tid, "closed")
            # Ring lifetime budget: the region is a linear append log per
            # stream (device/inject.py), so published + queued rows may
            # never exceed it - rejecting here keeps QUEUED an eventual-
            # service promise instead of a silent wedge.
            if lane.published + len(lane.queue) >= self.region_rows:
                lane.rejected += record_reject
                return Admission(ADMIT_REJECTED, tid, "ring")
            if len(lane.queue) >= lane.spec.queue_capacity:
                lane.rejected += record_reject
                return Admission(ADMIT_REJECTED, tid, "backlog")
            if lane.bucket is not None and not lane.bucket.try_take(1):
                lane.rejected += record_reject
                return Admission(ADMIT_REJECTED, tid, "rate")
            over = (
                lane.spec.max_in_flight is not None
                and lane.backlog >= lane.spec.max_in_flight
            )
            r = np.array(row, np.int32).reshape(RING_ROW)
            r[TEN_ID] = lane.idx
            r[TEN_EXPIRED] = 0
            r[TEN_DEADLINE_MS] = 0  # stamped only at checkpoint export
            fut = None
            if self.futures is not None:
                # Token minted only after every admission gate passed:
                # a rejected submit never enters the conservation ledger.
                fut = self.futures.create(
                    tid, int(r[F_FN]), int(r[F_OUT])
                )
                r[TEN_TOKEN] = fut.token
            else:
                r[TEN_TOKEN] = 0
            lane.queue.append(_Pending(r, deadline_at, now))
            lane.accepted += 1
            return Admission(
                ADMIT_QUEUED if over else ADMIT_ACCEPTED, tid,
                index=lane.accepted - 1, future=fut,
            )

    def record_reject(self, tenant: Union[str, int], reason: str) -> (
            Admission):
        """Count a terminal rejection decided by an outer wait loop
        (submit(wait=True) probes with record_reject=False)."""
        lane = self._lane(tenant)
        with self._lock:
            lane.rejected += 1
        return Admission(ADMIT_REJECTED, lane.spec.id, reason)

    def submit(self, tenant: Union[str, int], fn: int,
               args: Sequence[int] = (), out: int = 0,
               succ0: int = NO_TASK, succ1: int = NO_TASK,
               deadline_s: Optional[float] = None,
               cancel_scope: Optional[CancelScope] = None) -> Admission:
        """Build, deadline-resolve, and admit one request in a single
        call - the serving-loop face (mirrors MeshTenantTable.submit).
        On an egress-enabled table the returned Admission carries
        ``.future``, whose ``result(timeout=)`` rides the completion
        mailbox to exactly one terminal rung of the degradation ladder:
        RESULT | EXPIRED | POISONED | PREEMPTED(resume_token)."""
        row = build_row(fn, args, out, succ0, succ1)
        deadline_at = self.resolve_deadline(tenant, deadline_s,
                                            cancel_scope)
        return self.admit(tenant, row, deadline_at, cancel_scope)

    def reattach(self, resume_token):
        """Re-attach a PREEMPTED future across a checkpoint cut: feed
        the ``resume_token`` a FuturePreempted carried to the successor
        table and get a fresh Future bound to the same in-flight
        request (its token rode the residue row / etok export)."""
        if self.futures is None:
            raise ValueError(
                "reattach needs an egress-enabled table (pass egress= "
                "or set HCLIB_TPU_EGRESS_DEPTH)"
            )
        return self.futures.reattach(resume_token)

    # ---- future-ledger plumbing (all called with the lock held) ----

    def _expire_token_locked(self, p: _Pending, reason: str) -> None:
        if self.futures is not None and p.token:
            self.futures.expire(p.token, reason)
        p.token = 0

    def _poison_queue_locked(self, lane: _Lane, reason: str) -> None:
        """Resolve the futures of every host-queued row the caller is
        about to drop (quarantine / cancel / deadline-budget drains):
        POISONED, never a hang - the ladder's no-wedge rung."""
        if self.futures is None:
            return
        for p in lane.queue:
            if p.token:
                self.futures.poison(p.token, reason)
                p.token = 0

    # ---- failure reporting / isolation ----

    def _note_poison_locked(self, lane: _Lane) -> None:
        lane.poisoned += 1
        if lane.poisoned >= lane.spec.poison_quarantine:
            self._quarantine_locked(
                lane,
                f"poison quarantine ({lane.poisoned} terminal failures)",
            )
        elif lane.poisoned >= lane.spec.poison_throttle:
            lane.throttled = True

    def _quarantine_locked(self, lane: _Lane, reason: str) -> None:
        if lane.quarantined is None:
            lane.quarantined = reason
        self._poison_queue_locked(lane, f"quarantined: {reason}")
        lane.dropped += len(lane.queue)
        lane.queue.clear()

    def report_failure(self, tenant: Union[str, int],
                       exc: Optional[BaseException] = None) -> None:
        """Tell the front door a task attributed to ``tenant`` failed
        TERMINALLY (its RetryPolicy exhausted attempts and quarantined
        the task). Climbs the poison ladder: throttle, then quarantine.
        Cancellation is a control signal, never poison."""
        if isinstance(exc, CancelledError):
            return
        lane = self._lane(tenant)
        with self._lock:
            self._note_poison_locked(lane)

    def quarantine(self, tenant: Union[str, int], reason: str) -> None:
        lane = self._lane(tenant)
        with self._lock:
            self._quarantine_locked(lane, reason)

    def throttle(self, tenant: Union[str, int]) -> None:
        """Clamp the lane's WRR weight to 1 at the next entry (the
        ladder's first rung, applied externally - the mesh front door's
        aggregate poison enforcement uses it on every replica)."""
        lane = self._lane(tenant)
        with self._lock:
            lane.throttled = True

    def cancel(self, tenant: Union[str, int],
               reason: str = "tenant cancelled") -> None:
        """Per-tenant cancellation: the lane's CancelScope cancels (its
        siblings' scopes are untouched), the host backlog drops, and the
        device poll pauses the lane at the next entry. Published rows
        already consumed stay consumed - cancellation is prospective."""
        lane = self._lane(tenant)
        lane.scope.cancel(reason)
        with self._lock:
            self._poison_queue_locked(lane, f"cancelled: {reason}")
            lane.dropped += len(lane.queue)
            lane.queue.clear()

    # ---- the stream driver's half (pump before entry, absorb after) ----

    def set_admit_round(self, r: int) -> None:
        """Telemetry (ISSUE 19): record the stream's last echoed
        cumulative round gauge; the next :meth:`pump` stamps it onto
        newly published rows' TEN_ADMIT_ROUND word (never overwriting a
        nonzero stamp - resumed residue keeps its original admission)."""
        with self._lock:
            self._admit_round = int(r)

    def pump(self, ring: np.ndarray) -> np.ndarray:
        """Expire, publish, and build the tctl block for one entry:
        drops expired host-queued rows, marks expired published rows for
        the device poll to drop, publishes backlog into each lane's ring
        region up to its in-flight budget, and returns the (T, 8) tctl
        array the entry uploads."""
        now = self.clock()
        T = len(self._lanes)
        tctl = np.zeros((T, 8), np.int32)
        with self._lock:
            for lane in self._lanes:
                base = lane.idx * self.region_rows
                spec = lane.spec
                if lane.paused() and lane.queue:
                    self._poison_queue_locked(
                        lane, lane.quarantined or "cancelled scope"
                    )
                    lane.dropped += len(lane.queue)
                    lane.queue.clear()
                # Deadline budget: too many expirations cancels the lane
                # (checked before publishing so a storm cuts off fast).
                if (
                    spec.deadline_budget is not None
                    and lane.expired >= spec.deadline_budget
                    and not lane.scope.cancelled()
                ):
                    lane.scope.cancel(
                        f"tenant {spec.id}: deadline budget exhausted "
                        f"({lane.expired} expired >= "
                        f"{spec.deadline_budget})"
                    )
                    self._poison_queue_locked(
                        lane, "deadline budget exhausted"
                    )
                    lane.dropped += len(lane.queue)
                    lane.queue.clear()
                # Expire published-but-unconsumed rows: mark the ring row
                # so the device poll drops it (lazily, counted).
                for p in lane.pub_meta:
                    if (
                        not p.marked
                        and p.deadline_at is not None
                        and now >= p.deadline_at
                    ):
                        ring[base + p.index, TEN_EXPIRED] = 1
                        p.marked = True
                        # The client learns EXPIRED the moment the host
                        # knows, not when the device sweeps the row.
                        self._expire_token_locked(p, "deadline (on ring)")
                # Publish backlog into the region, respecting the
                # in-flight budget (budget freed as the consume cursor
                # echoes forward).
                cap = (
                    self.region_rows if spec.max_in_flight is None
                    else spec.max_in_flight
                )
                while (
                    lane.queue
                    and lane.published < self.region_rows
                    and lane.in_flight < cap
                    and not lane.paused()
                ):
                    p = lane.queue.popleft()
                    if p.deadline_at is not None and now >= p.deadline_at:
                        lane.expired_host += 1
                        self._expire_token_locked(p, "deadline (queued)")
                        continue
                    if spec.validator is not None and not self._validate(
                        lane, p
                    ):
                        continue
                    ring[base + lane.published] = p.row
                    # Telemetry admit stamp - PRESERVE a nonzero word:
                    # residue re-published after a checkpoint cut keeps
                    # its ORIGINAL admission round (the round gauge is
                    # cumulative across the cut), so measured latency
                    # spans the preemption, not just the resumed tail.
                    if (
                        self._admit_round
                        and ring[base + lane.published,
                                 TEN_ADMIT_ROUND] == 0
                    ):
                        ring[base + lane.published, TEN_ADMIT_ROUND] = (
                            self._admit_round
                        )
                    p.index = lane.published
                    lane.pub_meta.append(p)
                    lane.published += 1
                tctl[lane.idx, TC_TAIL] = lane.published
                tctl[lane.idx, TC_CONSUMED] = lane.consumed
                tctl[lane.idx, TC_WEIGHT] = (
                    1 if lane.throttled else spec.weight
                )
                tctl[lane.idx, TC_PAUSE] = 1 if lane.paused() else 0
                tctl[lane.idx, TC_EXPIRED] = lane.dev_expired
                tctl[lane.idx, TC_INSTALLED] = lane.installed
        return tctl

    def _validate(self, lane: _Lane, p: _Pending) -> bool:
        """Run the lane's validator with IMMEDIATE retries per its
        RetryPolicy; a terminal failure poisons (ladder) and drops the
        row. Returns True when the row may publish. Lock is held - the
        validator must be fast and must not call back into the table."""
        spec = lane.spec
        attempts = spec.retry.max_attempts if spec.retry else 1
        for attempt in range(attempts):
            try:
                spec.validator(p.row)
                return True
            except BaseException as e:  # noqa: BLE001 - policy decides
                if spec.retry is not None and spec.retry.should_retry(
                    attempt, e
                ):
                    continue
                if isinstance(e, (CancelledError, StallError)):
                    # Control signals drop the row without poisoning
                    # the LANE; its future still resolves POISONED (the
                    # request will never run - a hang would be worse).
                    lane.dropped += 1
                    if self.futures is not None and p.token:
                        self.futures.poison(
                            p.token, "cancelled in validation"
                        )
                        p.token = 0
                    return False
                # The poisoned row IS a dropped row: counting it keeps
                # accepted == completed + expired + dropped reconciling
                # exactly for validator-poisoned lanes too (the storm
                # soak's per-cut identity).
                lane.dropped += 1
                if self.futures is not None and p.token:
                    self.futures.poison(p.token, f"validator: {e!r}")
                    p.token = 0
                self._note_poison_locked(lane)
                return False
        return False

    def absorb(self, tctl_out: np.ndarray) -> None:
        """Fold one entry's tctl echo back into the lanes: advance the
        consume cursors, record admission-to-install latencies, and
        refresh the cumulative device counters. A paused lane's consume
        advance is the device SWEEP (quarantine/cancel drain): those
        rows count as dropped, never as install latencies."""
        now = self.clock()
        tctl_out = np.asarray(tctl_out)
        with self._lock:
            for lane in self._lanes:
                swept = int(tctl_out[lane.idx, TC_PAUSE]) != 0
                new_consumed = int(tctl_out[lane.idx, TC_CONSUMED])
                while lane.pub_meta and lane.pub_meta[0].index < (
                    new_consumed
                ):
                    p = lane.pub_meta.popleft()
                    if not p.marked and not swept:
                        lane.latencies.append(now - p.t_submit)
                    elif swept and self.futures is not None and p.token:
                        # Device SWEEP of a paused lane: the row was
                        # consumed without installing - resolve its
                        # future POISONED so no client waits on it.
                        self.futures.poison(p.token, "swept (lane paused)")
                        p.token = 0
                lane.consumed = new_consumed
                lane.dev_expired = int(tctl_out[lane.idx, TC_EXPIRED])
                lane.installed = int(tctl_out[lane.idx, TC_INSTALLED])
                # TC_DROPPED is per-entry (pump seeds it 0): fold the
                # sweep count into the host's cumulative dropped so
                # accepted == completed + expired + dropped still holds
                # for quarantined/cancelled lanes.
                d = int(tctl_out[lane.idx, TC_DROPPED])
                lane.dev_dropped += d
                lane.dropped += d

    def total_published(self) -> int:
        with self._lock:
            return sum(lane.published for lane in self._lanes)

    def _drained_locked(self) -> bool:
        return all(
            not lane.queue and lane.consumed == lane.published
            for lane in self._lanes
        )

    def drained(self) -> bool:
        """Every lane's backlog is empty and its region fully consumed
        (paused lanes count as drained for their *unpublished* side -
        a quarantined tenant must not wedge the stream exit)."""
        with self._lock:
            return self._drained_locked()

    def close_if_drained(self) -> bool:
        """The stream driver's final-exit check: atomically verify every
        lane is drained AND close the front door. A submit racing the
        drain exit either lands first (the drained check fails and the
        driver pumps it next entry) or gets a "closed" verdict - it can
        never get an ACCEPTED for a row the returned stream will not
        run."""
        with self._lock:
            if self._drained_locked():
                self._closed = True
                return True
            return False

    # ---- checkpoint / resume ----

    def export_state(self, ring: np.ndarray) -> Dict[str, np.ndarray]:
        """The per-tenant half of a quiesce export: residue rows (host
        backlog + published-but-unconsumed, tenant-tagged; rows already
        expired - host-marked on the ring OR past their deadline at the
        cut - are folded into the expired count rather than carried),
        plus the cumulative tctl/tstats counter blocks. Deadlines
        SURVIVE the cut as remaining budget: each live residue row is
        stamped with ``TEN_DEADLINE_MS`` (milliseconds left at export;
        0 = no deadline) and ``resume_from`` re-arms it against the
        resuming clock."""
        T = len(self._lanes)
        now = self.clock()
        rows: List[np.ndarray] = []
        tctl = np.zeros((T, 8), np.int32)
        tstats = np.zeros((T, 8), np.int32)

        def carry(lane: _Lane, p: _Pending, row: np.ndarray) -> None:
            if p.marked or (
                p.deadline_at is not None and now >= p.deadline_at
            ):
                # Doomed either way; count it now so the conservation
                # identity holds across the cut.
                lane.expired_host += 1
                self._expire_token_locked(p, "deadline (at export)")
                return
            r = np.array(row, np.int32)
            r[TEN_DEADLINE_MS] = _remaining_ms(p.deadline_at, now)
            rows.append(r)

        with self._lock:
            self._closed = True
            for lane in self._lanes:
                base = lane.idx * self.region_rows
                for p in lane.pub_meta:
                    carry(lane, p, ring[base + p.index])
                lane.pub_meta.clear()
                for p in lane.queue:
                    carry(lane, p, p.row)
                lane.queue.clear()
                lane.published = 0
                lane.consumed = 0
                tctl[lane.idx, TC_WEIGHT] = lane.spec.weight
                tctl[lane.idx, TC_PAUSE] = 1 if lane.paused() else 0
                tctl[lane.idx, TC_EXPIRED] = lane.dev_expired
                tctl[lane.idx, TC_INSTALLED] = lane.installed
                tstats[lane.idx, TS_ACCEPTED] = lane.accepted
                tstats[lane.idx, TS_REJECTED] = lane.rejected
                tstats[lane.idx, TS_EXPIRED_HOST] = lane.expired_host
                tstats[lane.idx, TS_POISONED] = lane.poisoned
                tstats[lane.idx, TS_DROPPED] = lane.dropped
                tstats[lane.idx, TS_THROTTLED] = int(lane.throttled)
                tstats[lane.idx, TS_QUARANTINED] = int(
                    lane.quarantined is not None
                )
        ring_rows = (
            np.stack(rows).astype(np.int32)
            if rows else np.zeros((0, RING_ROW), np.int32)
        )
        # Everything still pending at the cut - carried residue AND
        # installed-but-unretired tasks - is preempted: each live future
        # resolves PREEMPTED carrying a resume token the client feeds to
        # the successor table's reattach(). Only the table that OWNS its
        # FutureTable preempts; mesh replicas share the mesh ledger and
        # the MeshTenantTable preempts once after every replica export.
        if self.futures is not None and self._owns_futures:
            self.futures.preempt_all()
        # tenant_ids rides the in-memory state dict so the direct
        # run_stream(resume_state=) path can validate the roster the
        # same way checkpoint.restore_stream's manifest guard does
        # (CheckpointBundle ignores keys outside its schema, so the
        # bundle path keeps using its manifest check).
        return {"ring_rows": ring_rows, "tctl": tctl, "tstats": tstats,
                "tenant_ids": np.array(self.ids)}

    def resume_from(self, state: Dict[str, Any]) -> None:
        """Seed the lanes from a checkpointed state: cumulative counters
        restore from tctl/tstats and residue rows re-enter their lanes'
        host backlogs (re-published by the next pump from region slot 0,
        so per-tenant accepted/completed/expired/backlog counts are
        conserved exactly across the cut). Rows carrying a stamped
        ``TEN_DEADLINE_MS`` remaining budget re-arm their deadlines
        against THIS table's clock."""
        if "tctl" not in state or "tstats" not in state:
            # A plain stream's quiesce state has ring_rows but no lane
            # blocks: adopting it would misfile every residue row (all
            # TEN_ID words are 0) into lane 0's budget and quotas.
            raise ValueError(
                "resume state carries no per-tenant counter blocks "
                "(tctl/tstats): it was exported from a stream without "
                "tenant lanes and cannot resume on a tenant-enabled one"
            )
        tctl = np.asarray(state["tctl"])
        tstats = np.asarray(state["tstats"])
        if tctl.shape[0] != len(self._lanes):
            raise ValueError(
                f"resume state carries {tctl.shape[0]} tenant lanes, this "
                f"stream has {len(self._lanes)}"
            )
        ids = state.get("tenant_ids")
        if ids is not None:
            want = [str(x) for x in np.asarray(ids).tolist()]
            if want != self.ids:
                # Residue rows and the tctl/tstats blocks are keyed by
                # lane index: a same-count reordered roster would
                # silently credit one tenant's work and quotas to
                # another.
                raise ValueError(
                    f"tenant roster mismatch: resume state carries "
                    f"{want!r}, this stream has {self.ids!r} (ids and "
                    f"order must match - lane state is keyed by index)"
                )
        now = self.clock()
        with self._lock:
            self._closed = False
            for lane in self._lanes:
                i = lane.idx
                lane.queue.clear()
                lane.pub_meta.clear()
                lane.published = 0
                lane.consumed = 0
                lane.dev_expired = int(tctl[i, TC_EXPIRED])
                lane.installed = int(tctl[i, TC_INSTALLED])
                lane.accepted = int(tstats[i, TS_ACCEPTED])
                lane.rejected = int(tstats[i, TS_REJECTED])
                lane.expired_host = int(tstats[i, TS_EXPIRED_HOST])
                lane.poisoned = int(tstats[i, TS_POISONED])
                lane.dropped = int(tstats[i, TS_DROPPED])
                lane.throttled = bool(tstats[i, TS_THROTTLED])
                if tstats[i, TS_QUARANTINED] and lane.quarantined is None:
                    lane.quarantined = "quarantined before checkpoint"
            rows = np.asarray(
                state.get("ring_rows", np.zeros((0, RING_ROW), np.int32)),
                np.int32,
            ).reshape(-1, RING_ROW)
            for r in rows:
                t = int(r[TEN_ID])
                if not (0 <= t < len(self._lanes)):
                    raise ValueError(
                        f"residue row tagged for tenant lane {t}; this "
                        f"stream has {len(self._lanes)} lanes"
                    )
                self._lanes[t].queue.append(_readmit_pending(r, now))
                self._adopt_row_locked(self._lanes[t], r)
            for lane in self._lanes:
                # The same residue-vs-capacity guard the plain stream
                # raises: a lane's re-published residue must fit its
                # ring region, or the pump could never drain the queue
                # and a closed stream would re-enter forever.
                if len(lane.queue) > self.region_rows:
                    raise ValueError(
                        f"tenant {lane.spec.id!r}: resume residue "
                        f"({len(lane.queue)} rows) exceeds this "
                        f"stream's ring region ({self.region_rows} "
                        f"rows); raise ring_capacity"
                    )

    def readmit(self, tenant: Union[str, int], row: np.ndarray) -> None:
        """Append one residue row to a lane's host backlog (the mesh
        resume re-deal path; the deadline re-arms from the row's stamped
        TEN_DEADLINE_MS remaining budget)."""
        lane = self._lane(tenant)
        now = self.clock()
        with self._lock:
            lane.queue.append(_readmit_pending(row, now))
            self._adopt_row_locked(lane, np.asarray(row))

    def _adopt_row_locked(self, lane: _Lane, r: np.ndarray) -> None:
        """A residue row stamped with a nonzero TEN_TOKEN re-enters the
        conservation ledger on the resuming side: the token becomes
        re-attachable (reattach binds a fresh Future to it)."""
        if self.futures is not None and int(r[TEN_TOKEN]):
            self.futures.adopt_row_token(
                int(r[TEN_TOKEN]), lane.spec.id,
                int(r[F_FN]), int(r[F_OUT]),
            )

    # ---- telemetry ----

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant counter snapshot keyed by tenant id (numbers plus
        the quarantine reason string; MetricsRegistry flattening drops
        strings by design). ``completed`` counts INSTALLS - rows the
        device poll handed to the scheduler, which a non-aborted stream
        runs to completion before returning (the megakernel executes
        every installed task or the run errors); the same install event
        stamps the admission-to-complete latency sample."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for lane in self._lanes:
                out[lane.spec.id] = {
                    "accepted": lane.accepted,
                    "rejected": lane.rejected,
                    "expired": lane.expired,
                    "completed": lane.installed,
                    "backlog": lane.backlog,
                    "queued": len(lane.queue),
                    "in_flight": lane.in_flight,
                    "published": lane.published,
                    "consumed": lane.consumed,
                    "poisoned": lane.poisoned,
                    "dropped": lane.dropped,
                    "throttled": int(lane.throttled),
                    "quarantined": int(lane.quarantined is not None),
                    "weight": lane.spec.weight,
                    "quarantine_reason": lane.quarantined,
                }
        return out

    def metrics(self) -> Dict[str, Dict[str, float]]:
        """The live-source shape for ``MetricsRegistry.register(
        "tenant", table.metrics)``: numeric-only per-tenant series, so
        snapshots carry ``tenant.<id>.accepted`` etc."""
        snap = self.stats()
        return {
            tid: {
                k: float(v) for k, v in s.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
            for tid, s in snap.items()
        }

    def latency_stats(self, tenant: Union[str, int]) -> Dict[str, float]:
        """Admission-to-install latency percentiles for one lane (from
        the bounded reservoir; seconds)."""
        lane = self._lane(tenant)
        with self._lock:
            xs = sorted(lane.latencies)
        if not xs:
            return {"n": 0}
        def pct(p: float) -> float:
            return xs[min(len(xs) - 1, int(p * len(xs)))]
        return {
            "n": len(xs),
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "mean_s": sum(xs) / len(xs),
        }


class MeshTenantTable:
    """The mesh-wide admission front door: one tenant roster spanning
    every device of a resident mesh (ROADMAP direction 2 / the PR 8
    single-device residual). Device ``d``'s injection ring is
    partitioned into the same per-tenant contiguous regions as the
    single-device front door - internally one :class:`TenantTable`
    replica per device, all sharing the roster - and the in-kernel WRR
    poll runs unchanged per device against that device's tctl block.

    **Routing** (``submit``): an admission lands on one device - an
    explicit ``device=``, else the least-backlogged replica of the
    tenant's lane among its ``placement`` candidates (ties to the
    lowest id). Devices whose region/backlog gates would reject are
    passed over before any quota is charged, so a full device spills to
    its siblings and the whole mesh must be saturated before a
    ``REJECTED("ring"/"backlog")`` verdict surfaces. The Admission
    ladder itself is the single-device ladder verbatim (the routed
    replica's ``admit`` decides).

    **Quota scope**: ``rate`` is MESH-WIDE (one aggregate token bucket
    per tenant, charged once before the routed admit; replicas are
    built rate-free so nothing double-charges); ``max_in_flight`` /
    ``queue_capacity`` / the ring budget are per device-lane region.
    The poison ladder and the ``deadline_budget`` are enforced on
    AGGREGATE counts at every pump - throttle clamps the lane's WRR
    weight on every device, quarantine pauses it everywhere - so a
    tenant cannot evade isolation by spreading failures across devices.

    **Survivability**: ``export_state`` packs per-device tenant-tagged
    residue (each live row stamped with its TEN_DEADLINE_MS remaining
    budget) plus aggregate tctl/tstats counter blocks in the resident
    bundle schema; ``resume_from`` accepts any exported mesh size and
    re-deals residue round-robin per tenant across THIS table's devices
    (per-tenant counts conserved by construction, deadlines re-armed),
    so a ``reshard(N -> M)`` cut is a fresh M-device table resuming the
    N-device state. ``pressure()`` is the autoscaler feed: per-tenant
    backlog / in-flight / ring-residue / deadline-budget drain.
    """

    def __init__(self, specs: Sequence[TenantSpec], ndev: int,
                 region_rows: int,
                 clock: Callable[[], float] = time.monotonic,
                 placement: Optional[Dict[str, Sequence[int]]] = None,
                 egress=None, futures: "Optional[FutureTable]" = None,
                 ) -> None:
        self.specs = list(specs)
        if not self.specs:
            raise ValueError("at least one tenant lane")
        self.ndev = int(ndev)
        if self.ndev < 1:
            raise ValueError(f"ndev must be >= 1, got {ndev}")
        self.region_rows = int(region_rows)
        self.clock = clock
        self._lock = threading.Lock()
        # Mesh-wide rate quota: one aggregate bucket per tenant; the
        # replicas are rate-free and their poison/budget thresholds are
        # disabled (enforced on aggregates here instead).
        self._buckets: Dict[str, Optional[TokenBucket]] = {
            s.id: (
                None if s.rate is None
                else TokenBucket(s.rate, s.burst, clock)
            )
            for s in self.specs
        }
        self._replicas = [
            TenantSpec(
                s.id, weight=s.weight, rate=None,
                max_in_flight=s.max_in_flight,
                queue_capacity=s.queue_capacity,
                deadline_s=s.deadline_s, deadline_budget=None,
                poison_throttle=2**30, poison_quarantine=2**30,
                retry=s.retry, validator=s.validator,
            )
            for s in self.specs
        ]
        # Completion-mailbox egress: ONE mesh-wide FutureTable shared by
        # every replica (a future routed to device d must resolve no
        # matter which successor device retires it after a reshard).
        # Replicas are built egress=False so an env knob can never make
        # one privately own a second ledger.
        self.egress = normalize_egress(egress)
        if futures is not None and self.egress is None:
            raise ValueError(
                "futures= (a shared ledger) needs egress= on too"
            )
        # ``futures=`` shares a predecessor mesh's ledger across a
        # reshard cut (resized() passes it), so PREEMPTED tokens
        # reattach against the SAME conservation identity.
        self.futures: Optional[FutureTable] = (
            futures if futures is not None
            else None if self.egress is None
            else FutureTable(backoff_s=self.egress.backoff_s, clock=clock)
        )
        self.tables: List[TenantTable] = [
            TenantTable(self._replicas, self.region_rows, clock,
                        egress=False, futures=self.futures)
            for _ in range(self.ndev)
        ]
        if placement is not None:
            for tid, devs in placement.items():
                if tid not in self.ids:
                    raise ValueError(
                        f"placement names unknown tenant {tid!r} "
                        f"(have {self.ids})"
                    )
                devs = [int(d) for d in devs]
                if not devs or any(
                    not 0 <= d < self.ndev for d in devs
                ):
                    raise ValueError(
                        f"placement for {tid!r} must be a non-empty "
                        f"subset of devices 0..{self.ndev - 1}, got "
                        f"{devs}"
                    )
        self.placement = (
            None if placement is None
            else {tid: [int(d) for d in devs]
                  for tid, devs in placement.items()}
        )
        T = len(self.specs)
        # Aggregate counter base from a resumed checkpoint (stats() adds
        # it on top of the live replica sums).
        self._base_tctl = np.zeros((T, 8), np.int64)
        self._base_tstats = np.zeros((T, 8), np.int64)
        self._rotor = [0] * T  # per-tenant resume re-deal cursor
        self._budget_cancelled: set = set()

    # ---- lookups ----

    def __len__(self) -> int:
        return len(self.specs)

    @property
    def ids(self) -> List[str]:
        return [s.id for s in self.specs]

    def _idx(self, tenant: Union[str, int]) -> int:
        return self.tables[0]._lane(tenant).idx

    def _candidates(self, tid: str) -> List[int]:
        if self.placement is not None and tid in self.placement:
            return self.placement[tid]
        return list(range(self.ndev))

    # ---- admission (any thread) ----

    def resolve_deadline(self, tenant, deadline_s, cancel_scope):
        return self.tables[0].resolve_deadline(
            tenant, deadline_s, cancel_scope
        )

    def submit(self, tenant: Union[str, int], fn: int,
               args: Sequence[int] = (), out: int = 0,
               succ0: int = NO_TASK, succ1: int = NO_TASK,
               deadline_s: Optional[float] = None,
               cancel_scope: Optional[CancelScope] = None,
               device: Optional[int] = None) -> Admission:
        """Admit one task into the mesh: build the row, resolve the
        deadline (explicit > scope chain > lane default), route, and
        return the typed verdict (``.device`` names the landing)."""
        row = build_row(fn, args, out, succ0, succ1)
        deadline_at = self.resolve_deadline(tenant, deadline_s,
                                            cancel_scope)
        return self.submit_row(tenant, row, deadline_at, cancel_scope,
                               device=device)

    def submit_row(self, tenant: Union[str, int], row: np.ndarray,
                   deadline_at: Optional[float] = None,
                   cancel_scope: Optional[CancelScope] = None,
                   device: Optional[int] = None) -> Admission:
        """Route one prepared row to a device and admit it there. The
        routed replica's ``admit`` is the single-device ladder verbatim;
        routing only picks WHICH replica decides."""
        i = self._idx(tenant)
        tid = self.specs[i].id
        # Terminal gates FIRST, mirroring the single-device ladder's
        # cheapest-first order (quarantine/cancel flags are mesh-uniform
        # by construction - every rung applies to every replica), so a
        # doomed submission never burns a mesh-wide rate token.
        lane0 = self.tables[0]._lanes[i]
        if lane0.quarantined is not None:
            adm = self.tables[0].record_reject(tid, "quarantined")
            adm.device = 0
            return adm
        if lane0.scope.cancelled() or (
            cancel_scope is not None and cancel_scope.cancelled()
        ):
            adm = self.tables[0].record_reject(tid, "cancelled")
            adm.device = 0
            return adm
        if deadline_at is not None and self.clock() >= deadline_at:
            adm = self.tables[0].record_reject(tid, "expired")
            adm.device = 0
            return adm
        if device is not None:
            if not 0 <= int(device) < self.ndev:
                raise KeyError(f"no device {device} in a {self.ndev}-"
                               "device mesh")
            order = [int(device)]
        else:
            # Least-backlogged lane replica first; ties to the lowest
            # device id (sorted() is stable over the id-ordered list).
            order = sorted(
                self._candidates(tid),
                key=lambda d: self.tables[d]._lanes[i].backlog,
            )
        last_reason = "ring"
        target: Optional[int] = None
        for d in order:
            lane = self.tables[d]._lanes[i]
            # The region/backlog gates, probed cheaply so routing can
            # pass over a full device before any quota is charged (the
            # probe is advisory - the routed admit re-checks under its
            # own lock).
            if lane.published + len(lane.queue) >= self.region_rows:
                last_reason = "ring"
                continue
            if len(lane.queue) >= lane.spec.queue_capacity:
                last_reason = "backlog"
                continue
            target = d
            break
        if target is None:
            adm = self.tables[order[0]].record_reject(tid, last_reason)
            adm.device = order[0]
            return adm
        bucket = self._buckets[tid]
        if bucket is not None:
            with self._lock:
                ok = bucket.try_take(1)
            if not ok:
                adm = self.tables[target].record_reject(tid, "rate")
                adm.device = target
                return adm
        adm = self.tables[target].admit(
            tenant, row, deadline_at, cancel_scope
        )
        adm.device = target
        return adm

    # ---- isolation (aggregate enforcement) ----

    def report_failure(self, tenant: Union[str, int],
                       exc: Optional[BaseException] = None) -> None:
        """Aggregate poison ladder: the failure lands on the replica the
        caller routed to conceptually, but the LADDER climbs on the
        mesh-wide count (``_enforce`` at the next pump applies the
        rung everywhere)."""
        if isinstance(exc, CancelledError):
            return
        i = self._idx(tenant)
        lane = self.tables[0]._lanes[i]
        with self.tables[0]._lock:
            lane.poisoned += 1  # thresholds are mesh-level (see _enforce)
        self._enforce()

    def quarantine(self, tenant: Union[str, int], reason: str) -> None:
        for t in self.tables:
            t.quarantine(tenant, reason)

    def cancel(self, tenant: Union[str, int],
               reason: str = "tenant cancelled") -> None:
        for t in self.tables:
            t.cancel(tenant, reason)

    def _agg(self, field: str, i: int) -> int:
        return sum(
            getattr(t._lanes[i], field) for t in self.tables
        )

    def _enforce(self) -> None:
        """Apply the aggregate isolation policies: a tenant's mesh-wide
        poison count climbs the ORIGINAL spec's ladder (replicas carry
        disabled thresholds), and a mesh-wide expiry count past the
        deadline budget cancels the lane everywhere - once."""
        for i, spec in enumerate(self.specs):
            tid = spec.id
            poisoned = self._agg("poisoned", i) + int(
                self._base_tstats[i, TS_POISONED]
            )
            if poisoned >= spec.poison_quarantine:
                self.quarantine(
                    tid,
                    f"poison quarantine ({poisoned} terminal failures "
                    f"mesh-wide)",
                )
            elif poisoned >= spec.poison_throttle:
                for t in self.tables:
                    t.throttle(tid)
            if spec.deadline_budget is not None and tid not in (
                self._budget_cancelled
            ):
                expired = (
                    self._agg("expired_host", i)
                    + self._agg("dev_expired", i)
                    + int(self._base_tstats[i, TS_EXPIRED_HOST])
                    + int(self._base_tctl[i, TC_EXPIRED])
                )
                if expired >= spec.deadline_budget:
                    self._budget_cancelled.add(tid)
                    self.cancel(
                        tid,
                        f"tenant {tid}: deadline budget exhausted "
                        f"({expired} expired mesh-wide >= "
                        f"{spec.deadline_budget})",
                    )

    # ---- the mesh driver's half ----

    def set_admit_round(self, r: int, device: Optional[int] = None) -> None:
        """Telemetry admit-round feedback, mesh face: one device's round
        gauge (``device=``) or all replicas at once (mesh drivers with a
        single merged gauge)."""
        if device is not None:
            self.tables[int(device)].set_admit_round(r)
            return
        for t in self.tables:
            t.set_admit_round(r)

    def pump(self, rings: np.ndarray) -> np.ndarray:
        """Expire/publish every device's lanes and build the stacked
        ``(ndev, T, 8)`` tctl block one mesh entry uploads. ``rings``
        is the host image of the per-device injection rings,
        ``(ndev, T * region_rows, RING_ROW)``."""
        rings = np.asarray(rings)
        if rings.shape[0] != self.ndev:
            raise ValueError(
                f"rings cover {rings.shape[0]} devices, this table has "
                f"{self.ndev}"
            )
        self._enforce()
        return np.stack(
            [self.tables[d].pump(rings[d]) for d in range(self.ndev)]
        )

    def absorb(self, tctl_out: np.ndarray) -> None:
        """Fold one mesh entry's stacked tctl echo back per device."""
        tctl_out = np.asarray(tctl_out)
        for d in range(self.ndev):
            self.tables[d].absorb(tctl_out[d])

    def drained(self) -> bool:
        return all(t.drained() for t in self.tables)

    def close_if_drained(self) -> bool:
        return all(t.close_if_drained() for t in self.tables)

    def total_published(self) -> int:
        return sum(t.total_published() for t in self.tables)

    # ---- telemetry ----

    _BASE_FIELDS = {
        # aggregate stat key -> (block, word) base-offset sources
        "accepted": (("tstats", TS_ACCEPTED),),
        "rejected": (("tstats", TS_REJECTED),),
        "expired": (("tstats", TS_EXPIRED_HOST), ("tctl", TC_EXPIRED)),
        "completed": (("tctl", TC_INSTALLED),),
        "poisoned": (("tstats", TS_POISONED),),
        "dropped": (("tstats", TS_DROPPED),),
    }

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Mesh-aggregate per-tenant counters (the single-device stats
        shape; counts sum across replicas plus any resumed base, flags
        OR). ``per_device_stats()`` keeps the replica detail."""
        out: Dict[str, Dict[str, Any]] = {}
        per_dev = [t.stats() for t in self.tables]
        for i, spec in enumerate(self.specs):
            tid = spec.id
            agg: Dict[str, Any] = {}
            for d in range(self.ndev):
                for k, v in per_dev[d][tid].items():
                    if isinstance(v, bool) or not isinstance(
                        v, (int, float)
                    ):
                        continue
                    if k in ("weight",):
                        agg[k] = v
                    elif k in ("throttled", "quarantined"):
                        agg[k] = max(agg.get(k, 0), v)
                    else:
                        agg[k] = agg.get(k, 0) + v
            for k, srcs in self._BASE_FIELDS.items():
                for block, word in srcs:
                    base = (
                        self._base_tstats if block == "tstats"
                        else self._base_tctl
                    )
                    agg[k] = agg.get(k, 0) + int(base[i, word])
            agg["quarantine_reason"] = next(
                (per_dev[d][tid]["quarantine_reason"]
                 for d in range(self.ndev)
                 if per_dev[d][tid]["quarantine_reason"]),
                None,
            )
            out[tid] = agg
        return out

    def per_device_stats(self) -> List[Dict[str, Dict[str, Any]]]:
        return [t.stats() for t in self.tables]

    def metrics(self) -> Dict[str, Dict[str, float]]:
        """Numeric-only aggregate series (``MetricsRegistry.register(
        "tenant", mesh_table.metrics)``)."""
        return {
            tid: {
                k: float(v) for k, v in s.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
            for tid, s in self.stats().items()
        }

    def latency_stats(self, tenant: Union[str, int]) -> Dict[str, float]:
        """Admission-to-install percentiles pooled across replicas."""
        i = self._idx(tenant)
        xs: List[float] = []
        for t in self.tables:
            with t._lock:
                xs.extend(t._lanes[i].latencies)
        xs.sort()
        if not xs:
            return {"n": 0}

        def pct(p: float) -> float:
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        return {"n": len(xs), "p50_s": pct(0.50), "p99_s": pct(0.99),
                "mean_s": sum(xs) / len(xs)}

    def pressure(self) -> Dict[str, Dict[str, float]]:
        """The autoscaler feed: per-tenant mesh-aggregate backlog,
        in-flight/ring residue, and deadline-budget drain. ``expired``
        and ``budget`` let the policy compute per-slice drain deltas;
        ``pressure`` is the cumulative drained fraction (1.0 = the
        watchdog rung: the lane cancels)."""
        out: Dict[str, Dict[str, float]] = {}
        snap = self.stats()
        for i, spec in enumerate(self.specs):
            s = snap[spec.id]
            budget = float(spec.deadline_budget or 0)
            out[spec.id] = {
                "backlog": float(s["backlog"]),
                "queued": float(s["queued"]),
                "in_flight": float(s["in_flight"]),
                # Alias of in_flight: published-but-unconsumed rows ARE
                # the ring residue in this design; both spellings exist
                # so hand-built Observation feeds can use either.
                "ring_residue": float(s["in_flight"]),
                "expired": float(s["expired"]),
                "budget": budget,
                "pressure": (
                    min(1.0, s["expired"] / budget) if budget else 0.0
                ),
            }
        return out

    # ---- checkpoint / reshard ----

    def export_state(self, rings: np.ndarray) -> Dict[str, np.ndarray]:
        """The mesh quiesce export, in the resident bundle schema:
        per-device packed residue (``ring_rows`` ``(ndev, R, RING_ROW)``
        + ``ictl`` live counts, every live row deadline-stamped) and the
        AGGREGATE ``tctl``/``tstats`` counter blocks (device-count-free,
        so a reshard passes them through untouched)."""
        rings = np.asarray(rings)
        T = len(self.specs)
        R = rings.shape[1]
        rr = np.zeros((self.ndev, R, RING_ROW), np.int32)
        ictl = np.zeros((self.ndev, 8), np.int32)
        tctl = self._base_tctl.copy()
        tstats = self._base_tstats.copy()
        for d in range(self.ndev):
            st = self.tables[d].export_state(rings[d])
            n = st["ring_rows"].shape[0]
            rr[d, :n] = st["ring_rows"]
            ictl[d, 0] = n
            ictl[d, 1] = 1
            for i in range(T):
                for w in (TC_EXPIRED, TC_INSTALLED):
                    tctl[i, w] += int(st["tctl"][i, w])
                for w in (TS_ACCEPTED, TS_REJECTED, TS_EXPIRED_HOST,
                          TS_POISONED, TS_DROPPED):
                    tstats[i, w] += int(st["tstats"][i, w])
                for w in (TS_THROTTLED, TS_QUARANTINED):
                    tstats[i, w] = max(
                        int(tstats[i, w]), int(st["tstats"][i, w])
                    )
                tctl[i, TC_PAUSE] = max(
                    int(tctl[i, TC_PAUSE]), int(st["tctl"][i, TC_PAUSE])
                )
                tctl[i, TC_WEIGHT] = int(st["tctl"][i, TC_WEIGHT])
        if self.futures is not None:
            # One mesh-wide preempt AFTER every replica export: the
            # replicas share this ledger (and never preempt it
            # themselves), so each export above already expired its
            # doomed rows and everything still live preempts exactly
            # once, carrying a resume token for the successor table.
            self.futures.preempt_all()
        return {
            "ring_rows": rr, "ictl": ictl,
            "tctl": tctl.astype(np.int32),
            "tstats": tstats.astype(np.int32),
            "tenant_ids": np.array(self.ids),
        }

    def resume_from(self, state: Dict[str, Any]) -> None:
        """Seed THIS table (any device count) from an exported mesh
        state: aggregate counters become the stats base, lane flags
        (throttle / quarantine / cancel) re-apply everywhere, and
        tenant-tagged residue re-deals round-robin per tenant across
        this mesh's devices - per-tenant counts conserved by
        construction, deadlines re-armed from their stamped remaining
        budgets."""
        if "tctl" not in state or "tstats" not in state:
            raise ValueError(
                "resume state carries no per-tenant counter blocks "
                "(tctl/tstats): it was exported without tenant lanes "
                "and cannot resume on a tenant-enabled mesh"
            )
        T = len(self.specs)
        tctl = np.asarray(state["tctl"])
        tstats = np.asarray(state["tstats"])
        if tctl.shape[0] != T:
            raise ValueError(
                f"resume state carries {tctl.shape[0]} tenant lanes, "
                f"this mesh has {T}"
            )
        ids = state.get("tenant_ids")
        if ids is not None:
            want = [str(x) for x in np.asarray(ids).tolist()]
            if want != self.ids:
                raise ValueError(
                    f"tenant roster mismatch: resume state carries "
                    f"{want!r}, this mesh has {self.ids!r} (ids and "
                    f"order must match - lane state is keyed by index)"
                )
        self._base_tctl = tctl.astype(np.int64).copy()
        self._base_tstats = tstats.astype(np.int64).copy()
        # Fresh replicas: live lane counters fold into the base above at
        # export, so a table resumed IN PLACE (the autoscaler's hold
        # path re-feeds the same object every slice) must not count them
        # twice.
        self.tables = [
            TenantTable(self._replicas, self.region_rows, self.clock,
                        egress=False, futures=self.futures)
            for _ in range(self.ndev)
        ]
        self._rotor = [0] * T
        self._budget_cancelled = set()
        for i, spec in enumerate(self.specs):
            if tstats[i, TS_QUARANTINED]:
                self.quarantine(spec.id, "quarantined before checkpoint")
            elif tstats[i, TS_THROTTLED]:
                for t in self.tables:
                    t.throttle(spec.id)
            elif tctl[i, TC_PAUSE]:
                # Paused but not quarantined: the lane was cancelled.
                self.cancel(spec.id, "cancelled before checkpoint")
        rr = np.asarray(
            state.get("ring_rows", np.zeros((0, RING_ROW), np.int32)),
            np.int32,
        )
        if rr.ndim == 3:
            ic = state.get("ictl")
            if ic is None:
                raise ValueError(
                    "per-device ring_rows need ictl for live row counts"
                )
            ic = np.asarray(ic)
            rows = [
                rr[d, j]
                for d in range(rr.shape[0])
                for j in range(int(ic[d, 0]))
            ]
        else:
            rows = list(rr.reshape(-1, RING_ROW))
        for row in rows:
            i = int(row[TEN_ID])
            if not 0 <= i < T:
                raise ValueError(
                    f"residue row tagged for tenant lane {i}; this mesh "
                    f"has {T} lanes"
                )
            cand = self._candidates(self.specs[i].id)
            dev = cand[self._rotor[i] % len(cand)]
            self._rotor[i] += 1
            self.tables[dev].readmit(i, row)
        for d, t in enumerate(self.tables):
            for lane in t._lanes:
                if len(lane.queue) > self.region_rows:
                    raise ValueError(
                        f"tenant {lane.spec.id!r}: resume residue on "
                        f"device {d} ({len(lane.queue)} rows) exceeds "
                        f"the ring region ({self.region_rows} rows); "
                        "resume on more devices or raise ring_capacity"
                    )

    def resized(self, ndev_new: int) -> "MeshTenantTable":
        """A fresh table of the same roster on ``ndev_new`` devices
        (state rides the exported bundle, not the table - feed the
        resharded state to the new table's ``resume_from``)."""
        return MeshTenantTable(
            self.specs, ndev_new, self.region_rows, clock=self.clock,
            placement=None if self.placement is None else {
                tid: [d for d in devs if d < ndev_new] or [0]
                for tid, devs in self.placement.items()
            },
            egress=self.egress, futures=self.futures,
        )

    def reshard(self, rings: np.ndarray, ndev_new: int
                ) -> Tuple["MeshTenantTable", Dict[str, np.ndarray]]:
        """The live-cut convenience: export this table's state, build
        the ``ndev_new``-device successor, resume it. Returns
        ``(new_table, exported_state)`` - per-tenant counts conserved
        across the cut by construction."""
        st = self.export_state(rings)
        nxt = self.resized(ndev_new)
        nxt.resume_from(st)
        return nxt, st

    def reattach(self, resume_token):
        """Re-attach a PREEMPTED future on this (successor) mesh: the
        resume token a FuturePreempted carried binds a fresh Future to
        the same in-flight request, whose TEN_TOKEN rode the re-dealt
        residue row (or the etok export for installed tasks)."""
        if self.futures is None:
            raise ValueError(
                "reattach needs an egress-enabled mesh (pass egress= "
                "or set HCLIB_TPU_EGRESS_DEPTH)"
            )
        return self.futures.reattach(resume_token)


# ------------------------------------------------------------- plumbing

def _env_float(name: str) -> Optional[float]:
    from ..runtime.env import env_raw

    v = env_raw(name)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        # Loud, not lenient: a typo'd quota must not silently become
        # "no quota" - that is the isolation failure this module exists
        # to prevent.
        raise ValueError(f"{name}={v!r} is not a number") from None


def tenants_from_env() -> Optional[List[TenantSpec]]:
    """The wrapper-script spelling: ``HCLIB_TPU_TENANTS=N`` enables N
    equal lanes ``t0..t{N-1}``; ``HCLIB_TPU_TENANT_WEIGHTS=4,2,1``
    overrides weights (when both are set their lane counts must agree);
    ``HCLIB_TPU_TENANT_RATE`` / ``_BURST`` / ``_INFLIGHT`` /
    ``_DEADLINE_S`` apply to every lane. Returns None when unset."""
    from ..runtime.env import env_raw

    n_env = env_raw("HCLIB_TPU_TENANTS", "")
    w_env = env_raw("HCLIB_TPU_TENANT_WEIGHTS", "")
    weights: Optional[List[int]] = None
    if w_env:
        try:
            weights = [int(w) for w in w_env.split(",")]
        except ValueError:
            raise ValueError(
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r} must be a "
                f"comma-separated list of ints (e.g. '4,2,1')"
            ) from None
        if any(w < 1 for w in weights):
            # No silent clamp: 4,0,1 quietly running as 4,1,1 is an
            # isolation-policy change with no signal.
            raise ValueError(
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r}: weights must "
                f"be >= 1 (WRR shares; a lane cannot be disabled by "
                f"weight - quarantine or cancel it instead)"
            )
    n = 0
    if n_env:
        try:
            n = int(n_env)
        except ValueError:
            # A malformed enable must not silently run the stream as a
            # single anonymous firehose.
            raise ValueError(
                f"HCLIB_TPU_TENANTS={n_env!r} must be an int"
            ) from None
    if weights:
        if n and n != len(weights):
            raise ValueError(
                f"HCLIB_TPU_TENANTS={n} disagrees with "
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r} "
                f"({len(weights)} lanes) - update both or unset one"
            )
        n = len(weights)
    if n < 1:
        return None
    rate = _env_float("HCLIB_TPU_TENANT_RATE")
    burst = _env_float("HCLIB_TPU_TENANT_BURST")
    if burst is not None and rate is None:
        # A burst cap without a rate builds no token bucket at all: the
        # operator asked for a quota and would silently get none.
        raise ValueError(
            "HCLIB_TPU_TENANT_BURST needs HCLIB_TPU_TENANT_RATE: burst "
            "is the token bucket's depth, rate its refill - without a "
            "rate no bucket is built and admission is unlimited"
        )
    inflight = _env_float("HCLIB_TPU_TENANT_INFLIGHT")
    if inflight is not None and inflight != int(inflight):
        # No silent truncation: 2.9 quietly becoming 2 is an admission-
        # policy change with no signal.
        raise ValueError(
            f"HCLIB_TPU_TENANT_INFLIGHT={inflight} must be a whole "
            f"number of in-flight tasks"
        )
    deadline = _env_float("HCLIB_TPU_TENANT_DEADLINE_S")
    return [
        TenantSpec(
            f"t{i}",
            weight=(weights[i] if weights else 1),
            rate=rate,
            burst=burst,
            max_in_flight=None if inflight is None else int(inflight),
            deadline_s=deadline,
        )
        for i in range(n)
    ]


def mesh_tenants_from_env() -> Optional[List[TenantSpec]]:
    """The mesh-tenancy wrapper-script spelling:
    ``HCLIB_TPU_MESH_TENANTS=N`` enables N equal lanes ``t0..t{N-1}``
    on resident inject meshes, sharing the per-lane
    ``HCLIB_TPU_TENANT_RATE`` / ``_BURST`` / ``_INFLIGHT`` /
    ``_DEADLINE_S`` knobs (and ``_WEIGHTS``, whose lane count must
    agree) with the streaming spelling. Malformed text raises - a
    typo'd enable must not silently run the mesh unshaped. Returns
    None when unset."""
    from ..runtime.env import env_int

    n = env_int("HCLIB_TPU_MESH_TENANTS", 0)
    if not n:
        return None
    if n < 1:
        raise ValueError(
            f"HCLIB_TPU_MESH_TENANTS={n} must be >= 1 (unset or 0 "
            "disables mesh tenancy)"
        )
    return _lane_specs_from_env(n)


def _lane_specs_from_env(n: int) -> List[TenantSpec]:
    """Build N lanes from the shared per-lane env knobs (the body both
    env spellings share; weight list length must agree with ``n``)."""
    from ..runtime.env import env_raw

    w_env = env_raw("HCLIB_TPU_TENANT_WEIGHTS", "")
    weights: Optional[List[int]] = None
    if w_env:
        try:
            weights = [int(w) for w in w_env.split(",")]
        except ValueError:
            raise ValueError(
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r} must be a "
                f"comma-separated list of ints (e.g. '4,2,1')"
            ) from None
        if any(w < 1 for w in weights):
            raise ValueError(
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r}: weights must "
                f"be >= 1"
            )
        if len(weights) != n:
            raise ValueError(
                f"HCLIB_TPU_TENANT_WEIGHTS={w_env!r} names "
                f"{len(weights)} lanes but {n} were requested - "
                "update both or unset one"
            )
    rate = _env_float("HCLIB_TPU_TENANT_RATE")
    burst = _env_float("HCLIB_TPU_TENANT_BURST")
    if burst is not None and rate is None:
        raise ValueError(
            "HCLIB_TPU_TENANT_BURST needs HCLIB_TPU_TENANT_RATE: burst "
            "is the token bucket's depth, rate its refill - without a "
            "rate no bucket is built and admission is unlimited"
        )
    inflight = _env_float("HCLIB_TPU_TENANT_INFLIGHT")
    if inflight is not None and inflight != int(inflight):
        raise ValueError(
            f"HCLIB_TPU_TENANT_INFLIGHT={inflight} must be a whole "
            f"number of in-flight tasks"
        )
    deadline = _env_float("HCLIB_TPU_TENANT_DEADLINE_S")
    return [
        TenantSpec(
            f"t{i}",
            weight=(weights[i] if weights else 1),
            rate=rate,
            burst=burst,
            max_in_flight=None if inflight is None else int(inflight),
            deadline_s=deadline,
        )
        for i in range(n)
    ]


def normalize_mesh_tenants(arg: Any) -> Optional[List[TenantSpec]]:
    """Normalize a resident mesh's ``tenants=`` argument: None -> the
    ``HCLIB_TPU_MESH_TENANTS`` env spelling (or disabled); everything
    else exactly as :func:`normalize_tenants` (int lane count, spec
    sequence, False to force off)."""
    if arg is None:
        return mesh_tenants_from_env()
    return normalize_tenants(arg)


def normalize_tenants(arg: Any) -> Optional[List[TenantSpec]]:
    """Normalize a ``tenants=`` argument: None -> the env spelling (or
    disabled); an int N -> N equal lanes; a sequence of TenantSpec /
    str ids / kwargs dicts -> specs."""
    if arg is None:
        return tenants_from_env()
    if arg is False:
        return None
    if arg is True:
        # bool is an int: True would silently become one anonymous,
        # quota-less lane (ignoring the HCLIB_TPU_TENANTS* env) - the
        # unshaped firehose the caller was trying to turn off.
        raise ValueError(
            "tenants=True is ambiguous: pass a lane count (int), a "
            "spec sequence, or leave tenants=None and set "
            "HCLIB_TPU_TENANTS"
        )
    if isinstance(arg, int):
        if arg < 1:
            raise ValueError(f"tenants must be >= 1, got {arg}")
        return [TenantSpec(f"t{i}") for i in range(arg)]
    specs: List[TenantSpec] = []
    for item in arg:
        if isinstance(item, TenantSpec):
            specs.append(item)
        elif isinstance(item, str):
            specs.append(TenantSpec(item))
        elif isinstance(item, dict):
            specs.append(TenantSpec(**item))
        else:
            raise TypeError(
                f"tenants entries must be TenantSpec/str/dict, got "
                f"{type(item).__name__}"
            )
    return specs


def wrr_poll_reference(ring: np.ndarray, tctl: np.ndarray,
                       region_rows: int, round_idx: int,
                       headroom: int) -> List[np.ndarray]:
    """Numpy reference model of ONE in-kernel WRR tenant poll - the
    executable spec of ``tpoll`` in device/inject.py, shared by the
    deterministic fairness tests and the chaos scenarios so they run
    (and mean the same thing) without Mosaic interpret. Semantics
    mirrored exactly: visit lane ``(round_idx + k) % T`` for k in
    [0, T), install at most ``min(weight, avail, headroom-left)`` rows
    from the lane's ring region, drop host-marked TEN_EXPIRED rows
    (counted, not installed), and sweep paused lanes - cursor jumps to
    tail, swept rows counted in TC_DROPPED, nothing installed. Mutates
    ``tctl`` in place exactly like the device echo (feed it back through
    ``TenantTable.absorb``); returns the installed rows in install
    order. One divergence, conservative by construction: the kernel
    re-reads live scheduler headroom per lane visit, the model debits a
    single ``headroom`` budget as it installs."""
    T = tctl.shape[0]
    remaining = int(headroom)
    installed: List[np.ndarray] = []
    for k in range(T):
        lane = (int(round_idx) + k) % T
        tail = int(tctl[lane, TC_TAIL])
        cons = int(tctl[lane, TC_CONSUMED])
        paused = int(tctl[lane, TC_PAUSE]) != 0
        avail = tail - cons
        weight = int(tctl[lane, TC_WEIGHT])
        take = 0 if paused else max(
            0, min(weight, avail, remaining)
        )
        inst = exp = 0
        for c in range(cons, cons + take):
            row = ring[lane * region_rows + c]
            if int(row[TEN_EXPIRED]) != 0:
                exp += 1
            else:
                installed.append(np.array(row, np.int32))
                inst += 1
        if paused:
            tctl[lane, TC_CONSUMED] = tail
            tctl[lane, TC_DROPPED] += avail
        else:
            tctl[lane, TC_CONSUMED] = cons + take
        tctl[lane, TC_INSTALLED] += inst
        tctl[lane, TC_EXPIRED] += exp
        remaining -= inst
    return installed


def per_tenant_ring_counts(ring_rows: Any,
                           ictl: Any = None) -> Dict[int, int]:
    """Count residue ring rows by tenant lane (the conservation probe
    checkpoint/reshard tests use). ``ring_rows`` is either a stream
    state's flat ``(n, RING_ROW)`` residue or a resident bundle's
    ``(ndev, R, RING_ROW)`` per-device rings - the latter needs ``ictl``
    to know each device's live row count."""
    counts: Dict[int, int] = {}
    rows = np.asarray(ring_rows)
    if rows.ndim == 3:
        if ictl is None:
            raise ValueError(
                "per-device ring_rows need ictl for live row counts"
            )
        ic = np.asarray(ictl)
        live = [
            rows[d, i]
            for d in range(rows.shape[0])
            for i in range(int(ic[d, 0]))
        ]
    else:
        live = list(rows.reshape(-1, rows.shape[-1]))
    for r in live:
        t = int(r[TEN_ID])
        counts[t] = counts.get(t, 0) + 1
    return counts
