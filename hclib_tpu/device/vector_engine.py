"""Batched task dispatch: recursive task families run wide over VPU lanes.

This is the megakernel's *vector tier* - the answer to the scalar
scheduler's per-task SMEM cost (~30 read-modify-writes per task,
megakernel.py). A task family that is (a) recursive (tasks spawn tasks of
the same family) and (b) reduction-shaped (results combine associatively
into accumulators) is described by a ``VectorTaskSpec`` and dispatched as a
whole subtree across (rows, 128) VPU lanes inside the resident kernel:

- every lane runs an independent tail-call DFS over its own stack of task
  *frames* (the descriptor equivalent for the vector tier: a tuple of int32
  planes per stack level), so each active step executes one task per lane -
  thousands of tasks per VPU step instead of one per ~30 scalar RMWs;
- load balancing is *lane-level work stealing by ring rotation*: when
  enough lanes starve, each starved lane claims the bottom-of-stack frame
  (the largest remaining subtree, the classic steal-from-the-cold-end of
  Chase-Lev) from the lane a rotating ring permutation pairs it with.
  Rotations are plain vector rolls, so there is no gather/scatter at all,
  and a rotation pairs each donor with exactly one claimant (it is a
  bijection) - the same conflict-freedom the reference gets from CAS on the
  deque head (src/hclib-deque.c:75-106), by construction instead of by
  atomics.

The reference analogue of this tier is the flat/recursive ``forasync``
family (src/hclib.c:158-416) plus the dynamic-tasking benchmarks (fib, UTS:
test/fib/fib.c, test/uts/uts.c); the per-lane DFS machinery generalizes
uts_vec.make_dfs_step (same tail-call discipline, same starve/refill
structure) from the UTS tree to any user-defined task family.

Frames vs descriptors: a vector-tier task never owns a 16-word SMEM
descriptor row. Its identity is a tuple of ``frame_words`` int32 words plus
the engine-managed (cursor, count) pair; dependencies are implicit in the
tree structure (children complete before the parent's accumulator is read),
and the only cross-task communication is through the named accumulators -
which is exactly the fragment of the task model the five reference
benchmarks that matter for throughput (fib, UTS, forasync reductions)
actually use. General DAGs (Cholesky, Smith-Waterman) stay on the scalar
tier; the two tiers share one kernel, one ready ring, and one
pending/executed protocol (megakernel.py wires a VectorTaskSpec into the
``lax.switch`` table next to scalar kernels).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "VectorTaskSpec",
    "make_subtree_runner",
    "fib_spec",
    "nqueens_spec",
]


class VectorTaskSpec:
    """Describes a recursive, reduction-shaped task family for the vector
    tier.

    ``frame_words``: number of int32 words identifying one task (a *frame*).
    ``seed(args)``: host/scalar-side map from the 6 descriptor arg words to
        ``(frame_word_scalars, child_count_scalar)`` for the root task.
    ``child(frame_planes, k, jnp)``: vectorized map from a parent frame and
        child ordinal k to ``(child_frame_planes, child_child_count)``.
        A count of 0 marks the child a leaf.
    ``contrib(frame_planes, ccount, jnp)``: per-expanded-node contributions,
        a dict acc_name -> int32 plane (added where the node was expanded).
    ``accumulators``: ordered accumulator names; ``out_acc`` names the one
        written to the task's F_OUT value slot by the megakernel bridge.
    ``stack_depth``: static per-lane stack height (frames). Overflow sets
        the engine's overflow flag (reported through C_OVERFLOW by the
        megakernel bridge - the analogue of the reference's deque-full
        assert, src/hclib-runtime.c:520-524).
    ``root_contrib(args)``: scalar contribution of the seed task itself
        when the seed is a leaf (count == 0); vector steps never see the
        seed node.
    """

    def __init__(
        self,
        name: str,
        frame_words: int,
        seed: Callable,
        child: Callable,
        contrib: Callable,
        accumulators: Sequence[str],
        out_acc: Optional[str] = None,
        stack_depth: int = 34,
        lanes: Tuple[int, int] = (8, 128),
        min_idle_div: int = 8,
        root_contrib: Optional[Callable] = None,
    ) -> None:
        self.name = name
        self.frame_words = frame_words
        self.seed = seed
        self.child = child
        self.contrib = contrib
        self.accumulators = tuple(accumulators)
        self.out_acc = out_acc if out_acc is not None else (
            self.accumulators[0] if self.accumulators else None
        )
        self.stack_depth = stack_depth
        self.lanes = tuple(lanes)
        self.min_idle_div = min_idle_div
        self.root_contrib = root_contrib


def _select(stack, sp):
    """Per-lane read of a tuple-of-planes stack at level sp (select chain -
    Mosaic has no per-lane axis-0 gather; see uts_vec._level_select)."""
    out = jnp.zeros_like(stack[0])
    for L, plane in enumerate(stack):
        out = jnp.where(sp == L, plane, out)
    return out


def _store(stack, sp, value, mask):
    return tuple(
        jnp.where(mask & (sp == L), value, plane)
        for L, plane in enumerate(stack)
    )


def _shift_down(stack, mask):
    """Drop level 0 where mask (the donated frame): level L takes level
    L+1's plane. The top level keeps its plane (it is dead above sp)."""
    S = len(stack)
    return tuple(
        jnp.where(mask, stack[L + 1], stack[L]) if L + 1 < S else stack[L]
        for L in range(S)
    )


def make_subtree_runner(
    spec: VectorTaskSpec,
    max_steps: int = (1 << 31) - 1,
    use_pltpu_roll: bool = False,
):
    """Builds ``run(seed_frame_scalars, seed_count_scalar) ->
    (nodes, acc_dict, overflow)`` - the whole-subtree vector dispatch,
    usable inside a Pallas kernel branch or as plain JAX.

    ``nodes`` counts expanded tasks (the seed itself is NOT counted - the
    scalar tier accounts for it as one task); ``acc_dict`` maps accumulator
    names to int32 totals reduced over all lanes.
    """
    S = spec.stack_depth
    lanes = spec.lanes
    rows, cols = lanes
    nlanes = rows * cols
    U = spec.frame_words
    min_idle = max(cols // 2, nlanes // spec.min_idle_div)
    nacc = len(spec.accumulators)

    if use_pltpu_roll:
        # jnp.roll with a traced shift lowers to dynamic_slice, which
        # Mosaic does not implement; inside a real TPU kernel the native
        # dynamic-rotate primitive does the job.
        from jax.experimental.pallas import tpu as pltpu

        def _roll(x, shift, axis):
            return pltpu.roll(x, shift, axis)
    else:

        def _roll(x, shift, axis):
            return jnp.roll(x, shift, axis)

    def step(carry):
        sp, over, nodes, accs, fr, ch, cn = carry
        active = sp >= 0
        child = _select(ch, sp)
        count = _select(cn, sp)
        frame = tuple(
            _select(tuple(fr[L][w] for L in range(S)), sp) for w in range(U)
        )
        expand = active & (child < count)
        cframe, ccount = spec.child(frame, child, jnp)
        is_leaf = ccount == 0
        nodes = nodes + expand.astype(jnp.int32)
        contribs = spec.contrib(cframe, ccount, jnp)
        accs = tuple(
            accs[i] + jnp.where(expand, contribs.get(name, 0), 0)
            for i, name in enumerate(spec.accumulators)
        )
        # Tail-call scheduling (uts_vec.make_dfs_step): the last non-leaf
        # child replaces its parent's frame, so no steps pop exhausted
        # frames and stack depth tracks the leftmost spine only.
        last = expand & (child + 1 >= count)
        push = expand & ~is_leaf & ~last
        tail = expand & ~is_leaf & last
        pop = (expand & is_leaf & last) | (active & ~expand)
        ch = _store(ch, sp, child + 1, expand & ~last)
        spp = sp + 1
        # `over` is an int32 0/1 plane: i1 vectors do not survive Mosaic
        # while-loop carries (scf.yield legalization).
        over = over | (push & (spp >= S)).astype(jnp.int32)
        spp = jnp.minimum(spp, S - 1)
        lvl = jnp.where(push, spp, sp)
        newf = push | tail
        fr = tuple(
            tuple(
                jnp.where(newf & (lvl == L), cframe[w], fr[L][w])
                for w in range(U)
            )
            for L in range(S)
        )
        ch = _store(ch, lvl, jnp.zeros(lanes, jnp.int32), newf)
        cn = _store(cn, lvl, ccount, newf)
        sp = jnp.where(push, spp, jnp.where(pop, sp - 1, sp))
        return sp, over, nodes, accs, fr, ch, cn

    def balance(rnd, carry):
        """One ring-rotation steal round: starved lanes take the bottom
        frame of the donor lane the current rotation pairs them with."""
        sp, over, nodes, accs, fr, ch, cn = carry
        # Rotation schedule: column shift walks 1..cols-1 while the row
        # shift advances every full column cycle, covering every offset
        # with dc != 0 (same-column pairs at dc=0 never meet directly -
        # their work drains through other columns). Any bijective family
        # works for correctness (who meets whom only affects efficiency),
        # and liveness never rests on the schedule: the outer do-while
        # guarantees an expansion step per round regardless of claims.
        dc = 1 + rnd % (cols - 1)
        dr = (rnd // (cols - 1)) % rows

        def rot(x):
            return _roll(_roll(x, dc, 1), dr, 0)

        def unrot(x):
            # Positive complementary shifts (some rotate lowerings dislike
            # negative amounts); % keeps them in [0, size).
            return _roll(_roll(x, (rows - dr) % rows, 0), (cols - dc) % cols, 1)

        donor = sp >= 1  # keeps its top frame; gives away level 0
        # Masks ride through the rotate as int32 (TPU dynamic_rotate has no
        # 1-bit flavor).
        claim = (sp < 0) & (rot(donor.astype(jnp.int32)) != 0)
        robbed = unrot(claim.astype(jnp.int32)) != 0
        taken_fr = tuple(rot(fr[0][w]) for w in range(U))
        taken_ch = rot(ch[0])
        taken_cn = rot(cn[0])
        # Donors lose their bottom: stacks shift down one level.
        fr_cols = tuple(
            tuple(fr[L][w] for L in range(S)) for w in range(U)
        )
        fr_cols = tuple(_shift_down(c, robbed) for c in fr_cols)
        ch = _shift_down(ch, robbed)
        cn = _shift_down(cn, robbed)
        sp = jnp.where(robbed, sp - 1, sp)
        # Claimants install the stolen frame at level 0.
        fr_cols = tuple(
            (jnp.where(claim, taken_fr[w], fr_cols[w][0]),) + fr_cols[w][1:]
            for w in range(U)
        )
        ch = (jnp.where(claim, taken_ch, ch[0]),) + ch[1:]
        cn = (jnp.where(claim, taken_cn, cn[0]),) + cn[1:]
        sp = jnp.where(claim, 0, sp)
        fr = tuple(
            tuple(fr_cols[w][L] for w in range(U)) for L in range(S)
        )
        return sp, over, nodes, accs, fr, ch, cn

    def run(seed_frame, seed_count):
        zeros = jnp.zeros(lanes, jnp.int32)
        flat = (
            jax.lax.broadcasted_iota(jnp.int32, lanes, 0) * cols
            + jax.lax.broadcasted_iota(jnp.int32, lanes, 1)
        )
        lane0 = flat == 0
        fr = tuple(
            tuple(
                jnp.where(lane0, jnp.int32(seed_frame[w]), 0)
                if L == 0
                else zeros
                for w in range(U)
            )
            for L in range(S)
        )
        ch = (zeros,) + (zeros,) * (S - 1)
        cn = (jnp.where(lane0, jnp.int32(seed_count), 0),) + (zeros,) * (
            S - 1
        )
        sp = jnp.where(lane0 & (jnp.int32(seed_count) > 0), 0, -1)
        accs = tuple(zeros for _ in range(nacc))

        def outer_cond(carry):
            (sp, *_), rnd, steps = carry[0], carry[1], carry[2]
            return jnp.any(sp >= 0) & (steps < max_steps)

        def inner_cond(carry):
            inner, steps = carry
            sp = inner[0]
            ndone = jnp.sum((sp < 0).astype(jnp.int32))
            # Expand refill-free until enough lanes starve to justify a
            # steal round - unless no lane can donate, in which case a
            # round is pointless and expansion continues to drain.
            donors = jnp.any(sp >= 1)
            return (
                jnp.any(sp >= 0)
                & ((ndone < min_idle) | ~donors)
                & (steps < max_steps)
            )

        def inner_body(carry):
            inner, steps = carry
            return step(inner), steps + 1

        def outer_body(carry):
            inner, rnd, steps = carry
            inner = balance(rnd, inner)
            # Do-while: at least one expansion step per balance round, so
            # `steps` (and with it max_steps) bounds the whole run - a
            # rotation round that claims nothing can never spin the outer
            # loop without forward progress.
            inner, steps = jax.lax.while_loop(
                inner_cond, inner_body, inner_body((inner, steps))
            )
            return inner, rnd + 1, steps

        inner = (sp, zeros, zeros, accs, fr, ch, cn)
        inner, _, steps = jax.lax.while_loop(
            outer_cond, outer_body, (inner, jnp.int32(0), jnp.int32(0))
        )
        sp, over, nodes, accs, *_ = inner
        acc_dict = {
            name: jnp.sum(accs[i])
            for i, name in enumerate(spec.accumulators)
        }
        return (
            jnp.sum(nodes),
            acc_dict,
            jnp.any(over != 0) | (steps >= max_steps),
        )

    return run


# ----------------------------------------------------------------- fib

def fib_spec(
    max_n: int = 32,
    lanes: Tuple[int, int] = (8, 128),
    min_idle_div: int = 8,
) -> VectorTaskSpec:
    """fib as a vector-tier task family: frame = (n,), children (n-1, n-2),
    leaves contribute F(n) = n for n in {0, 1}. Task count equals the naive
    recursion-tree node count (2*fib(n+1) - 1), the same count the native
    C++ runtime reports for its fib (native/src/workloads: one task per
    call) - the join/SUM tasks of the scalar-tier fib are an artifact of
    explicit continuation passing and do not exist here (the reference's
    fib likewise has no separate join tasks, test/fib/fib.c:119-131)."""

    def seed(args):
        n = args[0]
        return (n,), jnp.where(n >= 2, 2, 0)

    def child(frame, k, jnp):
        c = frame[0] - 1 - k
        return (c,), jnp.where(c >= 2, 2, 0)

    def contrib(cframe, ccount, jnp):
        # Expanded leaves are n in {0, 1}: contribution is n itself.
        return {"value": jnp.where(ccount == 0, cframe[0], 0)}

    def root_contrib(args):
        return {"value": args[0]}

    return VectorTaskSpec(
        name="vfib",
        frame_words=1,
        seed=seed,
        child=child,
        contrib=contrib,
        accumulators=("value",),
        out_acc="value",
        stack_depth=max_n + 2,
        lanes=lanes,
        min_idle_div=min_idle_div,
        root_contrib=root_contrib,
    )


# ------------------------------------------------------------- n-queens

def _popcount(x, jnp):
    """SWAR popcount over int32 planes (no hardware popcount in the VPU
    op set; 12 plane ops)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return (x * 0x01010101) >> 24


def nqueens_spec(
    n: int,
    lanes: Tuple[int, int] = (8, 128),
    min_idle_div: int = 8,
) -> VectorTaskSpec:
    """N-queens as a vector-tier task family (reference workload
    test/misc/nqueens): a frame is a partial placement as three bitboards
    (cols, left-diag, right-diag attack masks); a task's children are the
    safe columns of the next row, selected by ordinal with an unrolled
    k-th-set-bit scan (all branch-free plane ops). Completed boards
    (cols == full) contribute one solution. Task count = number of safe
    partial placements (the same tree the host model explores)."""
    if not (1 <= n <= 16):
        raise ValueError("nqueens_spec wants 1 <= n <= 16")
    full = (1 << n) - 1

    def counts_of(cols, ld, rd, jnp):
        free = jnp.bitwise_not(cols | ld | rd) & full
        return jnp.where(cols == full, 0, _popcount(free, jnp))

    def seed(args):
        # args unused: the seed is the empty board. jnp-typed zeros keep
        # the bridge's seed plumbing uniform.
        z = args[0] * 0
        return (z, z, z), jnp.int32(n)

    def child(frame, k, jnp):
        cols, ld, rd = frame
        free = jnp.bitwise_not(cols | ld | rd) & full
        # k-th set bit of `free` (branch-free ordinal selection).
        bit = jnp.zeros_like(free)
        rank = jnp.zeros_like(free)
        for b in range(n):
            m = (free >> b) & 1
            hit = (m == 1) & (rank == k)
            bit = jnp.where(hit, 1 << b, bit)
            rank = rank + m
        ncols = cols | bit
        nld = ((ld | bit) << 1) & full
        nrd = (rd | bit) >> 1
        return (ncols, nld, nrd), counts_of(ncols, nld, nrd, jnp)

    def contrib(cframe, ccount, jnp):
        return {"solutions": (cframe[0] == full).astype(jnp.int32)}

    return VectorTaskSpec(
        name="vnqueens",
        frame_words=3,
        seed=seed,
        child=child,
        contrib=contrib,
        accumulators=("solutions",),
        out_acc="solutions",
        stack_depth=n + 2,
        lanes=lanes,
        min_idle_div=min_idle_div,
    )
