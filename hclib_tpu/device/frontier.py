"""Graph-analytics frontier tier: BFS / SSSP / PageRank on the batch lanes.

ROADMAP direction 5: UTS and fib prove dynamic trees, but nothing in the
bench family exercised skewed frontier expansion over a large in-HBM
structure. This module is that workload family - an adjacency kept in
HBM is traversed by EXPAND task descriptors, and because every EXPAND of
one traversal is the same kind, each round's frontier dynamically groups
onto ONE per-F_FN batch lane (the PR 3 tier) and fires ``width`` at a
time through one tiled body, with the cross-round double-buffered
prefetch streaming the next batch's edge slabs under the current batch's
relax loop.

**Blocked CSR.** The adjacency is CSR with every vertex's edge run
padded out to ``EBLOCK``-edge blocks (``Graph``): ``indices`` (and
``weights``) are ``(nblocks, EBLOCK)`` int32 arrays in HBM, and a
vertex's edges occupy blocks ``[blk_start[v], blk_start[v] +
blk_count[v])``. Block alignment is what makes the edge slab a STATIC
DMA shape - each EXPAND names one block, so a hub vertex is simply many
same-kind descriptors (the R-MAT skew becomes batch occupancy instead
of a ragged-transfer problem), and the slab address is a legal dynamic
offset on real hardware (Mosaic wants coarse alignment).

**Descriptor ABI.** ``EXPAND(v, blk, carry, cnt)``: expand block ``blk``
(``cnt`` live edges) of vertex ``v``, propagating ``carry`` - the
tentative distance of ``v`` (BFS/SSSP) or the residual mass delivered to
``v`` (PageRank). Everything a task needs rides its own descriptor plus
per-vertex state in SMEM value slots, and EXPANDs are spawned link-free,
so they are migratable on every multi-device runner by construction.

**Relaxation model.** BFS and SSSP are label-correcting: an edge
``v -> u`` relaxes ``dist[u] = min(dist[u], carry + w)`` and an
IMPROVING relax re-spawns u's blocks with the new distance. The final
distance array is the exact shortest-path fixpoint - independent of
execution order, batch grouping, and migration - which is what makes
"bit-identical across scalar dispatch, batched frontier, and the
4-device mesh" hold without any ordering machinery: per-device distance
arrays are local caches combined by elementwise min (a suppressed spawn
on one device means an equal-or-better carry was already propagated
there; propagation is transitive). Level-synchronous BFS order is the
special case the lane LIFO/FIFO approximates; with
``priority_buckets=B`` (ISSUE 15) SSSP runs TRUE delta-stepping -
EXPANDs route into bucket ring ``dist // delta`` and the scheduler
retires the lowest non-empty bucket first, so most relaxations happen
at final distances and the re-relaxation work of label correction
largely disappears (executed-EXPAND count and TEPS are the headline;
the fixpoint is the same either way). PageRank is push-style with integer
fixed-point mass: a delivery of ``q`` to ``u`` retains
``q - deg(u) * q_child`` into rank[u] and forwards ``q_child =
(alpha * q) / deg(u)`` along every out-edge, folding entirely into
rank[u] once ``q`` drops under ``reps`` - mass is conserved exactly,
every delivery's children depend only on its own descriptor, so the
result is deterministic across schedules and mesh runs (per-device
ranks combine by sum), and it approximates the float PageRank series
``(1-alpha) * sum_k alpha^k P^k`` to the fixed-point tolerance.

**Firing policy.** Frontier expansion is exactly the chained-spawner
shape the lane-policy watch item predicted: every batch deposits a
fan-out of same-kind children on the ready ring, so under pure
ring-drain-first firing the lane sits starved for the whole routing
drain. The frontier megakernels therefore default the ISSUE 10 age
trigger ON (``lane_max_age = 4 * width``): a lane that has held entries
for that many rounds jumps the ring and fires - full batches mid-drain
once >= width entries accumulated - keeping ``lane_partial_age`` and the
device-side ``max_starved_age`` gauge bounded (the frontier-batch perf
guard pins both).

**TEPS.** Every EXPAND counts its ``cnt`` live edges into value slot
``V_EDGES``; traversed-edges/s = edges / wall over a run - the headline
the graph bench reports beside UTS nodes/s. Improving relaxations (or
PageRank deliveries) count into ``V_RELAX``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.locality import MeshPlacement, resolve_placement
from .descriptor import TaskGraphBuilder
from .megakernel import BK_MAX, BatchSpec, Megakernel, _batch_stub

__all__ = [
    "EBLOCK",
    "INF",
    "FR_EXPAND",
    "Graph",
    "FrontierKernel",
    "bfs_kernel",
    "sssp_kernel",
    "pagerank_kernel",
    "make_frontier_megakernel",
    "run_frontier",
    "seed_frontier",
    "host_bfs",
    "host_sssp",
    "host_pagerank_push",
    "host_pagerank",
    "priority_bucket",
    "default_delta",
    "PR_NUM",
    "PR_DEN",
]

# Edge-block width: one VMEM lane row of int32, and the blocked-CSR
# alignment unit (every vertex's edge run starts on a block boundary).
EBLOCK = 128

# Unreached distance sentinel (fits int32 with relax headroom: INF + any
# edge weight stays positive and still compares greater than any real
# path length).
INF = 0x3FFFFFFF

# The EXPAND kernel's table index: frontier megakernels are single-kind
# (one traversal family per build), so the id is fixed - which is also
# what puts every frontier descriptor on ONE batch lane.
FR_EXPAND = 0

# PageRank damping as an exact int32 rational: alpha = 13/16 = 0.8125
# (exactly representable in the float host reference too, so the only
# device-vs-float divergence is fixed-point truncation).
PR_NUM = 13
PR_DEN = 16

# Value-slot layout: two counters, then the vertex table (3 words per
# vertex: block start / block count / out-degree), then per-vertex state
# (distance or rank). All host-preset, so the whole layout stages into
# SMEM and the device reads it with plain dynamic indexing.
V_EDGES = 0   # traversed edges (the TEPS numerator; combines by sum)
V_RELAX = 1   # improving relaxations / PR deliveries (combines by sum)
VT_BASE = 8


class Graph:
    """Host-side blocked-CSR adjacency (module docstring): dense int32
    arrays shaped for the device tier plus python adjacency for the host
    reference arms."""

    def __init__(
        self,
        n: int,
        src: np.ndarray,
        dst: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ) -> None:
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if src.shape != dst.shape:
            raise ValueError("src/dst must be the same length")
        if len(src) and (
            src.min() < 0 or src.max() >= n or dst.min() < 0 or dst.max() >= n
        ):
            raise ValueError(f"edge endpoints out of range [0, {n})")
        self.n = int(n)
        self.m = int(len(src))
        w = (
            np.asarray(weights, np.int64)
            if weights is not None
            else np.ones(self.m, np.int64)
        )
        if w.shape != src.shape:
            raise ValueError("weights must match the edge count")
        if len(w) and w.min() < 0:
            raise ValueError("weights must be >= 0")
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        self.deg = np.bincount(src, minlength=n).astype(np.int32)
        self.blk_count = ((self.deg + EBLOCK - 1) // EBLOCK).astype(np.int32)
        self.blk_start = np.zeros(n, np.int32)
        if n > 1:
            self.blk_start[1:] = np.cumsum(self.blk_count)[:-1].astype(
                np.int32
            )
        self.nblocks = max(1, int(self.blk_count.sum()))
        self.indices = np.full((self.nblocks, EBLOCK), -1, np.int32)
        self.weights = np.zeros((self.nblocks, EBLOCK), np.int32)
        # Per-vertex adjacency (python lists) for the host references.
        splits = np.searchsorted(src, np.arange(n + 1))
        self.adj: List[np.ndarray] = []
        self.adj_w: List[np.ndarray] = []
        for v in range(n):
            lo, hi = int(splits[v]), int(splits[v + 1])
            self.adj.append(dst[lo:hi].astype(np.int32))
            self.adj_w.append(w[lo:hi].astype(np.int32))
            d = hi - lo
            b0 = int(self.blk_start[v])
            flat = self.indices[
                b0 : b0 + int(self.blk_count[v])
            ].reshape(-1)
            flat[:d] = dst[lo:hi]
            wflat = self.weights[
                b0 : b0 + int(self.blk_count[v])
            ].reshape(-1)
            wflat[:d] = w[lo:hi]

    def block_cnt(self, v: int, i: int) -> int:
        """Live edges in block ``i`` of vertex ``v`` (the descriptor's
        ``cnt`` arg): full blocks then the ragged tail."""
        return int(min(EBLOCK, int(self.deg[v]) - i * EBLOCK))

    # -- value-slot layout --

    @property
    def st_base(self) -> int:
        return VT_BASE + 3 * self.n

    @property
    def num_value_slots(self) -> int:
        """Host-preset slots: counters + vertex table + per-vertex state."""
        return self.st_base + self.n

    def preset_values(self, num_values: int, state0: int) -> np.ndarray:
        """The host ivalues row: vertex table filled, per-vertex state
        initialized to ``state0`` (INF for distances, 0 for ranks)."""
        if num_values < self.num_value_slots:
            raise ValueError(
                f"graph wants num_values >= {self.num_value_slots}, "
                f"got {num_values}"
            )
        iv = np.zeros(num_values, np.int32)
        vt = np.stack(
            [self.blk_start, self.blk_count, self.deg], axis=1
        ).reshape(-1)
        iv[VT_BASE : VT_BASE + 3 * self.n] = vt
        iv[self.st_base : self.st_base + self.n] = state0
        return iv


# ----------------------------------------------------------- device tier


def _spawn_blocks(kctx, u, carry) -> None:
    """Spawn one EXPAND per adjacency block of vertex ``u`` (the device
    side of frontier growth; the host seeding mirrors it exactly)."""
    vt = VT_BASE + 3 * u
    bs = kctx.ivalues[vt]
    bc = kctx.ivalues[vt + 1]
    deg = kctx.ivalues[vt + 2]

    def sp(i, _):
        cnt = jnp.clip(deg - i * EBLOCK, 0, EBLOCK)
        kctx.spawn(FR_EXPAND, [u, bs + i, carry, cnt], nargs=4)
        return 0

    jax.lax.fori_loop(0, bc, sp, 0)


class FrontierKernel:
    """One traversal family as an edge-slab pipeline: a per-edge scalar
    ``relax(fk, kctx, u, w, carry)`` plus the slab declarations, from
    which BOTH dispatch spellings derive (the TileKernel pattern): the
    scalar-tier kernel (DMA one block in, relax its edges - the
    bit-identity reference arm) and the batched body (all live slots'
    slabs in flight before the first wait, the prospective next batch's
    slabs prefetched into the other VMEM half during this round's relax
    loop, the PR 3 double-buffer protocol) with its ``drain``. One relax
    trace means scalar-vs-batched identity holds by construction - and
    for these kernels the RESULT is additionally schedule-independent
    (module docstring), which is what extends the identity across the
    mesh.

    ``relax`` receives the kernel itself first so it can read the
    graph-layout base ``fk.st_base`` at TRACE time -
    ``make_frontier_megakernel`` stamps it before the megakernel's lazy
    first trace."""

    def __init__(
        self,
        name: str,
        relax: Callable,
        weighted: bool,
        state0: int,
    ) -> None:
        self.name = name
        self._relax = relax
        self.weighted = bool(weighted)
        self.state0 = int(state0)
        # Per-vertex state region base in the value slots; stamped by
        # make_frontier_megakernel from the graph layout (trace-time
        # read, so the kernel must be bound to ONE graph layout).
        self.st_base: Optional[int] = None

    def relax(self, kctx, u, w, carry) -> None:
        if self.st_base is None:
            raise ValueError(
                "FrontierKernel has no graph layout bound: build it "
                "through make_frontier_megakernel (which stamps st_base)"
            )
        self._relax(self, kctx, u, w, carry)

    def data_specs(self, graph: Graph) -> Dict[str, jax.ShapeDtypeStruct]:
        specs = {
            "indices": jax.ShapeDtypeStruct(
                (graph.nblocks, EBLOCK), jnp.int32
            )
        }
        if self.weighted:
            specs["weights"] = jax.ShapeDtypeStruct(
                (graph.nblocks, EBLOCK), jnp.int32
            )
        return specs

    def data(self, graph: Graph) -> Dict[str, np.ndarray]:
        d = {"indices": graph.indices}
        if self.weighted:
            d["weights"] = graph.weights
        return d

    def _eff_cnt(self, kctx, v, blk, cnt):
        """Live-edge count of block ``blk`` as THIS device sees it -
        the static tier trusts the descriptor (``cnt`` unchanged, so
        static builds trace zero extra words); the dynamic-graph
        subclass clamps to the local vertex table so an EXPAND spawned
        after a splice the local replica has not applied yet never
        reads past the locally-live edges (dyngraph.py)."""
        return cnt

    def _relax_block(self, kctx, eslab, wslab, carry, cnt) -> None:
        """The shared relax loop over one loaded edge slab: the single
        arithmetic trace both dispatch spellings run. ``eslab``/``wslab``
        are zero-arg VMEM readers ``f(e) -> scalar``."""
        kctx.ivalues[V_EDGES] = kctx.ivalues[V_EDGES] + cnt

        def e_body(e, _):
            u = eslab(e)
            w = wslab(e) if self.weighted else jnp.int32(0)
            self.relax(kctx, u, w, carry)
            return 0

        jax.lax.fori_loop(0, cnt, e_body, 0)

    # -- scalar-tier spelling --

    def scalar_scratch(self) -> Dict[str, Any]:
        sc: Dict[str, Any] = {
            "fr_idx": pltpu.VMEM((EBLOCK,), jnp.int32),
            "fr_lsem": pltpu.SemaphoreType.DMA((1,)),
        }
        if self.weighted:
            sc["fr_wgt"] = pltpu.VMEM((EBLOCK,), jnp.int32)
        return sc

    def scalar_kernel(self, ctx) -> None:
        v, blk, carry, cnt = (ctx.arg(i) for i in range(4))
        sem = ctx.scratch["fr_lsem"].at[0]
        copies = [
            pltpu.make_async_copy(
                ctx.data["indices"].at[blk], ctx.scratch["fr_idx"], sem
            )
        ]
        if self.weighted:
            copies.append(
                pltpu.make_async_copy(
                    ctx.data["weights"].at[blk], ctx.scratch["fr_wgt"], sem
                )
            )
        for cp in copies:
            cp.start()
        for cp in copies:
            cp.wait()
        cnt = self._eff_cnt(ctx, v, blk, cnt)
        self._relax_block(
            ctx,
            lambda e: ctx.scratch["fr_idx"][e],
            (lambda e: ctx.scratch["fr_wgt"][e]) if self.weighted else None,
            carry,
            cnt,
        )

    # -- batch-tier spelling --

    def batch_scratch(self, width: int) -> Dict[str, Any]:
        sc: Dict[str, Any] = {
            # Double-buffered (leading 2): one half relaxes while the
            # tier's cross-round prefetch streams the next batch's edge
            # slabs into the other.
            "fr_idx": pltpu.VMEM((2, width, EBLOCK), jnp.int32),
            "fr_lsem": pltpu.SemaphoreType.DMA((2, width)),
        }
        if self.weighted:
            sc["fr_wgt"] = pltpu.VMEM((2, width, EBLOCK), jnp.int32)
        return sc

    def _slot_loads(self, ctx, buf, slot: int, blk, wait: bool) -> None:
        """Start (or retire) the edge-slab copies of batch slot ``slot``
        into half ``buf`` - one semaphore per (half, slot) counting every
        stream, each start matched by exactly one wait."""
        sem = ctx.scratch["fr_lsem"].at[buf, slot]
        cp = pltpu.make_async_copy(
            ctx.data["indices"].at[blk],
            ctx.scratch["fr_idx"].at[buf, slot],
            sem,
        )
        (cp.wait if wait else cp.start)()
        if self.weighted:
            cp = pltpu.make_async_copy(
                ctx.data["weights"].at[blk],
                ctx.scratch["fr_wgt"].at[buf, slot],
                sem,
            )
            (cp.wait if wait else cp.start)()

    def batch_body(self, ctx) -> None:
        width = ctx.width
        buf = ctx.buf

        # Phase 1: start edge-slab copies for live slots the prefetch
        # didn't already cover.
        for b in range(width):
            @pl.when(ctx.live(b) & (jnp.int32(b) >= ctx.prefetched))
            def _(b=b):
                self._slot_loads(ctx, buf, b, ctx.arg(b, 1), wait=False)

        # Phase 2: the prospective NEXT batch's slabs start into the
        # other half now, landing under this round's relax loops.
        obuf = 1 - buf
        for b in range(width):
            @pl.when(jnp.int32(b) < ctx.prefetch_count)
            def _(b=b):
                self._slot_loads(ctx, obuf, b, ctx.next_arg(b, 1),
                                 wait=False)

        # Phase 3: retire this round's loads (prefetched slots wait the
        # copies LAST round's phase 2 started into this half).
        for b in range(width):
            @pl.when(ctx.live(b))
            def _(b=b):
                self._slot_loads(ctx, buf, b, ctx.arg(b, 1), wait=True)

        # Phase 4: per-slot relax loops, in slot order - each slot's
        # relaxes see the SMEM state earlier slots of the same batch
        # wrote, exactly as scalar dispatch of the same rows would.
        for b in range(width):
            @pl.when(ctx.live(b))
            def _(b=b):
                kctx = ctx.slot_ctx(b)
                self._relax_block(
                    kctx,
                    lambda e, b=b: ctx.scratch["fr_idx"][buf, b, e],
                    (lambda e, b=b: ctx.scratch["fr_wgt"][buf, b, e])
                    if self.weighted
                    else None,
                    ctx.arg(b, 2),
                    self._eff_cnt(
                        kctx, ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 3)
                    ),
                )

    def batch_drain(self, ctx) -> None:
        """Retire an in-flight prefetch whose target entries will spill
        instead of batching (scheduler exit: fuel, quiesce) - no DMA
        outlives the round loop."""
        for b in range(ctx.width):
            @pl.when(jnp.int32(b) < ctx.prefetched)
            def _(b=b):
                self._slot_loads(ctx, ctx.buf, b, ctx.arg(b, 1), wait=True)


# ----------------------------------------------------- the three kernels


def bfs_kernel(spawn: Callable = _spawn_blocks) -> FrontierKernel:
    """Level-style BFS as monotone label correction: carry is dist[v] at
    spawn; an improving hop re-spawns the target's blocks. ``spawn``
    is the block spawner (dyngraph.py substitutes the two-range spare-
    aware spelling; the default traces byte-identically to PR 10)."""

    def relax(fk, kctx, u, w, carry) -> None:
        nd = carry + 1
        st = fk.st_base + u
        better = nd < kctx.ivalues[st]

        @pl.when(better)
        def _():
            kctx.ivalues[st] = nd
            kctx.ivalues[V_RELAX] = kctx.ivalues[V_RELAX] + 1
            spawn(kctx, u, nd)

    return FrontierKernel("fr_bfs", relax, weighted=False, state0=INF)


def sssp_kernel(spawn: Callable = _spawn_blocks) -> FrontierKernel:
    """SSSP (nonnegative int weights): the same monotone relaxation
    with ``carry + w``. Unordered, the lane's pop order stands in for
    the bucket discipline and re-expansions are the correction; with
    ``priority_buckets`` the build runs TRUE delta-stepping (bucket =
    dist // delta, lowest first) and the re-expansions mostly vanish -
    exactness depends on neither (the relax body is identical)."""

    def relax(fk, kctx, u, w, carry) -> None:
        nd = carry + w
        st = fk.st_base + u
        better = nd < kctx.ivalues[st]

        @pl.when(better)
        def _():
            kctx.ivalues[st] = nd
            kctx.ivalues[V_RELAX] = kctx.ivalues[V_RELAX] + 1
            spawn(kctx, u, nd)

    return FrontierKernel("fr_sssp", relax, weighted=True, state0=INF)


# PageRank residual-magnitude bands grow by this factor per bucket:
# bucket k holds deliveries with q in [reps*2^k, reps*2^(k+1)) - small
# residuals (which FOLD, freeing rows) land in bucket 0 and fire first,
# so the push collapses each subtree before the next large delivery
# splits (depth-first by magnitude = the bounded-frontier fix). Factor
# 2 resolves one alpha-split step (a delivery's children are ~q/deg:
# always a lower band), which the live-set model showed is what holds
# the peak flat as m0 grows; coarser bands leak whole generations into
# one bucket and the breadth returns.
PR_BAND = 2


def priority_bucket(kind: str, carry: int, *, delta: int = 1,
                    reps: int = 64) -> int:
    """HOST-int spelling of the priority-bucket functions the device
    routing runs (``_bucket_fn`` below is the traced twin - keep the two
    in lockstep; analysis/model.py certifies the bucketed pop order
    through THIS spelling). ``carry`` is the descriptor's carry word:
    the tentative distance (bfs/sssp - bucket = dist // delta, the
    delta-stepping discipline) or the delivered residual mass
    (pagerank - ascending magnitude bands). The scheduler clips into
    [0, priority_buckets)."""
    if kind in ("bfs", "sssp", "fr_bfs", "fr_sssp"):
        return int(carry) // max(1, int(delta))
    b = 0
    for k in range(1, BK_MAX):
        b += int(carry) >= int(reps) * (PR_BAND ** k)
    return b


def _bucket_fn(name: str, delta: int, reps: int):
    """Device (traced int32) twin of ``priority_bucket`` - the
    ``BatchSpec.priority`` callable for one frontier kind. Reads ONLY
    the descriptor's own arg words (carry is arg 2), which is what
    makes spilled/stolen/resharded residue re-bucket on its next
    routing pop."""
    if name in ("fr_bfs", "fr_sssp"):
        d = max(1, int(delta))
        return lambda arg: arg(2) // jnp.int32(d)

    def pr(arg):
        q = arg(2)
        b = jnp.int32(0)
        for k in range(1, BK_MAX):
            b = b + (q >= jnp.int32(int(reps) * (PR_BAND ** k))).astype(
                jnp.int32
            )
        return b

    return pr


def default_delta(graph: Graph) -> int:
    """Default delta-stepping bucket width for a graph: max edge weight
    over the bucket-ring count (>= 1), so the static ring set resolves
    roughly one relaxation step where the frontier lives. Measured on
    seeded weighted R-MAT this FINE delta beats the classic coarse
    ~max-weight choice even though far distances clip into the top
    ring (executed-EXPAND 0.68-0.77x FIFO at delta = w_max/8 vs
    0.85-0.87x at w_max/2): the early frontier is where re-relaxation
    happens, so that is where resolution pays. Override via
    ``make_frontier_megakernel(delta=)``."""
    w = int(graph.weights.max()) if graph.m else 1
    return max(1, w // BK_MAX)


def _pr_split(q, deg):
    """Child mass of a PageRank delivery ``q`` at a vertex of out-degree
    ``deg`` (int fixed point) - the ONE place the split arithmetic
    lives, shared by the device relax (traced int32), host seeding, and
    the exact host twin (python ints, so the twin is bit-exact)."""
    if isinstance(q, (int, np.integer)):
        return (int(q) * PR_NUM // PR_DEN) // max(int(deg), 1)
    return (q * PR_NUM // PR_DEN) // jnp.maximum(deg, 1)


def pagerank_kernel(reps: int = 64,
                    spawn: Callable = _spawn_blocks) -> FrontierKernel:
    """Push-style PageRank on integer fixed-point mass: a delivery of
    ``q`` retains ``q - deg*q_child`` into rank[u] and forwards
    ``q_child`` per out-edge; ``q < reps`` (or a zero child, or a
    dangling target) folds the whole delivery into rank[u]. Mass
    conserves exactly, so the result is deterministic across schedules
    and sums across mesh devices."""

    reps = int(reps)
    if reps < 1:
        raise ValueError(f"pagerank reps must be >= 1, got {reps}")

    def relax(fk, kctx, u, w, q) -> None:
        vt = VT_BASE + 3 * u
        deg = kctx.ivalues[vt + 2]
        qc = _pr_split(q, deg)
        expand = (q >= jnp.int32(reps)) & (qc > 0) & (deg > 0)
        retained = jnp.where(expand, q - deg * qc, q)
        st = fk.st_base + u
        kctx.ivalues[st] = kctx.ivalues[st] + retained
        kctx.ivalues[V_RELAX] = kctx.ivalues[V_RELAX] + 1

        @pl.when(expand)
        def _():
            spawn(kctx, u, qc)

    fk = FrontierKernel("fr_pagerank", relax, weighted=False, state0=0)
    fk.reps = reps
    return fk


# ------------------------------------------------------------ host side

_KINDS: Dict[str, Callable[..., FrontierKernel]] = {
    "bfs": bfs_kernel,
    "sssp": sssp_kernel,
    "pagerank": pagerank_kernel,
}


def seed_frontier(
    builder: TaskGraphBuilder,
    graph: Graph,
    kind: str,
    src: int = 0,
    m0: int = 1 << 14,
    reps: int = 64,
) -> List[Tuple[int, ...]]:
    """Host seeding (mirrors the device relax exactly). BFS/SSSP: dist
    preset 0 at ``src`` (the caller's preset row carries it) and one
    EXPAND per block of ``src``. PageRank: every vertex receives the
    initial mass ``m0`` host-side - retained rank goes into the preset
    row, survivors seed their blocks. Returns the seeded arg tuples (the
    placement path deals them across devices)."""
    seeds: List[Tuple[int, ...]] = []
    if kind in ("bfs", "sssp"):
        v = int(src)
        if not 0 <= v < graph.n:
            raise ValueError(f"source {v} out of range [0, {graph.n})")
        for i in range(int(graph.blk_count[v])):
            seeds.append(
                (v, int(graph.blk_start[v]) + i, 0, graph.block_cnt(v, i))
            )
    elif kind == "pagerank":
        for v in range(graph.n):
            deg = int(graph.deg[v])
            qc = _pr_split(m0, deg)
            if m0 >= reps and qc > 0 and deg > 0:
                for i in range(int(graph.blk_count[v])):
                    seeds.append(
                        (
                            v,
                            int(graph.blk_start[v]) + i,
                            qc,
                            graph.block_cnt(v, i),
                        )
                    )
    else:
        raise ValueError(f"unknown frontier kind {kind!r} (bfs|sssp|pagerank)")
    if builder is not None:
        for args in seeds:
            builder.add(FR_EXPAND, args=list(args))
    return seeds


def _pr_seed_rank(graph: Graph, m0: int, reps: int) -> np.ndarray:
    """Rank retained by the host-side seed deliveries (the preset the
    device run starts from; mirrors seed_frontier's split decisions)."""
    rank = np.zeros(graph.n, np.int64)
    for v in range(graph.n):
        deg = int(graph.deg[v])
        qc = _pr_split(m0, deg)
        if m0 >= reps and qc > 0 and deg > 0:
            rank[v] = m0 - deg * qc
        else:
            rank[v] = m0
    return rank


def host_bfs(graph: Graph, src: int = 0) -> np.ndarray:
    """Exact hop distances (frontier BFS; INF where unreached)."""
    dist = np.full(graph.n, INF, np.int64)
    dist[src] = 0
    frontier = [int(src)]
    while frontier:
        nxt: List[int] = []
        for v in frontier:
            nd = dist[v] + 1
            for u in graph.adj[v]:
                if nd < dist[u]:
                    dist[u] = nd
                    nxt.append(int(u))
        frontier = nxt
    return dist.astype(np.int32)


def host_sssp(graph: Graph, src: int = 0) -> np.ndarray:
    """Exact shortest paths (Dijkstra; nonnegative int weights)."""
    import heapq

    dist = np.full(graph.n, INF, np.int64)
    dist[src] = 0
    heap: List[Tuple[int, int]] = [(0, int(src))]
    while heap:
        d, v = heapq.heappop(heap)
        if d > dist[v]:
            continue
        for u, w in zip(graph.adj[v], graph.adj_w[v]):
            nd = d + int(w)
            if nd < dist[u]:
                dist[u] = nd
                heapq.heappush(heap, (nd, int(u)))
    return dist.astype(np.int32)


def host_pagerank_push(
    graph: Graph, m0: int = 1 << 14, reps: int = 64
) -> Tuple[np.ndarray, int]:
    """Exact integer twin of the device push (same split, same fold
    rule, any processing order - the bit-identity reference arm).
    Returns (rank, deliveries)."""
    rank = _pr_seed_rank(graph, m0, reps)
    queue: List[Tuple[int, int]] = []
    # Seed deliveries: every surviving seed vertex pushes qc along each
    # out-edge (the queue order is irrelevant - the push is
    # schedule-independent, which is the property under test).
    for v in range(graph.n):
        deg = int(graph.deg[v])
        qc = _pr_split(m0, deg)
        if m0 >= reps and qc > 0 and deg > 0:
            for u in graph.adj[v]:
                queue.append((int(u), qc))
    deliveries = 0
    while queue:
        u, q = queue.pop()
        deliveries += 1
        deg = int(graph.deg[u])
        qc = _pr_split(q, deg)
        if q >= reps and qc > 0 and deg > 0:
            rank[u] += q - deg * qc
            for t in graph.adj[u]:
                queue.append((int(t), qc))
        else:
            rank[u] += q
    return rank.astype(np.int64), deliveries


def host_pagerank(
    graph: Graph,
    alpha: float = PR_NUM / PR_DEN,
    iters: int = 80,
    m0: float = 1.0,
) -> np.ndarray:
    """Float PageRank series the push approximates: rank = sum_k of the
    mass retained at step k, with dangling vertices absorbing fully
    (the push's fold rule). Normalized to ``m0`` seed mass per vertex."""
    m = np.full(graph.n, float(m0))
    rank = np.zeros(graph.n)
    deg = graph.deg.astype(np.float64)
    for _ in range(iters):
        keep = np.where(deg > 0, (1.0 - alpha) * m, m)
        rank += keep
        push = np.where(deg > 0, alpha * m / np.maximum(deg, 1), 0.0)
        m2 = np.zeros(graph.n)
        for v in range(graph.n):
            if push[v] > 0:
                np.add.at(m2, graph.adj[v], push[v])
        m = m2
    return rank + m  # fold the residual tail


# ------------------------------------------------------------ megakernel


def _default_lane_max_age(width: int) -> int:
    """Frontier builds default the ISSUE 10 age trigger ON at 4x the
    lane width (module docstring); HCLIB_TPU_LANE_MAX_AGE (handled by
    Megakernel itself) still overrides process-wide."""
    from ..runtime.env import env_set

    if env_set("HCLIB_TPU_LANE_MAX_AGE"):
        return None  # type: ignore[return-value]  # env wins
    return 4 * width


def make_frontier_megakernel(
    fk: FrontierKernel,
    graph: Graph,
    *,
    width: int = 8,
    prefetch: bool = True,
    capacity: int = 512,
    num_values: Optional[int] = None,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    lane_max_age: Optional[int] = None,
    priority_buckets: Optional[int] = None,
    delta: Optional[int] = None,
) -> Megakernel:
    """Build a traversal's megakernel. ``width=0`` is the scalar-
    dispatch arm (the bit-identity reference); ``width>0`` routes EXPAND
    through the batch lanes with the double-buffered edge-slab prefetch,
    and arms the age-triggered firing policy (``lane_max_age``; default
    4*width, 0 disables).

    ``priority_buckets=B`` (batched builds only) arms the ISSUE 15
    priority tier: EXPANDs route into B bucket rings popped lowest-
    nonempty-first - bucket = dist // ``delta`` for BFS/SSSP (TRUE
    delta-stepping: ordered relaxation replaces label-correction
    re-relaxation, so the executed-EXPAND count drops - the raw-speed
    story) or the residual-magnitude band for PageRank (small deliveries
    fold first, bounding the live frontier). Exactness never depends on
    it: the result is schedule-independent (certified via ``si_claim``)
    and bit-identical to the unordered arm."""
    if num_values is None:
        num_values = graph.num_value_slots + 8
    if priority_buckets is None:
        # The process-wide spelling reaches the builder too (the
        # builder must know: it disables the cross-round prefetch and
        # rescales the age default for bucketed builds).
        from ..runtime.env import env_int

        priority_buckets = env_int("HCLIB_TPU_PRIORITY_BUCKETS", None)
    priority_buckets = int(priority_buckets or 0)
    if priority_buckets and not width:
        raise ValueError(
            "priority_buckets needs the batched arm (width > 0): the "
            "bucket rings layer over the per-kind batch lanes"
        )
    if delta is None:
        delta = default_delta(graph)
    if width:
        # Bucketed builds genuinely run WITHOUT the cross-round
        # prefetch (the next firing ring is chosen at fire time, so
        # there is no prospective next batch; the scheduler would never
        # announce one anyway) - the spec says so too, so describe()
        # and the prefetch-protocol analysis see the build that
        # actually runs. Bucket rings still pop FIFO (the scheduler's
        # bucket-ring discipline, independent of spec.prefetch).
        prefetch = bool(prefetch) and not priority_buckets
        spec = BatchSpec(
            fk.batch_body,
            width=width,
            prefetch=prefetch,
            drain=fk.batch_drain if prefetch else None,
            # The priority callable is carried unconditionally (it is
            # only consulted when priority_buckets arms the tier, so an
            # unbucketed build stays byte-identical - asserted in
            # tests/test_priority.py).
            priority=_bucket_fn(fk.name, delta, getattr(fk, "reps", 64)),
        )
        kernels = [(fk.name, _batch_stub)]
        route = {fk.name: spec}
        scratch = fk.batch_scratch(width)
        if lane_max_age is None:
            if priority_buckets:
                # Bucketed builds arm the SAME age-fire guard but at
                # the DRAIN-PERIOD scale (2x capacity - a routing
                # drain can hold a ring unfired for at most ~capacity
                # rounds, the ring size), not PR 10's 4*width latency
                # tune: at 4*width the guard fires constantly during
                # long routing drains and every forced fire jumps the
                # bucket order (measured: executed-EXPAND ratio decays
                # 0.63x -> 0.86x and the PageRank live-set fix washes
                # out 0.26x -> 0.92x). At 2x capacity it is a pure
                # starvation backstop: zero fires in steady state,
                # high buckets still provably bounded against a
                # pathological low-bucket refill.
                from ..runtime.env import env_set

                lane_max_age = (
                    None  # env wins, Megakernel resolves it
                    if env_set("HCLIB_TPU_LANE_MAX_AGE")
                    else 2 * capacity
                )
            else:
                lane_max_age = _default_lane_max_age(width)
    else:
        kernels = [(fk.name, fk.scalar_kernel)]
        route = None
        scratch = fk.scalar_scratch()
        lane_max_age = 0 if lane_max_age is None else lane_max_age
    if fk.st_base is not None and fk.st_base != graph.st_base:
        raise ValueError(
            "FrontierKernel is already bound to a different graph layout "
            f"(st_base {fk.st_base} vs {graph.st_base}): build a fresh "
            "kernel per graph - megakernels trace lazily, so rebinding "
            "would silently retarget an earlier build's state region"
        )
    fk.st_base = graph.st_base
    mk = Megakernel(
        kernels=kernels,
        route=route,
        data_specs=fk.data_specs(graph),
        scratch_specs=scratch,
        capacity=capacity,
        num_values=num_values,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        checkpoint=checkpoint,
        lane_max_age=lane_max_age,
        priority_buckets=priority_buckets,
    )
    # Stamp the graph layout the traced kernel is bound to: the relax
    # closures bake st_base (and the data specs bake nblocks) into the
    # trace, so running this build over a DIFFERENT graph layout would
    # silently read the wrong state region - run_frontier refuses it.
    mk._frontier_layout = (fk.name, graph.n, graph.nblocks, graph.st_base)
    # Schedule-independence claim (the exactness model this module's
    # docstring promises): certified lazily by analysis/model.py - K
    # permuted pop orders to the fixpoint - and surfaced in describe()
    # beside the reshard classification.
    kind = {"fr_bfs": "bfs", "fr_sssp": "sssp",
            "fr_pagerank": "pagerank"}.get(fk.name)
    if kind is not None:
        # Bucketed builds extend the claim with (buckets, delta) so
        # certify_claim includes the BUCKETED pop order among the K
        # permutations it proves reach the same fixpoint - the priority
        # tier's exactness gate (the 3-tuple spelling stays for
        # unbucketed builds; certify_claim parses both).
        mk.si_claim = (
            ("frontier", kind, getattr(fk, "reps", None),
             priority_buckets, delta)
            if priority_buckets
            else ("frontier", kind, getattr(fk, "reps", None))
        )
    return mk


# ---------------------------------------------------------------- runner


def run_frontier(
    kind: str,
    graph: Graph,
    src: int = 0,
    *,
    width: int = 8,
    prefetch: bool = True,
    m0: int = 1 << 14,
    reps: int = 64,
    capacity: int = 512,
    interpret: Optional[bool] = None,
    trace=None,
    fuel: Optional[int] = None,
    lane_max_age: Optional[int] = None,
    priority_buckets: Optional[int] = None,
    delta: Optional[int] = None,
    mk: Optional[Megakernel] = None,
    placement=None,
    mesh=None,
    runner: str = "sharded",
    quantum: int = 64,
    window: int = 16,
    hop_order=None,
) -> Tuple[np.ndarray, Dict]:
    """Run one traversal to completion; returns ``(result, info)`` where
    ``result`` is the distance array (bfs/sssp, int32, INF = unreached)
    or the fixed-point rank array (pagerank, int64, ``m0`` mass units
    per vertex seeded). ``info`` gains ``edges`` (TEPS numerator) and
    ``relaxations``.

    Single device when ``placement`` is None. With a placement the seed
    descriptors deal across the per-device ready rings through
    ``runtime.locality.resolve_placement`` (the forasync placement
    discipline - data, not code), EXPANDs migrate through the chosen
    runner's steal exchange (``runner='sharded'`` fast-interpret, or
    ``'resident'`` - Mosaic interpret - whose XOR-hop exchange takes the
    graph-ordered ``hop_order``), per-device distance caches combine by
    elementwise min and ranks/counters by sum."""
    if kind not in _KINDS:
        raise ValueError(f"unknown frontier kind {kind!r} (bfs|sssp|pagerank)")
    fk = _KINDS[kind](reps=reps) if kind == "pagerank" else _KINDS[kind]()
    if mk is None:
        mk = make_frontier_megakernel(
            fk, graph, width=width, prefetch=prefetch, capacity=capacity,
            interpret=interpret, trace=trace, lane_max_age=lane_max_age,
            priority_buckets=priority_buckets, delta=delta,
        )
    else:
        # A prebuilt megakernel owns its own (already-bound) kernel; it
        # must have been built for THIS graph's layout (the trace bakes
        # st_base and the slab shapes in - a mismatch would silently
        # relax the wrong value slots). The local fk only supplies the
        # layout helpers below.
        expect = (fk.name, graph.n, graph.nblocks, graph.st_base)
        bound = getattr(mk, "_frontier_layout", None)
        if bound != expect:
            raise ValueError(
                f"prebuilt megakernel is bound to frontier layout "
                f"{bound}, but this run wants {expect} "
                "(kind, n, nblocks, st_base): build one megakernel per "
                "(kind, graph) via make_frontier_megakernel"
            )
        fk.st_base = graph.st_base
    st = graph.st_base
    iv = graph.preset_values(mk.num_values, fk.state0)
    if kind in ("bfs", "sssp"):
        iv[st + int(src)] = 0
    else:
        iv[st : st + graph.n] = _pr_seed_rank(graph, m0, reps).astype(
            np.int32
        )
    seeds = seed_frontier(None, graph, kind, src=src, m0=m0, reps=reps)
    data = fk.data(graph)

    def finish(iv_rows: np.ndarray, info: Dict) -> Tuple[np.ndarray, Dict]:
        rows = np.asarray(iv_rows, np.int64)
        if rows.ndim == 1:
            rows = rows[None]
        states = rows[:, st : st + graph.n]
        if kind in ("bfs", "sssp"):
            result = states.min(axis=0).astype(np.int32)
        else:
            result = states.sum(axis=0) - (
                (rows.shape[0] - 1) * iv[st : st + graph.n].astype(np.int64)
            )  # presets replicate per device; count the seed rank once
        info["edges"] = int(rows[:, V_EDGES].sum())
        info["relaxations"] = int(rows[:, V_RELAX].sum())
        return result, info

    if placement is None:
        b = TaskGraphBuilder()
        b.reserve_values(graph.num_value_slots)
        for args in seeds:
            b.add(FR_EXPAND, args=list(args))
        iv_o, _, info = mk.run(
            b, data=dict(data), ivalues=iv,
            fuel=1 << 22 if fuel is None else fuel,
        )
        return finish(iv_o, info)

    if fuel is not None:
        # The mesh runners budget by quantum/rounds, not fuel; silently
        # dropping a caller's bound would turn "bounded traversal" into
        # "unbounded run".
        raise ValueError(
            "fuel= applies to the single-device path only; bound a mesh "
            "run with quantum= (per-round budget) instead"
        )
    p = resolve_placement(placement)
    from ..parallel.mesh import cpu_mesh

    if mesh is None:
        if not isinstance(p, MeshPlacement):
            raise ValueError(
                "a dist-func placement needs an explicit mesh= (a "
                "MeshPlacement knows its own device count)"
            )
        mesh = cpu_mesh(p.ndev, axis_name="q")
    ndev = int(np.prod(mesh.devices.shape))
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].reserve_values(graph.num_value_slots)
    dev_of = p.device_of if isinstance(p, MeshPlacement) else (
        lambda i, tot: p(1, i, tot)
    )
    pcounts = [0] * ndev
    for i, args in enumerate(seeds):
        d = int(dev_of(i, max(1, len(seeds))))
        if not 0 <= d < ndev:
            raise ValueError(
                f"placement sent seed {i} to device {d} (mesh has {ndev})"
            )
        builders[d].add(FR_EXPAND, args=list(args))
        pcounts[d] += 1
    stacked_iv = np.broadcast_to(iv, (ndev,) + iv.shape).copy()
    stacked = {
        k: np.broadcast_to(v, (ndev,) + v.shape).copy()
        for k, v in data.items()
    }
    if runner == "sharded":
        from .sharded import ShardedMegakernel

        if hop_order is None and isinstance(p, MeshPlacement):
            hop_order = p.hop_order()
        smk = ShardedMegakernel(mk, mesh, migratable_fns=[FR_EXPAND])
        iv_o, _, info = smk.run(
            builders, data=stacked, ivalues=stacked_iv, steal=True,
            quantum=quantum, window=window, hop_order=hop_order,
        )
    elif runner == "resident":
        from .resident import ResidentKernel

        if hop_order is None and isinstance(p, MeshPlacement):
            hop_order = p.xor_hop_order()
        rk = ResidentKernel(
            mk, mesh, migratable_fns=[FR_EXPAND], window=window,
            homed=False,
        )
        iv_o, _, info = rk.run(
            builders, data=stacked, ivalues=stacked_iv, quantum=quantum,
            hop_order=hop_order,
        )
    else:
        raise ValueError(
            f"unknown frontier runner {runner!r} (sharded|resident)"
        )
    info["placement_counts"] = pcounts
    info["hop_order"] = list(hop_order) if hop_order else None
    result, info = finish(iv_o, info)
    return result, info
