"""forasync device tier: tile loops lowered onto the batch-lane dispatcher.

The reference's flagship data-parallel construct - forasync1D/2D/3D with
flat tiling and dist-func locale placement (src/hclib.c:158-416,
inc/hclib-forasync.h) - rendered for the megakernel: every tile of the
iteration space becomes one task descriptor, and because all tiles of one
loop share one body, a tile IS a same-kind batch - the whole loop lowers
straight onto the PR 3 per-F_FN batch lanes. Each batch round pops up to
``width`` tile descriptors and runs ONE tiled Pallas body over the group,
with the cross-round double-buffered operand prefetch riding underneath
(a tile's operand slab is exactly the entries-behind-head pattern the
prefetch pipeline targets), so per-task scalar dispatch - the ring pop +
``lax.switch`` per descriptor that dominates map-style loops - is paid
once per ROUND instead of once per tile.

The lowering is organized around **slab pipelines**: a ``TileKernel``
declares its operand slabs (windows of named HBM data buffers addressed
by the tile's loop offsets), a pure compute function from loaded slab
values to output slab values, and its output slabs. From that one
declaration the tier derives BOTH dispatch spellings:

- the scalar-tier kernel (DMA in -> compute -> DMA out, one tile per
  ``lax.switch`` dispatch) - the bit-identity reference arm, and
- the batched body (all live slots' loads in flight before the first
  wait; the prospective next batch's slabs prefetched into the other
  VMEM half during this round's compute; one store wave) plus its
  ``drain`` callback, so the scheduler can retire in-flight prefetches
  when it exits with lane entries unrun (fuel, quiesce).

On a mesh, placement is DATA, not code: a dist-func or JSON placement
descriptor (runtime/locality.py ``MeshPlacement``, resolved against
``locality_graphs/*.json``) maps each flat tile to a device, seeding the
per-device ready rings of the sharded/resident runners; the machine
graph additionally orders the steal scan near-neighbors-first
(``steal_hop_order``), so a skewed or stale placement degrades into
recoverable work stealing instead of a wrong or wedged run.

Tiles are successor-free descriptors whose kernels only read their args
and write disjoint output slabs, so they are migratable by construction;
on the mesh every device runs the loop over a replicated input and
writes its executed tiles into its own output copy, and the host sums
the per-device outputs (each tile executes exactly once mesh-wide, and
output buffers are required to start zero).

Device-path constraints (both explicit ``ValueError``\\ s):

- bounds must divide exactly by the tile (slab shapes are static; the
  reference's ragged last tile would need dynamic DMA sizes), and
- ``mode=FLAT`` only (recursive splitting produces unaligned piece
  shapes; RECURSIVE remains a host-tier mode).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..runtime.env import env_int
from ..runtime.locality import MeshPlacement, resolve_placement
from .descriptor import TaskGraphBuilder
from .megakernel import BatchSpec, Megakernel, _batch_stub

__all__ = [
    "Slab",
    "TileKernel",
    "tile_grid",
    "tile_args",
    "seed_tiles",
    "place_tiles",
    "make_forasync_megakernel",
    "run_forasync_device",
    "FA_TILE",
]

# The tile kernel's table index: the tier builds single-kind megakernels
# (one loop body per kernel), so the id is fixed.
FA_TILE = 0


# ------------------------------------------------------------- tiling math


def tile_grid(
    bounds: Sequence, tile: Sequence
) -> Tuple[List[Tuple[int, int]], List[int], List[int], int]:
    """Normalize (bounds, tile) into (dims, tile_dims, tile_counts,
    total) with the EXACT-DIVISION constraint the device tier needs:
    slab shapes are static per kernel, so a ragged last tile (which the
    host tier handles by clamping) is refused with a sizing hint."""
    dims: List[Tuple[int, int]] = []
    for b in bounds:
        if isinstance(b, int):
            dims.append((0, b))
        else:
            lo, hi = b
            dims.append((int(lo), int(hi)))
    if not 1 <= len(dims) <= 3:
        raise ValueError("forasync supports 1-3 dimensions")
    if isinstance(tile, int):
        tile_dims = [tile] * len(dims)
    else:
        tile_dims = [int(t) for t in tile]
    if len(tile_dims) != len(dims):
        raise ValueError("tile rank must match loop rank")
    counts = []
    for (lo, hi), t in zip(dims, tile_dims):
        n = hi - lo
        if t < 1 or n < 1:
            raise ValueError(f"empty dimension or tile: {(lo, hi)} / {t}")
        if n % t:
            raise ValueError(
                f"device forasync tiles must divide the bounds exactly "
                f"(dimension {(lo, hi)} is {n} long, tile {t}): pad the "
                "iteration space or pick a dividing tile"
            )
        counts.append(n // t)
    return dims, tile_dims, counts, math.prod(counts)


def tile_args(
    dims: Sequence[Tuple[int, int]],
    tile_dims: Sequence[int],
    counts: Sequence[int],
    flat: int,
) -> List[int]:
    """Descriptor args of flat tile ``flat``: ``[flat, lo0, lo1, lo2]``
    (trailing los zero below rank 3) - the lo corner in LOOP coordinates;
    slab index functions add their own data-layout offsets."""
    idx = []
    rem = flat
    for c in reversed(list(counts)):
        idx.append(rem % c)
        rem //= c
    idx.reverse()
    los = [lo + i * t for (lo, _), t, i in zip(dims, tile_dims, idx)]
    return [flat] + los + [0] * (3 - len(los))


def seed_tiles(
    builder: TaskGraphBuilder,
    bounds: Sequence,
    tile: Sequence,
    fn: int = FA_TILE,
) -> int:
    """Add one descriptor per flat tile (flat order); returns the total."""
    dims, tile_dims, counts, total = tile_grid(bounds, tile)
    for flat in range(total):
        builder.add(fn, args=tile_args(dims, tile_dims, counts, flat))
    return total


def place_tiles(
    builders: Sequence[TaskGraphBuilder],
    bounds: Sequence,
    tile: Sequence,
    placement,
    fn: int = FA_TILE,
) -> List[int]:
    """Seed per-device ready rings from a placement: every flat tile's
    descriptor lands in ``builders[device_of(flat)]``. ``placement`` is
    anything ``runtime.locality.resolve_placement`` accepts (descriptor
    object / dict / JSON path / dist-func). Returns the per-device tile
    counts - totals are conserved by construction (each flat index is
    placed exactly once), which the placement acceptance pins down."""
    ndev = len(builders)
    dims, tile_dims, counts, total = tile_grid(bounds, tile)
    p = resolve_placement(placement, ndev=ndev)
    dev_of = p.device_of if isinstance(p, MeshPlacement) else (
        lambda flat, tot: p(len(dims), flat, tot)
    )
    out = [0] * ndev
    for flat in range(total):
        d = int(dev_of(flat, total))
        if not 0 <= d < ndev:
            raise ValueError(
                f"placement sent tile {flat} to device {d} "
                f"(mesh has {ndev})"
            )
        builders[d].add(fn, args=tile_args(dims, tile_dims, counts, flat))
        out[d] += 1
    return out


# ---------------------------------------------------------- slab pipeline


class Slab:
    """One operand (or output) window of a named HBM data buffer.

    ``index(args)`` receives the tile's descriptor args ``(flat, lo0,
    lo1, lo2)`` as traced int32 and returns the indexer tuple applied as
    ``data_ref.at[...]`` - scalars for picked leading axes, ``pl.ds``
    for sliced ones. ``shape`` is the (static) slab shape the DMA moves;
    it must agree with what the indexer selects."""

    def __init__(
        self,
        name: str,
        data: str,
        index: Callable[[Sequence], Tuple],
        shape: Tuple[int, ...],
    ) -> None:
        self.name = name
        self.data = data
        self.index = index
        self.shape = tuple(int(s) for s in shape)


class TileKernel:
    """The device body of a forasync tile loop, as a slab pipeline:
    ``compute`` maps loaded input-slab VALUES (dict keyed by slab name)
    to output-slab values - pure jnp, no refs - and the tier derives the
    scalar kernel, the batched body, and its prefetch drain from the
    slab declarations. One ``TileKernel`` therefore has ONE arithmetic
    trace, which is what makes the scalar-vs-tile-tier bit-identity of
    the acceptance runs hold by construction.

    ``data_specs`` declares every named buffer the slabs touch (the
    megakernel's ``data_specs``); output buffers must be disjointly
    written across tiles (the same contract batch bodies always carry).
    """

    def __init__(
        self,
        loads: Sequence[Slab],
        stores: Sequence[Slab],
        compute: Callable[[Dict[str, Any]], Dict[str, Any]],
        data_specs: Dict[str, jax.ShapeDtypeStruct],
        name: str = "fa_tile",
    ) -> None:
        names = [s.name for s in list(loads) + list(stores)]
        if len(set(names)) != len(names):
            raise ValueError(f"slab names must be unique: {names}")
        for s in list(loads) + list(stores):
            if s.data not in data_specs:
                raise ValueError(
                    f"slab {s.name!r} targets undeclared buffer {s.data!r}"
                )
        self.loads = list(loads)
        self.stores = list(stores)
        self.compute = compute
        self.data_specs = dict(data_specs)
        self.name = name
        # Output buffers (store targets): the mesh path requires them to
        # start zero so per-device copies combine by sum.
        self.out_names = sorted({s.data for s in self.stores})

    def _dtype(self, slab: Slab):
        return self.data_specs[slab.data].dtype

    # -- scalar-tier spelling --

    def scalar_scratch(self) -> Dict[str, Any]:
        sc: Dict[str, Any] = {}
        for s in self.loads + self.stores:
            sc[f"fa_{s.name}"] = pltpu.VMEM(s.shape, self._dtype(s))
        sc["fa_lsem"] = pltpu.SemaphoreType.DMA((1,))
        sc["fa_ssem"] = pltpu.SemaphoreType.DMA((1,))
        return sc

    def scalar_kernel(self, ctx) -> None:
        a = tuple(ctx.arg(i) for i in range(4))
        lsem = ctx.scratch["fa_lsem"]
        ssem = ctx.scratch["fa_ssem"]
        # All loads in flight before the first wait (one sem counts all
        # streams - every start is matched by exactly one wait).
        for wait in (False, True):
            for s in self.loads:
                cp = pltpu.make_async_copy(
                    ctx.data[s.data].at[s.index(a)],
                    ctx.scratch[f"fa_{s.name}"],
                    lsem.at[0],
                )
                (cp.wait if wait else cp.start)()
        ins = {s.name: ctx.scratch[f"fa_{s.name}"][...] for s in self.loads}
        outs = self.compute(ins)
        for s in self.stores:
            ctx.scratch[f"fa_{s.name}"][...] = outs[s.name]
        for wait in (False, True):
            for s in self.stores:
                cp = pltpu.make_async_copy(
                    ctx.scratch[f"fa_{s.name}"],
                    ctx.data[s.data].at[s.index(a)],
                    ssem.at[0],
                )
                (cp.wait if wait else cp.start)()

    # -- batch-tier spelling --

    def batch_scratch(self, width: int) -> Dict[str, Any]:
        sc: Dict[str, Any] = {}
        for s in self.loads:
            # Double-buffered (leading 2): one half computes while the
            # tier's cross-round prefetch fills the other.
            sc[f"fa_{s.name}"] = pltpu.VMEM(
                (2, width) + s.shape, self._dtype(s)
            )
        for s in self.stores:
            sc[f"fa_{s.name}"] = pltpu.VMEM((width,) + s.shape, self._dtype(s))
        # One DMA semaphore per (half, slot) counting every load stream
        # of that slot; one per slot for the store wave.
        sc["fa_lsem"] = pltpu.SemaphoreType.DMA((2, width))
        sc["fa_ssem"] = pltpu.SemaphoreType.DMA((width,))
        return sc

    def _slot_loads(self, ctx, buf, slot: int, a, wait: bool) -> None:
        """Start (or retire) every load copy of batch slot ``slot`` into
        operand half ``buf``; args ``a`` name the tile whose slabs move.
        Starts and waits use the same (src, dst, sem) triples under the
        same predicates, so each start has exactly one matching wait."""
        sem = ctx.scratch["fa_lsem"].at[buf, slot]
        for s in self.loads:
            cp = pltpu.make_async_copy(
                ctx.data[s.data].at[s.index(a)],
                ctx.scratch[f"fa_{s.name}"].at[buf, slot],
                sem,
            )
            (cp.wait if wait else cp.start)()

    def batch_body(self, ctx) -> None:
        width = ctx.width
        buf = ctx.buf

        def args_of(s):
            return tuple(ctx.arg(s, i) for i in range(4))

        # Phase 1: start operand copies for live slots the prefetch
        # didn't already cover.
        for b in range(width):
            @pl.when(ctx.live(b) & (jnp.int32(b) >= ctx.prefetched))
            def _(b=b):
                self._slot_loads(ctx, buf, b, args_of(b), wait=False)

        # Phase 2: the prospective NEXT batch's slabs start into the
        # other half now - they land under this round's compute, so the
        # next round opens without a single operand-DMA stall.
        obuf = 1 - buf
        for b in range(width):
            @pl.when(jnp.int32(b) < ctx.prefetch_count)
            def _(b=b):
                nxt = tuple(ctx.next_arg(b, i) for i in range(4))
                self._slot_loads(ctx, obuf, b, nxt, wait=False)

        # Phase 3: retire this round's loads (prefetched slots wait the
        # copies LAST round's phase 2 started into this half).
        for b in range(width):
            @pl.when(ctx.live(b))
            def _(b=b):
                self._slot_loads(ctx, buf, b, args_of(b), wait=True)

        # Phase 4: per-slot compute into the store staging.
        for b in range(width):
            @pl.when(ctx.live(b))
            def _(b=b):
                ins = {
                    s.name: ctx.scratch[f"fa_{s.name}"][buf, b]
                    for s in self.loads
                }
                outs = self.compute(ins)
                for s in self.stores:
                    ctx.scratch[f"fa_{s.name}"][b] = outs[s.name]

        # Phase 5: one store wave - all starts, then all waits, so
        # nothing is still in flight toward the output buffers when the
        # batch's completions run.
        for wait in (False, True):
            for b in range(width):
                @pl.when(ctx.live(b))
                def _(b=b, wait=wait):
                    a = args_of(b)
                    sem = ctx.scratch["fa_ssem"].at[b]
                    for s in self.stores:
                        cp = pltpu.make_async_copy(
                            ctx.scratch[f"fa_{s.name}"].at[b],
                            ctx.data[s.data].at[s.index(a)],
                            sem,
                        )
                        (cp.wait if wait else cp.start)()

    def batch_drain(self, ctx) -> None:
        """Retire an in-flight prefetch whose target entries will be
        spilled instead of batched (scheduler exit - fuel, quiesce): wait
        the same copies phase 2 started, so no DMA outlives the round
        loop and checkpoint export sees only settled buffers."""
        for b in range(ctx.width):
            @pl.when(jnp.int32(b) < ctx.prefetched)
            def _(b=b):
                a = tuple(ctx.arg(b, i) for i in range(4))
                self._slot_loads(ctx, ctx.buf, b, a, wait=True)


# ------------------------------------------------------------ megakernel


def make_forasync_megakernel(
    tk: TileKernel,
    *,
    width: int = 0,
    prefetch: bool = True,
    capacity: int = 256,
    interpret: Optional[bool] = None,
    trace=None,
    checkpoint: Optional[bool] = None,
    quiesce_stride: Optional[int] = None,
    verify: Optional[bool] = None,
) -> Megakernel:
    """Build the loop's megakernel. ``width=0`` is the scalar-dispatch
    arm (one tile per ``lax.switch`` round - the bit-identity reference);
    ``width>0`` routes the tile kind through the batch lanes, with the
    double-buffered operand prefetch on by default."""
    if width:
        spec = BatchSpec(
            tk.batch_body,
            width=width,
            prefetch=prefetch,
            drain=tk.batch_drain if prefetch else None,
        )
        kernels = [(tk.name, _batch_stub)]
        route = {tk.name: spec}
        scratch = tk.batch_scratch(width)
    else:
        kernels = [(tk.name, tk.scalar_kernel)]
        route = None
        scratch = tk.scalar_scratch()
    mk = Megakernel(
        kernels=kernels,
        route=route,
        data_specs=tk.data_specs,
        scratch_specs=scratch,
        capacity=capacity,
        num_values=16,
        succ_capacity=8,
        interpret=interpret,
        trace=trace,
        checkpoint=checkpoint,
        quiesce_stride=quiesce_stride,
        verify=verify,
    )
    # Schedule-independence claim: tiles write disjoint slabs, so any
    # pop order yields one output state. The tile SPACE isn't known
    # until a run names (bounds, tile) - run_forasync_device completes
    # the claim then; analysis/model.py certifies it lazily (K permuted
    # orders over the concrete space) for describe()/hclint.
    mk.si_claim = ("tile", tk, None, None)
    return mk


def _verify_default() -> bool:
    from ..analysis.findings import verify_default

    return verify_default()


def _default_width() -> int:
    """Batch width when the caller leaves it unset: 8, overridable
    process-wide with HCLIB_TPU_FORASYNC_WIDTH (>= 1; malformed values
    raise - a typo must not silently change the dispatch tier)."""
    w = env_int("HCLIB_TPU_FORASYNC_WIDTH", 8)
    if w < 1:
        raise ValueError(
            f"HCLIB_TPU_FORASYNC_WIDTH must be >= 1, got {w!r}"
        )
    return w


def run_forasync_device(
    tk: TileKernel,
    bounds: Sequence,
    tile: Sequence,
    data: Dict[str, np.ndarray],
    *,
    width: Optional[int] = None,
    prefetch: bool = True,
    placement=None,
    mesh=None,
    steal: bool = True,
    quantum: int = 64,
    window: int = 16,
    hop_order: Optional[Sequence[int]] = None,
    capacity: Optional[int] = None,
    interpret: Optional[bool] = None,
    trace=None,
    fuel: int = 1 << 22,
    mk: Optional[Megakernel] = None,
) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Run one forasync tile loop on the device tier to completion;
    returns ``(data_out, info)``.

    Single device when ``placement`` is None. With a placement (and an
    optional ``mesh``; defaults to a CPU mesh sized by the placement),
    tiles seed the per-device ready rings through ``place_tiles``, inputs
    replicate, tile descriptors migrate through the bulk-synchronous
    steal exchange (scan ordered by the placement's machine graph unless
    ``hop_order`` overrides), and per-device output copies combine by
    sum - which requires every output buffer to start zero (checked).
    ``info['placement_counts']`` carries the seeded per-device counts."""
    w = _default_width() if width is None else int(width)
    dims, tile_dims, tcounts, total = tile_grid(bounds, tile)
    cap = capacity or max(64, total + 8)
    if mk is not None and getattr(mk, "verify", False) or (
        mk is None and _verify_default()
    ):
        # Whole-loop store-window race detection (hclib_tpu.analysis):
        # the slab index callables are pure Python, so the bounds known
        # HERE let the verifier prove pairwise disjointness over the
        # CONCRETE tile space - the strong form of the construction-time
        # synthetic check (witness: the two colliding tile coords).
        from ..analysis import check_tile_windows

        check_tile_windows(
            tk, bounds, tile,
            suppress=getattr(mk, "verify_suppress", ()) if mk else (),
        ).raise_errors()
    if mk is not None:
        # A prebuilt kernel OWNS the dispatch configuration: verify the
        # caller's width agrees, so a benchmark arm can never believe it
        # measured the batch tier while running a scalar build (or vice
        # versa) - the results would still be bit-identical, hiding the
        # swap.
        mk_width = (
            mk.batch_specs[0][1].width if mk.batch_specs else 0
        )
        if width is not None and int(width) != mk_width:
            raise ValueError(
                f"width={width} disagrees with the prebuilt megakernel "
                f"(its tile kind is "
                f"{f'batch-routed at width {mk_width}' if mk_width else 'scalar-dispatched'})"
            )
        kernel = mk
    else:
        kernel = make_forasync_megakernel(
            tk, width=w, prefetch=prefetch, capacity=cap,
            interpret=interpret, trace=trace,
        )
    # Complete the schedule-independence claim with the concrete tile
    # space this run names (make_forasync_megakernel stamps it
    # unbound). Re-stamped on EVERY run: a later call over a different
    # (bounds, tile) space must invalidate the previous certificate -
    # an index fn can alias at one size and not another - and the
    # model.py cache keys on the space, so describe() re-certifies.
    claim = getattr(kernel, "si_claim", None)
    if claim is not None and claim[0] == "tile":
        nb = tuple(
            tuple(b) if not isinstance(b, int) else b for b in bounds
        )
        nt = tuple(tile) if not isinstance(tile, int) else (tile,)
        if (claim[2], claim[3]) != (nb, nt):
            kernel.si_claim = ("tile", claim[1], nb, nt)
    if placement is None:
        b = TaskGraphBuilder()
        seed_tiles(b, bounds, tile)
        _, data_out, info = kernel.run(b, data=dict(data), fuel=fuel)
        return data_out, info

    p = resolve_placement(placement)
    from ..parallel.mesh import cpu_mesh
    from .sharded import ShardedMegakernel

    if mesh is None:
        if not isinstance(p, MeshPlacement):
            raise ValueError(
                "a dist-func placement needs an explicit mesh= (a "
                "MeshPlacement knows its own device count)"
            )
        mesh = cpu_mesh(p.ndev, axis_name="q")
    ndev = int(np.prod(mesh.devices.shape))
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    pcounts = place_tiles(builders, bounds, tile, p)
    if hop_order is None and isinstance(p, MeshPlacement):
        hop_order = p.hop_order()
    for name in tk.out_names:
        if np.asarray(data[name]).any():
            raise ValueError(
                f"mesh forasync output buffer {name!r} must start zero: "
                "per-device copies combine by sum"
            )
    stacked = {
        k: np.broadcast_to(
            np.asarray(v), (ndev,) + np.asarray(v).shape
        ).copy()
        for k, v in data.items()
    }
    smk = ShardedMegakernel(kernel, mesh, migratable_fns=[FA_TILE])
    _, data_o, info = smk.run(
        builders, data=stacked, steal=steal, quantum=quantum,
        window=window, hop_order=hop_order,
    )
    out: Dict[str, np.ndarray] = {}
    for k, v in data_o.items():
        arr = np.asarray(v)
        out[k] = (
            arr.sum(axis=0, dtype=arr.dtype)
            if k in tk.out_names
            else arr[0]
        )
    info["placement_counts"] = pcounts
    info["hop_order"] = list(hop_order) if hop_order else None
    return out, info
