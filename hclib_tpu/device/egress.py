"""Completion-mailbox egress: the device->host result path and the typed
``Future`` face on top of it (ISSUE 16).

The injection ring (device/inject.py) made task ENTRY streamable; until
now nothing carried a finished task's RESULT back, so the runtime was a
batch engine, not a server. This module defines the other half of the
request/response loop:

- **EGR row ABI** - the completion mailbox is the mirror of the
  injection ring: a per-device ring of fixed-width ``EGR_WORDS`` int32
  rows carrying a status word, the submit token, TEN_ID, F_FN, the
  result slot, and the result value, with a device-side write cursor and
  the host-consumed cursor echoed back through the ``ectl`` control
  block (``EC_*`` words). Rows are written at task retirement inside the
  round loop (the ``complete_hook`` seam of ``megakernel._make_core``).

- **Backpressure, not loss** - a full mailbox parks the retired row in a
  bounded park buffer and the round re-attempts the flush; parks are
  counted (``EC_PARKED``) and traced (``TR_EGRESS``), never dropped,
  never an OVF abort. The park buffer is bounded by construction:
  installs of token-bearing rows are credit-gated so that parked +
  in-flight tokens never exceed the task-table capacity (the invariant
  ``EgressMailboxModel`` in hclib_tpu/analysis explores adversarially).
  A full mailbox cannot wedge quiesce or the drained exit: parked rows
  ride out through the aliased park buffer and the host - the consumer -
  drains both regions at every entry boundary.

- **Degradation ladder** - ``TenantTable.submit()`` /
  ``MeshTenantTable.submit()`` (device/tenants.py) return an Admission
  carrying a :class:`Future` whose ``result(timeout=)`` rides a
  bounded-backoff poll and whose terminal states are exactly::

      RESULT    - the mailbox row arrived; result() returns the value
      EXPIRED   - the deadline lapsed in flight (reconciled with the
                  tenant expiry counters: host-lapsed, ring-marked, and
                  export-time folds all land here)
      POISONED  - the lane was quarantined/cancelled or the row failed
                  validation; result() raises FuturePoisoned, never hangs
      PREEMPTED - a checkpoint cut landed mid-flight; result() raises
                  FuturePreempted carrying a resume_token, and
                  ``reattach(resume_token)`` on the resumed table yields
                  a fresh Future bound to the same submit token (the
                  token rides the ring row's TEN_TOKEN word, so it
                  survives export_state/resume_from/reshard)

- **Conservation** - :meth:`FutureTable.conservation` certifies the
  ledger identity ``submitted + adopted == resolved + expired +
  poisoned + preempted + pending`` per table; the chaos soak's serve
  arm (tools/chaos_soak.py --serve) proves the cross-cut identity
  ``submitted == resolved + expired + poisoned`` exactly across live
  4->2->4 reshards with futures re-attached via resume tokens.

The numpy functions here (``egress_reference`` / ``flush_parked_
reference`` / :class:`HostMailbox`) are the EXECUTABLE SPEC of the
device semantics - the same role ``tenants.wrr_poll_reference`` plays
for the WRR inject poll: chaos scenarios, the tutorial, and bench drive
them directly, and the in-kernel publish path in device/inject.py is
written to match them word for word.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "EGR_STATUS",
    "EGR_TOKEN",
    "EGR_TEN",
    "EGR_FN",
    "EGR_SLOT",
    "EGR_VALUE",
    "EGR_T_ADMIT",
    "EGR_T_SPANS",
    "EGR_WORDS",
    "EGR_EMPTY",
    "EGR_OK",
    "EC_WRITE",
    "EC_CONSUMED",
    "EC_PARKED",
    "EC_PARK_COUNT",
    "EC_PARK_HEAD",
    "EC_INFLIGHT",
    "TOKEN_LIMIT",
    "EgressSpec",
    "egress_from_env",
    "normalize_egress",
    "Future",
    "FutureTable",
    "FutureTimeout",
    "FutureExpired",
    "FuturePoisoned",
    "FuturePreempted",
    "EgressProtocolError",
    "HostMailbox",
    "egress_reference",
    "flush_parked_reference",
]

# ---------------------------------------------------------------- EGR ABI
#
# One completion-mailbox row: EGR_WORDS int32 words (the mirror of the
# injection ring's RING_ROW rows, sized to the payload instead of a
# descriptor). Word order is pinned by hclib_tpu/analysis/layout.py with
# the same transport-word ordering invariant as TEN_ID..TEN_TOKEN.
EGR_STATUS = 0   # EGR_EMPTY | EGR_OK (a consumed slot is re-zeroed)
EGR_TOKEN = 1    # submit token (TEN_TOKEN word of the injected row);
                 # 0 = untracked task, never published
EGR_TEN = 2      # tenant lane index (TEN_ID of the injected row)
EGR_FN = 3       # kernel-table F_FN of the retired task
EGR_SLOT = 4     # result slot (descriptor F_OUT)
EGR_VALUE = 5    # ivalues[F_OUT] at retirement
EGR_T_ADMIT = 6  # telemetry builds only: the row's TEN_ADMIT_ROUND
                 # stamp (absolute cumulative scheduler round at host
                 # admission; 0 = unstamped / telemetry off)
EGR_T_SPANS = 7  # telemetry builds only: packed lifecycle deltas
                 # ((fire - install) << 16) | (install - admit), each
                 # half clamped to [0, 0xFFFF]. Retirement happens in
                 # the same inner round as fire in this core (dispatch
                 # and completion are atomic per round), so retire ==
                 # fire and two deltas reconstruct the whole span.
EGR_WORDS = 8    # row stride

EGR_EMPTY = 0
EGR_OK = 1

# ectl control words (8-word block, mirror of the inject ctl row): the
# device write cursor and park counters are echoes the host reads after
# every entry; EC_CONSUMED is host-seeded (the host is the only writer).
# Cursors are monotonic totals - slot = cursor % depth, occupancy =
# EC_WRITE - EC_CONSUMED (the tracebuf overflow-counted idiom).
EC_WRITE = 0       # rows ever published (device echo)
EC_CONSUMED = 1    # rows ever consumed (host-seeded)
EC_PARKED = 2      # cumulative park events (device echo; backpressure)
EC_PARK_COUNT = 3  # rows currently held in the park buffer (device echo)
EC_PARK_HEAD = 4   # park FIFO read cursor (the buffer is a ring: append
                   # slot is (head + count) % capacity - no compaction
                   # in-kernel)
EC_INFLIGHT = 5    # token-bearing rows installed but not yet retired
                   # (device echo; the install credit gate holds
                   # EC_PARK_COUNT + EC_INFLIGHT < park capacity, which
                   # bounds the park buffer BY CONSTRUCTION: retirement
                   # moves one in-flight token to either the mailbox or
                   # the park buffer, never both)

# Submit tokens are bounded below 2^24 so the per-task token table
# (``etok`` in device/inject.py) can pack ``token | tenant << 24`` into
# one int32 word; a serving session exhausting 16M tracked submits rolls
# over to a fresh table.
TOKEN_LIMIT = 1 << 24


class EgressSpec:
    """Host-side spec of a completion mailbox: ``depth`` rows of
    ``EGR_WORDS`` int32 words plus the bounded-backoff cap
    ``backoff_s`` that :meth:`Future.result` polls with."""

    def __init__(self, depth: int = 64, backoff_s: float = 0.05) -> None:
        depth = int(depth)
        if depth < 1:
            raise ValueError(f"egress depth must be >= 1, got {depth}")
        backoff_s = float(backoff_s)
        if backoff_s <= 0:
            raise ValueError(
                f"egress backoff must be > 0 seconds, got {backoff_s}"
            )
        self.depth = depth
        self.backoff_s = backoff_s

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"EgressSpec(depth={self.depth}, backoff_s={self.backoff_s})"


def egress_from_env() -> Optional[EgressSpec]:
    """Build an EgressSpec from ``HCLIB_TPU_EGRESS_DEPTH`` /
    ``HCLIB_TPU_EGRESS_BACKOFF_S`` (runtime/env.py registry; malformed
    text raises naming the variable). Depth unset or 0 -> None (off)."""
    from ..runtime.env import env_float, env_int

    depth = env_int("HCLIB_TPU_EGRESS_DEPTH", 0)
    if not depth:
        return None
    backoff = env_float("HCLIB_TPU_EGRESS_BACKOFF_S", 0.05)
    return EgressSpec(depth=depth, backoff_s=backoff)


def normalize_egress(egress) -> Optional[EgressSpec]:
    """Normalize an ``egress=`` argument: None -> env (off unless
    HCLIB_TPU_EGRESS_DEPTH is set), False -> off, True -> env-or-default
    spec, int -> depth, EgressSpec -> itself."""
    if egress is None:
        return egress_from_env()
    if egress is False:
        return None
    if egress is True:
        return egress_from_env() or EgressSpec()
    if isinstance(egress, EgressSpec):
        return egress
    return EgressSpec(depth=int(egress))


# --------------------------------------------------------------- futures

PENDING = "PENDING"
RESULT = "RESULT"
EXPIRED = "EXPIRED"
POISONED = "POISONED"
PREEMPTED = "PREEMPTED"

_TERMINAL = (RESULT, EXPIRED, POISONED, PREEMPTED)


class FutureTimeout(TimeoutError):
    """``result(timeout=)`` lapsed with the future still PENDING. Carries
    the owning table's ``stats_dict()`` snapshot so the caller can see
    WHERE the request is stuck (mailbox backpressure vs ring backlog vs
    a stopped poller) without a second call."""

    def __init__(self, msg: str, stats: Dict[str, Any]) -> None:
        super().__init__(msg)
        self.stats = dict(stats)


class FutureExpired(RuntimeError):
    """Terminal EXPIRED: the deadline lapsed while the request was in
    flight (host-lapsed before publish, ring-marked and dropped by the
    device poll, or folded at a checkpoint export)."""


class FuturePoisoned(RuntimeError):
    """Terminal POISONED: the lane was quarantined or cancelled, or the
    row failed admission-time validation - the ladder rung below
    EXPIRED. Cancelled-scope futures land here; they never hang."""


class FuturePreempted(RuntimeError):
    """Terminal PREEMPTED: a checkpoint cut landed while the request was
    in flight. Carries ``resume_token``; ``reattach(resume_token)`` on
    the table resumed from that cut returns a fresh Future bound to the
    same submit token."""

    def __init__(self, msg: str, resume_token) -> None:
        super().__init__(msg)
        self.resume_token = resume_token


class EgressProtocolError(RuntimeError):
    """The exactly-once contract was violated: a token resolved twice,
    or a mailbox row carried a token this table never issued."""


class Future:
    """One submitted request's handle. States: PENDING then exactly one
    of RESULT | EXPIRED | POISONED | PREEMPTED(resume_token) - the
    degradation ladder. Thread-safe: the driving loop resolves, any
    thread may ``result()``/``wait()``."""

    __slots__ = (
        "token", "tenant", "fn", "slot", "state", "value", "reason",
        "resume_token", "t_submit", "t_done", "_event", "_table",
    )

    def __init__(self, table: "FutureTable", token: int, tenant: str,
                 fn: int, slot: int) -> None:
        self.token = int(token)
        self.tenant = tenant
        self.fn = int(fn)
        self.slot = int(slot)
        self.state = PENDING
        self.value: Optional[int] = None
        self.reason: Optional[str] = None
        self.resume_token = None
        self.t_submit = table._clock()
        self.t_done: Optional[float] = None
        self._event = threading.Event()
        self._table = table

    # -- driver side (FutureTable only) --

    def _finish(self, state: str, value=None, reason=None,
                resume_token=None) -> None:
        self.state = state
        self.value = value
        self.reason = reason
        self.resume_token = resume_token
        self.t_done = self._table._clock()
        self._event.set()

    # -- client side --

    def done(self) -> bool:
        return self.state != PENDING

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block (bounded-backoff poll) until terminal; True if done."""
        if self.state != PENDING:
            return True
        backoff = self._table.backoff_s
        deadline = None if timeout is None else (
            time.monotonic() + float(timeout)
        )
        step = min(0.0005, backoff)
        while not self._event.is_set():
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return self._event.is_set()
                step = min(step, left)
            self._event.wait(step)
            step = min(step * 2, backoff)
        return True

    def result(self, timeout: Optional[float] = None) -> int:
        """The result value, or the ladder's typed raise: FutureTimeout
        (still PENDING - carries the table stats_dict), FutureExpired,
        FuturePoisoned, FuturePreempted (carries resume_token)."""
        if not self.wait(timeout):
            raise FutureTimeout(
                f"token {self.token} ({self.tenant}) still pending after "
                f"{timeout}s", self._table.stats_dict(),
            )
        if self.state == RESULT:
            return int(self.value)
        if self.state == EXPIRED:
            raise FutureExpired(
                f"token {self.token} ({self.tenant}) expired in flight"
                + (f": {self.reason}" if self.reason else "")
            )
        if self.state == POISONED:
            raise FuturePoisoned(
                f"token {self.token} ({self.tenant}) poisoned"
                + (f": {self.reason}" if self.reason else "")
            )
        raise FuturePreempted(
            f"token {self.token} ({self.tenant}) preempted by a "
            "checkpoint cut; reattach(resume_token) on the resumed table",
            self.resume_token,
        )

    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return float(self.t_done - self.t_submit)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Future(token={self.token}, tenant={self.tenant!r}, "
            f"state={self.state})"
        )


# resume_token shape: validated by reattach(), opaque to callers.
_RESUME_MAGIC = "hclib-egress-resume"


class FutureTable:
    """The submit-token ledger: allocates tokens (nonzero int32,
    monotonic), maps them to live Futures, applies the degradation
    ladder, and certifies conservation.

    Exactly-once is structural: a token is live exactly until its ONE
    terminal transition; a second ``resolve``/``expire``/``poison`` of
    the same token raises :class:`EgressProtocolError` (the mailbox
    cursor consumes each row once, so in correct operation this never
    fires - the tests force it to prove it would).

    Across a checkpoint cut the ledger hands over: ``preempt_all()``
    turns every live future PREEMPTED (terminal for ``result()``) and
    ``export_tokens()`` / ``adopt_tokens()`` move the still-pending
    token set to the successor table, where ``reattach(resume_token)``
    binds a fresh Future to the same token - the token itself rides the
    ring row's TEN_TOKEN word through export_state/reshard/resume_from,
    so a residue row retires on the resumed mesh into the SAME ledger
    entry the original submit opened."""

    def __init__(self, backoff_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.backoff_s = float(backoff_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._next = 1
        self._live: Dict[int, Future] = {}
        # tokens adopted from a predecessor table, awaiting reattach():
        # token -> (tenant, fn, slot)
        self._unattached: Dict[int, Tuple[str, int, int]] = {}
        # adopted tokens that reached a terminal state BEFORE the client
        # re-attached (a residue row can retire immediately on resume):
        # token -> (state, value, reason)
        self._early: Dict[int, Tuple[str, Optional[int], Optional[str]]] = {}
        self._terminal: Dict[int, str] = {}
        self.submitted = 0
        self.adopted = 0
        self.resolved = 0
        self.expired = 0
        self.poisoned = 0
        self.preempted = 0
        self.reattached = 0

    # -- submit side --

    def create(self, tenant: str, fn: int, slot: int) -> Future:
        with self._lock:
            token = self._next
            if token >= TOKEN_LIMIT:
                raise EgressProtocolError(
                    f"submit-token space exhausted ({TOKEN_LIMIT} tracked "
                    "submits per serving session): roll over to a fresh "
                    "table"
                )
            self._next += 1
            fut = Future(self, token, tenant, fn, slot)
            self._live[token] = fut
            self.submitted += 1
            return fut

    # -- terminal transitions (driver side) --

    def _take(self, token: int, what: str):
        """Pop a pending token (live future OR unattached adoption); a
        token already terminal or never issued is a protocol violation."""
        token = int(token)
        fut = self._live.pop(token, None)
        if fut is not None:
            return fut, None
        meta = self._unattached.pop(token, None)
        if meta is not None:
            return None, meta
        if token in self._terminal:
            raise EgressProtocolError(
                f"double resolution: token {token} already "
                f"{self._terminal[token]} (mailbox rows are consumed "
                f"exactly once; second {what} refused)"
            )
        raise EgressProtocolError(
            f"{what} of unknown token {token}: this table never issued "
            "or adopted it"
        )

    def _terminate(self, token: int, what: str, state: str, value=None,
                   reason=None, resume_token=None) -> None:
        with self._lock:
            fut, meta = self._take(token, what)
            self._terminal[int(token)] = state
            if fut is not None:
                fut._finish(state, value=value, reason=reason,
                            resume_token=resume_token)
            else:
                self._early[int(token)] = (state, value, reason)
            if state == RESULT:
                self.resolved += 1
            elif state == EXPIRED:
                self.expired += 1
            elif state == POISONED:
                self.poisoned += 1

    def resolve(self, token: int, value: int) -> None:
        """A mailbox row for ``token`` was consumed: terminal RESULT."""
        self._terminate(token, "resolve", RESULT, value=int(value))

    def expire(self, token: int, reason: str = "deadline") -> None:
        self._terminate(token, "expire", EXPIRED, reason=reason)

    def poison(self, token: int, reason: str = "quarantined") -> None:
        self._terminate(token, "poison", POISONED, reason=reason)

    def poison_all(self, reason: str = "stream aborted") -> int:
        """The abort rung: every pending token - live futures AND
        unattached adoptions - resolves POISONED, so an aborted stream
        never leaves a single client hanging. Returns tokens poisoned."""
        with self._lock:
            tokens = (
                list(self._live.keys()) + list(self._unattached.keys())
            )
        for t in tokens:
            self.poison(t, reason)
        return len(tokens)

    # -- checkpoint-cut handover --

    def preempt_all(self) -> List[Tuple[str, str, int, int, int]]:
        """A checkpoint cut landed: every live future turns PREEMPTED
        (terminal, with a resume token) and its still-pending token
        moves to the export set. Returns the resume tokens issued."""
        out = []
        with self._lock:
            for token, fut in list(self._live.items()):
                rt = (_RESUME_MAGIC, fut.tenant, token, fut.fn, fut.slot)
                del self._live[token]
                self._unattached[token] = (fut.tenant, fut.fn, fut.slot)
                fut._finish(PREEMPTED, resume_token=rt)
                self.preempted += 1
                out.append(rt)
        return out

    def export_tokens(self) -> Dict[int, Tuple[str, int, int]]:
        """The still-pending token set (after preempt_all): what a
        successor table adopts. Early-terminal adoptions ride too so a
        twice-cut pipeline keeps its ledger."""
        with self._lock:
            return dict(self._unattached)

    def adopt_tokens(self, tokens: Dict[int, Tuple[str, int, int]]) -> None:
        """Adopt a predecessor's pending tokens (resume_from/reshard):
        they become resolvable here and reattach()-able by clients."""
        with self._lock:
            for token, meta in tokens.items():
                token = int(token)
                if token in self._live or token in self._unattached:
                    raise EgressProtocolError(
                        f"adopt of token {token} collides with a live "
                        "entry"
                    )
                self._unattached[token] = (
                    str(meta[0]), int(meta[1]), int(meta[2])
                )
                self.adopted += 1
                self._next = max(self._next, token + 1)

    def adopt_row_token(self, token: int, tenant: str, fn: int,
                        slot: int) -> None:
        """Adopt ONE token read back off a residue ring row's TEN_TOKEN
        word (resume_from's readmit loop). Idempotent against an
        adopt_tokens() that already carried it."""
        with self._lock:
            token = int(token)
            if (token in self._live or token in self._unattached
                    or token in self._terminal):
                return
            self._unattached[token] = (str(tenant), int(fn), int(slot))
            self.adopted += 1
            self._next = max(self._next, token + 1)

    def reattach(self, resume_token) -> Future:
        """Bind a fresh Future to a preempted submit token on THIS
        (resumed) table. The token must be one this table adopted - a
        foreign or stale resume token raises EgressProtocolError."""
        if (not isinstance(resume_token, tuple)
                or len(resume_token) != 5
                or resume_token[0] != _RESUME_MAGIC):
            raise EgressProtocolError(
                f"not a resume token: {resume_token!r}"
            )
        _, tenant, token, fn, slot = resume_token
        with self._lock:
            token = int(token)
            meta = self._unattached.pop(token, None)
            if meta is not None:
                fut = Future(self, token, meta[0], meta[1], meta[2])
                self._live[token] = fut
                self.reattached += 1
                return fut
            early = self._early.pop(token, None)
            if early is not None:
                # The residue row retired before the client re-attached:
                # hand back an already-terminal future.
                fut = Future(self, token, str(tenant), int(fn), int(slot))
                fut._finish(early[0], value=early[1], reason=early[2])
                self.reattached += 1
                return fut
        raise EgressProtocolError(
            f"reattach of token {token}: not pending on this table "
            "(wrong resume generation, or never exported)"
        )

    # -- ledger --

    def pending(self) -> int:
        with self._lock:
            return len(self._live) + len(self._unattached)

    def conservation(self) -> Dict[str, Any]:
        """The ledger identity, checked exactly: every token this table
        ever held (submitted + adopted) is accounted by exactly one of
        resolved / expired / poisoned / preempted-exported / pending."""
        with self._lock:
            pending = len(self._live) + len(self._unattached)
            held = self.submitted + self.adopted
            # preempt moves live -> unattached (still held here) until
            # export; `preempted` counts futures, not token departures,
            # so the identity closes over the pending set directly.
            accounted = (
                self.resolved + self.expired + self.poisoned + pending
            )
            return {
                "submitted": self.submitted,
                "adopted": self.adopted,
                "resolved": self.resolved,
                "expired": self.expired,
                "poisoned": self.poisoned,
                "preempted": self.preempted,
                "reattached": self.reattached,
                "pending": pending,
                "ok": held == accounted,
            }

    def stats_dict(self) -> Dict[str, Any]:
        d = self.conservation()
        d["backoff_s"] = self.backoff_s
        return d


# ------------------------------------------------- executable spec (host)

def egress_reference(rows, egr, park, ectl, depth: int) -> int:
    """The executable spec of the device publish path (the role
    ``tenants.wrr_poll_reference`` plays for the WRR poll): append each
    retired ``(token, ten, fn, slot, value)`` tuple to the mailbox
    ``egr`` (shape ``(depth, EGR_WORDS)``), or PARK it in ``park`` when
    the mailbox is full - counted in ``ectl[EC_PARKED]``, never dropped.
    Token-0 rows are untracked and skipped. Mutates egr/park/ectl in
    place; returns rows published. The in-kernel path in device/inject.py
    matches this word for word (asserted by tests/test_serving.py)."""
    egr = np.asarray(egr)
    park = np.asarray(park)
    published = 0
    for token, ten, fn, slot, value in rows:
        if int(token) == 0:
            continue
        write = int(ectl[EC_WRITE])
        room = int(depth) - (write - int(ectl[EC_CONSUMED]))
        if room > 0:
            r = egr[write % int(depth)]
            r[EGR_STATUS] = EGR_OK
            r[EGR_TOKEN] = int(token)
            r[EGR_TEN] = int(ten)
            r[EGR_FN] = int(fn)
            r[EGR_SLOT] = int(slot)
            r[EGR_VALUE] = int(value)
            ectl[EC_WRITE] = write + 1
            published += 1
        else:
            n = int(ectl[EC_PARK_COUNT])
            if n >= park.shape[0]:
                raise EgressProtocolError(
                    f"park buffer overflow ({n} rows): the install-side "
                    "credit gate is broken"
                )
            p = park[(int(ectl[EC_PARK_HEAD]) + n) % park.shape[0]]
            p[EGR_STATUS] = EGR_OK
            p[EGR_TOKEN] = int(token)
            p[EGR_TEN] = int(ten)
            p[EGR_FN] = int(fn)
            p[EGR_SLOT] = int(slot)
            p[EGR_VALUE] = int(value)
            ectl[EC_PARK_COUNT] = n + 1
            ectl[EC_PARKED] = int(ectl[EC_PARKED]) + 1
    return published


def flush_parked_reference(egr, park, ectl, depth: int) -> int:
    """The entry-start parked retry, as the kernel performs it: move
    parked rows (FIFO off the EC_PARK_HEAD ring cursor) into the mailbox
    while there is room. Mutates in place; returns rows flushed."""
    egr = np.asarray(egr)
    park = np.asarray(park)
    cap = park.shape[0]
    flushed = 0
    while int(ectl[EC_PARK_COUNT]) > 0:
        write = int(ectl[EC_WRITE])
        if int(depth) - (write - int(ectl[EC_CONSUMED])) <= 0:
            break
        h = int(ectl[EC_PARK_HEAD])
        egr[write % int(depth)] = park[h]
        park[h] = 0
        ectl[EC_PARK_HEAD] = (h + 1) % cap
        ectl[EC_PARK_COUNT] = int(ectl[EC_PARK_COUNT]) - 1
        ectl[EC_WRITE] = write + 1
        flushed += 1
    return flushed


class HostMailbox:
    """One device's completion mailbox, host-model form: the numpy
    arrays (``egr``/``park``/``ectl``) plus the consume side. Chaos
    serve scenarios, the tutorial, and bench drive this directly; the
    streaming driver holds one per run and drains it after every kernel
    entry. ``park_cap`` defaults to the mailbox depth - host-model
    drives publish at retirement inside the same step that installed,
    so in-flight tokens never exceed the install credit."""

    def __init__(self, spec: EgressSpec, park_cap: Optional[int] = None
                 ) -> None:
        self.spec = spec
        self.depth = int(spec.depth)
        cap = self.depth if park_cap is None else int(park_cap)
        self.egr = np.zeros((self.depth, EGR_WORDS), np.int32)
        self.park = np.zeros((max(1, cap), EGR_WORDS), np.int32)
        self.ectl = np.zeros(8, np.int32)

    def publish(self, rows) -> int:
        """Retire rows into the mailbox (park on full; see
        egress_reference)."""
        return egress_reference(rows, self.egr, self.park, self.ectl,
                                self.depth)

    def flush(self) -> int:
        return flush_parked_reference(self.egr, self.park, self.ectl,
                                      self.depth)

    def occupancy(self) -> int:
        return int(self.ectl[EC_WRITE]) - int(self.ectl[EC_CONSUMED])

    def parked(self) -> int:
        return int(self.ectl[EC_PARK_COUNT])

    def park_events(self) -> int:
        return int(self.ectl[EC_PARKED])

    def drain(self, futures: Optional[FutureTable] = None,
              limit: Optional[int] = None,
              include_parked: bool = True) -> List[Tuple[int, int]]:
        """Consume published rows (advance EC_CONSUMED), flushing parked
        rows through the mailbox as space frees so a backlogged device
        empties in one call when ``include_parked``. Each consumed row
        resolves its token on ``futures`` - exactly once: the slot is
        re-zeroed behind the cursor. Returns the (token, value) pairs
        consumed. A ``limit`` models a slow poller (consume at most N
        rows, leave the rest parked/published)."""
        out: List[Tuple[int, int]] = []
        while limit is None or len(out) < limit:
            consumed = int(self.ectl[EC_CONSUMED])
            if consumed >= int(self.ectl[EC_WRITE]):
                if include_parked and self.flush() > 0:
                    continue
                break
            slot = consumed % self.depth
            row = self.egr[slot]
            if int(row[EGR_STATUS]) != EGR_OK:
                raise EgressProtocolError(
                    f"mailbox slot {slot} consumed twice or never "
                    f"published (status {int(row[EGR_STATUS])})"
                )
            token, value = int(row[EGR_TOKEN]), int(row[EGR_VALUE])
            row[:] = 0
            self.ectl[EC_CONSUMED] = consumed + 1
            if futures is not None:
                futures.resolve(token, value)
            out.append((token, value))
        return out
