"""Smith-Waterman wavefront inside the megakernel.

Tile tasks on the same 2D DDF grid as the host model (reference:
test/smithwaterman/smith_waterman.cpp:77-180), with the tile computation
re-designed for the VPU instead of translated from the scalar DP loop:

- Rows are processed top to bottom; the row recurrence's left-to-right
  dependency H[i,j] = max(0, cand[i,j], H[i,j-1] - G) is solved *exactly* as
  a max-plus prefix scan: H = max(0, cummax(cand + j*G) - j*G), where the
  0-truncation can be applied once at the end because a truncation point
  only ever contributes negative values downstream. cummax is 7 log-step
  shift+max ops over the 128 lanes.
- Inter-tile boundaries travel through dedicated HBM buffers (bottom row,
  right column, corner per tile) instead of overlapping tile reads, keeping
  every DMA aligned. The right column and the per-row left boundary live in
  SMEM so the row loop can read/write per-row scalars without dynamic lane
  indexing in VMEM.

The global best score accumulates in ivalues[0].
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.smithwaterman import GAP, MATCH, MISMATCH
from .descriptor import TaskGraphBuilder
from .megakernel import KernelContext, Megakernel

__all__ = [
    "device_sw", "make_sw_megakernel", "device_sw_wave",
    "make_sw_wave_megakernel", "build_sw_wave_graph", "sw_wave_buffers",
    "device_sw_batched", "make_sw_batched_megakernel", "build_sw_tile_graph",
]

T = 128
TILE_FN = 0
NEG = -(1 << 30)  # plain int: a jnp constant here would be captured by the trace


def _cummax_lanes(x):
    """Inclusive running max along the 128 lanes of an (R, T) plane (each
    sublane row scans independently)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    for sh in (1, 2, 4, 8, 16, 32, 64):
        shifted = pltpu.roll(x, sh, axis=1)
        shifted = jnp.where(lane >= sh, shifted, NEG)
        x = jnp.maximum(x, shifted)
    return x


def _sw_tile_kernel(ctx: KernelContext, with_h: bool = True) -> None:
    ti, tj = ctx.arg(0), ctx.arg(1)
    aseq, bseq = ctx.data["aseq"], ctx.data["bseq"]
    bot, right = ctx.data["bot"], ctx.data["right"]
    htiles = ctx.data["htiles"] if with_h else None
    vh = ctx.scratch["vh"] if with_h else None  # (T, T) VMEM: this tile's H
    vtop = ctx.scratch["vtop"]  # (1, T) VMEM: incoming top boundary
    vb = ctx.scratch["vb"]  # (1, T) VMEM: b chars for this column tile
    a_sm = ctx.scratch["a_sm"]  # (1, T) SMEM: a chars (per-row scalars)
    left_sm = ctx.scratch["left_sm"]  # (1, T) SMEM: incoming left boundary
    rout_sm = ctx.scratch["rout_sm"]  # (1, T) SMEM: outgoing right column
    corner_sm = ctx.scratch["corner_sm"]  # (1, T) SMEM; corner at lane T-1
    sems = ctx.scratch["sems"]

    def dma(src, dst, s):
        cp = pltpu.make_async_copy(src, dst, s)
        cp.start()
        cp.wait()

    dma(aseq.at[ti], a_sm, sems.at[0])
    dma(bseq.at[tj], vb, sems.at[1])

    @pl.when(ti > 0)
    def _():
        dma(bot.at[ti - 1, tj], vtop, sems.at[0])

    @pl.when(ti == 0)
    def _():
        vtop[:] = jnp.zeros((1, T), jnp.int32)

    @pl.when(tj > 0)
    def _():
        dma(right.at[ti, tj - 1], left_sm, sems.at[1])

    @pl.when(tj == 0)
    def _():
        # SMEM only takes scalar stores - zero it with a scalar loop.
        def z(i, _):
            left_sm[0, i] = 0
            return 0

        jax.lax.fori_loop(0, T, z, 0)

    # The diagonal corner H[(ti-1,tj-1)][T-1,T-1] is lane T-1 of that
    # tile's right column - no separate (1,1) buffer (DMA lane alignment).
    @pl.when((ti > 0) & (tj > 0))
    def _():
        dma(right.at[ti - 1, tj - 1], corner_sm, sems.at[2])

    @pl.when((ti == 0) | (tj == 0))
    def _():
        corner_sm[0, T - 1] = 0

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    bvec = vb[:]

    def row(i, carry):
        hprev = carry[0]
        ai = a_sm[0, i]
        # H[i-1, j0-1]: the left boundary one row up (corner for row 0).
        im1 = jnp.maximum(i - 1, 0)
        prev_left = jnp.where(i == 0, corner_sm[0, T - 1], left_sm[0, im1])
        sub = jnp.where(bvec == ai, jnp.int32(MATCH), jnp.int32(MISMATCH))
        diag = pltpu.roll(hprev, 1, axis=1)
        diag = jnp.where(lane == 0, prev_left, diag)
        cand = jnp.maximum(diag + sub, hprev - GAP)
        # This row's left boundary enters as an extra candidate at lane 0.
        cand = jnp.maximum(
            cand, jnp.where(lane == 0, left_sm[0, i] - GAP, NEG)
        )
        scan = _cummax_lanes(cand + lane * GAP) - lane * GAP
        hrow = jnp.maximum(scan, 0)
        if with_h:
            vh[pl.ds(i, 1), :] = hrow
        rout_sm[0, i] = hrow[0, T - 1]
        return hrow, jnp.maximum(carry[1], hrow)

    hlast, hmax = jax.lax.fori_loop(
        0, T, lambda i, c: row(i, c), (vtop[:], jnp.zeros((1, T), jnp.int32))
    )

    # Publish boundaries + tile, update the global best score.
    vtop[:] = hlast
    dma(vtop, bot.at[ti, tj], sems.at[0])
    dma(rout_sm, right.at[ti, tj], sems.at[1])
    if with_h:
        dma(vh, htiles.at[ti, tj], sems.at[3])
    tile_max = jnp.max(hmax)
    best = ctx.value(0)
    ctx.set_value(0, jnp.maximum(best, tile_max))


WAVE_R = 8  # tile slots per wave-chunk descriptor (VPU sublanes)
WAVE_FN = 0
WAVE_B = 2  # chunk descriptors per batch round (16 stacked tile planes)


def _zero_slot(ctx, buf, slot) -> None:
    """Uniform zero planes for a dead tile slot (scores can't leak: vb of
    -1 never matches a real character)."""
    zrow = jnp.zeros((1, T), jnp.int32)
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    ctx.scratch["vtop"][buf, pl.ds(slot, 1)] = zrow
    ctx.scratch["vleft"][buf, pl.ds(slot, 1)] = zrow
    ctx.scratch["vcorn"][buf, pl.ds(slot, 1)] = zrow
    va[buf, pl.ds(slot, 1)] = zrow
    vb[buf, pl.ds(slot, 1)] = zrow - 1


def _chunk_dma(ctx, buf, b, chunk: int, w, lo, cnt, wait: bool) -> None:
    """Start (``wait=False``) or retire (``wait=True``) the operand copies
    of chunk descriptor ``b`` - tiles (lo+s, w-lo-s) for s < cnt - into
    operand half ``buf``. Starts and waits are split so a round can put
    EVERY copy of every slot in flight before the first wait (the old
    wave kernel's serial start/wait per tile paid ~40 DMA latencies per
    chunk - the single biggest term in BENCH_r05's 1.2 GCUPS), and so the
    prefetch path can issue the identical starts one round early. One
    DMA semaphore per (half, slot) counts all five streams; every start
    is matched by exactly one wait under the same predicate."""
    aseq, bseq = ctx.data["aseq"], ctx.data["bseq"]
    bot, right = ctx.data["bot"], ctx.data["right"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    vtop, vleft = ctx.scratch["vtop"], ctx.scratch["vleft"]
    vcorn = ctx.scratch["vcorn"]
    lsem = ctx.scratch["lsem"]
    zrow = jnp.zeros((1, T), jnp.int32)
    for s in range(chunk):
        slot = b * chunk + s
        ti = lo + s
        tj = w - ti
        sem = lsem.at[buf, slot]

        def go(src, dst):
            cp = pltpu.make_async_copy(src, dst, sem)
            (cp.wait if wait else cp.start)()

        @pl.when(jnp.int32(s) < cnt)
        def _(slot=slot, ti=ti, tj=tj, go=go):
            go(aseq.at[ti], va.at[buf, pl.ds(slot, 1)])
            go(bseq.at[tj], vb.at[buf, pl.ds(slot, 1)])

            @pl.when(ti > 0)
            def _():
                go(bot.at[ti - 1, tj], vtop.at[buf, pl.ds(slot, 1)])

            @pl.when(tj > 0)
            def _():
                go(right.at[ti, tj - 1], vleft.at[buf, pl.ds(slot, 1)])

            @pl.when((ti > 0) & (tj > 0))
            def _():
                go(
                    right.at[ti - 1, tj - 1],
                    vcorn.at[buf, pl.ds(slot, 1)],
                )

            if not wait:
                @pl.when(ti == 0)
                def _():
                    vtop[buf, pl.ds(slot, 1)] = zrow

                @pl.when(tj == 0)
                def _():
                    vleft[buf, pl.ds(slot, 1)] = zrow

                @pl.when((ti == 0) | (tj == 0))
                def _():
                    vcorn[buf, pl.ds(slot, 1)] = zrow

        if not wait:
            @pl.when(jnp.int32(s) >= cnt)
            def _(slot=slot):
                _zero_slot(ctx, buf, slot)


def _sw_wave_batch_kernel(ctx, chunk: int, with_h: bool = True) -> None:
    """Batched-tier SW wavefront body: up to ``ctx.width`` same-kind wave
    descriptors per round, each carrying up to ``chunk`` anti-diagonal
    tiles, swept together as (width*chunk, T) VPU planes - the tile
    kernel's (1, T) row recurrence runs width*chunk tiles per VPU step.
    Dependencies stay REAL: descriptors are DAG tasks whose dep counters
    encode the wavefront order (the reference's wavefront DAG,
    test/smithwaterman/smith_waterman.cpp:77-180, regrouped for the
    hardware); the scheduler's per-F_FN lane is what groups the
    simultaneously-ready ones.

    Operand motion is double-buffered across rounds via the tier's
    prefetch protocol: ``ctx.prefetched`` descriptors already have their
    boundaries in half ``ctx.buf`` (issued during the PREVIOUS round's
    compute), the rest start now; the next prospective batch's copies are
    put in flight into the other half before this round's waits, so they
    ride under this round's 128-row sweep. A lane entry's inputs are
    final before it enters the lane (its predecessors' stores drained
    before their completion), which is what makes the early issue safe.

    descriptor args: [w, lo, count] - tiles (ti, w - ti), ti in
    [lo, lo+count). A per-tile graph is the chunk=1 special case.
    """
    width = ctx.width
    S = width * chunk
    buf = ctx.buf
    vtop, vleft = ctx.scratch["vtop"], ctx.scratch["vleft"]
    vcorn = ctx.scratch["vcorn"]
    va, vb = ctx.scratch["va"], ctx.scratch["vb"]
    vh = ctx.scratch["vwh"] if with_h else None
    htiles = ctx.data["htiles"] if with_h else None
    bot, right = ctx.data["bot"], ctx.data["right"]
    ssem = ctx.scratch["ssem"]

    # Phase 1: start operand copies for live descriptors the prefetch
    # didn't cover; zero the dead ones.
    for b in range(width):
        @pl.when(ctx.live(b) & (jnp.int32(b) >= ctx.prefetched))
        def _(b=b):
            _chunk_dma(
                ctx, buf, b, chunk,
                ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 2), wait=False,
            )

        @pl.when(jnp.logical_not(ctx.live(b)))
        def _(b=b):
            for s in range(chunk):
                _zero_slot(ctx, buf, b * chunk + s)

    # Phase 2: put the NEXT batch's copies in flight into the other half -
    # they land while this round computes, so the next round starts its
    # sweep without a single boundary-DMA stall.
    obuf = 1 - buf
    for b in range(width):
        @pl.when(jnp.int32(b) < ctx.prefetch_count)
        def _(b=b):
            _chunk_dma(
                ctx, obuf, b, chunk,
                ctx.next_arg(b, 0), ctx.next_arg(b, 1), ctx.next_arg(b, 2),
                wait=False,
            )

    # Phase 3: retire this round's loads (prefetched and fresh alike wait
    # the same (src, dst, sem) triples their starts used).
    for b in range(width):
        @pl.when(ctx.live(b))
        def _(b=b):
            _chunk_dma(
                ctx, buf, b, chunk,
                ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 2), wait=True,
            )

    # Phase 4: the (S, T) wavefront sweep.
    lane = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
    aplane = va[buf]
    bplane = vb[buf]
    leftp = vleft[buf]
    corner = vcorn[buf][:, T - 1 :]  # (S, 1)

    def col(plane, i):
        """Column i of an (S, T) plane as (S, 1): mask + lane-reduce
        (Mosaic has no dynamic_slice on values; this is 2 plane ops)."""
        return jnp.sum(
            jnp.where(lane == i, plane, 0), axis=1, keepdims=True
        )

    def row(i, carry):
        hprev, rout, _mpl = carry
        achar = col(aplane, i)
        prev_left = jnp.where(i == 0, corner, col(leftp, i - 1))
        this_left = col(leftp, i)
        sub = jnp.where(
            bplane == achar, jnp.int32(MATCH), jnp.int32(MISMATCH)
        )
        diag = pltpu.roll(hprev, 1, axis=1)
        diag = jnp.where(lane == 0, prev_left, diag)
        cand = jnp.maximum(diag + sub, hprev - GAP)
        cand = jnp.maximum(cand, jnp.where(lane == 0, this_left - GAP, NEG))
        scan = _cummax_lanes(cand + lane * GAP) - lane * GAP
        hrow = jnp.maximum(scan, 0)
        if with_h:
            vh[:, pl.ds(i, 1), :] = hrow[:, None, :]
        # Accumulate the right column (lane T-1 of each row) into column i
        # of rout - pure plane ops, no scalar extracts in the hot loop.
        rcol = hrow[:, T - 1 :]
        rout = jnp.where(lane == i, rcol, rout)
        mplane = jnp.maximum(_mpl, hrow)
        return hrow, rout, mplane

    zero_st = jnp.zeros((S, T), jnp.int32)
    hlast, rout, mplane = jax.lax.fori_loop(
        0, T, row, (vtop[buf], zero_st, zero_st)
    )
    # Reuse this half as store staging (the prefetch lives in the other
    # half, and these stores drain before this body returns).
    vtop[buf] = hlast
    vleft[buf] = rout
    vcorn[buf] = mplane
    mall = vcorn[buf]

    # Phase 5: publish boundaries (+ tiles), fold the running best score;
    # all stores start together, then all are waited - successors may be
    # dispatched the moment this body returns, so nothing may still be in
    # flight toward the boundary buffers they read.
    def stores(wait: bool):
        for b in range(width):
            @pl.when(ctx.live(b))
            def _(b=b):
                w, lo, cnt = ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 2)
                for s in range(chunk):
                    slot = b * chunk + s
                    ti = lo + s
                    tj = w - ti

                    @pl.when(jnp.int32(s) < cnt)
                    def _(slot=slot, ti=ti, tj=tj):
                        def go(src, dst):
                            cp = pltpu.make_async_copy(
                                src, dst, ssem.at[slot]
                            )
                            (cp.wait if wait else cp.start)()

                        go(vtop.at[buf, pl.ds(slot, 1)], bot.at[ti, tj])
                        go(vleft.at[buf, pl.ds(slot, 1)], right.at[ti, tj])
                        if with_h:
                            go(vh.at[slot], htiles.at[ti, tj])

                if not wait:
                    # Each descriptor accounts for `cnt` tiles (itself +
                    # cnt-1 extra) so 'executed' counts tiles across
                    # tiers, as the vector tier does.
                    ctx.add_executed(cnt - 1)
                    for s in range(chunk):
                        @pl.when(jnp.int32(s) < cnt)
                        def _(s=s, b=b):
                            m = jnp.max(mall[b * chunk + s])
                            ctx.set_value(
                                0, jnp.maximum(ctx.value(0), m)
                            )

    stores(wait=False)
    stores(wait=True)


def _sw_wave_drain(ctx, chunk: int) -> None:
    """Retire an in-flight prefetch whose targets will be spilled instead
    of batched (scheduler exit with lane entries unrun): wait the same
    copies Phase 2 started, so no DMA outlives the kernel's round loop."""
    for b in range(ctx.width):
        @pl.when(jnp.int32(b) < ctx.prefetched)
        def _(b=b):
            _chunk_dma(
                ctx, ctx.buf, b, chunk,
                ctx.arg(b, 0), ctx.arg(b, 1), ctx.arg(b, 2), wait=True,
            )


def _sw_batch_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool], with_h: bool,
    chunk: int, width: int, capacity: int, succ_capacity: int,
    checkpoint: Optional[bool] = None,
) -> Megakernel:
    import functools as _ft

    from .megakernel import BatchSpec, _batch_stub

    i32 = jnp.int32
    S = width * chunk
    data_specs = {
        "aseq": jax.ShapeDtypeStruct((nt_i, 1, T), i32),
        "bseq": jax.ShapeDtypeStruct((nt_j, 1, T), i32),
        "bot": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
        "right": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
    }
    scratch = {
        # Operand planes are double-buffered (leading 2): one half computes
        # while the tier's prefetch fills the other.
        "va": pltpu.VMEM((2, S, T), i32),
        "vb": pltpu.VMEM((2, S, T), i32),
        "vtop": pltpu.VMEM((2, S, T), i32),
        "vleft": pltpu.VMEM((2, S, T), i32),
        "vcorn": pltpu.VMEM((2, S, T), i32),
        "lsem": pltpu.SemaphoreType.DMA((2, S)),
        "ssem": pltpu.SemaphoreType.DMA((S,)),
    }
    if with_h:
        data_specs["htiles"] = jax.ShapeDtypeStruct((nt_i, nt_j, T, T), i32)
        scratch["vwh"] = pltpu.VMEM((S, T, T), i32)
    return Megakernel(
        kernels=[("sw_wave", _batch_stub)],
        route={
            "sw_wave": BatchSpec(
                _ft.partial(
                    _sw_wave_batch_kernel, chunk=chunk, with_h=with_h
                ),
                width=width,
                prefetch=True,
                drain=_ft.partial(_sw_wave_drain, chunk=chunk),
            )
        },
        data_specs=data_specs,
        scratch_specs=scratch,
        capacity=capacity,
        num_values=8,
        succ_capacity=succ_capacity,
        interpret=interpret,
        checkpoint=checkpoint,
    )


def make_sw_wave_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool] = None,
    with_h: bool = True, chunk: int = WAVE_R, width: int = WAVE_B,
    checkpoint: Optional[bool] = None,
) -> Megakernel:
    nwaves = nt_i + nt_j - 1
    chunks = [
        -(-min(w + 1, nt_i, nt_j, nt_i + nt_j - 1 - w) // chunk)
        for w in range(nwaves)
    ]
    ntasks = sum(chunks)
    # Exact CSR demand: every wave-w chunk lists ALL wave-(w+1) chunks as
    # successors (2 ride inline, the rest spill to CSR) - quadratic in
    # chunks-per-diagonal, so a heuristic multiple of ntasks under-counts
    # on large grids.
    csr_words = sum(
        chunks[w] * max(0, chunks[w + 1] - 2) for w in range(nwaves - 1)
    )
    return _sw_batch_megakernel(
        nt_i, nt_j, interpret, with_h, chunk, width,
        capacity=max(64, ntasks), succ_capacity=max(64, csr_words),
        checkpoint=checkpoint,
    )


def build_sw_wave_graph(
    nt_i: int, nt_j: int, chunk: int = WAVE_R
) -> TaskGraphBuilder:
    """Wave-chunk task DAG: up to ``chunk`` tiles of one anti-diagonal per
    task, consecutive anti-diagonals chained by dependencies (shared by
    device_sw_wave and the bench so both stage the SAME graph)."""
    builder = TaskGraphBuilder()
    prev_wave: list = []
    for w in range(nt_i + nt_j - 1):
        lo = max(0, w - (nt_j - 1))
        hi = min(nt_i - 1, w)
        this_wave = []
        for base in range(lo, hi + 1, chunk):
            cnt = min(chunk, hi + 1 - base)
            this_wave.append(
                builder.add(WAVE_FN, args=[w, base, cnt], deps=prev_wave)
            )
        prev_wave = this_wave
    return builder


def build_sw_tile_graph(nt_i: int, nt_j: int) -> TaskGraphBuilder:
    """Per-TILE task DAG with the precise 3-neighbor dependencies (the
    reference's granularity): descriptors carry [w, lo, 1] so the batched
    wave body runs them as its chunk=1 special case. Which tiles execute
    together is decided by the SCHEDULER's same-kind lane, round by round
    - the dynamic-grouping shape the batched dispatch tier exists for."""
    builder = TaskGraphBuilder()
    ids: dict = {}
    for ti in range(nt_i):
        for tj in range(nt_j):
            deps = [
                ids[key]
                for key in ((ti - 1, tj), (ti, tj - 1), (ti - 1, tj - 1))
                if key in ids
            ]
            ids[(ti, tj)] = builder.add(
                WAVE_FN, args=[ti + tj, ti, 1], deps=deps
            )
    return builder


def make_sw_batched_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool] = None,
    with_h: bool = True, width: int = WAVE_R,
) -> Megakernel:
    """Megakernel for the per-tile graph: ``width`` tile descriptors per
    batch round (the scheduler groups whatever subset of the wavefront is
    ready). SMEM note: the per-tile table is nt_i*nt_j rows - grids past
    ~32x32 tiles want the chunked graph (make_sw_wave_megakernel), whose
    descriptor count divides by the chunk size."""
    ntasks = nt_i * nt_j
    return _sw_batch_megakernel(
        nt_i, nt_j, interpret, with_h, chunk=1, width=width,
        capacity=max(64, ntasks), succ_capacity=max(64, 3 * ntasks),
    )


def sw_wave_buffers(a: np.ndarray, b: np.ndarray) -> dict:
    """Host data buffers for the wave engine (without the optional H
    matrix): sequences in row-tile layout + the boundary channels."""
    n, m = len(a), len(b)
    nt_i, nt_j = n // T, m // T
    i32 = np.int32
    return {
        "aseq": np.asarray(a, i32).reshape(nt_i, 1, T),
        "bseq": np.asarray(b, i32).reshape(nt_j, 1, T),
        "bot": np.zeros((nt_i, nt_j, 1, T), i32),
        "right": np.zeros((nt_i, nt_j, 1, T), i32),
    }


def device_sw_wave(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    with_h: bool = True,
) -> Tuple[int, Optional[np.ndarray], dict]:
    """Tiled SW where each task is a WAVE CHUNK (up to WAVE_R tiles of one
    anti-diagonal batched over VPU sublanes); dependencies chain
    anti-diagonals. Same results as device_sw, ~WAVE_R x the vector-unit
    utilization once diagonals are wide."""
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_wave_megakernel(nt_i, nt_j, interpret, with_h=with_h)
    builder = build_sw_wave_graph(nt_i, nt_j)
    i32 = np.int32
    data = sw_wave_buffers(a, b)
    if "htiles" in mk.data_specs:
        data["htiles"] = np.zeros((nt_i, nt_j, T, T), i32)
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = (
        np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
        if "htiles" in out
        else None
    )
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info


def device_sw_batched(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    with_h: bool = True,
    width: int = WAVE_R,
) -> Tuple[int, Optional[np.ndarray], dict]:
    """Tiled SW where each task is ONE tile on the precise 3-neighbor DAG
    and the megakernel's batched same-kind dispatch tier groups whatever
    subset of the wavefront is ready - up to ``width`` tiles per round
    through one (width, T)-plane body. Same results as device_sw, with the
    grouping decided at run time by the scheduler instead of at graph
    build time; ``info['tiers']`` carries the lane/occupancy counters."""
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_batched_megakernel(
            nt_i, nt_j, interpret, with_h=with_h, width=width
        )
    builder = build_sw_tile_graph(nt_i, nt_j)
    data = sw_wave_buffers(a, b)
    if "htiles" in mk.data_specs:
        data["htiles"] = np.zeros((nt_i, nt_j, T, T), np.int32)
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = (
        np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
        if "htiles" in out
        else None
    )
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info


def make_sw_megakernel(
    nt_i: int, nt_j: int, interpret: Optional[bool] = None,
    with_h: bool = True,
) -> Megakernel:
    import functools as _ft

    i32 = jnp.int32
    data_specs = {
        "aseq": jax.ShapeDtypeStruct((nt_i, 1, T), i32),
        "bseq": jax.ShapeDtypeStruct((nt_j, 1, T), i32),
        "bot": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
        "right": jax.ShapeDtypeStruct((nt_i, nt_j, 1, T), i32),
    }
    scratch = {
        "vtop": pltpu.VMEM((1, T), i32),
        "vb": pltpu.VMEM((1, T), i32),
        "a_sm": pltpu.SMEM((1, T), i32),
        "left_sm": pltpu.SMEM((1, T), i32),
        "rout_sm": pltpu.SMEM((1, T), i32),
        "corner_sm": pltpu.SMEM((1, T), i32),
        "sems": pltpu.SemaphoreType.DMA((4,)),
    }
    if with_h:
        data_specs["htiles"] = jax.ShapeDtypeStruct((nt_i, nt_j, T, T), i32)
        scratch["vh"] = pltpu.VMEM((T, T), i32)
    return Megakernel(
        kernels=[("sw_tile", _ft.partial(_sw_tile_kernel, with_h=with_h))],
        data_specs=data_specs,
        scratch_specs=scratch,
        capacity=max(64, nt_i * nt_j),
        num_values=8,
        succ_capacity=max(64, 3 * nt_i * nt_j),
        interpret=interpret,
    )


def device_sw(
    a: np.ndarray,
    b: np.ndarray,
    interpret: Optional[bool] = None,
    mk: Optional[Megakernel] = None,
    with_h: bool = True,
) -> Tuple[int, Optional[np.ndarray], dict]:
    """Run tiled SW on-device; returns (best_score, H[1:, 1:], info).

    Sequence lengths must be multiples of the 128 tile edge.
    """
    n, m = len(a), len(b)
    if n % T or m % T:
        raise ValueError(f"sequence lengths must be multiples of {T}")
    nt_i, nt_j = n // T, m // T
    if mk is None:
        mk = make_sw_megakernel(nt_i, nt_j, interpret, with_h=with_h)
    builder = TaskGraphBuilder()
    ids = {}
    for ti in range(nt_i):
        for tj in range(nt_j):
            deps = [
                ids[key]
                for key in ((ti - 1, tj), (ti, tj - 1), (ti - 1, tj - 1))
                if key in ids
            ]
            ids[(ti, tj)] = builder.add(TILE_FN, args=[ti, tj], deps=deps)
    i32 = np.int32
    data = {
        "aseq": np.asarray(a, i32).reshape(nt_i, 1, T),
        "bseq": np.asarray(b, i32).reshape(nt_j, 1, T),
        "bot": np.zeros((nt_i, nt_j, 1, T), i32),
        "right": np.zeros((nt_i, nt_j, 1, T), i32),
    }
    if "htiles" in mk.data_specs:
        data["htiles"] = np.zeros((nt_i, nt_j, T, T), i32)
    t0 = time.perf_counter()
    ivalues, out, info = mk.run(builder, data=data)
    dt = time.perf_counter() - t0
    h = (
        np.asarray(out["htiles"]).swapaxes(1, 2).reshape(n, m)
        if "htiles" in out
        else None
    )
    info = dict(info)
    info["seconds"] = dt
    info["cells_per_sec"] = n * m / dt
    return int(ivalues[0]), h, info
